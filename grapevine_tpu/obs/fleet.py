"""Fleet observatory: multi-process scrape aggregation with a leak-safe
re-export policy (the observability substrate for ROADMAP items 1/2/4).

Every serving direction left on the roadmap is multi-process — pod-scale
recipient sharding, N frontend processes, journal-shipped hot standby —
while the PR-1/2/6/9 surfaces are single-process: one /metrics, one
/healthz, one transcript verdict. This module makes the fleet a
first-class observable object: a stdlib aggregator scrapes N member
processes' /metrics, /healthz, /leakaudit, and /flightrec and serves
merged fleet endpoints, plus the cross-shard schedule-uniformity
detectors (obs/leakmon.py :class:`FleetUniformityMonitor`) that BOLT's
fleet-level adversary model demands (arXiv:2509.01742 — at fleet scale
the *inter-shard schedule* is the access pattern).

Two leak-policy obligations are structural here, not conventions:

- **scrape cadence is a pure function of config.** The aggregator
  scrapes on a fixed wall-clock grid (``t0 + k·interval``) in declared
  member order, never adapting to observed traffic, queue depths, or
  verdicts. An aggregator that scraped "interesting" members faster
  would itself encode which shard's recipients are busy into observable
  network timing — the exact side channel the fleet detectors exist to
  catch (OPERATIONS.md §20 has the full argument).
- **shard identity is public topology; member identity is not.** The
  merged /metrics re-exports member families under a ``shard`` label
  whose values are the declared integer indices (position in
  ``--fleet-members``). The registry enforces integer-only shard values
  (obs/registry.py), so a hostname or address can never ride a label —
  audited by tools/check_telemetry_policy.py.

Degraded-but-served: a member that flaps mid-scrape (timeout, refused,
truncated exposition) surfaces as ``grapevine_fleet_member_up == 0``
with a growing stale-age while its last-good families stay in the
merged view — the fleet endpoint never answers 500 because one member
wobbled. Partial evidence slows the uniformity verdict (ticks with a
missing shard contribute nothing) instead of distorting it.

Replication-lag telemetry (ROADMAP item 4): every member's
``grapevine_last_durable_seq`` and ``grapevine_journal_applied_seq``
(engine/checkpoint.py) are folded into per-shard
``grapevine_fleet_journal_lag_seq`` / ``_lag_seconds`` gauges — the
hot-standby RPO as a dashboard number before the standby exists.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import re
import threading
import time
import urllib.error
import urllib.request

from .exporter import _escape_label_value, render_prometheus
from .leakmon import PASS, SUSPECT, FleetUniformityConfig, FleetUniformityMonitor
from .registry import TelemetryRegistry

log = logging.getLogger("grapevine_tpu.obs.fleet")

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$"
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(value: str) -> str:
    return (
        value.replace("\\n", "\n").replace('\\"', '"').replace("\\\\", "\\")
    )


def parse_exposition(text: str) -> dict:
    """Parse a Prometheus 0.0.4 text exposition into ordered families.

    Returns ``{family_name: {"kind", "help", "samples"}}`` where each
    sample is ``(sample_name, ((k, v), ...), value)``. Strict on
    purpose: any malformed sample line raises ``ValueError``, so a
    truncated body from a member dying mid-write rejects the whole
    scrape (last-good view retained) instead of merging half a family.
    """
    families: dict = {}
    kinds: dict = {}
    helps: dict = {}

    def family_of(sample_name: str) -> str:
        if sample_name in kinds:
            return sample_name
        for suffix in ("_bucket", "_sum", "_count"):
            if sample_name.endswith(suffix):
                base = sample_name[: -len(suffix)]
                if base in kinds:
                    return base
        return sample_name

    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] == "TYPE":
                kinds[parts[2]] = parts[3] if len(parts) > 3 else "untyped"
            elif len(parts) >= 3 and parts[1] == "HELP":
                helps[parts[2]] = parts[3] if len(parts) > 3 else ""
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"malformed exposition line: {raw[:80]!r}")
        name, labelstr, value = m.groups()
        try:
            val = float(value)
        except ValueError:
            raise ValueError(f"bad sample value in line: {raw[:80]!r}")
        labels: list = []
        if labelstr:
            pos = 0
            for lm in _LABEL_RE.finditer(labelstr):
                if labelstr[pos:lm.start()].strip(", ") != "":
                    raise ValueError(
                        f"bad label syntax in line: {raw[:80]!r}")
                labels.append((lm.group(1), _unescape(lm.group(2))))
                pos = lm.end()
            if labelstr[pos:].strip(", ") != "":
                raise ValueError(f"bad label syntax in line: {raw[:80]!r}")
        fam = family_of(name)
        entry = families.setdefault(
            fam, {"kind": kinds.get(fam, "untyped"),
                  "help": helps.get(fam, ""), "samples": []}
        )
        entry["kind"] = kinds.get(fam, entry["kind"])
        entry["help"] = helps.get(fam, entry["help"])
        entry["samples"].append((name, tuple(labels), val))
    return families


def _sample_value(families: dict, family: str, sample: str | None = None,
                  default: float | None = None) -> float | None:
    """The (first) unlabeled-or-any sample value of a family."""
    fam = families.get(family)
    if fam is None:
        return default
    want = sample or family
    for name, _labels, value in fam["samples"]:
        if name == want:
            return value
    return default


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Fleet aggregator topology + cadence (all public, all declared).

    ``members``: scrape endpoints as ``host:port``, one per member role
    process; list position IS the shard index — the only member
    identity that ever reaches a metric label."""

    members: tuple[str, ...]
    #: fixed scrape period in seconds — with the start instant, the
    #: ENTIRE scrape schedule (a pure function of config, never of
    #: observed traffic; see module docstring)
    scrape_interval_s: float = 1.0
    #: per-request timeout; None = min(2s, scrape_interval_s)
    scrape_timeout_s: float | None = None
    uniformity: FleetUniformityConfig | None = None

    def __post_init__(self):
        if not self.members:
            raise ValueError("fleet needs at least one member")
        if self.scrape_interval_s <= 0:
            raise ValueError("scrape_interval_s must be positive")

    @property
    def timeout_s(self) -> float:
        if self.scrape_timeout_s is not None:
            return self.scrape_timeout_s
        return min(2.0, self.scrape_interval_s)


class _MemberState:
    """Last-known view of one member (the degraded-view substrate)."""

    __slots__ = ("up", "t_good", "families", "healthz", "flightrec",
                 "leakaudit", "t_caught_up", "ever_scraped")

    def __init__(self):
        self.up = False
        self.t_good: float | None = None
        self.families: dict | None = None
        self.healthz: dict | None = None
        self.leakaudit: dict | None = None
        self.flightrec: dict | None = None
        self.t_caught_up: float | None = None
        self.ever_scraped = False


class FleetAggregator:
    """Scrape N members on a fixed cadence; serve the merged fleet view.

    ``scrape_once()`` runs one synchronous cycle (tests drive it
    directly); ``start()``/``serve()`` run the cadence thread and the
    merged HTTP endpoint. All HTTP fetching is stdlib
    (``urllib.request``) — the container policy bakes no client
    library, and four small GETs per member per tick need none.
    """

    def __init__(self, cfg: FleetConfig, clock=time.monotonic,
                 fetch=None):
        self.cfg = cfg
        self.n = len(cfg.members)
        self._clock = clock
        #: injectable fetcher (tests): (url, timeout_s) -> bytes
        self._fetch = fetch or self._http_get
        self._lock = threading.Lock()
        self._members = [_MemberState() for _ in range(self.n)]
        self.registry = TelemetryRegistry()
        shards = tuple(str(i) for i in range(self.n))
        labels = {"shard": shards}
        self._g_members = self.registry.gauge(
            "grapevine_fleet_members",
            "declared fleet member count (config, not liveness)")
        self._g_members.set(float(self.n))
        self._g_up = self.registry.gauge(
            "grapevine_fleet_member_up",
            "1 when the shard's last /metrics scrape succeeded "
            "(0 = degraded: last-good families still served, see "
            "stale_age)", labels=labels)
        self._g_stale = self.registry.gauge(
            "grapevine_fleet_member_stale_age_seconds",
            "seconds since the shard's last successful /metrics scrape "
            "(-1 = never scraped)", labels=labels)
        self._c_scrapes = self.registry.counter(
            "grapevine_fleet_scrapes_total",
            "scrape cycles attempted against the shard (fixed public "
            "cadence — a pure function of config)", labels=labels)
        self._c_failures = self.registry.counter(
            "grapevine_fleet_scrape_failures_total",
            "scrape cycles that failed against the shard (timeout, "
            "refused, or malformed exposition)", labels=labels)
        self._g_lag_seq = self.registry.gauge(
            "grapevine_fleet_journal_lag_seq",
            "journal records the shard's applied-seq trails the fleet's "
            "newest durable seq by (hot-standby RPO in records — "
            "OPERATIONS.md §20)", labels=labels)
        self._g_lag_sec = self.registry.gauge(
            "grapevine_fleet_journal_lag_seconds",
            "seconds the shard has spent behind the fleet's newest "
            "durable seq (0 while caught up)", labels=labels)
        self._g_standbys = self.registry.gauge(
            "grapevine_fleet_standbys",
            "members whose /healthz reports role=standby — live hot "
            "replicas replaying the shipped journal (a promoted "
            "standby leaves this count and starts serving; "
            "OPERATIONS.md §23)")
        self._g_promotions = self.registry.gauge(
            "grapevine_fleet_promotions",
            "sum of members' promotion counters — a nonzero value "
            "means a takeover happened and the fenced old primary "
            "needs operator attention (OPERATIONS.md §23 runbook)")
        self.uniformity = (
            FleetUniformityMonitor(
                self.n, cfg.uniformity, registry=self.registry)
            if self.n >= 2 else None
        )
        for i in range(self.n):
            self._g_stale.set(-1.0, shard=str(i))
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._httpd = None

    # -- fetching -------------------------------------------------------

    @staticmethod
    def _http_get(url: str, timeout_s: float) -> bytes:
        try:
            with urllib.request.urlopen(url, timeout=timeout_s) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            # /healthz 503 and /leakaudit 503 still carry their JSON
            # body — an unhealthy member is a *successful* scrape; only
            # 404 (endpoint not configured) returns nothing
            if e.code == 404:
                return b""
            body = e.read()
            if body:
                return body
            raise

    def _get_json(self, addr: str, path: str) -> dict | None:
        body = self._fetch(f"http://{addr}{path}", self.cfg.timeout_s)
        if not body:
            return None
        return json.loads(body)

    # -- one scrape cycle ----------------------------------------------

    def scrape_once(self) -> None:
        """One synchronous scrape cycle over every member, in declared
        order (fixed — ordering by anything observed would leak)."""
        samples: list = []
        now = self._clock()
        for i, addr in enumerate(self.cfg.members):
            st = self._members[i]
            self._c_scrapes.inc(shard=str(i))
            try:
                body = self._fetch(
                    f"http://{addr}/metrics", self.cfg.timeout_s)
                families = parse_exposition(body.decode("utf-8"))
            except Exception as exc:
                # degraded, not dead: keep the last-good view, mark the
                # member down, keep serving (the whole point)
                self._c_failures.inc(shard=str(i))
                with self._lock:
                    st.up = False
                    st.ever_scraped = True
                self._g_up.set(0.0, shard=str(i))
                log.debug("scrape of shard %d (%s) failed: %r",
                          i, addr, exc)
                samples.append(None)
            else:
                with self._lock:
                    st.up = True
                    st.ever_scraped = True
                    st.t_good = now
                    st.families = families
                self._g_up.set(1.0, shard=str(i))
                samples.append(self._uniformity_sample(families))
            # auxiliary endpoints are best-effort: their absence or
            # failure never degrades the /metrics view
            for path, attr in (("/healthz", "healthz"),
                               ("/leakaudit", "leakaudit"),
                               ("/flightrec", "flightrec")):
                try:
                    doc = self._get_json(addr, path)
                except Exception:
                    continue
                if doc is not None:
                    with self._lock:
                        setattr(st, attr, doc)
        for i in range(self.n):
            st = self._members[i]
            self._g_stale.set(
                round(now - st.t_good, 3) if st.t_good is not None
                else -1.0,
                shard=str(i))
        self._update_lag(now)
        self._update_standbys()
        if self.uniformity is not None:
            self.uniformity.observe_tick(samples)
            self.uniformity.verdict()  # refresh the exported gauges

    @staticmethod
    def _uniformity_sample(families: dict) -> dict | None:
        """Per-shard public series for the uniformity monitor; None
        when the member exports no round counter (not a device owner
        — e.g. a frontend), which contributes no evidence."""
        rounds = _sample_value(families, "grapevine_rounds_total")
        if rounds is None:
            return None
        return {
            "rounds_total": rounds,
            "fill_sum": _sample_value(
                families, "grapevine_load_batch_fill",
                "grapevine_load_batch_fill_sum", 0.0),
            "fill_count": _sample_value(
                families, "grapevine_load_batch_fill",
                "grapevine_load_batch_fill_count", 0.0),
            "flushes_total": _sample_value(
                families, "grapevine_evict_flushes_total", default=0.0),
            "queue_depth": _sample_value(
                families, "grapevine_queue_depth", default=0.0),
        }

    def _update_lag(self, now: float) -> None:
        """Fold member durable/applied seqs into the per-shard lag
        gauges. Fleet-newest durable seq is the replication frontier;
        a shard's applied-seq trailing it is the standby RPO."""
        durable = []
        applied = []
        for st in self._members:
            fams = st.families or {}
            durable.append(_sample_value(
                fams, "grapevine_last_durable_seq", default=None))
            applied.append(_sample_value(
                fams, "grapevine_journal_applied_seq", default=None))
        frontier = max(
            (d for d in durable if d is not None), default=None)
        if frontier is None:
            return
        for i, st in enumerate(self._members):
            a = applied[i]
            if a is None:
                # a member with no durability exports no lag (unknown
                # is not zero and not infinite) — leave the gauge at 0
                continue
            lag = max(0.0, frontier - a)
            self._g_lag_seq.set(lag, shard=str(i))
            if lag == 0.0:
                st.t_caught_up = now
                self._g_lag_sec.set(0.0, shard=str(i))
            else:
                base = st.t_caught_up if st.t_caught_up is not None else now
                st.t_caught_up = st.t_caught_up or base
                self._g_lag_sec.set(round(now - base, 3), shard=str(i))

    def _update_standbys(self) -> None:
        """Count live standbys and sum promotion counters across the
        fleet. Role comes from /healthz (the body tag every member
        carries) — an un-promoted standby exports no round counter, so
        nothing else in the merge distinguishes it from a dead shard."""
        standbys = 0
        promotions = 0.0
        with self._lock:
            for st in self._members:
                hz = st.healthz or {}
                if st.up and hz.get("role") == "standby" \
                        and not hz.get("promoted"):
                    standbys += 1
                p = _sample_value(
                    st.families or {},
                    "grapevine_replication_promotions_total",
                    default=None)
                if p is not None:
                    promotions += p
        self._g_standbys.set(float(standbys))
        self._g_promotions.set(promotions)

    # -- merged views ---------------------------------------------------

    def render_merged(self) -> str:
        """The fleet /metrics body: every member family re-exported
        under its shard label (declared integer indices only), then the
        fleet's own ``grapevine_fleet_*`` registry."""
        with self._lock:
            views = [
                (i, dict(st.families)) for i, st in enumerate(self._members)
                if st.families is not None
            ]
        names: list = []
        seen = set()
        for _i, fams in views:
            for name in fams:
                if name not in seen:
                    seen.add(name)
                    names.append(name)
        lines: list = []
        for name in names:
            first = next(f[name] for _i, f in views if name in f)
            if first["help"]:
                lines.append(f"# HELP {name} {first['help']}")
            lines.append(f"# TYPE {name} {first['kind']}")
            for i, fams in views:
                fam = fams.get(name)
                if fam is None:
                    continue
                for sname, labels, value in fam["samples"]:
                    # the ONE label the merge may add: the declared
                    # integer shard index; a member's own stray shard
                    # label is dropped rather than re-exported
                    pairs = [
                        (k, v) for k, v in labels if k != "shard"
                    ] + [("shard", str(i))]
                    ls = ",".join(
                        f'{k}="{_escape_label_value(v)}"' for k, v in pairs
                    )
                    val = ("%g" % value) if value == value else "NaN"
                    lines.append(f"{sname}{{{ls}}} {val}")
        merged = "\n".join(lines)
        own = render_prometheus(self.registry)
        return (merged + "\n" + own) if merged else own

    def healthz(self) -> tuple[bool, dict]:
        """Fold member health + merged SLO burn rates + the fleet
        uniformity verdict. Healthy iff every member is up and itself
        healthy and no cross-shard detector trips — a degraded or
        skewed fleet stops routing as a unit."""
        with self._lock:
            members = []
            healthy = True
            worst_fast = worst_slow = 0.0
            for i, st in enumerate(self._members):
                hz = st.healthz or {}
                m_healthy = hz.get("healthy")
                entry = {
                    "shard": i,
                    "address": self.cfg.members[i],
                    "up": bool(st.up),
                    "healthy": m_healthy,
                    "leakaudit": hz.get("leakaudit"),
                }
                if hz.get("role") is not None:
                    entry["role"] = hz["role"]
                if hz.get("role") == "standby":
                    # the DR surface an operator pages on: is the
                    # replica fed, and at what epoch (OPERATIONS.md §23)
                    entry["promoted"] = bool(hz.get("promoted"))
                    entry["replication_connected"] = bool(
                        hz.get("replication_connected"))
                    entry["journal_epoch"] = hz.get("journal_epoch")
                members.append(entry)
                healthy = healthy and st.up and bool(m_healthy)
                slo = hz.get("slo") or {}
                worst_fast = max(worst_fast,
                                 float(slo.get("fast_burn_rate", 0.0)))
                worst_slow = max(worst_slow,
                                 float(slo.get("slow_burn_rate", 0.0)))
        detail: dict = {
            "role": "fleet",
            "n_members": self.n,
            "n_standbys": sum(
                1 for m in members
                if m.get("role") == "standby" and not m.get("promoted")),
            "members": members,
            # merged burn rates: the fleet burns as fast as its
            # worst-burning shard (error budgets do not average away)
            "slo_fast_burn_rate": round(worst_fast, 4),
            "slo_slow_burn_rate": round(worst_slow, 4),
        }
        if self.uniformity is not None:
            uv = self.uniformity.verdict()
            detail["uniformity"] = uv["verdict"]
            healthy = healthy and uv["verdict"] == PASS
        return healthy, detail

    def leakaudit(self) -> dict:
        """Fold member /leakaudit verdicts + the cross-shard detectors
        (the fleet /leakaudit body; 200/503 semantics ride on the
        overall verdict like the single-process endpoint)."""
        with self._lock:
            members = []
            suspect = False
            for i, st in enumerate(self._members):
                v = (st.leakaudit or {}).get("verdict")
                members.append({
                    "shard": i,
                    "up": bool(st.up),
                    "verdict": v,
                })
                # a member with no leak monitor (no /leakaudit) cannot
                # testify either way; only an explicit SUSPECT trips
                suspect = suspect or v == SUSPECT
        out: dict = {"members": members}
        if self.uniformity is not None:
            uv = self.uniformity.verdict()
            out["fleet_detectors"] = uv["detectors"]
            out["window_ticks"] = uv["window_ticks"]
            suspect = suspect or uv["verdict"] == SUSPECT
        out["verdict"] = SUSPECT if suspect else PASS
        return out

    def flightrec(self) -> dict:
        """Last-scraped member flight-recorder dumps, by shard."""
        with self._lock:
            return {
                "members": [
                    {"shard": i, "up": bool(st.up),
                     "flightrec": st.flightrec}
                    for i, st in enumerate(self._members)
                ]
            }

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        """Run the scrape cadence thread: cycles fire on the fixed grid
        ``t0 + k·interval`` (monotonic clock). A cycle that overruns
        skips to the next grid point — the schedule stays a pure
        function of config even under slow members."""
        if self._thread is not None:
            return
        self._stop.clear()

        def _loop():
            t0 = self._clock()
            k = 0
            while not self._stop.is_set():
                self.scrape_once()
                k += 1
                target = t0 + k * self.cfg.scrape_interval_s
                now = self._clock()
                while target <= now:  # overran: skip, never compress
                    k += 1
                    target = t0 + k * self.cfg.scrape_interval_s
                if self._stop.wait(timeout=target - now):
                    return

        self._thread = threading.Thread(
            target=_loop, daemon=True, name="grapevine-fleet-scrape")
        self._thread.start()

    def serve(self, port: int = 0, host: str = "127.0.0.1") -> int:
        """Start the cadence thread + the merged HTTP endpoint; returns
        the bound port."""
        from .httpd import MetricsServer

        self.start()
        self._httpd = MetricsServer(
            self.registry,
            health=self.healthz,
            host=host,
            port=port,
            leakaudit=self.leakaudit,
            flightrec=self.flightrec,
            render=self.render_merged,
        )
        return self._httpd.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self._httpd is not None:
            self._httpd.stop()
            self._httpd = None
