"""Batch-level workload telemetry: what the service *sees* under load.

Every number the repo banked before PR 9 came from uniform closed-loop
drains; this module is the measurement half of ROADMAP items 2/4 — the
arrival/utilization signals the adaptive-batching and pipelined-round
work will control on, and the first honest view of bursty/diurnal/
pop-heavy traffic (the ``grapevine_tpu/load`` scenario harness is the
source of that traffic; this module is where its shape becomes
operable telemetry):

- **batch fill fraction** and **queue depth** as fixed-bucket
  histograms sampled at round cadence (one observation per committed
  round, from ``PendingRound.resolve`` — never per op);
- an **arrival-rate EWMA gauge** updated at enqueue time (exponentially
  decayed event weight — for a Poisson stream of rate λ the decayed
  weight settles at λ·τ, so weight/τ estimates λ without per-op
  timestamps ever leaving the process);
- **per-phase utilization fractions** derived from the PR-6 tracer
  span ledgers (phase duration / round duration, windowed EWMA) — the
  host/device balance per phase that sizes the pipeline refactor;
- **saturation / backpressure counters**: rounds that dispatched full
  with work still queued behind them, and arrivals that landed on a
  queue already at least one full batch deep.

Leak stance (the PR-1/2 contract): everything here is batch-level. The
histograms' buckets are fixed at registration; the only label anywhere
is ``phase`` with registration-declared values; arrivals are counted,
never keyed — there is no per-op, per-client, or per-type dimension in
which an identity could travel, and tools/check_telemetry_policy.py
audits the ``grapevine_load_*`` namespace in tier-1.

Thread-safety: one lock; ``note_arrival`` runs on gRPC handler / load
dispatcher threads, ``observe_round`` on the collector thread
(PendingRound.resolve), gauge reads on the scrape thread.
"""

from __future__ import annotations

import math
import threading
import time

from .registry import TelemetryRegistry

#: fixed batch-fill-fraction boundaries (fraction of slots real). The
#: last edge is 1.0 — a full round; the +Inf bucket stays empty.
FILL_BUCKETS = (
    0.0625, 0.125, 0.1875, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0,
)

#: fixed queue-depth boundaries (ops waiting at round dispatch):
#: log-spaced from "empty" to far past any sane batch size, so the same
#: schema serves a B=4 dev engine and a B=4096 production round
DEPTH_BUCKETS = (
    0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0,
    512.0, 1024.0, 2048.0, 4096.0,
)

#: span names whose utilization fraction is exported — the host spans
#: of the PR-6 tracer ledger plus the host-observed device window
#: (obs/tracer.py HOST_SPANS + "device"); declared at registration so a
#: typo'd (or per-op) phase value raises instead of minting a series
UTILIZATION_SPANS = (
    "assembly", "verify", "dispatch", "journal", "checkpoint",
    "evict", "demux", "device",
)


class WorkloadTelemetry:
    """Arrival/fill/depth/utilization telemetry on a TelemetryRegistry.

    Attach to an engine via ``GrapevineEngine.attach_workload``; the
    scheduler notes arrivals (``note_arrival``) and every committed
    round contributes one ``observe_round`` from its span ledger.
    """

    def __init__(
        self,
        registry: TelemetryRegistry,
        batch_size: int,
        ewma_tau_s: float = 5.0,
        util_alpha: float = 1.0 / 16.0,
        clock=time.monotonic,
    ):
        if batch_size <= 0:
            raise ValueError("batch_size must be positive")
        if ewma_tau_s <= 0:
            raise ValueError("ewma_tau_s must be positive")
        self.batch_size = int(batch_size)
        self._tau = float(ewma_tau_s)
        self._alpha = float(util_alpha)
        self._clock = clock
        self._lock = threading.Lock()
        #: exponentially decayed arrival weight; weight/τ estimates the
        #: instantaneous arrival rate (see module docstring)
        self._weight = 0.0
        self._t_last = None
        #: per-span utilization EWMA state
        self._util = {name: 0.0 for name in UTILIZATION_SPANS}

        self._h_fill = registry.histogram(
            "grapevine_load_batch_fill",
            "real ops / batch slots per committed round (round cadence; "
            "the batch-occupancy histogram adaptive batching sizes from)",
            buckets=FILL_BUCKETS)
        self._h_depth = registry.histogram(
            "grapevine_load_queue_depth",
            "scheduler queue depth at round dispatch (ops left waiting "
            "after the round's chunk was taken; round cadence)",
            buckets=DEPTH_BUCKETS)
        self._c_arrivals = registry.counter(
            "grapevine_load_arrivals_total",
            "ops enqueued into the scheduler (count only, never keyed)")
        self._g_rate = registry.gauge(
            "grapevine_load_arrival_rate_ops_s",
            "EWMA arrival rate (decayed event weight / tau; tau = "
            f"{ewma_tau_s:g}s by default)")
        self._g_util = registry.gauge(
            "grapevine_load_phase_utilization",
            "windowed mean fraction of each round's wall clock spent in "
            "the phase (from the PR-6 span ledgers; 'device' = the "
            "host-observed device window)",
            labels={"phase": UTILIZATION_SPANS})
        self._c_saturated = registry.counter(
            "grapevine_load_saturated_rounds_total",
            "rounds dispatched completely full with ops still queued "
            "behind them (sustained-overload signal)")
        self._c_backpressure = registry.counter(
            "grapevine_load_backpressure_arrivals_total",
            "arrivals that found the queue already >= one full batch "
            "deep (the op will wait at least one extra round)")

    # -- arrival path (scheduler submit; any thread) --------------------

    def note_arrival(self, queue_depth: int) -> None:
        """Record one enqueue; ``queue_depth`` is the depth *after* the
        op joined the queue."""
        now = self._clock()
        with self._lock:
            if self._t_last is not None:
                dt = max(0.0, now - self._t_last)
                self._weight *= math.exp(-dt / self._tau)
            self._weight += 1.0
            self._t_last = now
            rate = self._weight / self._tau
        self._c_arrivals.inc()
        self._g_rate.set(rate)
        # pre-join depth: an op joining at exactly batch_size depth
        # (itself included) still rides the very next round — only a
        # queue ALREADY a full batch deep costs it an extra round
        if queue_depth - 1 >= self.batch_size:
            self._c_backpressure.inc()

    def arrival_rate(self) -> float:
        """Current decayed arrival-rate estimate (ops/s)."""
        now = self._clock()
        with self._lock:
            if self._t_last is None:
                return 0.0
            dt = max(0.0, now - self._t_last)
            return self._weight * math.exp(-dt / self._tau) / self._tau

    # -- round path (PendingRound.resolve; collector thread) ------------

    def observe_round(
        self,
        n_real: int,
        batch_size: int,
        queue_depth: int | None,
        spans: dict | None = None,
    ) -> None:
        """Record one committed round: fill, post-dispatch queue depth,
        and per-phase utilization from the round's span ledger."""
        fill = (n_real / batch_size) if batch_size else 0.0
        self._h_fill.observe(fill)
        depth = int(queue_depth) if queue_depth is not None else 0
        self._h_depth.observe(depth)
        # round cadence is also when the arrival gauge decays toward
        # zero: updated only at enqueue time it would freeze at the
        # last burst's rate forever on an idle service
        self._g_rate.set(self.arrival_rate())
        if n_real >= batch_size and depth > 0:
            self._c_saturated.inc()
        if not spans:
            return
        round_dur = spans.get("round", (0.0, 0.0))[1]
        if round_dur <= 0.0:
            return
        with self._lock:
            a = self._alpha
            for name in UTILIZATION_SPANS:
                span = spans.get(name)
                frac = max(0.0, min(1.0, span[1] / round_dur)) if span else 0.0
                self._util[name] = (1 - a) * self._util[name] + a * frac
                self._g_util.set(self._util[name], phase=name)

    def utilization(self) -> dict:
        """Current per-span utilization EWMA (a copy)."""
        with self._lock:
            return dict(self._util)
