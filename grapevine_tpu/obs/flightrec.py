"""Round flight recorder: a fixed-size ring of per-round summaries.

The black-box analog for the oblivious engine: when the leak monitor
(obs/leakmon.py) or an operator needs to reconstruct *what the engine
was doing* around a SUSPECT verdict or a healthz degradation, the
recorder holds the last N rounds' batch-level summaries — batch fill,
host phase timings, detector statistics — dumpable as JSON on demand
(the /flightrec endpoint, obs/httpd.py) or automatically on a
PASS→SUSPECT transition (OPERATIONS.md runbook).

Leak stance — enforced structurally, like the telemetry registry's
label allowlist rather than by convention: ``record()`` validates every
summary against a fixed field schema and rejects anything else with
:class:`TelemetryLeakError`. A summary can only carry batch-level
scalars (fill, phase seconds, windowed detector statistics, verdict
strings); there is no field in which a logical key, a recipient id, a
message id, or a per-op timestamp *could* travel, so the dump is safe
to hand to an operator or attach to an incident ticket. A tier-1 test
(tests/test_leakmon.py) asserts both the schema enforcement and the
dump's cleanliness.

Thread-safety: one lock around the ring; ``record()`` runs on the leak
monitor's worker thread, ``dump()`` on the metrics scrape thread.
"""

from __future__ import annotations

import json
import threading
import time

from .phases import PHASES
from .registry import TelemetryLeakError

#: top-level summary fields a recorded round may carry. ``phase_s`` is
#: a {phase name: seconds} dict over the canonical PHASES (+ "round"
#: for the commit latency); ``stats`` is {tree: {stat name: number}}
#: over the detector stat fields below. Everything else is a scalar.
ALLOWED_FIELDS = frozenset({
    "seq",         # monotone engine-round sequence number (recorder-local)
    "t_mono_s",    # round-level monotonic clock (batch-level; never per-op)
    "batch_size",  # configured slots per round
    "n_real",      # real (non-padding) ops in the round — an aggregate
    "fill",        # n_real / batch_size
    "queue_depth", # ops left waiting at dispatch (scheduler backlog —
                   # an aggregate of the queue, never of any op in it)
    "phase_s",     # {phase: seconds} host phase timings for this round
    "stats",       # {tree: {stat: number}} windowed detector statistics
    "verdict",     # "PASS" / "SUSPECT" at the time the round was recorded
})

ALLOWED_PHASE_KEYS = frozenset(PHASES) | {"round"}

#: detector streams: the two payload trees plus — under a recursive
#: position map (oram/posmap.py) — their internal position-ORAM streams.
#: All four are windowed batch-level statistics, never per-op.
ALLOWED_TREES = frozenset({"rec", "mb", "rec_pm", "mb_pm"})

ALLOWED_STAT_KEYS = frozenset({
    "collision_rate", "collision_pairs",
    "repeat_rate", "repeat_opportunities",
    "uniformity_z", "pooled_leaves",
})

_SCALARS = (int, float, str, bool, type(None))


def _check_scalar(field: str, value) -> None:
    if not isinstance(value, _SCALARS):
        raise TelemetryLeakError(
            f"flight recorder: field {field!r} holds a {type(value).__name__}"
            " — summaries are batch-level scalars only (an array-valued "
            "field is how per-op data would leak into a dump)"
        )


class FlightRecorder:
    """Fixed-size ring of schema-checked per-round summaries."""

    def __init__(self, capacity: int = 512):
        if capacity <= 0:
            raise ValueError("flight recorder capacity must be positive")
        self.capacity = capacity
        self._lock = threading.Lock()
        self._ring: list[dict] = [None] * capacity  # type: ignore[list-item]
        self._n = 0  # total rounds ever recorded

    # -- recording ------------------------------------------------------

    def record(self, summary: dict) -> None:
        """Append one round summary; raises TelemetryLeakError unless it
        fits the batch-level schema exactly."""
        if not isinstance(summary, dict):
            raise TelemetryLeakError("flight recorder: summary must be a dict")
        unknown = set(summary) - ALLOWED_FIELDS
        if unknown:
            raise TelemetryLeakError(
                f"flight recorder: fields {sorted(unknown)} are not in the "
                f"summary schema {sorted(ALLOWED_FIELDS)} — there is no "
                "field for per-op or per-client data by design"
            )
        clean: dict = {}
        for field, value in summary.items():
            if field == "phase_s":
                if not isinstance(value, dict):
                    raise TelemetryLeakError(
                        "flight recorder: phase_s must be {phase: seconds}")
                bad = set(value) - ALLOWED_PHASE_KEYS
                if bad:
                    raise TelemetryLeakError(
                        f"flight recorder: unknown phases {sorted(bad)} "
                        f"(allowed: {sorted(ALLOWED_PHASE_KEYS)})"
                    )
                for k, v in value.items():
                    _check_scalar(f"phase_s[{k}]", v)
                clean[field] = dict(value)
            elif field == "stats":
                if not isinstance(value, dict):
                    raise TelemetryLeakError(
                        "flight recorder: stats must be {tree: {stat: num}}")
                bad = set(value) - ALLOWED_TREES
                if bad:
                    raise TelemetryLeakError(
                        f"flight recorder: unknown trees {sorted(bad)} "
                        f"(allowed: {sorted(ALLOWED_TREES)})"
                    )
                trees: dict = {}
                for tree, stats in value.items():
                    if not isinstance(stats, dict):
                        raise TelemetryLeakError(
                            "flight recorder: per-tree stats must be a dict")
                    badstat = set(stats) - ALLOWED_STAT_KEYS
                    if badstat:
                        raise TelemetryLeakError(
                            f"flight recorder: unknown stats {sorted(badstat)}"
                            f" (allowed: {sorted(ALLOWED_STAT_KEYS)})"
                        )
                    for k, v in stats.items():
                        _check_scalar(f"stats[{tree}][{k}]", v)
                    trees[tree] = dict(stats)
                clean[field] = trees
            else:
                _check_scalar(field, value)
                clean[field] = value
        with self._lock:
            self._ring[self._n % self.capacity] = clean
            self._n += 1

    # -- dumping --------------------------------------------------------

    def dump(self) -> dict:
        """JSON-able snapshot: the retained rounds, oldest first."""
        with self._lock:
            n = self._n
            if n <= self.capacity:
                rounds = [r for r in self._ring[:n]]
            else:
                cut = n % self.capacity
                rounds = self._ring[cut:] + self._ring[:cut]
        return {
            "capacity": self.capacity,
            "recorded_total": n,
            "retained": len(rounds),
            "rounds": rounds,
        }

    def dump_json(self) -> str:
        return json.dumps(self.dump())

    def dump_to(self, path: str) -> str:
        """Write the dump to ``path`` (the SUSPECT runbook artifact);
        returns the path."""
        payload = self.dump()
        payload["dumped_at_mono_s"] = round(time.monotonic(), 3)
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(payload, fh)
        return path
