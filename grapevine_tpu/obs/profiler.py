"""Programmatic ``jax.profiler`` capture for a live engine.

The TPU profiler is the only instrument that can split device time
inside the fused round program (the host phase timers stop at the
``evict`` wait; the ``jax.named_scope`` annotations compiled into the
round only become visible in a profiler capture). Until now getting one
meant restarting the server under ``tools/tpu_capture.py`` — this module
makes a capture a runtime operation instead: ``/profile?ms=N``
(obs/httpd.py) starts a ``jax.profiler`` trace on the live process,
sleeps N milliseconds while the engine keeps serving, stops the trace,
and returns the capture directory. Load the result in Perfetto /
TensorBoard next to ``/trace``'s round spans.

Gated and bounded by design: the endpoint exists only when the operator
passed ``--profile-enable`` (a capture costs real overhead and writes
device-level traces to disk — not something an exposed scrape port
should trigger), one capture runs at a time (a second request gets 409
rather than corrupting the active session), and the duration is clamped
to ``max_ms``.

Leak stance: the profiler records *phase-level* annotations
(``grapevine/<phase>`` TraceAnnotations and named_scopes — obs/phases.py)
and XLA op timings, all functions of (capacity, batch size); request
payloads and identities never enter trace metadata. The capture
directory itself stays operator-local — the endpoint returns its path,
never its contents.
"""

from __future__ import annotations

import os
import threading
import time


class ProfilerBusy(RuntimeError):
    """A capture is already in progress (one at a time by design)."""


class ProfilerGate:
    """Serialized, duration-clamped ``jax.profiler`` capture trigger."""

    def __init__(self, outdir: str | None = None, max_ms: int = 60_000):
        import tempfile

        self.outdir = outdir or os.path.join(
            tempfile.gettempdir(), f"grapevine-profile-{os.getpid()}"
        )
        self.max_ms = max_ms
        self._lock = threading.Lock()
        self._n = 0

    def capture(self, ms: int = 1000) -> dict:
        """Run one profiler capture of ``ms`` milliseconds (clamped to
        [1, max_ms]); returns ``{"trace_dir", "ms"}``. Raises
        :class:`ProfilerBusy` if a capture is already running."""
        ms = max(1, min(int(ms), self.max_ms))
        if not self._lock.acquire(blocking=False):
            raise ProfilerBusy(
                "a profiler capture is already in progress; retry when "
                "it completes"
            )
        try:
            import jax.profiler

            self._n += 1
            trace_dir = os.path.join(self.outdir, f"capture-{self._n:04d}")
            os.makedirs(trace_dir, exist_ok=True)
            jax.profiler.start_trace(trace_dir)
            try:
                time.sleep(ms / 1e3)
            finally:
                jax.profiler.stop_trace()
            return {"trace_dir": trace_dir, "ms": ms}
        finally:
            self._lock.release()
