"""Round-trace profiler: a fixed ring of per-round span ledgers.

The flight recorder (obs/flightrec.py) answers *what* the engine was
doing (fill, detector stats); this module answers *where the time went*
— the question that sizes ROADMAP items 1-2 (tree-top caching, pipelined
rounds) before anyone builds them. Each committed round contributes one
span ledger assembled from the phase timers the engine already runs
(assembly/verify/dispatch/journal/checkpoint/evict/demux plus the
host-observed device window), kept in a fixed ring like the flight
recorder and exported two ways:

- ``chrome_trace()`` — Chrome trace-event JSON (the ``/trace`` endpoint,
  obs/httpd.py), loadable directly in Perfetto / chrome://tracing, with
  host spans and the device window on separate tracks so the
  host/device overlap is visible per round. Rounds alternate between
  two lanes per track (tid = lane): the pipelined scheduler keeps up to
  two rounds in flight, and the trace-event format requires complete
  (``X``) events on one tid to nest or stay disjoint — consecutive
  overlapping rounds on a single track would misrender;
- ``grapevine_round_bubble_ratio`` — a derived gauge: the windowed mean
  fraction of each round's wall clock the host spends *blocked* on the
  device (the ``evict`` wait over the whole round span). This is the
  number that sizes the pipelined-round refactor (Palermo,
  arXiv:2411.05400, motivates protocol/hardware pipelining from exactly
  this phase-overlap accounting). Read it as the host/device balance
  ``b``: with one device, double-buffered rounds take
  ``max(host, device)`` instead of today's ``host + device``, so the
  steady-state speedup is ``1 / max(b, 1-b)`` — maximal (≈2×) at
  ``b ≈ 0.5``, and ≈1× at *both* extremes: near 0 the host path is the
  bottleneck (scale frontends / host pipeline instead), near 1 the
  round is device-bound and there is no second device to overlap with
  (attack the device round itself — tree-top caching, ROADMAP item 1).

Leak stance — the PR-1/2 contract, enforced structurally: a span is a
*phase*, never an operation. ``record_round()`` validates every ledger
against the fixed span-name allowlist (the canonical phases plus the
derived ``device``/``round`` windows) and rejects anything else with
:class:`TelemetryLeakError`; a span value is exactly a ``(start,
duration)`` pair of floats. There is no field in which an op type, a
client identity, or a per-op timestamp *could* travel — every span
covers the whole fixed-size round, so its timing is a function of
(capacity, batch size), never of the ops inside (obs/phases.py).

Shape stability: every recorded ledger is normalized to carry exactly
:data:`STABLE_SPANS` — configurations without durability contribute
zero-duration ``journal``/``checkpoint`` spans rather than omitting
them, so trace consumers (and the A/B tooling diffing two configs) see
the same JSON shape everywhere.

Timestamps are ``time.perf_counter`` seconds (one clock domain across
the scheduler and batcher call sites); the Chrome export converts to
microseconds as the trace-event format requires.

Span pairing: collector-side spans (assembly/verify) are stamped onto
the round's own handle (engine/batcher.py PendingRound.note_span), so a
ledger always describes exactly one round even under the pipelined
scheduler — there is no cross-round staging here.

Thread-safety: one lock around the ring; ``record_round()`` runs on the
collector thread (PendingRound.resolve), ``chrome_trace()`` on the
metrics scrape thread.
"""

from __future__ import annotations

import json
import math
import threading

from .phases import PHASES
from .registry import TelemetryLeakError, TelemetryRegistry

#: spans assembled on the host side of every round (obs/phases.py names)
HOST_SPANS = (
    "assembly", "verify", "dispatch", "journal", "checkpoint",
    "evict", "demux",
)

#: every recorded ledger carries exactly these spans (missing ones are
#: normalized to zero duration at the round start) — the stable shape
#: contract consumers rely on across durability/impl configs
STABLE_SPANS = HOST_SPANS + ("device", "round")

#: names a ledger may mention at all: the stable set plus any canonical
#: phase (sweep/replay/sort appear in calibration or recovery ledgers)
ALLOWED_SPAN_NAMES = frozenset(STABLE_SPANS) | frozenset(PHASES)


def _check_span(name: str, value) -> tuple[float, float]:
    if name not in ALLOWED_SPAN_NAMES:
        raise TelemetryLeakError(
            f"round tracer: span name {name!r} is not a round phase "
            f"(allowed: {sorted(ALLOWED_SPAN_NAMES)}) — a span is a "
            "phase, never an operation; per-op span names are how the "
            "access-pattern side channel would reopen in a trace dump"
        )
    try:
        start, dur = value
        start = float(start)
        dur = float(dur)
    except (TypeError, ValueError):
        raise TelemetryLeakError(
            f"round tracer: span {name!r} must be a (start_s, duration_s)"
            " pair of numbers — there is no field for payload data by "
            "design"
        ) from None
    if not (math.isfinite(start) and math.isfinite(dur)) or dur < 0:
        raise TelemetryLeakError(
            f"round tracer: span {name!r} has non-finite or negative "
            f"bounds ({start!r}, {dur!r})"
        )
    return start, dur


class RoundTracer:
    """Fixed-size ring of schema-checked per-round span ledgers."""

    def __init__(
        self,
        capacity: int = 512,
        registry: TelemetryRegistry | None = None,
        bubble_window: int = 64,
    ):
        if capacity <= 0:
            raise ValueError("tracer ring capacity must be positive")
        self.capacity = capacity
        self.bubble_window = max(1, bubble_window)
        self._lock = threading.Lock()
        self._ring: list[dict] = [None] * capacity  # type: ignore[list-item]
        self._n = 0  # total rounds ever recorded
        self._g_bubble = self._c_rounds = self._g_retained = None
        if registry is not None:
            self._g_bubble = registry.gauge(
                "grapevine_round_bubble_ratio",
                "windowed mean fraction of round wall clock the host is "
                "blocked waiting on the device (evict wait / round "
                "span). Double-buffered-round speedup ceiling = "
                "1/max(b, 1-b): ~2x at b~0.5, ~1x at both extremes "
                "(~0 host-bound, ~1 device-bound)")
            self._c_rounds = registry.counter(
                "grapevine_trace_rounds_total",
                "rounds recorded into the trace ring")
            self._g_retained = registry.gauge(
                "grapevine_trace_ring_rounds",
                "round ledgers currently retained in the trace ring")

    # -- recording ------------------------------------------------------

    def record_round(self, spans: dict) -> None:
        """Append one round's ledger; raises TelemetryLeakError unless
        every span fits the phase-level schema. Missing STABLE_SPANS are
        normalized to zero duration so the trace shape is identical with
        and without durability (journal/checkpoint) and across impls."""
        if not isinstance(spans, dict):
            raise TelemetryLeakError(
                "round tracer: a ledger must be a {span: (start, dur)} dict")
        merged: dict[str, tuple[float, float]] = {}
        for name, value in spans.items():
            merged[name] = _check_span(name, value)
        # anchor for normalized zero-duration spans: the round span's
        # start, else the earliest recorded start, else 0
        anchor = merged.get("round", (None, 0.0))[0]
        if anchor is None:
            anchor = min((s for s, _ in merged.values()), default=0.0)
        for name in STABLE_SPANS:
            merged.setdefault(name, (anchor, 0.0))
        with self._lock:
            self._n += 1
            self._ring[(self._n - 1) % self.capacity] = {
                "seq": self._n,
                "spans": merged,
            }
            retained = min(self._n, self.capacity)
            bubble = self._bubble_locked()
        if self._c_rounds is not None:
            self._c_rounds.inc()
            self._g_retained.set(retained)
            self._g_bubble.set(bubble)

    # -- derived signals ------------------------------------------------

    @staticmethod
    def _entry_bubble(entry: dict) -> float | None:
        spans = entry["spans"]
        _, round_dur = spans.get("round", (0.0, 0.0))
        _, evict_dur = spans.get("evict", (0.0, 0.0))
        if round_dur <= 0.0:
            return None
        return max(0.0, min(1.0, evict_dur / round_dur))

    def _recent_locked(self, k: int) -> list[dict]:
        n = min(self._n, self.capacity)
        out = []
        for i in range(max(0, n - k), n):
            out.append(self._ring[(self._n - n + i) % self.capacity])
        return out

    def _bubble_locked(self) -> float:
        ratios = [
            r for r in (
                self._entry_bubble(e)
                for e in self._recent_locked(self.bubble_window)
            )
            if r is not None
        ]
        return sum(ratios) / len(ratios) if ratios else 0.0

    def bubble_ratio(self) -> float:
        """Windowed mean host-blocked fraction (the exported gauge)."""
        with self._lock:
            return self._bubble_locked()

    def span_durations_ms(self, name: str) -> list[float]:
        """Non-zero durations (ms) of one phase span across the
        retained ledgers, oldest first — the A/B tooling's accessor
        (bench.py ``pipeline_ab``, tools/tpu_capture.py
        ``pipeline_perf``), shared so the banked journal-span
        methodology can never diverge between the two. Phase-level by
        construction: the ring holds nothing finer."""
        if name not in ALLOWED_SPAN_NAMES:
            raise ValueError(
                f"{name!r} is not a round span "
                f"(allowed: {sorted(ALLOWED_SPAN_NAMES)})"
            )
        with self._lock:
            entries = self._recent_locked(self.capacity)
        return [
            e["spans"][name][1] * 1e3
            for e in entries
            if e["spans"].get(name, (0.0, 0.0))[1] > 0.0
        ]

    # -- export ---------------------------------------------------------

    #: rounds alternate across this many lanes per track: the pipelined
    #: scheduler holds at most two rounds in flight (round k settles
    #: before round k+2 dispatches), and complete ("X") events sharing a
    #: tid must nest or stay disjoint per the trace-event format —
    #: adjacent rounds overlap, alternate rounds cannot
    _LANES = 2

    def chrome_trace(self) -> dict:
        """The retained rounds as Chrome trace-event JSON (Perfetto-
        loadable): complete ("X") events in microseconds, host spans on
        tids 1-2 and the device window on tids 3-4 of one process
        (round seq picks the lane)."""
        with self._lock:
            entries = self._recent_locked(self.capacity)
            bubble = self._bubble_locked()
            total = self._n
        events: list[dict] = [
            {"name": "process_name", "ph": "M", "pid": 1, "tid": 0,
             "args": {"name": "grapevine-engine"}},
        ]
        for lane in range(self._LANES):
            events.append(
                {"name": "thread_name", "ph": "M", "pid": 1,
                 "tid": 1 + lane,
                 "args": {"name": f"host round phases (lane {lane})"}})
            events.append(
                {"name": "thread_name", "ph": "M", "pid": 1,
                 "tid": 1 + self._LANES + lane,
                 "args": {"name": f"device window (lane {lane})"}})
        for entry in entries:
            seq = entry["seq"]
            lane = seq % self._LANES
            for name, (start, dur) in sorted(
                entry["spans"].items(), key=lambda kv: (kv[1][0], kv[0])
            ):
                events.append({
                    "name": f"grapevine/{name}",
                    "cat": "round",
                    "ph": "X",
                    "ts": int(start * 1e6),
                    "dur": max(0, int(dur * 1e6)),
                    "pid": 1,
                    "tid": (1 + self._LANES + lane) if name == "device"
                    else 1 + lane,
                    "args": {"seq": seq},
                })
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "rounds_recorded_total": total,
                "rounds_retained": len(entries),
                "bubble_ratio": round(bubble, 6),
            },
        }

    def chrome_trace_json(self) -> str:
        return json.dumps(self.chrome_trace())
