"""Central telemetry registry with a structural leak policy.

Obliviousness makes telemetry a part of the attack surface (reference
grapevine.proto:120-122): a metric keyed by client identity, message id,
or operation type IS the side channel — a scrape endpoint exporting
``round_seconds{op_type="delete"}`` leaks what the constant-shape device
round was built to hide. The registry therefore rejects dangerous
series *at registration time* instead of trusting call sites:

- label **keys** must come from :data:`ALLOWED_LABEL_KEYS` (batch-level
  dimensions only); anything else — and in particular anything in
  :data:`FORBIDDEN_LABEL_KEYS` — raises :class:`TelemetryLeakError`;
- label **values** are declared at registration and children are
  instantiated eagerly; ``labels()`` with an undeclared value raises.
  Dynamic label values are how identities leak into label sets (a
  "safe" key like ``phase`` with a session token as its value), so the
  cardinality of every series is fixed before the first sample;
- histogram **bucket boundaries** are fixed at registration — a
  data-dependent bucket layout would itself be a signal.

``audit()`` re-checks the invariants over the full registry (the
telemetry analog of testing/leakcheck.py's transcript detectors) and is
run by tools/check_telemetry_policy.py and a tier-1 test, so a metric
sneaking past the allowlist fails CI, not a security review.

Thread-safety: one lock per registry guards registration and the metric
maps; each sample mutation takes the same lock (samples are a few dict
and float ops — uncontended in practice next to the device round).
"""

from __future__ import annotations

import bisect
import math
import re
import threading

#: Batch-level label dimensions that cannot identify a client, message,
#: or operation. Everything else is rejected at registration.
ALLOWED_LABEL_KEYS = frozenset({
    "phase",   # round phase name (assembly/verify/dispatch/...)
    "tree",    # which ORAM ("rec" / "mb") — structural, not data
    "role",    # serving role ("mono" / "engine" / "frontend")
    "result",  # coarse outcome bucket ("ok" / "error")
    "shard",   # fleet shard index — declared small-integer topology
               # positions only (obs/fleet.py); never a member name,
               # address, or anything derived from traffic
    "worker",  # hostpipe worker-pool index — a config-declared position
               # (0..W-1, server/hostpipe.py), same integer-only rule as
               # shard. A worker index is NOT a channel identity: many
               # channels hash onto one worker and the mapping is the
               # public sticky-routing function, but a channel_id (or
               # anything derived from one) as a label VALUE is still
               # rejected by the declared-values rule
})

#: Known-dangerous keys, named so the registration error can say *why*.
#: The allowlist is what enforces safety; this set exists to turn "not
#: allowlisted" into "this is the side channel" for the obvious cases.
FORBIDDEN_LABEL_KEYS = frozenset({
    "client", "client_id", "session", "session_id", "channel",
    "channel_id", "user", "user_id", "identity", "auth", "auth_identity",
    "msg_id", "message_id", "sender", "recipient", "key", "block",
    "leaf", "path", "op", "op_type", "operation", "request_type",
})

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


class TelemetryLeakError(ValueError):
    """A metric registration or sample would violate the leak policy."""


def _check_labels(name: str, labels: dict[str, tuple[str, ...]] | None):
    if not labels:
        return {}
    out = {}
    for key, values in labels.items():
        if key in FORBIDDEN_LABEL_KEYS:
            raise TelemetryLeakError(
                f"metric {name!r}: label key {key!r} is per-client/per-op "
                "— exporting it reopens the access-pattern side channel "
                "(grapevine.proto:120-122); telemetry must stay "
                "batch-level"
            )
        if key not in ALLOWED_LABEL_KEYS:
            raise TelemetryLeakError(
                f"metric {name!r}: label key {key!r} is not in the "
                f"telemetry allowlist {sorted(ALLOWED_LABEL_KEYS)}"
            )
        values = tuple(str(v) for v in values)
        if not values:
            raise TelemetryLeakError(
                f"metric {name!r}: label key {key!r} declares no values "
                "— label values must be enumerated at registration "
                "(dynamic values are how identities leak into series)"
            )
        if key in ("shard", "worker"):
            # shard/worker identity is public topology (a config-
            # declared position), and ONLY that: integer indices. A
            # hostname, pod name — or a channel_id routed onto a worker
            # — as a value would export deployment or session identity
            # through every series.
            for v in values:
                if not v.isascii() or not v.isdigit():
                    raise TelemetryLeakError(
                        f"metric {name!r}: {key} label value {v!r} is "
                        "not a bare integer index — values are "
                        "declared topology positions (0..N-1), never "
                        "member names, addresses, or channel ids "
                        "(obs/fleet.py, server/hostpipe.py)"
                    )
        out[key] = values
    return out


class _Metric:
    """Base: a named family with eagerly-instantiated labeled children."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labels=None):
        if not _NAME_RE.match(name):
            raise ValueError(f"bad metric name {name!r}")
        self.name = name
        self.help = help
        self.labels_decl: dict[str, tuple[str, ...]] = _check_labels(name, labels)
        self.label_keys = tuple(self.labels_decl)
        self._lock = threading.Lock()
        self._children: dict[tuple[str, ...], object] = {}
        # eager children: every declared series exists (and exports as
        # zero) before the first sample, so scrapes see a stable schema
        for vals in self._cartesian(self.label_keys):
            self._children[vals] = self._new_child()
        if not self.label_keys:
            self._children[()] = self._new_child()

    def _cartesian(self, keys):
        if not keys:
            return
        combos = [()]
        for k in keys:
            combos = [c + (v,) for c in combos for v in self.labels_decl[k]]
        yield from combos

    def _new_child(self):
        raise NotImplementedError

    def labels(self, **kv):
        """The child for the given label values; undeclared values raise."""
        if set(kv) != set(self.label_keys):
            raise TelemetryLeakError(
                f"metric {self.name!r} takes labels {self.label_keys}, "
                f"got {tuple(kv)}"
            )
        vals = tuple(str(kv[k]) for k in self.label_keys)
        for k, v in zip(self.label_keys, vals):
            if v not in self.labels_decl[k]:
                raise TelemetryLeakError(
                    f"metric {self.name!r}: label {k}={v!r} was not "
                    "declared at registration — dynamic label values "
                    "are forbidden (fixed cardinality is the leak guard)"
                )
        return self._children[vals]

    def child(self):
        """The unlabeled child (metrics registered without labels)."""
        if self.label_keys:
            raise TelemetryLeakError(
                f"metric {self.name!r} is labeled; use .labels()"
            )
        return self._children[()]

    def series(self):
        """Yield (label_values_tuple, child) for every declared series."""
        with self._lock:
            return list(self._children.items())


class _CounterChild:
    __slots__ = ("_lock", "value")

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0.0

    def inc(self, amount: float = 1.0):
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self.value += amount


class Counter(_Metric):
    kind = "counter"

    def _new_child(self):
        return _CounterChild()

    def inc(self, amount: float = 1.0, **kv):
        (self.labels(**kv) if kv else self.child()).inc(amount)

    def get(self, **kv) -> float:
        return (self.labels(**kv) if kv else self.child()).value


class _GaugeChild:
    __slots__ = ("_lock", "value")

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0.0

    def set(self, value: float):
        with self._lock:
            self.value = float(value)

    def inc(self, amount: float = 1.0):
        with self._lock:
            self.value += amount

    def set_max(self, value: float):
        """Monotonic high-water update (value = max(value, new))."""
        with self._lock:
            self.value = max(self.value, float(value))


class Gauge(_Metric):
    kind = "gauge"

    def _new_child(self):
        return _GaugeChild()

    def set(self, value: float, **kv):
        (self.labels(**kv) if kv else self.child()).set(value)

    def set_max(self, value: float, **kv):
        (self.labels(**kv) if kv else self.child()).set_max(value)

    def inc(self, amount: float = 1.0, **kv):
        (self.labels(**kv) if kv else self.child()).inc(amount)

    def get(self, **kv) -> float:
        return (self.labels(**kv) if kv else self.child()).value


class _HistogramChild:
    __slots__ = ("_lock", "buckets", "counts", "total", "count")

    def __init__(self, buckets: tuple[float, ...]):
        self._lock = threading.Lock()
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)  # +1 for +Inf
        self.total = 0.0
        self.count = 0

    def observe(self, value: float):
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self.counts[i] += 1
            self.total += value
            self.count += 1

    def state(self) -> tuple[list[int], float, int]:
        """Consistent (counts copy, sum, count) for the read path — a
        scrape racing observe() must never render cumulative buckets
        that disagree with _count (Prometheus histogram_quantile chokes
        on torn histograms)."""
        with self._lock:
            return list(self.counts), self.total, self.count

    def quantile(self, q: float) -> float:
        """Conservative (upper-bound) quantile from the bucket counts:
        the upper edge of the bucket holding the q-th sample. Never
        under-reports, unlike linear interpolation over a small sample
        (the np.percentile bias engine/metrics.py used to have)."""
        with self._lock:
            n = self.count
            if n == 0:
                return 0.0
            rank = max(1, math.ceil(q * n))
            acc = 0
            for i, c in enumerate(self.counts):
                acc += c
                if acc >= rank:
                    return self.buckets[i] if i < len(self.buckets) else math.inf
        return math.inf


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help, buckets: tuple[float, ...], labels=None):
        buckets = tuple(float(b) for b in buckets)
        if not buckets or list(buckets) != sorted(set(buckets)):
            raise ValueError(
                f"histogram {name!r}: buckets must be a non-empty "
                "strictly-increasing tuple (fixed at registration)"
            )
        self.buckets = buckets
        super().__init__(name, help, labels)

    def _new_child(self):
        return _HistogramChild(self.buckets)

    def observe(self, value: float, **kv):
        (self.labels(**kv) if kv else self.child()).observe(value)


class TelemetryRegistry:
    """A process-local metric namespace; the unit the exporter serves.

    One registry per engine (not a module global): tests and multi-engine
    processes would otherwise collide on duplicate registration.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}

    def _register(self, metric: _Metric) -> _Metric:
        with self._lock:
            if metric.name in self._metrics:
                raise ValueError(f"duplicate metric {metric.name!r}")
            self._metrics[metric.name] = metric
        return metric

    def counter(self, name, help, labels=None) -> Counter:
        return self._register(Counter(name, help, labels))

    def gauge(self, name, help, labels=None) -> Gauge:
        return self._register(Gauge(name, help, labels))

    def histogram(self, name, help, buckets, labels=None) -> Histogram:
        return self._register(Histogram(name, help, buckets, labels))

    def collect(self) -> list[_Metric]:
        with self._lock:
            return list(self._metrics.values())

    def get(self, name: str) -> _Metric | None:
        with self._lock:
            return self._metrics.get(name)

    # -- leak audit -----------------------------------------------------

    def audit(self) -> dict:
        """Assert the whole registry is batch-level only.

        Re-validates every metric against the allowlist (defense in
        depth: _check_labels runs at registration, but an audit must not
        trust that the object was built through the public path), checks
        that no series grew labels beyond its declaration, and that
        histogram buckets are still the registration-time boundaries.
        Raises TelemetryLeakError on any violation; returns a summary.
        """
        n_series = 0
        for m in self.collect():
            _check_labels(m.name, m.labels_decl)  # raises on bad keys
            declared = set(m._cartesian(m.label_keys)) or {()}
            actual = {vals for vals, _ in m.series()}
            if not actual <= declared:
                raise TelemetryLeakError(
                    f"metric {m.name!r} grew undeclared series "
                    f"{sorted(actual - declared)}"
                )
            if isinstance(m, Histogram):
                for _, child in m.series():
                    if child.buckets != m.buckets:
                        raise TelemetryLeakError(
                            f"histogram {m.name!r}: bucket boundaries "
                            "changed after registration"
                        )
            n_series += len(actual)
        return {
            "ok": True,
            "metrics": len(self.collect()),
            "series": n_series,
        }

    # -- flat snapshot (merged health view; server/service.py) ----------

    def snapshot(self) -> dict:
        """Flat {name or name{k=v}: value} across the registry.

        Counters/gauges export their value; histograms export
        ``_count``/``_sum`` plus conservative p50/p99 — the merged
        loopback health view server/service.py returns.
        """
        out: dict[str, float] = {}
        for m in self.collect():
            for vals, child in m.series():
                suffix = (
                    "{" + ",".join(
                        f"{k}={v}" for k, v in zip(m.label_keys, vals)
                    ) + "}"
                    if vals
                    else ""
                )
                key = m.name + suffix
                if m.kind == "histogram":
                    _, total, count = child.state()
                    out[key + "_count"] = count
                    out[key + "_sum"] = round(total, 6)
                    if count:
                        out[key + "_p50"] = child.quantile(0.50)
                        out[key + "_p99"] = child.quantile(0.99)
                else:
                    out[key] = child.value
        return out
