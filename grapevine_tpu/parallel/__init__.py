"""Multi-chip parallelism: device mesh, state shardings, sharded engine step.

The reference is single-node/single-enclave (capacity "close to the RAM
limits of the machine", reference README.md:75-76); its named scale-out
future is node-to-node replication (README.md:117-121). The TPU build's
scale axis instead shards the ORAM bucket trees across a chip mesh so bus
capacity grows with pod HBM (SURVEY.md §2c, BASELINE config 5).
"""

from .mesh import (
    TREE_AXIS,
    engine_state_specs,
    init_sharded_engine,
    make_mesh,
    make_sharded_flush,
    make_sharded_step,
    shard_engine_state,
    validate_sharded_geometry,
)

__all__ = [
    "TREE_AXIS",
    "engine_state_specs",
    "init_sharded_engine",
    "make_mesh",
    "make_sharded_flush",
    "make_sharded_step",
    "shard_engine_state",
    "validate_sharded_geometry",
]
