"""Bucket-tree sharding over a JAX device mesh.

Design (the TPU re-platforming of "one enclave's EPC holds everything",
SURVEY.md §1, §2c):

- The two Path-ORAM bucket trees (records + mailbox, the only state that
  scales with bus capacity) are sharded along the bucket axis: each chip
  owns a contiguous heap-index range of ``n_buckets_padded / n_chips``
  buckets in its local HBM.
- Per access, every chip gathers the path buckets it owns and one
  ``psum`` over ICI assembles the full root→leaf working set on all chips
  (oram/path_oram.py:_path_gather) — BASELINE config 5's "stash
  all-gather over ICI" in reduce form. Write-back is purely local: each
  heap index has exactly one owner.
- Stash, position map, freelist, and all scalar bookkeeping are
  replicated; every chip executes the identical branchless program, so
  the replicated state stays bit-identical without extra collectives.
  (The position map at 2^24 entries is 64 MiB — cheap to replicate; the
  trees are the GBs.)

Communication cost per access: one psum of ``path_len * Z`` slots
(index + leaf + value words) — for the records tree at 2^24 that is
25 * 4 * 1 KiB ≈ 100 KiB over ICI per op, overlapped across the batch by
XLA's scheduler. There is no NCCL/MPI analog anywhere: chip↔chip is XLA
collectives over ICI, host↔device is one dispatch per batch round
(SURVEY.md §5 "Distributed communication backend").
"""

from __future__ import annotations

import functools

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..engine.round_step import engine_flush_step, engine_round_step
from ..engine.state import EngineConfig, EngineState
from ..oram.path_oram import OramState

#: mesh axis across which the bucket trees are sharded
TREE_AXIS = "tree"

# shard_map across the API move: newer jax exposes ``jax.shard_map``
# (replication check spelled ``check_vma``); older releases ship it as
# ``jax.experimental.shard_map.shard_map`` with ``check_rep``. Same
# semantics either way; the new name stays authoritative when present.
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
    _SHARD_MAP_NOCHECK = {"check_vma": False}
else:  # pragma: no cover - exercised only on older jaxlibs
    from jax.experimental.shard_map import shard_map as _shard_map

    _SHARD_MAP_NOCHECK = {"check_rep": False}


def make_mesh(devices=None) -> Mesh:
    """1-D mesh over the given (default: all) devices."""
    if devices is None:
        devices = jax.devices()
    return Mesh(devices, (TREE_AXIS,))


def _oram_specs() -> OramState:
    return OramState(
        tree_idx=P(TREE_AXIS),
        tree_val=P(TREE_AXIS),
        # tree-top cache planes: replicated private state (stash
        # standing) — every chip reads and writes the identical values,
        # so cache accesses need no collective (2^k−1 buckets is KBs,
        # not the GBs the sharded trees are)
        cache_idx=P(),
        cache_val=P(),
        cache_leaf=P(),
        # leaf-metadata plane (recursive posmap): sharded like tree_idx;
        # zero-length under a flat map (every shard is empty — valid)
        tree_leaf=P(TREE_AXIS),
        stash_idx=P(),
        stash_val=P(),
        stash_leaf=P(),
        # delayed-eviction buffer + window bookkeeping (PR 15):
        # REPLICATED private state, the stash's standing — decided, not
        # defaulted. Every chip's fetch round psums the identical full
        # working set (_path_gather), then runs the identical branchless
        # accumulation into these planes, so the replicas stay
        # bit-identical with zero extra collectives; sharding them would
        # buy back KBs of HBM (the buffer is E·F·≈4 entries, not the
        # GB-scale trees) at the price of a collective in the flush's
        # eviction assignment. The flush (make_sharded_flush →
        # engine_flush_step(axis_name=...) → oram_flush) reads the
        # replicated buffer ∪ stash everywhere and owner-masks only the
        # final tree/nonce scatters per chip, so the union across the
        # mesh is the single-chip flush bit for bit.
        ebuf_idx=P(),
        ebuf_val=P(),
        ebuf_leaf=P(),
        ebuf_paths=P(),
        ebuf_rounds=P(),
        ebuf_gen=P(),
        fetch_tag=P(),
        # flat: one replicated array. Recursive: a RecursivePosMapState
        # pytree — the P() prefix replicates the whole internal ORAM
        # (its own bucket tree included; sharding the *inner* tree along
        # the bucket axis is the ROADMAP item 1/3 composition point)
        posmap=P(),
        overflow=P(),
        nonces=P(TREE_AXIS),
        cipher_key=P(),
        epoch=P(),
    )


def engine_state_specs() -> EngineState:
    """PartitionSpec pytree matching EngineState: trees sharded, rest replicated."""
    return EngineState(
        rec=_oram_specs(),
        mb=_oram_specs(),
        freelist=P(),
        free_top=P(),
        recipients=P(),
        seq=P(),
        hash_key=P(),
        id_key=P(),
        rng=P(),
    )


def shard_engine_state(state: EngineState, mesh: Mesh) -> EngineState:
    """Place an engine state onto the mesh per ``engine_state_specs``."""
    specs = engine_state_specs()
    return jax.tree.map(
        lambda s, x: jax.device_put(x, NamedSharding(mesh, s)),
        specs,
        state,
        is_leaf=lambda s: isinstance(s, P),
    )


def init_sharded_engine(ecfg: EngineConfig, mesh: Mesh, seed: int = 0) -> EngineState:
    """Initialize engine state *directly* sharded over the mesh.

    ``init_engine`` + ``shard_engine_state`` stages the full state on one
    device before copying shard-wise — impossible at pod scale (a 2^24
    bus is a 32 GB records tree; one v5e chip holds 16 GB) and a 2×
    host-memory spike in simulation. Jitting the initializer with
    ``out_shardings`` lets XLA materialize each shard on its owner
    device only, so peak memory is the sharded footprint itself."""
    from ..engine.state import init_engine

    specs = engine_state_specs()
    shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda s: isinstance(s, P),
    )
    return jax.jit(
        lambda: init_engine(ecfg, seed), out_shardings=shardings
    )()


def validate_sharded_geometry(ecfg: EngineConfig, mesh: Mesh) -> None:
    """Directed refusal for knob combinations the sharded programs do
    not cover: raise a precise error naming the combination, or return.

    Everything the sharded step/flush pair DOES cover is silent here:
    evict_every >= 1 (the owner-masked flush), recursive position maps
    (inner trees replicated), tree-top caching (cache planes
    replicated), all cipher impls (the fused Pallas scatter falls back
    to the jnp cipher inside shard_map), both sort/vphases impls.
    """
    n_dev = mesh.devices.size
    for label, cfg in (("records", ecfg.rec), ("mailbox", ecfg.mb)):
        if cfg.n_buckets_padded % n_dev:
            raise ValueError(
                f"sharded path: {n_dev} mesh devices do not divide the "
                f"{label} tree's {cfg.n_buckets_padded} padded buckets "
                "— the bucket axis shards as contiguous equal heap "
                "ranges; use a power-of-two mesh no larger than the "
                "smaller tree"
            )


def make_sharded_step(ecfg: EngineConfig, mesh: Mesh):
    """Jit-compiled engine step with the bucket trees sharded over ``mesh``.

    The returned function has the same signature and semantics as
    ``engine_round_step(ecfg, state, batch)`` — the phase-major batched
    engine, i.e. the same commit schedule the single-chip production path
    uses (bit-identical results — tested in tests/test_parallel.py, the
    analog of the reference's SGX_MODE=SW simulation testing, reference
    .github/workflows/ci.yaml:15-16). Delayed eviction (``evict_every >
    1``) composes: fetch-only rounds accumulate into the REPLICATED
    eviction buffer (see ``_oram_specs``) and the owner-masked flush
    (:func:`make_sharded_flush`) drains the window.
    """
    validate_sharded_geometry(ecfg, mesh)
    specs = engine_state_specs()
    step = _shard_map(
        functools.partial(engine_round_step, ecfg, axis_name=TREE_AXIS),
        mesh=mesh,
        in_specs=(specs, P()),
        out_specs=(specs, P(), P()),
        **_SHARD_MAP_NOCHECK,
    )
    return jax.jit(step, donate_argnums=0)


def make_sharded_flush(ecfg: EngineConfig, mesh: Mesh):
    """Jit-compiled delayed-eviction flush with the trees sharded.

    Same signature and semantics as ``engine_flush_step(ecfg, state)``:
    drains the accumulated window into both trees. Inside shard_map the
    dedup + eviction assignment run replicated (the buffer ∪ stash
    working set is replicated private state) and each chip's
    scatter+encrypt pass is owner-masked to its contiguous heap range
    via the same ``_path_scatter`` machinery the sharded round uses —
    the per-chip write still carries all ``flush_target_slots`` rows
    (uniform static shape; the leak argument in oram/round.py), but
    only owned rows land, so the union across the mesh is exactly the
    single-chip flush.
    """
    if ecfg.evict_every <= 1:
        raise ValueError(
            "make_sharded_flush: evict_every=1 has no flush program — "
            "the per-round sharded step already writes back every path"
        )
    validate_sharded_geometry(ecfg, mesh)
    specs = engine_state_specs()
    flush = _shard_map(
        functools.partial(engine_flush_step, ecfg, axis_name=TREE_AXIS),
        mesh=mesh,
        in_specs=(specs,),
        out_specs=specs,
        **_SHARD_MAP_NOCHECK,
    )
    return jax.jit(flush, donate_argnums=0)
