"""Bucket-tree sharding over a JAX device mesh.

Design (the TPU re-platforming of "one enclave's EPC holds everything",
SURVEY.md §1, §2c):

- The two Path-ORAM bucket trees (records + mailbox, the only state that
  scales with bus capacity) are sharded along the bucket axis: each chip
  owns a contiguous heap-index range of ``n_buckets_padded / n_chips``
  buckets in its local HBM.
- Per access, every chip gathers the path buckets it owns and one
  ``psum`` over ICI assembles the full root→leaf working set on all chips
  (oram/path_oram.py:_path_gather) — BASELINE config 5's "stash
  all-gather over ICI" in reduce form. Write-back is purely local: each
  heap index has exactly one owner.
- Stash, position map, freelist, and all scalar bookkeeping are
  replicated; every chip executes the identical branchless program, so
  the replicated state stays bit-identical without extra collectives.
  (The position map at 2^24 entries is 64 MiB — cheap to replicate; the
  trees are the GBs.)

Communication cost per access: one psum of ``path_len * Z`` slots
(index + leaf + value words) — for the records tree at 2^24 that is
25 * 4 * 1 KiB ≈ 100 KiB over ICI per op, overlapped across the batch by
XLA's scheduler. There is no NCCL/MPI analog anywhere: chip↔chip is XLA
collectives over ICI, host↔device is one dispatch per batch round
(SURVEY.md §5 "Distributed communication backend").
"""

from __future__ import annotations

import functools

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..engine.round_step import engine_round_step
from ..engine.state import EngineConfig, EngineState
from ..oram.path_oram import OramState

#: mesh axis across which the bucket trees are sharded
TREE_AXIS = "tree"

# shard_map across the API move: newer jax exposes ``jax.shard_map``
# (replication check spelled ``check_vma``); older releases ship it as
# ``jax.experimental.shard_map.shard_map`` with ``check_rep``. Same
# semantics either way; the new name stays authoritative when present.
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
    _SHARD_MAP_NOCHECK = {"check_vma": False}
else:  # pragma: no cover - exercised only on older jaxlibs
    from jax.experimental.shard_map import shard_map as _shard_map

    _SHARD_MAP_NOCHECK = {"check_rep": False}


def make_mesh(devices=None) -> Mesh:
    """1-D mesh over the given (default: all) devices."""
    if devices is None:
        devices = jax.devices()
    return Mesh(devices, (TREE_AXIS,))


def _oram_specs() -> OramState:
    return OramState(
        tree_idx=P(TREE_AXIS),
        tree_val=P(TREE_AXIS),
        # tree-top cache planes: replicated private state (stash
        # standing) — every chip reads and writes the identical values,
        # so cache accesses need no collective (2^k−1 buckets is KBs,
        # not the GBs the sharded trees are)
        cache_idx=P(),
        cache_val=P(),
        cache_leaf=P(),
        # leaf-metadata plane (recursive posmap): sharded like tree_idx;
        # zero-length under a flat map (every shard is empty — valid)
        tree_leaf=P(TREE_AXIS),
        stash_idx=P(),
        stash_val=P(),
        stash_leaf=P(),
        # delayed-eviction buffer + window bookkeeping (PR 15): would be
        # replicated private state with the stash's standing, but the
        # sharded path currently supports evict_every=1 ONLY — there is
        # no sharded flush program yet (engine_flush_step/oram_flush
        # take no axis_name; composing the deduplicated flush targets
        # with bucket-axis sharding is the ROADMAP item-1∘2 follow-up),
        # so make_sharded_step rejects delayed-eviction geometries and
        # these specs only ever carry the zero-length E=1 planes
        ebuf_idx=P(),
        ebuf_val=P(),
        ebuf_leaf=P(),
        ebuf_paths=P(),
        ebuf_rounds=P(),
        ebuf_gen=P(),
        fetch_tag=P(),
        # flat: one replicated array. Recursive: a RecursivePosMapState
        # pytree — the P() prefix replicates the whole internal ORAM
        # (its own bucket tree included; sharding the *inner* tree along
        # the bucket axis is the ROADMAP item 1/3 composition point)
        posmap=P(),
        overflow=P(),
        nonces=P(TREE_AXIS),
        cipher_key=P(),
        epoch=P(),
    )


def engine_state_specs() -> EngineState:
    """PartitionSpec pytree matching EngineState: trees sharded, rest replicated."""
    return EngineState(
        rec=_oram_specs(),
        mb=_oram_specs(),
        freelist=P(),
        free_top=P(),
        recipients=P(),
        seq=P(),
        hash_key=P(),
        id_key=P(),
        rng=P(),
    )


def shard_engine_state(state: EngineState, mesh: Mesh) -> EngineState:
    """Place an engine state onto the mesh per ``engine_state_specs``."""
    specs = engine_state_specs()
    return jax.tree.map(
        lambda s, x: jax.device_put(x, NamedSharding(mesh, s)),
        specs,
        state,
        is_leaf=lambda s: isinstance(s, P),
    )


def init_sharded_engine(ecfg: EngineConfig, mesh: Mesh, seed: int = 0) -> EngineState:
    """Initialize engine state *directly* sharded over the mesh.

    ``init_engine`` + ``shard_engine_state`` stages the full state on one
    device before copying shard-wise — impossible at pod scale (a 2^24
    bus is a 32 GB records tree; one v5e chip holds 16 GB) and a 2×
    host-memory spike in simulation. Jitting the initializer with
    ``out_shardings`` lets XLA materialize each shard on its owner
    device only, so peak memory is the sharded footprint itself."""
    from ..engine.state import init_engine

    specs = engine_state_specs()
    shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda s: isinstance(s, P),
    )
    return jax.jit(
        lambda: init_engine(ecfg, seed), out_shardings=shardings
    )()


def make_sharded_step(ecfg: EngineConfig, mesh: Mesh):
    """Jit-compiled engine step with the bucket trees sharded over ``mesh``.

    The returned function has the same signature and semantics as
    ``engine_round_step(ecfg, state, batch)`` — the phase-major batched
    engine, i.e. the same commit schedule the single-chip production path
    uses (bit-identical results — tested in tests/test_parallel.py, the
    analog of the reference's SGX_MODE=SW simulation testing, reference
    .github/workflows/ci.yaml:15-16).
    """
    if ecfg.evict_every > 1:
        # no sharded flush program exists yet: a shard_map'd
        # engine_flush_step would scatter the full deduplicated target
        # set into every local shard unmasked (oram_flush is
        # axis_name-less), corrupting the trees — refuse loudly instead
        # of accumulating windows that can never drain (the item-1∘2
        # composition is on the ROADMAP)
        raise ValueError(
            "delayed batched eviction (evict_every > 1) is not "
            "supported on the sharded path yet — use evict_every=1 "
            "with make_sharded_step"
        )
    specs = engine_state_specs()
    step = _shard_map(
        functools.partial(engine_round_step, ecfg, axis_name=TREE_AXIS),
        mesh=mesh,
        in_specs=(specs, P()),
        out_specs=(specs, P(), P()),
        **_SHARD_MAP_NOCHECK,
    )
    return jax.jit(step, donate_argnums=0)
