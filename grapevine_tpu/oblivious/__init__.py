"""Data-oblivious vector primitives (the TPU analog of aligned-cmov).

The reference's storage layer is built on constant-time conditional moves
(upstream ``aligned-cmov``, SURVEY.md §2b). On TPU the same discipline is
the *natural* programming model: all selection is `jnp.where` over full
vectors, all control flow is masks, nothing branches on secret data.
"""
