"""Pallas TPU kernel: fused ChaCha keystream + XOR over bucket rows.

The jnp cipher path (bucket_cipher.row_keystream) materializes the full
keystream in HBM — at B=2048 on the records tree that is an extra
~170 MB written and re-read per round, pure HBM-bandwidth overhead
(PERF.md "next levers" 2). This kernel generates the keystream in VMEM
tile by tile and XORs it into the row data in the same pass: one HBM
read + one HBM write per row, no keystream traffic. The slot-index and
value arrays are separate kernel refs, so no concatenated staging copy
is made either.

Layout: the keystream uses the j-major stream order defined by
``row_keystream`` — word ``m`` of a row comes from ChaCha state word
``m // n_blocks`` of block ``m % n_blocks`` — so each of the 16 output
state arrays ([rows, n_blocks]) is a *contiguous lane range* of the
keystream tile and assembly is a concatenate, not a 16-way interleave.
The ChaCha core itself (quarter-round, constants, round schedule) is
imported from bucket_cipher so the two implementations cannot drift;
bit-identical ciphertext is asserted by tests/test_pallas_cipher.py,
making engine states interchangeable between impls.

Off-TPU the kernel runs in Pallas interpret mode (CI's CPU backend —
the SGX_MODE=SW analog), so the selection knob is safe everywhere;
``cipher_impl="pallas"`` on real TPU compiles the Mosaic kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .bucket_cipher import _SIGMA, _qr

U32 = jnp.uint32

#: VMEM budget per input/output tile (bytes) used to pick the row tile
_TILE_BYTES = 1 << 21


def keystream_tile(key_ref, n1, n2, n3, nb, rounds):
    """ChaCha keystream for a [TR, nb]-shaped tile of rows, j-major.

    ``n1/n2/n3`` are the per-row nonce words broadcast to [TR, nb];
    the counter word is the block index within the row. The ONE copy
    of the in-kernel ChaCha block shared by every Pallas cipher kernel
    (this module's XOR kernel and pallas_gather.py's fused fetch and
    write-back) — the round schedule and state layout cannot drift
    between them."""
    tr = n1.shape[0]
    ctr = jax.lax.broadcasted_iota(U32, (tr, nb), 1)
    init = [jnp.full((tr, nb), U32(c)) for c in _SIGMA]
    init += [jnp.broadcast_to(key_ref[0, i], (tr, nb)) for i in range(8)]
    init += [ctr, n1, n2, n3]
    s = list(init)
    for _ in range(rounds // 2):
        _qr(s, 0, 4, 8, 12)
        _qr(s, 1, 5, 9, 13)
        _qr(s, 2, 6, 10, 14)
        _qr(s, 3, 7, 11, 15)
        _qr(s, 0, 5, 10, 15)
        _qr(s, 1, 6, 11, 12)
        _qr(s, 2, 7, 8, 13)
        _qr(s, 3, 4, 9, 14)
    # j-major assembly: 16 contiguous [TR, nb] lane ranges
    return jnp.concatenate([a + b for a, b in zip(s, init)], axis=1)


def _cipher_kernel(
    key_ref, bucket_ref, epoch_ref, idx_ref, val_ref, oidx_ref, oval_ref,
    *, nb, z, n_words, rounds,
):
    """One row tile: (idx [TR, z], val [TR, W-z]) ^= keystream rows."""
    tr = idx_ref.shape[0]
    n1 = jnp.broadcast_to(bucket_ref[:, 0][:, None], (tr, nb))
    n2 = jnp.broadcast_to(epoch_ref[:, 0][:, None], (tr, nb))
    n3 = jnp.broadcast_to(epoch_ref[:, 1][:, None], (tr, nb))
    ks = keystream_tile(key_ref, n1, n2, n3, nb, rounds)
    written = ((epoch_ref[:, 0] != U32(0)) | (epoch_ref[:, 1] != U32(0)))[:, None]
    oidx_ref[:, :] = idx_ref[:, :] ^ jnp.where(written, ks[:, :z], U32(0))
    oval_ref[:, :] = val_ref[:, :] ^ jnp.where(
        written, ks[:, z:n_words], U32(0)
    )


@functools.partial(jax.jit, static_argnames=("rounds", "interpret"))
def cipher_rows_pallas(
    key: jax.Array,  # u32[8]
    bucket: jax.Array,  # u32[R]
    epoch: jax.Array,  # u32[R, 2]; 0 = identity (never written)
    pidx: jax.Array,  # u32[R, z] slot-index words
    pval: jax.Array,  # u32[R, W-z] value words
    rounds: int = 8,
    interpret: bool = False,
):
    """Fused ``row ^ keystream``; returns (pidx', pval'), both u32."""
    r, z = pidx.shape
    w = z + pval.shape[1]
    nb = (w + 15) // 16
    # Mosaic tiling: the row tile is the second-minor block dim of every
    # rank-2 operand, so it must be a multiple of 8 (the u32 sublane
    # count); the budget-derived value is rounded down to keep VMEM
    # bounded, with 8 as the floor
    tr = max(8, min(512, _TILE_BYTES // max(1, 16 * nb * 4)) // 8 * 8)
    # pad rows to a tile multiple; padded rows carry epoch 0 (identity)
    r_pad = -(-r // tr) * tr
    if r_pad != r:
        pad = r_pad - r
        bucket = jnp.pad(bucket, (0, pad))
        epoch = jnp.pad(epoch, ((0, pad), (0, 0)))
        pidx = jnp.pad(pidx, ((0, pad), (0, 0)))
        pval = jnp.pad(pval, ((0, pad), (0, 0)))
    oidx, oval = pl.pallas_call(
        functools.partial(
            _cipher_kernel, nb=nb, z=z, n_words=w, rounds=rounds
        ),
        grid=(r_pad // tr,),
        in_specs=[
            pl.BlockSpec((1, 8), lambda i: (0, 0)),
            # rank-1 blocks must tile by 128 on TPU; carry the bucket id
            # as a [rows, 1] column instead so tr only needs 8-alignment
            pl.BlockSpec((tr, 1), lambda i: (i, 0)),
            pl.BlockSpec((tr, 2), lambda i: (i, 0)),
            pl.BlockSpec((tr, z), lambda i: (i, 0)),
            pl.BlockSpec((tr, w - z), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((tr, z), lambda i: (i, 0)),
            pl.BlockSpec((tr, w - z), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((r_pad, z), U32),
            jax.ShapeDtypeStruct((r_pad, w - z), U32),
        ],
        interpret=interpret,
    )(key[None, :], bucket[:, None], epoch, pidx, pval)
    return oidx[:r], oval[:r]
