"""Pallas TPU kernel: fused path-row gather + ChaCha decrypt.

PERF.md "next levers" 2: the unfused round does

    gather rows (HBM read + HBM write of the gathered copy)
    → keystream XOR (read + write again, or the fused cipher kernel)

i.e. the gathered working set crosses HBM at least twice before the
engine sees plaintext. This kernel performs the gather *and* the
decrypt in one pass: each grid step DMAs one tree row into VMEM (the
row index comes from the scalar-prefetched path-bucket vector, the
standard Pallas TPU dynamic-gather pattern), generates that row's
keystream in VMEM, and writes the decrypted row to the output — the
row's ciphertext never lands in HBM a second time and no keystream is
ever materialized.

Scope: the single-chip fetch path (``axis_name is None``). The sharded
path keeps gather → psum → decrypt: buckets are decrypted only *after*
ICI assembly, so tree plaintext never transits the interconnect —
fusing there would trade that property for bandwidth.

Like the fused cipher kernel (pallas_cipher.py) this reuses
bucket_cipher's ChaCha core verbatim and is asserted bit-identical to
the jnp path (tests/test_pallas_gather.py); off-TPU it runs in Pallas
interpret mode.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_cipher import keystream_tile

U32 = jnp.uint32

#: HBM-resident ref memory space across the pallas-tpu API rename:
#: newer jax exposes ``pltpu.MemorySpace.HBM``; older releases spell
#: the same "leave it in HBM, kernel DMAs tiles itself" contract
#: ``TPUMemorySpace.ANY`` (the idiom all the manual-DMA examples of
#: that era used). getattr keeps the new name authoritative when
#: present, so TPU-validated behavior is unchanged there.
_MS = getattr(pltpu, "MemorySpace", None)
HBM = _MS.HBM if _MS is not None else pltpu.TPUMemorySpace.ANY


def _gather_kernel(
    bucket_ref,  # scalar-prefetch: u32[R] row indices (the public path)
    key_ref,  # u32[1, 1, 8]
    idx_row_ref,  # u32[1, 1, z]      tree_idx row bucket_ref[i]
    val_row_ref,  # u32[1, 1, z*v]    tree_val row bucket_ref[i]
    nonce_row_ref,  # u32[1, 1, 2]    epoch nonce of that row
    oidx_ref,  # u32[1, 1, z]
    oval_ref,  # u32[1, 1, z*v]
    *,
    nb,
    z,
    n_words,
    rounds,
):
    # refs are rank-3 [1, 1, width]: Mosaic requires the last TWO block
    # dims be 8/128-divisible or equal to the array dims, and a gather
    # block is one non-contiguous row — so rows live on a leading
    # (untiled) axis and the trailing (1, width) plane equals the array
    i = pl.program_id(0)
    bid = bucket_ref[i]
    n1 = jnp.full((1, nb), bid, U32)
    n2 = jnp.broadcast_to(nonce_row_ref[0, 0, 0], (1, nb))
    n3 = jnp.broadcast_to(nonce_row_ref[0, 0, 1], (1, nb))
    ks = keystream_tile(key_ref[0], n1, n2, n3, nb, rounds)
    written = (
        (nonce_row_ref[0, 0, 0] != U32(0)) | (nonce_row_ref[0, 0, 1] != U32(0))
    )
    oidx_ref[0, 0, :] = idx_row_ref[0, 0, :] ^ jnp.where(
        written, ks[0, :z], U32(0)
    )
    oval_ref[0, 0, :] = val_row_ref[0, 0, :] ^ jnp.where(
        written, ks[0, z:n_words], U32(0)
    )


@functools.partial(
    jax.jit, static_argnames=("z", "rounds", "interpret")
)
def gather_decrypt_rows(
    key: jax.Array,  # u32[8]
    tree_idx: jax.Array,  # u32[n_padded * z] (flat slot words)
    tree_val: jax.Array,  # u32[n_padded, z*v]
    nonces: jax.Array,  # u32[n_padded, 2]
    flat_b: jax.Array,  # u32[R] heap-bucket indices (public transcript)
    z: int,
    rounds: int = 8,
    interpret: bool = False,
):
    """(pidx u32[R, z], pval u32[R, z*v]) — gathered AND decrypted.

    ``rounds=0`` (plaintext trees) still uses the fused gather so the
    single-chip fetch is one HBM pass either way.
    """
    n_padded = tree_val.shape[0]
    zv = tree_val.shape[1]
    r = flat_b.shape[0]
    w = z + zv
    nb = (w + 15) // 16
    idx_rows = tree_idx.reshape(n_padded, z)
    if rounds == 0:
        # no cipher: plain dynamic-slice gather (XLA emits one pass)
        return idx_rows[flat_b], tree_val[flat_b]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(r,),
        in_specs=[
            pl.BlockSpec((1, 1, 8), lambda i, b_ref: (0, 0, 0)),
            pl.BlockSpec(
                (1, 1, z), lambda i, b_ref: (b_ref[i].astype(jnp.int32), 0, 0)
            ),
            pl.BlockSpec(
                (1, 1, zv), lambda i, b_ref: (b_ref[i].astype(jnp.int32), 0, 0)
            ),
            pl.BlockSpec(
                (1, 1, 2), lambda i, b_ref: (b_ref[i].astype(jnp.int32), 0, 0)
            ),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, z), lambda i, b_ref: (i, 0, 0)),
            pl.BlockSpec((1, 1, zv), lambda i, b_ref: (i, 0, 0)),
        ],
    )
    oidx, oval = pl.pallas_call(
        functools.partial(
            _gather_kernel, nb=nb, z=z, n_words=w, rounds=rounds
        ),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((r, 1, z), U32),
            jax.ShapeDtypeStruct((r, 1, zv), U32),
        ],
        interpret=interpret,
    )(flat_b, key[None, None, :], idx_rows[:, None, :], tree_val[:, None, :],
      nonces[:, None, :])
    return oidx[:, 0, :], oval[:, 0, :]


def _gather_tiled_kernel(
    bucket_ref,  # scalar-prefetch: u32[R_pad] row indices (public path)
    key_ref,  # u32[1, 8] (VMEM)
    idx_hbm,  # u32[n, z]   whole tree_idx, stays in HBM
    val_hbm,  # u32[n, zv]  whole tree_val, stays in HBM
    non_hbm,  # u32[n, 2]   whole nonces, stays in HBM
    oidx_ref,  # u32[T, z]  (VMEM out block)
    oval_ref,  # u32[T, zv]
    scr_idx,  # u32[T, z]   VMEM scratch
    scr_val,  # u32[T, zv]
    scr_non,  # u32[T, 2]
    sems,  # DMA semaphores (T, 3)
    *,
    t,
    nb,
    z,
    n_words,
    rounds,
):
    """T rows per grid step: T×3 async row DMAs issued back-to-back,
    then ONE vectorized [T, nb] keystream + XOR. Amortizes per-step
    pipeline overhead and fills the VPU lanes that the one-row kernel
    leaves idle (a [1, nb] ChaCha tile uses 1 of 8 sublanes)."""
    i = pl.program_id(0)

    def dmas(k):
        row = bucket_ref[i * t + k]
        return (
            pltpu.make_async_copy(idx_hbm.at[row], scr_idx.at[k], sems.at[k, 0]),
            pltpu.make_async_copy(val_hbm.at[row], scr_val.at[k], sems.at[k, 1]),
            pltpu.make_async_copy(non_hbm.at[row], scr_non.at[k], sems.at[k, 2]),
        )

    for k in range(t):  # static unroll: issue every DMA before any wait
        for d in dmas(k):
            d.start()
    for k in range(t):
        for d in dmas(k):
            d.wait()
    bids = jnp.stack([bucket_ref[i * t + k] for k in range(t)])  # [T]
    n1 = jnp.broadcast_to(bids[:, None], (t, nb))
    n2 = jnp.broadcast_to(scr_non[:, 0][:, None], (t, nb))
    n3 = jnp.broadcast_to(scr_non[:, 1][:, None], (t, nb))
    ks = keystream_tile(key_ref, n1, n2, n3, nb, rounds)
    written = ((scr_non[:, 0] != U32(0)) | (scr_non[:, 1] != U32(0)))[:, None]
    oidx_ref[:, :] = scr_idx[:, :] ^ jnp.where(written, ks[:, :z], U32(0))
    oval_ref[:, :] = scr_val[:, :] ^ jnp.where(
        written, ks[:, z:n_words], U32(0)
    )


@functools.partial(
    jax.jit, static_argnames=("z", "rounds", "tile", "interpret")
)
def gather_decrypt_rows_tiled(
    key: jax.Array,  # u32[8]
    tree_idx: jax.Array,  # u32[n_padded * z]
    tree_val: jax.Array,  # u32[n_padded, z*v]
    nonces: jax.Array,  # u32[n_padded, 2]
    flat_b: jax.Array,  # u32[R] heap-bucket indices (public transcript)
    z: int,
    rounds: int = 8,
    tile: int = 8,
    interpret: bool = False,
):
    """Tiled variant of :func:`gather_decrypt_rows` (same contract).

    The trees stay in HBM (``MemorySpace.HBM`` refs) and each grid step
    manually DMAs ``tile`` rows into VMEM scratch — the Pallas analog of
    a batched dynamic gather — instead of one pipelined block per row.
    At B=2048 the one-row grid is ~43k steps; this cuts it ``tile``-fold
    and runs the ChaCha tile [T, nb] wide. ``tile=8`` keeps the output
    block sublane-aligned (u32 tiling is (8, 128)).
    """
    n_padded = tree_val.shape[0]
    zv = tree_val.shape[1]
    r = flat_b.shape[0]
    w = z + zv
    nb = (w + 15) // 16
    idx_rows = tree_idx.reshape(n_padded, z)
    if rounds == 0:
        return idx_rows[flat_b], tree_val[flat_b]
    r_pad = -(-r // tile) * tile
    if r_pad != r:
        # padded steps fetch row 0 harmlessly; outputs are sliced off
        flat_b = jnp.pad(flat_b, (0, r_pad - r))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(r_pad // tile,),
        in_specs=[
            pl.BlockSpec((1, 8), lambda i, b_ref: (0, 0)),
            pl.BlockSpec(memory_space=HBM),
            pl.BlockSpec(memory_space=HBM),
            pl.BlockSpec(memory_space=HBM),
        ],
        out_specs=[
            pl.BlockSpec((tile, z), lambda i, b_ref: (i, 0)),
            pl.BlockSpec((tile, zv), lambda i, b_ref: (i, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((tile, z), U32),
            pltpu.VMEM((tile, zv), U32),
            pltpu.VMEM((tile, 2), U32),
            pltpu.SemaphoreType.DMA((tile, 3)),
        ],
    )
    oidx, oval = pl.pallas_call(
        functools.partial(
            _gather_tiled_kernel, t=tile, nb=nb, z=z, n_words=w,
            rounds=rounds,
        ),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((r_pad, z), U32),
            jax.ShapeDtypeStruct((r_pad, zv), U32),
        ],
        interpret=interpret,
    )(flat_b, key[None, :], idx_rows, tree_val, nonces)
    return oidx[:r], oval[:r]


def _scatter_kernel(
    bucket_ref,  # scalar-prefetch: u32[R] write targets (junk-redirected)
    key_ref,  # u32[1, 1, 8]
    idx_new_ref,  # u32[1, 1, z]    plaintext row i to write
    val_new_ref,  # u32[1, 1, z*v]
    epoch_ref,  # u32[1, 1, 2]     write epoch (same for all rows)
    tree_idx_in_ref,  # aliased input (unread; aliasing carries state)
    tree_val_in_ref,  # aliased input (unread)
    nonces_in_ref,  # aliased input (unread)
    otree_idx_ref,  # u32[1, 1, z]   aliased tree_idx row bucket_ref[i]
    otree_val_ref,  # u32[1, 1, zv]  aliased tree_val row bucket_ref[i]
    ononce_ref,  # u32[1, 1, 2]     aliased nonce row bucket_ref[i]
    *,
    nb,
    z,
    n_words,
    rounds,
):
    # rank-3 refs for the same Mosaic tiling reason as _gather_kernel
    i = pl.program_id(0)
    bid = bucket_ref[i]
    n1 = jnp.full((1, nb), bid, U32)
    n2 = jnp.broadcast_to(epoch_ref[0, 0, 0], (1, nb))
    n3 = jnp.broadcast_to(epoch_ref[0, 0, 1], (1, nb))
    ks = keystream_tile(key_ref[0], n1, n2, n3, nb, rounds)
    otree_idx_ref[0, 0, :] = idx_new_ref[0, 0, :] ^ ks[0, :z]
    otree_val_ref[0, 0, :] = val_new_ref[0, 0, :] ^ ks[0, z:n_words]
    # the write epoch rides the same pass — the separate XLA nonce
    # scatter the jnp path pays (round.py) has no fused-path cost at all
    ononce_ref[0, 0, :] = epoch_ref[0, 0, :]


def _scatter_tiled_kernel(
    bucket_ref,  # scalar-prefetch: u32[R_pad] targets (junk-redirected)
    key_ref,  # u32[1, 8] (VMEM)
    idx_new_ref,  # u32[T, z]   plaintext rows (VMEM block)
    val_new_ref,  # u32[T, zv]
    epoch_ref,  # u32[1, 2]     write epoch (VMEM)
    tree_idx_in,  # aliased HBM input (unread)
    tree_val_in,  # aliased HBM input (unread)
    nonces_in,  # aliased HBM input (unread)
    oidx_hbm,  # u32[n, z]   aliased HBM output
    oval_hbm,  # u32[n, zv]  aliased HBM output
    onon_hbm,  # u32[n, 2]   aliased HBM output
    scr_idx,  # u32[T, z]   VMEM scratch (ciphertext staging)
    scr_val,  # u32[T, zv]
    scr_non,  # u32[T, 2]
    sems,  # DMA semaphores (T, 3)
    *,
    t,
    nb,
    z,
    n_words,
    rounds,
):
    """Write-back mirror of :func:`_gather_tiled_kernel`: one [T, nb]
    keystream, then T×3 async row DMAs VMEM→HBM. Junk-redirected rows
    may race on the junk row; its bytes are never read."""
    i = pl.program_id(0)
    bids = jnp.stack([bucket_ref[i * t + k] for k in range(t)])  # [T]
    n1 = jnp.broadcast_to(bids[:, None], (t, nb))
    n2 = jnp.broadcast_to(epoch_ref[0, 0], (t, nb))
    n3 = jnp.broadcast_to(epoch_ref[0, 1], (t, nb))
    ks = keystream_tile(key_ref, n1, n2, n3, nb, rounds)
    scr_idx[:, :] = idx_new_ref[:, :] ^ ks[:, :z]
    scr_val[:, :] = val_new_ref[:, :] ^ ks[:, z:n_words]
    scr_non[:, :] = jnp.broadcast_to(epoch_ref[0, :], (t, 2))

    def dmas(k):
        row = bucket_ref[i * t + k]
        return (
            pltpu.make_async_copy(scr_idx.at[k], oidx_hbm.at[row], sems.at[k, 0]),
            pltpu.make_async_copy(scr_val.at[k], oval_hbm.at[row], sems.at[k, 1]),
            pltpu.make_async_copy(scr_non.at[k], onon_hbm.at[row], sems.at[k, 2]),
        )

    for k in range(t):
        for d in dmas(k):
            d.start()
    for k in range(t):
        for d in dmas(k):
            d.wait()


@functools.partial(
    jax.jit,
    static_argnames=("z", "rounds", "tile", "interpret"),
    donate_argnums=(1, 2, 3),
)
def scatter_encrypt_rows_tiled(
    key: jax.Array,  # u32[8]
    tree_idx: jax.Array,  # u32[n_padded * z] (updated in place)
    tree_val: jax.Array,  # u32[n_padded, z*v] (updated in place)
    nonces: jax.Array,  # u32[n_padded, 2] (updated in place)
    flat_b: jax.Array,  # u32[R] heap-bucket targets (public transcript)
    owner: jax.Array,  # bool[R]; False rows must not write
    epoch: jax.Array,  # u32[2]
    new_pidx: jax.Array,  # u32[R, z]
    new_pval: jax.Array,  # u32[R, z*v]
    z: int,
    rounds: int,
    tile: int = 8,
    interpret: bool = False,
):
    """Tiled variant of :func:`scatter_encrypt_rows` (same contract).

    Padded steps and non-owner rows both redirect to the junk row;
    DMA write races there are benign (the row is never read).
    """
    n_padded = tree_val.shape[0]
    zv = tree_val.shape[1]
    r = flat_b.shape[0]
    w = z + zv
    nb = (w + 15) // 16
    idx_rows = tree_idx.reshape(n_padded, z)
    junk = U32(n_padded - 1)
    tgt = jnp.where(owner, flat_b, junk)
    r_pad = -(-r // tile) * tile
    if r_pad != r:
        pad = r_pad - r
        tgt = jnp.pad(tgt, (0, pad), constant_values=n_padded - 1)
        new_pidx = jnp.pad(new_pidx, ((0, pad), (0, 0)))
        new_pval = jnp.pad(new_pval, ((0, pad), (0, 0)))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(r_pad // tile,),
        in_specs=[
            pl.BlockSpec((1, 8), lambda i, b_ref: (0, 0)),
            pl.BlockSpec((tile, z), lambda i, b_ref: (i, 0)),
            pl.BlockSpec((tile, zv), lambda i, b_ref: (i, 0)),
            pl.BlockSpec((1, 2), lambda i, b_ref: (0, 0)),
            pl.BlockSpec(memory_space=HBM),
            pl.BlockSpec(memory_space=HBM),
            pl.BlockSpec(memory_space=HBM),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=HBM),
            pl.BlockSpec(memory_space=HBM),
            pl.BlockSpec(memory_space=HBM),
        ],
        scratch_shapes=[
            pltpu.VMEM((tile, z), U32),
            pltpu.VMEM((tile, zv), U32),
            pltpu.VMEM((tile, 2), U32),
            pltpu.SemaphoreType.DMA((tile, 3)),
        ],
    )
    oidx, oval, ononce = pl.pallas_call(
        functools.partial(
            _scatter_tiled_kernel, t=tile, nb=nb, z=z, n_words=w,
            rounds=rounds,
        ),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((n_padded, z), U32),
            jax.ShapeDtypeStruct((n_padded, zv), U32),
            jax.ShapeDtypeStruct((n_padded, 2), U32),
        ],
        # operands incl. scalar prefetch: tgt=0, key=1, new_pidx=2,
        # new_pval=3, epoch=4, idx_rows=5, tree_val=6, nonces=7
        input_output_aliases={5: 0, 6: 1, 7: 2},
        interpret=interpret,
    )(tgt, key[None, :], new_pidx, new_pval, epoch[None, :], idx_rows,
      tree_val, nonces)
    return oidx.reshape(-1), oval, ononce


@functools.partial(
    jax.jit,
    static_argnames=("z", "rounds", "interpret"),
    donate_argnums=(1, 2, 3),
)
def scatter_encrypt_rows(
    key: jax.Array,  # u32[8]
    tree_idx: jax.Array,  # u32[n_padded * z] (flat; updated in place)
    tree_val: jax.Array,  # u32[n_padded, z*v] (updated in place)
    nonces: jax.Array,  # u32[n_padded, 2] (updated in place)
    flat_b: jax.Array,  # u32[R] heap-bucket targets (public transcript)
    owner: jax.Array,  # bool[R]; False rows must not write
    epoch: jax.Array,  # u32[2] the write epoch for every owned row
    new_pidx: jax.Array,  # u32[R, z] plaintext rows to commit
    new_pval: jax.Array,  # u32[R, z*v]
    z: int,
    rounds: int,
    interpret: bool = False,
):
    """Encrypt + write back owned path rows in ONE HBM pass.

    The write-back mirror of :func:`gather_decrypt_rows`: each grid
    step generates its row's keystream in VMEM and writes the
    ciphertext straight into the (input/output-aliased) tree arrays —
    the encrypted copy never exists as a separate HBM array, and rows
    no grid step targets keep their contents via the aliasing.
    Non-owner rows (duplicate-bucket fetch copies) are redirected to
    the padded junk bucket, which heap indices never address; owner
    targets are unique, so writes never conflict (the junk row takes
    several writes — last wins, never read). The per-row write epoch
    (nonce) is committed in the same pass, so the fused path needs no
    separate XLA nonce scatter.

    Returns the updated ``(tree_idx, tree_val, nonces)``.
    """
    n_padded = tree_val.shape[0]
    zv = tree_val.shape[1]
    r = flat_b.shape[0]
    w = z + zv
    nb = (w + 15) // 16
    idx_rows = tree_idx.reshape(n_padded, z)
    # non-owners write the junk row (n_padded - 1: heap indices stop at
    # n_buckets = n_padded - 1, see OramConfig.n_buckets_padded)
    junk = U32(n_padded - 1)
    tgt = jnp.where(owner, flat_b, junk)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(r,),
        in_specs=[
            pl.BlockSpec((1, 1, 8), lambda i, b_ref: (0, 0, 0)),
            pl.BlockSpec((1, 1, z), lambda i, b_ref: (i, 0, 0)),
            pl.BlockSpec((1, 1, zv), lambda i, b_ref: (i, 0, 0)),
            pl.BlockSpec((1, 1, 2), lambda i, b_ref: (0, 0, 0)),
            # aliased tree inputs: unread by the kernel (constant row-0
            # block so the pipeline loads stay trivial)
            pl.BlockSpec((1, 1, z), lambda i, b_ref: (0, 0, 0)),
            pl.BlockSpec((1, 1, zv), lambda i, b_ref: (0, 0, 0)),
            pl.BlockSpec((1, 1, 2), lambda i, b_ref: (0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec(
                (1, 1, z), lambda i, b_ref: (b_ref[i].astype(jnp.int32), 0, 0)
            ),
            pl.BlockSpec(
                (1, 1, zv), lambda i, b_ref: (b_ref[i].astype(jnp.int32), 0, 0)
            ),
            pl.BlockSpec(
                (1, 1, 2), lambda i, b_ref: (b_ref[i].astype(jnp.int32), 0, 0)
            ),
        ],
    )
    oidx, oval, ononce = pl.pallas_call(
        functools.partial(
            _scatter_kernel, nb=nb, z=z, n_words=w, rounds=rounds
        ),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((n_padded, 1, z), U32),
            jax.ShapeDtypeStruct((n_padded, 1, zv), U32),
            jax.ShapeDtypeStruct((n_padded, 1, 2), U32),
        ],
        # operand indices count ALL inputs incl. the scalar prefetch:
        # tgt=0, key=1, new_pidx=2, new_pval=3, epoch=4, idx_rows=5,
        # tree_val=6, nonces=7
        input_output_aliases={5: 0, 6: 1, 7: 2},
        interpret=interpret,
    )(tgt, key[None, None, :], new_pidx[:, None, :], new_pval[:, None, :],
      epoch[None, None, :], idx_rows[:, None, :], tree_val[:, None, :],
      nonces[:, None, :])
    return oidx.reshape(-1), oval[:, 0, :], ononce[:, 0, :]
