"""BucketCipher: on-device keystream encryption of HBM bucket rows.

In the reference, ORAM contents live inside SGX's hardware-encrypted EPC
(reference README.md:16,49) — the operator snapshotting RAM sees only
ciphertext. A TPU has no enclave, so this module supplies the equivalent
property for the bucket trees at rest in HBM: every bucket row is XORed
with a ChaCha keystream keyed by a device-resident secret, the bucket's
heap index, and a per-write epoch nonce, so

- a memory snapshot reveals nothing about record contents or slot
  metadata (which blocks live where);
- rewriting a bucket with identical plaintext yields fresh ciphertext
  (the epoch advances every round), so snapshot diffing shows only
  *that* the transcript's buckets were written — which the transcript
  already reveals.

Cipher: RFC 7539 ChaCha block function on the 16-word state
``[consts | key(8) | block_ctr | bucket | epoch | 0]`` — i.e. standard
ChaCha with counter = in-row block index and nonce = (bucket, epoch, 0),
vectorized over rows and blocks in pure jnp (fully fused by XLA; the
MXU is untouched, this rides the VPU). ``rounds`` is configurable:
20 = RFC ChaCha20; the engine default is 8 (ChaCha8, unbroken, standard
in perf-sensitive deployments) because keystream cost scales linearly
with rounds. SURVEY.md §7 hard-part 3 names AES-CTR with a documented
fallback: this is that documented fallback — AES without AES-NI/VPU
byte-shuffles would be a bitsliced Pallas project for strictly worse
throughput at no security gain over ChaCha.

Epoch-0 convention: ``nonce == 0`` marks a never-written bucket and maps
to the identity keystream (the all-zero initial tree is its own
ciphertext). The operator learns which buckets were never written —
information the public access transcript already contains. The keystream
is still *computed* for every row and masked, so work is
content-independent.

The stash, position map, and freelist stay plaintext: they are private
working state (the EPC analog — see the threat model in
oram/path_oram.py), not part of the HBM bucket-tree surface this cipher
protects. Key material (u32[8]) lives in OramState, never in the tree.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

U32 = jnp.uint32

#: "expand 32-byte k"
_SIGMA = (0x61707865, 0x3320646E, 0x79622D32, 0x6B206574)


def _rotl(x: jax.Array, n: int) -> jax.Array:
    return (x << U32(n)) | (x >> U32(32 - n))


def _qr(s, a, b, c, d):
    s[a] = s[a] + s[b]
    s[d] = _rotl(s[d] ^ s[a], 16)
    s[c] = s[c] + s[d]
    s[b] = _rotl(s[b] ^ s[c], 12)
    s[a] = s[a] + s[b]
    s[d] = _rotl(s[d] ^ s[a], 8)
    s[c] = s[c] + s[d]
    s[b] = _rotl(s[b] ^ s[c], 7)


def chacha_blocks(
    key: jax.Array,  # u32[8]
    counter: jax.Array,  # u32[...] block counter per lane
    n1: jax.Array,  # u32[...] nonce word 1 (bucket heap index)
    n2: jax.Array,  # u32[...] nonce word 2 (write epoch, low word)
    n3: jax.Array | None = None,  # u32[...] nonce word 3 (epoch, high word)
    rounds: int = 8,
) -> jax.Array:
    """ChaCha block function, vectorized: → u32[..., 16] keystream."""
    zero = jnp.zeros_like(counter) if n3 is None else jnp.broadcast_to(n3, counter.shape)
    init = [jnp.broadcast_to(U32(c), counter.shape) for c in _SIGMA]
    init += [jnp.broadcast_to(key[i], counter.shape) for i in range(8)]
    init += [counter, n1, n2, zero]
    s = list(init)
    for _ in range(rounds // 2):
        _qr(s, 0, 4, 8, 12)
        _qr(s, 1, 5, 9, 13)
        _qr(s, 2, 6, 10, 14)
        _qr(s, 3, 7, 11, 15)
        _qr(s, 0, 5, 10, 15)
        _qr(s, 1, 6, 11, 12)
        _qr(s, 2, 7, 8, 13)
        _qr(s, 3, 4, 9, 14)
    # feedforward (state + init, mod 2^32 by RFC 7539) as a plain loop:
    # a listcomp would put the adds in a `<listcomp>` frame on py<=3.11,
    # making the rangelint allowlist site key python-version-dependent
    out = []
    for a, b in zip(s, init):
        out.append(a + b)
    return jnp.stack(out, axis=-1)


def row_keystream(
    key: jax.Array,  # u32[8]
    bucket: jax.Array,  # u32[R]
    epoch: jax.Array,  # u32[R, 2] (lo, hi); 0 = identity (never written)
    n_words: int,
    rounds: int = 8,
) -> jax.Array:
    """Keystream rows u32[R, n_words]; zero rows where epoch == 0.

    The epoch is 64 bits across two nonce words, so the per-round write
    counter cannot wrap within any feasible bus lifetime — a u32 epoch
    would wrap after 2^32 rounds (~1.4 years at 100 rounds/s), landing
    one access in plaintext (epoch 0) and replaying every historical
    (bucket, epoch) pair into a two-time pad for a snapshot-diffing
    operator."""
    r = bucket.shape[0]
    n_blocks = (n_words + 15) // 16
    ctr = jnp.broadcast_to(
        jnp.arange(n_blocks, dtype=U32)[None, :], (r, n_blocks)
    )
    ks = chacha_blocks(
        key, ctr, bucket[:, None], epoch[:, None, 0], epoch[:, None, 1], rounds
    )  # [r, n_blocks, 16]
    # j-major stream order: all blocks' word 0, then word 1, … — a fixed
    # permutation of the stream (PRF security is order-independent) that
    # keeps each of the 16 state words contiguous along the lane axis,
    # matching the Pallas kernel's layout (concatenate, no interleave)
    ks = jnp.swapaxes(ks, -1, -2).reshape(r, n_blocks * 16)[:, :n_words]
    written = (epoch[:, 0] != 0) | (epoch[:, 1] != 0)
    return jnp.where(written[:, None], ks, U32(0))


def epoch_next(epoch: jax.Array) -> jax.Array:
    """Advance a u32[2] (lo, hi) epoch counter with carry."""
    lo = epoch[0] + U32(1)
    hi = epoch[1] + jnp.where(lo == 0, U32(1), U32(0))
    return jnp.stack([lo, hi])


# NOTE: whole-tree passes (the expiry sweep) decrypt/re-encrypt entire
# rows chunk-by-chunk via engine/expiry.py:_chunked_tree_sweep; there is
# no partial-word decrypt API on purpose — CTR-mode random access would
# permit one, but nothing uses it and the sweep's cost model is the
# full-row recrypt documented there.
