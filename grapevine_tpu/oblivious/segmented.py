"""Segmented parallel-prefix primitives for vectorized batch semantics.

The batched engine resolves within-round read-after-write chains without
any sequential ``lax.scan`` (measured at ~30-130µs per iteration on TPU —
the dominant cost of the whole framework before this module existed).
Chains are grouped by key, sorted so each group is contiguous, and
resolved with **segmented associative scans** in O(log B) depth.

The workhorse is the *saturating-counter monoid*: functions of the form

    f(x) = min(max(x + a, lo), hi)

which are closed under composition — exactly the algebra of a bounded
counter walk (mailbox occupancy: CREATE = min(x+1, cap), zero-id DELETE
pop = max(x-1, 0), everything else = identity). Composing the per-op
steps with an exclusive segmented scan yields every op's
"count before me" in parallel, clamps included — the trick familiar from
parallel bracket matching.

All shapes are static and data-independent; values flow only through
min/max/add — the same oblivious discipline as the rest of the package.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

I32 = jnp.int32

#: lo/hi sentinels for the identity element (int32-safe, never saturate)
_NEG = jnp.int32(-(1 << 30))
_POS = jnp.int32(1 << 30)


def sat_identity(shape=()):
    """Identity element of the saturating-counter monoid."""
    return (
        jnp.zeros(shape, I32),
        jnp.full(shape, _NEG, I32),
        jnp.full(shape, _POS, I32),
    )


def sat_elem(add, lo, hi):
    """Element f(x) = min(max(x + add, lo), hi); args broadcastable i32."""
    return (
        jnp.asarray(add, I32),
        jnp.asarray(lo, I32),
        jnp.asarray(hi, I32),
    )


def sat_compose(f, g):
    """(g ∘ f): apply f first, then g. Both (add, lo, hi) triples.

    g(f(x)) = min(max(min(max(x+a1, l1), h1) + a2, l2), h2)
            = min(max(x + a1+a2, l'), h')   with
      l' = min(max(l1 + a2, l2), h2)
      h' = min(max(h1 + a2, l2), h2)
    """
    a1, l1, h1 = f
    a2, l2, h2 = g
    return (
        a1 + a2,
        jnp.minimum(jnp.maximum(l1 + a2, l2), h2),
        jnp.minimum(jnp.maximum(h1 + a2, l2), h2),
    )


def sat_apply(f, x):
    """Apply a saturating element to a counter value."""
    a, lo, hi = f
    return jnp.minimum(jnp.maximum(x + a, lo), hi)


def segmented_exclusive_sat_scan(elems, seg_start):
    """Exclusive segmented scan of saturating elements along axis 0.

    elems: (add, lo, hi) each i32[B], in segment-contiguous order.
    seg_start: bool[B], True at the first element of each segment.

    Returns (add, lo, hi) prefix elements: prefix[j] composes
    elems[s..j) where s is j's segment start (identity at segment
    starts). O(log B) depth via ``jax.lax.associative_scan``.
    """

    def combine(x, y):
        xs, xf = x
        ys, yf = y
        f = jax.tree.map(
            lambda keep, merged: jnp.where(ys, keep, merged),
            yf,
            sat_compose(xf, yf),
        )
        return (xs | ys, f)

    flags = seg_start.astype(jnp.bool_)
    _, incl = jax.lax.associative_scan(combine, (flags, elems))
    # exclusive: shift right within segments; segment starts get identity
    ident = sat_identity(seg_start.shape)
    excl = jax.tree.map(
        lambda i, v: jnp.where(
            seg_start, i, jnp.roll(v, 1, axis=0)
        ),
        ident,
        incl,
    )
    return excl


def group_sort(group: jax.Array, sort_impl: str = "xla",
               key_bits: int | None = None):
    """Stable permutation ordering ops by (group, slot).

    group: u32[B] group id per op (e.g. the first-occurrence slot of the
    op's key). Returns (perm, inv, seg_start_sorted):
    ``x[perm]`` is segment-contiguous, ``y[inv]`` undoes it, and
    seg_start marks group boundaries in sorted order.

    ``sort_impl="radix"`` with a declared ``key_bits`` bound computes
    the same permutation with counting passes instead of a comparison
    sort (oblivious/radix.py) — bit-identical outputs, zero ``sort``
    HLO; without a declared bound the XLA sort is kept.
    """
    if sort_impl == "radix" and key_bits is not None:
        from .radix import radix_group_sort

        return radix_group_sort([group], key_bits)
    perm = jnp.argsort(group, stable=True)  # stable ⇒ slot order
    inv = jnp.argsort(perm)
    sorted_g = group[perm]
    seg_start = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), sorted_g[1:] != sorted_g[:-1]]
    )
    return perm, inv, seg_start


def segmented_counts_before(group: jax.Array, flags: jax.Array) -> jax.Array:
    """#True flags among earlier ops of the same group, per op. O(B²) mask.

    Cheap and simple for B ≤ a few thousand; use the sorted scans above
    only where clamping (saturation) is required.
    """
    b = group.shape[0]
    same = group[:, None] == group[None, :]
    earlier = jnp.tril(jnp.ones((b, b), jnp.bool_), k=-1)
    return jnp.sum((same & earlier) & flags[None, :], axis=1).astype(I32)


# ----------------------------------------------------------------------
# sort-based grouping over multi-word keys (the O(B log B) forms the
# scan vphases implementation builds on — no [B,B] mask anywhere)
# ----------------------------------------------------------------------


def multiword_group_sort(cols):
    """Permutation ordering ops by a multi-word key, then slot.

    ``cols``: sequence of u32[B] key words, most significant first.
    Returns ``(perm, inv, seg_start)`` like `group_sort`: ``x[perm]`` is
    segment-contiguous with ops in slot order within each segment (the
    slot index rides as the final sort key, so no stability assumption),
    ``y[inv]`` undoes it, and ``seg_start`` marks group boundaries in
    sorted order. One variadic O(B log B) device sort.
    """
    cols = [jnp.asarray(c) for c in cols]
    b = cols[0].shape[0]
    iota = jnp.arange(b, dtype=jnp.uint32)
    out = jax.lax.sort(
        tuple(cols) + (iota,), num_keys=len(cols) + 1, is_stable=False
    )
    perm = out[-1]
    neq = jnp.zeros((b - 1,), jnp.bool_)
    for k in out[:-1]:
        neq = neq | (k[1:] != k[:-1])
    seg_start = jnp.concatenate([jnp.ones((1,), jnp.bool_), neq])
    inv = jnp.zeros((b,), jnp.uint32).at[perm].set(iota, unique_indices=True)
    return perm, inv, seg_start


def segment_bounds(seg_start: jax.Array):
    """Per element: the index of its segment's first and last element.

    ``seg_start``: bool[B] in segment-contiguous (sorted) order. Both
    returned arrays are i32[B] in the same order; O(log B) via cummax /
    cummin.
    """
    b = seg_start.shape[0]
    iota = jnp.arange(b, dtype=I32)
    start = jax.lax.cummax(jnp.where(seg_start, iota, 0))
    is_last = jnp.concatenate([seg_start[1:], jnp.ones((1,), jnp.bool_)])
    end = jnp.flip(jax.lax.cummin(jnp.flip(jnp.where(is_last, iota, b - 1))))
    return start, end


def segmented_scan(vals: jax.Array, seg_start: jax.Array, op):
    """Inclusive segmented scan of ``op`` (associative) along axis 0.

    ``vals``: [B, ...] in segment-contiguous order; ``seg_start``
    bool[B]. Standard flagged-operator trick: a segment start resets the
    running aggregate. O(log B) depth via ``lax.associative_scan``.
    """

    def combine(x, y):
        xs, xv = x
        ys, yv = y
        ysb = ys.reshape(ys.shape + (1,) * (yv.ndim - ys.ndim))
        return (xs | ys, jnp.where(ysb, yv, op(xv, yv)))

    _, out = jax.lax.associative_scan(combine, (seg_start, vals))
    return out


def segmented_sum_before(
    vals: jax.Array, seg_start: jax.Array, bounds=None
) -> jax.Array:
    """Exclusive segmented sum along axis 0 (i32). ``vals`` [B, ...] in
    segment-contiguous order — unsegmented cumsum re-based at each
    segment start (exact in i32; callers sum bounded counts).
    ``bounds``: optional precomputed ``segment_bounds(seg_start)`` so
    repeat callers (one group, many queries) pay for it once."""
    v = vals.astype(I32)
    c = jnp.cumsum(v, axis=0)
    start = (segment_bounds(seg_start) if bounds is None else bounds)[0]
    excl = c - v
    return excl - excl[start]


def segmented_sum_total(
    vals: jax.Array, seg_start: jax.Array, bounds=None
) -> jax.Array:
    """Per-element total sum over its whole segment (i32), axis 0.
    ``bounds`` as in `segmented_sum_before`."""
    v = vals.astype(I32)
    c = jnp.cumsum(v, axis=0)
    start, end = segment_bounds(seg_start) if bounds is None else bounds
    return c[end] - (c[start] - v[start])
