"""Data-oblivious LSD radix-rank over bounded keys — no comparison sort.

Every hot sort in the engine orders a *bounded* key: eviction sorts the
working set by leaf (``height+1`` bits), round dedup sorts by block
index (``log2(blocks)+1`` bits), the scan vphases impl groups ops by
bucket/record index, the admission walk groups by first-occurrence slot
(``log2(B)`` bits). XLA lowers ``lax.sort``/``jnp.argsort`` for those
to a generic comparison sort — a serial ``while`` thunk on XLA:CPU
(measured as the round's floor after PR 3; PERF.md Round 6) and a
bitonic network on TPU. A least-significant-digit radix *rank* does
the same job in a fixed number of counting passes: per pass one
conflict-free scatter-bincount, one cumsum, two gathers — all
fully-vectorized, shape-static, data-independent. This is the standard
move in hardware-oblivious-memory designs (Palermo, arXiv:2411.05400;
BOLT, arXiv:2509.01742): replace comparison networks with fixed-shape
counting passes that parallelize on wide SIMD/MXU hardware.

Obliviousness: pass count, shapes, and the instruction trace depend
only on the static ``(key_bits, bits_per_pass, B)`` — never on key
values. Values flow through scatters/gathers at *rank* positions,
which are private-working-memory accesses with exactly the standing
the existing ``group_sort`` permutations already have (the EPC analog;
see the threat-model notes in oram/path_oram.py and engine/vphases.py).

Contract: ``radix_rank`` is bit-identical to
``jnp.argsort(keys, stable=True)`` and ``radix_group_sort`` to
``segmented.multiword_group_sort`` for keys within their declared
bound (tests/test_radix.py). Keys must be *declared* bounded — there
is deliberately no hash-down fallback for wide keys: a correctness
property must never silently depend on a hash, so sorts over undeclared
or >``MAX_RADIX_BITS`` keys stay on ``lax.sort`` (the 256-bit
recipient-key sort in engine/vphases.py is the canonical example).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..obs.phases import device_phase

U32 = jnp.uint32
I32 = jnp.int32

#: ceiling on the total declared key width of one ``radix_group_sort``
#: call. Wider keys (e.g. the 256-bit recipient pubkey) take more
#: counting passes than a comparison sort is worth and must stay on
#: ``lax.sort`` — the explicit refusal is the guard against "just hash
#: it down", which would silently trade correctness for speed.
MAX_RADIX_BITS = 64


def _check_static(key_bits: int, bits_per_pass: int) -> None:
    if not isinstance(key_bits, int) or not 1 <= key_bits <= 32:
        raise ValueError(
            f"key_bits must be an int in [1, 32], got {key_bits!r}"
        )
    if not isinstance(bits_per_pass, int) or not 1 <= bits_per_pass <= 16:
        raise ValueError(
            f"bits_per_pass must be an int in [1, 16], got {bits_per_pass!r}"
        )


def _check_declared_bound(keys, key_bits: int) -> None:
    """Concrete (non-traced) keys are validated against the declared
    bound — an out-of-range key would silently mis-rank (high bits never
    enter any pass), so the eager path raises instead. Inside jit the
    keys are tracers and the caller's declared bound is the contract."""
    if key_bits >= 32 or isinstance(keys, jax.core.Tracer):
        return
    k = np.asarray(keys)
    if k.size and int(k.max()) >> key_bits:
        raise ValueError(
            f"key {int(k.max())} exceeds the declared {key_bits}-bit bound"
        )


def _rank_pass(digit: jax.Array, nbins: int) -> jax.Array:
    """Stable counting-sort positions for one digit column.

    digit: i32[B] in [0, nbins). Returns i32[B] — a permutation of
    [0, B): position j goes to ``offset[digit[j]] + (# i < j with
    digit[i] == digit[j])``. No comparison sort, no bool/f32
    intermediate wider than [B] (the jaxpr-audit discipline of
    tests/test_vphases_scan.py), O(B·nbins) integer work.
    """
    b = digit.shape[0]
    iota = jnp.arange(b, dtype=I32)
    # the max/min clamps below are runtime identities (each states an
    # invariant of counting ranks: an exclusive prefix never exceeds its
    # position, a permutation never exceeds B-1) written so a
    # non-relational interval domain (analysis/rangelint.py) can carry
    # the bound instead of widening to 2B — which would escape int32 at
    # the 2^30 certified geometry
    if nbins == 2:
        # the 1-bit pass needs no bin table: two exclusive ranks
        incl = jnp.cumsum(digit)
        ones_before = jnp.concatenate([jnp.zeros((1,), I32), incl[:-1]])
        zeros_before = jnp.maximum(iota - ones_before, 0)
        n_zeros = jnp.maximum(b - incl[-1], 0)
        # n_zeros + ones_before <= B-1 truly (a stable partition is a
        # permutation) but sums to 2B in interval arithmetic — escaping
        # int32 at B = 2^30; the add rides RANGE_ALLOWLIST and the clip
        # re-bounds the permutation for downstream (runtime identity)
        return jnp.clip(
            jnp.where(digit == 1, n_zeros + ones_before, zeros_before),
            0, b - 1,
        )
    # scatter-bincount one-hot (integer scatter — no [B, nbins] bool),
    # inclusive cumsum down the batch axis, then two gathers: the last
    # row is the per-bin total, the (j, digit[j]) entry the within-bin
    # inclusive rank
    oh = jnp.zeros((b, nbins), I32).at[iota, digit].set(
        1, unique_indices=True
    )
    csum = jnp.cumsum(oh, axis=0)
    within = jnp.maximum(
        jnp.take_along_axis(csum, digit[:, None], axis=1)[:, 0] - 1, 0
    )
    counts = csum[-1]
    # exclusive bin offsets, as the shifted inclusive cumsum
    binc = jnp.cumsum(counts)
    offs = jnp.concatenate([jnp.zeros((1,), I32), binc[:-1]])
    return jnp.minimum(offs[digit] + within, b - 1)


def partition_rank(flags) -> jax.Array:
    """Positions of a stable two-way partition (False first): i32[B].

    The 1-bit counting pass exposed directly — ``pos[i]`` is where
    element i lands when all False-flagged elements precede all True
    ones, each side in original order. The expiry sweep's freelist
    rebuild is exactly this pass (engine/expiry.py).
    """
    return _rank_pass(jnp.asarray(flags).astype(I32), 2)


def radix_rank(keys, key_bits: int, bits_per_pass: int = 8) -> jax.Array:
    """Stable ascending permutation of bounded u32 keys: u32[B].

    ``keys[perm]`` is sorted ascending with ties in original order —
    bit-identical to ``jnp.argsort(keys, stable=True)`` for
    ``keys < 2**key_bits`` — computed in ``ceil(key_bits /
    bits_per_pass)`` counting passes with zero ``sort`` HLO ops.
    """
    _check_static(key_bits, bits_per_pass)
    _check_declared_bound(keys, key_bits)
    keys = jnp.asarray(keys).astype(U32)
    b = keys.shape[0]
    perm = jnp.arange(b, dtype=U32)
    with device_phase("radix_rank"):
        for shift in range(0, key_bits, bits_per_pass):
            pbits = min(bits_per_pass, key_bits - shift)
            with device_phase(f"radix_pass_s{shift}"):
                cur = keys[perm]
                digit = (
                    (cur >> U32(shift)) & U32((1 << pbits) - 1)
                ).astype(I32)
                pos = _rank_pass(digit, 1 << pbits)
                perm = jnp.zeros((b,), U32).at[pos].set(
                    perm, unique_indices=True
                )
    return perm


def radix_group_sort(cols, key_bits, bits_per_pass: int = 8):
    """Drop-in for ``segmented.multiword_group_sort`` over declared-
    bounded keys: ``(perm, inv, seg_start)``, bit-identical outputs.

    ``cols``: sequence of u32[B] key words, most significant first.
    ``key_bits``: the declared bit bound — an int for a single column,
    else a sequence aligned with ``cols``. The total declared width
    must not exceed ``MAX_RADIX_BITS``; wider keys raise so the caller
    keeps ``lax.sort`` (never a hash). Stability of the LSD passes
    makes the slot index an implicit final key, exactly like the iota
    word ``multiword_group_sort`` appends.
    """
    cols = [jnp.asarray(c).astype(U32) for c in cols]
    if not cols:
        raise ValueError("radix_group_sort needs at least one key column")
    bits = [key_bits] if isinstance(key_bits, int) else list(key_bits)
    if len(bits) != len(cols):
        raise ValueError(
            f"key_bits must declare a bound per column: "
            f"{len(bits)} bounds for {len(cols)} columns"
        )
    for kb in bits:
        _check_static(kb, bits_per_pass)
    if sum(bits) > MAX_RADIX_BITS:
        raise ValueError(
            f"declared key width {sum(bits)} exceeds MAX_RADIX_BITS="
            f"{MAX_RADIX_BITS}; keep lax.sort for wide keys (hashing "
            f"them down would make correctness depend on a hash)"
        )
    b = cols[0].shape[0]
    perm = jnp.arange(b, dtype=U32)
    with device_phase("radix_group_sort"):
        # least-significant column first; each column's stable passes
        # preserve the order established by the columns after it
        for ci in range(len(cols) - 1, -1, -1):
            c, kb = cols[ci], bits[ci]
            _check_declared_bound(c, kb)
            for shift in range(0, kb, bits_per_pass):
                pbits = min(bits_per_pass, kb - shift)
                cur = c[perm]
                digit = (
                    (cur >> U32(shift)) & U32((1 << pbits) - 1)
                ).astype(I32)
                pos = _rank_pass(digit, 1 << pbits)
                perm = jnp.zeros((b,), U32).at[pos].set(
                    perm, unique_indices=True
                )
    neq = jnp.zeros((b - 1,), jnp.bool_)
    for c in cols:
        sc = c[perm]
        neq = neq | (sc[1:] != sc[:-1])
    seg_start = jnp.concatenate([jnp.ones((1,), jnp.bool_), neq])
    inv = jnp.zeros((b,), U32).at[perm].set(
        jnp.arange(b, dtype=U32), unique_indices=True
    )
    return perm, inv, seg_start
