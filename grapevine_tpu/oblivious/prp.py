"""Keyed small-domain PRP: block indices → random-looking id words.

The reference assigns fully random nonzero msg_ids precisely so onlookers
cannot probe id structure (reference grapevine.proto:66-79). This engine
embeds the record's physical block index in the id so lookup needs no
id→block oblivious map — but a raw index would leak allocator state
(LIFO free-list position ⇒ a proxy for global create/delete volume) to
every client through its own ids. Instead id words 0-1 are the Feistel
encryption of ``(block_index, fresh 32-bit nonce)`` under a secret
per-bus key — a bijection on the ``bits + 32``-bit joint space
(``bits = log2(max_messages)``), so ids remain collision-free among live
records and decodable on the device in a few vector ops, while clients
see fresh random-looking values on every create. The nonce matters: the
free list is LIFO, so a deterministic single-word PRP would hand a
create→delete→create client the *same* ciphertext back — a repeatable
1-bit probe of whether anyone else created in between. With the nonce in
the plaintext every encryption is fresh (Luby-Rackoff; the adversary
never gets an encryption/decryption oracle here, ids only ever flow
engine→client).

Visible structure: ciphertext word 1 is always < 2**bits — this reveals
only the bus capacity order, a public config value.

Obliviousness note: encrypt/decrypt are branchless fixed-shape jnp ops,
identical work for every op — nothing about the transcript depends on
the key or plaintext.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

U32 = jnp.uint32

ROUNDS = 4


def _f(x: jax.Array, k: jax.Array) -> jax.Array:
    """Murmur-style one-way mixer: (half, round key) → u32."""
    x = (x ^ k) * U32(0xCC9E2D51)
    x = ((x << 15) | (x >> 17)) * U32(0x1B873593)
    x = x ^ (x >> 13)
    x = x * U32(0x85EBCA6B)
    return x ^ (x >> 16)


def _halves(bits: int) -> list[tuple[int, int]]:
    """(left, right) bit widths per round; halves swap every round."""
    a, b = bits - (bits // 2), bits // 2
    out = []
    for _ in range(ROUNDS):
        out.append((a, b))
        a, b = b, a
    return out


def prp_encrypt(key: jax.Array, x: jax.Array, bits: int) -> jax.Array:
    """Bijection on [0, 2**bits); key u32[ROUNDS]; x u32[...]. Bits above
    ``bits`` are ignored on input and zero on output."""
    if bits <= 1:
        return x & U32((1 << bits) - 1)
    sizes = _halves(bits)
    a0, b0 = sizes[0]
    left = (x >> b0) & U32((1 << a0) - 1)
    right = x & U32((1 << b0) - 1)
    for i, (a, b) in enumerate(sizes):
        left, right = right, left ^ (_f(right, key[i]) & U32((1 << a) - 1))
    # after ROUNDS (even) swaps the widths are back to (a0, b0)
    return (left << b0) | right


def prp_decrypt(key: jax.Array, y: jax.Array, bits: int) -> jax.Array:
    if bits <= 1:
        return y & U32((1 << bits) - 1)
    sizes = _halves(bits)
    a0, b0 = sizes[0]
    left = (y >> b0) & U32((1 << a0) - 1)
    right = y & U32((1 << b0) - 1)
    for i in range(ROUNDS - 1, -1, -1):
        a, _b = sizes[i]
        left, right = right ^ (_f(left, key[i]) & U32((1 << a) - 1)), left
    return (left << b0) | right


def _halves2(bits: int) -> list[tuple[int, int]]:
    """(left, right) widths per round for the two-word PRP: left starts
    as the 32-bit nonce lane, right as the ``bits``-bit index lane."""
    a, b = 32, bits
    out = []
    for _ in range(ROUNDS):
        out.append((a, b))
        a, b = b, a
    return out


def _mask(nbits: int) -> jnp.uint32:
    return U32(0xFFFFFFFF) if nbits >= 32 else U32((1 << nbits) - 1)


def prp2_encrypt(key: jax.Array, x: jax.Array, nonce: jax.Array, bits: int):
    """Bijection on [0, 2**32) × [0, 2**bits): (nonce, block index) →
    (word0 u32, word1 < 2**bits). key u32[ROUNDS]; x/nonce u32[...]."""
    left = nonce
    right = x & _mask(bits)
    for i, (a, _b) in enumerate(_halves2(bits)):
        left, right = right, left ^ (_f(right, key[i]) & _mask(a))
    # ROUNDS is even ⇒ widths are back to (32, bits)
    return left, right


def prp2_decrypt(key: jax.Array, w0: jax.Array, w1: jax.Array, bits: int):
    """Inverse of prp2_encrypt; returns the block index (nonce discarded)."""
    sizes = _halves2(bits)
    left, right = w0, w1 & _mask(bits)
    for i in range(ROUNDS - 1, -1, -1):
        a, _b = sizes[i]
        left, right = right ^ (_f(left, key[i]) & _mask(a)), left
    return right  # (left, right) = (nonce, index)
