"""Oblivious (branchless, constant-shape) building blocks.

Every function here is a pure jnp program whose *instruction trace and
memory addresses are independent of the data values* — the vectorized
analog of the reference's constant-time cmov discipline (upstream
``aligned-cmov``; SURVEY.md §2b). Secret-dependent decisions only ever
appear as mask values flowing through `jnp.where`.

Conventions:
- multi-word values (keys, ids) are uint32 arrays with the word axis last;
- masks are bool arrays;
- "select one row" helpers use one-hot masked sums, never gathers at a
  secret-dependent index (a gather's address would put the secret in the
  access transcript).
"""

from __future__ import annotations

import jax.numpy as jnp

import numpy as np

U32 = jnp.uint32
#: sentinel for "empty slot" in index arrays (a numpy scalar, not a device
#: array: importing this package must not initialize a JAX backend)
SENTINEL = np.uint32(0xFFFFFFFF)


def cmov(cond, a, b):
    """Constant-shape conditional move: cond ? a : b (broadcasting where)."""
    return jnp.where(cond, a, b)


def words_equal(a, b):
    """Rowwise equality of multi-word values: a[..., W] == b[..., W] → bool[...]."""
    return jnp.all(a == b, axis=-1)


def is_zero_words(a):
    """True where a multi-word value is all-zero (invalid key / empty id)."""
    return jnp.all(a == 0, axis=-1)


def onehot_select(mask, values):
    """Select the single row of ``values`` where ``mask`` is True.

    mask: bool[N]; values: u32[N, ...] → u32[...]. If the mask has no (or
    several) set lanes the result is the masked sum — callers guarantee
    at-most-one match (an ORAM/table invariant) and handle the none-set
    case via a separate ``found`` flag.
    """
    m = mask.astype(values.dtype)
    m = m.reshape(m.shape + (1,) * (values.ndim - m.ndim))
    return jnp.sum(values * m, axis=0)


def first_true_onehot(mask):
    """One-hot of the first True lane (all-False → all-False). bool[N]→bool[N]."""
    idx = jnp.argmax(mask)  # 0 if none set; guarded below
    onehot = jnp.arange(mask.shape[0]) == idx
    return onehot & jnp.any(mask)


def argmin_u64_onehot(valid, hi, lo):
    """One-hot of the valid lane with the smallest (hi, lo) pair.

    valid: bool[N]; hi, lo: u32[N] (a u64 split into words — jax runs with
    x64 disabled, so the comparison is done lexicographically in u32).
    Invalid lanes rank as +inf; ties break toward the lowest lane index.
    Returns (onehot bool[N], any_valid bool).
    """
    inf = jnp.uint32(0xFFFFFFFF)
    hi_m = jnp.where(valid, hi, inf)
    min_hi = jnp.min(hi_m)
    cand = valid & (hi_m == min_hi)
    lo_m = jnp.where(cand, lo, inf)
    min_lo = jnp.min(lo_m)
    return first_true_onehot(cand & (lo_m == min_lo)), jnp.any(valid)


def rank_of(mask):
    """Exclusive prefix count of True lanes: rank[i] = #True among mask[:i].

    Computed as the shifted inclusive cumsum rather than
    ``cumsum(m) - m``: identical values (exclusive prefix, always
    >= 0), but interval-transparent — a non-relational domain
    (analysis/rangelint.py) cannot see that a prefix sum dominates its
    own last term, so the subtraction form reads as "can go to -1" and
    poisons every downstream u32 cast."""
    m = mask.astype(jnp.int32)
    incl = jnp.cumsum(m)
    return jnp.concatenate([jnp.zeros((1,), jnp.int32), incl[:-1]])

def u64_add_u32(lo, hi, k):
    """(lo, hi) + k with carry — u64 arithmetic in u32 lanes (x64 off)."""
    s = lo + k
    return s, hi + (s < lo).astype(lo.dtype)


def u64_le(a_lo, a_hi, b_lo, b_hi):
    """a <= b over (lo, hi) u32 lane pairs."""
    return (a_hi < b_hi) | ((a_hi == b_hi) & (a_lo <= b_lo))


def u64_sub(a_lo, a_hi, b_lo, b_hi):
    """a - b (mod 2^64) over u32 lane pairs."""
    lo = a_lo - b_lo
    return lo, a_hi - b_hi - (a_lo < b_lo).astype(a_lo.dtype)


def lex_argsort(lo, hi, axis=-1):
    """Ascending argsort by the 64-bit key (hi, lo), u32 lanes.

    Two stable passes: sort by the low lanes, then by the high lanes —
    lexicographic order without u64 dtypes (jax runs with x64 off).
    """
    p1 = jnp.argsort(lo, axis=axis, stable=True)
    hi_p = jnp.take_along_axis(hi, p1, axis=axis)
    p2 = jnp.argsort(hi_p, axis=axis, stable=True)
    return jnp.take_along_axis(p1, p2, axis=axis)
