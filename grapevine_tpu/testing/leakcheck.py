"""Transcript leak detectors — the obliviousness "sanitizer" (SURVEY §5).

The framework's security claim is empirical: the public transcript (the
sequence of tree leaves fetched per op per round) must be a sequence of
independent uniform draws, carrying no information about which logical
keys were touched. The reference gets the equivalent property from SGX
(the operator sees only encrypted EPC traffic, reference README.md:16);
here it must be *checked*, the way a race detector checks a lock
discipline. These detectors operationalize the three testable facets:

1. **within-round independence** — ops sharing a logical key in one
   round must not show correlated leaves (the dedup dummy-fetch rule,
   oram/round.py step 1);
2. **cross-round freshness** — successive rounds touching one key must
   draw fresh leaves (the position-map remap rule); a no-remap bug makes
   every re-access repeat the previous leaf;
3. **marginal uniformity** — pooled transcript leaves must be uniform
   over [0, leaves); a constant or biased dummy leaf (e.g. "absent keys
   fetch path 0") skews the histogram.

Each detector returns a plain statistic; thresholds live with the tests.
tests/test_leak_canary.py proves the detectors have *teeth* by driving
deliberately-leaky round variants through them (every leak built via the
public ``oram_round`` parameters, so the canaries exercise the real
production code path, not a mock).
"""

from __future__ import annotations

import numpy as np


def samekey_leaf_collisions(keys: np.ndarray, leaves: np.ndarray) -> int:
    """# of op pairs in one round sharing a key AND a transcript leaf.

    Under honest dedup the duplicate fetches an independent uniform
    dummy leaf, so collisions occur w.p. 1/leaves per pair; a missing
    dedup makes every same-key pair collide.
    """
    keys = np.asarray(keys)
    leaves = np.asarray(leaves)
    same_key = keys[:, None] == keys[None, :]
    same_leaf = leaves[:, None] == leaves[None, :]
    upper = np.triu(np.ones_like(same_key, dtype=bool), k=1)
    return int(np.sum(same_key & same_leaf & upper))


def samekey_collision_counts(
    keys: np.ndarray, leaves: np.ndarray
) -> tuple[int, int]:
    """(collisions, same-key pairs) for one round — the streaming form.

    Same statistic as :func:`samekey_leaf_collisions` plus the pair
    denominator, but grouped (O(B log B)) instead of all-pairs (O(B²))
    so the continuous monitor (obs/leakmon.py) can afford it every
    round at production batch sizes. Entries with ``keys < 0`` are
    excluded (the caller's "no key" sentinel for padding dummies and
    host-unresolvable ops); the quadratic detector instead counts
    whatever key values it is given, so callers there mask dummies
    themselves. tests/test_leakmon.py asserts both forms agree.
    """
    keys = np.asarray(keys).ravel()
    leaves = np.asarray(leaves).ravel()
    real = keys >= 0
    k, lf = keys[real], leaves[real]
    if k.size < 2:
        return 0, 0

    def _pairs(counts: np.ndarray) -> int:
        counts = counts.astype(np.int64)
        return int(np.sum(counts * (counts - 1) // 2))

    _, key_counts = np.unique(k, return_counts=True)
    _, pair_counts = np.unique(
        np.stack([k.astype(np.int64), np.asarray(lf, np.int64)], axis=1),
        axis=0,
        return_counts=True,
    )
    return _pairs(pair_counts), _pairs(key_counts)


def cross_round_repeat_rate(leaf_seq: np.ndarray) -> float:
    """Fraction of consecutive accesses to ONE key with equal leaves.

    ``leaf_seq``: the transcript leaves of successive rounds that each
    touched the same logical key. Honest remap → ~1/leaves; a no-remap
    leak → 1.0.
    """
    leaf_seq = np.asarray(leaf_seq)
    if leaf_seq.size < 2:
        return 0.0
    return float(np.mean(leaf_seq[1:] == leaf_seq[:-1]))


def _leaf_hist(leaves: np.ndarray, n_leaves: int, bins: int) -> np.ndarray:
    """Histogram of leaves into ``bins`` equal ranges (shared binning)."""
    leaves = np.asarray(leaves).ravel().astype(np.int64)
    assert n_leaves % bins == 0, "bins must divide the leaf range"
    return np.bincount(leaves * bins // n_leaves, minlength=bins)[:bins]


def twosample_z(
    leaves_a: np.ndarray, leaves_b: np.ndarray, n_leaves: int, bins: int = 16
) -> float:
    """Normal-approximated two-sample chi-square z between two transcript
    leaf samples (e.g. all-READ rounds vs all-DELETE rounds). Honest
    engines draw both from the same uniform distribution → |z| = O(1);
    an op-type-dependent leaf bias separates the histograms and blows z
    up. Complements the same-seed bit-equality test, which cannot see a
    bias that affects both runs identically."""
    ca = _leaf_hist(leaves_a, n_leaves, bins).astype(float)
    cb = _leaf_hist(leaves_b, n_leaves, bins).astype(float)
    na, nb = ca.sum(), cb.sum()
    k1, k2 = np.sqrt(nb / na), np.sqrt(na / nb)
    tot = ca + cb
    with np.errstate(invalid="ignore", divide="ignore"):
        terms = np.where(tot > 0, (k1 * ca - k2 * cb) ** 2 / np.maximum(tot, 1), 0.0)
    chi2 = float(terms.sum())
    dof = bins - 1
    return (chi2 - dof) / np.sqrt(2 * dof)


def timing_twosample_z(times_a: np.ndarray, times_b: np.ndarray) -> float:
    """Mann-Whitney U z-score between two round wall-time samples.

    The obliviousness invariant covers *timing* (reference
    grapevine.proto:120-122: "access patterns and timings"): rounds of
    different op mixes must draw round times from one distribution.
    Rank-based (robust to scheduler outliers), tie-corrected normal
    approximation — identical distributions give z ~ N(0,1); an
    op-type-dependent cost shows up as |z| growing like sqrt(N).
    Callers should *interleave* the two conditions in measurement order
    so host load drift hits both samples equally.
    """
    a = np.asarray(times_a, float).ravel()
    b = np.asarray(times_b, float).ravel()
    n1, n2 = a.size, b.size
    if n1 == 0 or n2 == 0:
        return 0.0
    combined = np.concatenate([a, b])
    order = np.argsort(combined, kind="mergesort")
    ranks = np.empty_like(combined)
    ranks[order] = np.arange(1, n1 + n2 + 1, dtype=float)
    # average ranks over ties
    uniq, inv, counts = np.unique(
        combined, return_inverse=True, return_counts=True
    )
    sums = np.zeros(uniq.size)
    np.add.at(sums, inv, ranks)
    ranks = sums[inv] / counts[inv]
    r1 = float(ranks[:n1].sum())
    u1 = r1 - n1 * (n1 + 1) / 2.0
    n = n1 + n2
    mu = n1 * n2 / 2.0
    tie_term = float(((counts**3 - counts).sum())) / (n * (n - 1)) if n > 1 else 0.0
    var = n1 * n2 / 12.0 * ((n + 1) - tie_term)
    if var <= 0:
        return 0.0
    return (u1 - mu) / np.sqrt(var)


def uniformity_z(leaves: np.ndarray, n_leaves: int, bins: int = 16) -> float:
    """Normal-approximated chi-square z-score of the leaf histogram.

    Bins the pooled leaves into ``bins`` equal ranges and computes
    z = (chi2 - dof) / sqrt(2 dof), dof = bins - 1. Honest uniform
    transcripts give |z| = O(1); a constant leaf gives z ≈ sqrt(N·bins)
    — unambiguous at any realistic sample size. (Normal approximation
    instead of an exact p-value to avoid a scipy dependency; the canary
    asserts orders-of-magnitude separation, not a 5% cut.)
    """
    return uniformity_z_from_counts(_leaf_hist(leaves, n_leaves, bins))


def uniformity_z_from_counts(counts: np.ndarray) -> float:
    """The chi-square z of :func:`uniformity_z` from a pre-binned
    histogram. Split out so the streaming monitor (obs/leakmon.py) can
    keep per-round bin counts in its sliding window — summing fixed-size
    histograms instead of pooling raw leaf arrays — and still compute
    the identical statistic."""
    counts = np.asarray(counts, dtype=float)
    bins = counts.size
    n = float(counts.sum())
    if n == 0 or bins < 2:
        return 0.0
    expected = n / bins
    chi2 = float(np.sum((counts - expected) ** 2) / expected)
    dof = bins - 1
    return (chi2 - dof) / np.sqrt(2 * dof)
