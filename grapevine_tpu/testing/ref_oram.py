"""Plain-Python Path ORAM mirror: independent double-entry bookkeeping.

Implements the *same algorithm* as :mod:`grapevine_tpu.oram.path_oram`
(same eviction policy, same insertion slot choice, same stash compaction
order) with dicts and loops instead of vector ops. Given the same inputs
(block index, fresh leaf, operation) it must produce bit-identical public
transcripts and results — the build's strongest correctness check
(SURVEY.md §4: "access-pattern transcripts bit-identical to a CPU
reference implementation"). Any divergence means one of the two
implementations mis-translates the algorithm.

Kept deliberately naive: readability over speed.
"""

from __future__ import annotations

import dataclasses

from ..oram.path_oram import OramConfig

_SENTINEL = 0xFFFFFFFF


@dataclasses.dataclass
class _Slot:
    idx: int = _SENTINEL
    leaf: int = 0
    val: tuple = ()


class RefPathOram:
    """Reference Path ORAM over Python lists. Same API shape, scalar ops."""

    def __init__(self, cfg: OramConfig, posmap_init: list[int]):
        self.cfg = cfg
        self.tree: list[list[_Slot]] = [
            [_Slot() for _ in range(cfg.bucket_slots)] for _ in range(cfg.n_buckets)
        ]
        self.stash: list[_Slot] = [_Slot() for _ in range(cfg.stash_size)]
        assert len(posmap_init) == cfg.blocks + 1
        self.posmap = list(posmap_init)
        self.overflow = 0

    def path_buckets(self, leaf: int) -> list[int]:
        cfg = self.cfg
        return [
            ((1 << d) - 1) + (leaf >> (cfg.height - d)) for d in range(cfg.path_len)
        ]

    def access(self, idx: int, new_leaf: int, fn):
        """fn(value_tuple, present) -> (new_value_tuple, keep, insert, out)."""
        cfg = self.cfg
        leaf = self.posmap[idx]
        self.posmap[idx] = new_leaf
        path = self.path_buckets(leaf)

        # working set: stash first, then path slots in bucket order —
        # identical ordering to the vectorized concatenate
        work: list[_Slot] = [dataclasses.replace(s) for s in self.stash]
        for b in path:
            work.extend(dataclasses.replace(s) for s in self.tree[b])

        present = False
        value = (0,) * cfg.value_words
        for s in work:
            if s.idx != _SENTINEL and s.idx == idx:
                present = True
                value = s.val

        new_value, keep, insert, out = fn(value, present)

        for s in work:
            if s.idx != _SENTINEL and s.idx == idx:
                s.val = new_value
                s.leaf = new_leaf
                if not keep:
                    s.idx = _SENTINEL

        if insert and not present and idx != cfg.dummy_index:
            placed = False
            for s in work:
                if s.idx == _SENTINEL:
                    s.idx, s.leaf, s.val = idx, new_leaf, new_value
                    placed = True
                    break
            if not placed:
                self.overflow += 1

        # greedy deepest-first eviction, rank order = working-set order
        def depth_of(l: int) -> int:
            d = 0
            for j in range(1, cfg.height + 1):
                if (l >> (cfg.height - j)) == (leaf >> (cfg.height - j)):
                    d += 1
            return d

        assign: dict[int, list[_Slot]] = {lvl: [] for lvl in range(cfg.path_len)}
        leftovers: list[_Slot] = []
        placed_ids = set()
        for level in range(cfg.height, -1, -1):
            for i, s in enumerate(work):
                if i in placed_ids or s.idx == _SENTINEL:
                    continue
                if depth_of(s.leaf) >= level and len(assign[level]) < cfg.bucket_slots:
                    assign[level].append(s)
                    placed_ids.add(i)
        for i, s in enumerate(work):
            if i not in placed_ids and s.idx != _SENTINEL:
                leftovers.append(s)

        # write back path
        for lvl, b in enumerate(path):
            bucket = [dataclasses.replace(s) for s in assign[lvl]]
            while len(bucket) < cfg.bucket_slots:
                bucket.append(_Slot())
            self.tree[b] = bucket

        # compact leftovers into the stash
        self.stash = [_Slot() for _ in range(cfg.stash_size)]
        for i, s in enumerate(leftovers):
            if i < cfg.stash_size:
                self.stash[i] = s
            else:
                self.overflow += 1

        return out, leaf

    def stash_occupancy(self) -> int:
        return sum(1 for s in self.stash if s.idx != _SENTINEL)
