"""Testing support: seeded fixtures and CPU reference models.

The reference tests everything against deterministic seeded RNGs
(``get_seeded_rng`` / ``run_with_several_seeds``, reference
api/tests/grapevine_types.rs:8-9) and validates the oblivious engine
against plain in-memory models; this package provides the analogs.
"""
