"""Plain-dict CPU reference engine: the CRUD semantics oracle.

Implements the complete documented behavior of the reference's query engine
(reference grapevine.proto:57-122, README.md:162-175) with ordinary Python
data structures and no obliviousness. The device engine is tested for
result-equality against this model on random operation sequences — the
analog of upstream mc-oblivious testing ORAM against a plain HashMap
(SURVEY.md §4).

Semantics implemented (each cited to the reference spec):

- CREATE (grapevine.proto:66-79): client msg_id and timestamp ignored;
  server assigns a random nonzero id and its own clock. Statuses:
  INVALID_RECIPIENT for a zero recipient; TOO_MANY_MESSAGES_FOR_RECIPIENT
  at the 62-message mailbox cap (README.md:78-80); TOO_MANY_RECIPIENTS /
  TOO_MANY_MESSAGES at table capacity; MESSAGE_ID_ALREADY_IN_USE on id
  collision.
- READ (grapevine.proto:81-91): nonzero id → record iff auth_identity is
  its sender or recipient, else NOT_FOUND (absence and permission failure
  are deliberately the same error — no existence oracle). Zero id → the
  next (oldest) message addressed to auth_identity.
- UPDATE (grapevine.proto:92-103): zero id is a hard protocol error;
  NOT_FOUND under the read rule; INVALID_RECIPIENT if the supplied
  recipient differs from the stored one; otherwise payload replaced and
  timestamp refreshed.
- DELETE (grapevine.proto:104-118): nonzero id → same checks as UPDATE,
  then record and its mailbox entry are removed together (README.md:173-175).
  Zero id → pop the next message for auth_identity.
- Expiry (README.md:86-98): records older than the expiry period are
  removed, including their mailbox entries (the reference MVP left hashmap
  eviction unimplemented, README.md:98-99; this build completes it).

Failure responses carry a zero record but a real (nonzero) server
timestamp so that even protobuf-encoded responses stay constant-size.

Status precedence when multiple CREATE failures apply simultaneously
(the reference never specifies this; pinned here and mirrored by the
device engine): INVALID_RECIPIENT, then TOO_MANY_MESSAGES (bus full),
then TOO_MANY_RECIPIENTS, then TOO_MANY_MESSAGES_FOR_RECIPIENT.
"""

from __future__ import annotations

import copy
import random
from dataclasses import dataclass, field

from ..config import GrapevineConfig
from ..wire import constants as C
from ..wire.records import QueryRequest, QueryResponse, Record


class HardProtocolError(Exception):
    """API misuse that fails fast at the transport layer, not via status code.

    Mirrors the reference's hard gRPC errors: zero auth identity
    (grapevine.proto:60-64), UPDATE with a zero msg_id (grapevine.proto:95).
    """


def _zero_response(now: int, status: int) -> QueryResponse:
    return QueryResponse(
        record=Record(timestamp=max(1, now)),  # nonzero ts: constant-size invariant
        status_code=status,
    )


@dataclass
class ReferenceEngine:
    """The oracle. Not oblivious, not fast — just exactly correct."""

    config: GrapevineConfig = field(default_factory=GrapevineConfig)
    rng: random.Random = field(default_factory=lambda: random.Random(0))

    def __post_init__(self):
        self.records: dict[bytes, Record] = {}
        # recipient -> msg_ids in insertion order; "next message" = index 0
        self.mailboxes: dict[bytes, list[bytes]] = {}

    # -- helpers --------------------------------------------------------

    def _assign_msg_id(self) -> bytes:
        while True:
            mid = self.rng.getrandbits(128).to_bytes(16, "little")
            if mid != C.ZERO_MSG_ID:
                return mid

    def _next_msg_id(self, identity: bytes) -> bytes | None:
        box = self.mailboxes.get(identity)
        return box[0] if box else None

    def _remove_mailbox_entry(self, recipient: bytes, msg_id: bytes) -> None:
        box = self.mailboxes.get(recipient)
        if box is None:
            return
        box[:] = [m for m in box if m != msg_id]
        # sticky slots: a drained mailbox keeps its recipient slot until
        # the expiry sweep reclaims it (engine/vphases.py docstring)

    @staticmethod
    def _ok(rec: Record) -> QueryResponse:
        # responses carry a snapshot, never an alias of live engine state
        return QueryResponse(record=copy.deepcopy(rec), status_code=C.STATUS_CODE_SUCCESS)

    # -- the CRUD API ---------------------------------------------------

    def handle_query(
        self, req: QueryRequest, now: int, forced_msg_id: bytes | None = None
    ) -> QueryResponse:
        """Handle one (already authenticated) query.

        ``forced_msg_id`` lets equality tests replay the device engine's id
        assignment; production callers leave it None.
        """
        req.validate()
        if req.auth_identity == C.ZERO_PUBKEY:
            raise HardProtocolError("auth identity must be nonzero")
        now = int(now)
        if now <= 0:
            raise ValueError("server clock must be positive")

        rt = req.request_type
        if rt == C.REQUEST_TYPE_CREATE:
            return self._create(req, now, forced_msg_id)
        if rt == C.REQUEST_TYPE_READ:
            return self._read(req, now)
        if rt == C.REQUEST_TYPE_UPDATE:
            return self._update(req, now)
        if rt == C.REQUEST_TYPE_DELETE:
            return self._delete(req, now)
        raise HardProtocolError(f"invalid request type {rt}")

    def _create(
        self, req: QueryRequest, now: int, forced_msg_id: bytes | None
    ) -> QueryResponse:
        recipient = req.record.recipient
        if recipient == C.ZERO_PUBKEY:
            return _zero_response(now, C.STATUS_CODE_INVALID_RECIPIENT)
        if len(self.records) >= self.config.max_messages:
            return _zero_response(now, C.STATUS_CODE_TOO_MANY_MESSAGES)
        box = self.mailboxes.get(recipient)
        if box is None and len(self.mailboxes) >= self.config.max_recipients:
            return _zero_response(now, C.STATUS_CODE_TOO_MANY_RECIPIENTS)
        if box is not None and len(box) >= self.config.mailbox_cap:
            return _zero_response(now, C.STATUS_CODE_TOO_MANY_MESSAGES_FOR_RECIPIENT)

        msg_id = forced_msg_id if forced_msg_id is not None else self._assign_msg_id()
        if msg_id in self.records:
            return _zero_response(now, C.STATUS_CODE_MESSAGE_ID_ALREADY_IN_USE)

        record = Record(
            msg_id=msg_id,
            sender=req.auth_identity,
            recipient=recipient,
            timestamp=now,
            payload=req.record.payload,
        )
        self.records[msg_id] = record
        self.mailboxes.setdefault(recipient, []).append(msg_id)
        return self._ok(record)

    def _lookup_authorized(self, msg_id: bytes, auth: bytes) -> Record | None:
        """Shared READ-rule lookup: absence ≡ permission failure (no oracle)."""
        rec = self.records.get(msg_id)
        if rec is None or auth not in (rec.sender, rec.recipient):
            return None
        return rec

    def _read(self, req: QueryRequest, now: int) -> QueryResponse:
        msg_id = req.record.msg_id
        if msg_id == C.ZERO_MSG_ID:
            next_id = self._next_msg_id(req.auth_identity)
            if next_id is None:
                return _zero_response(now, C.STATUS_CODE_NOT_FOUND)
            return self._ok(self.records[next_id])
        rec = self._lookup_authorized(msg_id, req.auth_identity)
        if rec is None:
            return _zero_response(now, C.STATUS_CODE_NOT_FOUND)
        return self._ok(rec)

    def _update(self, req: QueryRequest, now: int) -> QueryResponse:
        msg_id = req.record.msg_id
        if msg_id == C.ZERO_MSG_ID:
            raise HardProtocolError("UPDATE with zero msg_id")  # grapevine.proto:95
        rec = self._lookup_authorized(msg_id, req.auth_identity)
        if rec is None:
            return _zero_response(now, C.STATUS_CODE_NOT_FOUND)
        if req.record.recipient != rec.recipient:
            return _zero_response(now, C.STATUS_CODE_INVALID_RECIPIENT)
        rec.payload = req.record.payload
        rec.timestamp = now
        return self._ok(rec)

    def _delete(self, req: QueryRequest, now: int) -> QueryResponse:
        msg_id = req.record.msg_id
        if msg_id == C.ZERO_MSG_ID:
            next_id = self._next_msg_id(req.auth_identity)
            if next_id is None:
                return _zero_response(now, C.STATUS_CODE_NOT_FOUND)
            rec = self.records.pop(next_id)
            self._remove_mailbox_entry(rec.recipient, rec.msg_id)
            return self._ok(rec)
        rec = self._lookup_authorized(msg_id, req.auth_identity)
        if rec is None:
            return _zero_response(now, C.STATUS_CODE_NOT_FOUND)
        if req.record.recipient != rec.recipient:
            return _zero_response(now, C.STATUS_CODE_INVALID_RECIPIENT)
        del self.records[msg_id]
        self._remove_mailbox_entry(rec.recipient, msg_id)
        return self._ok(rec)

    # -- phase-major batch mode (mirrors engine/round_step.py) ----------

    def handle_batch(
        self,
        reqs: list[QueryRequest],
        now: int,
        forced_msg_ids: list[bytes | None] | None = None,
    ) -> list[QueryResponse]:
        """Handle one batch under **phase-major commit semantics**.

        The batched device engine (engine/round_step.py) commits each of
        its three phases for the whole batch before the next phase:
        mailbox effects (A), record effects (B), mailbox finalization (C).
        This oracle method replays exactly that schedule with plain dicts;
        see round_step.py's module docstring for the semantics and their
        consequences. For single-op batches it coincides with
        ``handle_query``.
        """
        n = len(reqs)
        forced = forced_msg_ids or [None] * n
        for req in reqs:
            req.validate()
            if req.auth_identity == C.ZERO_PUBKEY:
                raise HardProtocolError("auth identity must be nonzero")
            if not (1 <= req.request_type <= 4):
                raise HardProtocolError(f"invalid request type {req.request_type}")
            if (
                req.request_type == C.REQUEST_TYPE_UPDATE
                and req.record.msg_id == C.ZERO_MSG_ID
            ):
                raise HardProtocolError("UPDATE with zero msg_id")
        now = int(now)
        if now <= 0:
            raise ValueError("server clock must be positive")

        # ---- phase A: mailbox decisions and effects, slot order --------
        # statuses decided here stay final for CREATE; zero-id ops record
        # their selected message id
        status_a: list[int | None] = [None] * n
        selected: list[bytes | None] = [None] * n
        create_ok = [False] * n
        msg_ids: list[bytes | None] = [None] * n
        free_at_start = self.config.max_messages - len(self.records)
        creates_so_far = 0
        for i, req in enumerate(reqs):
            rt = req.request_type
            if rt == C.REQUEST_TYPE_CREATE:
                recipient = req.record.recipient
                box = self.mailboxes.get(recipient)
                if recipient == C.ZERO_PUBKEY:
                    status_a[i] = C.STATUS_CODE_INVALID_RECIPIENT
                elif free_at_start - creates_so_far <= 0:
                    # record slots freed by same-batch deletes are not
                    # reusable until the next batch (phase-major rule)
                    status_a[i] = C.STATUS_CODE_TOO_MANY_MESSAGES
                elif box is None and len(self.mailboxes) >= self.config.max_recipients:
                    status_a[i] = C.STATUS_CODE_TOO_MANY_RECIPIENTS
                elif box is not None and len(box) >= self.config.mailbox_cap:
                    status_a[i] = C.STATUS_CODE_TOO_MANY_MESSAGES_FOR_RECIPIENT
                else:
                    mid = forced[i] if forced[i] is not None else self._assign_msg_id()
                    create_ok[i] = True
                    creates_so_far += 1
                    msg_ids[i] = mid
                    self.mailboxes.setdefault(recipient, []).append(mid)
                    status_a[i] = C.STATUS_CODE_SUCCESS
            elif req.record.msg_id == C.ZERO_MSG_ID:
                selected[i] = self._next_msg_id(req.auth_identity)
                if rt == C.REQUEST_TYPE_DELETE and selected[i] is not None:
                    # zero-id pop removes the mailbox entry in phase A
                    self._remove_mailbox_entry(req.auth_identity, selected[i])

        # ---- phase B: record effects, slot order -----------------------
        out: list[QueryResponse | None] = [None] * n
        deferred_c: list[tuple[int, bytes, bytes]] = []  # (slot, recipient, msg_id)
        for i, req in enumerate(reqs):
            rt = req.request_type
            if rt == C.REQUEST_TYPE_CREATE:
                if not create_ok[i]:
                    out[i] = _zero_response(now, status_a[i])
                    continue
                record = Record(
                    msg_id=msg_ids[i],
                    sender=req.auth_identity,
                    recipient=req.record.recipient,
                    timestamp=now,
                    payload=req.record.payload,
                )
                self.records[msg_ids[i]] = record
                out[i] = self._ok(record)
                continue

            mid = (
                selected[i] if req.record.msg_id == C.ZERO_MSG_ID else req.record.msg_id
            )
            rec = (
                self._lookup_authorized(mid, req.auth_identity)
                if mid is not None
                else None
            )
            if rec is None:
                out[i] = _zero_response(now, C.STATUS_CODE_NOT_FOUND)
                continue
            if rt == C.REQUEST_TYPE_READ:
                out[i] = self._ok(rec)
            elif rt == C.REQUEST_TYPE_UPDATE:
                if req.record.recipient != rec.recipient:
                    out[i] = _zero_response(now, C.STATUS_CODE_INVALID_RECIPIENT)
                else:
                    rec.payload = req.record.payload
                    rec.timestamp = now
                    out[i] = self._ok(rec)
            else:  # DELETE
                if req.record.msg_id == C.ZERO_MSG_ID:
                    del self.records[mid]  # mailbox entry already popped in A
                    out[i] = self._ok(rec)
                elif req.record.recipient != rec.recipient:
                    out[i] = _zero_response(now, C.STATUS_CODE_INVALID_RECIPIENT)
                else:
                    del self.records[mid]
                    deferred_c.append((i, rec.recipient, mid))
                    out[i] = self._ok(rec)

        # ---- phase C: mailbox finalization, slot order -----------------
        for _i, recipient, mid in deferred_c:
            self._remove_mailbox_entry(recipient, mid)

        return out  # type: ignore[return-value]

    # -- expiry sweep (README.md:86-98) ---------------------------------

    def expire(self, now: int, period: int | None = None) -> int:
        """Remove every record older than the expiry period. Returns count."""
        period = self.config.expiry_period if period is None else period
        if period <= 0:
            return 0
        dead = [mid for mid, rec in self.records.items() if now - rec.timestamp > period]
        for mid in dead:
            rec = self.records.pop(mid)
            self._remove_mailbox_entry(rec.recipient, mid)
        # the sweep is the one place drained mailboxes release their slot
        self.mailboxes = {r: box for r, box in self.mailboxes.items() if box}
        return len(dead)

    # -- introspection for tests ---------------------------------------

    def message_count(self) -> int:
        return len(self.records)

    def recipient_count(self) -> int:
        return len(self.mailboxes)
