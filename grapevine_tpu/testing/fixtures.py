"""Seeded random fixtures for wire types.

Analog of the reference's ``from-random`` feature
(reference types/src/lib.rs:140-186): deterministic random instances of
every message type, with the same draw conventions (payload fixed at 936
bytes, request_type in 1..=4, status_code in 1..=9) and of the reference's
seeded-RNG test helpers (``get_seeded_rng`` / ``run_with_several_seeds``,
reference api/tests/grapevine_types.rs:8-9).
"""

from __future__ import annotations

import random
from typing import Callable

from ..wire import constants as C
from ..wire.records import QueryRequest, QueryResponse, Record, RequestRecord

DEFAULT_SEED = 7


def get_seeded_rng(seed: int = DEFAULT_SEED) -> random.Random:
    return random.Random(seed)


def run_with_several_seeds(func: Callable[[random.Random], None], n_seeds: int = 8) -> None:
    for seed in range(n_seeds):
        func(random.Random(seed))


def random_request_record(rng: random.Random) -> RequestRecord:
    return RequestRecord(
        msg_id=rng.randbytes(C.MSG_ID_SIZE),
        recipient=rng.randbytes(C.PUBKEY_SIZE),
        payload=rng.randbytes(C.PAYLOAD_SIZE),
    )


def random_record(rng: random.Random) -> Record:
    return Record(
        msg_id=rng.randbytes(C.MSG_ID_SIZE),
        sender=rng.randbytes(C.PUBKEY_SIZE),
        recipient=rng.randbytes(C.PUBKEY_SIZE),
        timestamp=rng.getrandbits(64) | 1,  # engine guarantees nonzero timestamps
        payload=rng.randbytes(C.PAYLOAD_SIZE),
    )


def random_query_request(rng: random.Random) -> QueryRequest:
    return QueryRequest(
        request_type=rng.randrange(4) + 1,
        auth_identity=rng.randbytes(C.PUBKEY_SIZE),
        auth_signature=rng.randbytes(C.SIGNATURE_SIZE),
        record=random_request_record(rng),
    )


def random_query_response(rng: random.Random) -> QueryResponse:
    return QueryResponse(
        record=random_record(rng),
        status_code=rng.randrange(9) + 1,
    )
