"""Crash-fault injection for the durability subsystem.

The chaos harness (tools/chaos_run.py, tests/test_chaos_recovery.py)
must be able to kill the process at *specific* points in the
journal/checkpoint protocol — mid-append, between write and fsync,
between checkpoint rename and journal prune — not just at random wall
times. Sprinkling ``faults.crash("name")`` calls at those points gives
deterministic, nameable crash sites; the whole module is inert (one
falsy global check per call) unless the ``GRAPEVINE_FAULTS`` environment
variable arms a plan.

Plan syntax::

    GRAPEVINE_FAULTS="journal.append.torn=3"
    GRAPEVINE_FAULTS="checkpoint.pre_rename=1;round.post_dispatch=5"

``point=n`` means: die (SIGKILL — no atexit, no flushing, the honest
crash) on the *n*-th time execution reaches that point. Multiple points
are independent counters; the first to reach its count kills the
process.

Instrumented points (grep ``faults.crash`` / ``faults.hit``):

- ``journal.append.pre``       before any frame bytes are written
- ``journal.append.torn``      half the frame written + fsynced, then die
                               (the torn-tail case replay must tolerate)
- ``journal.append.post_write``frame fully written, before fsync
- ``journal.append.post_fsync``frame durable, before the round dispatches
- ``checkpoint.tmp.torn``      half the sealed tmp file written, then die
- ``checkpoint.pre_rename``    tmp complete, before the atomic rename
- ``checkpoint.post_rename``   checkpoint live, before journal roll/prune
- ``round.pre_dispatch``       round journaled + fsynced, before its device
                               dispatch — under the pipelined engine
                               (pipeline_depth=2) this is the window where
                               round k+1 is durable but round k is still
                               mid-flight on the device
- ``round.post_dispatch``      round journaled + dispatched, before resolve
- ``flush.pre_dispatch``       delayed-eviction flush frame journaled +
                               fsynced, before the flush dispatches — the
                               kill-at-flush window: the E-th round is
                               durable and possibly mid-flight, the flush
                               is durable but not applied
- ``flush.post_dispatch``      flush journaled + dispatched, before any
                               resolve
"""

from __future__ import annotations

import os
import signal
import time

ENV_VAR = "GRAPEVINE_FAULTS"

#: every instrumented crash site; tools/chaos_run.py randomizes over
#: this list and tests/test_chaos_recovery.py enumerates it exhaustively
ALL_POINTS = (
    "journal.append.pre",
    "journal.append.torn",
    "journal.append.post_write",
    "journal.append.post_fsync",
    "checkpoint.tmp.torn",
    "checkpoint.pre_rename",
    "checkpoint.post_rename",
    "round.pre_dispatch",
    "round.post_dispatch",
    "flush.pre_dispatch",
    "flush.post_dispatch",
)


class _Plan:
    __slots__ = ("targets", "counts")

    def __init__(self, spec: str):
        self.targets: dict[str, int] = {}
        self.counts: dict[str, int] = {}
        for part in spec.split(";"):
            part = part.strip()
            if not part:
                continue
            point, _, n = part.partition("=")
            point = point.strip()
            if point not in ALL_POINTS:
                raise ValueError(
                    f"unknown fault point {point!r}; known: {ALL_POINTS}"
                )
            self.targets[point] = max(1, int(n or 1))
            self.counts[point] = 0


_plan: _Plan | None = None
_loaded = False


def _get_plan() -> _Plan | None:
    global _plan, _loaded
    if not _loaded:
        reset(os.environ.get(ENV_VAR, ""))
    return _plan


def reset(spec: str | None = None) -> None:
    """(Re)load the fault plan — from ``spec`` or the environment.

    Tests use ``reset("")`` to disarm and ``reset("point=n")`` to arm
    in-process without touching the environment."""
    global _plan, _loaded
    if spec is None:
        spec = os.environ.get(ENV_VAR, "")
    _plan = _Plan(spec) if spec.strip() else None
    _loaded = True


def active() -> bool:
    """True when any fault point is armed (the fast-path guard)."""
    return _get_plan() is not None


def hit(point: str) -> bool:
    """Count a visit to ``point``; True when its trigger count is
    reached — the caller then performs its custom damage (e.g. a
    partial write) and calls :func:`die`."""
    plan = _get_plan()
    if plan is None or point not in plan.targets:
        return False
    plan.counts[point] += 1
    return plan.counts[point] == plan.targets[point]


def crash(point: str) -> None:
    """Die on the spot when ``point``'s trigger count is reached."""
    if hit(point):
        die()


def die() -> None:
    """SIGKILL self: no cleanup handlers, no buffers flushed — the
    honest crash the recovery path is specified against."""
    os.kill(os.getpid(), signal.SIGKILL)
    while True:  # pragma: no cover - signal delivery races the next line
        time.sleep(1)
