"""State-comparison helpers shared by tests and the TPU capture tool."""

from __future__ import annotations

import jax
import numpy as np

from ..config import TPU_BACKENDS

__all__ = [
    "states_equal_excluding_junk",
    "logical_tree_planes",
    "assert_logical_state_equal",
    "TPU_BACKENDS",
]


def states_equal_excluding_junk(sa, sb):
    """Engine-state bit-equality with the padded junk bucket masked.

    The fused encrypt+scatter kernel redirects non-owner duplicate-row
    writes to the LAST (padded) bucket of each tree, which heap indices
    never address (oblivious/pallas_gather.py) — so that bucket's
    at-rest bytes legitimately differ from the jnp path while every
    path-addressable byte must match exactly. Z is derived per tree
    from the paired ``tree_idx``/``tree_val`` leaves, never hardcoded.

    Returns (equal, first_differing_keypath_or_None).
    """
    if jax.tree_util.tree_structure(sa) != jax.tree_util.tree_structure(sb):
        return False, "<tree structure>"
    la = {
        jax.tree_util.keystr(p): np.asarray(x)
        for p, x in jax.tree_util.tree_leaves_with_path(sa)
    }
    lb = dict(zip(la.keys(), map(np.asarray, jax.tree_util.tree_leaves(sb))))
    for key, x in la.items():
        y = lb[key]
        if key.endswith("tree_val"):
            x, y = x[:-1], y[:-1]
        elif key.endswith("tree_idx"):
            val = la[key[: -len("tree_idx")] + "tree_val"]
            z = x.size // val.shape[0]
            x, y = x[:-z], y[:-z]
        elif key.endswith("nonces"):
            # the fused kernel also commits the write epoch through the
            # junk redirect, so the junk bucket's nonce row differs too
            x, y = x[:-1], y[:-1]
        if not np.array_equal(x, y):
            return False, key
    return True, None


def logical_tree_planes(cfg, oram):
    """Decrypted logical content of one ORAM's bucket tree, with the
    tree-top cache overlaid (host-side; never on the round path).

    Returns ``(idx [n, Z], val [n, Z*V], leaf [n, Z] | None)`` plaintext
    planes. Under ``cfg.top_cache_levels = k > 0`` the top 2^k−1
    buckets' HBM rows are stale (empty-at-init ciphertext, re-keyed but
    never read) and the authoritative plaintext lives in the cache
    planes — so rows [0, 2^k−1) are taken from the cache. This is the
    canonical form the cached↔uncached bit-identity contract compares:
    two states are equal iff their logical planes, stashes, maps, and
    scalars are equal (ciphertext at cached levels legitimately
    diverges — the cached run never re-encrypts them).
    """
    from ..oblivious.bucket_cipher import row_keystream
    import jax.numpy as jnp

    z, v = cfg.bucket_slots, cfg.value_words
    n = cfg.n_buckets_padded
    idx = np.asarray(oram.tree_idx).reshape(n, z).copy()
    val = np.asarray(oram.tree_val).copy()
    leaf = (
        np.asarray(oram.tree_leaf).reshape(n, z).copy()
        if np.asarray(oram.tree_leaf).size
        else None
    )
    if cfg.encrypted:
        buckets = jnp.arange(n, dtype=jnp.uint32)
        ks = np.asarray(
            row_keystream(
                oram.cipher_key, buckets, oram.nonces, cfg.row_words,
                cfg.cipher_rounds,
            )
        )
        idx ^= ks[:, :z]
        val ^= ks[:, z:]
        if leaf is not None:
            ksl = np.asarray(
                row_keystream(
                    oram.cipher_key, buckets + jnp.uint32(n), oram.nonces,
                    z, cfg.cipher_rounds,
                )
            )
            leaf ^= ksl
    cb = cfg.cache_buckets
    if cb:
        idx[:cb] = np.asarray(oram.cache_idx).reshape(cb, z)
        val[:cb] = np.asarray(oram.cache_val)
        if leaf is not None:
            leaf[:cb] = np.asarray(oram.cache_leaf).reshape(cb, z)
    return idx, val, leaf


def assert_logical_state_equal(ecfg_a, sa, ecfg_b, sb, ctx=""):
    """Cached↔uncached final-state contract: every logical plane, stash,
    position map, and scalar equal — the tree-cache analog of PR 7's
    payload-state bit-equality (which cache-level ciphertext divergence
    makes too strict to apply raw). Works across differing
    ``top_cache_levels`` and across flat/recursive posmaps (inner trees
    compared logically too, via their own planes)."""
    from ..oram.posmap import inner_oram_config

    for tree in ("rec", "mb"):
        ca, cb_ = getattr(ecfg_a, tree), getattr(ecfg_b, tree)
        oa, ob = getattr(sa, tree), getattr(sb, tree)
        pa = logical_tree_planes(ca, oa)
        pb = logical_tree_planes(cb_, ob)
        for name, x, y in zip(("idx", "val", "leaf"), pa, pb):
            if x is None and y is None:
                continue
            # mask the padded junk bucket (states_equal_excluding_junk)
            assert np.array_equal(x[:-1], y[:-1]), (
                f"{ctx}: {tree} logical {name} plane diverges"
            )
        for f in ("stash_idx", "stash_val", "stash_leaf", "overflow",
                  "epoch", "cipher_key"):
            assert np.array_equal(
                np.asarray(getattr(oa, f)), np.asarray(getattr(ob, f))
            ), f"{ctx}: {tree}.{f} diverges"
        if ca.posmap is None:
            assert np.array_equal(
                np.asarray(oa.posmap), np.asarray(ob.posmap)
            ), f"{ctx}: {tree} flat posmap diverges"
        else:
            ia, ib = inner_oram_config(ca.posmap), inner_oram_config(cb_.posmap)
            qa = logical_tree_planes(ia, oa.posmap.inner)
            qb = logical_tree_planes(ib, ob.posmap.inner)
            for name, x, y in zip(("idx", "val"), qa[:2], qb[:2]):
                assert np.array_equal(x[:-1], y[:-1]), (
                    f"{ctx}: {tree} inner posmap logical {name} diverges"
                )
            for f in ("stash_idx", "stash_val", "posmap", "overflow"):
                assert np.array_equal(
                    np.asarray(getattr(oa.posmap.inner, f)),
                    np.asarray(getattr(ob.posmap.inner, f)),
                ), f"{ctx}: {tree} inner posmap {f} diverges"
            assert np.array_equal(
                np.asarray(oa.posmap.dummy_entry),
                np.asarray(ob.posmap.dummy_entry),
            ), f"{ctx}: {tree} posmap dummy_entry diverges"
    for f in ("freelist", "free_top", "recipients", "seq", "hash_key",
              "id_key", "rng"):
        assert np.array_equal(
            np.asarray(getattr(sa, f)), np.asarray(getattr(sb, f))
        ), f"{ctx}: {f} diverges"
