"""State-comparison helpers shared by tests and the TPU capture tool."""

from __future__ import annotations

import jax
import numpy as np

from ..config import TPU_BACKENDS

__all__ = [
    "states_equal_excluding_junk",
    "logical_tree_planes",
    "assert_logical_state_equal",
    "logical_block_map",
    "assert_logical_content_equal",
    "TPU_BACKENDS",
]


def states_equal_excluding_junk(sa, sb):
    """Engine-state bit-equality with the padded junk bucket masked.

    The fused encrypt+scatter kernel redirects non-owner duplicate-row
    writes to the LAST (padded) bucket of each tree, which heap indices
    never address (oblivious/pallas_gather.py) — so that bucket's
    at-rest bytes legitimately differ from the jnp path while every
    path-addressable byte must match exactly. Z is derived per tree
    from the paired ``tree_idx``/``tree_val`` leaves, never hardcoded.

    Returns (equal, first_differing_keypath_or_None).
    """
    if jax.tree_util.tree_structure(sa) != jax.tree_util.tree_structure(sb):
        return False, "<tree structure>"
    la = {
        jax.tree_util.keystr(p): np.asarray(x)
        for p, x in jax.tree_util.tree_leaves_with_path(sa)
    }
    lb = dict(zip(la.keys(), map(np.asarray, jax.tree_util.tree_leaves(sb))))
    for key, x in la.items():
        y = lb[key]
        if key.endswith("tree_val"):
            x, y = x[:-1], y[:-1]
        elif key.endswith("tree_idx"):
            val = la[key[: -len("tree_idx")] + "tree_val"]
            z = x.size // val.shape[0]
            x, y = x[:-z], y[:-z]
        elif key.endswith("nonces"):
            # the fused kernel also commits the write epoch through the
            # junk redirect, so the junk bucket's nonce row differs too
            x, y = x[:-1], y[:-1]
        if not np.array_equal(x, y):
            return False, key
    return True, None


def logical_tree_planes(cfg, oram):
    """Decrypted logical content of one ORAM's bucket tree, with the
    tree-top cache overlaid (host-side; never on the round path).

    Returns ``(idx [n, Z], val [n, Z*V], leaf [n, Z] | None)`` plaintext
    planes. Under ``cfg.top_cache_levels = k > 0`` the top 2^k−1
    buckets' HBM rows are stale (empty-at-init ciphertext, re-keyed but
    never read) and the authoritative plaintext lives in the cache
    planes — so rows [0, 2^k−1) are taken from the cache. This is the
    canonical form the cached↔uncached bit-identity contract compares:
    two states are equal iff their logical planes, stashes, maps, and
    scalars are equal (ciphertext at cached levels legitimately
    diverges — the cached run never re-encrypts them).
    """
    from ..oblivious.bucket_cipher import row_keystream
    import jax.numpy as jnp

    z, v = cfg.bucket_slots, cfg.value_words
    n = cfg.n_buckets_padded
    idx = np.asarray(oram.tree_idx).reshape(n, z).copy()
    val = np.asarray(oram.tree_val).copy()
    leaf = (
        np.asarray(oram.tree_leaf).reshape(n, z).copy()
        if np.asarray(oram.tree_leaf).size
        else None
    )
    if cfg.encrypted:
        buckets = jnp.arange(n, dtype=jnp.uint32)
        ks = np.asarray(
            row_keystream(
                oram.cipher_key, buckets, oram.nonces, cfg.row_words,
                cfg.cipher_rounds,
            )
        )
        idx ^= ks[:, :z]
        val ^= ks[:, z:]
        if leaf is not None:
            ksl = np.asarray(
                row_keystream(
                    oram.cipher_key, buckets + jnp.uint32(n), oram.nonces,
                    z, cfg.cipher_rounds,
                )
            )
            leaf ^= ksl
    cb = cfg.cache_buckets
    if cb:
        idx[:cb] = np.asarray(oram.cache_idx).reshape(cb, z)
        val[:cb] = np.asarray(oram.cache_val)
        if leaf is not None:
            leaf[:cb] = np.asarray(oram.cache_leaf).reshape(cb, z)
    if cfg.delayed_eviction:
        # delayed eviction (PR 15): buckets fetched since the last flush
        # hold stale copies — the live rows moved to the eviction buffer
        # (a separate private plane, like the stash, not part of the
        # tree view). Mask them so the logical planes show only
        # authoritative tree content.
        from ..oblivious.primitives import SENTINEL

        stale = np.asarray(oram.fetch_tag) == int(np.asarray(oram.ebuf_gen))
        idx[stale] = int(SENTINEL)
    return idx, val, leaf


def logical_block_map(cfg, oram) -> dict:
    """{block index: value bytes} of every live block in one ORAM —
    tree planes (cache overlaid, stale buckets masked) ∪ eviction
    buffer ∪ stash. Placement-free: the canonical content view the
    delayed-eviction bit-identity contract compares (host-side; never
    on the round path)."""
    from ..oblivious.primitives import SENTINEL

    z, v = cfg.bucket_slots, cfg.value_words
    idx, val, _leaf = logical_tree_planes(cfg, oram)
    out: dict = {}
    rows = val.reshape(-1, v)
    flat = idx.reshape(-1)
    for slot in np.nonzero(flat != int(SENTINEL))[0]:
        out[int(flat[slot])] = rows[slot].tobytes()
    for pidx, pval in ((oram.ebuf_idx, oram.ebuf_val),
                       (oram.stash_idx, oram.stash_val)):
        sidx = np.asarray(pidx)
        sval = np.asarray(pval)
        for j in np.nonzero(sidx != int(SENTINEL))[0]:
            blk = int(sidx[j])
            assert blk not in out, (
                f"block {blk} lives in two places — the "
                "tree/buffer/stash partition invariant broke"
            )
            out[blk] = sval[j].tobytes()
    return out


def assert_logical_content_equal(ecfg_a, sa, ecfg_b, sb, ctx=""):
    """Cross-``evict_every`` final-state contract (PR 15): the two
    engines hold the SAME live blocks with the SAME values, positions,
    and scalars — physical placement (which bucket/stash/buffer row a
    block occupies) legitimately differs, because E=1 evicts every
    round while E>1 evicts each window's union of paths at once. The
    position maps, freelist, and every engine scalar must still be
    bit-identical (the RNG chain and remap draws are E-independent)."""
    from ..oram.posmap import read_table

    for tree in ("rec", "mb"):
        ca, cb_ = getattr(ecfg_a, tree), getattr(ecfg_b, tree)
        oa, ob = getattr(sa, tree), getattr(sb, tree)
        ma, mb_ = logical_block_map(ca, oa), logical_block_map(cb_, ob)
        assert set(ma) == set(mb_), (
            f"{ctx}: {tree} live-block sets diverge "
            f"(only-a={sorted(set(ma) - set(mb_))[:8]}, "
            f"only-b={sorted(set(mb_) - set(ma))[:8]})"
        )
        bad = [k for k in ma if ma[k] != mb_[k]]
        assert not bad, f"{ctx}: {tree} block values diverge at {bad[:8]}"
        assert np.array_equal(
            read_table(ca, oa.posmap), read_table(cb_, ob.posmap)
        ), f"{ctx}: {tree} logical position table diverges"
        for f in ("overflow", "cipher_key"):
            assert np.array_equal(
                np.asarray(getattr(oa, f)), np.asarray(getattr(ob, f))
            ), f"{ctx}: {tree}.{f} diverges"
    for f in ("freelist", "free_top", "recipients", "seq", "hash_key",
              "id_key", "rng"):
        assert np.array_equal(
            np.asarray(getattr(sa, f)), np.asarray(getattr(sb, f))
        ), f"{ctx}: {f} diverges"


def assert_logical_state_equal(ecfg_a, sa, ecfg_b, sb, ctx=""):
    """Cached↔uncached final-state contract: every logical plane, stash,
    position map, and scalar equal — the tree-cache analog of PR 7's
    payload-state bit-equality (which cache-level ciphertext divergence
    makes too strict to apply raw). Works across differing
    ``top_cache_levels`` and across flat/recursive posmaps (inner trees
    compared logically too, via their own planes)."""
    from ..oram.posmap import inner_oram_config

    for tree in ("rec", "mb"):
        ca, cb_ = getattr(ecfg_a, tree), getattr(ecfg_b, tree)
        oa, ob = getattr(sa, tree), getattr(sb, tree)
        pa = logical_tree_planes(ca, oa)
        pb = logical_tree_planes(cb_, ob)
        for name, x, y in zip(("idx", "val", "leaf"), pa, pb):
            if x is None and y is None:
                continue
            # mask the padded junk bucket (states_equal_excluding_junk)
            assert np.array_equal(x[:-1], y[:-1]), (
                f"{ctx}: {tree} logical {name} plane diverges"
            )
        for f in ("stash_idx", "stash_val", "stash_leaf", "overflow",
                  "epoch", "cipher_key"):
            assert np.array_equal(
                np.asarray(getattr(oa, f)), np.asarray(getattr(ob, f))
            ), f"{ctx}: {tree}.{f} diverges"
        if ca.posmap is None:
            assert np.array_equal(
                np.asarray(oa.posmap), np.asarray(ob.posmap)
            ), f"{ctx}: {tree} flat posmap diverges"
        else:
            ia, ib = inner_oram_config(ca.posmap), inner_oram_config(cb_.posmap)
            qa = logical_tree_planes(ia, oa.posmap.inner)
            qb = logical_tree_planes(ib, ob.posmap.inner)
            for name, x, y in zip(("idx", "val"), qa[:2], qb[:2]):
                assert np.array_equal(x[:-1], y[:-1]), (
                    f"{ctx}: {tree} inner posmap logical {name} diverges"
                )
            for f in ("stash_idx", "stash_val", "posmap", "overflow"):
                assert np.array_equal(
                    np.asarray(getattr(oa.posmap.inner, f)),
                    np.asarray(getattr(ob.posmap.inner, f)),
                ), f"{ctx}: {tree} inner posmap {f} diverges"
            assert np.array_equal(
                np.asarray(oa.posmap.dummy_entry),
                np.asarray(ob.posmap.dummy_entry),
            ), f"{ctx}: {tree} posmap dummy_entry diverges"
    for f in ("freelist", "free_top", "recipients", "seq", "hash_key",
              "id_key", "rng"):
        assert np.array_equal(
            np.asarray(getattr(sa, f)), np.asarray(getattr(sb, f))
        ), f"{ctx}: {f} diverges"
