"""State-comparison helpers shared by tests and the TPU capture tool."""

from __future__ import annotations

import jax
import numpy as np

from ..config import TPU_BACKENDS

__all__ = ["states_equal_excluding_junk", "TPU_BACKENDS"]


def states_equal_excluding_junk(sa, sb):
    """Engine-state bit-equality with the padded junk bucket masked.

    The fused encrypt+scatter kernel redirects non-owner duplicate-row
    writes to the LAST (padded) bucket of each tree, which heap indices
    never address (oblivious/pallas_gather.py) — so that bucket's
    at-rest bytes legitimately differ from the jnp path while every
    path-addressable byte must match exactly. Z is derived per tree
    from the paired ``tree_idx``/``tree_val`` leaves, never hardcoded.

    Returns (equal, first_differing_keypath_or_None).
    """
    if jax.tree_util.tree_structure(sa) != jax.tree_util.tree_structure(sb):
        return False, "<tree structure>"
    la = {
        jax.tree_util.keystr(p): np.asarray(x)
        for p, x in jax.tree_util.tree_leaves_with_path(sa)
    }
    lb = dict(zip(la.keys(), map(np.asarray, jax.tree_util.tree_leaves(sb))))
    for key, x in la.items():
        y = lb[key]
        if key.endswith("tree_val"):
            x, y = x[:-1], y[:-1]
        elif key.endswith("tree_idx"):
            val = la[key[: -len("tree_idx")] + "tree_val"]
            z = x.size // val.shape[0]
            x, y = x[:-z], y[:-z]
        elif key.endswith("nonces"):
            # the fused kernel also commits the write epoch through the
            # junk redirect, so the junk bucket's nonce row differs too
            x, y = x[:-1], y[:-1]
        if not np.array_equal(x, y):
            return False, key
    return True, None
