"""The capacity model: a saturation knee over the SLO burn-rate signal.

The ramp schedule (generators.ramp_to_saturation) declares a staircase
of offered rates; the replay (harness.ScenarioRunner) yields per-op
enqueue→settle latencies. This module grades each declared step against
the commit-latency SLO the PR-6 engine gates on (obs/slo.py semantics:
a round/op *breaches* past the target; the *burn rate* is the breach
fraction over the error budget) and reports the **knee** — the highest
offered rate at which the SLO still held — which is the repo's banked
capacity number (``bench.py load_scenarios``; BOLT, arXiv:2509.01742,
reports its oblivious-map capacity as exactly this swept-load
saturation throughput).

Knee semantics, deliberately conservative:

- a step *holds* when its burn rate is ≤ ``burn_limit`` (default 1.0 —
  spending within the error budget) AND almost none of its ops failed
  or timed out (``fail_limit``; a step that "holds" latency by failing
  ops has not held anything). Achieved throughput — completions inside
  the step's wall window — is *reported* but never gates: once commit
  latency approaches the step length, completions inside window k
  belong to arrivals from earlier steps, so a throughput floor would
  systematically fail healthy low-rate steps;
- the knee is the LAST holding step *before the first failing step* —
  a lucky later step on a noisy host must not inflate capacity past a
  measured failure;
- when no step fails the ramp never saturated: the knee is reported as
  the last step's rate with ``saturated: false`` — a lower bound, and
  the caller should ramp higher.
"""

from __future__ import annotations

import numpy as np

from .generators import Schedule


def step_stats(offered_rate: float, step_s: float, latencies_s,
               ok, target_ms: float, error_budget: float,
               achieved_count: int | None = None) -> dict:
    """Grade one ramp step: breach fraction, burn rate, percentiles.

    ``achieved_count`` is the number of ops that COMPLETED inside the
    step's window (analyze_ramp computes it from settle times). Without
    it the fallback counts the step's arrivals that eventually
    succeeded — which under overload equals the arrival rate (every op
    settles *sometime*) and overstates throughput at saturation; pass
    the real count whenever settle times exist."""
    lat = np.asarray(latencies_s, float)
    ok = np.asarray(ok, bool)
    settled = lat[~np.isnan(lat)]
    n = len(lat)
    n_settled = len(settled)
    breaches = int(np.sum(settled > target_ms / 1e3)) + int(
        np.sum(np.isnan(lat))  # an op that never settled breached
    )
    breach_frac = breaches / n if n else 0.0
    n_done = int(np.sum(ok)) if achieved_count is None else int(
        achieved_count)
    # ops that failed outright or never settled (ok is only set on an
    # accepted response) — the non-latency way a step stops holding
    fail_frac = (n - int(np.sum(ok))) / n if n else 0.0
    out = {
        "offered_rate": round(float(offered_rate), 1),
        "n_ops": n,
        # the rate the Poisson draw actually realized this step — the
        # fair baseline for the achieved-throughput check (a sparse
        # draw must not read as the server failing to keep up)
        "arrival_rate": round(n / step_s, 1) if step_s else 0.0,
        "achieved_ops_per_sec": round(
            n_done / step_s, 1) if step_s else 0.0,
        "breach_fraction": round(breach_frac, 4),
        "burn_rate": round(breach_frac / error_budget, 2),
        "failure_fraction": round(fail_frac, 4),
    }
    if n_settled:
        out["p50_commit_ms"] = round(
            float(np.percentile(settled, 50, method="higher")) * 1e3, 2)
        out["p99_commit_ms"] = round(
            float(np.percentile(settled, 99, method="higher")) * 1e3, 2)
    return out


def find_knee(steps: list[dict], burn_limit: float = 1.0,
              fail_limit: float = 0.1, min_ops: int = 8) -> dict:
    """The saturation knee over graded steps (offered-rate order)."""
    knee = None
    first_fail = None
    for s in steps:
        if s["n_ops"] < min_ops:
            continue  # insufficient evidence grades nothing (the
            # leakmon min-samples stance)
        holds = (
            s["burn_rate"] <= burn_limit
            and s.get("failure_fraction", 0.0) <= fail_limit
        )
        if holds and first_fail is None:
            knee = s
        elif not holds:
            first_fail = s
            break
    return {
        "knee_ops_per_sec": knee["offered_rate"] if knee else 0.0,
        "knee_p99_commit_ms": knee.get("p99_commit_ms") if knee else None,
        "saturated": first_fail is not None,
        "first_failing_rate": (
            first_fail["offered_rate"] if first_fail else None),
        "burn_limit": burn_limit,
    }


def analyze_ramp(schedule: Schedule, result, target_ms: float,
                 error_budget: float = 0.01,
                 burn_limit: float = 1.0) -> dict:
    """Grade a ramp replay step by declared step and find the knee.

    LATENCY and breach accounting attribute ops to the step their
    *arrival* was scheduled in (an op admitted at rate r whose latency
    explodes is r's breach, even if it settles two steps later).
    THROUGHPUT counts completions inside the step's wall window
    regardless of arrival step — under overload arrivals always settle
    eventually, so counting a step's arrivals-that-succeeded would
    report the arrival rate, not what the server sustained. Offered
    rates are converted to wall terms by the replay's time_scale so
    the knee is in real ops/s.
    """
    steps_meta = schedule.meta.get("steps")
    if not steps_meta:
        raise ValueError("schedule has no declared ramp steps")
    scale = result.time_scale
    # settle time relative to the replay start, wall seconds (latency
    # is anchored at submit ≈ the scaled scheduled arrival)
    settle_wall = schedule.t_s * scale + result.latency_s
    graded = []
    for sm in steps_meta:
        in_step = (schedule.t_s >= sm["t0"]) & (schedule.t_s < sm["t1"])
        done_in_step = (
            result.ok
            & ~np.isnan(result.latency_s)
            & (settle_wall >= sm["t0"] * scale)
            & (settle_wall < sm["t1"] * scale)
        )
        graded.append(step_stats(
            offered_rate=sm["offered_rate"] / scale,
            step_s=(sm["t1"] - sm["t0"]) * scale,
            latencies_s=result.latency_s[in_step],
            ok=result.ok[in_step],
            target_ms=target_ms,
            error_budget=error_budget,
            achieved_count=int(np.sum(done_in_step)),
        ))
    knee = find_knee(graded, burn_limit=burn_limit)
    return {
        "target_ms": round(float(target_ms), 1),
        "error_budget": error_budget,
        "steps": graded,
        **knee,
    }


def fleet_capacity(shard_analyses: list[dict]) -> dict:
    """Fold N per-shard ramp analyses (``analyze_ramp`` output, shard
    order) into the fleet grade.

    The declared partition serves disjoint recipient spaces, so shard
    knees ADD into the fleet knee — but only while every shard holds:
    the fleet is ``saturated`` as soon as ANY shard saturates (one hot
    shard past its knee is a capacity failure the sum must not paper
    over; the sum reported for a saturated fleet is still the additive
    lower bound of the holding knees). Banked by bench.py
    ``fleet_loopback`` under the ``shard_count`` geometry key
    (tools/check_perf_regression.py) so an N=2 number never grades
    against the N=1 series."""
    if not shard_analyses:
        raise ValueError("need at least one shard analysis")
    return {
        "shard_count": len(shard_analyses),
        "fleet_knee_ops_per_sec": round(
            sum(a["knee_ops_per_sec"] for a in shard_analyses), 1),
        "saturated": any(a["saturated"] for a in shard_analyses),
        "shards": [
            {
                "shard": i,
                "knee_ops_per_sec": a["knee_ops_per_sec"],
                "knee_p99_commit_ms": a.get("knee_p99_commit_ms"),
                "saturated": a["saturated"],
            }
            for i, a in enumerate(shard_analyses)
        ],
    }
