"""Deterministic, seeded, open-loop arrival-schedule generators.

Every generator here is a pure function of its parameters and a seed:
it pre-materializes the COMPLETE (timestamp, op-template) schedule
before a single op is submitted. That open-loop property is the whole
point (and a tier-1 test asserts it): a closed-loop client waits for
its previous op before sending the next, so under overload it silently
self-throttles and the measured latency stays flattering — the classic
coordinated-omission trap. An open-loop schedule keeps arriving at its
declared rate no matter how the server fares, so queue growth and
latency blow-up are *measured* rather than hidden, which is what makes
the ramp stage's saturation knee (load/capacity.py) an honest capacity
number (BOLT, arXiv:2509.01742, sweeps offered load the same way).

Op templates are small integers — a request kind (the wire request
types) plus indices into ONE identity pool shared by auth and
recipient roles, so a CREATE aimed at pool slot r can later be drained
by the identity at pool slot r. Materialization into signed wire
requests happens in the harness; schedules stay cheap to generate,
hash, and compare.

Time is in *schedule seconds* from t=0; the replay harness scales it
(``time_scale``) so one schedule serves both a compressed CI soak and
a real-time drill.
"""

from __future__ import annotations

import dataclasses
import hashlib

import numpy as np

from ..wire import constants as C

#: op-kind codes — exactly the wire request types, so a schedule reads
#: like the traffic it produces
CREATE = C.REQUEST_TYPE_CREATE
READ = C.REQUEST_TYPE_READ
DELETE = C.REQUEST_TYPE_DELETE


@dataclasses.dataclass(frozen=True)
class Schedule:
    """A pre-materialized open-loop arrival schedule.

    Parallel arrays over ops, sorted by arrival time:

    - ``t_s``       float64 — arrival offset in schedule seconds
    - ``kind``      uint8   — CREATE / READ / DELETE (wire codes)
    - ``auth``      uint32  — identity-pool index of the submitter
    - ``recipient`` uint32  — identity-pool index of the CREATE target
                              (ignored for zero-id READ/DELETE drains)

    ``meta`` carries the generator's *declared* envelope (process kind,
    rates, periods) — what the shape tests check the empirical arrivals
    against — and never anything per-op.
    """

    scenario: str
    seed: int
    duration_s: float
    t_s: np.ndarray
    kind: np.ndarray
    auth: np.ndarray
    recipient: np.ndarray
    meta: dict

    def __post_init__(self):
        n = len(self.t_s)
        if not (len(self.kind) == len(self.auth) == len(self.recipient) == n):
            raise ValueError("schedule arrays must align")
        if n and (np.any(np.diff(self.t_s) < 0) or self.t_s[0] < 0
                  or self.t_s[-1] > self.duration_s):
            raise ValueError("arrival times must be sorted within "
                             "[0, duration_s]")

    @property
    def n_ops(self) -> int:
        return int(len(self.t_s))

    @property
    def offered_rate(self) -> float:
        """Mean offered rate over the schedule (ops per schedule second)."""
        return self.n_ops / self.duration_s if self.duration_s else 0.0

    def empirical_rate(self, n_bins: int = 16) -> np.ndarray:
        """Per-bin arrival rate (ops/s) over ``n_bins`` equal time bins
        — the shape tests' view of the envelope."""
        edges = np.linspace(0.0, self.duration_s, n_bins + 1)
        counts, _ = np.histogram(self.t_s, bins=edges)
        return counts / (self.duration_s / n_bins)

    def fingerprint(self) -> str:
        """Content hash of the full schedule — determinism and
        open-loop tests compare these (a replay must never mutate or
        regenerate its schedule)."""
        h = hashlib.sha256()
        for arr in (self.t_s, self.kind, self.auth, self.recipient):
            h.update(np.ascontiguousarray(arr).tobytes())
        return h.hexdigest()


# ----------------------------------------------------------------------
# arrival-process primitives
# ----------------------------------------------------------------------


def _poisson_arrivals(rng, rate: float, t0: float, t1: float) -> np.ndarray:
    """Homogeneous Poisson arrivals on [t0, t1): draw the count, then
    order statistics of uniforms (equivalent to exponential gaps, one
    vectorized draw)."""
    dt = t1 - t0
    if rate <= 0 or dt <= 0:
        return np.empty(0, np.float64)
    n = rng.poisson(rate * dt)
    return np.sort(rng.uniform(t0, t1, n))


def _mixed_ops(rng, n: int, n_idents: int, create_frac: float = 0.55,
               read_frac: float = 0.30) -> tuple:
    """Default CRUD mix over a uniform identity pool: CREATEs to random
    recipients, zero-id READ/DELETE drains of the submitter's inbox."""
    r = rng.random(n)
    kind = np.where(
        r < create_frac, CREATE,
        np.where(r < create_frac + read_frac, READ, DELETE),
    ).astype(np.uint8)
    auth = rng.integers(0, n_idents, n).astype(np.uint32)
    recipient = rng.integers(0, n_idents, n).astype(np.uint32)
    return kind, auth, recipient


def _finish(scenario, seed, duration_s, t, kind, auth, recipient, meta):
    order = np.argsort(t, kind="stable")
    return Schedule(
        scenario=scenario, seed=int(seed), duration_s=float(duration_s),
        t_s=np.asarray(t, np.float64)[order],
        kind=np.asarray(kind, np.uint8)[order],
        auth=np.asarray(auth, np.uint32)[order],
        recipient=np.asarray(recipient, np.uint32)[order],
        meta=meta,
    )


# ----------------------------------------------------------------------
# the scenario generators
# ----------------------------------------------------------------------


def steady_poisson(rate: float, duration_s: float, seed: int,
                   n_idents: int = 64) -> Schedule:
    """The baseline: memoryless arrivals at a constant rate — the
    closed-loop drains' opposite, and the null shape the bursty/diurnal
    envelopes are contrasted against."""
    rng = np.random.default_rng(seed)
    t = _poisson_arrivals(rng, rate, 0.0, duration_s)
    kind, auth, recipient = _mixed_ops(rng, len(t), n_idents)
    return _finish(
        "steady", seed, duration_s, t, kind, auth, recipient,
        {"process": "poisson", "rate": float(rate), "n_idents": n_idents},
    )


def bursty_onoff(rate_on: float, duty: float, period_s: float,
                 duration_s: float, seed: int,
                 n_idents: int = 64) -> Schedule:
    """ON/OFF bursts: Poisson at ``rate_on`` during the first
    ``duty``-fraction of every period, silence otherwise. Mean rate is
    ``rate_on * duty``; the peak-to-mean ratio ``1/duty`` is what the
    fixed round cadence has never been measured against."""
    if not 0.0 < duty < 1.0:
        raise ValueError("duty must be in (0, 1)")
    rng = np.random.default_rng(seed)
    parts = []
    t0 = 0.0
    while t0 < duration_s:
        on_end = min(t0 + duty * period_s, duration_s)
        parts.append(_poisson_arrivals(rng, rate_on, t0, on_end))
        t0 += period_s
    t = np.concatenate(parts) if parts else np.empty(0, np.float64)
    kind, auth, recipient = _mixed_ops(rng, len(t), n_idents)
    return _finish(
        "bursty", seed, duration_s, t, kind, auth, recipient,
        {"process": "onoff", "rate_on": float(rate_on), "duty": float(duty),
         "period_s": float(period_s), "mean_rate": float(rate_on * duty),
         "n_idents": n_idents},
    )


def diurnal_sinusoid(mean_rate: float, rel_amplitude: float,
                     period_s: float, duration_s: float, seed: int,
                     n_idents: int = 64) -> Schedule:
    """Inhomogeneous Poisson with a sinusoidal rate —
    ``λ(t) = mean·(1 + a·sin(2πt/T))`` — generated by thinning a
    homogeneous stream at the peak rate (Lewis–Shedler): the compressed
    day/night cycle a real deployment breathes with."""
    if not 0.0 <= rel_amplitude < 1.0:
        raise ValueError("rel_amplitude must be in [0, 1)")
    rng = np.random.default_rng(seed)
    peak = mean_rate * (1.0 + rel_amplitude)
    cand = _poisson_arrivals(rng, peak, 0.0, duration_s)
    lam = mean_rate * (
        1.0 + rel_amplitude * np.sin(2.0 * np.pi * cand / period_s)
    )
    keep = rng.uniform(0.0, peak, len(cand)) < lam
    t = cand[keep]
    kind, auth, recipient = _mixed_ops(rng, len(t), n_idents)
    return _finish(
        "diurnal", seed, duration_s, t, kind, auth, recipient,
        {"process": "sinusoid", "mean_rate": float(mean_rate),
         "rel_amplitude": float(rel_amplitude), "period_s": float(period_s),
         "n_idents": n_idents},
    )


def pop_heavy_drain(rate: float, duration_s: float, seed: int,
                    n_idents: int = 64, n_hot: int = 4,
                    hot_frac: float = 0.75,
                    drain_frac: float = 0.4) -> Schedule:
    """Pop-heavy mailbox drains: ``hot_frac`` of CREATEs target the
    ``n_hot`` hottest identities (a celebrity inbox), and the drain ops
    are zero-id READ/DELETEs *by* those same hot identities emptying
    their own mailboxes — the 62-cap-stressing mix from the zipf bench
    configs, now with realistic open-loop timing."""
    if not 1 <= n_hot < n_idents:
        raise ValueError("need 1 <= n_hot < n_idents")
    rng = np.random.default_rng(seed)
    t = _poisson_arrivals(rng, rate, 0.0, duration_s)
    n = len(t)
    is_drain = rng.random(n) < drain_frac
    hot = rng.integers(0, n_hot, n).astype(np.uint32)
    cold = rng.integers(n_hot, n_idents, n).astype(np.uint32)
    # drains: the hot identity pops its own inbox (READ then DELETE in
    # equal measure so the mailbox actually empties)
    drain_kind = np.where(rng.random(n) < 0.5, READ, DELETE).astype(np.uint8)
    kind = np.where(is_drain, drain_kind, np.uint8(CREATE))
    auth = np.where(is_drain, hot, cold)
    recipient = np.where(
        ~is_drain & (rng.random(n) < hot_frac), hot, cold
    ).astype(np.uint32)
    return _finish(
        "pop_heavy", seed, duration_s, t, kind, auth, recipient,
        {"process": "pop_heavy", "rate": float(rate), "n_hot": n_hot,
         "hot_frac": float(hot_frac), "drain_frac": float(drain_frac),
         "n_idents": n_idents},
    )


def adversarial_probe(pulse_period_s: float, duration_s: float, seed: int,
                      n_probe_keys: int = 4,
                      probes_per_pulse: int = 2) -> Schedule:
    """The probe campaign aimed at the leakmon detectors
    (obs/leakmon.py): a tiny set of identities fires synchronized
    pulses of zero-id READs against their own mailboxes,
    ``probes_per_pulse`` copies per key per pulse with sub-ms jitter so
    same-key ops land in the SAME round.

    The shape maximizes every detector's evidence per round — same-key
    pairs (copies of one key in one batch), cross-round repeat
    opportunities (every key re-accessed every pulse), and a pooled
    leaf histogram fed from very few keys — under maximally non-uniform
    timing. Against an honest engine every statistic stays at its
    uniform baseline (that IS the obliviousness claim, and the
    discrimination test pins it as the false-positive gate); paired
    with the harness's ``ProbeCampaignInjector`` it is the red-team
    drill that proves /leakaudit flips when a leak signature rides
    exactly this traffic."""
    rng = np.random.default_rng(seed)
    pulses = np.arange(0.0, duration_s, pulse_period_s)
    n = len(pulses) * n_probe_keys * probes_per_pulse
    t = np.repeat(pulses, n_probe_keys * probes_per_pulse)
    # sub-ms jitter keeps a pulse inside one collection window while
    # making the schedule an honest point process, not an exact comb
    t = np.minimum(t + rng.uniform(0.0, 1e-3, n), duration_s)
    auth = np.tile(
        np.repeat(np.arange(n_probe_keys, dtype=np.uint32),
                  probes_per_pulse),
        len(pulses),
    )
    kind = np.full(n, READ, np.uint8)
    recipient = np.zeros(n, np.uint32)
    return _finish(
        "adversarial", seed, duration_s, t, kind, auth, recipient,
        {"process": "probe_pulses", "pulse_period_s": float(pulse_period_s),
         "n_probe_keys": n_probe_keys, "probes_per_pulse": probes_per_pulse,
         "n_idents": n_probe_keys},
    )


def partition_schedule(schedule: Schedule, n_shards: int) -> list:
    """Split one schedule into N per-shard schedules by recipient space
    — the declared partition a recipient-sharded fleet would serve
    (ROADMAP item 1: each shard owns ``recipient % n_shards == i``).

    CREATEs route by their recipient; zero-id READ/DELETE drains route
    by the submitter (``auth``), since a drain empties the submitter's
    own inbox, which lives on the submitter's home shard. The split is
    a pure function of (schedule, n_shards): replaying shard i's
    sub-schedule is deterministic, and the union of the parts is the
    whole (asserted) — so a fleet replay offers exactly the same
    traffic as the monolithic replay, just partitioned.

    Each part's ``meta`` carries ``shard``/``n_shards``/``partition``
    plus the parent envelope — the fleet uniformity monitor's
    *declared* load split, against which fill-correlation beyond the
    declared partition is the leak (obs/leakmon.py
    FleetUniformityMonitor)."""
    if n_shards < 1:
        raise ValueError("n_shards must be >= 1")
    route = np.where(
        schedule.kind == CREATE,
        schedule.recipient % n_shards,
        schedule.auth % n_shards,
    )
    parts = []
    for i in range(n_shards):
        sel = route == i
        parts.append(Schedule(
            scenario=f"{schedule.scenario}[shard{i}/{n_shards}]",
            seed=schedule.seed,
            duration_s=schedule.duration_s,
            t_s=schedule.t_s[sel],
            kind=schedule.kind[sel],
            auth=schedule.auth[sel],
            recipient=schedule.recipient[sel],
            meta={**schedule.meta, "shard": i, "n_shards": n_shards,
                  "partition": "recipient_mod"},
        ))
    assert sum(p.n_ops for p in parts) == schedule.n_ops
    return parts


def ramp_to_saturation(rate0: float, factor: float, n_steps: int,
                       step_s: float, seed: int,
                       n_idents: int = 64) -> Schedule:
    """The capacity stage: a staircase of Poisson segments at
    geometrically increasing offered rates (``rate0 · factor^i``).
    ``meta["steps"]`` declares each step's [t0, t1) and offered rate —
    load/capacity.py groups the replay's per-op latencies by these
    declared steps and finds the saturation knee over the SLO
    burn-rate signal."""
    if factor <= 1.0 or n_steps < 2:
        raise ValueError("need factor > 1 and at least 2 steps")
    rng = np.random.default_rng(seed)
    parts, steps = [], []
    for i in range(n_steps):
        r = rate0 * factor ** i
        t0, t1 = i * step_s, (i + 1) * step_s
        parts.append(_poisson_arrivals(rng, r, t0, t1))
        steps.append({"t0": t0, "t1": t1, "offered_rate": float(r)})
    t = np.concatenate(parts)
    kind, auth, recipient = _mixed_ops(rng, len(t), n_idents)
    return _finish(
        "ramp", seed, n_steps * step_s, t, kind, auth, recipient,
        {"process": "ramp", "rate0": float(rate0), "factor": float(factor),
         "n_steps": n_steps, "step_s": float(step_s), "steps": steps,
         "n_idents": n_idents},
    )
