"""Open-loop scenario replay through the production scheduler.

``ScenarioRunner`` replays a pre-materialized Schedule
(load/generators.py) against a ``BatchScheduler`` via its non-blocking
``submit_nowait`` path: ops join the queue at their scheduled times
regardless of how earlier ops are faring, completions land through
Future callbacks, and the per-op enqueue→settle latency is measured —
under overload the queue grows and the latencies stretch, which is
exactly the signal the capacity model (load/capacity.py) needs and
exactly what a closed-loop client would have hidden.

Honesty guard: a replay also records its own *dispatch skew* (how late
the dispatcher thread was against the schedule). A skewed replay is a
degraded measurement — the summary reports the skew so a capacity
number taken on an overloaded host discredits itself instead of
quietly under-offering.

``ProbeCampaignInjector`` is the red-team half of the /leakaudit
discrimination drill (ISSUE 9 satellite): against an HONEST engine no
client traffic shape can flip the leak audit — the transcript stays
uniform whatever arrives; that is the security claim itself, and the
honest scenarios pin it as the false-positive gate. So to prove the
tripwire *fires* under adversarial timing, the injector wraps the
monitor hand-off and rewrites the transcript COPY handed to the
detectors with the signature a remap/dedup bug would produce (each
probed key's mailbox slots pinned to one leaf, round after round).
Engine state and real responses are untouched; what is verified is
that leakmon + /leakaudit, wired exactly as production wires them,
flip to SUSPECT within rounds when a leak rides probe-shaped traffic.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..wire import constants as C
from ..wire.records import QueryRequest, RequestRecord
from .generators import CREATE, Schedule, partition_schedule

#: response statuses that mean "the engine handled the op as specified"
#: under load: drains of an empty inbox are NOT_FOUND, creates against
#: a pop-heavy mailbox may hit the reference's 62-message cap — both
#: are correct behavior, not harness failures
OK_STATUSES = frozenset({
    C.STATUS_CODE_SUCCESS,
    C.STATUS_CODE_NOT_FOUND,
    C.STATUS_CODE_TOO_MANY_MESSAGES_FOR_RECIPIENT,
})


def identity_pool(n: int) -> list[bytes]:
    """Deterministic nonzero 32-byte identities, index-stable across
    runs (slot i is always the same identity — what lets a schedule's
    pool indices mean the same principals everywhere)."""
    out = []
    for i in range(n):
        ident = bytes([1 + (i % 255)]) + i.to_bytes(8, "little")
        out.append(ident + b"\x5a" * (32 - len(ident)))
    return out


def calibrate_unloaded_round(engine, now: int, reps: int = 3) -> tuple:
    """Warm the engine's jit and measure its unloaded full-batch round.

    Returns ``(t_round_s, est_ops_s, knee_target_ms)`` — the host
    scaling every load scenario rates itself against, and THE knee SLO
    target: ``max(250 ms, 8× the unloaded round)``. The capacity
    question is where latency departs from the intrinsic baseline, not
    whether a 2-vCPU sandbox meets a production target it never could
    (OPERATIONS.md §15); the one formula lives here so the CI bench
    (bench.py load_scenarios) and the chip capture (tools/
    tpu_capture.py load_perf) can never diverge on methodology.
    Min-of-``reps`` after a warm call (the PERF.md noise rule)."""
    idents = identity_pool(8)
    batch = engine.ecfg.batch_size
    calib = [
        QueryRequest(
            request_type=CREATE, auth_identity=idents[i % 8],
            auth_signature=b"\x01" * C.SIGNATURE_SIZE,
            record=RequestRecord(
                msg_id=C.ZERO_MSG_ID, recipient=idents[(i + 1) % 8],
                payload=bytes([i & 0xFF]) * C.PAYLOAD_SIZE))
        for i in range(batch)
    ]
    engine.handle_queries(calib, now)  # compile + warm
    ts = []
    for _ in range(max(1, reps)):
        t0 = time.perf_counter()
        engine.handle_queries(calib, now)
        ts.append(time.perf_counter() - t0)
    t_round = min(ts)
    return t_round, batch / t_round, max(250.0, 8.0 * t_round * 1e3)


def materialize_request(idents: list, schedule: Schedule, i: int,
                        payload: bytes) -> QueryRequest:
    """Op template → signed-shape wire request: CREATEs aim at the
    recipient's pool identity; zero-id READ/DELETE drains pop the
    submitter's own inbox. Module-level so the single-process runner
    and the per-shard fleet replay materialize identically from ONE
    identity pool (a shard's sub-schedule indexes the same principals
    the monolithic schedule declared)."""
    kind = int(schedule.kind[i])
    auth = idents[int(schedule.auth[i]) % len(idents)]
    if kind == CREATE:
        rcp = idents[int(schedule.recipient[i]) % len(idents)]
        rec = RequestRecord(
            msg_id=C.ZERO_MSG_ID, recipient=rcp, payload=payload
        )
    else:  # zero-id READ/DELETE: pop the submitter's own inbox
        rec = RequestRecord(
            msg_id=C.ZERO_MSG_ID, recipient=C.ZERO_PUBKEY,
            payload=payload,
        )
    return QueryRequest(
        request_type=kind, auth_identity=auth,
        auth_signature=b"\x01" * C.SIGNATURE_SIZE, record=rec,
    )


class RunResult:
    """Per-op outcome arrays plus the scenario summary."""

    def __init__(self, schedule: Schedule, time_scale: float):
        self.schedule = schedule
        self.time_scale = time_scale
        n = schedule.n_ops
        #: enqueue→settle seconds (WALL clock, unscaled); NaN = never
        #: settled / failed before dispatch
        self.latency_s = np.full(n, np.nan)
        #: dispatcher lateness vs the scaled schedule (wall seconds)
        self.skew_s = np.full(n, np.nan)
        self.status = np.zeros(n, np.int32)
        self.ok = np.zeros(n, bool)
        self.failed = np.zeros(n, bool)
        self.t_first_submit = None
        self.t_last_settle = None

    def summary(self) -> dict:
        """Batch-level scenario statistics (the bench/capture line)."""
        lat = self.latency_s[~np.isnan(self.latency_s)]
        skew = self.skew_s[~np.isnan(self.skew_s)]
        wall = (
            (self.t_last_settle - self.t_first_submit)
            if self.t_first_submit is not None
            and self.t_last_settle is not None else 0.0
        )
        n_ok = int(self.ok.sum())
        out = {
            "n_ops": self.schedule.n_ops,
            "n_ok": n_ok,
            "n_failed": int(self.failed.sum()),
            # offered rate in WALL terms (schedule rate / time_scale):
            # what the scheduler actually saw per second
            "offered_rate": round(
                self.schedule.offered_rate / self.time_scale, 1
            ) if self.time_scale else 0.0,
            "achieved_ops_per_sec": round(n_ok / wall, 1) if wall else 0.0,
        }
        if len(lat):
            out["p50_commit_ms"] = round(
                float(np.percentile(lat, 50, method="higher")) * 1e3, 2)
            out["p99_commit_ms"] = round(
                float(np.percentile(lat, 99, method="higher")) * 1e3, 2)
        if len(skew):
            out["dispatch_skew_p99_ms"] = round(
                float(np.percentile(skew, 99, method="higher")) * 1e3, 2)
        return out


class ScenarioRunner:
    """Replay schedules through a scheduler-like object.

    ``scheduler`` needs only ``submit_nowait(req) -> Future`` — the
    production BatchScheduler, or a test double. One runner holds one
    identity pool; run scenarios sequentially, never concurrently."""

    def __init__(
        self,
        scheduler,
        n_idents: int = 64,
        time_scale: float = 1.0,
        payload: bytes | None = None,
        settle_timeout_s: float = 120.0,
        clock=time.perf_counter,
    ):
        if time_scale <= 0:
            raise ValueError("time_scale must be positive")
        self.scheduler = scheduler
        self.idents = identity_pool(n_idents)
        self.time_scale = float(time_scale)
        self.payload = payload or b"\x00" * C.PAYLOAD_SIZE
        self.settle_timeout_s = float(settle_timeout_s)
        self._clock = clock

    def _materialize(self, schedule: Schedule, i: int) -> QueryRequest:
        return materialize_request(self.idents, schedule, i, self.payload)

    def run(self, schedule: Schedule) -> RunResult:
        """Replay one schedule open-loop; blocks until every dispatched
        op settles (or ``settle_timeout_s`` expires — remaining ops are
        counted as failed, never silently dropped)."""
        res = RunResult(schedule, self.time_scale)
        lock = threading.Lock()
        pending: list = []

        def on_done(i, t_sub, fut):
            t_done = self._clock()
            with lock:
                res.t_last_settle = (
                    t_done if res.t_last_settle is None
                    else max(res.t_last_settle, t_done)
                )
                exc = fut.exception()
                if exc is not None:
                    # no latency recorded: an errored future is not a
                    # commit (a scheduler crash settles queued futures
                    # near-instantly — recording those as ~0 ms commits
                    # would dilute p99 and hide breaches); NaN latency
                    # counts as a breach in the step grading
                    res.failed[i] = True
                    return
                res.latency_s[i] = t_done - t_sub
                resp = fut.result()
                res.status[i] = int(resp.status_code)
                res.ok[i] = int(resp.status_code) in OK_STATUSES
                res.failed[i] = not res.ok[i]

        t0 = self._clock()
        for i in range(schedule.n_ops):
            target = t0 + float(schedule.t_s[i]) * self.time_scale
            while True:
                now = self._clock()
                if now >= target:
                    break
                time.sleep(min(target - now, 0.002))
            req = self._materialize(schedule, i)
            t_sub = self._clock()
            res.skew_s[i] = max(0.0, t_sub - target)
            if res.t_first_submit is None:
                res.t_first_submit = t_sub
            try:
                fut = self.scheduler.submit_nowait(req)
            except Exception:
                res.failed[i] = True
                continue
            fut.add_done_callback(
                lambda f, i=i, t=t_sub: on_done(i, t, f)
            )
            pending.append((i, fut))
        deadline = self._clock() + self.settle_timeout_s
        for i, fut in pending:
            remaining = max(0.0, deadline - self._clock())
            if not self._wait(fut, remaining):
                # unsettled past the timeout: explicit failure, never a
                # silent drop (latency stays NaN — excluded from stats)
                with lock:
                    if np.isnan(res.latency_s[i]):
                        res.failed[i] = True
        return res

    @staticmethod
    def _wait(fut, timeout: float) -> bool:
        try:
            fut.exception(timeout=timeout)
            return True
        except Exception:
            return False  # TimeoutError or cancellation


class ProbeCampaignInjector:
    """Leak-signature injector for the /leakaudit discrimination drill.

    Wraps an ``EngineLeakMonitor`` behind the same ``submit_round``
    interface the engine hands transcripts to (engine.attach_leakmon
    accepts it transparently) and rewrites each round's transcript
    *copy* before delegating: every real op's mailbox fetch slots are
    pinned to one remembered leaf per (key, choice column) — the
    steady-state signature of a broken remap/dedup path. Same-key
    collision AND cross-round repeat statistics are driven toward 1 on
    the ``mb`` stream, so the monitor must flip SUSPECT within its
    min-evidence budget; the engine's actual state, responses, and
    device transcript are untouched.

    Flat position maps only (the transcript layout it rewrites); a
    recursive-posmap transcript passes through unmodified.
    """

    def __init__(self, monitor, ecfg):
        self.monitor = monitor
        self._d = int(ecfg.mb_choices)
        self._mb_leaves = int(ecfg.mb.leaves)
        self._pinned: dict = {}

    # engine-facing surface (PendingRound.resolve duck-types these)
    @property
    def recorder(self):
        return self.monitor.recorder

    def verdict(self):
        return self.monitor.verdict()

    def last_verdict(self):
        return self.monitor.last_verdict()

    def flush(self, timeout: float = 30.0):
        return self.monitor.flush(timeout)

    def close(self, timeout: float = 5.0):
        return self.monitor.close(timeout)

    def submit_round(self, batch, transcript, n_real, batch_size,
                     phases=None, queue_depth=None):
        from ..engine.round_step import transcript_key_groups

        tr = np.array(np.asarray(transcript))  # device→host, own copy
        d = self._d
        if tr.ndim != 2 or tr.shape[1] != 2 * d + 1:
            # recursive-posmap (widened) or unexpected layout: deliver
            # untouched rather than corrupt a transcript we don't parse
            return self.monitor.submit_round(
                batch, transcript, n_real, batch_size, phases, queue_depth)
        (mb_keys, mb_stable), _ = transcript_key_groups(
            {k: np.asarray(v) for k, v in batch.items()
             if k in ("req_type", "auth", "msg_id", "recipient")}, d)
        for slot in np.nonzero(mb_keys >= 0)[0]:
            j, c = divmod(int(slot), d)
            stable = mb_stable[slot]
            leaf = self._pinned.setdefault(
                stable,
                int.from_bytes(stable[:4], "little") % self._mb_leaves,
            )
            tr[j, c] = leaf           # mailbox round A column
            tr[j, d + 1 + c] = leaf   # mailbox round C column
        return self.monitor.submit_round(
            batch, tr, n_real, batch_size, phases, queue_depth)


# ----------------------------------------------------------------------
# per-shard fleet replay (ISSUE 16 — ROADMAP item 1 substrate)
# ----------------------------------------------------------------------


class ShardedScenarioRunner:
    """Replay ONE schedule across N shard schedulers, partitioned by
    recipient space (generators.partition_schedule) — the fleet-shaped
    replay the aggregator (obs/fleet.py) observes.

    Each shard's sub-schedule runs open-loop on its own thread against
    its own scheduler, all from one shared identity pool and one shared
    clock origin, so the fleet is offered exactly the traffic the
    monolithic replay would offer — just partitioned the way a
    recipient-sharded deployment declares. Returns per-shard
    ``RunResult``s in shard order; capacity grading folds them with
    ``load.capacity.fleet_capacity``."""

    def __init__(self, schedulers: list, n_idents: int = 64,
                 time_scale: float = 1.0, payload: bytes | None = None,
                 settle_timeout_s: float = 120.0, clock=time.perf_counter):
        if not schedulers:
            raise ValueError("need at least one shard scheduler")
        self.runners = [
            ScenarioRunner(
                s, n_idents=n_idents, time_scale=time_scale,
                payload=payload, settle_timeout_s=settle_timeout_s,
                clock=clock,
            )
            for s in schedulers
        ]

    @property
    def n_shards(self) -> int:
        return len(self.runners)

    def run(self, schedule: Schedule) -> list:
        parts = partition_schedule(schedule, self.n_shards)
        results: list = [None] * self.n_shards
        errors: list = []

        def _one(i):
            try:
                results[i] = self.runners[i].run(parts[i])
            except Exception as exc:  # surfaced after join, not lost
                errors.append((i, exc))

        threads = [
            threading.Thread(target=_one, args=(i,),
                             name=f"grapevine-shard-replay-{i}")
            for i in range(self.n_shards)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            i, exc = errors[0]
            raise RuntimeError(f"shard {i} replay failed") from exc
        return results


class ShardRoundDriver:
    """The cross-shard discrimination drill: N shard round loops on a
    shared tick clock feeding a ``FleetUniformityMonitor``.

    ``policy="uniform"`` is the production contract: every shard
    dispatches exactly one round per tick whether or not its queue
    holds real ops (cadence a pure function of the clock — padded
    rounds are the price of obliviousness). ``policy="skewed"`` is the
    seeded mutant ISSUE 16 requires: a shard dispatches a round ONLY
    when its own queue is hot (depth >= ``hot_threshold``), i.e. the
    scheduler leaks per-shard offered load into per-shard cadence —
    exactly what a traffic observer at fleet grain could read
    recipient activity from. The fleet verdict must flip SUSPECT on
    the mutant within a bounded number of ticks while the uniform
    policy stays PASS under any arrival shape (tests/test_fleet.py).

    ``round_fn(shard, n_real)`` optionally runs a REAL engine round
    per dispatch (the slow soaks drive live engines); default is pure
    queue accounting, which is all the monitor ever sees either way —
    it consumes only the public per-shard series.
    """

    POLICIES = ("uniform", "skewed")

    def __init__(self, n_shards: int, monitor, policy: str = "uniform",
                 batch_size: int = 8, hot_threshold: int = 4,
                 flush_every: int = 4, round_fn=None):
        if policy not in self.POLICIES:
            raise ValueError(f"policy must be one of {self.POLICIES}")
        if n_shards < 2:
            raise ValueError("the drill needs >= 2 shards")
        self.n = int(n_shards)
        self.monitor = monitor
        self.policy = policy
        self.batch_size = int(batch_size)
        self.hot_threshold = int(hot_threshold)
        self.flush_every = max(1, int(flush_every))
        self.round_fn = round_fn
        self.queue = [0] * self.n
        self.rounds = [0] * self.n
        self.fill_sum = [0.0] * self.n
        self.flushes = [0] * self.n
        self.ticks = 0

    def tick(self, arrivals) -> None:
        """One shared tick: enqueue per-shard arrivals, apply the
        dispatch policy, hand the monitor the cumulative public
        series."""
        if len(arrivals) != self.n:
            raise ValueError("arrivals must have one entry per shard")
        for i, a in enumerate(arrivals):
            self.queue[i] += int(a)
        for i in range(self.n):
            if self.policy == "skewed" and \
                    self.queue[i] < self.hot_threshold:
                continue  # the leak: cadence follows the shard's load
            n_real = min(self.queue[i], self.batch_size)
            self.queue[i] -= n_real
            if self.round_fn is not None:
                self.round_fn(i, n_real)
            self.rounds[i] += 1
            self.fill_sum[i] += n_real / self.batch_size
            if self.rounds[i] % self.flush_every == 0:
                self.flushes[i] += 1
        self.ticks += 1
        self.monitor.observe_tick([
            {
                "rounds_total": float(self.rounds[i]),
                "fill_sum": self.fill_sum[i],
                "fill_count": float(self.rounds[i]),
                "flushes_total": float(self.flushes[i]),
                "queue_depth": float(self.queue[i]),
            }
            for i in range(self.n)
        ])

    def run(self, arrival_fn, n_ticks: int, stop_on=None) -> dict:
        """Drive ``n_ticks`` ticks with ``arrival_fn(tick) ->
        per-shard arrivals``; returns the final monitor verdict.
        ``stop_on`` (e.g. ``"SUSPECT"``) ends the drill early at the
        first matching verdict — the bounded-detection measurement."""
        verdict = self.monitor.verdict()
        for k in range(n_ticks):
            self.tick(arrival_fn(k))
            verdict = self.monitor.verdict()
            if stop_on is not None and verdict["verdict"] == stop_on:
                break
        return {**verdict, "ticks": self.ticks}
