"""Scenario-diverse load harness (ROADMAP item 4's measurement half).

Deterministic, seeded, **open-loop** arrival-schedule generators plus a
replay harness that drives them through the production BatchScheduler:

- ``generators``: pre-materialized (timestamp, op-template) schedules —
  steady Poisson, bursty ON/OFF, diurnal sinusoid, pop-heavy mailbox
  drain, an adversarial probe campaign aimed at the leakmon detectors,
  and a ramp-to-saturation staircase;
- ``harness``: ``ScenarioRunner`` (open-loop replay via
  ``BatchScheduler.submit_nowait`` — overload latency is measured, not
  self-throttled) and the probe-campaign leak injector for the
  /leakaudit discrimination drill;
- ``capacity``: per-step SLO accounting over a ramp schedule and the
  saturation-knee model behind the repo's banked capacity number
  (``bench.py load_scenarios``).
"""

from .generators import (  # noqa: F401
    Schedule,
    adversarial_probe,
    bursty_onoff,
    diurnal_sinusoid,
    pop_heavy_drain,
    ramp_to_saturation,
    steady_poisson,
)
from .harness import (  # noqa: F401
    ProbeCampaignInjector,
    RunResult,
    ScenarioRunner,
    calibrate_unloaded_round,
)
from .capacity import analyze_ramp, find_knee  # noqa: F401
