"""Scenario-diverse load harness (ROADMAP item 4's measurement half).

Deterministic, seeded, **open-loop** arrival-schedule generators plus a
replay harness that drives them through the production BatchScheduler:

- ``generators``: pre-materialized (timestamp, op-template) schedules —
  steady Poisson, bursty ON/OFF, diurnal sinusoid, pop-heavy mailbox
  drain, an adversarial probe campaign aimed at the leakmon detectors,
  and a ramp-to-saturation staircase;
- ``harness``: ``ScenarioRunner`` (open-loop replay via
  ``BatchScheduler.submit_nowait`` — overload latency is measured, not
  self-throttled) and the probe-campaign leak injector for the
  /leakaudit discrimination drill;
- ``capacity``: per-step SLO accounting over a ramp schedule and the
  saturation-knee model behind the repo's banked capacity number
  (``bench.py load_scenarios``).

Fleet-shaped replay (ISSUE 16): ``partition_schedule`` splits one
schedule across N shards by recipient space, ``ShardedScenarioRunner``
replays the parts against N schedulers concurrently,
``ShardRoundDriver`` is the cross-shard schedule-uniformity
discrimination drill (uniform contract vs the seeded skewed-scheduler
mutant), and ``fleet_capacity`` folds per-shard knees into the
fleet-wide grade banked under the ``shard_count`` geometry key.
"""

from .generators import (  # noqa: F401
    Schedule,
    adversarial_probe,
    bursty_onoff,
    diurnal_sinusoid,
    partition_schedule,
    pop_heavy_drain,
    ramp_to_saturation,
    steady_poisson,
)
from .harness import (  # noqa: F401
    ProbeCampaignInjector,
    RunResult,
    ScenarioRunner,
    ShardedScenarioRunner,
    ShardRoundDriver,
    calibrate_unloaded_round,
    materialize_request,
)
from .capacity import analyze_ramp, find_knee, fleet_capacity  # noqa: F401
