"""SLO-adaptive round-collection sizing from PUBLIC load aggregates.

The engine's batch geometry is compile-fixed (`ecfg.batch_size` slots,
under-full rounds dummy-padded), so "adaptive batch sizing" on this
stack means choosing how long the scheduler's collection window stays
open and how many real ops it waits for — the two knobs that trade
commit latency against round occupancy without touching the device
program. This module makes that choice each round from three signals:

- the **arrival-rate EWMA** (obs/workload.py, PR 9) — ops/s, decayed;
- the **queue depth** at window open — ops already waiting;
- the **SLO burn rates** (obs/slo.py, PR 6) — how fast the commit-
  latency error budget is being spent.

Every input is a batch-level public aggregate: counts, rates, and
latency quantiles the telemetry leak policy already exports on
/metrics. Nothing here may read request contents, identities, keys, or
the op-type mix — the decision must stay a function a passive observer
of /metrics could compute themselves, because the round cadence it
shapes is visible on the wire. ``decide()`` takes only the queue
*depth* (an integer), never the queue, and CI seeds a mutant that
threads op contents into the decision to prove the analyzers catch the
violation (analysis/mutants.py ``adaptive_batch_from_contents``).

Policy (one decision per round, at window open):

1. **shed** — the fast burn window is spending error budget above its
   alert threshold: the SLO is in danger, so collection drops to the
   floor window and dispatches at the first quiescence gap. Smaller
   rounds cost device efficiency but cut the queue-wait term of every
   op's latency — the correct trade while the budget burns.
2. **fill** — ops already queued (depth >= batch_size): no reason to
   wait; the round leaves full regardless.
3. **sparse** — the EWMA expects less than ~one arrival inside even a
   stretched window: holding the window open buys nothing, so a lone
   client commits after the floor wait instead of the full cap.
4. **cruise** — in between: the window scales with the traffic so the
   expected fill approaches the batch size, capped at
   ``ceil_factor x`` the configured base wait. This is where adaptive
   sizing beats the static window: bursty-but-sub-saturating load gets
   fuller rounds (fewer rounds per op, more device headroom) without
   penalizing the sparse tail.
"""

from __future__ import annotations

import dataclasses
import math

#: decision-kind label values for grapevine_host_adaptive_decisions_total
DECISION_KINDS = ("shed", "fill", "sparse", "cruise")


@dataclasses.dataclass(frozen=True)
class AdaptiveBatchConfig:
    """Shape of the adaptive window policy (OPERATIONS.md §24)."""

    #: the floor collection window (ms): what "dispatch promptly" means
    #: under shed/sparse. Never 0 — a zero window would dispatch
    #: singleton rounds under concurrent load and waste whole batches.
    floor_wait_ms: float = 1.0
    #: cruise may stretch the window up to base_wait * ceil_factor when
    #: the arrival rate suggests a fuller round is one short wait away
    ceil_factor: float = 4.0
    #: fast-window burn rate above which the policy sheds latency
    #: (1.0 = spending exactly the error budget)
    shed_burn_rate: float = 1.0
    #: minimum rounds of burn-rate evidence before shed may trigger
    #: (insufficient evidence is not an overload — the SLO tracker's
    #: own min_rounds stance)
    min_burn_rounds: int = 16

    def __post_init__(self):
        if self.floor_wait_ms <= 0:
            raise ValueError("floor_wait_ms must be positive")
        if self.ceil_factor < 1.0:
            raise ValueError("ceil_factor must be >= 1")


class AdaptiveBatchPolicy:
    """Per-round window decisions; one instance per BatchScheduler.

    ``workload`` is an obs.WorkloadTelemetry (arrival EWMA) and ``slo``
    an obs.SloTracker (burn rates) — both optional so the policy
    degrades to static behavior when a signal is missing (a stub engine
    in tests, or an SLO-less deployment).
    """

    def __init__(
        self,
        batch_size: int,
        base_wait_s: float,
        idle_gap_s: float,
        cfg: AdaptiveBatchConfig | None = None,
        workload=None,
        slo=None,
        registry=None,
    ):
        if batch_size < 1:
            raise ValueError("batch_size must be >= 1")
        self.batch_size = int(batch_size)
        self.base_wait = float(base_wait_s)
        self.idle_gap = float(idle_gap_s)
        self.cfg = cfg or AdaptiveBatchConfig()
        self.workload = workload
        self.slo = slo
        self._g_wait = self._g_target = self._c_decisions = None
        if registry is not None:
            self._g_wait = registry.gauge(
                "grapevine_host_adaptive_wait_ms",
                "collection-window cap chosen by the adaptive batch "
                "policy for the current round (ms)")
            self._g_target = registry.gauge(
                "grapevine_host_adaptive_target_fill",
                "real-op fill target chosen for the current round "
                "(<= the compiled batch size; the round is dummy-"
                "padded to geometry either way)")
            self._c_decisions = registry.counter(
                "grapevine_host_adaptive_decisions_total",
                "adaptive window decisions by kind",
                labels={"phase": DECISION_KINDS})

    # -- signal reads (each tolerates a missing provider) ---------------

    def _arrival_rate(self) -> float:
        if self.workload is None:
            return 0.0
        try:
            return float(self.workload.arrival_rate())
        except Exception:  # pragma: no cover - defensive
            return 0.0

    def _fast_burn(self) -> tuple[float, int]:
        if self.slo is None:
            return 0.0, 0
        try:
            rates = self.slo.burn_rates()
            return float(rates["fast_burn_rate"]), int(rates["fast_rounds"])
        except Exception:  # pragma: no cover - defensive
            return 0.0, 0

    # -- the per-round decision -----------------------------------------

    def decide(self, queue_depth: int) -> tuple[float, float, int]:
        """(max_wait_s, idle_gap_s, target_fill) for the round about to
        be collected. ``queue_depth`` is the scheduler queue length at
        window open — an integer aggregate, never the queue itself."""
        cfg = self.cfg
        floor = cfg.floor_wait_ms / 1000.0
        rate = self._arrival_rate()
        burn, burn_rounds = self._fast_burn()
        bs = self.batch_size
        if burn > cfg.shed_burn_rate and burn_rounds >= cfg.min_burn_rounds:
            kind, wait, target = "shed", floor, max(1, queue_depth)
        elif queue_depth >= bs:
            kind, wait, target = "fill", floor, bs
        else:
            need = bs - queue_depth
            expected = rate * self.base_wait
            if expected < 1.0:
                kind, wait, target = "sparse", floor, max(1, queue_depth)
            else:
                # stretch the window toward the time the EWMA says a
                # full round takes to accumulate, capped at the ceiling
                t_full = need / rate if rate > 0 else self.base_wait
                wait = min(self.base_wait * cfg.ceil_factor,
                           max(self.base_wait, t_full))
                kind, target = "cruise", bs
        target = min(bs, max(1, int(math.ceil(target))))
        if self._c_decisions is not None:
            self._c_decisions.inc(phase=kind)
            self._g_wait.set(wait * 1000.0)
            self._g_target.set(target)
        return wait, min(self.idle_gap, wait), target
