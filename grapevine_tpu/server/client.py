"""Client library: attested-style connection + signed CRUD helpers.

The "example client" role from the reference (README.md:128,179-199):
handshake via Auth, then per-request challenge-sign-encrypt over Query.
The client holds one ristretto identity key; every request draws the next
32-byte challenge from the session RNG (staying in lockstep with the
server), signs it under ``b"grapevine-challenge"``, and ships the
constant-size encrypted QueryRequest.
"""

from __future__ import annotations

import threading

import grpc

from ..session import channel as chan
from ..session.chacha import ChallengeRng
from ..wire import constants as C
from ..wire import protowire as pw
from ..wire.records import QueryRequest, QueryResponse, RequestRecord
from .uri import SERVICE_NAME
from .uri import GrapevineUri


class GrapevineClient:
    def __init__(
        self,
        uri: str | GrapevineUri,
        identity_seed: bytes,
        root_certs: bytes | None = None,
        signature_scheme: str = "schnorrkel",
        server_static: bytes | None = None,
        client_static=None,
    ):
        self.uri = uri if isinstance(uri, GrapevineUri) else GrapevineUri.parse(uri)
        from ..session import get_signature_scheme

        self._scheme = get_signature_scheme(signature_scheme)
        self.sk, self.public_key = self._scheme.keygen(identity_seed)
        if self.uri.use_tls:
            creds = grpc.ssl_channel_credentials(root_certificates=root_certs)
            self._grpc = grpc.secure_channel(self.uri.address, creds)
        else:
            self._grpc = grpc.insecure_channel(self.uri.address)
        ident = lambda b: b  # noqa: E731
        self._auth_rpc = self._grpc.unary_unary(
            f"/{SERVICE_NAME}/Auth", request_serializer=ident, response_deserializer=ident
        )
        self._query_rpc = self._grpc.unary_unary(
            f"/{SERVICE_NAME}/Query", request_serializer=ident, response_deserializer=ident
        )
        self._channel: chan.SecureChannel | None = None
        self._challenge: ChallengeRng | None = None
        self._channel_id = b""
        #: pinned server static (IX): auth() rejects a server whose
        #: handshake-authenticated static differs (MITM detection)
        self._server_static = server_static
        #: optional client static X25519 private key (IX initiator s)
        self._client_static = client_static
        # challenge draw + AEAD counters + wire round-trip must stay
        # ordered: an overtaking request desyncs the server's lockstep
        # challenge RNG permanently (reference README.md:195-196)
        self._lock = threading.Lock()

    # -- connection -----------------------------------------------------

    def auth(self, attestation=None) -> None:
        """Run the key exchange and seed the challenge RNG.

        Holds the same lock as ``_query``: a re-auth racing an in-flight
        request would otherwise mix the old challenge RNG with the new
        channel and permanently desync the server's lockstep RNG.
        """
        state, msg1 = chan.client_handshake(self._client_static)
        with self._lock:
            reply = pw.decode_auth_with_seed(
                self._auth_rpc(pw.encode_auth_message(pw.AuthMessage(data=msg1)))
            )
            self._channel = chan.client_finish(
                state,
                reply.auth_message.data,
                attestation,
                expected_server_static=self._server_static,
            )
            payload = self._channel.decrypt(reply.encrypted_challenge_seed)
            # seed (32) ‖ server-assigned session token (the channel id)
            seed, token = payload[:32], payload[32:]
            self._challenge = ChallengeRng(seed)
            self._channel_id = token

    def _query(self, req: QueryRequest) -> QueryResponse:
        if self._channel is None or self._challenge is None:
            raise RuntimeError("call auth() first")
        with self._lock:
            challenge = self._challenge.next_challenge()
            req.auth_identity = self.public_key
            req.auth_signature = self._scheme.sign(
                self.sk, C.GRAPEVINE_CHALLENGE_SIGNING_CONTEXT, challenge
            )
            ciphertext = self._channel.encrypt(req.pack())
            reply = pw.decode_envelope(
                self._query_rpc(
                    pw.encode_envelope(
                        pw.EnvelopeMessage(channel_id=self._channel_id, data=ciphertext)
                    )
                )
            )
            return QueryResponse.unpack(self._channel.decrypt(reply.data))

    # -- CRUD helpers (reference README.md:162-175) ---------------------

    def create(self, recipient: bytes, payload: bytes) -> QueryResponse:
        return self._query(
            QueryRequest(
                request_type=C.REQUEST_TYPE_CREATE,
                record=RequestRecord(recipient=recipient, payload=payload),
            )
        )

    def read(self, msg_id: bytes = C.ZERO_MSG_ID) -> QueryResponse:
        """Read by id; the zero id means "my next message"."""
        return self._query(
            QueryRequest(
                request_type=C.REQUEST_TYPE_READ,
                record=RequestRecord(msg_id=msg_id),
            )
        )

    def update(self, msg_id: bytes, recipient: bytes, payload: bytes) -> QueryResponse:
        return self._query(
            QueryRequest(
                request_type=C.REQUEST_TYPE_UPDATE,
                record=RequestRecord(msg_id=msg_id, recipient=recipient, payload=payload),
            )
        )

    def delete(self, msg_id: bytes = C.ZERO_MSG_ID, recipient: bytes = C.ZERO_PUBKEY) -> QueryResponse:
        """Delete by id (recipient must match), or pop my next message."""
        return self._query(
            QueryRequest(
                request_type=C.REQUEST_TYPE_DELETE,
                record=RequestRecord(msg_id=msg_id, recipient=recipient),
            )
        )

    def close(self):
        self._grpc.close()
