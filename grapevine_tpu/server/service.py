"""gRPC frontend: the GrapevineAPI service (Auth, Query).

Faithful to the reference service shape (grapevine.proto:10-15): ``Auth``
performs the key exchange and returns the handshake reply plus the
encrypted 32-byte challenge seed (AuthMessageWithChallengeSeed,
grapevine.proto:26-36); ``Query`` carries only encrypted constant-size
blobs. Implemented with grpc's generic handlers and the hand-rolled
protowire codec — no protoc build step.

Per-request auth (reference README.md:187-199): the server advances the
session's challenge RNG on every *authenticated* Query (lockstep,
README.md:195-196; the AEAD decrypt proves channel ownership before a
challenge is consumed), verifies the Schnorr signature over the challenge
under context ``b"grapevine-challenge"``, and fails fast with
INVALID_ARGUMENT on bad signatures or malformed requests (the reference's
hard-error behavior, grapevine.proto:57-64).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from concurrent import futures

import grpc

from ..config import GrapevineConfig
from ..engine.batcher import GrapevineEngine, validate_request

# the channel layer selects its backend itself: the cryptography wheel
# when present, else the stdlib port (session/stdcrypto.py) — this
# import succeeds in every container
from ..session import channel as chan
from ..session.chacha import ChallengeRng
from ..testing.reference import HardProtocolError
from ..wire import constants as C
from ..wire import protowire as pw
from ..wire.records import QueryRequest
from .scheduler import AuthFailure, BatchScheduler, SchedulerShutdown

log = logging.getLogger("grapevine_tpu.server")

from .uri import SERVICE_NAME  # noqa: E402  (re-export, see uri.py)


#: bytes appended to the challenge seed inside the Auth ciphertext: the
#: server-assigned session token the client must present as channel_id.
SESSION_TOKEN_SIZE = 16


def run_expiry_loop(engine, config, stop_event, clock, health=None):
    """The expiry-sweep loop, shared by the monolithic server and the
    engine tier (server/tier.py) — whoever owns the device owns this."""
    interval = max(1.0, config.expiry_period / 10)
    while not stop_event.wait(interval):
        evicted = engine.expire(clock())
        if evicted:
            log.info("expiry sweep evicted %d records", evicted)
        # health() syncs the device (stash sampling) — only pay that
        # when someone is listening at DEBUG
        if log.isEnabledFor(logging.DEBUG):
            log.debug("health %s", (health or engine.health)())


class _Session:
    __slots__ = ("channel", "challenge_rng", "created", "last_used", "lock",
                 "worker", "worker_epoch")

    def __init__(self, secure_channel: chan.SecureChannel, seed: bytes):
        self.channel = secure_channel
        self.challenge_rng = ChallengeRng(seed)
        self.created = time.time()
        self.last_used = self.created
        self.lock = threading.Lock()
        #: hostpipe sticky worker (index, epoch-at-attach) when the
        #: session's cipher states live in a worker process; None = the
        #: in-process path. A crashed worker bumps its epoch, so a stale
        #: session can never resume against a respawned worker's empty
        #: session map with desynced counters.
        self.worker: int | None = None
        self.worker_epoch = 0


class GrapevineServer:
    """The host server: session registry + engine + expiry timer."""

    def __init__(
        self,
        config: GrapevineConfig | None = None,
        seed: int = 0,
        max_wait_ms: float | None = None,
        attestation=None,
        clock=None,
        session_ttl: float = 3600.0,
        max_sessions: int = 4096,
        identity: chan.ServerIdentity | None = None,
        scheduler=None,
        leakmon=None,
        durability=None,
        worker_restart: bool = False,
        trace_ring_size: int = 512,
        slo=None,
        profile_enable: bool = False,
        replicate_to: str | None = None,
        ship_every: int = 1,
        host_workers: int = 0,
        adaptive_batch: bool = False,
        flush_window_ms: float | None = None,
    ):
        self.config = config or GrapevineConfig()
        if scheduler is not None and replicate_to is not None:
            raise ValueError(
                "replication needs the journal in-process (the frontend "
                "role has no journal to ship)"
            )
        if scheduler is not None:
            # injected op sink (server/tier.py's FrontendServer passes
            # its engine-tier RPC stub): no in-process device engine
            if durability is not None:
                raise ValueError(
                    "durability needs the device engine in-process (the "
                    "frontend role has no state to checkpoint)"
                )
            if adaptive_batch or flush_window_ms:
                raise ValueError(
                    "adaptive/flush-aware batching shapes the device "
                    "round collection window — only the engine owner "
                    "has one (the frontend forwards ops unbatched)"
                )
            self.engine = None
            self.scheduler = scheduler
        else:
            # constructing a durable engine runs recovery (checkpoint
            # load + journal replay) before the listener ever binds
            self.engine = GrapevineEngine(
                self.config, seed=seed, durability=durability
            )
            sched_kwargs = (
                {} if max_wait_ms is None else {"max_wait_ms": max_wait_ms}
            )
            from ..session import get_signature_scheme

            self.scheduler = BatchScheduler(
                self.engine,
                clock=clock,
                scheme=get_signature_scheme(self.config.signature_scheme),
                restart_on_crash=worker_restart,
                flush_window_ms=flush_window_ms,
                **sched_kwargs,
            )
        self.attestation = attestation or chan.NullAttestation()
        #: IX responder static; ``server.identity.public`` is what
        #: clients pin via ``expected_server_static`` (SECURITY.md)
        self.identity = identity or chan.ServerIdentity.generate()
        self._sessions: dict[bytes, _Session] = {}
        self._sessions_lock = threading.Lock()
        self.session_ttl = session_ttl
        self.max_sessions = max_sessions
        self._grpc_server: grpc.Server | None = None
        self._expiry_stop = threading.Event()
        self._expiry_thread: threading.Thread | None = None
        self.clock = clock or (lambda: int(time.time()))
        #: one merged telemetry namespace: the engine's registry when we
        #: own a device engine, a standalone one in the injected-
        #: scheduler (frontend) role — either way /metrics serves engine
        #: + scheduler + session telemetry from a single registry
        if self.engine is not None:
            self.metrics_registry = self.engine.metrics.registry
        else:
            from ..obs import TelemetryRegistry

            self.metrics_registry = TelemetryRegistry()
        self._g_sessions = self.metrics_registry.gauge(
            "grapevine_sessions", "live authenticated sessions"
        )
        #: multiprocess verify/codec pipeline (server/hostpipe.py):
        #: 0 = the historical in-process path, N = a pool of N worker
        #: processes holding the session cipher states sticky by
        #: channel_id. Crash policy rides worker_restart, like the
        #: batch collector.
        self.hostpipe = None
        if host_workers:
            from .hostpipe import HostPipeline

            self.hostpipe = HostPipeline(
                host_workers,
                scheme=self.config.signature_scheme,
                restart_on_crash=worker_restart,
                registry=self.metrics_registry,
            )
            self.hostpipe.on_crash(self._drop_worker_sessions)
            if self.engine is not None:
                # scheduler-side verify fan-out shares the same pool
                self.scheduler.hostpipe = self.hostpipe
        self._metrics_server = None
        #: continuous obliviousness auditing (obs/leakmon.py): pass a
        #: LeakMonitorConfig to watch every round's transcript. Device-
        #: owner only — the frontend role never sees a transcript.
        self.leakmon = None
        if leakmon is not None:
            if self.engine is None:
                raise ValueError(
                    "leak monitoring needs the device engine in-process "
                    "(the frontend role has no transcript to audit)"
                )
            from ..obs.leakmon import EngineLeakMonitor

            self.leakmon = EngineLeakMonitor.for_engine(self.engine, leakmon)
            self.engine.attach_leakmon(self.leakmon)
        #: primary-side journal shipping (engine/replication.py): stream
        #: every sealed frame to a hot standby. Device-owner only — the
        #: frontend role has no journal.
        self.shipper = None
        if replicate_to is not None:
            from ..engine.replication import JournalShipper

            self.shipper = JournalShipper(
                self.engine, replicate_to, ship_every=ship_every
            )
            self.shipper.start()
            if self.leakmon is not None:
                # fold the shipper's frame-length books into the audit
                # verdict (ship_cadence detector, obs/leakmon.py)
                self.leakmon.attach_shipper(self.shipper)
        #: round-trace profiler + commit-latency SLO + optional capture
        #: gate — one shared attach policy (obs.attach_round_observability
        #: has the rationale and the observe-only default contract)
        self.tracer = self.slo = self.profiler = None
        if self.engine is not None:
            from ..obs import attach_round_observability

            self.tracer, self.slo, self.profiler = (
                attach_round_observability(
                    self.engine, self.metrics_registry,
                    trace_ring_size=trace_ring_size, slo=slo,
                    profile_enable=profile_enable,
                )
            )
            if adaptive_batch:
                # SLO-adaptive window sizing (server/adaptive.py has the
                # policy and its obliviousness argument). Planted after
                # observability attaches so the policy reads the same
                # arrival EWMA and burn rates /metrics exports.
                from .adaptive import AdaptiveBatchPolicy

                self.scheduler.adaptive = AdaptiveBatchPolicy(
                    self.engine.ecfg.batch_size,
                    self.scheduler.max_wait,
                    self.scheduler.idle_gap,
                    workload=self.engine.workload,
                    slo=self.slo,
                    registry=self.metrics_registry,
                )

    # -- RPC handlers (raw-bytes serializers) ---------------------------

    def _auth(self, request_bytes: bytes, context: grpc.ServicerContext) -> bytes:
        try:
            auth_msg = pw.decode_auth_message(request_bytes)
            reply, secure_channel = chan.server_handshake(
                auth_msg.data, self.attestation, identity=self.identity
            )
        except ValueError as exc:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, f"handshake: {exc}")
        seed = chan.new_challenge_seed()
        # the channel id is a server-assigned random token, delivered only
        # inside the authenticated ciphertext: unguessable, unforgeable,
        # and immune to session-clobbering via a replayed client pubkey
        token = os.urandom(SESSION_TOKEN_SIZE)
        encrypted_seed = secure_channel.encrypt(seed + token)
        session = _Session(secure_channel, seed)
        if self.hostpipe is not None:
            from .hostpipe import HostPipeError

            # hand the cipher states (counters included: send_n is 1
            # after the seed ciphertext above) to the sticky worker
            # BEFORE the client can learn the token from our reply
            try:
                session.worker, session.worker_epoch = (
                    self.hostpipe.attach_session(token, secure_channel, seed)
                )
            except HostPipeError as exc:
                context.abort(
                    grpc.StatusCode.UNAVAILABLE, f"host pipeline: {exc}"
                )
        with self._sessions_lock:
            self._evict_sessions_locked()
            self._sessions[token] = session
            self._g_sessions.set(len(self._sessions))
        return pw.encode_auth_with_seed(
            pw.AuthMessageWithChallengeSeed(
                auth_message=pw.AuthMessage(data=reply),
                encrypted_challenge_seed=encrypted_seed,
            )
        )

    def _evict_sessions_locked(self):
        """Drop idle sessions past the TTL; at the cap, drop the oldest."""
        now = time.time()
        if self.session_ttl > 0:
            dead = [k for k, s in self._sessions.items() if now - s.last_used > self.session_ttl]
            for k in dead:
                self._forget_session_locked(k)
        while len(self._sessions) >= self.max_sessions:
            oldest = min(self._sessions, key=lambda k: self._sessions[k].last_used)
            self._forget_session_locked(oldest)

    def _forget_session_locked(self, token: bytes):
        session = self._sessions.pop(token, None)
        if (
            session is not None
            and session.worker is not None
            and self.hostpipe is not None
        ):
            # fire-and-forget: the worker's copy of the cipher state is
            # garbage once the registry forgets the token
            self.hostpipe.detach_session(token)

    def _drop_worker_sessions(self, worker_index: int):
        """hostpipe crash listener: every session stuck to the dead
        worker lost its cipher states — drop them so clients get a
        clean UNAUTHENTICATED and re-auth, instead of a decrypt loop
        against a respawned worker that never knew them."""
        with self._sessions_lock:
            dead = [
                k for k, s in self._sessions.items()
                if s.worker == worker_index
            ]
            for k in dead:
                del self._sessions[k]
            self._g_sessions.set(len(self._sessions))
        if dead:
            log.warning(
                "dropped %d sessions stuck to dead hostpipe worker %d",
                len(dead), worker_index,
            )

    def _query(self, request_bytes: bytes, context: grpc.ServicerContext) -> bytes:
        try:
            envelope = pw.decode_envelope(request_bytes)
        except ValueError as exc:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, f"malformed envelope: {exc}")
        now = time.time()
        with self._sessions_lock:
            session = self._sessions.get(envelope.channel_id)
            # enforce the TTL at use time too: a quiet server (no Auth
            # traffic) must not serve — or retain — idle-expired sessions
            if (
                session is not None
                and self.session_ttl > 0
                and now - session.last_used > self.session_ttl
            ):
                self._forget_session_locked(envelope.channel_id)
                self._g_sessions.set(len(self._sessions))
                session = None
        if session is None:
            context.abort(grpc.StatusCode.UNAUTHENTICATED, "unknown channel")
        if session.worker is not None:
            return self._query_hostpipe(envelope, session, now, context)
        with session.lock:
            # AEAD authentication FIRST: a replayed or injected envelope
            # (channel_id travels in the clear) must fail here without
            # consuming a challenge or advancing any cipher state —
            # otherwise one injected Query permanently desyncs the
            # legitimate client's lockstep (an injection-DoS the
            # reference never faced behind TLS). The channel's recv
            # counter likewise only advances on successful decryption.
            try:
                plaintext = session.channel.decrypt(envelope.data, aad=envelope.aad)
            except Exception:
                context.abort(grpc.StatusCode.UNAUTHENTICATED, "decryption failed")
            # lockstep: the sender has proven channel ownership; draw
            # their challenge (client drew the same one before signing).
            # Only now refresh the idle timestamp — unauthenticated
            # garbage must not keep a session alive past its TTL or pin
            # it against LRU eviction
            challenge = session.challenge_rng.next_challenge()
            session.last_used = now
            try:
                req = QueryRequest.unpack(plaintext)
                validate_request(req)
            except (ValueError, HardProtocolError) as exc:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(exc))
            # signature checked inside the round's batch verification
            # (scheduler.py: one multi-scalar multiplication per round)
            try:
                resp = self.scheduler.submit(
                    req,
                    auth=(
                        req.auth_identity,
                        C.GRAPEVINE_CHALLENGE_SIGNING_CONTEXT,
                        challenge,
                        req.auth_signature,
                    ),
                )
            except AuthFailure:
                context.abort(grpc.StatusCode.UNAUTHENTICATED, "bad challenge signature")
            except SchedulerShutdown as exc:
                # the drain path's explicit settle: the op never reached
                # the device — UNAVAILABLE tells the client to retry
                # against a serving replica
                context.abort(grpc.StatusCode.UNAVAILABLE, str(exc))
            ciphertext = session.channel.encrypt(resp.pack())
        return pw.encode_envelope(pw.EnvelopeMessage(data=ciphertext))

    def _query_hostpipe(self, envelope, session, now, context) -> bytes:
        """The multiprocess Query path: AEAD open, challenge draw,
        unpack/validate, and the response seal all run on the session's
        sticky hostpipe worker — same semantics as the inline path in
        :meth:`_query` (auth-first, lockstep, fail-fast), same status
        codes, but the GIL-bound work is off this process."""
        from .hostpipe import (
            HostAuthError,
            HostInvalidRequest,
            HostPipeError,
        )

        pipe = self.hostpipe
        token = envelope.channel_id
        with session.lock:
            if pipe.epoch_of(session.worker) != session.worker_epoch:
                # the sticky worker died after this session was looked
                # up (the crash listener races this request): its cipher
                # states are gone — drop and force a re-auth
                with self._sessions_lock:
                    self._sessions.pop(token, None)
                    self._g_sessions.set(len(self._sessions))
                context.abort(
                    grpc.StatusCode.UNAUTHENTICATED,
                    "session lost to a host worker restart",
                )
            try:
                req, challenge = pipe.open_request(
                    token, envelope.data, envelope.aad
                )
            except HostAuthError:
                context.abort(
                    grpc.StatusCode.UNAUTHENTICATED, "decryption failed"
                )
            except HostInvalidRequest as exc:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(exc))
            except HostPipeError:
                with self._sessions_lock:
                    self._forget_session_locked(token)
                    self._g_sessions.set(len(self._sessions))
                context.abort(
                    grpc.StatusCode.UNAVAILABLE,
                    "host worker lost; re-authenticate",
                )
            session.last_used = now
            try:
                resp = self.scheduler.submit(
                    req,
                    auth=(
                        req.auth_identity,
                        C.GRAPEVINE_CHALLENGE_SIGNING_CONTEXT,
                        challenge,
                        req.auth_signature,
                    ),
                )
            except AuthFailure:
                context.abort(
                    grpc.StatusCode.UNAUTHENTICATED, "bad challenge signature"
                )
            except SchedulerShutdown as exc:
                context.abort(grpc.StatusCode.UNAVAILABLE, str(exc))
            try:
                ciphertext = pipe.seal_response(token, resp.pack())
            except HostPipeError:
                with self._sessions_lock:
                    self._forget_session_locked(token)
                    self._g_sessions.set(len(self._sessions))
                context.abort(
                    grpc.StatusCode.UNAVAILABLE,
                    "host worker lost; re-authenticate",
                )
        return pw.encode_envelope(pw.EnvelopeMessage(data=ciphertext))

    # -- lifecycle ------------------------------------------------------

    def _handlers(self) -> grpc.GenericRpcHandler:
        identity = lambda b: b  # noqa: E731 — raw bytes on the wire
        method_handlers = {
            "Auth": grpc.unary_unary_rpc_method_handler(
                self._auth, request_deserializer=identity, response_serializer=identity
            ),
            "Query": grpc.unary_unary_rpc_method_handler(
                self._query, request_deserializer=identity, response_serializer=identity
            ),
        }
        return grpc.method_handlers_generic_handler(SERVICE_NAME, method_handlers)

    def start(self, listen_uri, tls_cert: bytes | None = None, tls_key: bytes | None = None) -> int:
        """Start serving; returns the bound port."""
        from .uri import GrapevineUri

        uri = (
            listen_uri
            if isinstance(listen_uri, GrapevineUri)
            else GrapevineUri.parse(listen_uri)
        )
        self._grpc_server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max(8, 2 * self.config.batch_size))
        )
        self._grpc_server.add_generic_rpc_handlers((self._handlers(),))
        if uri.use_tls:
            if not (tls_cert and tls_key):
                raise ValueError("grapevine:// (TLS) requires tls_cert and tls_key")
            creds = grpc.ssl_server_credentials([(tls_key, tls_cert)])
            port = self._grpc_server.add_secure_port(uri.address, creds)
        else:
            port = self._grpc_server.add_insecure_port(uri.address)
        if port == 0:
            raise RuntimeError(f"failed to bind {uri.address}")
        self._grpc_server.start()
        if self.config.expiry_period > 0 and self.engine is not None:
            self._expiry_thread = threading.Thread(target=self._expiry_loop, daemon=True)
            self._expiry_thread.start()
        log.info("grapevine-tpu serving on %s", uri)
        return port

    def health(self) -> dict:
        """Aggregate metrics (SURVEY §5: never keyed by client identity).

        One merged view: engine counters, scheduler/queue gauges, phase
        histograms, and ORAM stash telemetry all come from the shared
        obs registry (engine/metrics.py), so a loopback client sees the
        same picture /metrics exports — not just the engine snapshot.
        """
        with self._sessions_lock:
            n_sessions = len(self._sessions)
        if self.engine is not None:
            detail = self.engine.health()
        else:
            # frontend role: no device engine in-process; the registry
            # still carries the session gauge (engine telemetry lives on
            # the engine tier's own endpoint)
            detail = self.metrics_registry.snapshot()
        return {"sessions": n_sessions, **detail}

    def healthz(self, stall_threshold: float = 30.0) -> tuple[bool, dict]:
        """Liveness verdict for the /healthz endpoint (obs/httpd.py).

        Unhealthy when the scheduler's collector thread has died or its
        oldest queued op has waited past ``stall_threshold`` (the engine
        wedged mid-round); an idle server with an empty queue is healthy
        no matter how long ago the last round committed. Lock-light by
        design — this must answer while a stuck round holds the engine
        lock."""
        healthy = True
        # role tag: the fleet aggregator (obs/fleet.py) folds member
        # healthz docs and needs to tell tiers apart by body alone
        detail: dict = {"role": "frontend" if self.engine is None
                        else "mono"}
        sched = self.scheduler
        if hasattr(sched, "worker_alive"):  # injected stubs may lack it
            alive = sched.worker_alive()
            stall = sched.stall_age()
            detail["worker_alive"] = alive
            detail["stall_age_s"] = round(stall, 3)
            healthy = alive and stall < stall_threshold
        if self.engine is not None:
            age = self.engine.metrics.last_round_age()
            detail["last_round_age_s"] = None if age is None else round(age, 3)
            if self.engine.durability is not None:
                # last-durable-round + recovery progress (batch-level
                # sequence numbers only) — the RPO a probe can alert on
                detail["durability"] = self.engine.durability.status()
        if self.hostpipe is not None:
            # a dead verify/codec worker with restart off means part of
            # the session space can never decrypt again — stop routing
            # here so a supervisor can recycle the process
            alive = self.hostpipe.alive()
            detail["host_workers_alive"] = self.hostpipe.alive_count()
            detail["host_workers"] = self.hostpipe.workers
            healthy = healthy and alive
        if self.shipper is not None:
            detail["replication"] = self.shipper.stats()
            # a fatally-fenced shipper means a standby promoted out from
            # under us — this primary must stop serving (split-brain)
            healthy = healthy and self.shipper.fatal is None
        if self.leakmon is not None:
            # the leak audit verdict is part of liveness: a SUSPECT
            # transcript means the engine is *misbehaving* even though
            # it is serving — stop routing to it (OPERATIONS.md runbook:
            # quarantine, dump, re-baseline). Cached verdict: /healthz
            # must not pay detector math on the probe path.
            v = self.leakmon.last_verdict()
            detail["leakaudit"] = v["verdict"]
            healthy = healthy and v["verdict"] == "PASS"
        if self.slo is not None:
            # multi-window burn-rate verdict (obs/slo.py): a breached
            # commit-latency SLO is a serving fault like any other —
            # 503 stops routing before the error budget is gone
            # (OPERATIONS.md §12). O(window) scan over round stamps,
            # lock-independent of the engine.
            sv = self.slo.verdict()
            detail["slo"] = sv
            healthy = healthy and sv["ok"]
        return healthy, detail

    def start_metrics(self, port: int, host: str = "127.0.0.1",
                      stall_threshold: float = 30.0) -> int:
        """Serve /metrics + /healthz on ``host:port``; returns the bound
        port (pass 0 for an ephemeral one). Off unless called — the CLI
        wires ``--metrics-port`` here."""
        from ..obs import MetricsServer

        if self.engine is not None:
            try:  # populate the "sort" phase split before the first scrape
                self.engine.calibrate_sort_phase()
            except Exception:  # best-effort: metrics must still bind
                pass
            try:  # and the "posmap" position-resolution split (PR 7)
                self.engine.calibrate_posmap_phase()
            except Exception:
                pass
        lm = self.leakmon
        self._metrics_server = MetricsServer(
            self.metrics_registry,
            health=lambda: self.healthz(stall_threshold),
            refresh=(self.engine.sample_stash if self.engine is not None
                     else None),
            host=host,
            port=port,
            leakaudit=lm.verdict if lm is not None else None,
            flightrec=lm.recorder.dump if lm is not None else None,
            trace=(self.tracer.chrome_trace if self.tracer is not None
                   else None),
            profile=(self.profiler.capture if self.profiler is not None
                     else None),
        )
        return self._metrics_server.start()

    def _expiry_loop(self):
        run_expiry_loop(self.engine, self.config, self._expiry_stop,
                        self.clock, health=self.health)

    def stop(self, grace: float = 1.0, checkpoint: bool = False):
        """Drain: stop listeners, settle queued ops (SchedulerShutdown),
        finish the in-flight round, then optionally seal a final
        checkpoint — the SIGTERM path server/cli.py installs."""
        self._expiry_stop.set()
        if self._metrics_server is not None:
            self._metrics_server.stop()
            self._metrics_server = None
        if self._grpc_server is not None:
            self._grpc_server.stop(grace).wait()
        if self.shipper is not None:
            self.shipper.close()
        self.scheduler.close()
        if self.hostpipe is not None:
            self.hostpipe.close()
        if self.leakmon is not None:
            self.leakmon.close()
        if self.engine is not None:
            if checkpoint:
                self.engine.checkpoint_now()
            self.engine.close()

    def wait(self):
        if self._grpc_server is not None:
            self._grpc_server.wait_for_termination()
