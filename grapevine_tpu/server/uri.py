"""grapevine:// URI scheme.

Mirrors the reference's typed URI crate: scheme ``grapevine`` (TLS,
default port 443) and ``insecure-grapevine`` (plaintext, default port
3229) (reference uri/src/lib.rs:11-26).
"""

from __future__ import annotations

import dataclasses
from urllib.parse import urlparse

#: gRPC service name (reference grapevine.proto:10); lives here so the
#: jax-free client library can import it without touching the engine
SERVICE_NAME = "grapevine.GrapevineAPI"

SCHEME_SECURE = "grapevine"
SCHEME_INSECURE = "insecure-grapevine"
DEFAULT_SECURE_PORT = 443
DEFAULT_INSECURE_PORT = 3229


@dataclasses.dataclass(frozen=True)
class GrapevineUri:
    host: str
    port: int
    use_tls: bool

    @classmethod
    def parse(cls, uri: str) -> "GrapevineUri":
        parsed = urlparse(uri)
        if parsed.scheme == SCHEME_SECURE:
            use_tls, default_port = True, DEFAULT_SECURE_PORT
        elif parsed.scheme == SCHEME_INSECURE:
            use_tls, default_port = False, DEFAULT_INSECURE_PORT
        else:
            raise ValueError(
                f"unknown scheme {parsed.scheme!r}: expected "
                f"{SCHEME_SECURE}:// or {SCHEME_INSECURE}://"
            )
        if not parsed.hostname:
            raise ValueError("missing host")
        return cls(
            host=parsed.hostname,
            port=parsed.port if parsed.port is not None else default_port,
            use_tls=use_tls,
        )

    @property
    def address(self) -> str:
        host = f"[{self.host}]" if ":" in self.host else self.host  # IPv6
        return f"{host}:{self.port}"

    def __str__(self) -> str:
        scheme = SCHEME_SECURE if self.use_tls else SCHEME_INSECURE
        return f"{scheme}://{self.host}:{self.port}"
