"""Multiprocess verify/codec worker pool: the off-GIL host pipeline.

PERF.md's ceiling harness showed the host path parallelizes (~130 µs of
verify + AEAD + codec per op on one core), but everything ran in-process
under one GIL: the pure-Python AEAD/poly1305 work, challenge draws,
request unpack/validate, and the signature MSM's module-locked native
calls all serialized behind each other. This module moves that work to
a pool of worker *processes* (one Python runtime each — real cores, no
GIL sharing) while keeping every protocol invariant:

- **Sticky sessions.** A session's cipher states are *stateful*
  (directional AEAD counters, lockstep challenge RNG), so a channel's
  frames must always land on the same worker. Routing is the public
  function ``sha256(channel_id) % workers`` — many channels share one
  worker and the worker index reveals nothing a passive observer of the
  channel_id (which travels in the clear) could not already compute.
- **Auth-first semantics preserved.** The worker decrypts before
  drawing a challenge, exactly like the in-process path: an injected
  envelope fails AEAD without consuming a challenge or advancing any
  cipher state (service.py's injection-DoS note).
- **Crash = session loss, loudly.** A worker that dies takes its cipher
  states with it. The pool fails the dead worker's in-flight tasks,
  bumps the worker's epoch (so stale sessions can never resume on a
  respawned worker), notifies crash listeners (GrapevineServer drops
  the affected sessions — clients re-auth), increments
  ``grapevine_host_worker_crash_total``, and — under the same
  ``restart_on_crash`` policy as the batch collector (PR 4) — respawns
  a fresh worker. ``alive()`` folds into /healthz either way.
- **jax-free workers.** Workers are started from a forkserver/spawn
  context and import only the session/wire layers (the stdlib crypto
  backend, the ctypes native library, the pure-Python codec) — never
  the engine, so worker boot costs milliseconds, not a device runtime.

Telemetry: the ``grapevine_host_*`` families registered here are
label-free or declared-values-only (task kind under the ``phase`` key,
worker index under the integer-only ``worker`` key — a topology
position, never a channel identity; obs/registry.py)."""

from __future__ import annotations

import hashlib
import logging
import multiprocessing
import os
import threading
from concurrent.futures import Future, TimeoutError as _FutureTimeout

log = logging.getLogger("grapevine_tpu.hostpipe")

#: task kinds — the declared `phase` label values for
#: grapevine_host_tasks_total (anything else is a registration error)
TASK_KINDS = ("attach", "detach", "open", "seal", "verify", "ping")

#: default cap on waiting for one worker task; a worker wedged past
#: this is indistinguishable from dead for the caller
DEFAULT_TIMEOUT_S = 30.0


class HostPipeError(RuntimeError):
    """Base for pool failures."""


class HostWorkerCrash(HostPipeError):
    """The sticky worker died; its sessions are unrecoverable."""


class HostAuthError(HostPipeError):
    """AEAD/authentication failure inside a worker (maps to
    UNAUTHENTICATED; no cipher state was advanced)."""


class HostInvalidRequest(HostPipeError):
    """Malformed/invalid request decoded inside a worker (maps to
    INVALID_ARGUMENT; the challenge WAS consumed, like in-process)."""


class _Categorized(Exception):
    """Worker-side error with a wire category the main side maps back
    to the exception classes above."""

    def __init__(self, category: str, message: str):
        super().__init__(message)
        self.category = category
        self.message = message


_ERROR_CLASSES = {
    "auth": HostAuthError,
    "invalid": HostInvalidRequest,
    "error": HostPipeError,
}


def _worker_main(conn) -> None:
    """Worker process body: a FIFO task loop over one duplex pipe.

    Imports stay inside the function (and jax-free — see module
    docstring): the session channel layer picks its crypto backend
    per-process, the signature scheme loads the cached native .so."""
    from ..session import get_signature_scheme
    from ..session.chacha import ChallengeRng
    from ..session.channel import SecureChannel
    from ..testing.reference import HardProtocolError
    from ..wire.records import QueryRequest
    from ..wire.validate import validate_request

    sessions: dict[bytes, tuple] = {}
    schemes: dict[str, object] = {}
    while True:
        try:
            tid, kind, payload = conn.recv()
        except (EOFError, OSError):
            return
        try:
            if kind == "open":
                cid, ciphertext, aad = payload
                sess = sessions.get(cid)
                if sess is None:
                    raise _Categorized("auth", "unknown channel on worker")
                channel, rng = sess
                try:
                    plaintext = channel.decrypt(ciphertext, aad=aad)
                except Exception:
                    # recv counter did not advance (SecureChannel raises
                    # before incrementing) — same injection-DoS immunity
                    # as the in-process path
                    raise _Categorized("auth", "decryption failed") from None
                challenge = rng.next_challenge()
                try:
                    req = QueryRequest.unpack(plaintext)
                    validate_request(req)
                except (ValueError, HardProtocolError) as exc:
                    raise _Categorized("invalid", str(exc)) from None
                result = (req, challenge)
            elif kind == "seal":
                cid, plaintext = payload
                sess = sessions.get(cid)
                if sess is None:
                    raise _Categorized("auth", "unknown channel on worker")
                result = sess[0].encrypt(plaintext)
            elif kind == "attach":
                cid, send_key, recv_key, send_n, recv_n, seed = payload
                channel = SecureChannel(send_key, recv_key)
                channel._send_n = send_n
                channel._recv_n = recv_n
                sessions[cid] = (channel, ChallengeRng(seed))
                result = len(sessions)
            elif kind == "detach":
                sessions.pop(payload, None)
                result = len(sessions)
            elif kind == "verify":
                scheme_name, items = payload
                mod = schemes.get(scheme_name)
                if mod is None:
                    mod = schemes[scheme_name] = get_signature_scheme(
                        scheme_name
                    )
                result = bool(mod.batch_verify(items))
            elif kind == "ping":
                result = os.getpid()
            elif kind == "exit":
                conn.send((tid, True, None))
                return
            else:
                raise _Categorized("error", f"unknown task kind {kind!r}")
            conn.send((tid, True, result))
        except _Categorized as exc:
            conn.send((tid, False, (exc.category, exc.message)))
        except Exception as exc:  # never let one bad task kill the loop
            conn.send((tid, False, ("error", f"{type(exc).__name__}: {exc}")))


class _WorkerSlot:
    """Main-side bookkeeping for one worker process."""

    __slots__ = (
        "index", "process", "conn", "send_lock", "futures", "futures_lock",
        "epoch", "alive", "reader",
    )

    def __init__(self, index: int):
        self.index = index
        self.process = None
        self.conn = None
        self.send_lock = threading.Lock()
        self.futures: dict[int, Future] = {}
        self.futures_lock = threading.Lock()
        self.epoch = 0
        self.alive = False
        self.reader = None


def _mp_context():
    # forkserver: workers fork from a clean helper process — no jax, no
    # grpc threads, no re-import of heavy parents per worker. spawn is
    # the portable fallback (each worker boots a fresh interpreter).
    try:
        return multiprocessing.get_context("forkserver")
    except ValueError:  # pragma: no cover - non-POSIX fallback
        return multiprocessing.get_context("spawn")


class HostPipeline:
    """The worker pool: sticky session routing + task fan-out.

    ``registry`` (an obs.TelemetryRegistry) is optional; when given, the
    ``grapevine_host_*`` families register there. ``on_crash`` listeners
    receive the dead worker's index *before* any respawn — the session
    owner must drop sessions stuck to that worker (their cipher states
    died with the process)."""

    def __init__(
        self,
        workers: int,
        *,
        scheme: str = "schnorrkel",
        restart_on_crash: bool = False,
        registry=None,
        timeout_s: float = DEFAULT_TIMEOUT_S,
    ):
        if workers < 1:
            raise ValueError(f"host pipeline needs >= 1 worker, got {workers}")
        self.workers = int(workers)
        self.scheme_name = scheme
        self.restart_on_crash = restart_on_crash
        self.timeout_s = timeout_s
        self._ctx = _mp_context()
        self._task_seq = 0
        self._seq_lock = threading.Lock()
        self._closing = False
        self._crash_listeners: list = []
        self.crash_count = 0
        self._g_workers = self._g_alive = self._g_inflight = None
        self._c_tasks = self._c_crash = None
        if registry is not None:
            widx = tuple(str(i) for i in range(self.workers))
            self._g_workers = registry.gauge(
                "grapevine_host_workers",
                "configured hostpipe worker-pool size",
            )
            self._g_alive = registry.gauge(
                "grapevine_host_workers_alive",
                "hostpipe workers currently alive",
            )
            self._g_inflight = registry.gauge(
                "grapevine_host_inflight_tasks",
                "hostpipe tasks submitted and not yet settled",
            )
            self._c_tasks = registry.counter(
                "grapevine_host_tasks_total",
                "hostpipe tasks by kind and worker index",
                labels={"phase": TASK_KINDS, "worker": widx},
            )
            self._c_crash = registry.counter(
                "grapevine_host_worker_crash_total",
                "hostpipe worker processes that died unexpectedly",
                labels={"worker": widx},
            )
            self._g_workers.set(self.workers)
        self._slots = [_WorkerSlot(i) for i in range(self.workers)]
        for slot in self._slots:
            self._start_worker(slot)
        self._set_alive_gauge()

    # -- lifecycle -------------------------------------------------------

    def _start_worker(self, slot: _WorkerSlot) -> None:
        parent_conn, child_conn = self._ctx.Pipe(duplex=True)
        proc = self._ctx.Process(
            target=_worker_main,
            args=(child_conn,),
            name=f"grapevine-hostpipe-{slot.index}",
            daemon=True,
        )
        proc.start()
        child_conn.close()
        slot.process = proc
        slot.conn = parent_conn
        slot.alive = True
        slot.reader = threading.Thread(
            target=self._read_loop,
            args=(slot, parent_conn),
            name=f"hostpipe-reader-{slot.index}",
            daemon=True,
        )
        slot.reader.start()

    def _read_loop(self, slot: _WorkerSlot, conn) -> None:
        while True:
            try:
                tid, ok, result = conn.recv()
            except (EOFError, OSError):
                break
            except TypeError:
                # close() nulled the handle mid-recv (teardown race)
                break
            with slot.futures_lock:
                fut = slot.futures.pop(tid, None)
            if self._g_inflight is not None:
                self._g_inflight.inc(-1)
            if fut is None:
                continue
            if ok:
                fut.set_result(result)
            else:
                category, message = result
                cls = _ERROR_CLASSES.get(category, HostPipeError)
                fut.set_exception(cls(message))
        self._on_worker_exit(slot, conn)

    def _on_worker_exit(self, slot: _WorkerSlot, conn) -> None:
        if self._closing:
            return
        slot.alive = False
        slot.epoch += 1
        self.crash_count += 1
        with slot.futures_lock:
            orphans = list(slot.futures.values())
            slot.futures.clear()
        for fut in orphans:
            fut.set_exception(
                HostWorkerCrash(f"hostpipe worker {slot.index} died")
            )
        if self._g_inflight is not None and orphans:
            self._g_inflight.inc(-len(orphans))
        if self._c_crash is not None:
            self._c_crash.inc(worker=str(slot.index))
        log.warning(
            "hostpipe worker %d died (%d in-flight tasks failed)%s",
            slot.index, len(orphans),
            "; restarting" if self.restart_on_crash else "",
        )
        # listeners first: sessions stuck to this worker must be dropped
        # before a respawned worker could be handed new ones
        for listener in list(self._crash_listeners):
            try:
                listener(slot.index)
            except Exception:  # pragma: no cover - listener bug
                log.exception("hostpipe crash listener failed")
        if self.restart_on_crash:
            try:
                self._start_worker(slot)
            except Exception:  # pragma: no cover - spawn failure
                log.exception("hostpipe worker %d respawn failed", slot.index)
        self._set_alive_gauge()

    def _set_alive_gauge(self) -> None:
        if self._g_alive is not None:
            self._g_alive.set(self.alive_count())

    def close(self) -> None:
        self._closing = True
        for slot in self._slots:
            if slot.process is None:
                continue
            try:
                with slot.send_lock:
                    slot.conn.send((-1, "exit", None))
            except (OSError, ValueError):
                pass
        for slot in self._slots:
            if slot.process is None:
                continue
            slot.process.join(timeout=2.0)
            if slot.process.is_alive():  # pragma: no cover - wedged worker
                slot.process.kill()
                slot.process.join(timeout=2.0)
            try:
                slot.conn.close()
            except OSError:  # pragma: no cover
                pass
            slot.alive = False

    # -- introspection ---------------------------------------------------

    def alive_count(self) -> int:
        return sum(
            1
            for s in self._slots
            if s.alive and s.process is not None and s.process.is_alive()
        )

    def alive(self) -> bool:
        """Every configured worker is serving (healthz contract: a
        degraded pool without restart_on_crash must flip unhealthy, the
        same stance as the batch collector's worker_alive)."""
        return self.alive_count() == self.workers

    def on_crash(self, listener) -> None:
        self._crash_listeners.append(listener)

    def worker_for(self, channel_id: bytes) -> int:
        """The public sticky-routing function (stable across restarts)."""
        digest = hashlib.sha256(channel_id).digest()
        return int.from_bytes(digest[:8], "big") % self.workers

    def epoch_of(self, index: int) -> int:
        return self._slots[index].epoch

    # -- task submission -------------------------------------------------

    def _route(self, sticky: bytes | None) -> _WorkerSlot:
        if sticky is not None:
            return self._slots[self.worker_for(sticky)]
        live = [s for s in self._slots if s.alive]
        if not live:
            raise HostWorkerCrash("no live hostpipe workers")
        return min(live, key=lambda s: len(s.futures))

    def submit(self, kind: str, payload, *, sticky: bytes | None = None) -> Future:
        if self._closing:
            raise HostPipeError("host pipeline is closed")
        slot = self._route(sticky)
        if not slot.alive:
            raise HostWorkerCrash(
                f"hostpipe worker {slot.index} is dead (sticky session lost)"
            )
        with self._seq_lock:
            self._task_seq += 1
            tid = self._task_seq
        fut: Future = Future()
        with slot.futures_lock:
            slot.futures[tid] = fut
        try:
            with slot.send_lock:
                slot.conn.send((tid, kind, payload))
        except (OSError, ValueError):
            with slot.futures_lock:
                slot.futures.pop(tid, None)
            raise HostWorkerCrash(
                f"hostpipe worker {slot.index} pipe is closed"
            ) from None
        if self._g_inflight is not None:
            self._g_inflight.inc(1)
        if self._c_tasks is not None:
            self._c_tasks.inc(phase=kind, worker=str(slot.index))
        return fut

    def call(self, kind: str, payload, *, sticky: bytes | None = None,
             timeout: float | None = None):
        fut = self.submit(kind, payload, sticky=sticky)
        try:
            return fut.result(
                timeout=self.timeout_s if timeout is None else timeout
            )
        except _FutureTimeout:
            # a wedged worker is indistinguishable from a dead one for
            # this caller; surface the pool's own error type so the
            # status-code mapping in service.py stays exhaustive
            raise HostPipeError(
                f"hostpipe {kind} task timed out after "
                f"{self.timeout_s if timeout is None else timeout:.1f}s"
            ) from None

    # -- session-shaped conveniences (GrapevineServer's surface) ---------

    def attach_session(self, channel_id: bytes, secure_channel,
                       challenge_seed: bytes) -> tuple[int, int]:
        """Hand a freshly authenticated session to its sticky worker;
        returns (worker_index, worker_epoch) for crash invalidation."""
        send_key, recv_key, send_n, recv_n = secure_channel.export_keys()
        index = self.worker_for(channel_id)
        self.call(
            "attach",
            (channel_id, send_key, recv_key, send_n, recv_n, challenge_seed),
            sticky=channel_id,
        )
        return index, self._slots[index].epoch

    def detach_session(self, channel_id: bytes) -> None:
        try:
            self.submit("detach", channel_id, sticky=channel_id)
        except HostPipeError:
            pass  # dead worker already forgot it

    def open_request(self, channel_id: bytes, ciphertext: bytes, aad: bytes):
        """Decrypt + challenge-draw + unpack + validate on the sticky
        worker; returns (QueryRequest, challenge)."""
        return self.call(
            "open", (channel_id, ciphertext, aad), sticky=channel_id
        )

    def seal_response(self, channel_id: bytes, plaintext: bytes) -> bytes:
        return self.call("seal", (channel_id, plaintext), sticky=channel_id)

    def verify_parallel(self, items, chunks: int | None = None) -> bool:
        """Fan a batch-verify across the pool; True iff every chunk
        verifies (the scheduler bisects inline on False — failure is the
        attacker-funded path, parallelism optimizes the honest one)."""
        if not items:
            return True
        n = min(chunks or self.workers, len(items))
        if n <= 1:
            return bool(self.call("verify", (self.scheme_name, list(items))))
        step = (len(items) + n - 1) // n
        futs = [
            self.submit("verify", (self.scheme_name, items[i : i + step]))
            for i in range(0, len(items), step)
        ]
        ok = True
        for fut in futs:
            try:
                ok = bool(fut.result(timeout=self.timeout_s)) and ok
            except _FutureTimeout:
                raise HostPipeError(
                    "hostpipe verify task timed out"
                ) from None
        return ok
