"""Host runtime: gRPC frontend, request scheduler, client library, CLI.

The analog of the reference's ``grapevine-server`` binary + ``uri`` crate
(reference README.md:122-128, uri/src/lib.rs; SURVEY.md §1 layers 1,6,7).
"""

from .uri import GrapevineUri  # noqa: F401
from .service import GrapevineServer  # noqa: F401
from .client import GrapevineClient  # noqa: F401
