"""Host runtime: gRPC frontend, request scheduler, client library, CLI.

The analog of the reference's ``grapevine-server`` binary + ``uri`` crate
(reference README.md:122-128, uri/src/lib.rs; SURVEY.md §1 layers 1,6,7).

``GrapevineServer`` is imported lazily: the client library and URI
parsing must stay importable without pulling in the engine (and with it
jax + a device backend) — a client process never needs a device.
"""

from .uri import GrapevineUri, SERVICE_NAME  # noqa: F401

__all__ = ["GrapevineUri", "SERVICE_NAME", "GrapevineClient", "GrapevineServer"]


def __getattr__(name):
    # GrapevineServer stays lazy so client processes never pull in the
    # engine (jax + a device backend); GrapevineClient stays lazy so the
    # scheduler/metrics path never pays the session/grpc import
    if name == "GrapevineServer":
        from .service import GrapevineServer

        return GrapevineServer
    if name == "GrapevineClient":
        from .client import GrapevineClient

        return GrapevineClient
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
