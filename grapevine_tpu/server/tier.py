"""Split frontend/engine serving tier — the horizontal host-path story.

One CPython process is GIL-bound at ~10k ops/s of session crypto +
codec work (PERF.md host table), while the device engine targets
~10-100× that. The reference never faced this split (its frontend was
C-core gRPC + Rust); here it is explicit: N **frontend** processes
terminate client sessions (IX handshake, channel AEAD, challenge
lockstep, request unpack + validation) and forward validated ops to ONE
**engine** process, which batch-verifies sr25519 signatures ACROSS
frontends (one Pippenger MSM per round — better batching than any
frontend could do alone) and runs the oblivious rounds on the device.

Trust model: frontends are deployment-internal (same boundary as the
reference's untrusted host runtime). The engine accepts pre-decrypted
requests only from them — bind the engine listener to localhost or a
private network; client-facing confidentiality still ends at the
frontends' AEAD channels. The signature check stays in the ENGINE, so a
compromised frontend cannot forge ops for identities it has never seen
sign (it can only replay what the session layer already allows — same
as the reference's host).

Wire (internal, raw-bytes gRPC like the public API):
    /grapevine.EngineAPI/Submit
    request  = packed QueryRequest (wire codec, constant size)
               ‖ challenge (32 B) — the auth identity and signature
               already travel inside the packed request
    response = packed QueryResponse, or gRPC UNAUTHENTICATED /
               INVALID_ARGUMENT mirroring the public service.

The public-facing frontend behaves byte-identically to the monolithic
``GrapevineServer`` (same Auth/Query surface), so clients need no
changes and a load balancer can spread them across frontends.
"""

from __future__ import annotations

import logging
import threading
from concurrent import futures

import grpc

from ..config import GrapevineConfig
from ..engine.batcher import validate_request
from ..testing.reference import HardProtocolError
from ..wire import constants as C
from ..wire.records import QueryRequest, QueryResponse
from .scheduler import AuthFailure, SchedulerShutdown

log = logging.getLogger("grapevine_tpu.tier")

ENGINE_SERVICE_NAME = "grapevine.EngineAPI"


class EngineServer:
    """The engine tier: one device engine + cross-frontend batching.

    Exposes ``Submit`` (one validated op per RPC). Concurrent RPCs from
    many frontends land in the shared BatchScheduler, which fills
    device rounds and batch-verifies each round's signatures with one
    MSM — exactly the path the monolithic server uses, so every
    scheduler/engine test covers this tier too.
    """

    def __init__(self, config: GrapevineConfig | None = None, seed: int = 0,
                 max_wait_ms: float | None = None, clock=None, leakmon=None,
                 durability=None, worker_restart: bool = False,
                 trace_ring_size: int = 512, slo=None,
                 profile_enable: bool = False, engine=None,
                 replicate_to: str | None = None, ship_every: int = 1,
                 host_workers: int = 0, adaptive_batch: bool = False,
                 flush_window_ms: float | None = None):
        from ..engine.batcher import GrapevineEngine
        from ..session import get_signature_scheme
        from .scheduler import BatchScheduler

        import time as _time

        self.config = (engine.config if engine is not None
                       else config or GrapevineConfig())
        # durable construction runs recovery before the listener binds;
        # ``engine`` injection lets a promoted StandbyReplica serve its
        # already-warm state in-process — no second recovery, so the
        # "serving inside one checkpoint interval" RTO claim holds
        self.engine = engine or GrapevineEngine(
            self.config, seed=seed, durability=durability
        )
        #: primary-side journal shipping (engine/replication.py) — the
        #: engine tier owns the journal, so it owns the feed
        self.shipper = None
        if replicate_to is not None:
            from ..engine.replication import JournalShipper

            self.shipper = JournalShipper(
                self.engine, replicate_to, ship_every=ship_every
            )
            self.shipper.start()
        #: continuous obliviousness auditing (obs/leakmon.py) — the
        #: engine tier owns the device, so it owns the transcript audit
        self.leakmon = None
        if leakmon is not None:
            from ..obs.leakmon import EngineLeakMonitor

            self.leakmon = EngineLeakMonitor.for_engine(self.engine, leakmon)
            self.engine.attach_leakmon(self.leakmon)
            if self.shipper is not None:
                # ship-cadence detector: the audit verdict folds the
                # shipper's frame-length books (leakmon.py rationale)
                self.leakmon.attach_shipper(self.shipper)
        #: round tracing + commit-latency SLO + optional capture gate —
        #: one shared attach policy (obs.attach_round_observability has
        #: the rationale and the observe-only default contract)
        from ..obs import attach_round_observability

        self.tracer, self.slo, self.profiler = attach_round_observability(
            self.engine, self.engine.metrics.registry,
            trace_ring_size=trace_ring_size, slo=slo,
            profile_enable=profile_enable,
        )
        kwargs = {} if max_wait_ms is None else {"max_wait_ms": max_wait_ms}
        self.scheduler = BatchScheduler(
            self.engine,
            clock=clock,
            scheme=get_signature_scheme(self.config.signature_scheme),
            restart_on_crash=worker_restart,
            flush_window_ms=flush_window_ms,
            **kwargs,
        )
        if adaptive_batch:
            # SLO-adaptive window sizing (server/adaptive.py): planted
            # after observability attaches so the policy reads the same
            # public arrival EWMA and burn rates /metrics exports
            from .adaptive import AdaptiveBatchPolicy

            self.scheduler.adaptive = AdaptiveBatchPolicy(
                self.engine.ecfg.batch_size,
                self.scheduler.max_wait,
                self.scheduler.idle_gap,
                workload=self.engine.workload,
                slo=self.slo,
                registry=self.engine.metrics.registry,
            )
        #: optional verify fan-out pool: the engine tier holds no
        #: sessions, so its hostpipe does nothing but split the round's
        #: batch-verify MSM across worker processes (scheduler.py)
        self.hostpipe = None
        if host_workers:
            from .hostpipe import HostPipeline

            self.hostpipe = HostPipeline(
                host_workers,
                scheme=self.config.signature_scheme,
                restart_on_crash=worker_restart,
                registry=self.engine.metrics.registry,
            )
            self.scheduler.hostpipe = self.hostpipe
        self._grpc_server: grpc.Server | None = None
        self.clock = clock or (lambda: int(_time.time()))
        self._expiry_stop = threading.Event()
        self._expiry_thread: threading.Thread | None = None
        self._metrics_server = None

    def _submit(self, request_bytes: bytes, context: grpc.ServicerContext) -> bytes:
        if len(request_bytes) != C.QUERY_REQUEST_WIRE_SIZE + C.CHALLENGE_SIZE:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, "bad submit size")
        challenge = request_bytes[C.QUERY_REQUEST_WIRE_SIZE:]
        try:
            req = QueryRequest.unpack(request_bytes[: C.QUERY_REQUEST_WIRE_SIZE])
            validate_request(req)
        except (ValueError, HardProtocolError) as exc:
            # same exception scope as the public service's fail-fast —
            # anything else is an engine bug and must crash loudly, not
            # masquerade as malformed client traffic
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(exc))
        try:
            resp: QueryResponse = self.scheduler.submit(
                req,
                auth=(
                    req.auth_identity,
                    C.GRAPEVINE_CHALLENGE_SIGNING_CONTEXT,
                    challenge,
                    req.auth_signature,
                ),
            )
        except AuthFailure:
            context.abort(grpc.StatusCode.UNAUTHENTICATED,
                          "bad challenge signature")
        except SchedulerShutdown as exc:
            # drain settle: UNAVAILABLE is what the frontend stub's
            # bounded retry keys on (and never auth/protocol errors)
            context.abort(grpc.StatusCode.UNAVAILABLE, str(exc))
        return resp.pack()

    def start(self, address: str = "127.0.0.1:0") -> int:
        """Bind the internal listener (plain host:port — deployment-
        internal; keep it on localhost or a private interface)."""
        identity = lambda b: b  # noqa: E731
        handler = grpc.method_handlers_generic_handler(
            ENGINE_SERVICE_NAME,
            {"Submit": grpc.unary_unary_rpc_method_handler(
                self._submit, request_deserializer=identity,
                response_serializer=identity)},
        )
        self._grpc_server = grpc.server(
            futures.ThreadPoolExecutor(
                max_workers=max(8, 2 * self.config.batch_size))
        )
        self._grpc_server.add_generic_rpc_handlers((handler,))
        port = self._grpc_server.add_insecure_port(address)
        if port == 0:
            raise RuntimeError(f"failed to bind engine listener {address}")
        self._grpc_server.start()
        if self.config.expiry_period > 0:
            # the engine tier owns the device, so it owns the sweep —
            # the same loop the monolithic server runs (service.py)
            from .service import run_expiry_loop

            self._expiry_thread = threading.Thread(
                target=run_expiry_loop,
                args=(self.engine, self.config, self._expiry_stop, self.clock),
                daemon=True,
            )
            self._expiry_thread.start()
        log.info("engine tier serving on %s", address)
        return port

    def health(self) -> dict:
        return self.engine.health()

    def healthz(self, stall_threshold: float = 30.0) -> tuple[bool, dict]:
        """Engine-tier liveness: collector thread up, oldest queued op
        not waiting past the threshold (same semantics as the monolithic
        server's healthz, server/service.py)."""
        alive = self.scheduler.worker_alive()
        stall = self.scheduler.stall_age()
        age = self.engine.metrics.last_round_age()
        healthy = alive and stall < stall_threshold
        detail = {
            # role tag: the fleet aggregator (obs/fleet.py) folds member
            # healthz docs and needs to tell tiers apart by body alone
            "role": "engine",
            "worker_alive": alive,
            "stall_age_s": round(stall, 3),
            "last_round_age_s": None if age is None else round(age, 3),
        }
        if self.engine.durability is not None:
            detail["durability"] = self.engine.durability.status()
        if self.hostpipe is not None:
            # degraded verify pool: the scheduler degrades to in-process
            # verification (still correct), but the capacity loss should
            # page — same stance as the monolithic server's fold
            detail["host_workers_alive"] = self.hostpipe.alive_count()
            detail["host_workers"] = self.hostpipe.workers
            healthy = healthy and self.hostpipe.alive()
        if self.shipper is not None:
            detail["replication"] = self.shipper.stats()
            # a fatally-fenced shipper means a standby promoted out from
            # under us — this primary must stop serving (split-brain)
            healthy = healthy and self.shipper.fatal is None
        if self.leakmon is not None:
            # same folding as the monolithic server: a SUSPECT transcript
            # is a serving fault — 503 stops routing (cached verdict; the
            # probe path never pays detector math)
            v = self.leakmon.last_verdict()
            detail["leakaudit"] = v["verdict"]
            healthy = healthy and v["verdict"] == "PASS"
        # commit-latency SLO burn-rate verdict (obs/slo.py): breached =
        # stop routing, same as the monolithic server (OPERATIONS.md §12)
        sv = self.slo.verdict()
        detail["slo"] = sv
        healthy = healthy and sv["ok"]
        return healthy, detail

    def start_metrics(self, port: int, host: str = "127.0.0.1",
                      stall_threshold: float = 30.0) -> int:
        """Serve /metrics + /healthz for the engine tier; returns the
        bound port. The engine tier owns the device, so it owns the
        batch/round/stash telemetry — frontends export only their own
        session-layer registry."""
        from ..obs import MetricsServer

        try:  # populate the "sort" phase split before the first scrape
            self.engine.calibrate_sort_phase()
        except Exception:  # best-effort: metrics must still bind
            pass
        try:  # and the "posmap" position-resolution split (PR 7)
            self.engine.calibrate_posmap_phase()
        except Exception:
            pass
        lm = self.leakmon
        self._metrics_server = MetricsServer(
            self.engine.metrics.registry,
            health=lambda: self.healthz(stall_threshold),
            refresh=self.engine.sample_stash,
            host=host,
            port=port,
            leakaudit=lm.verdict if lm is not None else None,
            flightrec=lm.recorder.dump if lm is not None else None,
            trace=self.tracer.chrome_trace,
            profile=(self.profiler.capture if self.profiler is not None
                     else None),
        )
        return self._metrics_server.start()

    def stop(self, grace: float = 1.0, checkpoint: bool = False):
        """Drain the engine tier; with ``checkpoint`` seal the final
        state after the scheduler settles (the SIGTERM path)."""
        self._expiry_stop.set()
        if self._metrics_server is not None:
            self._metrics_server.stop()
            self._metrics_server = None
        if self._grpc_server is not None:
            self._grpc_server.stop(grace).wait()
        if self.shipper is not None:
            self.shipper.close()
        self.scheduler.close()
        if self.hostpipe is not None:
            self.hostpipe.close()
        if self.leakmon is not None:
            self.leakmon.close()
        if checkpoint:
            self.engine.checkpoint_now()
        self.engine.close()


class _EngineStub:
    """Scheduler-shaped adapter over the engine tier's Submit RPC, so
    the frontend can reuse GrapevineServer._query verbatim.

    Every RPC carries a deadline (a wedged engine must fail the client's
    call, not hang the frontend handler thread forever), and UNAVAILABLE
    — the engine restarting, draining, or unreachable — is retried a
    bounded number of times with jittered exponential backoff. Nothing
    else is retried: UNAUTHENTICATED / INVALID_ARGUMENT are deliberate
    rejections (retrying them re-spends a challenge), and
    DEADLINE_EXCEEDED is ambiguous — the op may have committed, and
    Submit is not idempotent."""

    def __init__(self, address: str, deadline_s: float = 30.0,
                 max_retries: int = 3, backoff_s: float = 0.05,
                 backoff_cap_s: float = 2.0):
        self._grpc = grpc.insecure_channel(address)
        identity = lambda b: b  # noqa: E731
        self._submit = self._grpc.unary_unary(
            f"/{ENGINE_SERVICE_NAME}/Submit",
            request_serializer=identity, response_deserializer=identity,
        )
        self.deadline_s = deadline_s
        self.max_retries = max_retries
        self.backoff_s = backoff_s
        self.backoff_cap_s = backoff_cap_s
        self._c_retries = None

    def bind_registry(self, registry) -> None:
        """Register the retry counter on the frontend's telemetry
        registry (counts only — batch-level by construction)."""
        self._c_retries = registry.counter(
            "grapevine_engine_rpc_retries_total",
            "engine-tier Submit RPCs retried after UNAVAILABLE",
        )

    def submit(self, req: QueryRequest, auth=None) -> QueryResponse:
        import random
        import time as _time

        challenge = auth[2] if auth else b"\x00" * C.CHALLENGE_SIZE
        payload = req.pack() + challenge
        attempt = 0
        while True:
            try:
                data = self._submit(payload, timeout=self.deadline_s)
            except grpc.RpcError as e:
                if e.code() == grpc.StatusCode.UNAUTHENTICATED:
                    raise AuthFailure(str(e.details())) from None
                if (
                    e.code() != grpc.StatusCode.UNAVAILABLE
                    or attempt >= self.max_retries
                ):
                    raise
                attempt += 1
                if self._c_retries is not None:
                    self._c_retries.inc()
                delay = min(
                    self.backoff_cap_s,
                    self.backoff_s * (2 ** (attempt - 1)),
                ) * random.uniform(0.5, 1.5)
                log.warning(
                    "engine Submit UNAVAILABLE (%s); retry %d/%d in %.0f ms",
                    e.details(), attempt, self.max_retries, delay * 1e3,
                )
                _time.sleep(delay)
                continue
            return QueryResponse.unpack(data)

    def close(self):
        self._grpc.close()


class FrontendServer:
    """A client-facing session-termination process.

    Byte-identical public surface to the monolithic ``GrapevineServer``
    (Auth + Query, IX handshake, AEAD, lockstep, validation) — but ops
    go to a shared engine tier instead of an in-process engine. Run N
    of these behind a load balancer; each is one CPython process of
    session crypto, and the engine batches across all of them.
    """

    def __init__(self, engine_address: str, config: GrapevineConfig | None = None,
                 attestation=None, clock=None, session_ttl: float = 3600.0,
                 max_sessions: int = 4096, identity=None,
                 host_workers: int = 0, worker_restart: bool = False):
        from .service import GrapevineServer

        # The monolithic server with its scheduler swapped for the
        # engine-tier RPC stub (GrapevineServer's injected-scheduler
        # mode): every session/auth behavior and its tests carry over
        # unchanged, and there is no device engine in this process.
        # ``host_workers`` is where the multiprocess verify/codec
        # pipeline pays off most: the frontend IS the host-crypto tier,
        # so its sessions fan out across worker processes while the
        # engine tier keeps the device.
        stub = _EngineStub(engine_address)
        self._inner = GrapevineServer(
            config=config,
            attestation=attestation,
            clock=clock,
            session_ttl=session_ttl,
            max_sessions=max_sessions,
            identity=identity,
            scheduler=stub,
            host_workers=host_workers,
            worker_restart=worker_restart,
        )
        stub.bind_registry(self._inner.metrics_registry)

    def start(self, listen_uri, tls_cert: bytes | None = None,
              tls_key: bytes | None = None) -> int:
        # expiry sweeps run in the ENGINE process; never start one here
        # (GrapevineServer.start already skips them when engine is None)
        return self._inner.start(listen_uri, tls_cert, tls_key)

    @property
    def identity(self):
        return self._inner.identity

    def health(self) -> dict:
        return self._inner.health()

    def start_metrics(self, port: int, host: str = "127.0.0.1",
                      stall_threshold: float = 30.0) -> int:
        # the frontend's registry carries session-layer telemetry only;
        # round/stash metrics live on the engine tier's endpoint
        return self._inner.start_metrics(port, host, stall_threshold)

    def wait(self):
        self._inner.wait()

    def stop(self, grace: float = 1.0):
        self._inner.stop(grace)
