"""grapevine-tpu server CLI (the reference's ``./grapevine-server --help``,
README.md:126, with the expiry period as a flag, README.md:90)."""

from __future__ import annotations

import argparse
import logging
import os
import sys


def _pin_platform() -> None:
    """Honor JAX_PLATFORMS before any backend initializes.

    Site hooks may pin a platform via ``jax.config`` (overriding the env
    var), so an explicit request like ``JAX_PLATFORMS=cpu`` must be
    re-asserted through the config API."""
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        import jax

        jax.config.update("jax_platforms", want)


_pin_platform()

from ..config import GrapevineConfig  # noqa: E402


def build_parser() -> argparse.ArgumentParser:
    # allow_abbrev=False: role/flag validation detects explicitly-
    # supplied options by exact token match in argv, which abbreviated
    # option prefixes would dodge
    p = argparse.ArgumentParser(
        prog="grapevine-server",
        description="TPU-native oblivious message bus server",
        allow_abbrev=False,
    )
    p.add_argument(
        "--listen",
        default="insecure-grapevine://0.0.0.0:3229",
        help="listen URI: grapevine://host:port (TLS) or insecure-grapevine://host:port",
    )
    p.add_argument("--tls-cert", help="PEM certificate chain (required for grapevine://)")
    p.add_argument("--tls-key", help="PEM private key (required for grapevine://)")
    p.add_argument(
        "--expiry-period",
        type=int,
        default=0,
        help="seconds until messages expire; 0 disables the sweep",
    )
    p.add_argument("--msg-capacity", type=int, default=1 << 14, help="max in-flight messages")
    p.add_argument(
        "--recipient-capacity", type=int, default=1 << 12, help="max recipients with mail"
    )
    p.add_argument("--batch-size", type=int, default=8, help="ops per oblivious round")
    p.add_argument(
        "--batch-wait-ms",
        type=float,
        default=None,
        help="cap on the round-collection window (default: scheduler's "
        "quiescence policy, 8ms cap / 2ms idle gap)",
    )
    p.add_argument(
        "--posmap-impl",
        choices=["flat", "recursive"],
        default=None,
        help="position-map implementation (oram/posmap.py): 'flat' = "
        "the private in-memory table (default via auto), 'recursive' = "
        "a one-level recursive position ORAM — ~sqrt(capacity)× less "
        "resident position memory for ~2× round path traffic, the "
        "knob that takes one replica past 2^24 records (sizing table: "
        "OPERATIONS.md §13). Responses are bit-identical either way. "
        "Device-owning roles only — the frontend never touches a "
        "position map",
    )
    p.add_argument(
        "--tree-top-cache-levels",
        type=int,
        default=None,
        help="tree-top cache depth k for every Path-ORAM bucket tree "
        "(oram/path_oram.py): the top k levels (2^k-1 buckets, on "
        "EVERY path) live decrypted-resident instead of in the "
        "encrypted HBM tree, cutting per-access path HBM traffic and "
        "cipher work to the bottom height+1-k levels. "
        "Access-pattern-neutral (the cached levels are touched by "
        "every access; CI-audited) and bit-identical at every k. "
        "0 = off; unset = auto per backend (OPERATIONS.md §14 sizing "
        "+ flip guidance). Device-owning roles only — the frontend "
        "never touches a tree",
    )
    p.add_argument(
        "--pipeline-depth",
        type=int,
        choices=[1, 2],
        default=None,
        help="round-pipeline depth (engine/batcher.py): max dispatched-"
        "but-unresolved engine rounds in flight. 2 = while round k "
        "executes on the device, round k+1 is assembled, verified, and "
        "its journal frame fsynced — steady-state cadence approaches "
        "max(host, fsync, device) and p99 commit latency stops paying "
        "the fsync; 1 = the serial program, bit for bit (responses and "
        "state are bit-identical either way, and replay order is "
        "journal order at every depth — OPERATIONS.md §16). Unset = "
        "auto: 2 on TPU backends, 1 elsewhere. Device-owning roles "
        "only — the frontend has no round pipeline",
    )
    p.add_argument(
        "--evict-every",
        type=int,
        default=None,
        help="delayed batched eviction cadence E (oram/round.py, "
        "OPERATIONS.md §19): fetched path contents accumulate in a "
        "bounded private buffer and the scatter+encrypt half of the "
        "round runs ONCE per E rounds over the window's deduplicated "
        "bucket union — the steady-state round is gather+decrypt+"
        "stash-update only. Responses and logical state are "
        "bit-identical at every E; the flush cadence is a pure round "
        "count, never buffer contents (CI-audited). 1 = per-round "
        "eviction, bit for bit; unset = auto (currently 1 — "
        "tools/tpu_capture.py evict_perf settles the on-chip flip). "
        "Device-owning roles only",
    )
    p.add_argument(
        "--evict-buffer-slots",
        type=int,
        default=None,
        help="eviction-buffer capacity override (rows per payload "
        "tree) under --evict-every > 1; unset = auto sizing "
        "(OPERATIONS.md §19 — min(blocks, 2·Z·window·fetches + "
        "slack)). Watch grapevine_evict_buffer_high_water before "
        "lowering it. Device-owning roles only",
    )
    p.add_argument(
        "--shards",
        type=int,
        default=1,
        help="bucket-tree shard count across the local device mesh "
        "(parallel/mesh.py, OPERATIONS.md §22): each of the first N JAX "
        "devices owns a contiguous heap range of both bucket trees; the "
        "round gathers over ICI and the delayed-eviction flush "
        "owner-masks its scatters per chip. Responses, transcripts, and "
        "logical state are bit-identical at every shard count, and "
        "journals/checkpoints replay across shard counts (the knob is "
        "outside the durability fingerprint, like --pipeline-depth). "
        "Power of two dividing both trees' padded bucket counts; "
        "requires N visible devices. 1 = single-chip (default). "
        "Device-owning roles only",
    )
    p.add_argument("--seed", type=int, default=0, help="engine RNG seed")
    p.add_argument(
        "--identity-seed",
        help="64 hex chars: derive a STABLE server static key (IX "
        "handshake) so clients can pin it across restarts; omitted = "
        "fresh identity per start. The public key is printed either way.",
    )
    p.add_argument(
        "--role",
        choices=["mono", "engine", "frontend", "fleet", "standby"],
        default="mono",
        help="mono = engine + sessions in one process (default); "
        "engine = device engine tier only (serves the internal Submit "
        "API on --engine-listen); frontend = client-facing session "
        "process forwarding validated ops to --engine (run N of these "
        "behind a load balancer — server/tier.py); fleet = scrape "
        "aggregator over N member processes' metrics endpoints, "
        "serving merged shard-labeled /metrics, /healthz, /leakaudit "
        "with cross-shard uniformity detectors (obs/fleet.py); "
        "standby = hot replica replaying a primary's shipped journal "
        "(engine/replication.py, OPERATIONS.md §23) — SIGUSR1 "
        "promotes it and it starts serving the Submit API on "
        "--engine-listen",
    )
    p.add_argument(
        "--fleet-members",
        help="(role=fleet) comma-separated member metrics endpoints as "
        "host:port; list POSITION is the shard index — the only member "
        "identity that ever reaches a metric label (obs/fleet.py)",
    )
    p.add_argument(
        "--fleet-scrape-interval",
        type=float,
        default=1.0,
        help="(role=fleet) seconds between scrape cycles. With the "
        "start instant this fixes the ENTIRE scrape schedule — a pure "
        "function of config, never of observed traffic "
        "(OPERATIONS.md §20)",
    )
    p.add_argument(
        "--fleet-port",
        type=int,
        default=0,
        help="(role=fleet) port for the merged fleet endpoints "
        "(0 = ephemeral); binds --metrics-host",
    )
    p.add_argument(
        "--engine-listen",
        default="127.0.0.1:0",
        help="(role=engine) internal host:port for the Submit API — "
        "keep it on localhost or a private interface",
    )
    p.add_argument(
        "--engine",
        help="(role=frontend) host:port of the engine tier's Submit API",
    )
    p.add_argument(
        "--replicate-to",
        help="(mono/engine, with --state-dir) host:port of a standby "
        "replica's --standby-listen endpoint: stream every sealed "
        "journal frame there at round cadence (engine/replication.py). "
        "Shipping traffic is a pure function of round count — the "
        "frames are the sealed constant-size journal records, so the "
        "leak monitor's cadence policing covers the wire verbatim "
        "(OPERATIONS.md §23)",
    )
    p.add_argument(
        "--ship-every",
        type=int,
        default=1,
        help="(with --replicate-to) journal frames per shipping wake "
        "(default 1 = every frame immediately). N>1 batches wakes; the "
        "standby still receives every frame, just up to N-1 frames "
        "later — a standby-RPO knob, not a durability knob",
    )
    p.add_argument(
        "--standby-listen",
        default="127.0.0.1:0",
        help="(role=standby) host:port to accept the primary's "
        "replication feed on (0 = ephemeral; keep it on localhost or "
        "a private interface — frames are sealed, but the cadence is "
        "operational telemetry)",
    )
    p.add_argument(
        "--promote-from",
        help="(role=standby) the primary's --state-dir path, reachable "
        "at promotion time (shared volume): promote() plants the "
        "split-brain fence there and drains the durable journal tail "
        "for RPO 0. Omitted = promote from shipped state only "
        "(accepting the shipping lag as RPO)",
    )
    p.add_argument(
        "--metrics-port",
        type=int,
        default=None,
        help="serve Prometheus /metrics and /healthz on this port "
        "(0 = ephemeral; default: off). Telemetry is batch-level only — "
        "the registry's leak audit guarantees nothing per-client or "
        "per-op is exported (OPERATIONS.md §8) — but keep the port on "
        "localhost or a private scrape network anyway",
    )
    p.add_argument(
        "--metrics-host",
        default="127.0.0.1",
        help="interface for the metrics endpoint (default: localhost "
        "only; point it at a private scrape interface explicitly — "
        "operational telemetry is nobody else's business)",
    )
    p.add_argument(
        "--leakmon",
        action="store_true",
        help="continuously audit the ORAM transcript for obliviousness "
        "leaks (obs/leakmon.py): sliding-window same-key collision / "
        "cross-round repeat / uniformity detectors, a /leakaudit verdict "
        "on the metrics endpoint, and the round flight recorder on "
        "/flightrec. Device-owning roles only (mono, engine) — a "
        "frontend never sees a transcript (OPERATIONS.md §10)",
    )
    p.add_argument(
        "--leakmon-window",
        type=int,
        default=256,
        help="leak monitor sliding window, in per-stream observations "
        "(default 256; larger = more statistical power, slower to "
        "flag AND to clear — OPERATIONS.md §10)",
    )
    p.add_argument(
        "--leakmon-uniformity-z",
        type=float,
        default=8.0,
        help="|z| threshold for the pooled-leaf uniformity detector "
        "(default 8.0; honest transcripts give |z| = O(1))",
    )
    p.add_argument(
        "--leakmon-collision-threshold",
        type=float,
        default=0.02,
        help="windowed same-key leaf collision rate above this is "
        "SUSPECT (default 0.02; honest rate is 1/leaves)",
    )
    p.add_argument(
        "--leakmon-repeat-threshold",
        type=float,
        default=0.05,
        help="windowed cross-round leaf repeat rate above this is "
        "SUSPECT (default 0.05; honest rate is 1/leaves)",
    )
    p.add_argument(
        "--leakmon-dump-path",
        help="file the flight recorder dumps to on a PASS→SUSPECT "
        "transition (default: no automatic dump; /flightrec always "
        "serves the ring on demand)",
    )
    p.add_argument(
        "--trace-ring-size",
        type=int,
        default=512,
        help="per-round span ledgers retained by the round tracer "
        "(obs/tracer.py): /trace serves them as Perfetto-loadable "
        "Chrome trace JSON and grapevine_round_bubble_ratio derives "
        "from them. Spans are phases, never operations — the PR-1/2 "
        "leak policy, enforced structurally. Device-owning roles only",
    )
    p.add_argument(
        "--slo-commit-p99-ms",
        type=float,
        default=None,
        help="end-to-end commit-latency SLO target in ms (enqueue → "
        "round settle, worst op per round). Multi-window burn rates "
        "over a 1%% error budget fold into /healthz: both windows "
        "burning = 503 = stop routing (OPERATIONS.md §12). Unset = "
        "observe-only: latencies, burn rates, and grapevine_slo_alert "
        "still export against a 250 ms reference target, but /healthz "
        "never gates on them — setting a target is the explicit "
        "operator decision to let a breach pull the replica from "
        "routing. Device-owning roles only — latency commits on the "
        "engine",
    )
    p.add_argument(
        "--profile-enable",
        action="store_true",
        help="expose /profile?ms=N on the metrics endpoint: a live "
        "jax.profiler capture of the serving process (one at a time, "
        "duration-clamped; obs/profiler.py). Off by default — a "
        "capture costs real overhead and writes device traces to "
        "disk. Device-owning roles only",
    )
    p.add_argument(
        "--state-dir",
        help="crash safety: directory for sealed checkpoints + the "
        "batch journal (engine/checkpoint.py). Every admitted batch is "
        "journaled before dispatch; restart = last checkpoint + replay. "
        "Default: off — state is volatile, exactly the pre-PR-4 "
        "behavior (OPERATIONS.md §11). Device-owning roles only",
    )
    p.add_argument(
        "--checkpoint-every-rounds",
        type=int,
        default=64,
        help="(with --state-dir) rounds+sweeps between sealed "
        "whole-state checkpoints — the RTO knob: recovery replays at "
        "most this many journal records (default 64)",
    )
    p.add_argument(
        "--journal-fsync-every",
        type=int,
        default=1,
        help="(with --state-dir) journal records per fsync. 1 (default) "
        "= every round is machine-crash-durable before it dispatches; "
        "N>1 amortizes the fsync, risking the last N-1 acknowledged "
        "rounds on power loss (process crashes lose nothing either way)",
    )
    p.add_argument(
        "--seal-key-file",
        help="(with --state-dir) 32-byte root seal key file (default: "
        "<state-dir>/root.key, auto-generated 0600). Mount a secret "
        "from outside the state volume in production — OPERATIONS.md "
        "§11 key management",
    )
    p.add_argument(
        "--worker-restart",
        action="store_true",
        help="supervised restart of the batch-collector thread after a "
        "crash (default: a dead collector flips /healthz unhealthy and "
        "stays dead for the orchestrator to replace the process). "
        "Either way the crash increments grapevine_worker_crash_total",
    )
    p.add_argument(
        "--host-workers",
        type=int,
        default=0,
        help="off-GIL host pipeline: N worker processes for session "
        "decrypt/encode/verify, sticky by channel id (server/hostpipe.py). "
        "0 (default) = the historical in-process path. Worker crash "
        "policy rides --worker-restart; either way /healthz folds the "
        "pool and crashes increment grapevine_host_worker_crash_total",
    )
    p.add_argument(
        "--adaptive-batch",
        action="store_true",
        help="SLO-adaptive round-collection window: size each round's "
        "wait from the arrival-rate EWMA, queue depth, and SLO burn "
        "rates — public load aggregates only, never queue contents "
        "(server/adaptive.py has the obliviousness argument). Default: "
        "the static --batch-wait-ms window",
    )
    p.add_argument(
        "--flush-window",
        dest="flush_window_ms",
        type=float,
        default=None,
        metavar="MS",
        help="flush-aware collection: when the delayed-eviction flush "
        "(--evict-every) occupies the device, stretch the overlapping "
        "collection window by MS milliseconds to harvest a fuller "
        "round. The flush cadence itself stays strictly every "
        "--evict-every rounds — this knob only retimes host-side "
        "collection, a pure function of the public round counter",
    )
    p.add_argument("-v", "--verbose", action="store_true")
    return p


#: which flags each role actually consumes — a flag explicitly supplied
#: outside its role's set is a misconfiguration, and silently dropping
#: it would hide exactly the kind of mistake (expecting TLS or a pinned
#: identity on the wrong listener) that must fail loudly
#: the leak monitor audits the device transcript, so only device-owning
#: roles take its flags — a frontend supplying --leakmon-* is exactly
#: the "expected monitoring that isn't happening" misconfiguration this
#: matrix exists to catch
_LEAKMON_FLAGS = {"leakmon", "leakmon_window", "leakmon_uniformity_z",
                  "leakmon_collision_threshold",
                  "leakmon_repeat_threshold", "leakmon_dump_path"}

#: durability owns device state, so only device-owning roles take it —
#: a frontend supplying --state-dir would silently checkpoint nothing
_DURABILITY_FLAGS = {"state_dir", "checkpoint_every_rounds",
                     "journal_fsync_every", "seal_key_file",
                     "worker_restart"}

#: round tracing, the commit-latency SLO, and live profiler capture all
#: observe the device round, so only device-owning roles take them — a
#: frontend supplying --slo-commit-p99-ms would silently measure nothing
_TRACE_SLO_FLAGS = {"trace_ring_size", "slo_commit_p99_ms",
                    "profile_enable"}

#: device-engine geometry/execution knobs: only roles that build an
#: engine take them — a frontend supplying --posmap-impl,
#: --tree-top-cache-levels, --pipeline-depth, or --evict-every would
#: silently configure nothing (its engine lives in another process)
_ENGINE_GEOM_FLAGS = {"posmap_impl", "tree_top_cache_levels",
                      "pipeline_depth", "evict_every",
                      "evict_buffer_slots", "shards"}

#: fleet-aggregator topology/cadence: only the fleet role scrapes —
#: any other role supplied --fleet-members would silently aggregate
#: nothing, and a fleet role supplied engine flags would silently
#: serve no engine
_FLEET_FLAGS = {"fleet_members", "fleet_scrape_interval", "fleet_port"}

#: journal shipping needs the journal in-process, so only roles that
#: own a durable engine take --replicate-to — a frontend supplying it
#: would silently replicate nothing (its journal lives in the engine
#: tier), exactly the misconfiguration that must fail loudly before an
#: operator believes they have a standby
_REPLICATION_FLAGS = {"replicate_to", "ship_every"}

#: the standby's own surface: its replication listener and the
#: primary state dir it fences at promotion
_STANDBY_FLAGS = {"standby_listen", "promote_from"}

#: the multiprocess host pipeline handles session decrypt/encode and
#: signature verify — any role that terminates sessions (mono,
#: frontend) or verifies rounds (engine) takes it; the fleet
#: aggregator and the pre-promotion standby touch neither
_HOSTPIPE_FLAGS = {"host_workers"}

#: adaptive/flush-aware collection shapes the device round window, so
#: only roles that own a BatchScheduler over an in-process engine take
#: them — a frontend supplying --adaptive-batch would silently shape
#: nothing (its rounds are collected in the engine tier)
_ADAPTIVE_FLAGS = {"adaptive_batch", "flush_window_ms"}

_ROLE_FLAGS = {
    "mono": {"listen", "tls_cert", "tls_key", "expiry_period",
             "msg_capacity", "recipient_capacity", "batch_size",
             "batch_wait_ms", "seed", "identity_seed", "verbose", "role",
             "metrics_port", "metrics_host"}
            | _LEAKMON_FLAGS | _DURABILITY_FLAGS | _TRACE_SLO_FLAGS
            | _ENGINE_GEOM_FLAGS | _REPLICATION_FLAGS
            | _HOSTPIPE_FLAGS | _ADAPTIVE_FLAGS,
    "engine": {"engine_listen", "expiry_period", "msg_capacity",
               "recipient_capacity", "batch_size", "batch_wait_ms",
               "seed", "verbose", "role", "metrics_port", "metrics_host"}
              | _LEAKMON_FLAGS | _DURABILITY_FLAGS | _TRACE_SLO_FLAGS
              | _ENGINE_GEOM_FLAGS | _REPLICATION_FLAGS
              | _HOSTPIPE_FLAGS | _ADAPTIVE_FLAGS,
    "frontend": {"engine", "listen", "tls_cert", "tls_key",
                 "batch_size", "identity_seed", "verbose", "role",
                 "metrics_port", "metrics_host", "worker_restart"}
                | _HOSTPIPE_FLAGS,
    # the fleet role owns no device, no listener, no sessions: it
    # scrapes declared members and serves the merged view — the only
    # non-fleet flag it takes is the bind interface
    "fleet": {"role", "verbose", "metrics_host"} | _FLEET_FLAGS,
    # the standby owns a durable device engine (it replays into one)
    # and, after promotion, serves the internal Submit API — so it
    # takes geometry + durability + the engine tier's listener, but no
    # client-facing session flags and no --replicate-to (it is the
    # replication *target*; chaining standbys is not supported)
    "standby": {"role", "verbose", "seed", "expiry_period",
                "msg_capacity", "recipient_capacity", "batch_size",
                "batch_wait_ms", "engine_listen", "metrics_port",
                "metrics_host"}
               | _STANDBY_FLAGS | _DURABILITY_FLAGS | _LEAKMON_FLAGS
               | _TRACE_SLO_FLAGS | _ENGINE_GEOM_FLAGS
               | _ADAPTIVE_FLAGS,
}


def _durability_config(args):
    """The DurabilityConfig for --state-dir, or None when off."""
    if not args.state_dir:
        return None
    from ..config import DurabilityConfig

    return DurabilityConfig(
        state_dir=args.state_dir,
        checkpoint_every_rounds=args.checkpoint_every_rounds,
        journal_fsync_every=args.journal_fsync_every,
        seal_key_file=args.seal_key_file,
    )


def _install_drain_handlers(drain):
    """SIGTERM/SIGINT → drain (settle queued ops, finish the in-flight
    round, seal a final checkpoint), then exit 0. Idempotent: a second
    signal while draining is ignored rather than re-entering stop()."""
    import signal
    import threading

    fired = threading.Event()

    def _handler(signum, frame):
        if fired.is_set():
            return
        fired.set()
        drain()
        raise SystemExit(0)

    signal.signal(signal.SIGTERM, _handler)
    signal.signal(signal.SIGINT, _handler)


def _slo_config(args):
    """The SloConfig for --slo-commit-p99-ms (always built for
    device-owning roles; the tracker itself is always on). No explicit
    target = observe-only: /healthz reports the burn rates but never
    gates on them, so upgrading a fleet whose honest latency exceeds
    the reference target cannot 503 every replica at once."""
    from ..obs.slo import SloConfig

    if args.slo_commit_p99_ms is None:
        return SloConfig(enforce=False)
    return SloConfig(commit_p99_ms=args.slo_commit_p99_ms)


def _leakmon_config(args):
    """The LeakMonitorConfig for --leakmon, or None when off."""
    if not args.leakmon:
        return None
    from ..obs.leakmon import LeakMonitorConfig

    return LeakMonitorConfig(
        window_rounds=args.leakmon_window,
        uniformity_z_threshold=args.leakmon_uniformity_z,
        collision_threshold=args.leakmon_collision_threshold,
        repeat_threshold=args.leakmon_repeat_threshold,
        dump_path=args.leakmon_dump_path,
    )


def _reject_misapplied_flags(parser, args, argv):
    allowed = _ROLE_FLAGS[args.role]
    # presence = the option token actually appears in argv (exact match
    # or --opt=value form; abbreviations are disabled on the parser), so
    # even a misapplied flag supplied WITH its default value fails loudly
    supplied = set()
    tokens = list(argv if argv is not None else sys.argv[1:])
    for action in parser._actions:
        for opt in action.option_strings:
            if any(t == opt or t.startswith(opt + "=") for t in tokens):
                supplied.add(action.dest)
    # every parser dest must be claimed by some role — catches a flag
    # added to build_parser but missed in the matrix at dev time
    dests = {a.dest for a in parser._actions if a.dest != "help"}
    unclaimed = dests - set().union(*_ROLE_FLAGS.values())
    if unclaimed:  # not assert: must survive python -O
        raise SystemExit(f"flags missing from _ROLE_FLAGS: {unclaimed}")
    bad = [
        f"--{dest.replace('_', '-')}"
        for dest in supplied
        if dest not in allowed
    ]
    if bad:
        raise SystemExit(
            f"--role {args.role} does not take {', '.join(sorted(bad))} "
            "(engine = internal plaintext Submit API only; frontend = "
            "client-facing sessions forwarding to --engine; see "
            "server/tier.py)"
        )


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    _reject_misapplied_flags(parser, args, argv)
    logging.basicConfig(level=logging.DEBUG if args.verbose else logging.INFO)
    config = GrapevineConfig(
        max_messages=args.msg_capacity,
        max_recipients=args.recipient_capacity,
        expiry_period=args.expiry_period,
        batch_size=args.batch_size,
        posmap_impl=args.posmap_impl,
        tree_top_cache_levels=args.tree_top_cache_levels,
        pipeline_depth=args.pipeline_depth,
        evict_every=args.evict_every,
        evict_buffer_slots=args.evict_buffer_slots,
        shards=args.shards,
    )
    identity = None
    if args.identity_seed:
        from ..session.channel import ServerIdentity

        try:
            identity = ServerIdentity.from_seed(bytes.fromhex(args.identity_seed))
        except ValueError as exc:
            raise SystemExit(
                f"--identity-seed must be 64 hex chars (32 bytes): {exc}"
            ) from None
    if args.role == "fleet":
        import threading

        from ..obs.fleet import FleetAggregator, FleetConfig

        if not args.fleet_members:
            raise SystemExit(
                "--role fleet requires --fleet-members host:port,..."
            )
        members = tuple(
            m.strip() for m in args.fleet_members.split(",") if m.strip()
        )
        agg = FleetAggregator(FleetConfig(
            members=members,
            scrape_interval_s=args.fleet_scrape_interval,
        ))
        fport = agg.serve(args.fleet_port, host=args.metrics_host)
        print(f"grapevine-tpu fleet aggregator on port {fport} "
              f"({len(members)} members)", flush=True)
        # the aggregator holds no engine state: drain = stop scraping
        # and close the endpoint
        _install_drain_handlers(agg.stop)
        try:
            threading.Event().wait()
        except KeyboardInterrupt:  # pragma: no cover - handler owns it
            agg.stop()
        return 0

    if args.role == "standby":
        import signal
        import threading

        from ..engine.replication import StandbyReplica

        dcfg = _durability_config(args)
        if dcfg is None:
            raise SystemExit(
                "--role standby requires --state-dir (the replica "
                "appends shipped frames to its own sealed journal)"
            )
        replica = StandbyReplica(config, seed=args.seed, durability=dcfg)
        host, _, port_s = args.standby_listen.rpartition(":")
        sport = replica.listen(host or "127.0.0.1", int(port_s or 0))
        print(f"grapevine-tpu standby replica on port {sport}",
              flush=True)
        if args.metrics_port is not None:
            mport = replica.start_metrics(args.metrics_port,
                                          host=args.metrics_host)
            print(f"metrics endpoint on port {mport}", flush=True)
        # SIGUSR1 = the operator's (or orchestrator's) promotion order;
        # the handler only sets an event — the takeover itself (fence,
        # tail drain, flush completion) runs on the main thread
        promote_wake = threading.Event()
        signal.signal(signal.SIGUSR1, lambda s, f: promote_wake.set())
        _install_drain_handlers(replica.close)
        try:
            promote_wake.wait()
        except KeyboardInterrupt:  # pragma: no cover - handler owns it
            replica.close()
            return 0
        info = replica.promote(primary_state_dir=args.promote_from)
        print(
            f"standby promoted: epoch {info['epoch']}, drained "
            f"{info['drained_frames']} durable frames, "
            f"rto {info['rto_seconds']:.3f}s", flush=True,
        )
        from .tier import EngineServer

        server = EngineServer(
            engine=replica.engine, max_wait_ms=args.batch_wait_ms,
            leakmon=_leakmon_config(args),
            worker_restart=args.worker_restart,
            trace_ring_size=args.trace_ring_size, slo=_slo_config(args),
            profile_enable=args.profile_enable,
            adaptive_batch=args.adaptive_batch,
            flush_window_ms=args.flush_window_ms,
        )
        eport = server.start(args.engine_listen)
        print(f"promoted engine tier listening on port {eport}",
              flush=True)
        _install_drain_handlers(lambda: server.stop(checkpoint=True))
        try:
            threading.Event().wait()
        except KeyboardInterrupt:  # pragma: no cover - handler owns it
            server.stop(checkpoint=True)
        return 0

    if args.role == "engine":
        import threading

        from .tier import EngineServer

        engine = EngineServer(config, seed=args.seed,
                              max_wait_ms=args.batch_wait_ms,
                              leakmon=_leakmon_config(args),
                              durability=_durability_config(args),
                              worker_restart=args.worker_restart,
                              trace_ring_size=args.trace_ring_size,
                              slo=_slo_config(args),
                              profile_enable=args.profile_enable,
                              replicate_to=args.replicate_to,
                              ship_every=args.ship_every,
                              host_workers=args.host_workers,
                              adaptive_batch=args.adaptive_batch,
                              flush_window_ms=args.flush_window_ms)
        port = engine.start(args.engine_listen)
        print(f"grapevine-tpu engine tier listening on port {port}",
              flush=True)
        if args.metrics_port is not None:
            mport = engine.start_metrics(args.metrics_port,
                                         host=args.metrics_host)
            print(f"metrics endpoint on port {mport}", flush=True)
        # drain-then-checkpoint on SIGTERM/SIGINT: queued ops settle
        # with UNAVAILABLE, the in-flight round commits, the final
        # state seals — restart loses nothing (OPERATIONS.md §11)
        _install_drain_handlers(lambda: engine.stop(checkpoint=True))
        try:
            threading.Event().wait()
        except KeyboardInterrupt:  # pragma: no cover - handler owns it
            engine.stop(checkpoint=True)
        return 0

    if args.role == "frontend":
        if not args.engine:
            raise SystemExit("--role frontend requires --engine host:port")
        from .tier import FrontendServer

        server = FrontendServer(args.engine, config=config,
                                identity=identity,
                                host_workers=args.host_workers,
                                worker_restart=args.worker_restart)
    else:
        # imported here (not at module top) so role/flag validation
        # fails fast without paying the session/service import
        from .service import GrapevineServer

        server = GrapevineServer(
            config, seed=args.seed, max_wait_ms=args.batch_wait_ms,
            identity=identity, leakmon=_leakmon_config(args),
            durability=_durability_config(args),
            worker_restart=args.worker_restart,
            trace_ring_size=args.trace_ring_size,
            slo=_slo_config(args),
            profile_enable=args.profile_enable,
            replicate_to=args.replicate_to,
            ship_every=args.ship_every,
            host_workers=args.host_workers,
            adaptive_batch=args.adaptive_batch,
            flush_window_ms=args.flush_window_ms,
        )
    tls_cert = open(args.tls_cert, "rb").read() if args.tls_cert else None
    tls_key = open(args.tls_key, "rb").read() if args.tls_key else None
    port = server.start(args.listen, tls_cert=tls_cert, tls_key=tls_key)
    print(f"grapevine-tpu listening on port {port}", flush=True)
    if args.metrics_port is not None:
        mport = server.start_metrics(args.metrics_port, host=args.metrics_host)
        print(f"metrics endpoint on port {mport}", flush=True)
    # the pinnable IX static (clients: GrapevineClient(server_static=...))
    print(f"server static key: {server.identity.public.hex()}", flush=True)
    if args.role == "frontend":
        _install_drain_handlers(server.stop)  # no engine state to seal
    else:
        _install_drain_handlers(lambda: server.stop(checkpoint=True))
    try:
        server.wait()
    except KeyboardInterrupt:  # pragma: no cover - handler owns it
        server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
