"""Cross-connection request batcher (+ batched signature verification).

The north-star component the reference never needed (its enclave
serialized per-op ECALLs; SURVEY.md §2c): concurrent gRPC handler threads
submit single operations, and a collector thread packs them into
fixed-size engine rounds — up to ``batch_size`` ops or ``max_wait_ms``,
whichever first. Under-full rounds are dummy-padded by the engine, so the
device cadence carries no information about load bursts beyond the round
count itself.

Challenge-signature verification rides the same batching: the round's
signatures are checked with ONE random-linear-combination multi-scalar
multiplication (session/ristretto.py:batch_verify — SURVEY.md §2b
"consider batch verify"); only a failing round pays per-item verification
to identify offenders, which are rejected without reaching the engine.

The collector is a staged pipeline (PR 10): it keeps up to
``pipeline_depth`` dispatched rounds in a bounded in-flight ledger and
settles them oldest-first, so at depth 2 round k+2's collection window,
batch verification, and journal fsync all overlap rounds k and k+1 on
the device (engine/batcher.py module docstring has the stage contract;
OPERATIONS.md §16 the ordering/durability argument). Depth 1 is
bit-for-bit the pre-PR-10 dispatch-then-settle loop.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future

from ..engine.batcher import GrapevineEngine
from ..session import schnorrkel
from ..wire.records import QueryRequest, QueryResponse

#: (pub, context, message, signature) as taken by the scheme's verify
AuthItem = tuple[bytes, bytes, bytes, bytes]


class AuthFailure(Exception):
    """The request's challenge signature did not verify."""


class SchedulerShutdown(RuntimeError):
    """The op was settled (or refused) because the scheduler is
    draining: the explicit shutdown error clients get instead of a
    silently dropped future. The serving layers map it to gRPC
    UNAVAILABLE so clients retry elsewhere."""


class BatchScheduler:
    def __init__(
        self,
        engine: GrapevineEngine,
        max_wait_ms: float = 8.0,
        idle_gap_ms: float = 2.0,
        clock=None,
        scheme=None,
        restart_on_crash: bool = False,
        pipeline_depth: int | None = None,
        flush_window_ms: float | None = None,
    ):
        self.engine = engine
        self.max_wait = max_wait_ms / 1000.0
        self.idle_gap = idle_gap_ms / 1000.0
        self.clock = clock or (lambda: int(time.time()))
        #: round-pipeline depth — max dispatched-but-unsettled rounds
        #: the collector keeps in flight (the bounded in-flight ledger;
        #: engine/batcher.py module docstring, OPERATIONS.md §16).
        #: Default: the engine's resolved ``config.pipeline_depth``
        #: (stub engines in tests have none → 1, the serial program);
        #: the explicit parameter exists for the bench's depth A/B.
        depth = (
            pipeline_depth
            if pipeline_depth is not None
            else getattr(engine, "pipeline_depth", 1)
        )
        if int(depth) < 1:
            raise ValueError(f"pipeline_depth must be >= 1, got {depth}")
        self.pipeline_depth = int(depth)
        #: signature scheme module (sign/verify/batch_verify); default is
        #: the reference-compatible sr25519 (session/schnorrkel.py)
        self.scheme = scheme or schnorrkel
        #: optional multiprocess verify fan-out (server/hostpipe.py):
        #: when GrapevineServer runs a host pipeline it plants the pool
        #: here, and the round's first-pass batch_verify splits across
        #: worker processes. None = the historical in-process MSM.
        self.hostpipe = None
        #: optional SLO-adaptive window policy (server/adaptive.py),
        #: planted by the serving layer after observability attaches;
        #: None = the static max_wait/idle_gap/full-batch window
        self.adaptive = None
        #: flush-aware collection (server/adaptive.py module docstring
        #: has the obliviousness argument): when the engine reports a
        #: delayed-eviction flush is on the device (flush_bubble_pending
        #: — a pure function of the round counter), the next collection
        #: window may stretch by this declared extra wait, harvesting
        #: arrivals into a fuller round instead of dispatching a thin
        #: round that queues behind the flush anyway. None/0 = off.
        self.flush_window = (flush_window_ms or 0.0) / 1000.0
        if self.flush_window < 0:
            raise ValueError("flush_window_ms must be >= 0")
        #: batch-level telemetry sink (engine/metrics.py on an
        #: obs.TelemetryRegistry); the scheduler records into the
        #: engine's registry so /metrics serves one merged view
        self.metrics = getattr(engine, "metrics", None)
        self._c_flush_stretch = None
        registry = getattr(self.metrics, "registry", None)
        if self.flush_window > 0 and registry is not None:
            # successive schedulers over one engine (bench arms, standby
            # promotion) share the counter instead of re-registering
            existing = registry.get(
                "grapevine_host_flush_window_stretches_total")
            self._c_flush_stretch = existing if existing is not None \
                else registry.counter(
                "grapevine_host_flush_window_stretches_total",
                "collection windows stretched into a delayed-eviction "
                "flush bubble (--flush-window; round-count cadence only)")
        #: (request, auth, future, perf_counter enqueue time)
        self._queue: list[
            tuple[QueryRequest, AuthItem | None, Future, float]
        ] = []
        self._inflight: list[Future] = []
        self._last_enqueue = 0.0
        #: monotonic enqueue time of the current queue head — the age of
        #: the oldest waiting op is the healthz stall signal (obs/httpd)
        self._head_enqueue = 0.0
        #: monotonic dispatch time of the round currently in flight on
        #: the device, None when none is. A wedge inside resolve() (the
        #: device never returning) empties the queue but freezes this —
        #: stall_age() must see it, or healthz serves 200 while every
        #: in-flight client hangs on fut.result() forever
        self._inflight_since: float | None = None
        self._cv = threading.Condition()
        self._closed = False
        #: explicit close() vs crash-closure: restart_on_crash revives
        #: the collector only for the latter
        self._shutdown = False
        self._restart_on_crash = restart_on_crash
        #: consecutive crashes without a successfully settled round in
        #: between; past the cap the collector stays dead so /healthz
        #: flips and the orchestrator replaces the process — supervised
        #: restart must not convert a persistent fault (disk full,
        #: wedged device) into a "healthy" server failing every request
        self._crash_streak = 0
        self.max_crash_streak = 8
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def submit(
        self, req: QueryRequest, auth: AuthItem | None = None
    ) -> QueryResponse:
        """Block until the op's round commits; returns its response.

        With ``auth`` set, the signature is verified as part of the
        round's batch; raises AuthFailure (and the op never reaches the
        engine) if it does not verify."""
        return self.submit_nowait(req, auth).result()

    def submit_nowait(
        self, req: QueryRequest, auth: AuthItem | None = None
    ) -> Future:
        """Enqueue one op and return its Future without waiting.

        The open-loop entry point (grapevine_tpu/load): an arrival
        joins the queue at its scheduled time regardless of how earlier
        ops are faring, so overload latency is *measured* (the queue
        grows and enqueue→settle waits stretch) instead of silently
        self-throttled by a blocked caller. The Future resolves to the
        op's QueryResponse, or raises AuthFailure / SchedulerShutdown /
        the round's error exactly as ``submit`` would."""
        fut: Future = Future()
        # perf_counter enqueue stamp: the SLO's enqueue→settle anchor
        # (one clock domain with the batcher's round spans); the
        # scheduler's own deadline math stays on time.monotonic
        t_enq = time.perf_counter()
        with self._cv:
            if self._closed:
                raise SchedulerShutdown("scheduler closed")
            self._queue.append((req, auth, fut, t_enq))
            depth = len(self._queue)
            self._last_enqueue = time.monotonic()
            if depth == 1:
                self._head_enqueue = self._last_enqueue
            if self.metrics is not None:
                self.metrics.observe_queue_depth(depth)
            self._cv.notify()
        wl = getattr(self.engine, "workload", None)
        if wl is not None:
            # outside the cv: a couple of registry samples must never
            # extend the collector's critical section
            wl.note_arrival(depth)
        return fut

    # -- health probes (obs/httpd.py's /healthz) ------------------------

    def worker_alive(self) -> bool:
        """False once the collector thread has died (crash or close)."""
        return self._worker.is_alive()

    def stall_age(self) -> float:
        """Seconds the oldest un-delivered op has been waiting: the max
        of the queue head's wait and the in-flight round's age. A
        healthy collector drains the head within max_wait + one device
        round and settles an in-flight round promptly, so a growing
        stall age means the engine thread has wedged — whether the ops
        are still queued or already on the device (the healthz
        trip-wire)."""
        now = time.monotonic()
        with self._cv:
            q_age = now - self._head_enqueue if self._queue else 0.0
        t = self._inflight_since  # benign unlocked float read
        return max(q_age, now - t if t is not None else 0.0)

    def _run(self):
        """Collector loop wrapper: a crash in the loop must not strand
        blocked submitters (ADVICE r3: submit() waits on fut.result()
        with no timeout — a dead worker meant a hung client forever).
        Fail every queued and in-flight future and count the crash;
        with ``restart_on_crash`` the loop is revived in place (the
        supervised-restart mode — the thread never reads as dead),
        otherwise re-raise so the death is loud in logs and subsequent
        submits fail immediately."""
        while True:
            try:
                self._run_inner()
                return
            except BaseException as exc:
                with self._cv:
                    self._closed = True
                    stranded = [fut for _, _, fut, _ in self._queue]
                    self._queue.clear()
                    self._cv.notify_all()
                stranded += self._inflight
                for fut in stranded:
                    if not fut.done():
                        fut.set_exception(
                            RuntimeError(f"scheduler worker died: {exc!r}")
                        )
                crash_counter = getattr(
                    self.metrics, "record_worker_crash", None
                )
                if crash_counter is not None:
                    crash_counter()
                self._crash_streak += 1
                if (
                    not self._restart_on_crash
                    or self._shutdown
                    or self._crash_streak > self.max_crash_streak
                ):
                    raise
                import logging

                logging.getLogger("grapevine_tpu.scheduler").exception(
                    "collector crashed (streak %d/%d); supervised "
                    "restart (--worker-restart)",
                    self._crash_streak, self.max_crash_streak,
                )
                # jittered backoff so a hot fault loop cannot spin the
                # core; capped well under the healthz stall threshold
                time.sleep(min(5.0, 0.1 * (2 ** (self._crash_streak - 1))))
                self._inflight = []
                self._inflight_since = None
                with self._cv:
                    self._closed = self._shutdown

    def _run_inner(self):
        bs = self.engine.ecfg.batch_size
        depth = self.pipeline_depth
        #: the bounded in-flight ledger: (PendingRound, live futures,
        #: monotonic dispatch time) in dispatch order. After a dispatch
        #: the collector settles the ledger down to ``depth`` rounds, so
        #: at depth 2 round k+2's collection window, verification, and
        #: journal fsync all run while rounds k and k+1 are still on the
        #: device; at depth 1 the sequence is bit-for-bit the pre-PR-10
        #: dispatch-then-settle loop. The bound is enforced AFTER
        #: dispatch on purpose (dispatch-then-settle IS the depth-1
        #: legacy ordering): depth+1 rounds are transiently dispatched-
        #: but-unresolved for the duration of each settle wait — size
        #: device resp/transcript buffer residency as depth+1 rounds,
        #: not depth (config.py knob docstring, OPERATIONS.md §16).
        #: Rounds always settle oldest-first (= dispatch = journal
        #: order), so responses, tracer ledgers, and leakmon hand-offs
        #: stay in round order at every depth.
        ledger: deque = deque()

        def settle_head():
            pending_h, live_h, t_h = ledger.popleft()
            # the round being settled is the oldest in flight — its
            # dispatch time anchors the stall signal while we block
            self._inflight_since = t_h
            self._settle(pending_h, live_h)
            self._crash_streak = 0  # a settled round = recovered
            self._inflight_since = ledger[0][2] if ledger else None

        while True:
            with self._cv:
                while not self._queue and not self._closed:
                    if ledger:
                        break  # drain the in-flight pipeline, then sleep
                    self._cv.wait()
                if self._closed and not self._queue and not ledger:
                    return
                has_work = bool(self._queue)
                depth0 = len(self._queue)
            # per-round window decision OUTSIDE the cv (the burn-rate
            # scans and registry samples must never extend the
            # collector's critical section — the note_arrival stance).
            # Inputs are public aggregates only: the queue DEPTH (an
            # integer), the arrival EWMA, the SLO burn rates, and the
            # engine's round-counter flush cadence — never queue or
            # buffer contents (server/adaptive.py; CI seeds the
            # contents-dependent mutants).
            w_wait, w_gap, w_target = self.max_wait, self.idle_gap, bs
            if has_work:
                if self.adaptive is not None:
                    w_wait, w_gap, w_target = self.adaptive.decide(depth0)
                if self.flush_window > 0 and getattr(
                    self.engine, "flush_bubble_pending", lambda: False
                )():
                    # the device is busy with the delayed-eviction flush
                    # (a round-count fact): stretch this window into the
                    # bubble and harvest a fuller round
                    w_wait += self.flush_window
                    w_target = bs
                    if self._c_flush_stretch is not None:
                        self._c_flush_stretch.inc()
            with self._cv:
                chunk = []
                if self._queue:
                    # Quiescence-based collection: a client wave
                    # re-arrives staggered over several ms after the
                    # previous round's responses land (decrypt → decode
                    # → sign → resubmit), so a fixed short window caught
                    # only the fastest few (measured 26% occupancy at 8
                    # clients). Keep the window open while arrivals are
                    # still trickling in (inter-arrival gap < idle_gap),
                    # capped at the window's wait total; a lone client
                    # still commits after the idle gap. The wait runs
                    # while the device executes the previous round (see
                    # below), so it costs no device idle time under load.
                    t_asm0 = time.monotonic()
                    t_asm0_pc = time.perf_counter()  # tracer clock
                    deadline = t_asm0 + w_wait
                    hit_cap = False
                    while len(self._queue) < w_target and not self._closed:
                        now = time.monotonic()
                        wait_until = min(
                            deadline, self._last_enqueue + w_gap
                        )
                        if now >= wait_until:
                            hit_cap = now >= deadline
                            break
                        self._cv.wait(timeout=wait_until - now)
                    chunk, self._queue = self._queue[:bs], self._queue[bs:]
                    backlog = len(self._queue)
                    asm_s = time.monotonic() - t_asm0
                    if self._queue:
                        # remaining head has been waiting since roughly
                        # now (it arrived during this window)
                        self._head_enqueue = time.monotonic()
                    if self.metrics is not None:
                        self.metrics.observe_queue_depth(len(self._queue))
                        self.metrics.observe_phase("assembly", asm_s)
                        if hit_cap and len(chunk) < bs:
                            # window closed by the max_wait cap, not by
                            # quiescence or a full batch: arrivals are
                            # starving mid-wave (the stall signal)
                            self.metrics.record_stall()

            # everything the death-guard must fail if we crash from here:
            # the rounds still in flight on the device plus the chunk
            # just popped off the queue (no longer reachable from _queue)
            self._inflight = [
                f for _, lv, _ in ledger for _, f in lv
            ] + [f for _, _, f, _ in chunk]
            pending, live = (None, [])
            if chunk:
                t_v0 = time.monotonic()
                t_v0_pc = time.perf_counter()
                if self.metrics is not None:
                    with self.metrics.time_phase("verify"):
                        live = self._verify_chunk(chunk)
                else:
                    live = self._verify_chunk(chunk)
                ver_s = time.monotonic() - t_v0
                if live:
                    reqs = [r for r, _ in live]
                    try:
                        # async dispatch: the device starts this round
                        # while we resolve the previous one and collect
                        # the next — PERF.md's dispatch/compute overlap
                        pending = self.engine.handle_queries_async(
                            reqs, self.clock()
                        )
                        t_disp = time.monotonic()
                        # collector-side spans + the oldest op's enqueue
                        # stamp ride the round handle itself, so the
                        # tracer/SLO pair them with THIS round even
                        # while the pipeline overlaps the next window
                        # (getattr: test fakes return bare objects)
                        if getattr(pending, "note_span", None) is not None:
                            pending.note_span("assembly", t_asm0_pc, asm_s)
                            pending.note_span("verify", t_v0_pc, ver_s)
                            # post-dispatch backlog: the queue-depth
                            # sample obs/workload.py histograms at
                            # round cadence (and flightrec records)
                            pending.set_queue_depth(backlog)
                            # anchor on the ops that actually entered
                            # the round: an auth-rejected op's queue
                            # wait is not a commit latency, and letting
                            # it in would hand an attacker (garbage
                            # signatures are their cheapest input) a
                            # lever on the SLO burn rate
                            enq_by_fut = {f: t for _, _, f, t in chunk}
                            pending.set_enqueued_at(
                                min(enq_by_fut[f] for _, f in live)
                            )
                    except Exception as exc:  # pragma: no cover - defensive
                        for _, fut in live:
                            if not fut.done():
                                fut.set_exception(exc)
                        live = []
            if pending is not None:
                ledger.append((pending, live, t_disp))
                self._inflight_since = ledger[0][2]
                # the pipeline bound: settle oldest-first down to depth,
                # so the NEXT collection window opens with exactly
                # ``depth`` rounds overlapping it
                while len(ledger) > depth:
                    settle_head()
            elif ledger:
                # nothing dispatched this pass (idle tail, drain, or an
                # all-rejected chunk): settle the oldest round so its
                # clients are answered promptly and close() can drain
                settle_head()

    def _batch_verify_fanout(self, items) -> bool:
        """First-pass batch verify, fanned across the hostpipe pool when
        one is attached. The happy path (everything verifies) gets the
        multiprocess speedup; a False answer hands off to the inline
        bisect below, which stays in-process — failure is the attacker-
        funded path and does not deserve the parallel hardware. Any pool
        fault degrades to the in-process MSM rather than rejecting
        honest traffic."""
        if self.hostpipe is not None:
            from .hostpipe import HostPipeError

            try:
                return self.hostpipe.verify_parallel(items)
            except HostPipeError:
                pass  # degraded pool: verified correctness beats speed
        return bool(self.scheme.batch_verify(items))

    def _verify_chunk(self, chunk):
        """Batch signature verification; returns surviving (req, fut)."""
        # --- one multi-scalar multiplication for the round ------------
        authed = [i for i, (_, a, _, _) in enumerate(chunk) if a is not None]
        rejected: set[int] = set()
        if authed and not self._batch_verify_fanout(
            [chunk[i][1] for i in authed]
        ):
            # bisect to the offenders: O(bad · log n) batch checks, so
            # one client spraying garbage signatures cannot force
            # per-item verification of every honest request
            stack = [authed]
            while stack:
                idxs = stack.pop()
                mid = len(idxs) // 2
                for half in (idxs[:mid], idxs[mid:]):
                    if not half:
                        continue
                    if len(half) == 1:
                        i = half[0]
                        if not self.scheme.verify(*chunk[i][1]):
                            rejected.add(i)
                            chunk[i][2].set_exception(
                                AuthFailure("bad challenge signature")
                            )
                    elif not self.scheme.batch_verify(
                        [chunk[i][1] for i in half]
                    ):
                        stack.append(half)
        if authed and self.metrics is not None:
            self.metrics.record_auth(failures=len(rejected))
        return [
            (req, fut)
            for i, (req, _, fut, _) in enumerate(chunk)
            if i not in rejected
        ]

    def _settle(self, pending, live):
        """Resolve a dispatched round and deliver its responses."""
        try:
            resps = pending.resolve()
            for (_, fut), resp in zip(live, resps):
                fut.set_result(resp)
        except Exception as exc:  # pragma: no cover - defensive
            for _, fut in live:
                if not fut.done():
                    fut.set_exception(exc)

    def close(self):
        """Graceful drain: stop admitting, settle queued-but-undispatched
        ops with an explicit SchedulerShutdown (never silently dropped —
        the serving layer maps it to gRPC UNAVAILABLE so clients retry
        elsewhere), and let the worker finish the round already on the
        device before joining."""
        with self._cv:
            self._shutdown = True
            self._closed = True
            undispatched = [fut for _, _, fut, _ in self._queue]
            self._queue.clear()
            self._cv.notify_all()
        for fut in undispatched:
            if not fut.done():
                fut.set_exception(
                    SchedulerShutdown(
                        "scheduler draining: op was queued but not yet "
                        "dispatched; retry against a serving replica"
                    )
                )
        self._worker.join(timeout=5)
