"""Cross-connection request batcher.

The north-star component the reference never needed (its enclave
serialized per-op ECALLs; SURVEY.md §2c): concurrent gRPC handler threads
submit single operations, and a collector thread packs them into
fixed-size engine rounds — up to ``batch_size`` ops or ``max_wait_ms``,
whichever first. Under-full rounds are dummy-padded by the engine, so the
device cadence carries no information about load bursts beyond the round
count itself.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future

from ..engine.batcher import GrapevineEngine
from ..wire.records import QueryRequest, QueryResponse


class BatchScheduler:
    def __init__(
        self,
        engine: GrapevineEngine,
        max_wait_ms: float = 2.0,
        clock=None,
    ):
        self.engine = engine
        self.max_wait = max_wait_ms / 1000.0
        self.clock = clock or (lambda: int(time.time()))
        self._queue: list[tuple[QueryRequest, Future]] = []
        self._cv = threading.Condition()
        self._closed = False
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def submit(self, req: QueryRequest) -> QueryResponse:
        """Block until the op's round commits; returns its response."""
        fut: Future = Future()
        with self._cv:
            if self._closed:
                raise RuntimeError("scheduler closed")
            self._queue.append((req, fut))
            self._cv.notify()
        return fut.result()

    def _run(self):
        bs = self.engine.ecfg.batch_size
        while True:
            with self._cv:
                while not self._queue and not self._closed:
                    self._cv.wait()
                if self._closed and not self._queue:
                    return
                deadline = time.monotonic() + self.max_wait
                while len(self._queue) < bs and not self._closed:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(timeout=remaining)
                chunk, self._queue = self._queue[:bs], self._queue[bs:]
            reqs = [r for r, _ in chunk]
            try:
                resps = self.engine.handle_queries(reqs, self.clock())
                for (_, fut), resp in zip(chunk, resps):
                    fut.set_result(resp)
            except Exception as exc:  # pragma: no cover - defensive
                for _, fut in chunk:
                    if not fut.done():
                        fut.set_exception(exc)

    def close(self):
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._worker.join(timeout=5)
