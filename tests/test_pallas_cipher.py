"""Fused Pallas cipher kernel ≡ the jnp keystream path (bit-identical).

The kernel runs in interpret mode on the CPU test backend (the
SGX_MODE=SW analog); on real TPU the same code compiles to Mosaic.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from grapevine_tpu.oblivious.bucket_cipher import row_keystream
from grapevine_tpu.oblivious.pallas_cipher import cipher_rows_pallas

U32 = jnp.uint32


@pytest.mark.parametrize(
    "r,w,rounds",
    [
        (5, 100, 8),     # ragged rows, non-multiple-of-16 words
        (37, 1024, 8),   # records-tree row shape (Z + Z*V = 4 + 4*255)
        (16, 4100, 20),  # mailbox-like wide row, ChaCha20
    ],
)
def test_fused_kernel_matches_jnp_keystream(r, w, rounds):
    key = jax.random.bits(jax.random.PRNGKey(0), (8,), U32)
    data = jax.random.bits(jax.random.PRNGKey(1), (r, w), U32)
    bucket = jax.random.bits(jax.random.PRNGKey(2), (r,), U32)
    epoch = jnp.stack(
        [jax.random.bits(jax.random.PRNGKey(3), (r,), U32) % 5,
         jnp.zeros((r,), U32)],
        axis=1,
    )  # includes epoch-0 (identity) rows
    z = 4  # slot-index words, as in the ORAM bucket rows
    want = data ^ row_keystream(key, bucket, epoch, w, rounds)
    gi, gv = cipher_rows_pallas(
        key, bucket, epoch, data[:, :z], data[:, z:], rounds, interpret=True
    )
    got = jnp.concatenate([gi, gv], axis=1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # decrypt = same pass
    bi, bv = cipher_rows_pallas(key, bucket, epoch, gi, gv, rounds, interpret=True)
    np.testing.assert_array_equal(
        np.asarray(jnp.concatenate([bi, bv], axis=1)), np.asarray(data)
    )


@pytest.mark.slow  # ~68 s interpret-mode whole-engine campaign; the
# kernel keystream bit-equality unit tests above and the Mosaic
# lowering gate (test_mosaic_lowering.py) stay always-on. Tier-1
# budget: ROADMAP.md tier-1 note (PR 5).
def test_engine_states_bit_identical_across_cipher_impls():
    """A CRUD stream through cipher_impl='pallas' produces the same
    responses AND the same device state as cipher_impl='jnp' — the two
    paths are interchangeable at rest."""
    import dataclasses

    from grapevine_tpu.config import GrapevineConfig
    from grapevine_tpu.engine.batcher import GrapevineEngine
    from grapevine_tpu.wire import constants as C
    from grapevine_tpu.wire.records import QueryRequest, RequestRecord

    base = GrapevineConfig(
        max_messages=64,
        max_recipients=16,
        mailbox_cap=4,
        batch_size=4,
        stash_size=96,
        bucket_cipher_rounds=8,
    )

    def req(rt, auth, recipient=C.ZERO_PUBKEY, tag=0):
        return QueryRequest(
            request_type=rt,
            auth_identity=auth,
            auth_signature=b"\x01" * C.SIGNATURE_SIZE,
            record=RequestRecord(
                msg_id=C.ZERO_MSG_ID,
                recipient=recipient,
                payload=bytes([tag]) * C.PAYLOAD_SIZE,
            ),
        )

    a, b = bytes([1]) * 32, bytes([2]) * 32
    streams = []
    states = []
    for impl in ("jnp", "pallas"):
        cfg = dataclasses.replace(base, bucket_cipher_impl=impl)
        e = GrapevineEngine(cfg, seed=7)
        resps = []
        for t in range(3):
            resps += e.handle_queries(
                [
                    req(C.REQUEST_TYPE_CREATE, a, recipient=b, tag=t),
                    req(C.REQUEST_TYPE_READ, b),
                ],
                1_700_000_000 + t,
            )
        streams.append([(x.status_code, x.record.payload) for x in resps])
        states.append(e.state)
    assert streams[0] == streams[1]
    for x, y in zip(jax.tree.leaves(states[0]), jax.tree.leaves(states[1])):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
