"""Fused gather+decrypt kernel (oblivious/pallas_gather.py).

Correctness contract: the fused single-pass fetch is bit-identical to
gather → keystream XOR, at the kernel level and through a full engine
round (interpret mode on CPU — the Mosaic compile is exercised on real
TPU by bench.py's pallas configs)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from grapevine_tpu.config import GrapevineConfig
from grapevine_tpu.engine.batcher import GrapevineEngine
from grapevine_tpu.oblivious.bucket_cipher import row_keystream
from grapevine_tpu.oblivious.pallas_gather import gather_decrypt_rows
from grapevine_tpu.wire import constants as C
from grapevine_tpu.wire.records import QueryRequest, RequestRecord

NOW = 1_700_000_000


def test_kernel_matches_gather_then_xor():
    rng = np.random.default_rng(2)
    n, z, v = 64, 4, 6
    zv = z * v
    tree_idx = jnp.asarray(rng.integers(0, 2**31, (n * z,)), jnp.uint32)
    tree_val = jnp.asarray(rng.integers(0, 2**31, (n, zv)), jnp.uint32)
    nonces = jnp.asarray(rng.integers(0, 3, (n, 2)), jnp.uint32)  # some 0
    key = jnp.asarray(rng.integers(0, 2**31, (8,)), jnp.uint32)
    flat_b = jnp.asarray(rng.integers(0, n, (17,)), jnp.uint32)
    oi, ov = gather_decrypt_rows(
        key, tree_idx, tree_val, nonces, flat_b, z=z, rounds=8,
        interpret=True,
    )
    pidx = tree_idx.reshape(n, z)[flat_b]
    pval = tree_val[flat_b]
    pn = nonces[flat_b]
    ks = row_keystream(key, flat_b, pn, z + zv, 8)
    written = ((pn[:, 0] != 0) | (pn[:, 1] != 0))[:, None]
    assert np.array_equal(
        np.asarray(oi), np.asarray(pidx ^ jnp.where(written, ks[:, :z], 0))
    )
    assert np.array_equal(
        np.asarray(ov), np.asarray(pval ^ jnp.where(written, ks[:, z:], 0))
    )


def test_plaintext_rounds0_is_plain_gather():
    rng = np.random.default_rng(3)
    n, z, zv = 16, 4, 8
    tree_idx = jnp.asarray(rng.integers(0, 2**31, (n * z,)), jnp.uint32)
    tree_val = jnp.asarray(rng.integers(0, 2**31, (n, zv)), jnp.uint32)
    nonces = jnp.zeros((n, 2), jnp.uint32)
    key = jnp.zeros((8,), jnp.uint32)
    flat_b = jnp.asarray([3, 0, 3], jnp.uint32)
    oi, ov = gather_decrypt_rows(
        key, tree_idx, tree_val, nonces, flat_b, z=z, rounds=0,
        interpret=True,
    )
    assert np.array_equal(np.asarray(oi), np.asarray(tree_idx.reshape(n, z)[flat_b]))
    assert np.array_equal(np.asarray(ov), np.asarray(tree_val[flat_b]))


def _run_crd(impl: str, seed: int = 9):
    cfg = GrapevineConfig(
        max_messages=64,
        max_recipients=8,
        mailbox_cap=4,
        batch_size=4,
        stash_size=64,
        bucket_cipher_rounds=8,
        bucket_cipher_impl=impl,
    )
    e = GrapevineEngine(cfg, seed=seed)
    a, b = b"\x11" * 32, b"\x22" * 32
    outs = []
    r = e.handle_queries(
        [QueryRequest(request_type=C.REQUEST_TYPE_CREATE, auth_identity=a,
                      record=RequestRecord(recipient=b,
                                           payload=b"\x05" * C.PAYLOAD_SIZE))],
        NOW,
    )[0]
    outs.append((r.status_code, r.record.msg_id, r.record.payload))
    r2 = e.handle_queries(
        [QueryRequest(request_type=C.REQUEST_TYPE_READ, auth_identity=b,
                      record=RequestRecord(msg_id=C.ZERO_MSG_ID))],
        NOW + 1,
    )[0]
    outs.append((r2.status_code, r2.record.msg_id, r2.record.payload))
    r3 = e.handle_queries(
        [QueryRequest(request_type=C.REQUEST_TYPE_DELETE, auth_identity=b,
                      record=RequestRecord(msg_id=C.ZERO_MSG_ID))],
        NOW + 2,
    )[0]
    outs.append((r3.status_code, r3.record.msg_id, r3.record.payload))
    return outs, e.state


@pytest.mark.slow  # the repo's single fattest test (~66 s interpret-mode
# e2e over three cipher impls); the kernel-level equality tests above
# stay always-on and the TPU capture's mosaic stage re-proves this
# contract on device — moved off the tier-1 budget in PR 3
def test_engine_round_identical_across_cipher_impls():
    """Full engine C-R-D through the fused fetch ≡ the jnp path: same
    seed ⇒ same ids, payloads, statuses, AND bit-identical state up to
    the junk bucket (found divergent-by-design in round 5; everything
    path-addressable must match exactly)."""
    from grapevine_tpu.testing.compare import states_equal_excluding_junk

    outs_f, state_f = _run_crd("pallas_fused")
    outs_j, state_j = _run_crd("jnp")
    assert outs_f == outs_j
    same, first_diff = states_equal_excluding_junk(state_j, state_f)
    assert same, f"state diverges at {first_diff}"


def test_tiled_gather_matches_gather_then_xor():
    """Kernel-level: the manual-DMA tiled gather ≡ gather → XOR,
    including ragged R (padding steps fetch row 0 harmlessly)."""
    from grapevine_tpu.oblivious.pallas_gather import gather_decrypt_rows_tiled

    rng = np.random.default_rng(2)
    n, z, v = 64, 4, 6
    zv = z * v
    tree_idx = jnp.asarray(rng.integers(0, 2**31, (n * z,)), jnp.uint32)
    tree_val = jnp.asarray(rng.integers(0, 2**31, (n, zv)), jnp.uint32)
    nonces = jnp.asarray(rng.integers(0, 3, (n, 2)), jnp.uint32)
    key = jnp.asarray(rng.integers(0, 2**31, (8,)), jnp.uint32)
    flat_b = jnp.asarray(rng.integers(0, n, (17,)), jnp.uint32)
    oi, ov = gather_decrypt_rows_tiled(
        key, tree_idx, tree_val, nonces, flat_b, z=z, rounds=8,
        interpret=True,
    )
    pidx = tree_idx.reshape(n, z)[flat_b]
    pval = tree_val[flat_b]
    pn = nonces[flat_b]
    ks = row_keystream(key, flat_b, pn, z + zv, 8)
    written = ((pn[:, 0] != 0) | (pn[:, 1] != 0))[:, None]
    assert np.array_equal(
        np.asarray(oi), np.asarray(pidx ^ jnp.where(written, ks[:, :z], 0))
    )
    assert np.array_equal(
        np.asarray(ov), np.asarray(pval ^ jnp.where(written, ks[:, z:], 0))
    )


def test_tiled_scatter_matches_encrypt_then_scatter():
    """Kernel-level: the manual-DMA tiled write-back ≡ cipher_rows →
    masked scatter, with duplicate junk-redirects and ragged R."""
    from grapevine_tpu.oblivious.pallas_gather import scatter_encrypt_rows_tiled

    rng = np.random.default_rng(5)
    n, z, v = 32, 4, 6
    zv = z * v
    tree_idx = jnp.asarray(rng.integers(0, 2**31, (n * z,)), jnp.uint32)
    tree_val = jnp.asarray(rng.integers(0, 2**31, (n, zv)), jnp.uint32)
    nonces = jnp.asarray(rng.integers(0, 3, (n, 2)), jnp.uint32)
    key = jnp.asarray(rng.integers(0, 2**31, (8,)), jnp.uint32)
    epoch = jnp.asarray([7, 0], jnp.uint32)
    flat_b = jnp.asarray([3, 9, 3, 20, 11], jnp.uint32)
    owner = jnp.asarray([True, True, False, True, True])
    new_pidx = jnp.asarray(rng.integers(0, 2**31, (5, z)), jnp.uint32)
    new_pval = jnp.asarray(rng.integers(0, 2**31, (5, zv)), jnp.uint32)
    orig_i = np.asarray(tree_idx).reshape(n, z).copy()
    orig_v = np.asarray(tree_val).copy()
    orig_n = np.asarray(nonces).copy()
    oi, ov, on = scatter_encrypt_rows_tiled(
        key, tree_idx, tree_val, nonces, flat_b, owner, epoch, new_pidx,
        new_pval, z=z, rounds=8, interpret=True,
    )
    oi = np.asarray(oi).reshape(n, z)
    ov = np.asarray(ov)
    on = np.asarray(on)
    ks = row_keystream(
        key, flat_b, jnp.broadcast_to(epoch[None, :], (5, 2)), z + zv, 8
    )
    ref_i, ref_v = orig_i.copy(), orig_v.copy()
    for j in range(5):
        if bool(owner[j]):
            ref_i[int(flat_b[j])] = np.asarray(new_pidx[j] ^ ks[j, :z])
            ref_v[int(flat_b[j])] = np.asarray(new_pval[j] ^ ks[j, z:])
    for row in range(n - 1):
        if row in (3, 9, 11, 20):
            assert np.array_equal(oi[row], ref_i[row]), f"idx row {row}"
            assert np.array_equal(ov[row], ref_v[row]), f"val row {row}"
            assert np.array_equal(on[row], np.asarray(epoch)), f"non {row}"
        else:
            assert np.array_equal(oi[row], orig_i[row]), row
            assert np.array_equal(ov[row], orig_v[row]), row
            assert np.array_equal(on[row], orig_n[row]), f"non {row}"


@pytest.mark.slow  # interpret-mode engine round: ~26 s; kernel-level
# tiled equality stays in tier-1 (test_tiled_*), this e2e pass is -m slow
def test_engine_round_identical_tiled_impl():
    """Same contract for the tiled fused impl (manual-DMA kernels)."""
    from grapevine_tpu.testing.compare import states_equal_excluding_junk

    outs_t, state_t = _run_crd("pallas_fused_tiled")
    outs_j, state_j = _run_crd("jnp")
    assert outs_t == outs_j
    same, first_diff = states_equal_excluding_junk(state_j, state_t)
    assert same, f"state diverges at {first_diff}"


@pytest.mark.slow  # 8-virtual-device compile ~25 s; sharded equality
# coverage in tier-1 budget lives in tests/test_parallel.py's fast params
def test_sharded_path_ignores_fused_fetch():
    """Under shard_map (axis_name set) the fused fetch must NOT engage —
    the sharded program still compiles and matches single-chip (the
    plaintext-over-ICI guard)."""
    from grapevine_tpu.engine.state import EngineConfig, init_engine
    from grapevine_tpu.engine.batcher import pack_batch
    from grapevine_tpu.parallel import make_mesh, make_sharded_step, shard_engine_state

    cfg = GrapevineConfig(
        max_messages=64, max_recipients=8, mailbox_cap=4, batch_size=4,
        stash_size=64, bucket_cipher_rounds=8,
        bucket_cipher_impl="pallas_fused",
    )
    ecfg = EngineConfig.from_config(cfg)
    mesh = make_mesh(jax.devices()[:4])
    state = shard_engine_state(init_engine(ecfg, seed=1), mesh)
    step = make_sharded_step(ecfg, mesh)
    req = QueryRequest(
        request_type=C.REQUEST_TYPE_CREATE,
        auth_identity=b"\x11" * 32,
        record=RequestRecord(recipient=b"\x22" * 32,
                             payload=b"\x07" * C.PAYLOAD_SIZE),
    )
    batch = pack_batch([req], 4, NOW)
    state, resp, _ = step(state, batch)
    assert int(np.asarray(resp["status"])[0]) == C.STATUS_CODE_SUCCESS


def test_scatter_encrypt_matches_encrypt_then_scatter():
    """The fused write-back ≡ cipher_rows → masked scatter: owners'
    rows land encrypted, non-owner duplicates are dropped, untouched
    rows (and nothing else) keep their exact contents."""
    from grapevine_tpu.oblivious.pallas_gather import scatter_encrypt_rows

    rng = np.random.default_rng(5)
    n, z, v = 32, 4, 6
    zv = z * v
    tree_idx = jnp.asarray(rng.integers(0, 2**31, (n * z,)), jnp.uint32)
    tree_val = jnp.asarray(rng.integers(0, 2**31, (n, zv)), jnp.uint32)
    nonces = jnp.asarray(rng.integers(0, 3, (n, 2)), jnp.uint32)
    key = jnp.asarray(rng.integers(0, 2**31, (8,)), jnp.uint32)
    epoch = jnp.asarray([7, 0], jnp.uint32)
    flat_b = jnp.asarray([3, 9, 3, 20], jnp.uint32)  # 3 duplicated
    owner = jnp.asarray([True, True, False, True])
    new_pidx = jnp.asarray(rng.integers(0, 2**31, (4, z)), jnp.uint32)
    new_pval = jnp.asarray(rng.integers(0, 2**31, (4, zv)), jnp.uint32)
    # snapshot BEFORE the call: the kernel donates the tree buffers
    # (in-place update is the point), so the inputs die with the call
    orig_i = np.asarray(tree_idx).reshape(n, z).copy()
    orig_v = np.asarray(tree_val).copy()
    orig_n = np.asarray(nonces).copy()
    oi, ov, on = scatter_encrypt_rows(
        key, tree_idx, tree_val, nonces, flat_b, owner, epoch, new_pidx,
        new_pval, z=z, rounds=8, interpret=True,
    )
    oi = np.asarray(oi).reshape(n, z)
    ov = np.asarray(ov)
    on = np.asarray(on)
    ks = row_keystream(
        key, flat_b, jnp.broadcast_to(epoch[None, :], (4, 2)), z + zv, 8
    )
    ref_i, ref_v = orig_i.copy(), orig_v.copy()
    for j in range(4):
        if bool(owner[j]):
            ref_i[int(flat_b[j])] = np.asarray(new_pidx[j] ^ ks[j, :z])
            ref_v[int(flat_b[j])] = np.asarray(new_pval[j] ^ ks[j, z:])
    for row in range(n - 1):  # row n-1 is the junk pad bucket
        if row in (3, 9, 20):
            assert np.array_equal(oi[row], ref_i[row]), f"idx row {row}"
            assert np.array_equal(ov[row], ref_v[row]), f"val row {row}"
            assert np.array_equal(on[row], np.asarray(epoch)), f"nonce {row}"
        else:
            assert np.array_equal(oi[row], orig_i[row]), row
            assert np.array_equal(ov[row], orig_v[row]), row
            assert np.array_equal(on[row], orig_n[row]), f"nonce {row}"
