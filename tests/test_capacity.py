"""Capacity scaling evidence: 2^24 messages on a v5e-8 pod.

BASELINE config 4 names a 2^24-capacity expiry sweep; one v5e chip has
~16 GB HBM, and 2^24 1-KB records are 17 GB of raw payload — the target
capacity is a *pod* configuration by construction, which is exactly the
sharding story (SURVEY.md §2c: bucket-tree sharded across chips,
BASELINE config 5). Evidence here comes in two tiers:

- an always-run geometry test pinning the arithmetic: at 2^24 and tree
  density 4 the records tree is 32 GB → 4 GB/chip on an 8-way mesh,
  comfortably inside HBM next to the mailbox tree and position map; and
  the per-chip shard is byte-identical to the single-chip
  2^20-at-density-2 tree the real-TPU bench runs (bench.py) — so the
  pod shape is the benched shape, 8 times over;
- a gated big test (GRAPEVINE_BIG_TESTS=1, default 2^23 ⇒ 16 GB
  sharded over the 8-device CPU mesh; GRAPEVINE_BIG_CAP_LOG2=24 for
  full scale on a multi-core host) that actually instantiates the
  engine, runs one batched CRUD round and one expiry sweep, and checks
  consistency — the SGX_MODE=SW-style simulation of the pod (reference
  .github/workflows/ci.yaml:15-16).
"""

import os

import numpy as np
import pytest

from grapevine_tpu.config import GrapevineConfig
from grapevine_tpu.engine.state import EngineConfig

V5E_HBM = 16 * 2**30
MESH = 8


def _tree_bytes(o) -> int:
    """HBM bytes of one ORAM's device-resident arrays (tree + nonces)."""
    z, v = o.bucket_slots, o.value_words
    per_bucket = z * v * 4 + z * 4 + 8  # values + slot idx + nonce
    return o.n_buckets_padded * per_bucket


def pod_config() -> GrapevineConfig:
    return GrapevineConfig(
        max_messages=1 << 24,
        max_recipients=1 << 14,
        batch_size=1024,
        stash_size=1024,
        tree_density=4,
    )


def test_pod_capacity_geometry():
    ecfg = EngineConfig.from_config(pod_config())
    rec_b, mb_b = _tree_bytes(ecfg.rec), _tree_bytes(ecfg.mb)
    # sharded axis 0 divides evenly across the mesh (n_buckets_padded is
    # a power of two, path_oram.py:n_buckets_padded)
    assert ecfg.rec.n_buckets_padded % MESH == 0
    assert ecfg.mb.n_buckets_padded % MESH == 0
    per_chip = (rec_b + mb_b) // MESH
    # replicated state (posmap + freelist + stash) rides along on every chip
    replicated = ecfg.rec.blocks * 4 * 2 + ecfg.mb.blocks * 4
    assert per_chip + replicated < V5E_HBM // 2, (
        f"per-chip {(per_chip + replicated) / 2**30:.1f} GB must leave "
        "headroom for working buffers"
    )
    # the per-chip shard is byte-for-byte the tree the single-chip bench
    # runs: 2^20 capacity at density 2 (bench.py batched_read/zipf/expiry
    # all use cap 2^20) — so the pod shape is the benched shape, 8×
    single = EngineConfig.from_config(
        GrapevineConfig(
            max_messages=1 << 20,
            max_recipients=1 << 14,
            batch_size=1024,
            stash_size=1024,
            tree_density=2,
        )
    )
    assert _tree_bytes(ecfg.rec) // MESH == _tree_bytes(single.rec)
    # capacity really is 2^24: enough tree slots for every message
    assert ecfg.rec.n_buckets * ecfg.rec.bucket_slots >= 1 << 24


def test_init_sharded_engine_matches_staged_init():
    """Shard-aware init is bit-identical to init-then-shard (threefry is
    deterministic under jit), at a shape small enough to stage both."""
    import jax
    import numpy as np

    from grapevine_tpu.engine.state import init_engine
    from grapevine_tpu.parallel import (
        init_sharded_engine,
        make_mesh,
        shard_engine_state,
    )

    cfg = GrapevineConfig(
        max_messages=256, max_recipients=32, mailbox_cap=4,
        batch_size=4, stash_size=64,
    )
    ecfg = EngineConfig.from_config(cfg)
    mesh = make_mesh(jax.devices()[:MESH])
    a = init_sharded_engine(ecfg, mesh, seed=7)
    b = shard_engine_state(init_engine(ecfg, seed=7), mesh)
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.skipif(
    not os.environ.get("GRAPEVINE_BIG_TESTS"),
    reason="multi-GB instantiation; set GRAPEVINE_BIG_TESTS=1 to run",
)
def test_pod_2e24_round_and_sweep():
    """Defaults to half scale (2^23 ⇒ 16 GB sharded state) with batch
    256. Larger shapes DO run (bisected: 2^23 at B=1024 completes
    standalone) but sit on the edge of XLA CPU's collectives rendezvous
    terminate-timeout when 8 virtual devices timeslice one host core —
    the round's working-set psum is hundreds of MB per device, and a
    thread arriving tens of seconds late SIGABRTs the process. Real ICI
    moves that in milliseconds; this is simulation-infra timing, not a
    product limit. GRAPEVINE_BIG_CAP_LOG2 / GRAPEVINE_BIG_BATCH
    override the scale on beefier hosts."""
    import jax

    from grapevine_tpu.engine.expiry import expiry_sweep
    from grapevine_tpu.parallel import (
        init_sharded_engine,
        make_mesh,
        make_sharded_step,
    )

    cap_log2 = int(os.environ.get("GRAPEVINE_BIG_CAP_LOG2", "23"))
    # GRAPEVINE_BIG_MESH=1: single-device execution (no collectives) —
    # the path that carries full 2^24 scale on a one-core host, where
    # the 8-virtual-device rendezvous timeout (docstring) rules the
    # sharded form out. The program is the same engine_round_step the
    # mesh path runs under shard_map.
    mesh_n = int(os.environ.get("GRAPEVINE_BIG_MESH", str(MESH)))
    cfg = GrapevineConfig(
        max_messages=1 << cap_log2,
        max_recipients=1 << 14,
        batch_size=int(os.environ.get("GRAPEVINE_BIG_BATCH", "256")),
        stash_size=1024,
        tree_density=4,
    )
    ecfg = EngineConfig.from_config(cfg)
    if mesh_n > 1:
        assert len(jax.devices()) >= mesh_n
        mesh = make_mesh(jax.devices()[:mesh_n])
        # shard-aware init: the unsharded 32 GB state never exists anywhere
        state = init_sharded_engine(ecfg, mesh, seed=0)
        step = make_sharded_step(ecfg, mesh)
    else:
        from grapevine_tpu.engine.round_step import engine_round_step
        from grapevine_tpu.engine.state import init_engine

        state = jax.jit(lambda: init_engine(ecfg, seed=0))()
        step = jax.jit(
            lambda st, batch: engine_round_step(ecfg, st, batch),
            donate_argnums=0,
        )

    rng = np.random.default_rng(1)
    b = cfg.batch_size
    from grapevine_tpu.engine.state import ID_WORDS, KEY_WORDS, PAYLOAD_WORDS

    batch = {
        "req_type": np.ones((b,), np.uint32),  # all CREATEs
        "auth": rng.integers(1, 2**31, (b, KEY_WORDS)).astype(np.uint32),
        "msg_id": np.zeros((b, ID_WORDS), np.uint32),
        "recipient": rng.integers(1, 2**31, (b, KEY_WORDS)).astype(np.uint32),
        "payload": rng.integers(0, 2**31, (b, PAYLOAD_WORDS)).astype(np.uint32),
        "now": np.uint32(1_700_000_000),
    }
    state, resp, transcripts = step(state, batch)
    jax.block_until_ready(resp)
    from grapevine_tpu.wire import constants as C

    assert np.all(np.asarray(resp["status"]) == C.STATUS_CODE_SUCCESS)
    assert int(np.asarray(state.rec.overflow)) == 0
    assert np.asarray(transcripts).shape == (b, 2 * cfg.resolved_mailbox_choices + 1)

    # GRAPEVINE_BIG_SWEEP=0 skips the expiry sweep: the sweep dominates
    # wall clock (ChaCha over 2×32 GB at 2^24) and was already executed
    # at full scale single-device (BIGRUN_r4.md); the sharded-2^24
    # attempt targets the ROUND under collectives (VERDICT r4 #6)
    if os.environ.get("GRAPEVINE_BIG_SWEEP", "1") == "0":
        return

    # donate: at 2^24 the 32 GB tree must not be double-buffered
    free_top_before = int(np.asarray(state.free_top))
    swept = jax.jit(expiry_sweep, static_argnums=(0,), donate_argnums=(1,))(
        ecfg, state, np.uint32(1_700_000_000 + 100), np.uint32(10)
    )
    jax.block_until_ready(swept.free_top)
    # every live record was older than the period → all expired
    assert int(np.asarray(swept.free_top)) == free_top_before + b
