"""Crash-safe engine: recovery bit-equality, graceful drain, RPC retry,
worker-crash handling, and the durability health surface.

The in-process half of the PR-4 acceptance: checkpoint → restore →
bit-identical state on tier-1; the SIGKILL half (randomized kill points,
multi-incarnation recovery, leakmon-PASS-across-recovery) lives in
tests/test_chaos_recovery.py (-m slow) and tools/chaos_run.py.
"""

import os
import shutil
import threading
import time

import grpc
import pytest

from grapevine_tpu.config import DurabilityConfig, GrapevineConfig
from grapevine_tpu.engine import checkpoint as cp
from grapevine_tpu.engine.batcher import GrapevineEngine
from grapevine_tpu.engine.metrics import EngineMetrics
from grapevine_tpu.server.scheduler import BatchScheduler, SchedulerShutdown
from grapevine_tpu.wire import constants as C
from grapevine_tpu.wire.records import (
    QueryRequest,
    QueryResponse,
    Record,
    RequestRecord,
)

NOW = 1_700_000_000

SMALL = GrapevineConfig(
    max_messages=64, max_recipients=8, mailbox_cap=4,
    batch_size=4, stash_size=64, bucket_cipher_rounds=0,
)


def _key(n: int) -> bytes:
    return bytes([n, n ^ 0x5A]) + b"\x01" * 30


def _req(rt, auth, recipient=C.ZERO_PUBKEY, tag=0):
    return QueryRequest(
        request_type=rt, auth_identity=auth,
        auth_signature=b"\x01" * C.SIGNATURE_SIZE,
        record=RequestRecord(
            msg_id=C.ZERO_MSG_ID, recipient=recipient,
            payload=bytes([tag & 0xFF]) * C.PAYLOAD_SIZE,
        ),
    )


def _drive(engine, n_events: int, t0=NOW):
    """Deterministic mixed workload: creates, zero-id reads, one sweep
    per 5 events."""
    import random

    rng = random.Random(17)
    out = []
    for i in range(n_events):
        if i % 5 == 3:
            engine.expire(t0 + i, period=10_000)
            continue
        reqs = []
        for _ in range(rng.randrange(1, SMALL.batch_size + 1)):
            if rng.random() < 0.6:
                reqs.append(_req(C.REQUEST_TYPE_CREATE,
                                 _key(rng.randrange(1, 5)),
                                 recipient=_key(rng.randrange(1, 5)),
                                 tag=rng.randrange(256)))
            else:
                reqs.append(_req(C.REQUEST_TYPE_READ,
                                 _key(rng.randrange(1, 5))))
        out.append([r.pack() for r in engine.handle_queries(reqs, t0 + i)])
    return out


@pytest.fixture(scope="module")
def durable_run(tmp_path_factory):
    """One durable run: 10 events (rounds + sweeps) with checkpoints
    every 4 records, cleanly closed. Yields (state_dir, final state
    bytes, journal seq) — the module's tests recover from copies."""
    state_dir = str(tmp_path_factory.mktemp("durable"))
    dcfg = DurabilityConfig(state_dir=state_dir, checkpoint_every_rounds=5)
    engine = GrapevineEngine(SMALL, seed=3, durability=dcfg)
    _drive(engine, 12)
    final = cp.state_to_bytes(engine.ecfg, engine.state)
    seq = engine.durability.seq
    ckpt_seq = engine.durability.ckpt_seq
    engine.close()
    assert ckpt_seq > 0, "cadence never checkpointed"
    assert seq > ckpt_seq, "fixture needs a journal tail to replay"
    return state_dir, final, seq


def _copy_dir(src: str, tmp_path) -> str:
    dst = str(tmp_path / "statedir")
    shutil.copytree(src, dst)
    return dst


def test_checkpoint_restore_state_bit_equality(durable_run, tmp_path):
    """The acceptance fast test: recovered state (checkpoint + replayed
    journal tail) is bit-identical to the uninterrupted engine's."""
    state_dir, final, seq = durable_run
    d = _copy_dir(state_dir, tmp_path)
    engine = GrapevineEngine(
        SMALL, seed=3,
        durability=DurabilityConfig(state_dir=d, checkpoint_every_rounds=4),
    )
    assert engine.durability.recovered_from_checkpoint
    assert engine.durability.replayed > 0, "journal tail was not replayed"
    assert engine.durability.seq == seq
    assert cp.state_to_bytes(engine.ecfg, engine.state) == final
    st = engine.durability.status()
    assert st["last_checkpoint_seq"] > 0
    assert st["last_durable_seq"] == seq
    engine.close()


@pytest.mark.slow  # a full replay = one more ~8 s jit compile; the
# property is also implied by the core test + the chaos suite
def test_recovery_with_wrong_seed_still_bit_identical(durable_run, tmp_path):
    """The recovered state comes from disk, not from the init seed —
    restoring under a different seed must not matter."""
    state_dir, final, _ = durable_run
    d = _copy_dir(state_dir, tmp_path)
    engine = GrapevineEngine(
        SMALL, seed=999,
        durability=DurabilityConfig(state_dir=d, checkpoint_every_rounds=4),
    )
    assert cp.state_to_bytes(engine.ecfg, engine.state) == final
    engine.close()


@pytest.mark.slow  # another full-replay jit compile; the torn-tail
# contract itself is tier-1-covered (no-compile) in test_checkpoint.py
def test_torn_journal_tail_recovers_to_previous_record(durable_run, tmp_path):
    """Truncating mid-way into the journal's final frame loses exactly
    that record (it never dispatched durably) — recovery succeeds at
    seq-1 and never half-applies the torn frame."""
    state_dir, _, seq = durable_run
    d = _copy_dir(state_dir, tmp_path)
    segs = [n for n in os.listdir(d) if n.endswith(".wal")]
    assert len(segs) == 1
    path = os.path.join(d, segs[0])
    size = os.path.getsize(path)
    with open(path, "r+b") as fh:
        fh.truncate(size - 30)  # inside the final frame's tag
    engine = GrapevineEngine(
        SMALL, seed=3,
        durability=DurabilityConfig(state_dir=d, checkpoint_every_rounds=4),
    )
    assert engine.durability.seq == seq - 1
    engine.close()


def test_corrupt_checkpoint_rejected_never_half_loaded(durable_run, tmp_path):
    state_dir, _, _ = durable_run
    d = _copy_dir(state_dir, tmp_path)
    ckpt = next(n for n in os.listdir(d) if n.startswith("ckpt-"))
    path = os.path.join(d, ckpt)
    blob = bytearray(open(path, "rb").read())
    blob[len(blob) // 2] ^= 0x10
    with open(path, "wb") as fh:
        fh.write(bytes(blob))
    with pytest.raises(cp.CheckpointError, match="integrity"):
        GrapevineEngine(
            SMALL, seed=3,
            durability=DurabilityConfig(state_dir=d,
                                        checkpoint_every_rounds=4),
        )


def test_wrong_root_key_rejected(durable_run, tmp_path):
    state_dir, _, _ = durable_run
    d = _copy_dir(state_dir, tmp_path)
    with open(os.path.join(d, "root.key"), "wb") as fh:
        fh.write(b"\x42" * 32)
    with pytest.raises(cp.CheckpointError, match="integrity|root key"):
        GrapevineEngine(
            SMALL, seed=3,
            durability=DurabilityConfig(state_dir=d,
                                        checkpoint_every_rounds=4),
        )


def test_geometry_change_rejected(durable_run, tmp_path):
    state_dir, _, _ = durable_run
    d = _copy_dir(state_dir, tmp_path)
    bigger = GrapevineConfig(
        max_messages=128, max_recipients=8, mailbox_cap=4,
        batch_size=4, stash_size=64, bucket_cipher_rounds=0,
    )
    with pytest.raises(cp.CheckpointError, match="fingerprint"):
        GrapevineEngine(
            bigger, seed=3,
            durability=DurabilityConfig(state_dir=d,
                                        checkpoint_every_rounds=4),
        )


# -- graceful drain (scheduler close settles, never drops) --------------


class _StubEcfg:
    batch_size = 4


class _ZeroResponses:
    @staticmethod
    def make(n):
        zero = Record(
            msg_id=C.ZERO_MSG_ID, sender=C.ZERO_PUBKEY,
            recipient=C.ZERO_PUBKEY, timestamp=0,
            payload=b"\x00" * C.PAYLOAD_SIZE,
        )
        return [QueryResponse(record=zero, status_code=C.STATUS_CODE_SUCCESS)
                for _ in range(n)]


class _WedgedEngine:
    """Rounds wedge on resolve until released; ``settling`` fires when
    the collector has actually entered resolve() — the moment later
    submits are guaranteed to stay queued rather than dispatch."""

    def __init__(self):
        self.ecfg = _StubEcfg()
        self.metrics = EngineMetrics()
        self.release = threading.Event()
        self.settling = threading.Event()

    def handle_queries_async(self, reqs, now):
        resps = _ZeroResponses.make(len(reqs))
        release, settling = self.release, self.settling

        class _Pending:
            def resolve(self):
                settling.set()
                release.wait(timeout=30)
                return resps

        return _Pending()


def _submit_async(sched, results, idx):
    def run():
        try:
            results[idx] = sched.submit(_req(C.REQUEST_TYPE_READ, _key(1)))
        except BaseException as exc:  # noqa: BLE001 - recorded for asserts
            results[idx] = exc

    t = threading.Thread(target=run)
    t.start()
    return t


def test_close_settles_queued_ops_with_shutdown_error():
    eng = _WedgedEngine()
    sched = BatchScheduler(eng, max_wait_ms=30.0, idle_gap_ms=5.0)
    results: dict = {}
    try:
        t0 = _submit_async(sched, results, 0)  # dispatches, wedges
        assert eng.settling.wait(timeout=10), "round never reached resolve"
        # these arrive while the collector is blocked settling the
        # wedged round: queued, not yet dispatched when close() lands
        t1 = _submit_async(sched, results, 1)
        t2 = _submit_async(sched, results, 2)
        time.sleep(0.2)
        closer = threading.Thread(target=sched.close)
        closer.start()
        for t in (t1, t2):
            t.join(timeout=10)
        assert isinstance(results[1], SchedulerShutdown)
        assert isinstance(results[2], SchedulerShutdown)
        # the in-flight round still commits: drain settles, not drops
        eng.release.set()
        t0.join(timeout=10)
        closer.join(timeout=10)
        assert isinstance(results[0], QueryResponse)
        with pytest.raises(SchedulerShutdown):
            sched.submit(_req(C.REQUEST_TYPE_READ, _key(1)))
    finally:
        eng.release.set()
        sched.close()


# -- worker crash handling ----------------------------------------------


class _WorkerDeath(BaseException):
    """Escapes the dispatch path's ``except Exception`` defensive guard
    — the genuine worker-killing fault class (a bug in the collector
    itself, a KeyboardInterrupt, an interpreter-level error)."""


class _CrashOnceEngine:
    def __init__(self, crashes: int = 1):
        self.ecfg = _StubEcfg()
        self.metrics = EngineMetrics()
        self.crashes_left = crashes

    def handle_queries_async(self, reqs, now):
        if self.crashes_left:
            self.crashes_left -= 1
            raise _WorkerDeath("injected collector fault")

        resps = _ZeroResponses.make(len(reqs))

        class _Pending:
            def resolve(self):
                return resps

        return _Pending()


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
def test_worker_crash_counts_and_flips_alive():
    eng = _CrashOnceEngine(crashes=1)
    sched = BatchScheduler(eng, max_wait_ms=20.0, idle_gap_ms=5.0)
    with pytest.raises(RuntimeError, match="worker died"):
        sched.submit(_req(C.REQUEST_TYPE_READ, _key(1)))
    deadline = time.monotonic() + 5
    while sched.worker_alive() and time.monotonic() < deadline:
        time.sleep(0.01)
    # the healthz signal (worker_alive → unhealthy) flips immediately...
    assert not sched.worker_alive()
    # ...and the crash is counted on the telemetry registry
    assert eng.metrics.registry.get("grapevine_worker_crash_total").get() == 1
    with pytest.raises(SchedulerShutdown):
        sched.submit(_req(C.REQUEST_TYPE_READ, _key(1)))


def test_worker_restart_revives_collector():
    eng = _CrashOnceEngine(crashes=1)
    sched = BatchScheduler(eng, max_wait_ms=20.0, idle_gap_ms=5.0,
                           restart_on_crash=True)
    try:
        with pytest.raises(RuntimeError, match="worker died"):
            sched.submit(_req(C.REQUEST_TYPE_READ, _key(1)))
        # supervised restart: the collector revives and serves again
        deadline = time.monotonic() + 5
        resp = None
        while time.monotonic() < deadline:
            try:
                resp = sched.submit(_req(C.REQUEST_TYPE_READ, _key(1)))
                break
            except SchedulerShutdown:
                time.sleep(0.02)
        assert isinstance(resp, QueryResponse)
        assert sched.worker_alive()
        assert (
            eng.metrics.registry.get("grapevine_worker_crash_total").get()
            == 1
        )
    finally:
        sched.close()


# -- engine-tier stub: deadline + bounded UNAVAILABLE retry -------------


def test_engine_stub_retries_unavailable_only():
    from grapevine_tpu.obs import TelemetryRegistry
    from grapevine_tpu.server.tier import _EngineStub

    # an address nothing listens on: immediate UNAVAILABLE per attempt
    stub = _EngineStub("127.0.0.1:1", deadline_s=2.0, max_retries=2,
                       backoff_s=0.01, backoff_cap_s=0.02)
    reg = TelemetryRegistry()
    stub.bind_registry(reg)
    t0 = time.monotonic()
    with pytest.raises(grpc.RpcError) as exc_info:
        stub.submit(_req(C.REQUEST_TYPE_READ, _key(1)))
    assert exc_info.value.code() == grpc.StatusCode.UNAVAILABLE
    assert time.monotonic() - t0 < 30
    assert reg.get("grapevine_engine_rpc_retries_total").get() == 2
    stub.close()


def test_engine_tier_drain_maps_to_unavailable_and_health_surfaces():
    pytest.importorskip("grpc")
    from grapevine_tpu.server.tier import EngineServer, _EngineStub

    import tempfile

    with tempfile.TemporaryDirectory() as d:
        server = EngineServer(
            SMALL, seed=0,
            durability=DurabilityConfig(state_dir=d,
                                        checkpoint_every_rounds=8),
        )
        port = server.start("127.0.0.1:0")
        healthy, detail = server.healthz()
        assert healthy
        assert detail["durability"]["last_checkpoint_seq"] == 0
        assert detail["durability"]["last_durable_seq"] == 0
        # drain: close the scheduler, then submits map to UNAVAILABLE
        server.scheduler.close()
        stub = _EngineStub(f"127.0.0.1:{port}", deadline_s=5.0,
                           max_retries=1, backoff_s=0.01)
        with pytest.raises(grpc.RpcError) as exc_info:
            stub.submit(_req(C.REQUEST_TYPE_READ, _key(1)))
        assert exc_info.value.code() == grpc.StatusCode.UNAVAILABLE
        stub.close()
        server.stop(checkpoint=True)
        # the final drain checkpoint sealed the (untouched) state
        assert any(n.startswith("ckpt-") for n in os.listdir(d))


# -- CLI role matrix for the durability flags ---------------------------


@pytest.mark.parametrize("argv", [
    ["--role", "frontend", "--engine", "h:1", "--state-dir", "/tmp/x"],
    ["--role", "frontend", "--engine", "h:1",
     "--journal-fsync-every", "4"],
    ["--role", "frontend", "--engine", "h:1",
     "--checkpoint-every-rounds", "8"],
])
def test_frontend_rejects_durability_flags(argv):
    from grapevine_tpu.server import cli

    parser = cli.build_parser()
    args = parser.parse_args(argv)
    with pytest.raises(SystemExit, match="does not take"):
        cli._reject_misapplied_flags(parser, args, argv)


@pytest.mark.parametrize("argv", [
    ["--role", "mono", "--state-dir", "/tmp/x", "--journal-fsync-every",
     "4", "--worker-restart"],
    ["--role", "engine", "--state-dir", "/tmp/x",
     "--checkpoint-every-rounds", "16", "--seal-key-file", "/tmp/k"],
])
def test_device_roles_accept_durability_flags(argv):
    from grapevine_tpu.server import cli

    parser = cli.build_parser()
    args = parser.parse_args(argv)
    cli._reject_misapplied_flags(parser, args, argv)  # no raise


def test_durability_config_validation():
    with pytest.raises(ValueError):
        DurabilityConfig(state_dir="")
    with pytest.raises(ValueError):
        DurabilityConfig(state_dir="/tmp/x", checkpoint_every_rounds=0)
    with pytest.raises(ValueError):
        DurabilityConfig(state_dir="/tmp/x", journal_fsync_every=0)
