"""Flat↔recursive↔oracle position-map equivalence (PR 7 tentpole).

The contract of ``GrapevineConfig.posmap_impl="recursive"``
(oram/posmap.py), following the PR-3/PR-5 selectable-impl playbook:

1. responses AND the final payload-facing engine state bit-identical to
   the flat map — randomized oracle campaigns over same-key-chain-heavy
   mixes, saturation fallback, single-op batches (and batch_size=1
   geometry under ``-m slow``), with the logical position table proven
   equal through every round via the test-only ``read_table`` view;
2. the leak monitor stays PASS with the recursive map's internal
   accesses included in the transcript (the appended ``*_pm`` columns /
   streams);
3. a flat checkpoint can never silently restore into a recursive
   engine, nor the reverse — the geometry fingerprint covers the
   posmap spec (the ISSUE-7 small-fix satellite);
4. crash recovery stays bit-identical with ``posmap_impl="recursive"``
   (chaos kill trials under ``-m slow``).

Always-on cost is one flat + one recursive engine compile (plaintext,
reused across every always-on assertion below, per the ROADMAP 5-8 s
rule); cipher pairs, regime breadth, and chaos ride ``-m slow``.
"""

from __future__ import annotations

import os
import random
import sys

import numpy as np
import pytest

from test_vphases_scan import (
    BASE,
    NOW,
    SAT_BUS,
    SAT_RECIP,
    _assert_responses_bitequal,
    _campaign_plan,
    _gen_batch,
    key,
    req,
)

from grapevine_tpu.config import GrapevineConfig
from grapevine_tpu.engine.batcher import GrapevineEngine
from grapevine_tpu.oram.posmap import read_table
from grapevine_tpu.testing.reference import ReferenceEngine
from grapevine_tpu.wire import constants as C

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: payload-facing OramState fields — everything except the posmap pytree
#: and the (recursive-only) leaf-metadata planes, whose *logical* content
#: is compared via read_table instead
_TREE_FIELDS = ("tree_idx", "tree_val", "stash_idx", "stash_val",
                "overflow", "nonces", "cipher_key", "epoch")
_SCALAR_FIELDS = ("freelist", "free_top", "recipients", "seq",
                  "hash_key", "id_key", "rng")


def _mk_posmap_pair(cfg_kwargs, seed):
    flat = GrapevineEngine(
        GrapevineConfig(posmap_impl="flat", **cfg_kwargs), seed=seed
    )
    rec = GrapevineEngine(
        GrapevineConfig(posmap_impl="recursive", **cfg_kwargs), seed=seed
    )
    return flat, rec


def _assert_payload_state_bitequal(ef, er, ctx=""):
    """Final-state contract: every payload-facing leaf equal bitwise;
    the position maps equal as logical tables."""
    for tree in ("rec", "mb"):
        of, orc = getattr(ef.state, tree), getattr(er.state, tree)
        for f in _TREE_FIELDS:
            assert np.array_equal(
                np.asarray(getattr(of, f)), np.asarray(getattr(orc, f))
            ), f"{ctx}: {tree}.{f} diverges flat vs recursive"
        cfg = getattr(ef.ecfg, tree)
        rcfg = getattr(er.ecfg, tree)
        assert np.array_equal(
            np.asarray(of.posmap)[: cfg.blocks], read_table(rcfg, orc.posmap)
        ), f"{ctx}: {tree} logical position table diverges"
        assert int(orc.posmap.inner.overflow) == 0, (
            f"{ctx}: internal posmap ORAM overflowed"
        )
    for f in _SCALAR_FIELDS:
        assert np.array_equal(
            np.asarray(getattr(ef.state, f)), np.asarray(getattr(er.state, f))
        ), f"{ctx}: {f} diverges"


def _run_pm_campaign(cfg_kwargs, seed, n_batches=3, batch_fill=None,
                     pair=None, sweep=False):
    """One campaign: flat/recursive pair + oracle over mixed batches.

    ``pair`` reuses already-compiled engines (fresh engines otherwise);
    reusing keeps the always-on cost at one compile per impl."""
    rng = np.random.default_rng(seed)
    ef, er = pair or _mk_posmap_pair(
        cfg_kwargs, seed=int(rng.integers(1 << 30))
    )
    oracle = None
    if pair is None:
        oracle = ReferenceEngine(
            config=GrapevineConfig(**cfg_kwargs), rng=random.Random(seed)
        )
    idents = [key(i) for i in range(1, 1 + int(rng.integers(2, 6)))]
    live_ids: list[tuple[bytes, bytes]] = []
    bs = cfg_kwargs["batch_size"]
    for bi in range(n_batches):
        n = batch_fill or int(rng.integers(1, bs + 1))
        reqs = _gen_batch(rng, idents, live_ids, n)
        t = NOW + bi
        rf = ef.handle_queries(reqs, t)
        rr = er.handle_queries(reqs, t)
        _assert_responses_bitequal(rf, rr, f"posmap seed {seed} batch {bi}")
        if oracle is not None:
            forced = [
                d.record.msg_id
                if r.request_type == C.REQUEST_TYPE_CREATE
                and d.status_code == C.STATUS_CODE_SUCCESS
                else None
                for r, d in zip(reqs, rf)
            ]
            ro = oracle.handle_batch(reqs, t, forced)
            for j, (d, o) in enumerate(zip(rf, ro)):
                assert d.status_code == o.status_code, (
                    f"posmap seed {seed} batch {bi} slot {j}: engine "
                    f"{d.status_code} != oracle {o.status_code}"
                )
                assert d.record.msg_id == o.record.msg_id
                assert d.record.payload == o.record.payload
            assert ef.message_count() == oracle.message_count()
            assert ef.recipient_count() == oracle.recipient_count()
        for r, d in zip(reqs, rf):
            if (r.request_type == C.REQUEST_TYPE_CREATE
                    and d.status_code == C.STATUS_CODE_SUCCESS):
                live_ids.append((d.record.msg_id, r.record.recipient))
            elif (r.request_type == C.REQUEST_TYPE_DELETE
                    and d.status_code == C.STATUS_CODE_SUCCESS):
                live_ids = [
                    (m, o_) for m, o_ in live_ids if m != d.record.msg_id
                ]
    if sweep:
        ef.expire(NOW + 10_000, 5_000)
        er.expire(NOW + 10_000, 5_000)
    _assert_payload_state_bitequal(ef, er, f"posmap seed {seed}")
    return ef, er


# -- always-on: one compiled pair carries every fast assertion ----------


def test_posmap_ab_campaign_with_sweep_leakmon_and_single_op():
    """The budget-shaped always-on path: ONE flat + ONE recursive engine
    (plaintext BASE geometry) run a randomized oracle campaign, then an
    expiry sweep, then single-op (dummy-padded) batches, then a leakmon
    soak — every stage asserting bit-identity, with zero additional
    compiles after the first round."""
    ef, er = _run_pm_campaign(BASE, seed=4100, n_batches=4, sweep=True)

    # single-op batches on the same compiled pair (fill=1 → 7 dummies)
    _run_pm_campaign(BASE, seed=4101, n_batches=2, batch_fill=1,
                     pair=(ef, er))

    # leak monitor with the internal accesses in the transcript: the
    # recursive engine's verdict must be PASS and the pm streams must
    # actually be observing (window fills)
    from grapevine_tpu.obs.leakmon import EngineLeakMonitor, LeakMonitorConfig

    mon = EngineLeakMonitor.for_engine(
        er, LeakMonitorConfig(window_rounds=64)
    )
    assert set(mon.monitor.streams) == {"rec", "mb", "rec_pm", "mb_pm"}
    er.attach_leakmon(mon)
    rng = np.random.default_rng(77)
    idents = [key(i) for i in range(1, 5)]
    live: list[tuple[bytes, bytes]] = []
    for bi in range(12):
        reqs = _gen_batch(rng, idents, live, 8)
        er.handle_queries(reqs, NOW + 100 + bi)
    assert mon.flush(), "leak monitor did not drain"
    v = mon.verdict()
    assert v["verdict"] == "PASS", v
    pm_stats = mon.monitor.stats("rec_pm")
    assert pm_stats["pooled_leaves"] > 0, "rec_pm stream saw no leaves"
    mon.close()


def test_posmap_checkpoint_fingerprint_rejects_cross_impl(tmp_path):
    """ISSUE-7 small fix: a flat checkpoint must fail loudly against a
    recursive engine (and vice versa) — the geometry fingerprint covers
    ``posmap_impl`` and the recursion geometry via the embedded
    PosMapSpec, so the mismatch is a CheckpointError, never a silent
    misload. Pure serialization — no engine compile."""
    from grapevine_tpu.engine.checkpoint import (
        CheckpointError,
        bytes_to_state,
        engine_fingerprint,
        state_to_bytes,
    )
    from grapevine_tpu.engine.state import EngineConfig, init_engine

    kw = dict(BASE, max_messages=32, batch_size=4)
    ecf = EngineConfig.from_config(GrapevineConfig(posmap_impl="flat", **kw))
    ecr = EngineConfig.from_config(
        GrapevineConfig(posmap_impl="recursive", **kw)
    )
    assert engine_fingerprint(ecf) != engine_fingerprint(ecr)
    blob_f = state_to_bytes(ecf, init_engine(ecf, seed=1))
    blob_r = state_to_bytes(ecr, init_engine(ecr, seed=1))
    assert bytes_to_state(ecf, blob_f) is not None  # control: self-loads
    with pytest.raises(CheckpointError, match="fingerprint"):
        bytes_to_state(ecr, blob_f)  # flat ckpt → recursive engine
    with pytest.raises(CheckpointError, match="fingerprint"):
        bytes_to_state(ecf, blob_r)  # recursive ckpt → flat engine

    # recursion geometry is fingerprinted too, not just the impl name:
    # same impl, different k must also refuse
    from dataclasses import replace

    from grapevine_tpu.oram.posmap import derive_posmap_spec

    spec2 = derive_posmap_spec(32, entries_per_block=2)
    ecr2 = replace(ecr, rec=replace(ecr.rec, posmap=spec2))
    assert engine_fingerprint(ecr2) != engine_fingerprint(ecr)
    with pytest.raises(CheckpointError, match="fingerprint"):
        bytes_to_state(ecr2, blob_r)


def test_posmap_impl_validation():
    with pytest.raises(ValueError, match="posmap_impl"):
        GrapevineConfig(posmap_impl="pyramid")
    with pytest.raises(ValueError, match="posmap_impl"):
        GrapevineConfig(commit="op", posmap_impl="recursive")
    # auto resolves to flat (until a measured win flips it — PERF.md R9)
    from grapevine_tpu.engine.state import EngineConfig

    ecfg = EngineConfig.from_config(GrapevineConfig(**BASE))
    assert ecfg.posmap_impl == "flat"
    assert ecfg.rec.posmap is None and ecfg.mb.posmap is None


# -- slow: breadth, cipher, regimes, batch_size=1 geometry, chaos -------


@pytest.mark.slow
def test_randomized_posmap_ab_campaigns_full():
    """Regime breadth: steady-state, bus/recipient saturation fallback,
    single-op batches — fresh pairs + oracle per campaign."""
    n = int(os.environ.get("GRAPEVINE_POSMAP_CAMPAIGNS", "20"))
    for i, (cfg, fill) in enumerate(_campaign_plan(n)):
        _run_pm_campaign(cfg, seed=4200 + i, batch_fill=fill)


@pytest.mark.slow
def test_posmap_ab_campaign_cipher_on():
    """The at-rest cipher pair: the leaf-metadata plane's ride on the
    bucket cipher (decrypt/re-encrypt per fetch, epoch re-key in the
    expiry sweep) must preserve bit-identity end to end."""
    cfg = dict(BASE, bucket_cipher_rounds=8)
    _run_pm_campaign(cfg, seed=4300, n_batches=4, sweep=True)


@pytest.mark.slow
def test_posmap_ab_campaign_scan_radix():
    """The recursive lookup's dedup glue follows the engine's
    vphases/sort knobs (the no-[B,B] audit holds through the posmap) —
    the scan+radix pair must stay bit-identical too."""
    cfg = dict(BASE, vphases_impl="scan", sort_impl="radix")
    _run_pm_campaign(cfg, seed=4400, n_batches=3)


@pytest.mark.slow
def test_posmap_single_op_batch_geometry():
    """batch_size=1 end to end: the recursive lookup round at B=1
    (degenerate dedup segments) stays bit-identical and oracle-true."""
    cfg = dict(BASE, batch_size=1)
    for i in range(3):
        _run_pm_campaign(cfg, seed=4500 + i, n_batches=6, batch_fill=1)


@pytest.mark.slow
def test_posmap_saturation_fallback_bitequal():
    """Bus saturation: rounds resolve through _admission_slow with the
    recursive map in the loop and must stay bit-identical, including
    TOO_MANY_MESSAGES admission order."""
    ef, er = _mk_posmap_pair(SAT_BUS, seed=9)
    a, x = key(1), key(2)
    for bi in range(3):
        reqs = [
            req(C.REQUEST_TYPE_CREATE, a, recipient=x, tag=bi * 8 + j)
            for j in range(8)
        ]
        rf = ef.handle_queries(reqs, NOW + bi)
        rr = er.handle_queries(reqs, NOW + bi)
        _assert_responses_bitequal(rf, rr, f"sat batch {bi}")
    codes = {r.status_code for r in rf}
    assert C.STATUS_CODE_TOO_MANY_MESSAGES in codes
    _assert_payload_state_bitequal(ef, er, "saturation")
    # recipient-table saturation regime as well
    _run_pm_campaign(SAT_RECIP, seed=4600, n_batches=3)


@pytest.mark.slow
def test_chaos_recovery_with_recursive_posmap():
    """SIGKILL trials with posmap_impl='recursive': recovered state and
    every response hash bit-identical to the uninterrupted oracle, leak
    monitor PASS across recovery (tools/chaos_run.py --posmap-impl)."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import chaos_run

    args = chaos_run.parse_args(
        ["--events", "14", "--posmap-impl", "recursive", "--seed", "41"]
    )
    failures = chaos_run.run_trials(3, args)
    assert not failures, "\n".join(failures)
