"""Generator determinism and schedule shape (ISSUE 9 satellite).

Three properties, per generator:

- **determinism**: the same seed materializes the bit-identical
  schedule (fingerprint equality), a different seed a different one —
  a load scenario is a reproducible experiment, not a vibe;
- **shape**: the empirical arrival envelope matches the generator's
  *declared* one (bursty silence in OFF windows, diurnal peak/trough
  contrast, pop-heavy recipient concentration, ramp staircase
  monotonicity) — a generator whose output does not look like its name
  would silently invalidate every capacity number taken through it;
- **open-loop**: schedules are pure functions of (params, seed) with
  no completion-time input anywhere in the signature, and the replay
  harness (tested in test_load_harness.py) never mutates them.

Pure numpy — no engine, no jax, milliseconds in tier-1.
"""

import inspect

import numpy as np
import pytest

from grapevine_tpu.load import generators as G
from grapevine_tpu.wire import constants as C

ALL_GENERATORS = {
    "steady": lambda seed: G.steady_poisson(200.0, 4.0, seed),
    "bursty": lambda seed: G.bursty_onoff(400.0, 0.25, 1.0, 4.0, seed),
    "diurnal": lambda seed: G.diurnal_sinusoid(200.0, 0.8, 2.0, 4.0, seed),
    "pop_heavy": lambda seed: G.pop_heavy_drain(200.0, 4.0, seed),
    "adversarial": lambda seed: G.adversarial_probe(0.05, 4.0, seed),
    "ramp": lambda seed: G.ramp_to_saturation(50.0, 2.0, 4, 1.0, seed),
}


@pytest.mark.parametrize("name", sorted(ALL_GENERATORS))
def test_same_seed_same_schedule(name):
    gen = ALL_GENERATORS[name]
    a, b = gen(7), gen(7)
    assert a.fingerprint() == b.fingerprint()
    assert np.array_equal(a.t_s, b.t_s)
    assert np.array_equal(a.kind, b.kind)
    assert np.array_equal(a.auth, b.auth)
    assert np.array_equal(a.recipient, b.recipient)
    assert gen(8).fingerprint() != a.fingerprint()


@pytest.mark.parametrize("name", sorted(ALL_GENERATORS))
def test_schedule_is_well_formed(name):
    s = ALL_GENERATORS[name](3)
    assert s.n_ops > 0
    assert np.all(np.diff(s.t_s) >= 0), "arrivals must be sorted"
    assert s.t_s[0] >= 0 and s.t_s[-1] <= s.duration_s
    assert set(np.unique(s.kind)) <= {
        C.REQUEST_TYPE_CREATE, C.REQUEST_TYPE_READ, C.REQUEST_TYPE_DELETE
    }
    n_id = s.meta["n_idents"]
    assert int(s.auth.max()) < n_id and int(s.recipient.max()) < n_id


@pytest.mark.parametrize("name", sorted(ALL_GENERATORS))
def test_open_loop_signature(name):
    """No generator takes any completion/latency/feedback input: the
    schedule cannot depend on how the server fares — the structural
    half of the open-loop property (the behavioral half is the
    harness replay test)."""
    fn = {
        "steady": G.steady_poisson, "bursty": G.bursty_onoff,
        "diurnal": G.diurnal_sinusoid, "pop_heavy": G.pop_heavy_drain,
        "adversarial": G.adversarial_probe, "ramp": G.ramp_to_saturation,
    }[name]
    params = set(inspect.signature(fn).parameters)
    forbidden = {"latency", "latencies", "completions", "responses",
                 "feedback", "engine", "scheduler", "clock"}
    assert not (params & forbidden), (
        f"{name} takes completion-side input {params & forbidden} — "
        "that is a closed loop"
    )


def test_steady_rate_matches_declared():
    s = G.steady_poisson(500.0, 8.0, 5)
    # Poisson(4000) total count: 5 sigma ≈ 316
    assert abs(s.n_ops - 4000) < 320
    rates = s.empirical_rate(8)
    assert np.all(rates > 250) and np.all(rates < 750)


def test_bursty_off_windows_are_silent():
    s = G.bursty_onoff(800.0, 0.25, 1.0, 4.0, 5)
    phase = np.mod(s.t_s, 1.0)
    assert np.all(phase <= 0.25 + 1e-9), "arrivals outside ON windows"
    # mean rate ≈ rate_on * duty
    assert abs(s.offered_rate - 200.0) < 60.0
    # peak-to-mean contrast is the declared 1/duty
    rates = s.empirical_rate(16)  # 4 bins per period, 1 ON per period
    assert rates.max() > 3.0 * max(1e-9, np.median(rates + 1e-9))


def test_diurnal_peak_trough_contrast():
    s = G.diurnal_sinusoid(400.0, 0.9, 4.0, 8.0, 5)
    # bin phases against the declared sinusoid: peak quarter vs trough
    phase = np.mod(s.t_s, 4.0) / 4.0
    peak = np.sum((phase >= 0.125) & (phase < 0.375))   # around sin max
    trough = np.sum((phase >= 0.625) & (phase < 0.875))  # around sin min
    assert peak > 4 * max(1, trough), (peak, trough)
    # total mass still ≈ mean_rate * duration
    assert abs(s.n_ops - 3200) < 450


def test_pop_heavy_concentration_and_drains():
    s = G.pop_heavy_drain(400.0, 8.0, 5, n_idents=64, n_hot=4,
                          hot_frac=0.75, drain_frac=0.4)
    creates = s.kind == C.REQUEST_TYPE_CREATE
    drains = ~creates
    # ~75% of CREATEs land on the 4 hot recipients (vs 6% uniform)
    hot_share = np.mean(s.recipient[creates] < 4)
    assert hot_share > 0.6, hot_share
    # drains are issued BY hot identities popping their own inboxes
    assert np.all(s.auth[drains] < 4)
    assert 0.25 < np.mean(drains) < 0.55
    drain_kinds = set(np.unique(s.kind[drains]))
    assert drain_kinds <= {C.REQUEST_TYPE_READ, C.REQUEST_TYPE_DELETE}


def test_adversarial_probe_shape():
    s = G.adversarial_probe(0.1, 2.0, 5, n_probe_keys=4,
                            probes_per_pulse=3)
    # tiny key set, READ-only, every key probed in every pulse
    assert set(np.unique(s.auth)) == {0, 1, 2, 3}
    assert np.all(s.kind == C.REQUEST_TYPE_READ)
    assert s.n_ops == 20 * 4 * 3
    # pulses are tight: every op lands within ~1ms of its pulse start
    assert np.all(np.mod(s.t_s, 0.1) < 2e-3)


def test_ramp_staircase_is_monotone_and_declared():
    s = G.ramp_to_saturation(100.0, 2.0, 4, 2.0, 5)
    steps = s.meta["steps"]
    declared = [st["offered_rate"] for st in steps]
    assert declared == [100.0, 200.0, 400.0, 800.0]
    empirical = []
    for st in steps:
        n = np.sum((s.t_s >= st["t0"]) & (s.t_s < st["t1"]))
        empirical.append(n / (st["t1"] - st["t0"]))
    # each step's realized rate is within 5 sigma of its declared one
    for emp, dec in zip(empirical, declared):
        assert abs(emp - dec) < 5.0 * np.sqrt(dec / 2.0) + 1e-9
    assert np.all(np.diff(empirical) > 0), "staircase must ascend"


def test_malformed_parameters_raise():
    with pytest.raises(ValueError):
        G.bursty_onoff(100.0, 1.5, 1.0, 4.0, 0)  # duty > 1
    with pytest.raises(ValueError):
        G.diurnal_sinusoid(100.0, 1.5, 1.0, 4.0, 0)  # amplitude >= 1
    with pytest.raises(ValueError):
        G.ramp_to_saturation(100.0, 0.5, 4, 1.0, 0)  # shrinking ramp
    with pytest.raises(ValueError):
        G.pop_heavy_drain(100.0, 4.0, 0, n_idents=4, n_hot=4)  # all hot
