"""SLO-adaptive + flush-aware round collection (server/adaptive.py,
server/scheduler.py).

The policy's contract: every decision is a function of PUBLIC load
aggregates — queue depth (an integer), the arrival-rate EWMA, the SLO
burn rates, and the round-counter flush cadence. The unit tests pin
each decision kind; the scheduler tests prove the decisions actually
shape the collection window; the obliviousness teeth live in
test_oblint.py (the seeded adaptive_batch_from_contents mutant must
FAIL the analyzer).

Uses the stub-engine pattern from test_scheduler.py (no JAX) with
generous timing margins for a single-core host.
"""

import threading
import time

import pytest

from grapevine_tpu.engine.metrics import EngineMetrics
from grapevine_tpu.obs import TelemetryRegistry
from grapevine_tpu.server.adaptive import (
    DECISION_KINDS,
    AdaptiveBatchConfig,
    AdaptiveBatchPolicy,
)
from grapevine_tpu.server.scheduler import BatchScheduler
from grapevine_tpu.wire import constants as C
from grapevine_tpu.wire.records import QueryRequest, QueryResponse, Record


class _FakeWorkload:
    def __init__(self, rate):
        self.rate = rate

    def arrival_rate(self):
        return self.rate


class _FakeSlo:
    def __init__(self, fast_burn=0.0, fast_rounds=0):
        self.fast_burn = fast_burn
        self.fast_rounds = fast_rounds

    def burn_rates(self):
        return {
            "fast_burn_rate": self.fast_burn,
            "slow_burn_rate": 0.0,
            "fast_rounds": self.fast_rounds,
            "slow_rounds": self.fast_rounds,
        }


def _policy(bs=16, base_ms=8.0, gap_ms=2.0, **kw):
    return AdaptiveBatchPolicy(bs, base_ms / 1000.0, gap_ms / 1000.0, **kw)


# -- config validation -------------------------------------------------


def test_config_rejects_zero_floor():
    with pytest.raises(ValueError):
        AdaptiveBatchConfig(floor_wait_ms=0.0)


def test_config_rejects_shrinking_ceil():
    with pytest.raises(ValueError):
        AdaptiveBatchConfig(ceil_factor=0.5)


def test_policy_rejects_bad_batch_size():
    with pytest.raises(ValueError):
        _policy(bs=0)


# -- the four decision kinds -------------------------------------------


def test_fill_dispatches_at_floor_when_queue_is_full():
    pol = _policy(bs=8, base_ms=50.0)
    wait, gap, target = pol.decide(8)
    assert wait == pytest.approx(pol.cfg.floor_wait_ms / 1000.0)
    assert target == 8
    assert gap <= wait


def test_shed_under_fast_burn_with_evidence():
    pol = _policy(bs=8, base_ms=50.0,
                  workload=_FakeWorkload(500.0),
                  slo=_FakeSlo(fast_burn=3.0, fast_rounds=64))
    wait, _gap, target = pol.decide(3)
    assert wait == pytest.approx(pol.cfg.floor_wait_ms / 1000.0)
    assert target == 3  # dispatch what's queued, don't hold for a fill


def test_shed_needs_min_rounds_of_evidence():
    # a scorching burn rate over 2 rounds is noise, not overload — the
    # policy must not flinch into tiny rounds on startup transients
    pol = _policy(bs=8, base_ms=50.0,
                  workload=_FakeWorkload(500.0),
                  slo=_FakeSlo(fast_burn=9.0, fast_rounds=2))
    _wait, _gap, target = pol.decide(3)
    assert target == 8  # cruise (rate is high), not shed


def test_sparse_lone_client_commits_at_floor():
    # EWMA expects < 1 arrival inside the base window: stretching buys
    # nothing, a lone op should not sit out the full wait
    pol = _policy(bs=8, base_ms=50.0, workload=_FakeWorkload(1.0))
    wait, _gap, target = pol.decide(1)
    assert wait == pytest.approx(pol.cfg.floor_wait_ms / 1000.0)
    assert target == 1


def test_cruise_stretches_toward_full_round():
    # 400 ops/s, 7 more needed -> t_full = 17.5ms: above base 8ms,
    # below the 32ms ceiling — the window stretches to exactly t_full
    pol = _policy(bs=8, base_ms=8.0, workload=_FakeWorkload(400.0))
    wait, gap, target = pol.decide(1)
    assert wait == pytest.approx(7 / 400.0)
    assert target == 8
    assert gap <= wait


def test_cruise_caps_at_ceil_factor():
    # 30 ops/s: expected arrivals within base window >= 1 but a full
    # round would take 7/30 = 233ms — the ceiling (4 x 10ms) wins
    pol = _policy(bs=8, base_ms=10.0, workload=_FakeWorkload(130.0))
    wait, _gap, target = pol.decide(1)
    assert wait <= 0.010 * pol.cfg.ceil_factor + 1e-9
    assert target == 8


def test_missing_signals_degrade_to_sparse():
    # no workload, no slo: rate reads 0, every under-full round is
    # sparse — static-window behavior at the floor, never a crash
    pol = _policy(bs=8, base_ms=50.0)
    wait, _gap, target = pol.decide(2)
    assert wait == pytest.approx(pol.cfg.floor_wait_ms / 1000.0)
    assert target == 2


def test_decision_telemetry_counts_by_kind():
    reg = TelemetryRegistry()
    pol = _policy(bs=8, base_ms=8.0, workload=_FakeWorkload(200.0),
                  slo=_FakeSlo(fast_burn=3.0, fast_rounds=64),
                  registry=reg)
    pol.decide(1)   # shed (burn dominates)
    pol.slo = None
    pol.decide(9)   # fill
    pol.decide(1)   # cruise
    pol.workload = None
    pol.decide(1)   # sparse
    c = reg.get("grapevine_host_adaptive_decisions_total")
    for kind in DECISION_KINDS:
        assert c.get(phase=kind) == 1, kind
    assert reg.get("grapevine_host_adaptive_wait_ms").get() > 0
    assert reg.get("grapevine_host_adaptive_target_fill").get() == 1
    assert reg.audit()["ok"]


# -- through the scheduler ---------------------------------------------


class _StubEcfg:
    batch_size = 16


class _StubEngine:
    def __init__(self):
        self.ecfg = _StubEcfg()
        self.metrics = EngineMetrics()
        self.rounds: list[int] = []
        self._lock = threading.Lock()

    def handle_queries(self, reqs, now):
        with self._lock:
            self.rounds.append(len(reqs))
        zero = Record(
            msg_id=C.ZERO_MSG_ID,
            sender=C.ZERO_PUBKEY,
            recipient=C.ZERO_PUBKEY,
            timestamp=0,
            payload=b"\x00" * C.PAYLOAD_SIZE,
        )
        return [
            QueryResponse(record=zero, status_code=C.STATUS_CODE_SUCCESS)
            for _ in reqs
        ]

    def handle_queries_async(self, reqs, now):
        resps = self.handle_queries(reqs, now)

        class _Pending:
            def resolve(self):
                return resps

        return _Pending()


def _req():
    return QueryRequest(
        request_type=C.REQUEST_TYPE_READ,
        auth_identity=b"\x01" * 32,
        auth_signature=b"\x02" * C.SIGNATURE_SIZE,
        record=None,
    )


def test_adaptive_sparse_beats_static_window_latency():
    """A lone op under a huge static window would sit out the idle gap;
    the sparse decision dispatches it at the floor wait instead."""
    eng = _StubEngine()
    sched = BatchScheduler(eng, max_wait_ms=10_000.0, idle_gap_ms=5_000.0)
    sched.adaptive = AdaptiveBatchPolicy(
        _StubEcfg.batch_size, sched.max_wait, sched.idle_gap,
        workload=_FakeWorkload(0.0),
    )
    try:
        t0 = time.perf_counter()
        t = threading.Thread(target=sched.submit, args=(_req(),))
        t.start()
        t.join(timeout=10)
        assert time.perf_counter() - t0 < 3.0, (
            "sparse round sat out the static window"
        )
        assert eng.rounds == [1]
    finally:
        sched.close()


def test_flush_window_stretch_harvests_fuller_round():
    """With the engine reporting a flush bubble, the collection window
    stretches past max_wait and a straggler lands in the same round
    instead of paying a thin round that queues behind the flush."""

    class _FlushingEngine(_StubEngine):
        def flush_bubble_pending(self):
            return True

    eng = _FlushingEngine()
    sched = BatchScheduler(
        eng, max_wait_ms=150.0, idle_gap_ms=5_000.0,
        flush_window_ms=2_000.0,
    )
    try:
        t1 = threading.Thread(target=sched.submit, args=(_req(),))
        t1.start()
        time.sleep(0.6)  # past the 150ms base window, inside the stretch
        t2 = threading.Thread(target=sched.submit, args=(_req(),))
        t2.start()
        t1.join(timeout=15)
        t2.join(timeout=15)
        assert eng.rounds == [2], (
            f"straggler missed the stretched window: {eng.rounds}"
        )
        c = eng.metrics.registry.get(
            "grapevine_host_flush_window_stretches_total"
        )
        assert c.get() >= 1
    finally:
        sched.close()


def test_flush_window_ignored_without_engine_support():
    # stub engines without flush_bubble_pending must not crash the
    # collector — the getattr default reads "no bubble"
    eng = _StubEngine()
    sched = BatchScheduler(eng, max_wait_ms=50.0, idle_gap_ms=10.0,
                           flush_window_ms=1_000.0)
    try:
        t = threading.Thread(target=sched.submit, args=(_req(),))
        t0 = time.perf_counter()
        t.start()
        t.join(timeout=10)
        assert eng.rounds == [1]
        assert time.perf_counter() - t0 < 3.0
    finally:
        sched.close()


def test_negative_flush_window_rejected():
    with pytest.raises(ValueError):
        BatchScheduler(_StubEngine(), flush_window_ms=-1.0)


def test_frontend_role_rejects_adaptive_knobs():
    from grapevine_tpu.server.service import GrapevineServer

    with pytest.raises(ValueError):
        GrapevineServer(scheduler=object(), adaptive_batch=True)
    with pytest.raises(ValueError):
        GrapevineServer(scheduler=object(), flush_window_ms=5.0)


# -- flush-cadence leak detector (obs/leakmon.py note_flush) -----------


def _flush_monitor(flush_every):
    from grapevine_tpu.obs.leakmon import EngineLeakMonitor

    return EngineLeakMonitor(
        mb_leaves=8, rec_leaves=8, mb_choices=2, flush_every=flush_every
    )


def _detector(verdict, name):
    hits = [d for d in verdict["detectors"] if d["name"] == name]
    assert hits, f"{name} detector missing: {verdict['detectors']}"
    return hits[0]


def test_flush_cadence_detector_passes_on_strict_cadence():
    mon = _flush_monitor(4)
    try:
        for _ in range(6):
            mon.note_flush(4)
        d = _detector(mon.verdict(), "flush_cadence")
        assert d["verdict"] == "PASS" and d["samples"] == 6
    finally:
        mon.close()


def test_flush_cadence_detector_teeth():
    # one off-cadence scheduled flush is content-modulated scheduling
    # (the flush_on_buffer_contents signature) — SUSPECT immediately
    mon = _flush_monitor(4)
    try:
        mon.note_flush(4)
        mon.note_flush(3)
        v = mon.verdict()
        assert v["verdict"] == "SUSPECT"
        assert _detector(v, "flush_cadence")["verdict"] == "SUSPECT"
    finally:
        mon.close()


def test_flush_cadence_ignores_operator_flushes():
    # flush_now()/recovery completion pass scheduled=False — operator
    # actions are outside the steady-state cadence claim
    mon = _flush_monitor(4)
    try:
        mon.note_flush(2, scheduled=False)
        d = _detector(mon.verdict(), "flush_cadence")
        assert d["verdict"] == "PASS" and d["samples"] == 0
    finally:
        mon.close()


def test_flush_cadence_detector_absent_without_delayed_eviction():
    mon = _flush_monitor(None)
    try:
        names = [d["name"] for d in mon.verdict()["detectors"]]
        assert "flush_cadence" not in names
    finally:
        mon.close()


# -- the pop-heavy soak: adaptive + flush windows stay oblivious -------


@pytest.mark.slow  # ~11 s soak; tier-1 keeps the flush-stretch round
# test + the flush_cadence detector/mutant units for the same surface
def test_pop_heavy_soak_with_flush_windows_passes_leak_audit():
    """The acceptance soak: the PR-9 pop-heavy drain scenario through a
    scheduler running BOTH new knobs (adaptive window + flush-aware
    stretch) over a delayed-eviction engine. Every leak detector —
    including the new flush_cadence books — must PASS: the stretched
    windows retime host-side collection only, and the flush cadence
    stays strictly every E dispatched rounds."""
    from grapevine_tpu.config import GrapevineConfig
    from grapevine_tpu.engine.batcher import GrapevineEngine
    from grapevine_tpu.load import ScenarioRunner, pop_heavy_drain
    from grapevine_tpu.obs import attach_round_observability
    from grapevine_tpu.obs.leakmon import PASS, EngineLeakMonitor, \
        LeakMonitorConfig

    engine = GrapevineEngine(
        GrapevineConfig(
            bucket_cipher_rounds=0, max_messages=256, max_recipients=32,
            mailbox_cap=8, batch_size=8, stash_size=96, evict_every=4,
        ),
        seed=9,
    )
    _tracer, slo, _prof = attach_round_observability(
        engine, engine.metrics.registry
    )
    mon = EngineLeakMonitor.for_engine(
        engine, LeakMonitorConfig(window_rounds=64)
    )
    assert mon._flush_every == 4  # for_engine sized it from the config
    engine.attach_leakmon(mon)
    sched = BatchScheduler(
        engine, clock=lambda: 1_700_000_000, flush_window_ms=4.0
    )
    sched.adaptive = AdaptiveBatchPolicy(
        engine.ecfg.batch_size, sched.max_wait, sched.idle_gap,
        workload=engine.workload, slo=slo,
        registry=engine.metrics.registry,
    )
    try:
        runner = ScenarioRunner(sched, n_idents=16, settle_timeout_s=60.0)
        runner.run(pop_heavy_drain(100.0, 1.5, 37, n_idents=16))
    finally:
        sched.close()
        mon.flush(30)
        engine.attach_leakmon(None)
    v = mon.verdict()
    assert v["verdict"] == PASS, v
    fc = _detector(v, "flush_cadence")
    assert fc["samples"] >= 1, "soak never crossed a flush window"
    assert fc["verdict"] == "PASS"
    # the bubble predicate is the cadence counter, nothing else
    assert engine.flush_bubble_pending() == (engine._rounds_since_flush == 0)
    # the adaptive policy actually decided rounds, from public inputs
    dec = engine.metrics.registry.get("grapevine_host_adaptive_decisions_total")
    assert sum(dec.get(phase=k) for k in DECISION_KINDS) >= 1
    assert engine.metrics.registry.audit()["ok"]
    mon.close()
