"""Kill-at-every-phase chaos loop (slow): SIGKILL the engine process at
every instrumented fault site in the journal/checkpoint protocol — plus
randomized wall-clock kills — restart, and assert recovery is
bit-identical to an uninterrupted run with the leak monitor PASS
throughout.

Drives tools/chaos_run.py (the standalone ≥50-trial acceptance harness:
``python tools/chaos_run.py --trials 50``) at a phase-exhaustive trial
count that fits the slow bucket. Each trial spawns child processes, so
this must never run inside tier-1's budget — hence ``-m slow``.
"""

import os
import sys

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_chaos():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import chaos_run

    return chaos_run


def test_kill_at_every_fault_point_recovers_bit_identical():
    """One trial per crash site (testing/faults.py ALL_POINTS) plus one
    timer-kill trial: recovered state and every recorded response hash
    must match the uninterrupted oracle, and leakmon must report PASS on
    the recovered engine."""
    chaos = _load_chaos()
    from grapevine_tpu.testing.faults import ALL_POINTS

    args = chaos.parse_args(["--events", "18"])
    failures = chaos.run_trials(0, args, modes=list(ALL_POINTS) + ["timer"])
    assert not failures, "\n".join(failures)


def test_randomized_kill_trials_recover_bit_identical():
    """A handful of randomized trials (site and trigger count drawn per
    trial) on top of the exhaustive pass — the shape the standalone
    50-trial acceptance run uses."""
    chaos = _load_chaos()

    args = chaos.parse_args(["--events", "18", "--seed", "77"])
    failures = chaos.run_trials(6, args)
    assert not failures, "\n".join(failures)
