"""Kill-at-every-phase chaos loop (slow): SIGKILL the engine process at
every instrumented fault site in the journal/checkpoint protocol — plus
randomized wall-clock kills — restart, and assert recovery is
bit-identical to an uninterrupted run with the leak monitor PASS
throughout.

Drives tools/chaos_run.py (the standalone ≥50-trial acceptance harness:
``python tools/chaos_run.py --trials 50``) at a phase-exhaustive trial
count that fits the slow bucket. Each trial spawns child processes, so
this must never run inside tier-1's budget — hence ``-m slow``.
"""

import os
import sys

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_chaos():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import chaos_run

    return chaos_run


def test_kill_at_every_fault_point_recovers_bit_identical():
    """One trial per crash site (testing/faults.py ALL_POINTS) plus one
    timer-kill trial: recovered state and every recorded response hash
    must match the uninterrupted oracle, and leakmon must report PASS on
    the recovered engine."""
    chaos = _load_chaos()
    from grapevine_tpu.testing.faults import ALL_POINTS

    args = chaos.parse_args(["--events", "18"])
    failures = chaos.run_trials(0, args, modes=list(ALL_POINTS) + ["timer"])
    assert not failures, "\n".join(failures)


def test_randomized_kill_trials_recover_bit_identical():
    """A handful of randomized trials (site and trigger count drawn per
    trial) on top of the exhaustive pass — the shape the standalone
    50-trial acceptance run uses."""
    chaos = _load_chaos()

    args = chaos.parse_args(["--events", "18", "--seed", "77"])
    failures = chaos.run_trials(6, args)
    assert not failures, "\n".join(failures)


def test_delayed_eviction_kill_trials_recover_bit_identical():
    """ISSUE-15 chaos coverage: at ``--evict-every 4`` every fault site
    runs again — mid-accumulation kills (``round.*``/``append.*``
    landing with a part-filled eviction buffer and window ledger) AND
    the flush-boundary windows (``flush.pre_dispatch`` with the flush
    frame durable but undispatched, ``flush.post_dispatch`` before any
    later frame), plus a randomized timer kill. Each trial is
    multi-incarnation by construction (chaos_run relaunches until the
    schedule completes, re-killing when the trigger re-arms), and every
    incarnation's response hashes plus the final state must match the
    uninterrupted E=4 oracle, with leakmon PASS on the recovered
    engine — the buffer's stash-grade durability claim, end to end."""
    chaos = _load_chaos()
    from grapevine_tpu.testing.faults import ALL_POINTS

    args = chaos.parse_args(
        ["--events", "16", "--evict-every", "4", "--seed", "52",
         "--checkpoint-every", "5"]
    )
    failures = chaos.run_trials(
        0, args, modes=list(ALL_POINTS) + ["timer"]
    )
    assert not failures, "\n".join(failures)


def test_sharded_flush_kill_trials_recover_bit_identical():
    """ISSUE-18 chaos coverage: ``--shards 2 --evict-every 2`` runs the
    child on a 2-device virtual CPU mesh with the owner-masked sharded
    flush, and kills land at the flush boundaries —
    ``flush.pre_dispatch`` (flush frame durable, owner-masked scatter
    undispatched: recovery must replay the flush on the mesh) and
    ``flush.post_dispatch`` (scatter landed on both shards' HBM ranges,
    no later frame durable) — plus a mid-accumulation append kill and a
    randomized timer kill. The oracle is the SINGLE-CHIP serial E=2
    program, so bit-identical recovery proves the crash contract AND
    sharded<->single-chip equivalence through a kill-restart cycle at
    once, with leakmon PASS on the recovered engine."""
    chaos = _load_chaos()

    args = chaos.parse_args(
        ["--events", "16", "--evict-every", "2", "--shards", "2",
         "--seed", "64", "--checkpoint-every", "5"]
    )
    failures = chaos.run_trials(0, args, modes=[
        "flush.pre_dispatch", "flush.post_dispatch",
        "journal.append.post_fsync", "timer",
    ])
    assert not failures, "\n".join(failures)


def test_pipelined_kill_trials_recover_bit_identical():
    """PR-10 chaos coverage: ``--pipeline-depth 2`` keeps a round
    mid-flight on the device while the next one journals + fsyncs, and
    kills land (a) between journal-fsync(k+1) and dispatch(k+1)
    (``round.pre_dispatch``), (b) mid-flight of round k with k+1
    dispatched behind it (``round.post_dispatch``), (c) at the fsync
    barrier itself, the torn-frame window, and a randomized wall-clock
    point. The oracle is the SERIAL depth-1 program, so recovery being
    bit-identical proves both the crash contract (replay order = journal
    order, never completion order) and depth bit-equivalence at once,
    with leakmon PASS on the recovered engine."""
    chaos = _load_chaos()

    args = chaos.parse_args(
        ["--events", "18", "--seed", "99", "--pipeline-depth", "2"]
    )
    failures = chaos.run_trials(0, args, modes=[
        "round.pre_dispatch", "round.post_dispatch",
        "journal.append.post_fsync", "journal.append.torn", "timer",
    ])
    assert not failures, "\n".join(failures)


def test_standby_kill_at_every_fault_point_promotes_bit_identical():
    """ISSUE-19 chaos acceptance: the hot-standby drill at every
    instrumented fault site (plus a timer kill) at ``--evict-every 2
    --pipeline-depth 2``. Each trial streams the primary's sealed
    frames to an in-parent StandbyReplica, SIGKILLs the primary at the
    armed site — including ``flush.pre_dispatch``/``post_dispatch``
    (flush frame durable, scatter undispatched / landed) and the
    torn-frame window, which lands a half-written frame at the tail
    the promote-time drain must treat as not-yet-durable — then
    promotes, finishes the event schedule on the replica, and requires
    the final state to match the serial oracle bit-identically with
    leakmon (including the ship-cadence book) PASS, and the fenced
    primary dir to refuse a revived stale writer."""
    chaos = _load_chaos()
    from grapevine_tpu.testing.faults import ALL_POINTS

    args = chaos.parse_args(
        ["--standby", "--events", "16", "--evict-every", "2",
         "--pipeline-depth", "2", "--checkpoint-every", "5",
         "--seed", "43"]
    )
    failures = chaos.run_trials(0, args, modes=list(ALL_POINTS) + ["timer"])
    assert not failures, "\n".join(failures)


# -- live flip drill: CLI processes, SIGKILL + SIGUSR1, zero dropped ----


def _wait_line(proc, needle, timeout=120.0):
    import time as _t

    deadline = _t.monotonic() + timeout
    while _t.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            raise AssertionError(
                f"process exited before {needle!r}: "
                f"{proc.stderr.read()[-2000:]}"
            )
        if needle in line:
            return line
    raise AssertionError(f"no {needle!r} line within {timeout}s")


def _signed_req(scheme, seed_byte, rt, recipient, payload_byte, challenge):
    from grapevine_tpu.wire import constants as C
    from grapevine_tpu.wire.records import QueryRequest, RequestRecord

    sk, pub = scheme.keygen(bytes([seed_byte]) * 32)
    sig = scheme.sign(
        sk, C.GRAPEVINE_CHALLENGE_SIGNING_CONTEXT, challenge
    )
    req = QueryRequest(
        request_type=rt, auth_identity=pub, auth_signature=sig,
        record=RequestRecord(
            msg_id=C.ZERO_MSG_ID, recipient=recipient,
            payload=bytes([payload_byte]) * C.PAYLOAD_SIZE,
        ),
    )
    return req, (pub, C.GRAPEVINE_CHALLENGE_SIGNING_CONTEXT, challenge, sig)


def test_live_flip_drill_zero_dropped_ops(tmp_path):
    """The operational runbook (OPERATIONS.md §23) over real processes:
    an engine-role primary shipping to a standby-role process, clients
    acknowledged over gRPC, SIGKILL the primary, SIGUSR1 the standby,
    and every acknowledged write is readable from the promoted engine
    port — zero dropped ops across the flip."""
    import json
    import signal
    import subprocess
    import time as _t
    import urllib.request

    import grpc  # noqa: F401 - engine stub transport

    from grapevine_tpu.server.tier import _EngineStub
    from grapevine_tpu.session import get_signature_scheme
    from grapevine_tpu.wire import constants as C

    scheme = get_signature_scheme("schnorrkel")
    pdir, sdir = str(tmp_path / "primary"), str(tmp_path / "standby")
    for d in (pdir, sdir):
        os.makedirs(d)
        with open(os.path.join(d, "root.key"), "wb") as fh:
            fh.write(bytes(range(32)))
        os.chmod(os.path.join(d, "root.key"), 0o600)

    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    geometry = [
        "--msg-capacity", "64", "--recipient-capacity", "8",
        "--batch-size", "4", "--evict-every", "2",
        "--tree-top-cache-levels", "0", "--pipeline-depth", "1",
        "--batch-wait-ms", "30",
    ]
    procs = []
    try:
        standby = subprocess.Popen(
            [sys.executable, "-m", "grapevine_tpu.server.cli",
             "--role", "standby", "--state-dir", sdir,
             "--standby-listen", "127.0.0.1:0",
             "--promote-from", pdir,
             "--engine-listen", "127.0.0.1:0",
             "--metrics-port", "0"] + geometry,
            cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True)
        procs.append(standby)
        line = _wait_line(standby, "standby replica on port")
        feed_port = int(line.rsplit(" ", 1)[1])
        line = _wait_line(standby, "metrics endpoint on port")
        mport = int(line.rsplit(" ", 1)[1])

        primary = subprocess.Popen(
            [sys.executable, "-m", "grapevine_tpu.server.cli",
             "--role", "engine", "--engine-listen", "127.0.0.1:0",
             "--state-dir", pdir,
             "--replicate-to", f"127.0.0.1:{feed_port}"] + geometry,
            cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True)
        procs.append(primary)
        line = _wait_line(primary, "engine tier listening on port")
        eport = int(line.rsplit(" ", 1)[1])

        # acknowledged writes: 3 messages into mailbox X + filler ops
        stub = _EngineStub(f"127.0.0.1:{eport}", deadline_s=60.0)
        _, x_pub = scheme.keygen(b"\x07" * 32)
        for i in range(8):
            challenge = bytes([i + 1]) * C.CHALLENGE_SIZE
            req, auth = _signed_req(
                scheme, seed_byte=i + 10, rt=C.REQUEST_TYPE_CREATE,
                recipient=x_pub if i < 3 else bytes([i + 40]) * 32,
                payload_byte=0x70 + i, challenge=challenge)
            resp = stub.submit(req, auth=auth)
            assert resp.status_code == C.STATUS_CODE_SUCCESS, i
        stub.close()

        # wait for the live feed to have applied the acked tail (the
        # drill's "hot" claim: promotion replays no cold backlog)
        deadline = _t.monotonic() + 60
        while _t.monotonic() < deadline:
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{mport}/healthz",
                        timeout=5) as r:
                    hz = json.loads(r.read().decode())
            except urllib.error.HTTPError as e:
                hz = json.loads(e.read().decode())
            if (hz.get("replication_connected")
                    and hz["durability"]["applied_seq"] >= 8):
                break
            _t.sleep(0.2)
        else:
            raise AssertionError(f"standby never caught up: {hz}")

        # kill-the-primary, promote-the-standby
        primary.send_signal(signal.SIGKILL)
        primary.wait(timeout=30)
        standby.send_signal(signal.SIGUSR1)
        _wait_line(standby, "standby promoted: epoch")
        line = _wait_line(standby, "promoted engine tier listening on port")
        pport = int(line.rsplit(" ", 1)[1])

        # zero dropped: every pre-kill write survives the flip — pops
        # from mailbox X return the exact acknowledged payloads
        stub = _EngineStub(f"127.0.0.1:{pport}", deadline_s=60.0)
        x_sk, x_pub2 = scheme.keygen(b"\x07" * 32)
        assert x_pub2 == x_pub
        popped = []
        for i in range(3):
            challenge = bytes([0x80 + i]) * C.CHALLENGE_SIZE
            sig = scheme.sign(
                x_sk, C.GRAPEVINE_CHALLENGE_SIGNING_CONTEXT, challenge)
            from grapevine_tpu.wire.records import (
                QueryRequest,
                RequestRecord,
            )

            req = QueryRequest(
                request_type=C.REQUEST_TYPE_DELETE, auth_identity=x_pub,
                auth_signature=sig,
                record=RequestRecord(
                    msg_id=C.ZERO_MSG_ID, recipient=C.ZERO_PUBKEY,
                    payload=b"\x00" * C.PAYLOAD_SIZE))
            resp = stub.submit(
                req, auth=(x_pub, C.GRAPEVINE_CHALLENGE_SIGNING_CONTEXT,
                           challenge, sig))
            assert resp.status_code == C.STATUS_CODE_SUCCESS
            popped.append(resp.record.payload[0])
        assert popped == [0x70, 0x71, 0x72], popped
        # ...and the promoted engine keeps taking new writes
        challenge = b"\xaa" * C.CHALLENGE_SIZE
        req, auth = _signed_req(
            scheme, seed_byte=99, rt=C.REQUEST_TYPE_CREATE,
            recipient=b"\x63" * 32, payload_byte=0x63,
            challenge=challenge)
        assert stub.submit(req, auth=auth).status_code == \
            C.STATUS_CODE_SUCCESS
        stub.close()

        standby.send_signal(signal.SIGTERM)
        assert standby.wait(timeout=120) == 0, standby.stderr.read()[-2000:]
        procs = []
    finally:
        for p in procs:
            p.kill()
            p.wait(timeout=10)
