"""Kill-at-every-phase chaos loop (slow): SIGKILL the engine process at
every instrumented fault site in the journal/checkpoint protocol — plus
randomized wall-clock kills — restart, and assert recovery is
bit-identical to an uninterrupted run with the leak monitor PASS
throughout.

Drives tools/chaos_run.py (the standalone ≥50-trial acceptance harness:
``python tools/chaos_run.py --trials 50``) at a phase-exhaustive trial
count that fits the slow bucket. Each trial spawns child processes, so
this must never run inside tier-1's budget — hence ``-m slow``.
"""

import os
import sys

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_chaos():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import chaos_run

    return chaos_run


def test_kill_at_every_fault_point_recovers_bit_identical():
    """One trial per crash site (testing/faults.py ALL_POINTS) plus one
    timer-kill trial: recovered state and every recorded response hash
    must match the uninterrupted oracle, and leakmon must report PASS on
    the recovered engine."""
    chaos = _load_chaos()
    from grapevine_tpu.testing.faults import ALL_POINTS

    args = chaos.parse_args(["--events", "18"])
    failures = chaos.run_trials(0, args, modes=list(ALL_POINTS) + ["timer"])
    assert not failures, "\n".join(failures)


def test_randomized_kill_trials_recover_bit_identical():
    """A handful of randomized trials (site and trigger count drawn per
    trial) on top of the exhaustive pass — the shape the standalone
    50-trial acceptance run uses."""
    chaos = _load_chaos()

    args = chaos.parse_args(["--events", "18", "--seed", "77"])
    failures = chaos.run_trials(6, args)
    assert not failures, "\n".join(failures)


def test_delayed_eviction_kill_trials_recover_bit_identical():
    """ISSUE-15 chaos coverage: at ``--evict-every 4`` every fault site
    runs again — mid-accumulation kills (``round.*``/``append.*``
    landing with a part-filled eviction buffer and window ledger) AND
    the flush-boundary windows (``flush.pre_dispatch`` with the flush
    frame durable but undispatched, ``flush.post_dispatch`` before any
    later frame), plus a randomized timer kill. Each trial is
    multi-incarnation by construction (chaos_run relaunches until the
    schedule completes, re-killing when the trigger re-arms), and every
    incarnation's response hashes plus the final state must match the
    uninterrupted E=4 oracle, with leakmon PASS on the recovered
    engine — the buffer's stash-grade durability claim, end to end."""
    chaos = _load_chaos()
    from grapevine_tpu.testing.faults import ALL_POINTS

    args = chaos.parse_args(
        ["--events", "16", "--evict-every", "4", "--seed", "52",
         "--checkpoint-every", "5"]
    )
    failures = chaos.run_trials(
        0, args, modes=list(ALL_POINTS) + ["timer"]
    )
    assert not failures, "\n".join(failures)


def test_sharded_flush_kill_trials_recover_bit_identical():
    """ISSUE-18 chaos coverage: ``--shards 2 --evict-every 2`` runs the
    child on a 2-device virtual CPU mesh with the owner-masked sharded
    flush, and kills land at the flush boundaries —
    ``flush.pre_dispatch`` (flush frame durable, owner-masked scatter
    undispatched: recovery must replay the flush on the mesh) and
    ``flush.post_dispatch`` (scatter landed on both shards' HBM ranges,
    no later frame durable) — plus a mid-accumulation append kill and a
    randomized timer kill. The oracle is the SINGLE-CHIP serial E=2
    program, so bit-identical recovery proves the crash contract AND
    sharded<->single-chip equivalence through a kill-restart cycle at
    once, with leakmon PASS on the recovered engine."""
    chaos = _load_chaos()

    args = chaos.parse_args(
        ["--events", "16", "--evict-every", "2", "--shards", "2",
         "--seed", "64", "--checkpoint-every", "5"]
    )
    failures = chaos.run_trials(0, args, modes=[
        "flush.pre_dispatch", "flush.post_dispatch",
        "journal.append.post_fsync", "timer",
    ])
    assert not failures, "\n".join(failures)


def test_pipelined_kill_trials_recover_bit_identical():
    """PR-10 chaos coverage: ``--pipeline-depth 2`` keeps a round
    mid-flight on the device while the next one journals + fsyncs, and
    kills land (a) between journal-fsync(k+1) and dispatch(k+1)
    (``round.pre_dispatch``), (b) mid-flight of round k with k+1
    dispatched behind it (``round.post_dispatch``), (c) at the fsync
    barrier itself, the torn-frame window, and a randomized wall-clock
    point. The oracle is the SERIAL depth-1 program, so recovery being
    bit-identical proves both the crash contract (replay order = journal
    order, never completion order) and depth bit-equivalence at once,
    with leakmon PASS on the recovered engine."""
    chaos = _load_chaos()

    args = chaos.parse_args(
        ["--events", "18", "--seed", "99", "--pipeline-depth", "2"]
    )
    failures = chaos.run_trials(0, args, modes=[
        "round.pre_dispatch", "round.post_dispatch",
        "journal.append.post_fsync", "journal.append.torn", "timer",
    ])
    assert not failures, "\n".join(failures)
