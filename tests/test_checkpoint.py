"""Sealing, checkpoint files, and journal codec (engine/checkpoint.py,
engine/journal.py) — plus the checkpoint-seal CI gate.

The torn-file corpus here is the tier-1 half of the crash-safety story:
every truncation/bitflip of a sealed file must be rejected whole with a
clear error (or, for a journal *tail*, discarded whole) — never
half-loaded. The process-kill half lives in tests/test_chaos_recovery.py
(slow) and tools/chaos_run.py.
"""

import importlib.util
import os

import numpy as np
import pytest

from grapevine_tpu.config import DurabilityConfig, GrapevineConfig
from grapevine_tpu.engine import checkpoint as cp
from grapevine_tpu.engine import journal as jr
from grapevine_tpu.engine.batcher import pack_batch
from grapevine_tpu.engine.state import EngineConfig, init_engine
from grapevine_tpu.session.chacha import ChaCha20
from grapevine_tpu.wire import constants as C
from grapevine_tpu.wire.records import QueryRequest, RequestRecord

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SMALL = GrapevineConfig(
    max_messages=64, max_recipients=8, mailbox_cap=4,
    batch_size=4, stash_size=64, bucket_cipher_rounds=0,
)

ROOT = bytes(range(32))


# -- sealing primitives -------------------------------------------------


def test_bulk_chacha_matches_session_stream():
    """The numpy-vectorized keystream is the same RFC 7539 stream the
    session layer's (test-vector-pinned) implementation produces."""
    key, nonce = bytes(range(32)), bytes(range(12))
    for n in (1, 63, 64, 65, 1000, 4096):
        data = bytes((i * 7) & 0xFF for i in range(n))
        ks = ChaCha20(key, nonce).keystream(n)
        want = bytes(a ^ b for a, b in zip(data, ks))
        assert cp.chacha20_xor(key, nonce, data) == want


def test_seal_roundtrip_and_rejections():
    blob = cp.seal(ROOT, b"checkpoint", b"payload bytes", aad=b"hdr")
    assert cp.unseal(ROOT, b"checkpoint", blob, aad=b"hdr") == b"payload bytes"
    with pytest.raises(cp.SealError):  # tamper
        cp.unseal(ROOT, b"checkpoint", blob[:-1] + b"\x00", aad=b"hdr")
    with pytest.raises(cp.SealError):  # truncation
        cp.unseal(ROOT, b"checkpoint", blob[:-5], aad=b"hdr")
    with pytest.raises(cp.SealError):  # wrong domain subkey
        cp.unseal(ROOT, b"journal", blob, aad=b"hdr")
    with pytest.raises(cp.SealError):  # aad (header) mangled
        cp.unseal(ROOT, b"checkpoint", blob, aad=b"HDR")
    with pytest.raises(cp.SealError):  # wrong root key
        cp.unseal(b"\x01" * 32, b"checkpoint", blob, aad=b"hdr")
    with pytest.raises(cp.SealError):  # shorter than nonce+tag
        cp.unseal(ROOT, b"checkpoint", b"short")


def test_root_key_create_then_load(tmp_path):
    path = str(tmp_path / "root.key")
    k1 = cp.load_or_create_root_key(path)
    assert len(k1) == 32 and oct(os.stat(path).st_mode & 0o777) == "0o600"
    assert cp.load_or_create_root_key(path) == k1
    (tmp_path / "bad.key").write_bytes(b"short")
    with pytest.raises(cp.SealError):
        cp.load_or_create_root_key(str(tmp_path / "bad.key"))


# -- checkpoint files ---------------------------------------------------


@pytest.fixture(scope="module")
def ecfg():
    return EngineConfig.from_config(SMALL)


@pytest.fixture(scope="module")
def state(ecfg):
    return init_engine(ecfg, seed=5)


def test_state_bytes_roundtrip(ecfg, state):
    data = cp.state_to_bytes(ecfg, state)
    state2 = cp.bytes_to_state(ecfg, data)
    assert cp.state_to_bytes(ecfg, state2) == data


def test_checkpoint_write_load(tmp_path, ecfg, state):
    path = cp.write_checkpoint(str(tmp_path), ROOT, ecfg, state, seq=42)
    assert cp.find_latest_checkpoint(str(tmp_path)) == (42, path)
    seq, state2 = cp.load_checkpoint(path, ROOT, ecfg)
    assert seq == 42
    assert cp.state_to_bytes(ecfg, state2) == cp.state_to_bytes(ecfg, state)


def test_checkpoint_geometry_fingerprint_rejected(tmp_path, ecfg, state):
    path = cp.write_checkpoint(str(tmp_path), ROOT, ecfg, state, seq=1)
    other = EngineConfig.from_config(
        GrapevineConfig(
            max_messages=128, max_recipients=8, mailbox_cap=4,
            batch_size=4, stash_size=64, bucket_cipher_rounds=0,
        )
    )
    with pytest.raises(cp.CheckpointError, match="fingerprint"):
        cp.load_checkpoint(path, ROOT, other)


def test_renamed_checkpoint_rejected(tmp_path, ecfg, state):
    """The filename seq picks the file; the sealed payload seq anchors
    replay — a renamed checkpoint must not shift the replay base."""
    path = cp.write_checkpoint(str(tmp_path), ROOT, ecfg, state, seq=7)
    os.rename(path, cp.checkpoint_path(str(tmp_path), 5))
    with open(tmp_path / "root.key", "wb") as fh:
        fh.write(ROOT)
    mgr = cp.DurabilityManager(
        DurabilityConfig(state_dir=str(tmp_path)), ecfg
    )
    with pytest.raises(cp.CheckpointError, match="renamed"):
        mgr.recover(state, lambda s, rec: s)


def test_torn_checkpoint_corpus_never_half_loads(tmp_path, ecfg, state):
    """Truncations at a spread of offsets plus interior bitflips: every
    variant raises CheckpointError; none returns a state."""
    path = cp.write_checkpoint(str(tmp_path), ROOT, ecfg, state, seq=7)
    blob = open(path, "rb").read()
    cuts = [0, 1, len(cp.MAGIC), 11, 12, 50, len(blob) // 2, len(blob) - 33,
            len(blob) - 1]
    for cut in cuts:
        torn = str(tmp_path / f"torn-{cut}.sealed")
        with open(torn, "wb") as fh:
            fh.write(blob[:cut])
        with pytest.raises(cp.CheckpointError):
            cp.load_checkpoint(torn, ROOT, ecfg)
    for flip_at in (8, 20, len(blob) // 2, len(blob) - 10):
        flipped = str(tmp_path / f"flip-{flip_at}.sealed")
        mutated = bytearray(blob)
        mutated[flip_at] ^= 0x40
        with open(flipped, "wb") as fh:
            fh.write(bytes(mutated))
        with pytest.raises(cp.CheckpointError):
            cp.load_checkpoint(flipped, ROOT, ecfg)


# -- journal codec + torn-tail semantics --------------------------------


def _round_batch(ecfg, tag: int):
    reqs = [
        QueryRequest(
            request_type=C.REQUEST_TYPE_CREATE,
            auth_identity=bytes([tag]) * 32,
            auth_signature=b"\x01" * C.SIGNATURE_SIZE,
            record=RequestRecord(
                msg_id=C.ZERO_MSG_ID,
                recipient=bytes([tag ^ 0x5A]) * 32,
                payload=bytes([tag]) * C.PAYLOAD_SIZE,
            ),
        )
    ]
    return pack_batch(reqs, ecfg.batch_size, 1_700_000_000 + tag), len(reqs)


def _fresh_journal(tmp_path, ecfg, **kw):
    j = jr.BatchJournal(str(tmp_path), ROOT, ecfg, **kw)
    list(j.replay(after_seq=0))
    j.open_for_append()
    return j


def test_journal_roundtrip_rounds_and_sweeps(tmp_path, ecfg):
    j = _fresh_journal(tmp_path, ecfg)
    batches = [_round_batch(ecfg, t) for t in (1, 2)]
    assert j.append_round(*batches[0]) == 1
    assert j.append_sweep(123, 4, 60) == 2
    assert j.append_round(*batches[1]) == 3
    j.close()

    j2 = jr.BatchJournal(str(tmp_path), ROOT, ecfg)
    recs = list(j2.replay(after_seq=0))
    assert [r.seq for r in recs] == [1, 2, 3]
    assert [r.kind for r in recs] == [jr.KIND_ROUND, jr.KIND_SWEEP,
                                      jr.KIND_ROUND]
    assert recs[1].now == 123 and recs[1].now_hi == 4 and recs[1].period == 60
    for rec, (batch, n) in zip((recs[0], recs[2]), batches):
        assert rec.n_real == n
        for col in ("req_type", "auth", "msg_id", "recipient", "payload"):
            np.testing.assert_array_equal(rec.batch[col], batch[col])
        assert int(rec.batch["now"]) == int(batch["now"])
    # checkpoint covering seq 2: replay skips the covered prefix
    j3 = jr.BatchJournal(str(tmp_path), ROOT, ecfg)
    assert [r.seq for r in j3.replay(after_seq=2)] == [3]


def test_journal_torn_tail_discarded_everywhere_else_rejected(tmp_path, ecfg):
    j = _fresh_journal(tmp_path, ecfg)
    for t in range(3):
        j.append_round(*_round_batch(ecfg, t + 1))
    j.close()
    (first_seq, path), = jr.BatchJournal(str(tmp_path), ROOT, ecfg)._segments()
    blob = open(path, "rb").read()
    frame_len = len(blob) // 3

    # truncating anywhere inside the FINAL frame = torn tail: the first
    # two records replay, the torn one is discarded, never half-decoded
    for cut in (2 * frame_len + 1, 2 * frame_len + 16, len(blob) - 1):
        with open(path, "wb") as fh:
            fh.write(blob[:cut])
        jt = jr.BatchJournal(str(tmp_path), ROOT, ecfg)
        assert [r.seq for r in jt.replay(after_seq=0)] == [1, 2]
        # ...and appending after recovery truncates the torn bytes
        jt.open_for_append()
        seq = jt.append_round(*_round_batch(ecfg, 9))
        assert seq == 3
        jt.close()
        recs = list(jr.BatchJournal(str(tmp_path), ROOT, ecfg).replay(0))
        assert [r.seq for r in recs] == [1, 2, 3]
        with open(path, "wb") as fh:  # restore the 3-frame original
            fh.write(blob)

    # a bitflipped frame with valid frames after it is corruption
    mutated = bytearray(blob)
    mutated[frame_len + 20] ^= 1
    with open(path, "wb") as fh:
        fh.write(bytes(mutated))
    with pytest.raises(jr.JournalError, match="integrity"):
        list(jr.BatchJournal(str(tmp_path), ROOT, ecfg).replay(0))

    # header corruption mid-final-segment must raise too — NOT read as
    # a torn tail that would silently truncate durable frames behind it
    mutated = bytearray(blob)
    mutated[frame_len] ^= 0xFF  # second frame's magic
    with open(path, "wb") as fh:
        fh.write(bytes(mutated))
    with pytest.raises(jr.JournalError, match="magic"):
        list(jr.BatchJournal(str(tmp_path), ROOT, ecfg).replay(0))
    mutated = bytearray(blob)
    mutated[frame_len + 12] ^= 0xFF  # second frame's blob_len field
    with open(path, "wb") as fh:
        fh.write(bytes(mutated))
    with pytest.raises(jr.JournalError, match="impossible blob length"):
        list(jr.BatchJournal(str(tmp_path), ROOT, ecfg).replay(0))

    # a missing prefix (journal starts past the checkpoint's coverage)
    # is corruption, not a quiet skip — frames are constant-size here,
    # so dropping the first one leaves valid frames 2..3
    with open(path, "wb") as fh:
        fh.write(blob[frame_len:])
    with pytest.raises(jr.JournalError, match="starts at seq 2"):
        list(jr.BatchJournal(str(tmp_path), ROOT, ecfg).replay(after_seq=0))
    with open(path, "wb") as fh:  # restore for any later test
        fh.write(blob)


def test_journal_geometry_mismatch_rejected(tmp_path, ecfg):
    j = _fresh_journal(tmp_path, ecfg)
    j.append_round(*_round_batch(ecfg, 1))
    j.close()
    other = EngineConfig.from_config(
        GrapevineConfig(
            max_messages=64, max_recipients=8, mailbox_cap=4,
            batch_size=8, stash_size=64, bucket_cipher_rounds=0,
        )
    )
    # caught at the frame-length gate (round frames are constant-size
    # per geometry) before the sealed body's own batch_size check
    with pytest.raises(jr.JournalError,
                       match="impossible blob length|batch_size"):
        list(jr.BatchJournal(str(tmp_path), ROOT, other).replay(0))


def test_journal_roll_prunes_covered_segments(tmp_path, ecfg):
    j = _fresh_journal(tmp_path, ecfg)
    j.append_round(*_round_batch(ecfg, 1))
    j.append_round(*_round_batch(ecfg, 2))
    j.roll()  # as after a checkpoint at seq 2
    j.append_round(*_round_batch(ecfg, 3))
    j.close()
    segs = jr.BatchJournal(str(tmp_path), ROOT, ecfg)._segments()
    assert [s[0] for s in segs] == [3]
    recs = list(jr.BatchJournal(str(tmp_path), ROOT, ecfg).replay(after_seq=2))
    assert [r.seq for r in recs] == [3]


def test_journal_fsync_batching(tmp_path, ecfg):
    synced = []
    j = jr.BatchJournal(str(tmp_path), ROOT, ecfg, fsync_every=3,
                        on_fsync=synced.append)
    list(j.replay(0))
    j.open_for_append()
    for t in range(1, 8):
        j.append_round(*_round_batch(ecfg, t))
    assert synced == [3, 6]  # every 3rd record
    assert j.durable_seq == 6 and j.seq == 7
    j.sync()
    assert synced == [3, 6, 7]
    j.close()


# -- the CI seal gate (satellite: wired next to check_telemetry_policy) -


def test_checkpoint_seal_gate_passes():
    """tools/check_checkpoint_seal.py: no plaintext payload, identity,
    or key material in any checkpoint/journal file a real durable run
    writes."""
    path = os.path.join(REPO, "tools", "check_checkpoint_seal.py")
    spec = importlib.util.spec_from_file_location("check_checkpoint_seal", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main() == 0
