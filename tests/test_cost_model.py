"""The round-cost observatory (PR 17): the two-derivation ledger
identity, its mutant teeth, the model-graded knob decisions, and the
grapevine_cost_* export surface.

Everything here is trace-only or pure arithmetic — zero engine round
compiles — so the whole file rides tier-1. The structure mirrors the
rangelint/oblint suites: the analyzer is proven against the shipped
matrix, then proven ALIVE against seeded defects, then the gate tool
itself is exercised in-process (tools/check_cost_model.py), then the
serving-side export is checked end-to-end down to the Prometheus text
a scrape of a running engine role would see.
"""

import dataclasses
import importlib.util
import os

import pytest

from grapevine_tpu.analysis import costmodel as cm
from grapevine_tpu.analysis.mutants import control_failures
from grapevine_tpu.config import GrapevineConfig
from grapevine_tpu.engine.state import EngineConfig
from grapevine_tpu.obs.costmon import (
    CostMonitor,
    resolve_bandwidth_gbps,
)
from grapevine_tpu.obs.exporter import render_prometheus
from grapevine_tpu.obs.registry import TelemetryRegistry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    path = os.path.join(REPO, "tools", f"{name}.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# -- the two-derivation identity ---------------------------------------


@pytest.mark.parametrize(
    "name,cfg,b", cm.audit_oram_configs(),
    ids=[n for n, _, _ in cm.audit_oram_configs()],
)
def test_round_ledger_matches_traced_census(name, cfg, b):
    """Analytic row model == traced jaxpr census, bit-exact per operand
    shape class, for every shipped oram_round knob combination (cache-k
    x posmap x evict_every, cipher on/off)."""
    cm.cross_validate_round(cfg, b)
    if cfg.delayed_eviction:
        cm.cross_validate_flush(cfg)


@pytest.mark.parametrize(
    "name,ecfg", cm.audit_engine_configs(),
    ids=[n for n, _ in cm.audit_engine_configs()],
)
def test_engine_ledger_matches_traced_census(name, ecfg):
    """Same identity at the composed engine level: the recipient-tree
    round + the mailbox double-round (E=1 and the E=2 fetch/flush
    split), the engine flush, and the expiry sweep's chunked scan."""
    cm.cross_validate_engine_round(ecfg)
    if ecfg.evict_every > 1:
        cm.cross_validate_engine_flush(ecfg)
    cm.cross_validate_sweep(ecfg)


@pytest.mark.parametrize(
    "name,cfg,shards", cm.audit_sharded_flush_configs(),
    ids=[n for n, _, _ in cm.audit_sharded_flush_configs()],
)
def test_sharded_flush_ledger_matches_traced_census(name, cfg, shards):
    """ISSUE 18: the owner-masked sharded flush — shard-local analytic
    rows (full uniform t-row scatters against local plane shapes,
    replicated inner-posmap planes untouched) == the shard_map-traced
    census, bit-exact per shape class. Trace-only."""
    assert shards == 2  # conftest forces 8 virtual CPU devices
    cm.cross_validate_sharded_flush(cfg, shards)


def test_sharded_ledger_per_chip_bytes():
    """The per-chip ledger view: shards=1 reduces to the single-chip
    steady bytes exactly; at shards>1 only the owner-masked scatter
    half divides, and the aggregate across chips reconstructs the
    single-chip write bytes exactly (power-of-two binary division)."""
    ecfg = cm.sweep_engine_ecfg(64, evict_every=2)
    led1 = cm.engine_cost_ledger(ecfg)
    assert led1.per_shard_steady_round_bytes == led1.steady_round_bytes
    led4 = cm.engine_cost_ledger(ecfg, shards=4)
    assert led4.per_shard_steady_round_bytes < led1.steady_round_bytes
    # reconstruct: per-chip = gathers + repl scatters + sharded/4
    fl1, fl4 = led1.phases["flush"], led4.phases["flush"]
    assert fl1.sharded_scatter_bytes == fl4.sharded_scatter_bytes > 0
    assert fl4.per_chip_bytes(4) * 4 == (
        4 * (fl4.gather_bytes
             + fl4.scatter_bytes - fl4.sharded_scatter_bytes)
        + fl4.sharded_scatter_bytes
    )
    with pytest.raises(ValueError, match="power of two"):
        cm.engine_cost_ledger(ecfg, shards=3)
    # the isolated-ORAM helper agrees with its single-chip form
    cfg = cm.machinery_oram_cfg(1 << 12, 64, e=2)
    assert cm.oram_sharded_steady_bytes(cfg, 64, 1) == (
        cm.oram_steady_bytes(cfg, 64))
    assert cm.oram_sharded_steady_bytes(cfg, 64, 4) < (
        cm.oram_steady_bytes(cfg, 64))


def test_cost_mutants_all_caught():
    """Every seeded undercount mutant (dropped plane, halved fetch,
    forgotten nonce re-gather, missed mailbox double-round, ...) must
    trip CostModelMismatch with the declared kind — a cost checker
    that cannot catch a planted undercount is vacuous."""
    assert control_failures(
        cm.run_cost_mutants(), "cost-model mutant", log=lambda *_: None
    ) == []


def test_mismatch_reports_shape_and_kind():
    """A corrupted prediction surfaces as a typed, per-shape-class
    diff — the triage surface OPERATIONS.md §21 documents."""
    _, cfg, b = cm.audit_oram_configs()[0]
    with pytest.raises(cm.CostModelMismatch) as ei:
        cm.cross_validate_round(
            cfg, b,
            _corrupt=lambda rows: {
                n: (dataclasses.replace(r, gather_rows=r.gather_rows // 2)
                    if r.hbm else r)
                for n, r in rows.items()
            },
        )
    assert ei.value.kind == "gather-undercount"
    assert "disagree" in str(ei.value) and "shape (" in str(ei.value)


# -- the ledger's knob sensitivity (arithmetic, no tracing) ------------


def test_tree_cache_cuts_hbm_bytes_not_rows():
    """Cached levels move path rows from HBM planes to private planes:
    HBM bytes strictly fall with k while the row CENSUS (which counts
    private planes too) stays internally consistent."""
    cap_n, b = 1 << 12, 64
    b0 = cm.oram_steady_bytes(cm.machinery_oram_cfg(cap_n, b, k=0), b)
    b2 = cm.oram_steady_bytes(cm.machinery_oram_cfg(cap_n, b, k=2), b)
    b4 = cm.oram_steady_bytes(cm.machinery_oram_cfg(cap_n, b, k=4), b)
    assert b0 > b2 > b4


def test_evict_amortized_bytes_tie_below_saturation():
    """The PR-15 byte structure the verdict rule rides: below window
    saturation the amortized flush equals the E=1 write-back exactly
    (min not clamping), so delayed eviction is byte-neutral; past
    saturation larger E strictly drops bytes."""
    cap_n, b = 1 << 16, 256  # unsaturated at these arms
    e1 = cm.oram_steady_bytes(cm.machinery_oram_cfg(cap_n, b, e=1), b)
    e4 = cm.oram_steady_bytes(cm.machinery_oram_cfg(cap_n, b, e=4), b)
    assert e1 == e4
    cap_n, b = 1 << 16, 1024  # E=8 saturates: min clamps
    e1 = cm.oram_steady_bytes(cm.machinery_oram_cfg(cap_n, b, e=1), b)
    e8 = cm.oram_steady_bytes(cm.machinery_oram_cfg(cap_n, b, e=8), b)
    assert e8 < e1


def test_ab_verdicts_shape():
    """Every A/B kind yields a winner + per-arm modeled bytes (or a
    structural basis) — the dict bench.py embeds per config group."""
    for kind in ("tree_cache", "evict"):
        for scope in ("machinery", "sweep"):
            v = cm.ab_verdict(kind, scope=scope, cap_n=1 << 12, batch=64)
            assert v["winner"] in v["arms"]
            assert all(d["modeled_bytes"] > 0 for d in v["arms"].values())
    for s in (1, 2, 4):
        v = cm.ab_verdict("sharded_evict", cap_n=1 << 12, batch=64,
                          shards=s)
        assert v["winner"] in v["arms"] and v["shards"] == s
        assert all(d["modeled_bytes"] > 0 for d in v["arms"].values())
    assert cm.ab_verdict("sort", backend="cpu")["winner"] == "xla"
    assert cm.ab_verdict("pipeline")["winner"] == "depth2"
    with pytest.raises(ValueError):
        cm.ab_verdict("nonsense")


# -- the gate tool, in-process (the leakcheck wrapper pattern) ---------


def test_check_cost_model_grade_banked_trajectory():
    """The gate's --grade replay covers all five banked A/B kinds and
    the model reproduces every fresh banked winner. Tolerated
    disagreements are pinned by name: PR13's evict sweep b1024 line
    (superseded by PR15's re-measurement of the identical config,
    which agrees — see PERF.md) and PR18's smoke-sized mesh-sim
    sharded_evict lines (regime comment below). Anything else
    disagreeing is a regression in the model or an unexplained machine
    regime, and should fail loudly here."""
    tool = _load_tool("check_cost_model")
    results, problems = tool.grade_trajectory()
    assert problems == []
    assert {r["kind"] for r in results} == {
        "sort", "tree_cache", "evict", "pipeline", "sharded_evict"
    }
    disagreements = {r["config"] for r in results if r["agree"] is False}
    # PR18's sharded_evict lines are cpu-mesh-sim at SMOKE geometry
    # (cap4096/b64, the only size the 2-vCPU host sim can measure):
    # below window saturation amortized flush bytes tie across E, so
    # the byte model's least-machinery tiebreak picks e1, while the
    # host sim's fixed per-dispatch overheads amortize with E and the
    # wall clock favors E>1. Same regime split as evict_ab, where the
    # full-size b256 line agrees on e1 — the banked smoke line records
    # the fetch_fraction_of_e1 acceptance ratio, not a byte claim.
    assert disagreements <= {
        "PR13/sweep/b1024",
        "PR18/machinery/round_cap4096_b64_s1",
        "PR18/machinery/round_cap4096_b64_s2",
        "PR18/machinery/round_cap4096_b64_s4",
    }, disagreements


def test_check_cost_model_smoke_gate():
    """tools/check_cost_model.py --smoke wired into tier-1 next to the
    telemetry/seal/oblint/rangelint gates: the full shipped identity
    matrix cross-validates and every mutant is caught. Budget: traces
    only, zero engine compiles."""
    tool = _load_tool("check_cost_model")
    assert tool.main(["--smoke"]) == 0


def test_telemetry_policy_cost_audit():
    """The telemetry gate's cost-namespace audit passes on the shipped
    CostMonitor: phase-only labels, fixed schedule values, teeth."""
    tool = _load_tool("check_telemetry_policy")
    report = tool.audit_cost_registry()
    assert report["cost_families"] >= 9


# -- the export surface ------------------------------------------------


def _small_ecfg():
    return EngineConfig.from_config(GrapevineConfig(
        max_messages=1 << 10, max_recipients=1 << 7, batch_size=8,
    ))


def test_costmon_gauges_and_residual():
    """CostMonitor exports the static ledger at attach and scores each
    resolved round's device span against the roofline floor."""
    reg = TelemetryRegistry()
    mon = CostMonitor(_small_ecfg(), reg, bandwidth_gbps=10.0)
    assert mon.bandwidth_gbps == 10.0
    steady = reg.get("grapevine_cost_steady_round_hbm_bytes").get()
    assert steady == float(mon.ledger.steady_round_bytes) > 0
    floor = reg.get("grapevine_cost_roofline_floor_ms").get()
    assert floor == pytest.approx(steady / (10.0 * 1e6))
    phase_bytes = reg.get("grapevine_cost_phase_hbm_bytes")
    total = sum(phase_bytes.get(phase=p) for p in cm.COST_PHASES)
    assert total > 0

    # a round whose device span is exactly 2x the floor -> residual 2
    mon.observe_round({"device": (0.0, 2.0 * floor / 1e3)})
    assert reg.get("grapevine_cost_roofline_residual").get() == (
        pytest.approx(2.0))
    mon.observe_round({"device": (0.0, 0.5 * floor / 1e3)})
    assert reg.get("grapevine_cost_roofline_residual").get() == (
        pytest.approx(0.5))
    assert reg.get("grapevine_cost_roofline_residual_max").get() == (
        pytest.approx(2.0))
    # rounds without a device span (tracer detached) are a no-op
    mon.observe_round({})


def test_costmon_bandwidth_resolution_order():
    """Override > GRAPEVINE_COST_GBPS env > per-backend placeholder."""
    assert resolve_bandwidth_gbps(42.0) == 42.0
    old = os.environ.get("GRAPEVINE_COST_GBPS")
    os.environ["GRAPEVINE_COST_GBPS"] = "123.5"
    try:
        assert resolve_bandwidth_gbps() == 123.5
        assert resolve_bandwidth_gbps(7.0) == 7.0
    finally:
        if old is None:
            del os.environ["GRAPEVINE_COST_GBPS"]
        else:
            os.environ["GRAPEVINE_COST_GBPS"] = old
    assert resolve_bandwidth_gbps() > 0


def test_cost_gauges_on_live_engine_metrics():
    """attach_round_observability (the one serving-layer policy point)
    wires the CostMonitor onto a real engine, and the gauges land in
    the same Prometheus exposition a scrape of /metrics serves."""
    from grapevine_tpu.engine.batcher import GrapevineEngine
    from grapevine_tpu.obs import attach_round_observability

    engine = GrapevineEngine(GrapevineConfig(
        max_messages=1 << 10, max_recipients=1 << 7, batch_size=8,
    ))
    try:
        attach_round_observability(engine, engine.metrics.registry)
        assert engine.costmon is not None
        text = render_prometheus(engine.metrics.registry)
        assert "grapevine_cost_steady_round_hbm_bytes" in text
        assert "grapevine_cost_roofline_floor_ms" in text
        assert "grapevine_cost_roofline_residual" in text
        assert 'grapevine_cost_phase_hbm_bytes{phase="fetch"}' in text
    finally:
        engine.close()
