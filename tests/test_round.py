"""Batched ORAM rounds (oram/round.py) and the phase-major engine.

- round vs sequential ORAM: identical logical results on random KV op
  sequences with duplicates and dummies;
- phase-major engine vs the oracle's ``handle_batch`` on random CRUD;
- single-op batches: phase-major ≡ per-op oracle semantics;
- R/U/D transcript bit-equality for the round engine;
- duplicate-key dedup keeps transcript leaves uncorrelated.
"""

import random

import jax
import pytest
import jax.numpy as jnp
import numpy as np

from grapevine_tpu.config import GrapevineConfig
from grapevine_tpu.engine.batcher import GrapevineEngine
from grapevine_tpu.oram.path_oram import (
    OramConfig,
    init_oram,
    oram_access_batch,
    stash_occupancy,
    tree_occupancy,
)
from grapevine_tpu.oram.round import (
    occurrence_masks,
    occurrence_masks_sorted,
    oram_round,
)
from grapevine_tpu.testing.reference import ReferenceEngine
from grapevine_tpu.wire import constants as C
from grapevine_tpu.wire.records import QueryRequest, RequestRecord

U32 = jnp.uint32
NOW = 1_700_000_000

OP_READ, OP_WRITE, OP_DELETE = 1, 2, 3


def kv_fn(value, present, opnd):
    code, val = opnd
    is_w = code == OP_WRITE
    is_d = code == OP_DELETE
    new_value = jnp.where(is_w, val, value)
    keep = ~(is_d & present)
    insert = is_w
    out = {"present": present, "value": jnp.where(present, value, 0)}
    return new_value, keep, insert, out


def kv_apply_batch(cfg, idxs, codes, vals):
    """Vectorized slot-order chain semantics for the simple KV ops —
    the test-side model of the engine's vphases approach: the last
    state-changing op (write/delete) before each op defines its view."""

    def apply_batch(vals0, present0):
        b = idxs.shape[0]
        real = idxs != U32(cfg.dummy_index)
        eq = (idxs[:, None] == idxs[None, :]) & real[:, None] & real[None, :]
        tril_s = jnp.tril(jnp.ones((b, b), jnp.bool_), k=-1)
        iota = jnp.arange(b, dtype=jnp.int32)
        is_w = (codes == OP_WRITE) & real
        is_d = (codes == OP_DELETE) & real
        ch = eq & (is_w | is_d)[None, :]

        def state_at(mask):
            lj = jnp.max(jnp.where(mask, iota[None, :], -1), axis=1)
            has = lj >= 0
            ljc = jnp.clip(lj, 0, b - 1)
            alive = jnp.where(has, is_w[ljc], present0 & real)
            value = jnp.where(
                (has & is_w[ljc])[:, None],
                vals[ljc],
                jnp.where(present0[:, None], vals0, 0),
            )
            return alive, value

        present_i, value_i = state_at(ch & tril_s)  # state before each op
        out = {
            "present": present_i,
            "value": jnp.where(present_i[:, None], value_i, 0),
        }
        final_alive, final_val = state_at(ch)  # state after the round
        return out, final_val, final_alive

    return apply_batch


def _random_kv_batches(cfg, n_batches, batch, seed):
    rng = np.random.default_rng(seed)
    live = set()
    batches = []
    for _ in range(n_batches):
        idxs = np.empty((batch,), np.uint32)
        codes = np.empty((batch,), np.uint32)
        vals = rng.integers(1, 2**31, (batch, cfg.value_words)).astype(np.uint32)
        for i in range(batch):
            r = rng.random()
            if r < 0.1:
                idxs[i] = cfg.dummy_index
                codes[i] = OP_READ
            elif r < 0.5 or not live:
                idxs[i] = rng.integers(0, cfg.leaves)
                codes[i] = OP_WRITE
                live.add(int(idxs[i]))
            elif r < 0.8:
                idxs[i] = rng.choice(sorted(live))
                codes[i] = OP_READ
            else:
                x = int(rng.choice(sorted(live)))
                idxs[i] = x
                codes[i] = OP_DELETE
                live.discard(x)
        batches.append((idxs, codes, vals))
    return batches


def test_round_matches_sequential_oram():
    """Same op stream through oram_access_batch and oram_round gives the
    same logical outputs and the same final contents (leaves differ — the
    two paths draw different randomness; semantics must not)."""
    cfg = OramConfig(height=5, value_words=4, stash_size=96)
    batch = 12
    key = jax.random.PRNGKey(0)
    st_seq = init_oram(cfg, key)
    st_rnd = init_oram(cfg, key)

    seq_step = jax.jit(
        lambda st, idxs, nl, ops: oram_access_batch(cfg, st, idxs, nl, ops, kv_fn),
        static_argnums=(),
    )

    def rnd_fn(st, idxs, nl, dl, codes, vals):
        return oram_round(
            cfg, st, idxs, nl, dl, kv_apply_batch(cfg, idxs, codes, vals)
        )

    rnd_step = jax.jit(rnd_fn)

    rkey = jax.random.PRNGKey(42)
    for bi, (idxs, codes, vals) in enumerate(_random_kv_batches(cfg, 8, batch, 7)):
        rkey, k1, k2, k3 = jax.random.split(rkey, 4)
        nl1 = jax.random.bits(k1, (batch,), U32) & U32(cfg.leaves - 1)
        nl2 = jax.random.bits(k2, (batch,), U32) & U32(cfg.leaves - 1)
        dl = jax.random.bits(k3, (batch,), U32) & U32(cfg.leaves - 1)
        ops = (jnp.asarray(codes), jnp.asarray(vals))
        st_seq, out_s, _ = seq_step(st_seq, jnp.asarray(idxs), nl1, ops)
        st_rnd, out_r, leaves = rnd_step(
            st_rnd, jnp.asarray(idxs), nl2, dl, jnp.asarray(codes), jnp.asarray(vals)
        )
        np.testing.assert_array_equal(
            np.asarray(out_s["present"]), np.asarray(out_r["present"]), f"batch {bi}"
        )
        np.testing.assert_array_equal(
            np.asarray(out_s["value"]), np.asarray(out_r["value"]), f"batch {bi}"
        )
        assert np.asarray(leaves).shape == (batch,)
        assert np.all(np.asarray(leaves) < cfg.leaves)

    assert int(st_seq.overflow) == 0 and int(st_rnd.overflow) == 0
    # identical logical content: same live blocks in tree+stash
    assert int(tree_occupancy(st_seq) + stash_occupancy(st_seq)) == int(
        tree_occupancy(st_rnd) + stash_occupancy(st_rnd)
    )
    # read back every index through the sequential path on both states
    all_idx = jnp.arange(cfg.leaves, dtype=U32)
    zeros = jnp.zeros((cfg.leaves, cfg.value_words), U32)
    ops = (jnp.full((cfg.leaves,), OP_READ, U32), zeros)
    nl = jax.random.bits(jax.random.PRNGKey(9), (cfg.leaves,), U32) & U32(
        cfg.leaves - 1
    )
    _, back_s, _ = oram_access_batch(cfg, st_seq, all_idx, nl, ops, kv_fn)
    _, back_r, _ = oram_access_batch(cfg, st_rnd, all_idx, nl, ops, kv_fn)
    np.testing.assert_array_equal(np.asarray(back_s["present"]), np.asarray(back_r["present"]))
    np.testing.assert_array_equal(np.asarray(back_s["value"]), np.asarray(back_r["value"]))


def test_occurrence_masks():
    idxs = jnp.asarray([3, 5, 3, 9, 5, 3, 7], U32)
    first, last, chain = occurrence_masks(idxs, dummy_index=9)  # 9 = dummy here
    np.testing.assert_array_equal(
        np.asarray(first), [True, True, False, False, False, False, True]
    )
    np.testing.assert_array_equal(
        np.asarray(last), [False, False, False, False, True, True, True]
    )
    # [3,5,3,9,5,3,7]: same-key ops share the first occurrence's slot;
    # the dummy (9) keeps its own
    np.testing.assert_array_equal(np.asarray(chain), [0, 1, 0, 3, 1, 0, 6])


def test_occurrence_masks_sorted_bit_identical():
    """The O(B log B) dedup (scan engine) must match the [B,B] form on
    random index streams with duplicates and dummies, including B=1."""
    rng = np.random.default_rng(17)
    sizes = [1, 2, 5, 8, 16, 32]  # fixed shapes: bounded compile count
    for trial in range(24):
        b = sizes[trial % len(sizes)]
        dummy = 64
        idxs = rng.integers(0, 6, b).astype(np.uint32)
        idxs[rng.random(b) < 0.25] = dummy
        f1, l1, c1 = occurrence_masks(jnp.asarray(idxs), dummy)
        f2, l2, c2 = occurrence_masks_sorted(jnp.asarray(idxs), dummy)
        np.testing.assert_array_equal(np.asarray(f1), np.asarray(f2), trial)
        np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2), trial)
        np.testing.assert_array_equal(np.asarray(c1), np.asarray(c2), trial)


# ---- phase-major engine vs oracle -------------------------------------

SMALL = GrapevineConfig(bucket_cipher_rounds=0, 
    max_messages=64,
    max_recipients=8,
    mailbox_cap=4,
    batch_size=8,
    stash_size=96,
)


def key(n: int) -> bytes:
    return bytes([n, n ^ 0x5A]) + b"\x01" * 30


def req(rt, auth, msg_id=C.ZERO_MSG_ID, recipient=C.ZERO_PUBKEY, pl=None, tag=0):
    return QueryRequest(
        request_type=rt,
        auth_identity=auth,
        auth_signature=b"\x01" * C.SIGNATURE_SIZE,
        record=RequestRecord(
            msg_id=msg_id,
            recipient=recipient,
            payload=pl if pl is not None else bytes([tag & 0xFF]) * C.PAYLOAD_SIZE,
        ),
    )


def assert_responses_equal(dev, ora, ctx=""):
    assert dev.status_code == ora.status_code, f"{ctx}: status {dev.status_code} != {ora.status_code}"
    assert dev.record.msg_id == ora.record.msg_id, f"{ctx}: id"
    assert dev.record.sender == ora.record.sender, f"{ctx}: sender"
    assert dev.record.recipient == ora.record.recipient, f"{ctx}: recipient"
    assert dev.record.payload == ora.record.payload, f"{ctx}: payload"
    assert dev.record.timestamp == ora.record.timestamp, f"{ctx}: ts"


def test_round_engine_matches_batch_oracle():
    """Random multi-op batches (with same-key hazards): round engine must
    agree with the oracle's phase-major handle_batch on everything."""
    _run_engine_vs_oracle(SMALL, n_steps=30)


@pytest.mark.slow  # heaviest randomized campaign of the suite (~77 s:
# ChaCha keystream on a scalar backend dominates); the plaintext
# campaigns above/below stay always-on and the cipher layer keeps its
# directed always-on coverage in test_bucket_cipher.py. Tier-1 budget:
# ROADMAP.md tier-1 note (PR 5).
def test_round_engine_matches_batch_oracle_with_bucket_cipher():
    """Same harness with the at-rest bucket cipher enabled (the shipped
    default): randomized CRUD through encrypted trees must stay
    oracle-identical."""
    import dataclasses

    cfg = dataclasses.replace(SMALL, bucket_cipher_rounds=8)
    _run_engine_vs_oracle(cfg, n_steps=10)


def test_round_engine_matches_batch_oracle_density4():
    """tree_density=4 — the max-capacity-per-HBM-byte shape used by the
    2^22 bench sweep and the 2^24 pod config (tests/test_capacity.py):
    randomized CRUD, then a full expiry sweep, must stay
    oracle-identical at 4x blocks per leaf."""
    import dataclasses

    cfg = dataclasses.replace(SMALL, tree_density=4)
    engine, oracle, t = _run_engine_vs_oracle(cfg, n_steps=12)
    evicted_dev = engine.expire(t + 1000, period=10)
    evicted_ora = oracle.expire(t + 1000, period=10)
    assert evicted_dev == evicted_ora
    assert engine.message_count() == oracle.message_count() == 0
    assert engine.recipient_count() == oracle.recipient_count() == 0


def _run_engine_vs_oracle(cfg, n_steps):
    engine = GrapevineEngine(cfg, seed=3)
    oracle = ReferenceEngine(config=cfg, rng=random.Random(99))
    rng = random.Random(1234)
    idents = [key(i + 1) for i in range(5)]
    live_ids: list[tuple[bytes, bytes, bytes]] = []

    t = NOW
    for step_no in range(n_steps):
        t += rng.randrange(3)
        n_ops = rng.randrange(1, cfg.batch_size + 1)
        reqs = []
        for _ in range(n_ops):
            c = rng.random()
            if c < 0.35 or not live_ids:
                sender, recip = rng.choice(idents), rng.choice(idents)
                reqs.append(req(C.REQUEST_TYPE_CREATE, sender, recipient=recip, tag=rng.randrange(256)))
            elif c < 0.55:
                mid, snd, rcp = rng.choice(live_ids)
                auth = rng.choice([snd, rcp, rng.choice(idents)])
                reqs.append(req(C.REQUEST_TYPE_READ, auth, msg_id=mid))
            elif c < 0.7:
                reqs.append(req(C.REQUEST_TYPE_READ, rng.choice(idents)))
            elif c < 0.8:
                mid, snd, rcp = rng.choice(live_ids)
                reqs.append(req(C.REQUEST_TYPE_UPDATE, rng.choice([snd, rcp]), msg_id=mid, recipient=rcp, tag=rng.randrange(256)))
            elif c < 0.9:
                mid, snd, rcp = rng.choice(live_ids)
                auth = rng.choice([snd, rcp, rng.choice(idents)])
                reqs.append(req(C.REQUEST_TYPE_DELETE, auth, msg_id=mid, recipient=rcp))
            else:
                reqs.append(req(C.REQUEST_TYPE_DELETE, rng.choice(idents)))

        dev_resps = engine.handle_queries(reqs, t)
        forced = [
            dev.record.msg_id
            if r.request_type == C.REQUEST_TYPE_CREATE
            and dev.status_code == C.STATUS_CODE_SUCCESS
            else None
            for r, dev in zip(reqs, dev_resps)
        ]
        ora_resps = oracle.handle_batch(reqs, t, forced)
        for j, (r, dev, ora) in enumerate(zip(reqs, dev_resps, ora_resps)):
            assert_responses_equal(dev, ora, f"step {step_no} slot {j} rt {r.request_type}")
            if ora.status_code == C.STATUS_CODE_SUCCESS:
                if r.request_type == C.REQUEST_TYPE_CREATE:
                    live_ids.append((ora.record.msg_id, ora.record.sender, ora.record.recipient))
                elif r.request_type == C.REQUEST_TYPE_DELETE:
                    live_ids = [e for e in live_ids if e[0] != ora.record.msg_id]

        assert engine.message_count() == oracle.message_count(), f"step {step_no}"
        assert engine.recipient_count() == oracle.recipient_count(), f"step {step_no}"
    assert engine.health()["stash_overflow"] == 0
    return engine, oracle, t


def test_round_engine_single_op_matches_per_op_oracle():
    """For single-op batches, phase-major ≡ per-op semantics — the oracle's
    plain handle_query is the yardstick."""
    cfg = GrapevineConfig(bucket_cipher_rounds=0, 
        max_messages=16, max_recipients=4, mailbox_cap=3, batch_size=1, stash_size=96
    )
    engine = GrapevineEngine(cfg, seed=8)
    oracle = ReferenceEngine(config=cfg, rng=random.Random(5))
    rng = random.Random(77)
    idents = [key(i + 1) for i in range(4)]
    live: list[tuple[bytes, bytes, bytes]] = []
    t = NOW
    for n in range(60):
        t += 1
        c = rng.random()
        if c < 0.45 or not live:
            r = req(C.REQUEST_TYPE_CREATE, rng.choice(idents), recipient=rng.choice(idents), tag=n)
        elif c < 0.65:
            mid, snd, rcp = rng.choice(live)
            r = req(C.REQUEST_TYPE_READ, rng.choice([snd, rcp]), msg_id=mid)
        elif c < 0.8:
            r = req(C.REQUEST_TYPE_READ, rng.choice(idents))
        else:
            r = req(C.REQUEST_TYPE_DELETE, rng.choice(idents))
        (dev,) = engine.handle_queries([r], t)
        forced = (
            dev.record.msg_id
            if r.request_type == C.REQUEST_TYPE_CREATE
            and dev.status_code == C.STATUS_CODE_SUCCESS
            else None
        )
        ora = oracle.handle_query(r, t, forced_msg_id=forced)
        assert_responses_equal(dev, ora, f"op {n}")
        if ora.status_code == C.STATUS_CODE_SUCCESS:
            if r.request_type == C.REQUEST_TYPE_CREATE:
                live.append((ora.record.msg_id, ora.record.sender, ora.record.recipient))
            elif r.request_type == C.REQUEST_TYPE_DELETE:
                live = [e for e in live if e[0] != ora.record.msg_id]


def test_round_engine_rud_transcripts_bit_identical():
    """grapevine.proto:120-122 for the phase-major engine: R/U/D of the
    same message from identically-seeded engines → identical transcripts."""
    a, b = key(7), key(8)

    def fresh():
        e = GrapevineEngine(SMALL, seed=11)
        (r,) = e.handle_queries([req(C.REQUEST_TYPE_CREATE, a, recipient=b)], NOW)
        assert r.status_code == C.STATUS_CODE_SUCCESS
        return e, r.record.msg_id

    transcripts = {}
    for rt in (C.REQUEST_TYPE_READ, C.REQUEST_TYPE_UPDATE, C.REQUEST_TYPE_DELETE):
        e, mid = fresh()
        _, tr = e.handle_queries_with_transcript(
            [req(rt, b, msg_id=mid, recipient=b)], NOW + 1
        )
        transcripts[rt] = tr
    assert np.array_equal(transcripts[C.REQUEST_TYPE_READ], transcripts[C.REQUEST_TYPE_UPDATE])
    assert np.array_equal(transcripts[C.REQUEST_TYPE_READ], transcripts[C.REQUEST_TYPE_DELETE])

    # failed ops indistinguishable from successful ones
    e, mid = fresh()
    _, tr_bad = e.handle_queries_with_transcript(
        [req(C.REQUEST_TYPE_DELETE, key(9), msg_id=mid, recipient=b)], NOW + 1
    )
    assert np.array_equal(transcripts[C.REQUEST_TYPE_DELETE], tr_bad)


def test_duplicate_key_ops_get_uncorrelated_leaves():
    """Two ops on the same message in one batch must not show the same
    records-ORAM leaf (the dedup dummy-fetch rule in oram_round)."""
    e = GrapevineEngine(SMALL, seed=13)
    a, b = key(1), key(2)
    (r,) = e.handle_queries([req(C.REQUEST_TYPE_CREATE, a, recipient=b)], NOW)
    mid = r.record.msg_id
    resps, tr = e.handle_queries_with_transcript(
        [req(C.REQUEST_TYPE_READ, b, msg_id=mid), req(C.REQUEST_TYPE_READ, b, msg_id=mid)],
        NOW + 1,
    )
    assert all(x.status_code == C.STATUS_CODE_SUCCESS for x in resps)
    assert resps[0].record.payload == resps[1].record.payload
    # same mailbox bucket(s) and same record block in one round: the
    # fetched leaves are an independent real draw + an independent dummy
    # draw per column ([a_0..a_{D-1}, b, c_0..c_{D-1}]). A full-row
    # collision has probability (1/leaves)^cols; seed 13 avoids it.
    assert not np.array_equal(tr[0], tr[1])


def test_phase_major_divergence_is_as_documented():
    """The one visible batch hazard: a CREATE cannot reuse a record slot
    freed by an explicit DELETE in the same batch (TOO_MANY_MESSAGES),
    but can in the next batch — and the oracle agrees."""
    cfg = GrapevineConfig(bucket_cipher_rounds=0, 
        max_messages=2, max_recipients=4, mailbox_cap=2, batch_size=4, stash_size=96
    )
    engine = GrapevineEngine(cfg, seed=2)
    oracle = ReferenceEngine(config=cfg, rng=random.Random(3))
    a, b = key(1), key(2)

    def run(reqs, t):
        dev = engine.handle_queries(reqs, t)
        forced = [
            d.record.msg_id
            if r.request_type == C.REQUEST_TYPE_CREATE and d.status_code == C.STATUS_CODE_SUCCESS
            else None
            for r, d in zip(reqs, dev)
        ]
        ora = oracle.handle_batch(reqs, t, forced)
        for i, (d, o) in enumerate(zip(dev, ora)):
            assert_responses_equal(d, o, f"slot {i}")
        return dev

    r1 = run([req(C.REQUEST_TYPE_CREATE, a, recipient=b), req(C.REQUEST_TYPE_CREATE, a, recipient=b)], NOW)
    assert [x.status_code for x in r1] == [C.STATUS_CODE_SUCCESS] * 2
    mid = r1[0].record.msg_id
    # delete + create in ONE batch: the create sees a full bus
    r2 = run(
        [req(C.REQUEST_TYPE_DELETE, b, msg_id=mid, recipient=b),
         req(C.REQUEST_TYPE_CREATE, a, recipient=b)],
        NOW + 1,
    )
    assert r2[0].status_code == C.STATUS_CODE_SUCCESS
    assert r2[1].status_code == C.STATUS_CODE_TOO_MANY_MESSAGES
    # next batch: the freed slot is available
    r3 = run([req(C.REQUEST_TYPE_CREATE, a, recipient=b)], NOW + 2)
    assert r3[0].status_code == C.STATUS_CODE_SUCCESS
