"""Native ristretto255 library ≡ the pure-Python implementation.

The C library (grapevine_tpu/native/r255.c) is verification-speed
infrastructure; the pure-Python RFC 9496 implementation (vector-tested in
test_session.py) is its correctness oracle. Skipped entirely when no C
compiler is available (the package degrades to pure Python)."""

import os
import random

import pytest

from grapevine_tpu import native
from grapevine_tpu.session import ristretto as R

pytestmark = pytest.mark.skipif(
    native.lib is None, reason="no C compiler; pure-Python fallback in use"
)

rng = random.Random(1234)


def test_point_encode_decode_roundtrip_matches_python():
    for _ in range(64):
        k = rng.randrange(1, R.L)
        enc = (k * R.BASEPOINT).encode()
        assert native.reencode(enc) == enc


def test_decode_validity_agrees_with_python():
    cases = [
        b"\x00" * 32,  # identity: valid
        b"\x01" + b"\x00" * 31,
        b"\xff" * 32,
        (R.P - 1).to_bytes(32, "little"),
        (R.P).to_bytes(32, "little"),
    ] + [os.urandom(32) for _ in range(64)]
    for enc in cases:
        py_ok = True
        try:
            R.RistrettoPoint.decode(enc)
        except ValueError:
            py_ok = False
        assert (native.reencode(enc) is not None) == py_ok, enc.hex()


def test_verify_and_batch_agree_with_python_paths():
    items = []
    for i in range(12):
        sk, pub = R.keygen(bytes([i + 1]) * 32)
        msg = bytes([i]) * 32
        sig = R.sign(sk, b"ctx", msg)
        items.append((pub, b"ctx", msg, sig))
    # public API (native-dispatching) accepts all
    for it in items:
        assert R.verify(*it)
    assert R.batch_verify(items)
    # pure-python check of the same signatures (oracle agreement)
    for pub, ctx, msg, sig in items:
        s = int.from_bytes(sig[32:], "little")
        k = R._h_scalar(R._CHAL_DOMAIN, ctx, sig[:32], pub, msg)
        big_r = R.RistrettoPoint.decode(sig[:32])
        a_pt = R.RistrettoPoint.decode(pub)
        assert R._fixed_base_mult(s) == (big_r + k * a_pt)
    # tampering caught by both
    pub, ctx, msg, sig = items[3]
    bad = sig[:32] + bytes([sig[32] ^ 1]) + sig[33:]
    assert not R.verify(pub, ctx, msg, bad)
    bad_batch = list(items)
    bad_batch[3] = (pub, ctx, msg, bad)
    assert not R.batch_verify(bad_batch)


def test_malformed_inputs_return_invalid_not_crash():
    assert not R.verify(b"\x00" * 32, b"c", b"m", b"\xff" * 64)
    assert not R.verify(b"\xff" * 32, b"c", b"m", b"\x00" * 64)
    assert not R.batch_verify([(b"\xff" * 32, b"c", b"m" * 8, b"\x00" * 64)])
    # scalar ≥ L rejected
    sk, pub = R.keygen(b"q" * 32)
    sig = R.sign(sk, b"c", b"m" * 8)
    big_s = sig[:32] + (R.L).to_bytes(32, "little")
    assert not R.verify(pub, b"c", b"m" * 8, big_s)


def test_mult_base_matches_python():
    """Native fixed-base mult ≡ pure-Python scalar·B (the signing path)."""
    for _ in range(32):
        k = rng.randrange(1, R.L)
        assert native.mult_base(k.to_bytes(32, "little")) == (k * R.BASEPOINT).encode()
    # edge scalars: 1, L-1, and a value that reduces mod L
    for k in (1, R.L - 1):
        assert native.mult_base(k.to_bytes(32, "little")) == (k * R.BASEPOINT).encode()


def test_sign_uses_native_and_stays_verifiable():
    """sign() with the native fast path produces signatures the (native
    and python) verifiers accept, and is deterministic."""
    sk, pub = R.keygen(b"\x09" * 32)
    sig1 = R.sign(sk, b"grapevine-challenge", b"m" * 32)
    sig2 = R.sign(sk, b"grapevine-challenge", b"m" * 32)
    assert sig1 == sig2
    assert R.verify(pub, b"grapevine-challenge", b"m" * 32, sig1)


def test_batch_verify_pippenger_paths():
    """Batches large enough to cross the Straus→Pippenger dispatch
    (>64 points → c=6; >=1024 points → c=8). A wrong bucket MSM makes
    the random-linear-combination equation fail with overwhelming
    probability, so valid-batch acceptance + corrupted-batch rejection
    pin the new path against the algebra."""
    import grapevine_tpu.native as native

    if native.lib is None:
        pytest.skip("native library unavailable")
    ctx = b"test-pippenger"
    for n_sigs in (100, 520):  # 200 points (c=6) and 1040 points (c=8)
        items = []
        for i in range(n_sigs):
            sk, pub = R.keygen(i.to_bytes(4, "little") * 8)
            msg = i.to_bytes(8, "little")
            items.append((pub, ctx, msg, R.sign(sk, ctx, msg)))
        assert R.batch_verify(items), f"valid batch of {n_sigs} rejected"
        bad = list(items)
        sig = bytearray(bad[n_sigs // 2][3])
        sig[1] ^= 0x40
        bad[n_sigs // 2] = (bad[n_sigs // 2][0], ctx, bad[n_sigs // 2][2], bytes(sig))
        assert not R.batch_verify(bad), f"corrupted batch of {n_sigs} accepted"


def test_pub_decode_cache_transparent():
    """The C decoded-public-key cache must be semantically invisible:
    same pub verifying twice (hit path), a bad signature under a cached
    pub still rejected, and an invalid encoding rejected repeatedly
    (never cached)."""
    import grapevine_tpu.native as native

    if native.lib is None:
        pytest.skip("native library unavailable")
    sk, pub = R.keygen(b"\x21" * 32)
    ctx, msg = b"cache-test", b"m" * 16
    sig = R.sign(sk, ctx, msg)
    assert R.verify(pub, ctx, msg, sig)      # cold: caches pub
    assert R.verify(pub, ctx, msg, sig)      # hit: same result
    bad = bytearray(sig)
    bad[3] ^= 1
    assert not R.verify(pub, ctx, msg, bytes(bad))  # hit + bad sig
    # invalid encoding: rejected every time, never enters the cache
    non_canonical = b"\xff" * 32
    for _ in range(3):
        assert not R.verify(non_canonical, ctx, msg, sig)
    assert R.verify(pub, ctx, msg, sig)      # cache still coherent
