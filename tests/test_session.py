"""Session-layer tests: ChaCha20 vectors, ristretto255 vectors, Schnorr,
channel handshake + framing, challenge lockstep."""

import pytest

from grapevine_tpu.session import chacha, channel, ristretto
from grapevine_tpu.wire import constants as C


def test_chacha20_rfc7539_vector():
    """RFC 7539 §2.3.2 test vector (key 00..1f, nonce 000000090000004a00000000,
    counter 1)."""
    key = bytes(range(32))
    nonce = bytes.fromhex("000000090000004a00000000")
    stream = chacha.ChaCha20(key, nonce, counter=1)
    block = stream.keystream(64)
    expected = bytes.fromhex(
        "10f1e7e4d13b5915500fdd1fa32071c4"
        "c7d1f4c733c068030422aa9ac3d46c4e"
        "d2826446079faa0914c2d705d98b02a2"
        "b5129cd1de164eb9cbd083e8a2503c4e"
    )
    assert block == expected


def test_challenge_rng_lockstep_and_decoupling():
    seed = bytes(range(32))
    a = chacha.ChallengeRng(seed)
    b = chacha.ChallengeRng(seed)
    c1, c2 = a.next_challenge(), a.next_challenge()
    assert [b.next_challenge(), b.next_challenge()] == [c1, c2]
    assert c1 != c2 and len(c1) == 32
    # different seed → different stream
    assert chacha.ChallengeRng(bytes(32)).next_challenge() != c1


def test_ristretto_basepoint_vectors():
    """Small-multiple encodings from the ristretto255 spec (RFC 9496 §A.1)."""
    B = ristretto.BASEPOINT
    assert (0 * B).encode() == bytes(32)
    assert B.encode() == bytes.fromhex(
        "e2f2ae0a6abc4e71a884a961c500515f58e30b6aa582dd8db6a65945e08d2d76"
    )
    assert (2 * B).encode() == bytes.fromhex(
        "6a493210f7499cd17fecb510ae0cea23a110e8d5b901f8acadd3095c73a3b919"
    )


def test_ristretto_roundtrip_and_group_laws():
    B = ristretto.BASEPOINT
    for k in (1, 2, 3, 57, 1000, ristretto.L - 1):
        pt = k * B
        assert ristretto.RistrettoPoint.decode(pt.encode()) == pt
    assert (3 * B) + (4 * B) == 7 * B
    assert (5 * B) + (-(5 * B)) == ristretto.IDENTITY
    assert (ristretto.L * B) == ristretto.IDENTITY


def test_ristretto_rejects_bad_encodings():
    with pytest.raises(ValueError):
        ristretto.RistrettoPoint.decode(b"\xff" * 32)  # ≥ p
    with pytest.raises(ValueError):
        ristretto.RistrettoPoint.decode(b"\x01" + b"\x00" * 31)  # negative (odd)
    with pytest.raises(ValueError):
        ristretto.RistrettoPoint.decode(b"\x00" * 31)  # wrong length


def test_schnorr_sign_verify():
    sk, pk = ristretto.keygen(b"\x07" * 32)
    ctx = C.GRAPEVINE_CHALLENGE_SIGNING_CONTEXT
    msg = b"\xAA" * 32
    sig = ristretto.sign(sk, ctx, msg)
    assert len(sig) == C.SIGNATURE_SIZE
    assert ristretto.verify(pk, ctx, msg, sig)
    # determinism
    assert ristretto.sign(sk, ctx, msg) == sig
    # any perturbation fails
    assert not ristretto.verify(pk, ctx, b"\xAB" + msg[1:], sig)
    assert not ristretto.verify(pk, b"other-context", msg, sig)
    assert not ristretto.verify(pk, ctx, msg, sig[:-1] + bytes([sig[-1] ^ 1]))
    sk2, pk2 = ristretto.keygen(b"\x08" * 32)
    assert not ristretto.verify(pk2, ctx, msg, sig)
    # malformed inputs return False, never raise
    assert not ristretto.verify(b"\xff" * 32, ctx, msg, sig)
    assert not ristretto.verify(pk, ctx, msg, b"short")


def test_channel_handshake_and_framing():
    priv, client_pub = channel.client_handshake()
    reply, server_chan = channel.server_handshake(client_pub)
    client_chan = channel.client_finish(priv, reply)

    seed = channel.new_challenge_seed()
    ct = server_chan.encrypt(seed)
    assert client_chan.decrypt(ct) == seed

    # bidirectional, multiple frames, constant overhead
    m1 = b"\x01" * C.QUERY_REQUEST_WIRE_SIZE
    m2 = b"\x02" * C.QUERY_REQUEST_WIRE_SIZE
    c1, c2 = client_chan.encrypt(m1), client_chan.encrypt(m2)
    assert len(c1) == len(c2) == C.QUERY_REQUEST_WIRE_SIZE + 16
    assert server_chan.decrypt(c1) == m1
    assert server_chan.decrypt(c2) == m2

    # tampering is detected
    priv2, pub2 = channel.client_handshake()
    reply2, server2 = channel.server_handshake(pub2)
    client2 = channel.client_finish(priv2, reply2)
    bad = bytearray(client2.encrypt(m1))
    bad[5] ^= 1
    with pytest.raises(Exception):
        server2.decrypt(bytes(bad))

    # out-of-order (nonce desync) fails: a skipped frame breaks the stream
    client_chan.encrypt(m1)  # c3: sent but never delivered
    c4 = client_chan.encrypt(m2)
    with pytest.raises(Exception):
        server_chan.decrypt(c4)  # expects c3 first


def test_batch_verify_accepts_valid_and_rejects_forgeries():
    """Random-linear-combination batch verification (one multi-scalar
    multiplication per engine round, SURVEY.md §2b 'consider batch
    verify'): all-valid batches pass; any tampered item fails the batch."""
    from grapevine_tpu.session import ristretto as R

    items = []
    for i in range(8):
        sk, pub = R.keygen(bytes([i + 1]) * 32)
        msg = bytes([i]) * 32
        sig = R.sign(sk, b"ctx", msg)
        assert R.verify(pub, b"ctx", msg, sig)
        items.append((pub, b"ctx", msg, sig))
    assert R.batch_verify(items)
    assert R.batch_verify(items[:1])
    assert R.batch_verify([])

    flipped = list(items)
    pub, ctx, msg, sig = flipped[3]
    flipped[3] = (pub, ctx, msg, sig[:32] + bytes([sig[32] ^ 1]) + sig[33:])
    assert not R.batch_verify(flipped)

    wrong_msg = list(items)
    pub, ctx, msg, sig = wrong_msg[5]
    wrong_msg[5] = (pub, ctx, b"other" + msg[5:], sig)
    assert not R.batch_verify(wrong_msg)

    garbage = list(items)
    garbage[0] = (b"\x00" * 32, b"ctx", b"m" * 32, b"\xff" * 64)
    assert not R.batch_verify(garbage)


def test_fixed_base_mult_matches_naive():
    from grapevine_tpu.session import ristretto as R

    for s in [1, 2, 7, R.L - 1, 0xDEADBEEF1234567890ABCDEF]:
        assert R._fixed_base_mult(s) == (s * R.BASEPOINT)


def test_chacha_fast_backend_matches_pure_python():
    """Whichever fast keystream backend is active (OpenSSL with the
    wheel, the numpy block-axis stream without) is the same RFC 7539
    stream as the pure-Python block-function spec oracle, across
    partial-block draw patterns."""
    from grapevine_tpu.session import chacha

    key = bytes(range(32))
    for pattern in [(32,) * 8, (1, 63, 64, 65, 13, 200), (7,) * 40, (256,)]:
        fast = chacha.ChaCha20(key)
        total = sum(pattern)
        blocks = (total + 63) // 64
        oracle = b"".join(fast._block(i) for i in range(blocks))[:total]
        got = b"".join(fast.keystream(n) for n in pattern)
        assert got == oracle, pattern


def test_chacha_fast_backend_nonzero_counter():
    from grapevine_tpu.session import chacha

    key = b"\x42" * 32
    fast = chacha.ChaCha20(key, counter=7)
    oracle = b"".join(fast._block(7 + i) for i in range(2))[:100]
    assert fast.keystream(100) == oracle
