"""Merlin transcript conformance (the layer under sr25519 signatures).

The STROBE-128/Keccak construction is pinned against merlin's published
transcript test vectors (merlin transcript.rs tests) — if these hold,
every byte the schnorrkel layer feeds through the transcript is framed
exactly as the reference's schnorrkel-og build frames it.
"""

import struct

from grapevine_tpu.session.merlin import Strobe128, Transcript, keccak_f1600


def test_keccak_f1600_known_vector():
    """Keccak-f[1600] on the zero state — first lanes of the standard
    permutation test vector (XKCP TestVectors/KeccakF-1600-IntermediateValues)."""
    st = bytearray(200)
    keccak_f1600(st)
    lanes = struct.unpack("<25Q", st)
    assert lanes[0] == 0xF1258F7940E1DDE7
    assert lanes[1] == 0x84D5CCF933C0478A
    assert lanes[2] == 0xD598261EA65AA9EE
    # second application continues the intermediate-value chain
    keccak_f1600(st)
    lanes = struct.unpack("<25Q", st)
    assert lanes[0] == 0x2D5C954DF96ECB3C


def test_native_keccak_matches_python_oracle():
    """The C permutation (native/r255.c) ≡ the pure-Python oracle on
    random states — and the vector tests above exercise whichever is
    dispatched by default."""
    import os

    from grapevine_tpu import native
    from grapevine_tpu.session.merlin import _keccak_f1600_py

    if native.lib is None:
        import pytest

        pytest.skip("native library unavailable")
    for _ in range(8):
        st = bytearray(os.urandom(200))
        a, b = bytearray(st), bytearray(st)
        native.keccak_f1600(a)
        _keccak_f1600_py(b)
        assert a == b


def test_merlin_simple_transcript_vector():
    """merlin transcript.rs::test equivalence with the simple protocol."""
    t = Transcript(b"test protocol")
    t.append_message(b"some label", b"some data")
    c = t.challenge_bytes(b"challenge", 32)
    assert c.hex() == (
        "d5a21972d0d5fe320c0d263fac7fffb8145aa640af6e9bca177c03c7efcf0615"
    )


def test_merlin_complex_transcript_self_consistent():
    """Interleaved appends/challenges: deterministic, length-framed
    (label ‖ LE32(len) framing means moving a byte across a message
    boundary must change every later challenge)."""
    def run(msgs):
        t = Transcript(b"proto")
        out = []
        for label, data in msgs:
            t.append_message(label, data)
            out.append(t.challenge_bytes(b"c", 16))
        return out

    a = run([(b"x", b"abc"), (b"y", b"defg")])
    b = run([(b"x", b"abc"), (b"y", b"defg")])
    assert a == b
    c = run([(b"x", b"abcd"), (b"y", b"efg")])
    assert a[1] != c[1]


def test_merlin_big_messages_cross_rate_boundary():
    """Absorb > 166-byte rate in one op and across continued ops."""
    t = Transcript(b"big")
    t.append_message(b"blob", bytes(range(256)) * 4)
    c1 = t.challenge_bytes(b"c", 64)
    t2 = Transcript(b"big")
    t2.append_message(b"blob", bytes(range(256)) * 4)
    assert t2.challenge_bytes(b"c", 64) == c1
    # a 400-byte challenge squeezes across the rate boundary too
    assert len(t.challenge_bytes(b"more", 400)) == 400


def test_strobe_op_flag_discipline():
    s = Strobe128(b"proto")
    s.ad(b"data", False)
    s.ad(b"more of the same op", True)
    try:
        s.meta_ad(b"x", True)  # continuing with different flags
    except ValueError:
        pass
    else:  # pragma: no cover
        raise AssertionError("flag mismatch must raise")


def test_transcript_clone_diverges():
    t = Transcript(b"fork")
    t.append_message(b"a", b"1")
    u = t.clone()
    assert t.challenge_bytes(b"c", 32) == u.challenge_bytes(b"c", 32)
    t.append_message(b"b", b"2")
    assert t.challenge_bytes(b"c", 32) != u.challenge_bytes(b"c", 32)


def test_native_strobe_matches_python_oracle():
    """Every C STROBE op (native/r255.c) against the pure-Python duplex:
    drive the same randomized op sequence through both and require
    byte-identical blobs and outputs at every step."""
    import random

    from grapevine_tpu import native
    from grapevine_tpu.session import merlin

    if native.lib is None:
        pytest.skip("native library unavailable")

    rng = random.Random(42)
    # pure-Python twin: monkeypatch the dispatch off for one instance
    # by driving the private oracle methods directly
    nat = Strobe128(b"equiv-proto")
    pure = Strobe128.__new__(Strobe128)
    pure.blob = bytearray(nat.blob)  # same post-init state

    flag_ops = [
        ("meta_ad", merlin._FLAG_M | merlin._FLAG_A),
        ("ad", merlin._FLAG_A),
        ("key", merlin._FLAG_A | merlin._FLAG_C),
    ]
    for step in range(60):
        kind = rng.randrange(4)
        if kind < 3:
            name, flags = flag_ops[kind]
            data = bytes(rng.randrange(256) for _ in range(rng.randrange(0, 400)))
            getattr(nat, name)(data, False)
            # oracle path, bypassing native dispatch
            if name == "key":
                pure._begin_op(flags, False)
                pure._overwrite(data)
            else:
                pure._begin_op(flags, False)
                pure._absorb(data)
        else:
            n = rng.randrange(1, 300)
            out_nat = nat.prf(n, False)
            pure._begin_op(
                merlin._FLAG_I | merlin._FLAG_A | merlin._FLAG_C, False)
            out_pure = pure._squeeze(n)
            assert out_nat == out_pure, f"prf diverged at step {step}"
        assert nat.blob == pure.blob, f"state diverged at step {step}"


def test_native_merlin_transcript_matches_pure(monkeypatch):
    """Transcript-level equivalence: the fused C append/challenge ops vs
    the pure-Python framing, same labels/messages, identical challenges."""
    from grapevine_tpu import native
    from grapevine_tpu.session import merlin

    if native.lib is None:
        pytest.skip("native library unavailable")

    t_nat = Transcript(b"equiv")
    # build the pure twin with native dispatch disabled
    monkeypatch.setattr(merlin, "_native_strobe", lambda: None)
    t_pure = Transcript(b"equiv")
    monkeypatch.undo()

    msgs = [(b"a", b"x" * 3), (b"label-2", b""), (b"l3", bytes(range(200)) * 2)]
    for label, m in msgs:
        t_nat.append_message(label, m)
        monkeypatch.setattr(merlin, "_native_strobe", lambda: None)
        t_pure.append_message(label, m)
        monkeypatch.undo()
    c_nat = t_nat.challenge_bytes(b"c", 64)
    monkeypatch.setattr(merlin, "_native_strobe", lambda: None)
    c_pure = t_pure.challenge_bytes(b"c", 64)
    monkeypatch.undo()
    assert c_nat == c_pure
