"""Malformed-wire fuzzing: decoders fail closed, with ValueError only.

The gRPC handlers translate ValueError into INVALID_ARGUMENT
(server/service.py); any other exception type escaping a decoder would
surface as an opaque handler crash (UNKNOWN) — so the contract under
test is: for arbitrary byte mutations of valid messages, every decoder
either round-trips successfully or raises ValueError. Seeded, not
time-based, so failures reproduce.
"""

import random

import pytest

from grapevine_tpu.testing.fixtures import (
    get_seeded_rng,
    random_query_request,
    random_query_response,
)
from grapevine_tpu.wire import protowire as pw
from grapevine_tpu.wire.records import QueryRequest, QueryResponse

N_CASES = 300


def _mutations(rng: random.Random, blob: bytes):
    """A mix of truncations, extensions, and byte flips."""
    b = bytearray(blob)
    case = rng.randrange(5)
    if case == 0:  # truncate
        return bytes(b[: rng.randrange(len(b))])
    if case == 1:  # extend with junk
        return bytes(b) + bytes(rng.randrange(256) for _ in range(rng.randrange(1, 64)))
    if case == 2:  # flip a single byte
        i = rng.randrange(len(b))
        b[i] ^= rng.randrange(1, 256)
        return bytes(b)
    if case == 3:  # flip several bytes
        for _ in range(rng.randrange(2, 16)):
            i = rng.randrange(len(b))
            b[i] ^= rng.randrange(1, 256)
        return bytes(b)
    return bytes(rng.randrange(256) for _ in range(rng.randrange(0, 2048)))  # noise


@pytest.mark.parametrize(
    "make,unpack",
    [
        (lambda r: random_query_request(r).pack(), QueryRequest.unpack),
        (lambda r: random_query_response(r).pack(), QueryResponse.unpack),
    ],
    ids=["request", "response"],
)
def test_fixed_layout_unpack_fails_closed(make, unpack):
    rng = get_seeded_rng(1)
    for _ in range(N_CASES):
        blob = _mutations(rng, make(rng))
        try:
            unpack(blob)
        except ValueError:
            pass  # the only permitted failure mode


@pytest.mark.parametrize(
    "encode,decode",
    [
        (lambda r: pw.encode_query_request(random_query_request(r)),
         pw.decode_query_request),
        (lambda r: pw.encode_query_response(random_query_response(r)),
         pw.decode_query_response),
    ],
    ids=["request", "response"],
)
def test_protowire_decode_fails_closed(encode, decode):
    rng = get_seeded_rng(2)
    for _ in range(N_CASES):
        blob = _mutations(rng, encode(rng))
        try:
            decode(blob)
        except ValueError:
            pass


def test_envelope_and_auth_decoders_fail_closed():
    rng = get_seeded_rng(3)
    env = pw.encode_envelope(
        pw.EnvelopeMessage(data=b"\x07" * 64, aad=b"a", channel_id=b"c" * 16)
    )
    auth = pw.encode_auth_with_seed(
        pw.AuthMessageWithChallengeSeed(
            auth_message=pw.AuthMessage(data=b"\x05" * 80),
            encrypted_challenge_seed=b"\x06" * 48,
        )
    )
    for blob, dec in [(env, pw.decode_envelope), (auth, pw.decode_auth_with_seed)]:
        for _ in range(N_CASES):
            mut = _mutations(rng, blob)
            try:
                dec(mut)
            except ValueError:
                pass
