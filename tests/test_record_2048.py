"""The 2048-byte record compile-time option (reference README.md:138-139).

The reference offers record size as a compile-time constant (1024
default, 2048 optional). The analog here is a process-wide constant
fixed before import (``GRAPEVINE_RECORD_SIZE``); this test launches a
subprocess in 2048 mode and drives wire-layer constant-size checks plus
an engine CRUD round — proving every derived layout (wire codec, device
block geometry, codecs) follows the option."""

import os
import subprocess
import sys

import pytest

_CHILD = r"""
import os
assert os.environ["GRAPEVINE_RECORD_SIZE"] == "2048"
import jax
jax.config.update("jax_platforms", "cpu")
from grapevine_tpu.wire import constants as C
from grapevine_tpu.wire.records import QueryRequest, QueryResponse, Record, RequestRecord

assert C.RECORD_SIZE == 2048 and C.PAYLOAD_SIZE == 1960
# constant-size property holds at the new geometry (the reference's
# signature test idea, api/tests/grapevine_types.rs:21-31)
sizes = set()
for fill in (b"\x00", b"\xaa", b"\xff"):
    req = QueryRequest(
        request_type=C.REQUEST_TYPE_CREATE,
        auth_identity=fill * 32,
        auth_signature=fill * 64,
        record=RequestRecord(
            msg_id=fill * 16, recipient=fill * 32,
            payload=fill * C.PAYLOAD_SIZE,
        ),
    )
    sizes.add(len(req.pack()))
    assert RequestRecord.unpack(req.pack()[4 + 32 + 64:]).payload == fill * C.PAYLOAD_SIZE
assert sizes == {C.QUERY_REQUEST_WIRE_SIZE}
resp = QueryResponse(record=Record(payload=b"\x07" * C.PAYLOAD_SIZE),
                     status_code=C.STATUS_CODE_SUCCESS)
assert len(resp.pack()) == C.QUERY_RESPONSE_WIRE_SIZE == 2052

# device engine at the 2048-byte block geometry (512-word blocks)
from grapevine_tpu.engine.state import PAYLOAD_WORDS, REC_WORDS
assert (PAYLOAD_WORDS, REC_WORDS) == (490, 512)
from grapevine_tpu.config import GrapevineConfig
from grapevine_tpu.engine.batcher import GrapevineEngine
cfg = GrapevineConfig(bucket_cipher_rounds=8, max_messages=64,
                      max_recipients=8, mailbox_cap=4, batch_size=4,
                      stash_size=64)
e = GrapevineEngine(cfg, seed=1)
a, b = b"\x11" * 32, b"\x22" * 32
r = e.handle_queries([QueryRequest(
    request_type=C.REQUEST_TYPE_CREATE, auth_identity=a,
    record=RequestRecord(recipient=b, payload=b"\x09" * C.PAYLOAD_SIZE))],
    1_700_000_000)[0]
assert r.status_code == C.STATUS_CODE_SUCCESS
r2 = e.handle_queries([QueryRequest(
    request_type=C.REQUEST_TYPE_READ, auth_identity=b,
    record=RequestRecord(msg_id=C.ZERO_MSG_ID))], 1_700_000_001)[0]
assert r2.status_code == C.STATUS_CODE_SUCCESS
assert r2.record.payload == b"\x09" * C.PAYLOAD_SIZE
print("RECORD2048_OK")
"""


@pytest.mark.slow  # ~64 s whole-engine subprocess campaign at the 2 KB
# record size (fresh jit compile of the doubled geometry each run);
# directed 2 KB layout-constant checks stay always-on above. Tier-1
# budget: ROADMAP.md tier-1 note (PR 5).
def test_2048_byte_record_mode():
    env = dict(os.environ)
    env["GRAPEVINE_RECORD_SIZE"] = "2048"
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, "-c", _CHILD], env=env, capture_output=True,
        text=True, timeout=600,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert "RECORD2048_OK" in out.stdout, out.stderr[-2000:]


def test_invalid_record_size_rejected():
    env = dict(os.environ)
    env["GRAPEVINE_RECORD_SIZE"] = "1536"
    out = subprocess.run(
        [sys.executable, "-c", "from grapevine_tpu.wire import constants"],
        env=env, capture_output=True, text=True, timeout=120,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert out.returncode != 0 and "1024 or 2048" in out.stderr
