"""BatchScheduler quiescence collection (server/scheduler.py).

The window must stay open while a wave of requests is still trickling
in (inter-arrival gap < idle_gap) and close once arrivals stall, capped
at max_wait — measured 26% round occupancy with the old fixed window
(PERF.md). Uses a stub engine (no JAX) and generous timing margins so
the test is stable on a single-core host.
"""

import threading
import time

from grapevine_tpu.engine.metrics import EngineMetrics
from grapevine_tpu.server.scheduler import BatchScheduler
from grapevine_tpu.wire import constants as C
from grapevine_tpu.wire.records import QueryRequest, QueryResponse, Record


class _StubEcfg:
    batch_size = 16


class _StubEngine:
    """Counts rounds; responds instantly."""

    def __init__(self):
        self.ecfg = _StubEcfg()
        self.metrics = EngineMetrics()
        self.rounds: list[int] = []  # ops per round
        self._lock = threading.Lock()

    def handle_queries(self, reqs, now):
        with self._lock:
            self.rounds.append(len(reqs))
        zero = Record(
            msg_id=C.ZERO_MSG_ID,
            sender=C.ZERO_PUBKEY,
            recipient=C.ZERO_PUBKEY,
            timestamp=0,
            payload=b"\x00" * C.PAYLOAD_SIZE,
        )
        return [
            QueryResponse(record=zero, status_code=C.STATUS_CODE_SUCCESS)
            for _ in reqs
        ]

    def handle_queries_async(self, reqs, now):
        resps = self.handle_queries(reqs, now)

        class _Pending:
            def resolve(self):
                return resps

        return _Pending()


def _req():
    return QueryRequest(
        request_type=C.REQUEST_TYPE_READ,
        auth_identity=b"\x01" * 32,
        auth_signature=b"\x02" * C.SIGNATURE_SIZE,
        record=None,
    )


def test_trickling_wave_lands_in_one_round():
    eng = _StubEngine()
    sched = BatchScheduler(eng, max_wait_ms=2000.0, idle_gap_ms=300.0)
    try:
        threads = [
            threading.Thread(target=sched.submit, args=(_req(),)) for _ in range(6)
        ]
        for t in threads:
            t.start()
            time.sleep(0.05)  # arrivals well inside the 300ms idle gap
        for t in threads:
            t.join(timeout=10)
        assert eng.rounds == [6], f"wave split across rounds: {eng.rounds}"
    finally:
        sched.close()


def test_stalled_arrivals_close_the_round():
    eng = _StubEngine()
    sched = BatchScheduler(eng, max_wait_ms=5000.0, idle_gap_ms=150.0)
    try:
        t1 = threading.Thread(target=sched.submit, args=(_req(),))
        t1.start()
        t1.join(timeout=10)  # idle gap passes with nothing else queued
        assert eng.rounds == [1], "lone request should commit after idle_gap"
        # a second burst forms its own round
        t2 = threading.Thread(target=sched.submit, args=(_req(),))
        t3 = threading.Thread(target=sched.submit, args=(_req(),))
        t2.start(); t3.start()
        t2.join(timeout=10); t3.join(timeout=10)
        assert eng.rounds[0] == 1 and sum(eng.rounds) == 3
    finally:
        sched.close()


def test_stall_age_sees_wedged_inflight_round():
    """A round wedged on the device empties the queue — stall_age()
    must age the in-flight round, or /healthz serves 200 while every
    blocked client hangs on fut.result() forever."""
    unwedge = threading.Event()

    class _WedgedEngine(_StubEngine):
        def handle_queries_async(self, reqs, now):
            resps = self.handle_queries(reqs, now)

            class _Pending:
                def resolve(self):
                    unwedge.wait(timeout=30)  # the wedge
                    return resps

            return _Pending()

    eng = _WedgedEngine()
    sched = BatchScheduler(eng, max_wait_ms=50.0, idle_gap_ms=10.0)
    try:
        assert sched.stall_age() == 0.0  # idle: no queue, nothing in flight
        t = threading.Thread(target=sched.submit, args=(_req(),))
        t.start()
        # the op leaves the queue (dispatched) but never resolves; the
        # stall signal must keep growing with an empty queue
        deadline = time.monotonic() + 10
        while sched.stall_age() < 0.2 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert sched.stall_age() >= 0.2, "wedged in-flight round invisible"
        assert sched.worker_alive()
        unwedge.set()
        t.join(timeout=10)
        deadline = time.monotonic() + 10
        while sched.stall_age() > 0.0 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert sched.stall_age() == 0.0  # settled: signal clears
    finally:
        unwedge.set()
        sched.close()


def test_full_batch_commits_without_waiting():
    eng = _StubEngine()
    sched = BatchScheduler(eng, max_wait_ms=10_000.0, idle_gap_ms=10_000.0)
    try:
        threads = [
            threading.Thread(target=sched.submit, args=(_req(),))
            for _ in range(_StubEcfg.batch_size)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        # a full batch must not sit out the 10s window
        assert time.perf_counter() - t0 < 5.0
        assert eng.rounds and max(eng.rounds) == _StubEcfg.batch_size
    finally:
        sched.close()
