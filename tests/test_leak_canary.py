"""Leak-canary tests: prove the transcript detectors have teeth.

SURVEY §5 names this the security analog of a race detector's
self-test: deliberately break obliviousness and assert the harness
*catches* it. Each canary builds a real leak through the public
``oram_round`` parameters — the round trusts its callers to supply
fresh uniform ``new_leaves``/``dummy_leaves`` (engine/round_step.py:76-87
draws them from the engine RNG), so a careless caller IS the realistic
bug, and the canaries run the production round code, not a mock:

- **no-dedup canary**: dummy fetches reuse the key's real leaf
  (``dummy_leaves = posmap[idxs]``) → same-key ops in one round show
  equal leaves → `samekey_leaf_collisions` fires;
- **no-remap canary**: the remap target is the key's *current* leaf
  (``new_leaves = posmap[idxs]``) → every later round re-fetches the
  same path → `cross_round_repeat_rate` ≈ 1;
- **biased-dummy canary**: absent/padding ops fetch constant leaf 0
  → pooled transcript skews → `uniformity_z` explodes.

The honest engine (fresh uniform draws, same shapes, same seeds) passes
all three detectors in the same run — so a regression that weakens
either the round or the detectors turns at least one assertion red.
"""

import jax
import jax.numpy as jnp
import numpy as np

from grapevine_tpu.oram.path_oram import OramConfig, init_oram
from grapevine_tpu.oram.round import oram_round
from grapevine_tpu.testing.leakcheck import (
    cross_round_repeat_rate,
    samekey_leaf_collisions,
    uniformity_z,
)

U32 = jnp.uint32
NOW = 1_700_000_000

CFG = OramConfig(height=12, value_words=4, stash_size=128)
B = 16


def _passthrough(vals0, present0):
    """Read-only apply: no inserts, no kills — isolates the transcript."""
    return {}, vals0, present0


def _step(state, idxs, nl, dl):
    st, _, leaves = oram_round(CFG, state, idxs, nl, dl, _passthrough)
    return st, leaves


STEP = jax.jit(_step)


def _uniform(key, n=B):
    return jax.random.bits(key, (n,), U32) & U32(CFG.leaves - 1)


def _populated(seed=0):
    """An ORAM with blocks 0..B-1 inserted (so lookups are real)."""
    state = init_oram(CFG, jax.random.PRNGKey(seed))

    def ins(vals0, present0):
        return {}, jnp.ones_like(vals0), jnp.ones_like(present0)

    key = jax.random.PRNGKey(seed + 100)
    k1, k2 = jax.random.split(key)
    idxs = jnp.arange(B, dtype=U32)
    state, _, _ = oram_round(CFG, state, idxs, _uniform(k1), _uniform(k2), ins)
    return state


def test_no_dedup_canary_trips_collision_detector():
    state = _populated()
    # every op in the round touches the SAME key
    idxs = jnp.zeros((B,), U32)
    k = jax.random.PRNGKey(1)
    k1, k2 = jax.random.split(k)

    # honest: fresh uniform dummy leaves → no same-key collisions
    # (120 pairs × 1/4096 per pair ⇒ P(any) ≈ 3%; seed avoids the fluke)
    _, leaves = STEP(state, idxs, _uniform(k1), _uniform(k2))
    honest = samekey_leaf_collisions(np.asarray(idxs), np.asarray(leaves))

    # leaky: dummies fetch the key's real current leaf
    real_leaf = jnp.broadcast_to(state.posmap[0], (B,))
    _, leaves_bad = STEP(state, idxs, _uniform(k1), real_leaf)
    leaky = samekey_leaf_collisions(np.asarray(idxs), np.asarray(leaves_bad))

    assert honest == 0, "honest round showed correlated same-key leaves"
    assert leaky == B * (B - 1) // 2, "detector missed the no-dedup leak"


def test_no_remap_canary_trips_repeat_detector():
    k = jax.random.PRNGKey(2)
    # track key 3 via slot 0; every other slot is a padding dummy
    idxs = jnp.where(jnp.arange(B) == 0, U32(3), U32(CFG.dummy_index))

    def run(leaky: bool, rounds=12):
        state = _populated()
        key = k
        seq = []
        for _ in range(rounds):
            key, k1, k2 = jax.random.split(key, 3)
            nl = state.posmap[idxs] if leaky else _uniform(k1)
            _state, leaves = STEP(state, idxs, nl, _uniform(k2))
            state = _state
            seq.append(int(np.asarray(leaves)[0]))
        return np.asarray(seq)

    assert cross_round_repeat_rate(run(leaky=False)) < 0.2
    assert cross_round_repeat_rate(run(leaky=True)) == 1.0, (
        "detector missed the no-remap leak"
    )


def test_biased_dummy_canary_trips_uniformity_detector():
    k = jax.random.PRNGKey(3)
    idxs = jnp.full((B,), U32(CFG.dummy_index))  # an all-padding round

    def run(leaky: bool, rounds=24):
        state = _populated()
        key = k
        pool = []
        for _ in range(rounds):
            key, k1, k2 = jax.random.split(key, 3)
            dl = jnp.zeros((B,), U32) if leaky else _uniform(k2)
            state, leaves = STEP(state, idxs, _uniform(k1), dl)
            pool.append(np.asarray(leaves))
        return np.concatenate(pool)

    z_honest = uniformity_z(run(leaky=False), CFG.leaves)
    z_leaky = uniformity_z(run(leaky=True), CFG.leaves)
    assert abs(z_honest) < 6, f"honest transcript flagged non-uniform (z={z_honest})"
    assert z_leaky > 50, f"detector missed the biased-dummy leak (z={z_leaky})"


def test_engine_transcript_passes_all_detectors():
    """The production engine's own transcript (mailbox + records leaves
    over mixed-CRUD rounds) clears every detector — the positive control
    that the honest path satisfies what the canaries falsify."""
    import random

    from grapevine_tpu.config import GrapevineConfig
    from grapevine_tpu.engine.batcher import GrapevineEngine
    from grapevine_tpu.wire import constants as C
    from grapevine_tpu.wire.records import QueryRequest, RequestRecord

    cfg = GrapevineConfig(
        bucket_cipher_rounds=0,
        max_messages=256,
        max_recipients=64,
        mailbox_cap=8,
        batch_size=4,
        stash_size=96,
    )
    e = GrapevineEngine(cfg, seed=5)
    rng = random.Random(9)
    a = bytes([1]) * 32
    b = bytes([2]) * 32

    def req(rt, auth, msg_id=C.ZERO_MSG_ID, recipient=C.ZERO_PUBKEY):
        return QueryRequest(
            request_type=rt,
            auth_identity=auth,
            auth_signature=b"\x01" * C.SIGNATURE_SIZE,
            record=RequestRecord(
                msg_id=msg_id,
                recipient=recipient,
                payload=bytes([rng.randrange(256)]) * C.PAYLOAD_SIZE,
            ),
        )

    # transcript columns: [a_0..a_{D-1}, b, c_0..c_{D-1}] (round_step.py)
    dcol = cfg.resolved_mailbox_choices
    mb_cols = list(range(dcol)) + list(range(dcol + 1, 2 * dcol + 1))
    mb_pool, rec_pool = [], []
    mid = None
    rec_leaves_of_mid = []
    for t in range(24):
        reqs = [req(C.REQUEST_TYPE_CREATE, a, recipient=b)]
        if mid is not None:
            reqs.append(req(C.REQUEST_TYPE_READ, b, msg_id=mid))
        resps, tr = e.handle_queries_with_transcript(reqs, 1_700_000_000 + t)
        tr = np.asarray(tr)
        if mid is None and resps[0].status_code == C.STATUS_CODE_SUCCESS:
            mid = resps[0].record.msg_id
        elif mid is not None:
            rec_leaves_of_mid.append(int(tr[1, dcol]))  # records-round leaf
        mb_pool.append(tr[:, mb_cols].ravel())
        rec_pool.append(tr[:, dcol])

    from grapevine_tpu.engine.state import EngineConfig

    ecfg = EngineConfig.from_config(cfg)
    assert abs(uniformity_z(np.concatenate(mb_pool), ecfg.mb.leaves, bins=8)) < 6
    assert abs(uniformity_z(np.concatenate(rec_pool), ecfg.rec.leaves, bins=8)) < 6
    # the SAME record read every round draws fresh leaves each time
    assert cross_round_repeat_rate(np.asarray(rec_leaves_of_mid)) < 0.3


def test_rud_transcript_distributions_indistinguishable():
    """SURVEY §4 pyramid item 4, distributional form: transcripts of
    all-READ vs all-UPDATE vs all-DELETE sessions over DIFFERENT random
    engines are two-sample-indistinguishable; a synthetic op-type leaf
    bias is caught by the same detector (the canary)."""
    import random

    from grapevine_tpu.config import GrapevineConfig
    from grapevine_tpu.engine.batcher import GrapevineEngine
    from grapevine_tpu.engine.state import EngineConfig
    from grapevine_tpu.testing.leakcheck import twosample_z
    from grapevine_tpu.wire import constants as C
    from grapevine_tpu.wire.records import QueryRequest, RequestRecord

    cfg = GrapevineConfig(
        bucket_cipher_rounds=0,
        max_messages=256,
        max_recipients=32,
        mailbox_cap=8,
        batch_size=4,
        stash_size=96,
    )
    ecfg = EngineConfig.from_config(cfg)
    a, b = bytes([1]) * 32, bytes([2]) * 32

    def req(rt, auth, msg_id=C.ZERO_MSG_ID, recipient=C.ZERO_PUBKEY, tag=0):
        return QueryRequest(
            request_type=rt,
            auth_identity=auth,
            auth_signature=b"\x01" * C.SIGNATURE_SIZE,
            record=RequestRecord(
                msg_id=msg_id,
                recipient=recipient,
                payload=bytes([tag]) * C.PAYLOAD_SIZE,
            ),
        )

    def session_leaves(rt, seed, n_rounds=12):
        """Create a message, then hammer it with `rt` ops; pool the
        records-round leaf of each rt round itself."""
        rng = random.Random(seed)
        e = GrapevineEngine(cfg, seed=seed)
        (r0,) = e.handle_queries([req(C.REQUEST_TYPE_CREATE, a, recipient=b)], NOW)
        assert r0.status_code == C.STATUS_CODE_SUCCESS
        pool = []
        for t in range(n_rounds):
            if rt == C.REQUEST_TYPE_DELETE:
                # recreate so the delete target always exists
                (rc,) = e.handle_queries(
                    [req(C.REQUEST_TYPE_CREATE, a, recipient=b, tag=t & 0xFF)],
                    NOW + 2 * t,
                )
                mid = rc.record.msg_id
            else:
                mid = r0.record.msg_id
            resps, tr = e.handle_queries_with_transcript(
                [req(rt, b, msg_id=mid, recipient=b, tag=rng.randrange(256))],
                NOW + 2 * t + 1,
            )
            # the rt op itself must succeed — a silently failing op
            # would make all three pools identical no-op samples
            assert resps[0].status_code == C.STATUS_CODE_SUCCESS
            # records-round leaf: column D in [a_0..a_{D-1}, b, c_...]
            pool.append(int(np.asarray(tr)[0, cfg.resolved_mailbox_choices]))
        return np.asarray(pool)

    pools = {}
    for rt in (C.REQUEST_TYPE_READ, C.REQUEST_TYPE_UPDATE, C.REQUEST_TYPE_DELETE):
        pools[rt] = np.concatenate([session_leaves(rt, s) for s in range(6)])
    n_leaves = ecfg.rec.leaves
    zs = [
        twosample_z(pools[C.REQUEST_TYPE_READ], pools[C.REQUEST_TYPE_UPDATE], n_leaves, bins=8),
        twosample_z(pools[C.REQUEST_TYPE_READ], pools[C.REQUEST_TYPE_DELETE], n_leaves, bins=8),
    ]
    for z in zs:
        assert abs(z) < 6, f"honest R/U/D distributions separated (z={z})"
    # canary: a leaf bias keyed on op type must be caught
    biased = pools[C.REQUEST_TYPE_DELETE] % (n_leaves // 8)  # squashed range
    z_bad = twosample_z(pools[C.REQUEST_TYPE_READ], biased, n_leaves, bins=8)
    assert z_bad > 20, f"detector missed the op-type bias (z={z_bad})"
