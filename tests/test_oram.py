"""Path ORAM correctness: dict-model equivalence, transcript equality with
the plain-Python mirror, determinism, and stash bounds.

The test pyramid from SURVEY.md §4: (2) results equal a plain dict model;
(3) public transcripts bit-identical to the scalar CPU reference.
"""

import dataclasses
import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from grapevine_tpu.oram.path_oram import (
    OramConfig,
    init_oram,
    oram_access,
    oram_access_batch,
    stash_occupancy,
    tree_occupancy,
)
from grapevine_tpu.testing.ref_oram import RefPathOram

CFG = OramConfig(height=5, value_words=4, bucket_slots=4, stash_size=48)


def _fn(value, present, operand):
    """Generic test op: mode 0=read, 1=write(insert), 2=delete."""
    mode, wval = operand["mode"], operand["wval"]
    is_write = mode == 1
    is_delete = mode == 2
    new_value = jnp.where(is_write, wval, value)
    keep = ~is_delete
    insert = is_write
    out = {"value": value, "present": present}
    return new_value, keep, insert, out


def _ref_fn_factory(mode, wval):
    def fn(value, present):
        new_value = tuple(wval) if mode == 1 else value
        keep = mode != 2
        insert = mode == 1
        return new_value, keep, insert, {"value": value, "present": present}

    return fn


@pytest.fixture(scope="module")
def jit_access():
    return jax.jit(oram_access, static_argnums=(0, 5))


def random_ops(seed, n_ops, cfg):
    """A random op sequence with a live-set model driving sensible ops."""
    rng = random.Random(seed)
    live = {}
    ops = []
    for _ in range(n_ops):
        choices = ["insert"]
        if live:
            choices += ["read", "read", "delete", "update"]
        if len(live) >= cfg.blocks - 1:
            choices = ["read", "read", "delete", "update"]
        c = rng.choice(choices)
        if c == "insert":
            free = [i for i in range(cfg.blocks) if i not in live]
            idx = rng.choice(free)
            val = tuple(rng.getrandbits(32) for _ in range(cfg.value_words))
            live[idx] = val
            ops.append((1, idx, val))
        elif c == "update":
            idx = rng.choice(list(live))
            val = tuple(rng.getrandbits(32) for _ in range(cfg.value_words))
            live[idx] = val
            ops.append((1, idx, val))
        elif c == "read":
            # mix of live reads and misses
            idx = rng.choice(list(live)) if rng.random() < 0.8 else rng.randrange(cfg.blocks)
            ops.append((0, idx, (0,) * cfg.value_words))
        else:
            idx = rng.choice(list(live))
            del live[idx]
            ops.append((2, idx, (0,) * cfg.value_words))
    return ops


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("density", [1, 2])
def test_oram_matches_dict_model_and_mirror_transcript(seed, density):
    """One jitted scan over 300 random ops; bulk-compare every output with
    the plain dict model and the scalar mirror (results AND transcript).
    Runs the classic blocks == leaves shape and the packed density-2
    shape (the shipped default, config.tree_density)."""
    cfg = CFG if density == 1 else dataclasses.replace(
        CFG, n_blocks=CFG.leaves * 2
    )
    key = jax.random.PRNGKey(seed)
    state = init_oram(cfg, key)
    mirror = RefPathOram(cfg, np.asarray(state.posmap).tolist())

    n_ops = 300
    ops = random_ops(seed, n_ops, cfg)
    leaf_rng = random.Random(1000 + seed)
    new_leaves = [leaf_rng.randrange(cfg.leaves) for _ in range(n_ops)]

    modes = np.array([m for m, _, _ in ops], np.uint32)
    idxs = np.array([i for _, i, _ in ops], np.uint32)
    wvals = np.array([v for _, _, v in ops], np.uint32)

    batched = jax.jit(oram_access_batch, static_argnums=(0, 5))
    state, outs, leaves = batched(
        cfg,
        state,
        jnp.array(idxs),
        jnp.array(new_leaves, dtype=jnp.uint32),
        {"mode": jnp.array(modes), "wval": jnp.array(wvals)},
        _fn,
    )
    leaves = np.asarray(leaves)
    out_present = np.asarray(outs["present"])
    out_values = np.asarray(outs["value"])
    assert int(state.overflow) == 0

    # replay through the scalar mirror and the dict model, compare everything
    model = {}
    for t, (mode, idx, val) in enumerate(ops):
        ref_out, ref_leaf = mirror.access(
            idx, new_leaves[t], _ref_fn_factory(mode, val)
        )
        assert leaves[t] == ref_leaf, f"transcript diverged at op {t}"
        assert bool(out_present[t]) == ref_out["present"] == (idx in model)
        if idx in model and mode == 0:
            assert tuple(out_values[t]) == model[idx] == ref_out["value"]
        if mode == 1:
            model[idx] = val
        elif mode == 2:
            model.pop(idx, None)
    assert mirror.overflow == 0

    # end state: occupancy agrees everywhere
    assert int(stash_occupancy(state)) + int(tree_occupancy(state)) == len(model)
    assert int(stash_occupancy(state)) == mirror.stash_occupancy()


def test_transcript_deterministic(jit_access):
    """Same seed → same transcript; the engine's replayability guarantee."""

    def run():
        key = jax.random.PRNGKey(7)
        state = init_oram(CFG, key)
        leaves = []
        leaf_rng = random.Random(7)
        for i in range(50):
            operand = {
                "mode": jnp.uint32(1),
                "wval": jnp.arange(CFG.value_words, dtype=jnp.uint32) + i,
            }
            state, _, leaf = jit_access(
                CFG,
                state,
                jnp.uint32(i % CFG.leaves),
                jnp.uint32(leaf_rng.randrange(CFG.leaves)),
                operand,
                _fn,
            )
            leaves.append(leaf)
        return np.asarray(jnp.stack(leaves)).tolist()

    assert run() == run()


def test_batch_scan_matches_sequential(jit_access):
    """oram_access_batch(scan) ≡ the same accesses issued one by one."""
    key = jax.random.PRNGKey(3)
    state_a = init_oram(CFG, key)
    state_b = init_oram(CFG, key)

    B = 32
    rng = random.Random(5)
    idxs = np.array([rng.randrange(CFG.leaves) for _ in range(B)], np.uint32)
    leaves_in = np.array([rng.randrange(CFG.leaves) for _ in range(B)], np.uint32)
    modes = np.array([1] * (B // 2) + [0] * (B // 2), np.uint32)
    wvals = np.array(
        [[rng.getrandbits(32) for _ in range(CFG.value_words)] for _ in range(B)],
        np.uint32,
    )
    operands = {"mode": jnp.array(modes), "wval": jnp.array(wvals)}

    batched = jax.jit(oram_access_batch, static_argnums=(0, 5))
    state_a, outs, leaves_a = batched(
        CFG, state_a, jnp.array(idxs), jnp.array(leaves_in), operands, _fn
    )

    seq_leaves = []
    for i in range(B):
        operand = {"mode": jnp.uint32(modes[i]), "wval": jnp.array(wvals[i])}
        state_b, out, leaf = jit_access(
            CFG, state_b, jnp.uint32(idxs[i]), jnp.uint32(leaves_in[i]), operand, _fn
        )
        seq_leaves.append(leaf)
    seq_leaves = np.asarray(jnp.stack(seq_leaves)).tolist()

    assert np.asarray(leaves_a).tolist() == seq_leaves
    assert jax.tree_util.tree_all(
        jax.tree_util.tree_map(lambda a, b: bool(jnp.all(a == b)), state_a, state_b)
    )


def test_stash_bounded_under_load():
    """Fill to 75% occupancy, hammer with accesses: stash stays small."""
    cfg = OramConfig(height=7, value_words=2, bucket_slots=4, stash_size=64)
    key = jax.random.PRNGKey(11)
    state = init_oram(cfg, key)
    access = jax.jit(oram_access_batch, static_argnums=(0, 5))

    n = (cfg.leaves * 3) // 4
    rng = random.Random(13)
    idxs = jnp.arange(n, dtype=jnp.uint32)
    leaves_in = jnp.array([rng.randrange(cfg.leaves) for _ in range(n)], jnp.uint32)
    operands = {
        "mode": jnp.ones((n,), jnp.uint32),
        "wval": jnp.ones((n, cfg.value_words), jnp.uint32),
    }
    state, _, _ = access(cfg, state, idxs, leaves_in, operands, _fn)
    assert int(state.overflow) == 0

    high_water = 0
    for round_ in range(10):
        perm = [rng.randrange(n) for _ in range(64)]
        idxs = jnp.array(perm, jnp.uint32)
        leaves_in = jnp.array(
            [rng.randrange(cfg.leaves) for _ in range(64)], jnp.uint32
        )
        operands = {
            "mode": jnp.zeros((64,), jnp.uint32),
            "wval": jnp.zeros((64, cfg.value_words), jnp.uint32),
        }
        state, _, _ = access(cfg, state, idxs, leaves_in, operands, _fn)
        high_water = max(high_water, int(stash_occupancy(state)))
        assert int(state.overflow) == 0

    # Z=4 Path ORAM stash stays far below the budget
    assert high_water < cfg.stash_size // 2, high_water


def test_density_packed_tree_stash_behavior():
    """blocks > leaves (tree_density 2 and 4): fill the ORAM to 90% of
    the block space and hammer it with random batched rounds; results
    stay correct (vs a dict model), nothing is dropped, and the stash
    keeps headroom. This is the evidence behind config.tree_density."""

    from grapevine_tpu.oram.round import oram_round
    from grapevine_tpu.oram.path_oram import stash_occupancy

    for density in (2, 4):
        cfg = OramConfig(
            height=8, value_words=2, stash_size=160, n_blocks=(1 << 8) * density
        )
        key = jax.random.PRNGKey(density)
        state = init_oram(cfg, key)
        model = {}
        rng = np.random.default_rng(density)
        b = 16

        def kv_apply(idxs, vals):
            def apply_batch(vals0, present0):
                # last write per key wins; write everything
                return {}, vals, jnp.ones_like(present0)

            return apply_batch

        n_fill = int(0.9 * cfg.blocks)
        live = rng.choice(cfg.blocks, size=n_fill, replace=False)
        step = jax.jit(
            lambda st, idxs, nl, dl, vals: oram_round(
                cfg, st, idxs, nl, dl, kv_apply(idxs, vals), None
            ),
            static_argnums=(),
        )
        hw = 0
        pos = 0
        k2 = jax.random.PRNGKey(999)
        while pos < n_fill:
            chunk = live[pos : pos + b]
            idxs = np.full((b,), cfg.dummy_index, np.uint32)
            idxs[: len(chunk)] = chunk
            vals = np.zeros((b, 2), np.uint32)
            vals[: len(chunk), 0] = chunk
            vals[: len(chunk), 1] = 1
            k2, ka, kb = jax.random.split(k2, 3)
            nl = jax.random.bits(ka, (b,), jnp.uint32) & jnp.uint32(cfg.leaves - 1)
            dl = jax.random.bits(kb, (b,), jnp.uint32) & jnp.uint32(cfg.leaves - 1)
            state, _, _ = step(state, jnp.asarray(idxs), nl, dl, jnp.asarray(vals))
            for c in chunk:
                model[int(c)] = 1
            pos += b
            hw = max(hw, int(stash_occupancy(state)))
        assert int(state.overflow) == 0, f"density {density}: dropped blocks"
        assert hw < cfg.stash_size // 2, (
            f"density {density}: stash high-water {hw}/{cfg.stash_size}"
        )
        # every live block is where the posmap says (full sweep readback)
        occupied = int(
            jnp.sum(state.tree_idx != jnp.uint32(0xFFFFFFFF))
        ) + int(jnp.sum(state.stash_idx != jnp.uint32(0xFFFFFFFF)))
        assert occupied == len(model)
