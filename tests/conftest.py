"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

The analog of the reference's ``SGX_MODE=SW`` simulation testing
(reference .github/workflows/ci.yaml:15-16): tests never require real TPU
hardware. Multi-chip sharding tests run against
``--xla_force_host_platform_device_count=8``.

Must run before anything imports jax, hence the env mutation at module
import time (pytest imports conftest first).
"""

import os

# Force, don't setdefault: the ambient environment may point JAX at the
# tunneled TPU (JAX_PLATFORMS=axon), and running thousands of tiny test
# dispatches over the tunnel is both slow and hardware-dependent.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags = (_flags + " --xla_force_host_platform_device_count=8").strip()
# 8 timesliced virtual devices rendezvous slowly on a loaded CI core;
# the default terminate timeout SIGABRTs spuriously at larger test
# shapes (BIGRUN_r5.md — a flag, not a scale wall). Guard each flag by
# its own name so ambient values are never overridden by a late append.
# Older jaxlibs hard-abort (CHECK-fail) on *unknown* XLA flags, which
# would kill the whole test session at backend init — probe once in a
# subprocess and only add the flags this jaxlib actually parses. One
# combined probe covers the common case (all supported or none: the two
# flags shipped in the same jaxlib release), and the verdict is cached
# per jaxlib version so the cold jax subprocess start is paid once per
# environment, not once per pytest session.
def _xla_flags_supported(flags: str) -> bool:
    import hashlib
    import subprocess
    import sys
    import tempfile

    import jaxlib

    tag = hashlib.sha256(
        f"{jaxlib.__version__}:{flags}".encode()
    ).hexdigest()[:16]
    cache = os.path.join(
        tempfile.gettempdir(), f"grapevine_xla_flag_probe_{tag}"
    )
    try:
        with open(cache) as fh:
            return fh.read().strip() == "ok"
    except OSError:
        pass
    probe = (
        "import os; os.environ['JAX_PLATFORMS']='cpu'; "
        f"os.environ['XLA_FLAGS']={flags!r}; "
        "import jax; jax.devices()"
    )
    try:
        ok = (
            subprocess.run(
                [sys.executable, "-c", probe],
                capture_output=True,
                timeout=120,
            ).returncode
            == 0
        )
    except Exception:
        return False  # don't cache a flaky probe run
    try:
        with open(cache, "w") as fh:
            fh.write("ok" if ok else "unsupported")
    except OSError:
        pass
    return ok


_timeout_flags = [
    f
    for f in (
        "--xla_cpu_collective_call_warn_stuck_timeout_seconds=120",
        "--xla_cpu_collective_call_terminate_timeout_seconds=600",
    )
    if f.split("=")[0].lstrip("-") not in _flags
]
if _timeout_flags and _xla_flags_supported(" ".join(_timeout_flags)):
    _flags += " " + " ".join(_timeout_flags)
os.environ["XLA_FLAGS"] = _flags

# The env var alone is not enough: plugin site hooks (e.g. the axon PJRT
# tunnel's sitecustomize) may pin the platform via jax.config, which
# overrides JAX_PLATFORMS. jax.config wins over both, as long as it runs
# before backend initialization — conftest import is early enough.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
