"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

The analog of the reference's ``SGX_MODE=SW`` simulation testing
(reference .github/workflows/ci.yaml:15-16): tests never require real TPU
hardware. Multi-chip sharding tests run against
``--xla_force_host_platform_device_count=8``.

Must run before anything imports jax, hence the env mutation at module
import time (pytest imports conftest first).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
