"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

The analog of the reference's ``SGX_MODE=SW`` simulation testing
(reference .github/workflows/ci.yaml:15-16): tests never require real TPU
hardware. Multi-chip sharding tests run against
``--xla_force_host_platform_device_count=8``.

Must run before anything imports jax, hence the env mutation at module
import time (pytest imports conftest first).
"""

import os

# Force, don't setdefault: the ambient environment may point JAX at the
# tunneled TPU (JAX_PLATFORMS=axon), and running thousands of tiny test
# dispatches over the tunnel is both slow and hardware-dependent.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The env var alone is not enough: plugin site hooks (e.g. the axon PJRT
# tunnel's sitecustomize) may pin the platform via jax.config, which
# overrides JAX_PLATFORMS. jax.config wins over both, as long as it runs
# before backend initialization — conftest import is early enough.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
