"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

The analog of the reference's ``SGX_MODE=SW`` simulation testing
(reference .github/workflows/ci.yaml:15-16): tests never require real TPU
hardware. Multi-chip sharding tests run against
``--xla_force_host_platform_device_count=8``.

Must run before anything imports jax, hence the env mutation at module
import time (pytest imports conftest first).
"""

import os

# Force, don't setdefault: the ambient environment may point JAX at the
# tunneled TPU (JAX_PLATFORMS=axon), and running thousands of tiny test
# dispatches over the tunnel is both slow and hardware-dependent.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags = (_flags + " --xla_force_host_platform_device_count=8").strip()
# 8 timesliced virtual devices rendezvous slowly on a loaded CI core;
# the default terminate timeout SIGABRTs spuriously at larger test
# shapes (BIGRUN_r5.md — a flag, not a scale wall). Guard each flag by
# its own name so ambient values are never overridden by a late append.
if "xla_cpu_collective_call_warn_stuck_timeout_seconds" not in _flags:
    _flags += " --xla_cpu_collective_call_warn_stuck_timeout_seconds=120"
if "xla_cpu_collective_call_terminate_timeout_seconds" not in _flags:
    _flags += " --xla_cpu_collective_call_terminate_timeout_seconds=600"
os.environ["XLA_FLAGS"] = _flags

# The env var alone is not enough: plugin site hooks (e.g. the axon PJRT
# tunnel's sitecustomize) may pin the platform via jax.config, which
# overrides JAX_PLATFORMS. jax.config wins over both, as long as it runs
# before backend initialization — conftest import is early enough.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
