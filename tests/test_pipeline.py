"""Pipelined round execution (PR 10): depth knob validation, depth-1 ↔
depth-2 bit-identity, journal-order/durability invariants across the
pipeline, and span pairing with two rounds genuinely in flight.

The contract under test (engine/batcher.py module docstring,
OPERATIONS.md §16):

- ``pipeline_depth=1`` is bit-for-bit the serial pre-PR-10 program;
  depth 2 overlaps round k+1's assembly + journal fsync with round k's
  device execution and STILL produces bit-identical responses and final
  state (the engine round is deterministic given (state, batch), and
  neither the dispatch ledger nor the deferred resolve touches either).
- Journal order is dispatch order at every depth, and a journal written
  at depth 2 replays bit-identically on a depth-1 engine: the depth is
  an execution knob, not geometry — the checkpoint fingerprint must not
  cover it.
- Tracer ledgers pair spans with the right round even with two rounds
  in flight (PendingRound.note_span), and /trace stays Perfetto-valid
  (complete events within one tid disjoint or nested).

Depth-2 crash coverage (kill between fsync and dispatch, mid-flight of
round k) lives in tests/test_chaos_recovery.py / tools/chaos_run.py
``--pipeline-depth 2``.
"""

from __future__ import annotations

import hashlib
import random
import threading

import numpy as np
import pytest

from grapevine_tpu.config import DurabilityConfig, GrapevineConfig
from grapevine_tpu.engine.batcher import GrapevineEngine
from grapevine_tpu.engine.checkpoint import state_to_bytes
from grapevine_tpu.wire import constants as C
from grapevine_tpu.wire.records import QueryRequest, RequestRecord

NOW0 = 1_700_000_000


def _toy_config(pipeline_depth, **kw):
    base = dict(
        max_messages=64, max_recipients=8, mailbox_cap=4,
        batch_size=4, stash_size=64, bucket_cipher_rounds=0,
    )
    base.update(kw)
    return GrapevineConfig(pipeline_depth=pipeline_depth, **base)


def _key(n: int) -> bytes:
    return bytes([n & 0xFF, (n >> 8) & 0xFF, n ^ 0x5A]) + b"\x01" * 29


def _campaign_reqs(rng: random.Random, n: int) -> list[QueryRequest]:
    """Randomized CREATE/READ/DELETE mix, schedule a pure function of
    the rng (the chaos-harness shape: zero-id pops, no response-derived
    inputs)."""
    out = []
    for _ in range(n):
        c = rng.random()
        if c < 0.6:
            rt, rcp = C.REQUEST_TYPE_CREATE, _key(rng.randrange(1, 6))
        elif c < 0.9:
            rt, rcp = C.REQUEST_TYPE_READ, C.ZERO_PUBKEY
        else:
            rt, rcp = C.REQUEST_TYPE_DELETE, C.ZERO_PUBKEY
        out.append(QueryRequest(
            request_type=rt,
            auth_identity=_key(rng.randrange(1, 6)),
            auth_signature=b"\x01" * C.SIGNATURE_SIZE,
            record=RequestRecord(
                msg_id=C.ZERO_MSG_ID,
                recipient=rcp,
                payload=bytes([rng.randrange(256)]) * C.PAYLOAD_SIZE,
            ),
        ))
    return out


def _run_campaign(engine, seed=7, calls=12, max_reqs=12, expire_every=5):
    """Drive multi-chunk handle_queries calls (up to 3 rounds per call —
    the path that actually pipelines) plus expiry sweeps; returns the
    response-stream hash."""
    rng = random.Random(seed)
    h = hashlib.sha256()
    for i in range(calls):
        if expire_every and i % expire_every == expire_every - 1:
            engine.expire(NOW0 + i, period=10_000)
            continue
        reqs = _campaign_reqs(rng, rng.randrange(1, max_reqs))
        for r in engine.handle_queries(reqs, NOW0 + i):
            h.update(r.pack())
    return h.hexdigest()


def _state_hash(engine) -> str:
    return hashlib.sha256(
        state_to_bytes(engine.ecfg, engine.state)
    ).hexdigest()


# -- knob validation + resolution ---------------------------------------


def test_pipeline_depth_validation():
    for bad in (0, 3, -1, "2"):
        with pytest.raises(ValueError, match="pipeline_depth"):
            GrapevineConfig(pipeline_depth=bad)
    for ok in (None, 1, 2):
        GrapevineConfig(pipeline_depth=ok)


def test_scheduler_rejects_bad_depth_and_defaults_serial_for_stubs():
    from grapevine_tpu.server.scheduler import BatchScheduler

    class _Stub:
        class ecfg:
            batch_size = 4

        metrics = None

    with pytest.raises(ValueError, match="pipeline_depth"):
        BatchScheduler(_Stub(), pipeline_depth=0)
    s = BatchScheduler(_Stub())
    try:
        # no resolved engine depth on the stub → the serial program
        assert s.pipeline_depth == 1
    finally:
        s.close()


# -- bit-identity + durability across depths ----------------------------


def test_depth2_bit_identical_and_journal_replays_on_depth1(tmp_path):
    """One campaign, three engines:

    1. depth 1, no durability — the serial oracle;
    2. depth 2, durability on (fsync every round, checkpoints rolling
       mid-campaign) — responses AND final state must equal (1) bit for
       bit while rounds genuinely overlap;
    3. a depth-1 engine recovered from (2)'s state dir — the journal
       a pipelined engine wrote must replay bit-identically on a serial
       engine (replay order is journal order, and the fingerprint does
       not cover the depth; a knob change must never strand a fleet's
       checkpoints)."""
    e1 = GrapevineEngine(_toy_config(1), seed=3)
    assert e1.pipeline_depth == 1
    resp1 = _run_campaign(e1)
    state1 = _state_hash(e1)

    dcfg = DurabilityConfig(
        state_dir=str(tmp_path / "d2"), checkpoint_every_rounds=10,
        journal_fsync_every=1,
    )
    e2 = GrapevineEngine(_toy_config(2), seed=3, durability=dcfg)
    assert e2.pipeline_depth == 2
    resp2 = _run_campaign(e2)
    state2 = _state_hash(e2)
    assert resp2 == resp1, "depth-2 responses diverge from the serial run"
    assert state2 == state1, "depth-2 final state diverges"
    seq2 = e2.durability.seq
    assert seq2 > 10, "campaign too short to roll a checkpoint"
    e2.close()

    e3 = GrapevineEngine(_toy_config(1), seed=3, durability=dcfg)
    assert _state_hash(e3) == state2, (
        "depth-1 recovery from a depth-2 journal is not bit-identical"
    )
    assert e3.durability.seq == seq2
    e3.close()


def test_depth2_journal_order_is_dispatch_order(tmp_path):
    """Two rounds dispatched back-to-back with NEITHER resolved: the
    journal must hold round A's frame before round B's (replay order =
    journal order = dispatch order, never completion/resolve order)."""
    from grapevine_tpu.engine.journal import BatchJournal, KIND_ROUND

    dcfg = DurabilityConfig(state_dir=str(tmp_path / "ord"))
    engine = GrapevineEngine(_toy_config(2), seed=0, durability=dcfg)

    def mk(pay):
        return QueryRequest(
            request_type=C.REQUEST_TYPE_CREATE,
            auth_identity=_key(1),
            auth_signature=b"\x01" * C.SIGNATURE_SIZE,
            record=RequestRecord(
                msg_id=C.ZERO_MSG_ID, recipient=_key(2),
                payload=bytes([pay]) * C.PAYLOAD_SIZE,
            ),
        )

    pa = engine.handle_queries_async([mk(0xAA), mk(0xAA)], NOW0)
    pb = engine.handle_queries_async([mk(0xBB)], NOW0 + 1)
    # both journaled + dispatched, neither resolved — the depth-2 window
    # resolve out of order on purpose: the journal must not care
    rb = pb.resolve()
    ra = pa.resolve()
    assert [r.status_code for r in ra + rb] == [C.STATUS_CODE_SUCCESS] * 3
    engine.close()

    j = BatchJournal(dcfg.state_dir, engine.durability.root_key,
                     engine.ecfg, fsync_every=1)
    recs = list(j.replay(after_seq=0))
    assert [r.kind for r in recs] == [KIND_ROUND, KIND_ROUND]
    assert [r.n_real for r in recs] == [2, 1]
    assert int(np.asarray(recs[0].batch["payload"])[0, 0]) & 0xFF == 0xAA
    assert int(np.asarray(recs[1].batch["payload"])[0, 0]) & 0xFF == 0xBB


# -- two-in-flight observability ----------------------------------------


def _tid_events_disjoint_or_nested(events):
    """Perfetto's complete-event contract: within one tid, X events
    sorted by ts must nest or stay disjoint (the test_trace_slo lane
    rule, applied to REAL overlapping rounds). Tolerance of 2 µs: ts
    and dur are independently floor()ed to µs by the export, so a child
    ending at its parent's edge can land 1 µs past it — real pipeline
    mispairings overlap by whole phase durations (ms), never 2 µs."""
    eps = 2
    by_tid: dict = {}
    for ev in events:
        if ev.get("ph") == "X":
            by_tid.setdefault(ev["tid"], []).append(
                (ev["ts"], ev["ts"] + ev["dur"]))
    for tid, spans in by_tid.items():
        # equal starts: the longer (outer) span must come first or the
        # nesting walk reads its own parent as a violation
        spans.sort(key=lambda p: (p[0], -p[1]))
        stack = []
        for start, end in spans:
            while stack and start >= stack[-1] - eps:
                stack.pop()
            if stack and end > stack[-1] + eps:
                return False, tid
            stack.append(end)
    return True, None


def test_two_inflight_rounds_pair_spans_and_trace_stays_valid():
    """Depth-2 directed check: with rounds A and B simultaneously in
    flight, (a) each tracer ledger carries ITS round's collector spans
    (note_span rides the handle — no cross-round staging mispairing),
    (b) the evict span is the true host-blocked wait actually measured
    at resolve (what the bubble ratio derives from), and (c) /trace
    Chrome JSON stays Perfetto-valid with overlapping rounds split
    across the two lanes."""
    from grapevine_tpu.obs.tracer import RoundTracer

    engine = GrapevineEngine(_toy_config(2), seed=1)
    tracer = RoundTracer(capacity=16, registry=engine.metrics.registry)
    engine.attach_tracer(tracer)

    def mk(i):
        return QueryRequest(
            request_type=C.REQUEST_TYPE_CREATE,
            auth_identity=_key(i + 1),
            auth_signature=b"\x01" * C.SIGNATURE_SIZE,
            record=RequestRecord(
                msg_id=C.ZERO_MSG_ID, recipient=_key(1),
                payload=b"\x07" * C.PAYLOAD_SIZE,
            ),
        )

    # collector-side markers stamped onto each round's own handle; the
    # windows sit well clear of the real dispatch spans (a collection
    # window always precedes its round's lock section)
    pa = engine.handle_queries_async([mk(0)], NOW0)
    pa.note_span("assembly", pa._t0 - 1.0, 0.001)  # round A's marker
    pb = engine.handle_queries_async([mk(1)], NOW0 + 1)
    pb.note_span("assembly", pb._t0 - 1.0, 0.002)  # round B's marker
    # both dispatched, neither resolved: genuinely overlapping rounds
    pa.resolve()
    pb.resolve()

    trace = tracer.chrome_trace()
    entries = [ev for ev in trace["traceEvents"] if ev.get("ph") == "X"]
    a_spans = {ev["name"]: ev for ev in entries if ev["args"]["seq"] == 1}
    b_spans = {ev["name"]: ev for ev in entries if ev["args"]["seq"] == 2}
    # (a) exact pairing: each ledger carries its own collector marker
    assert a_spans["grapevine/assembly"]["dur"] == 1000
    assert b_spans["grapevine/assembly"]["dur"] == 2000
    # dispatch order preserved in the ledgers
    assert (a_spans["grapevine/dispatch"]["ts"]
            < b_spans["grapevine/dispatch"]["ts"])
    # overlapping rounds land on different lanes (tids)
    assert (a_spans["grapevine/dispatch"]["tid"]
            != b_spans["grapevine/dispatch"]["tid"])
    # (b) the bubble input is the true evict wait: both ledgers carry a
    # finite non-negative evict span and the windowed ratio is in [0,1]
    for spans in (a_spans, b_spans):
        assert spans["grapevine/evict"]["dur"] >= 0
    assert 0.0 <= tracer.bubble_ratio() <= 1.0
    # (c) Perfetto validity under overlap
    ok, tid = _tid_events_disjoint_or_nested(trace["traceEvents"])
    assert ok, f"overlapping X events on tid {tid}"


def test_scheduler_depth2_serves_and_drains():
    """The pipelined scheduler end to end: concurrent closed-loop
    clients are all served at depth 2 (the idle tail settles the ledger
    — nobody waits on an un-popped pipeline), and close() drains the
    in-flight rounds."""
    from grapevine_tpu.server.scheduler import BatchScheduler

    engine = GrapevineEngine(
        _toy_config(2, max_messages=256, max_recipients=32,
                    mailbox_cap=16),
        seed=0,
    )
    sched = BatchScheduler(engine, clock=lambda: NOW0)
    assert sched.pipeline_depth == 2
    errs: list = []

    def client(i):
        try:
            for _ in range(5):
                r = sched.submit(QueryRequest(
                    request_type=C.REQUEST_TYPE_CREATE,
                    auth_identity=_key(i + 1),
                    auth_signature=b"\x01" * C.SIGNATURE_SIZE,
                    record=RequestRecord(
                        msg_id=C.ZERO_MSG_ID, recipient=_key(i % 5 + 1),
                        payload=b"\x07" * C.PAYLOAD_SIZE,
                    ),
                ))
                assert r.status_code == C.STATUS_CODE_SUCCESS
        except Exception as exc:  # pragma: no cover - surfaced below
            errs.append(exc)

    threads = [threading.Thread(target=client, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errs, errs[0]
    assert not any(t.is_alive() for t in threads)
    sched.close()
    assert not sched.worker_alive()
    assert engine.metrics.snapshot()["real_ops"] == 20


# -- heavier cross-impl pairs ride the slow bucket ----------------------


@pytest.mark.slow
def test_depth_pair_with_cipher_and_recursive_posmap():
    """Depth-1 ↔ depth-2 bit-identity with the production trimmings on:
    ChaCha8 bucket cipher, recursive position map, tree-top cache (the
    toy auto), scan vphases — the full-stack pair the acceptance
    criteria name."""
    kw = dict(
        max_messages=64, max_recipients=16, bucket_cipher_rounds=8,
        posmap_impl="recursive", tree_top_cache_levels=2,
    )
    e1 = GrapevineEngine(_toy_config(1, **kw), seed=5)
    e2 = GrapevineEngine(_toy_config(2, **kw), seed=5)
    r1 = _run_campaign(e1, seed=21, calls=16)
    r2 = _run_campaign(e2, seed=21, calls=16)
    assert r1 == r2
    assert _state_hash(e1) == _state_hash(e2)
