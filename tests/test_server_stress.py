"""Gated concurrency stress for the pipelined scheduler + server.

Set GRAPEVINE_STRESS=seconds to run (skipped by default; CI runs the
deterministic server suite). Hammers one server with concurrent client
threads doing mixed CRUD, mid-traffic re-auths, and hand-rolled
bad-signature queries, then checks: every thread finished (no deadlock
in the pipeline's drain paths), every response is protocol-consistent,
bad signatures were rejected AND counted, and the engine's aggregate
state reconciles with the per-thread tallies.

Round-3 builder campaigns (single host core): 45 s, 180 s, and 2400 s —
the long run processed 666,533 ops in 187,540 rounds with 36,773 bad
signatures rejected; zero deadlocks, protocol violations, or overflow.
"""

import os
import random
import threading

import grpc
import pytest

from grapevine_tpu.config import GrapevineConfig
from grapevine_tpu.server.client import GrapevineClient
from grapevine_tpu.server.service import GrapevineServer
from grapevine_tpu.session import ristretto
from grapevine_tpu.wire import constants as C
from grapevine_tpu.wire import protowire as pw
from grapevine_tpu.wire.records import QueryRequest, RequestRecord

STRESS_S = float(os.environ.get("GRAPEVINE_STRESS", "0"))

pytestmark = pytest.mark.skipif(
    STRESS_S <= 0, reason="set GRAPEVINE_STRESS=<seconds> to run"
)


def _pl(b: int) -> bytes:
    return bytes([b]) * C.PAYLOAD_SIZE


def test_concurrent_stress_with_churn_and_bad_signatures():
    cfg = GrapevineConfig(
        bucket_cipher_rounds=0,
        max_messages=1 << 10,
        max_recipients=64,
        batch_size=8,
        stash_size=128,
    )
    srv = GrapevineServer(config=cfg, seed=1)
    port = srv.start("insecure-grapevine://127.0.0.1:0")
    uri = f"insecure-grapevine://127.0.0.1:{port}"
    n_threads = 6
    stop = threading.Event()
    errs: list[BaseException] = []
    tallies = {"created": 0, "bad_sig": 0}
    lock = threading.Lock()

    def worker(tid: int):
        rng = random.Random(tid)
        try:
            c = GrapevineClient(uri, identity_seed=bytes([tid + 1]) * 32)
            c.auth()
            peer_key = ristretto.keygen(bytes([((tid + 1) % n_threads) + 1]) * 32)[1]
            created = bad = 0
            while not stop.is_set():
                roll = rng.random()
                if roll < 0.05:
                    c.auth()  # mid-traffic re-auth: fresh channel + RNG
                elif roll < 0.10:
                    # hand-rolled query with a corrupted signature: must
                    # be rejected without desyncing the session. Drawing
                    # the challenge (discarded) keeps the client's
                    # stream aligned with the server's, which consumes
                    # one for this AEAD-valid request
                    _ = c._challenge.next_challenge()
                    req = QueryRequest(
                        request_type=C.REQUEST_TYPE_READ,
                        auth_identity=c.public_key,
                        auth_signature=bytes(64),  # invalid
                        record=RequestRecord(payload=_pl(0)),
                    )
                    raw = pw.encode_envelope(
                        pw.EnvelopeMessage(
                            channel_id=c._channel_id,
                            data=c._channel.encrypt(req.pack()),
                        )
                    )
                    try:
                        c._query_rpc(raw)
                        raise AssertionError("bad signature accepted")
                    except grpc.RpcError as e:
                        assert e.code() == grpc.StatusCode.UNAUTHENTICATED
                    # the reply never came: re-sync the channel by
                    # re-authing (the client's recv counter is unused,
                    # but challenge streams advanced on both sides —
                    # this models a client recovering from its own bug)
                    c.auth()
                    bad += 1
                elif roll < 0.55:
                    r = c.create(recipient=peer_key, payload=_pl(rng.randrange(256)))
                    assert r.status_code in (
                        C.STATUS_CODE_SUCCESS,
                        C.STATUS_CODE_TOO_MANY_MESSAGES,
                        C.STATUS_CODE_TOO_MANY_MESSAGES_FOR_RECIPIENT,
                        C.STATUS_CODE_TOO_MANY_RECIPIENTS,
                    ), r.status_code
                    created += r.status_code == C.STATUS_CODE_SUCCESS
                else:
                    r = c.read() if rng.random() < 0.5 else c.delete()
                    assert r.status_code in (
                        C.STATUS_CODE_SUCCESS,
                        C.STATUS_CODE_NOT_FOUND,
                    ), r.status_code
            with lock:
                tallies["created"] += created
                tallies["bad_sig"] += bad
        except BaseException as e:  # noqa: BLE001 — surface everything
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(n_threads)]
    for t in threads:
        t.start()
    stop.wait(STRESS_S)
    stop.set()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "worker deadlocked"
    assert not errs, errs[0]

    h = srv.health()
    assert h["stash_overflow"] == 0
    assert h["auth_failures"] >= tallies["bad_sig"]
    assert 0 <= h["messages"] <= cfg.max_messages
    assert h["real_ops"] > 0 and h["rounds"] > 0
    print(
        f"stress ok: {h['real_ops']} ops in {h['rounds']} rounds "
        f"(occupancy {h['batch_occupancy']:.2f}), "
        f"{h['auth_failures']} bad signatures rejected, "
        f"{h['messages']} live messages"
    )
    srv.stop()
