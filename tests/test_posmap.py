"""Directed position-map subsystem suite (oram/posmap.py, PR 7).

Always-on coverage (no engine compiles — everything here runs on small
standalone ORAMs or pure traces, per the ROADMAP tier-1 budget rule):

- recursion geometry derivation (k ≈ sqrt(blocks), caps, loud refusals);
- pack/unpack: the recursive map's logical table is bit-identical to
  the flat draw from the same PRNG key, through init and after rounds;
- lookup/remap semantics: round-start reads, remap-visible-on-next-
  lookup, within-round dedup of same-idx lookups, dummy handling;
- the op-major single-access path (oram_access with pm_leaf);
- 2^30-record geometry: shape-only construction + the capacity
  acceptance (position-handling private memory ≤ 1/64 of flat);
- the CI access-schedule gate (tools/check_posmap_oblivious.py), wired
  here next to the telemetry/seal/perf gates.

The flat↔recursive↔oracle *engine* campaigns live in
tests/test_posmap_ab.py (fast pair always-on, breadth under -m slow).
"""

from __future__ import annotations

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from grapevine_tpu.oram.path_oram import OramConfig, init_oram, oram_access
from grapevine_tpu.oram.posmap import (
    MIN_RECURSIVE_BLOCKS,
    derive_posmap_spec,
    inner_oram_config,
    lookup_remap_one,
    lookup_remap_round,
    posmap_hbm_bytes,
    posmap_private_bytes,
    read_table,
)
from grapevine_tpu.oram.round import occurrence_masks

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
U32 = jnp.uint32


def _cfg_pair(blocks=32, height=4, value_words=4, cipher=0, k=None):
    flat = OramConfig(
        height=height, value_words=value_words, n_blocks=blocks,
        cipher_rounds=cipher,
    )
    spec = derive_posmap_spec(
        blocks, cipher_rounds=cipher, entries_per_block=k
    )
    rec = OramConfig(
        height=height, value_words=value_words, n_blocks=blocks,
        cipher_rounds=cipher, posmap=spec,
    )
    return flat, rec


# -- geometry derivation ------------------------------------------------


def test_derive_spec_sqrt_k_and_caps():
    s = derive_posmap_spec(1 << 20)
    assert s.entries_per_block == 1 << 10  # sqrt
    assert s.inner_blocks == 1 << 10
    assert s.inner_leaves == s.inner_blocks // 2  # density-2 layout
    big = derive_posmap_spec(1 << 30)
    assert big.entries_per_block == 1 << 10  # capped at 2^10
    assert big.inner_blocks == 1 << 20
    small = derive_posmap_spec(MIN_RECURSIVE_BLOCKS)
    assert small.inner_blocks >= 4


@pytest.mark.parametrize("blocks", [0, 1, 4, 48, (1 << 20) + 1])
def test_derive_spec_refuses_bad_block_spaces(blocks):
    with pytest.raises(ValueError, match="power-of-two"):
        derive_posmap_spec(blocks)


@pytest.mark.parametrize("k", [3, 1, 64, 256])
def test_derive_spec_refuses_bad_explicit_k(k):
    # 3: not a power of two; 1: < 2; 64/256: blocks/k < 4 at blocks=128
    with pytest.raises(ValueError, match="entries_per_block"):
        derive_posmap_spec(128, entries_per_block=k)


def test_inner_config_is_flat_density2():
    s = derive_posmap_spec(1 << 12)
    icfg = inner_oram_config(s)
    assert icfg.posmap is None  # one level of recursion only
    assert icfg.value_words == s.entries_per_block
    assert icfg.blocks == s.inner_blocks
    assert icfg.leaves * 2 == s.inner_blocks


# -- pack/unpack + init bit-identity ------------------------------------


@pytest.mark.parametrize("cipher", [0, 8])
def test_initial_table_bit_identical_to_flat_draw(cipher):
    flat, rec = _cfg_pair(cipher=cipher)
    key = jax.random.PRNGKey(42)
    st_f = init_oram(flat, key)
    st_r = init_oram(rec, key)
    assert np.array_equal(
        np.asarray(st_f.posmap)[: flat.blocks], read_table(rec, st_r.posmap)
    )
    # recursive activates the leaf-metadata planes; flat keeps them empty
    assert st_r.tree_leaf.shape == st_r.tree_idx.shape
    assert st_r.stash_leaf.shape == (rec.stash_size,)
    assert st_f.tree_leaf.shape == (0,)
    assert st_f.stash_leaf.shape == (0,)


def test_inner_tree_holds_every_block_and_posmap_matches():
    _, rec = _cfg_pair(blocks=64, height=5)
    st = init_oram(rec, jax.random.PRNGKey(1))
    inner = st.posmap.inner
    icfg = inner_oram_config(rec.posmap)
    from grapevine_tpu.oblivious.primitives import SENTINEL

    tidx = np.asarray(inner.tree_idx)
    live = tidx[tidx != int(SENTINEL)]
    assert sorted(live.tolist()) == list(range(icfg.blocks))  # full, unique
    # the inner flat map agrees with where each block actually sits
    z = icfg.bucket_slots
    pm = np.asarray(inner.posmap)
    for slot in np.nonzero(tidx != int(SENTINEL))[0]:
        hb = slot // z
        depth_leaf = hb - ((1 << icfg.height) - 1)
        assert 0 <= depth_leaf < icfg.leaves  # placed at leaf level
        assert pm[tidx[slot]] == depth_leaf


# -- lookup/remap semantics (round form) --------------------------------


def _round_lookup(cfg, pm, idxs, nl, dl, pm_nl=None, pm_dl=None):
    fo, lo, _ = occurrence_masks(idxs, cfg.dummy_index)
    return lookup_remap_round(
        cfg, pm, idxs, nl, dl, fo, lo,
        pm_new_leaves=pm_nl, pm_dummy_leaves=pm_dl,
    )


@pytest.mark.parametrize("cipher", [0, 8])
def test_round_lookup_matches_flat_and_remap_visible_next_round(cipher):
    flat, rec = _cfg_pair(cipher=cipher)
    key = jax.random.PRNGKey(3)
    pm_f = init_oram(flat, key).posmap
    pm_r = init_oram(rec, key).posmap
    spec = rec.posmap
    rng = np.random.default_rng(0)
    k2 = jax.random.PRNGKey(9)
    for r in range(4):
        b = 8
        k2, ka, kb, kc, kd = jax.random.split(k2, 5)
        idxs = jnp.asarray(rng.integers(0, flat.blocks + 1, b).astype(np.uint32))
        nl = jax.random.bits(ka, (b,), U32) & U32(flat.leaves - 1)
        dl = jax.random.bits(kb, (b,), U32) & U32(flat.leaves - 1)
        pm_nl = jax.random.bits(kc, (b,), U32) & U32(spec.inner_leaves - 1)
        pm_dl = jax.random.bits(kd, (b,), U32) & U32(spec.inner_leaves - 1)
        pm_f, lv_f, none_inner = _round_lookup(flat, pm_f, idxs, nl, dl)
        pm_r, lv_r, inner = _round_lookup(rec, pm_r, idxs, nl, dl, pm_nl, pm_dl)
        assert none_inner is None
        assert inner is not None and inner.shape == (b,)
        assert np.array_equal(np.asarray(lv_f), np.asarray(lv_r)), r
        assert np.array_equal(
            np.asarray(pm_f)[: flat.blocks], read_table(rec, pm_r)
        ), f"remap not visible identically at round {r}"


def test_round_lookup_dedups_same_idx():
    """Duplicate indices in one batch: first occurrence reads the entry,
    later ones take their dummy leaves, the LAST remap wins."""
    flat, rec = _cfg_pair()
    key = jax.random.PRNGKey(5)
    pm_f = init_oram(flat, key).posmap
    pm_r = init_oram(rec, key).posmap
    start = int(pm_f[7])
    idxs = jnp.asarray(np.array([7, 7, 7, 3], np.uint32))
    nl = jnp.asarray(np.array([1, 2, 3, 4], np.uint32))
    dl = jnp.asarray(np.array([9, 10, 11, 12], np.uint32))
    pm_il = rec.posmap.inner_leaves
    pm_nl = jnp.zeros((4,), U32) % U32(pm_il)
    pm_dl = jnp.ones((4,), U32) % U32(pm_il)
    pm_f2, lv_f, _ = _round_lookup(flat, pm_f, idxs, nl, dl)
    pm_r2, lv_r, _ = _round_lookup(rec, pm_r, idxs, nl, dl, pm_nl, pm_dl)
    want = [start, 10, 11, int(pm_f[3])]
    assert np.asarray(lv_f).tolist() == want
    assert np.asarray(lv_r).tolist() == want
    assert int(pm_f2[7]) == 3  # last remap wins
    assert read_table(rec, pm_r2)[7] == 3
    assert read_table(rec, pm_r2)[3] == 4


def test_round_lookup_requires_internal_leaves():
    _, rec = _cfg_pair()
    pm = init_oram(rec, jax.random.PRNGKey(0)).posmap
    idxs = jnp.zeros((4,), U32)
    with pytest.raises(ValueError, match="pm_new_leaves"):
        _round_lookup(rec, pm, idxs, idxs, idxs)


# -- lookup/remap semantics (single-access form + op-major ORAM) --------


def test_one_lookup_remap_and_dummy_entry_mirror():
    flat, rec = _cfg_pair()
    key = jax.random.PRNGKey(11)
    pm_f = init_oram(flat, key).posmap
    pm_r = init_oram(rec, key).posmap
    # real access: same read, remap visible on the next lookup
    pm_f2, leaf_f = pm_f.at[5].set(U32(9)), pm_f[5]
    pm_r2, leaf_r, il = lookup_remap_one(rec, pm_r, U32(5), U32(9), U32(0))
    assert int(leaf_f) == int(leaf_r)
    _, leaf_r3, _ = lookup_remap_one(rec, pm_r2, U32(5), U32(2), U32(1))
    assert int(leaf_r3) == 9
    # dummy access mirrors flat's table[blocks] read/remap
    dummy = U32(rec.dummy_index)
    pm_r4, leaf_d, _ = lookup_remap_one(rec, pm_r2, dummy, U32(6), U32(1))
    assert int(leaf_d) == int(pm_r2.dummy_entry)
    assert int(pm_r4.dummy_entry) == 6
    with pytest.raises(ValueError, match="pm_leaf"):
        lookup_remap_one(rec, pm_r, U32(5), U32(9))


@pytest.mark.parametrize("cipher", [0, 8])
def test_op_major_oram_access_bit_identical(cipher):
    """The sequential oram_access path under both impls: same outputs,
    same payload tree, logical tables stay equal."""
    flat, rec = _cfg_pair(cipher=cipher)
    key = jax.random.PRNGKey(2)
    st_f = init_oram(flat, key)
    st_r = init_oram(rec, key)

    def kv(value, present, operand):
        new = jnp.where(present, value + U32(1), operand)
        return new, jnp.bool_(True), jnp.bool_(True), (value, present)

    rng = np.random.default_rng(4)
    k2 = jax.random.PRNGKey(21)
    for i in range(12):
        k2, ka, kb = jax.random.split(k2, 3)
        idx = U32(int(rng.integers(0, flat.blocks + 1)))
        nl = jax.random.bits(ka, (), U32) & U32(flat.leaves - 1)
        pml = jax.random.bits(kb, (), U32) & U32(
            rec.posmap.inner_leaves - 1
        )
        opnd = jnp.full((flat.value_words,), U32(i + 1))
        st_f, out_f, leaf_f = oram_access(flat, st_f, idx, nl, opnd, kv)
        st_r, out_r, leaf_r = oram_access(
            rec, st_r, idx, nl, opnd, kv, pm_leaf=pml
        )
        assert np.array_equal(np.asarray(out_f[0]), np.asarray(out_r[0])), i
        assert bool(out_f[1]) == bool(out_r[1])
        assert int(leaf_f) == int(np.asarray(leaf_r)[0])  # [payload, pm]
        assert np.asarray(leaf_r).shape == (2,)
        assert np.array_equal(np.asarray(st_f.tree_idx), np.asarray(st_r.tree_idx))
        assert np.array_equal(np.asarray(st_f.tree_val), np.asarray(st_r.tree_val))
        assert np.array_equal(np.asarray(st_f.stash_idx), np.asarray(st_r.stash_idx))
        assert int(st_r.overflow) == 0
    assert np.array_equal(
        np.asarray(st_f.posmap)[: flat.blocks], read_table(rec, st_r.posmap)
    )


# -- capacity: 2^30 records ---------------------------------------------


def test_2pow30_geometry_constructs_shape_only():
    """The ISSUE-7 capacity acceptance: a 2^30-logical-record geometry
    constructs (shape-only — no 4 GiB tables materialize in CI) and its
    resident position-handling memory is ≤ 1/64 of the flat map's."""
    blocks = 1 << 30
    spec = derive_posmap_spec(blocks)
    flat = OramConfig(height=29, value_words=256, n_blocks=blocks)
    rec = OramConfig(height=29, value_words=256, n_blocks=blocks, posmap=spec)
    st = jax.eval_shape(lambda: init_oram(rec, jax.random.PRNGKey(0)))
    # the resident pieces really shrank: inner table is blocks/k entries
    assert st.posmap.inner.posmap.shape == (spec.inner_blocks + 1,)
    assert st.tree_leaf.shape == st.tree_idx.shape
    flat_bytes = posmap_private_bytes(flat)
    rec_bytes = posmap_private_bytes(rec)
    assert flat_bytes == 4 * (blocks + 1)  # the 4 GiB resident table
    assert rec_bytes * 64 <= flat_bytes, (
        f"private position memory {rec_bytes} not <= 1/64 of {flat_bytes}"
    )
    # and the HBM side is declared, not hidden: tree + leaf plane
    assert posmap_hbm_bytes(rec) > 0
    assert posmap_hbm_bytes(flat) == 0

    # step the *small* standalone pieces of the same shape contract:
    # the lookup round traces at this geometry (abstract values only)
    def run(pm, idxs, nl, dl, pm_nl, pm_dl):
        fo, lo, _ = occurrence_masks(idxs, rec.dummy_index)
        return lookup_remap_round(
            rec, pm, idxs, nl, dl, fo, lo,
            pm_new_leaves=pm_nl, pm_dummy_leaves=pm_dl,
        )

    b = 4
    lf = jax.ShapeDtypeStruct((b,), jnp.uint32)
    out = jax.eval_shape(run, st.posmap, lf, lf, lf, lf, lf)
    assert out[1].shape == (b,) and out[2].shape == (b,)


# -- CI gate: access schedule is index-blind ----------------------------


def test_posmap_access_schedule_gate():
    """tools/check_posmap_oblivious.py wired into tier-1 (next to the
    telemetry/seal/perf gates): identical traced program for adversarial
    index sets, no data-dependent control flow, flat positive control."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import check_posmap_oblivious as gate

    out = gate.check_posmap_access_schedule(b=12)
    assert out["recursive"]["accesses"] > out["flat"]["accesses"]
    assert out["flat"]["gathers"] >= 1 and out["flat"]["scatters"] >= 1
