"""Two-stack protobuf conformance through the REAL protobuf library.

The reference proves its prost structs and protobuf-codegen structs agree
byte-for-byte and encode at constant size (reference
api/tests/grapevine_types.rs:13-55). Here the two stacks are the
hand-rolled wire codec (wire/protowire.py) and google.protobuf messages
generated at runtime from a FileDescriptorProto carrying the committed
schema — plus a parse of wire/grapevine.proto asserting the committed
artifact declares exactly the field numbers and types under test.
"""

import re
from pathlib import Path

from google.protobuf import descriptor_pb2, descriptor_pool, message_factory

from grapevine_tpu.testing.fixtures import (
    get_seeded_rng as seeded_rng,
    random_query_request,
    random_query_response,
)
from grapevine_tpu.wire import protowire as W

# (message, field name, number, proto type) — the wire contract
SCHEMA = {
    "AuthMessage": [("data", 1, "bytes")],
    "Message": [("aad", 1, "bytes"), ("channel_id", 2, "bytes"), ("data", 3, "bytes")],
    "AuthMessageWithChallengeSeed": [
        ("auth_message", 1, "AuthMessage"),
        ("encrypted_challenge_seed", 2, "bytes"),
    ],
    "QueryRequest": [
        ("request_type", 1, "fixed32"),
        ("auth_identity", 2, "bytes"),
        ("auth_signature", 3, "bytes"),
        ("record", 4, "RequestRecord"),
    ],
    "RequestRecord": [
        ("msg_id", 1, "bytes"),
        ("recipient", 2, "bytes"),
        ("payload", 3, "bytes"),
    ],
    "Record": [
        ("msg_id", 1, "bytes"),
        ("sender", 2, "bytes"),
        ("recipient", 3, "bytes"),
        ("timestamp", 4, "fixed64"),
        ("payload", 5, "bytes"),
    ],
    "QueryResponse": [("record", 1, "Record"), ("status_code", 2, "fixed32")],
}

_TYPE = {
    "bytes": descriptor_pb2.FieldDescriptorProto.TYPE_BYTES,
    "fixed32": descriptor_pb2.FieldDescriptorProto.TYPE_FIXED32,
    "fixed64": descriptor_pb2.FieldDescriptorProto.TYPE_FIXED64,
}


def _build_messages():
    fdp = descriptor_pb2.FileDescriptorProto()
    fdp.name = "grapevine_conformance.proto"
    fdp.package = "grapevine"
    fdp.syntax = "proto3"
    for msg, fields in SCHEMA.items():
        m = fdp.message_type.add()
        m.name = msg
        for fname, num, ftype in fields:
            f = m.field.add()
            f.name = fname
            f.number = num
            f.label = descriptor_pb2.FieldDescriptorProto.LABEL_OPTIONAL
            if ftype in _TYPE:
                f.type = _TYPE[ftype]
            else:
                f.type = descriptor_pb2.FieldDescriptorProto.TYPE_MESSAGE
                f.type_name = f".grapevine.{ftype}"
    pool = descriptor_pool.DescriptorPool()
    pool.Add(fdp)
    return {
        name: message_factory.GetMessageClass(pool.FindMessageTypeByName(f"grapevine.{name}"))
        for name in SCHEMA
    }


MSGS = _build_messages()


def _pb_request(q):
    m = MSGS["QueryRequest"]()
    m.request_type = q.request_type
    m.auth_identity = q.auth_identity
    m.auth_signature = q.auth_signature
    m.record.msg_id = q.record.msg_id
    m.record.recipient = q.record.recipient
    m.record.payload = q.record.payload
    return m


def _pb_response(q):
    m = MSGS["QueryResponse"]()
    m.record.msg_id = q.record.msg_id
    m.record.sender = q.record.sender
    m.record.recipient = q.record.recipient
    m.record.timestamp = q.record.timestamp
    m.record.payload = q.record.payload
    m.status_code = q.status_code
    return m


def test_request_bytes_identical_across_stacks():
    """protowire's encoding must byte-equal google.protobuf's (prost and
    protobuf-codegen emit fields in ascending number order; so do we)."""
    for seed in range(8):
        rng = seeded_rng(seed)
        q = random_query_request(rng)
        ours = W.encode_query_request(q)
        theirs = _pb_request(q).SerializeToString()
        assert ours == theirs


def test_response_bytes_identical_across_stacks():
    for seed in range(8):
        rng = seeded_rng(seed)
        q = random_query_response(rng)
        ours = W.encode_query_response(q)
        theirs = _pb_response(q).SerializeToString()
        assert ours == theirs


def test_real_protobuf_decodes_ours_and_back():
    rng = seeded_rng(42)
    q = random_query_request(rng)
    m = MSGS["QueryRequest"]()
    m.ParseFromString(W.encode_query_request(q))
    assert m.auth_identity == q.auth_identity
    rt = W.decode_query_request(m.SerializeToString())
    assert rt == q

    r = random_query_response(rng)
    m2 = MSGS["QueryResponse"]()
    m2.ParseFromString(W.encode_query_response(r))
    assert m2.record.timestamp == r.record.timestamp
    rt2 = W.decode_query_response(m2.SerializeToString())
    assert rt2 == r


def test_constant_size_through_real_protobuf():
    """The reference's signature test, through google.protobuf: every
    random fully-populated message serializes to the identical length
    (reference api/tests/grapevine_types.rs:21-31,45-55)."""
    sizes_q = set()
    sizes_r = set()
    for seed in range(16):
        rng = seeded_rng(seed)
        sizes_q.add(len(_pb_request(random_query_request(rng)).SerializeToString()))
        sizes_r.add(len(_pb_response(random_query_response(rng)).SerializeToString()))
    assert sizes_q == {W.QUERY_REQUEST_PROTO_SIZE}
    assert sizes_r == {W.QUERY_RESPONSE_PROTO_SIZE}


def test_envelope_messages_match_real_protobuf():
    env = W.EnvelopeMessage(aad=b"a" * 3, channel_id=b"c" * 16, data=b"d" * 100)
    m = MSGS["Message"]()
    m.aad, m.channel_id, m.data = env.aad, env.channel_id, env.data
    assert W.encode_envelope(env) == m.SerializeToString()

    seed_msg = W.AuthMessageWithChallengeSeed(
        auth_message=W.AuthMessage(data=b"h" * 64),
        encrypted_challenge_seed=b"s" * 48,
    )
    m2 = MSGS["AuthMessageWithChallengeSeed"]()
    m2.auth_message.data = b"h" * 64
    m2.encrypted_challenge_seed = b"s" * 48
    assert W.encode_auth_with_seed(seed_msg) == m2.SerializeToString()


# ---- the committed .proto artifact matches the schema under test -------

PROTO_PATH = Path(__file__).parent.parent / "grapevine_tpu" / "wire" / "grapevine.proto"


def _parse_proto_text(text: str):
    """Tiny structural parse: message → [(field, number, type)]."""
    out = {}
    for mname, body in re.findall(r"message\s+(\w+)\s*\{([^}]*)\}", text):
        fields = []
        for line in body.splitlines():
            line = line.split("//")[0].strip()
            m = re.match(r"(\w+)\s+(\w+)\s*=\s*(\d+)\s*;", line)
            if m:
                ftype, fname, num = m.group(1), m.group(2), int(m.group(3))
                fields.append((fname, num, ftype))
        out[mname] = fields
    return out


def test_committed_proto_artifact_matches_schema():
    parsed = _parse_proto_text(PROTO_PATH.read_text())
    assert set(parsed) == set(SCHEMA)
    for msg, fields in SCHEMA.items():
        assert parsed[msg] == fields, f"{msg} drifted from the wire contract"


def test_committed_proto_declares_the_service():
    text = PROTO_PATH.read_text()
    assert re.search(r"service\s+GrapevineAPI", text)
    assert re.search(r"rpc\s+Auth\(AuthMessage\)\s+returns\s+\(AuthMessageWithChallengeSeed\)", text)
    assert re.search(r"rpc\s+Query\(Message\)\s+returns\s+\(Message\)", text)
