"""Round tracer + commit-latency SLO engine (obs/tracer.py, obs/slo.py)
and their serving-layer wiring (ISSUE 6).

Three layers, mirroring the PR-1/2 test split:

- unit: bubble-ratio math on synthetic ledgers, ring wrap, the span
  schema's TelemetryLeakError teeth, SLO burn-rate math on a fake clock;
- endpoint: a live engine tier serves /trace as valid Chrome trace JSON
  (Perfetto-loadable), the bubble/SLO series on /metrics, and a gated
  /profile capture;
- policy: no per-op field survives in any exported span (the leak-check
  acceptance), and a burning SLO flips /healthz to 503.
"""

import json
import urllib.error
import urllib.request

import pytest

from grapevine_tpu.obs.registry import TelemetryLeakError, TelemetryRegistry
from grapevine_tpu.obs.slo import SloConfig, SloTracker
from grapevine_tpu.obs.tracer import (
    ALLOWED_SPAN_NAMES,
    STABLE_SPANS,
    RoundTracer,
)

NOW = 1_700_000_000


# -- tracer units -------------------------------------------------------


def test_bubble_ratio_math():
    """bubble = evict wait / round span, meaned over the window."""
    tr = RoundTracer(capacity=8)
    tr.record_round({"round": (0.0, 10.0), "evict": (5.0, 4.0)})
    assert tr.bubble_ratio() == pytest.approx(0.4)
    tr.record_round({"round": (10.0, 10.0), "evict": (15.0, 2.0)})
    assert tr.bubble_ratio() == pytest.approx(0.3)  # mean(0.4, 0.2)
    # zero-length rounds contribute no ratio rather than a div-by-zero
    tr.record_round({"round": (20.0, 0.0)})
    assert tr.bubble_ratio() == pytest.approx(0.3)


def test_bubble_window_bounds_the_mean():
    tr = RoundTracer(capacity=8, bubble_window=1)
    tr.record_round({"round": (0.0, 10.0), "evict": (0.0, 10.0)})
    tr.record_round({"round": (10.0, 10.0), "evict": (10.0, 0.0)})
    assert tr.bubble_ratio() == pytest.approx(0.0)  # only the last round


def test_ring_wraps_and_counts():
    tr = RoundTracer(capacity=4)
    for i in range(6):
        tr.record_round({"round": (float(i), 1.0)})
    trace = tr.chrome_trace()
    assert trace["otherData"]["rounds_recorded_total"] == 6
    assert trace["otherData"]["rounds_retained"] == 4
    seqs = {e["args"]["seq"] for e in trace["traceEvents"]
            if e.get("cat") == "round"}
    assert seqs == {3, 4, 5, 6}


def test_stable_span_shape_without_durability():
    """The satellite contract: a ledger recorded WITHOUT journal /
    checkpoint / device spans still exports all STABLE_SPANS (zero
    duration), so trace consumers see one JSON shape across configs."""
    tr = RoundTracer(capacity=4)
    tr.record_round({"dispatch": (1.0, 0.5), "evict": (1.5, 0.2),
                     "demux": (1.7, 0.1), "round": (1.0, 0.8),
                     "device": (1.4, 0.3)})
    trace = tr.chrome_trace()
    names = {e["name"] for e in trace["traceEvents"]
             if e.get("cat") == "round"}
    assert names == {f"grapevine/{s}" for s in STABLE_SPANS}
    zero = [e for e in trace["traceEvents"]
            if e["name"] in ("grapevine/journal", "grapevine/checkpoint")]
    assert zero and all(e["dur"] == 0 for e in zero)


def test_chrome_trace_is_valid_and_loadable_shape():
    tr = RoundTracer(capacity=4)
    tr.record_round({"round": (0.0, 0.01), "evict": (0.0, 0.004)})
    parsed = json.loads(tr.chrome_trace_json())
    assert isinstance(parsed["traceEvents"], list) and parsed["traceEvents"]
    for e in parsed["traceEvents"]:
        assert {"name", "ph", "pid"} <= set(e)
        if e["ph"] == "X":  # complete events: the Perfetto essentials
            assert {"ts", "dur", "tid", "cat"} <= set(e)
            assert isinstance(e["ts"], int) and e["dur"] >= 0
    # the device window rides its own thread track (seq 1 = lane 1)
    tids = {e["tid"] for e in parsed["traceEvents"] if e["ph"] == "X"}
    assert tids == {2, 4}


def test_chrome_trace_lanes_keep_pipelined_rounds_disjoint():
    """Complete ("X") events sharing a tid must nest or stay disjoint
    (the trace-event format contract). Adjacent pipelined rounds
    overlap — round k's evict/demux run after round k+1's assembly —
    so consecutive rounds must land on different lanes, and events
    within one lane must never partially overlap."""
    tr = RoundTracer(capacity=8)
    # two pipelined rounds: round 2 starts before round 1 ends
    tr.record_round({"round": (0.0, 1.0), "evict": (0.6, 0.4),
                     "device": (0.0, 0.9)})
    tr.record_round({"round": (0.5, 1.0), "evict": (1.2, 0.3),
                     "device": (0.5, 1.4)})
    events = [e for e in tr.chrome_trace()["traceEvents"]
              if e.get("ph") == "X"]
    lanes = {e["args"]["seq"]: e["tid"] for e in events
             if e["name"] == "grapevine/round"}
    assert lanes[1] != lanes[2]
    by_tid: dict = {}
    for e in events:
        by_tid.setdefault(e["tid"], []).append((e["ts"], e["ts"] + e["dur"]))
    for tid, spans in by_tid.items():
        for a0, a1 in spans:
            for b0, b1 in spans:
                # disjoint, nested, or identical — never partial overlap
                assert (a1 <= b0 or b1 <= a0
                        or (a0 >= b0 and a1 <= b1)
                        or (b0 >= a0 and b1 <= a1)), (tid, spans)


def test_span_schema_has_teeth():
    """A span is a phase, never an operation — the leak-check
    acceptance: per-op names and malformed values raise."""
    tr = RoundTracer(capacity=4)
    with pytest.raises(TelemetryLeakError, match="not a round phase"):
        tr.record_round({"op_read_client_7": (0.0, 1.0)})
    with pytest.raises(TelemetryLeakError, match="pair of numbers"):
        tr.record_round({"evict": "payload-bytes-here"})
    with pytest.raises(TelemetryLeakError, match="negative"):
        tr.record_round({"evict": (0.0, -1.0)})
    with pytest.raises(TelemetryLeakError, match="must be a"):
        tr.record_round([("evict", (0.0, 1.0))])
    # nothing leaked into the ring by the failed records
    assert tr.chrome_trace()["otherData"]["rounds_recorded_total"] == 0


def test_allowed_span_names_stay_inside_phase_vocabulary():
    from grapevine_tpu.obs.phases import PHASES

    assert ALLOWED_SPAN_NAMES <= set(PHASES) | {"device", "round"}


def test_tracer_gauges_export():
    reg = TelemetryRegistry()
    tr = RoundTracer(capacity=4, registry=reg)
    tr.record_round({"round": (0.0, 10.0), "evict": (0.0, 5.0)})
    snap = reg.snapshot()
    assert snap["grapevine_round_bubble_ratio"] == pytest.approx(0.5)
    assert snap["grapevine_trace_rounds_total"] == 1
    assert snap["grapevine_trace_ring_rounds"] == 1


# -- SLO units ----------------------------------------------------------


def _slo(clock, **kw):
    defaults = dict(commit_p99_ms=100.0, error_budget=0.1,
                    fast_window_s=10.0, slow_window_s=100.0,
                    fast_burn_threshold=2.0, slow_burn_threshold=1.0,
                    min_rounds=5)
    defaults.update(kw)
    return SloTracker(SloConfig(**defaults), clock=clock)


def test_slo_burn_rate_math_and_verdict_flip():
    t = [0.0]
    s = _slo(lambda: t[0])
    for _ in range(10):  # healthy traffic: no breach, ok
        t[0] += 0.1
        s.observe(0.01)
    v = s.verdict()
    assert v["ok"] and v["fast_burn_rate"] == 0.0
    for _ in range(10):  # every round breaches the 100 ms target
        t[0] += 0.1
        s.observe(1.0)
    v = s.verdict()
    # 10/20 breaching over a 0.1 budget = burn 5.0 in both windows
    assert v["fast_burn_rate"] == pytest.approx(5.0)
    assert v["slow_burn_rate"] == pytest.approx(5.0)
    assert v["ok"] is False
    # windows drain with time: stale breaches stop alerting
    t[0] += 1000.0
    v = s.verdict()
    assert v["ok"] and v["fast_rounds"] == 0


def test_slo_min_rounds_gate():
    """Insufficient evidence is not an outage: a cold engine's first
    compile-bearing rounds must not page."""
    t = [0.0]
    s = _slo(lambda: t[0], min_rounds=32)
    for _ in range(8):
        t[0] += 0.1
        s.observe(99.0)  # catastrophic — but only 8 rounds of evidence
    assert s.verdict()["ok"] is True


def test_slo_single_window_burn_does_not_alert():
    """The multi-window AND: a long-past burst burns the slow window
    only — no alert (the SRE-workbook shape)."""
    t = [0.0]
    s = _slo(lambda: t[0])
    for _ in range(10):
        t[0] += 0.1
        s.observe(1.0)  # burst of breaches
    t[0] += 50.0  # fast window (10 s) drains; slow window (100 s) keeps it
    for _ in range(10):
        t[0] += 0.1
        s.observe(0.01)  # healthy now
    v = s.verdict()
    assert v["slow_burn_rate"] > 1.0  # slow window still burning
    assert v["ok"] is True  # but the fast window cleared — no page


def test_slo_observe_only_reports_but_never_gates():
    """enforce=False (the CLI default until --slo-commit-p99-ms is set
    explicitly): the burn rates and the alerting flag still export, but
    ok never goes False — a fleet upgraded with a target its honest
    latency cannot meet must not flip every replica to 503 at once."""
    t = [0.0]
    s = _slo(lambda: t[0], enforce=False)
    for _ in range(10):
        t[0] += 0.1
        s.observe(1.0)  # every round breaches
    v = s.verdict()
    assert v["alerting"] is True and v["enforced"] is False
    assert v["ok"] is True
    assert v["fast_burn_rate"] > 2.0  # the signal is still there


def test_cli_slo_default_is_observe_only():
    """Without --slo-commit-p99-ms the CLI builds an observe-only
    SloConfig; setting it is the explicit opt-in to healthz gating."""
    from grapevine_tpu.server.cli import _slo_config, build_parser

    p = build_parser()
    cfg = _slo_config(p.parse_args(["--role", "engine"]))
    assert cfg.enforce is False
    cfg = _slo_config(p.parse_args(
        ["--role", "engine", "--slo-commit-p99-ms", "500"]))
    assert cfg.enforce is True and cfg.commit_p99_ms == 500.0


def test_slo_config_validation():
    with pytest.raises(ValueError, match="error budget"):
        SloTracker(SloConfig(error_budget=0.0))
    with pytest.raises(ValueError, match="error budget"):
        SloTracker(SloConfig(error_budget=1.0))


def test_slo_histogram_and_counters_export():
    reg = TelemetryRegistry()
    t = [0.0]
    s = SloTracker(SloConfig(commit_p99_ms=100.0), registry=reg,
                   clock=lambda: t[0])
    s.observe(0.01)
    s.observe(1.0)  # breach
    snap = reg.snapshot()
    assert snap["grapevine_slo_rounds_total"] == 2
    assert snap["grapevine_slo_breaches_total"] == 1
    assert snap["grapevine_slo_target_ms"] == 100.0


# -- live endpoint (one small engine; the module's single compile) ------


def _get(url, timeout=10):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:  # 503 still carries a body
        return e.code, e.read().decode()


@pytest.fixture(scope="module")
def tier():
    from grapevine_tpu.config import GrapevineConfig
    from grapevine_tpu.server.tier import EngineServer
    from grapevine_tpu.wire import constants as C
    from grapevine_tpu.wire.records import QueryRequest, RequestRecord

    cfg = GrapevineConfig(
        bucket_cipher_rounds=0, max_messages=64, max_recipients=16,
        mailbox_cap=4, batch_size=4, stash_size=96,
    )
    srv = EngineServer(cfg, seed=7, max_wait_ms=5.0, clock=lambda: NOW,
                       trace_ring_size=64, profile_enable=True)
    port = srv.start_metrics(0, host="127.0.0.1")
    # a couple of real rounds through the scheduler so the ring has
    # ledgers and the SLO has observations
    for i in range(2):
        resp = srv.scheduler.submit(QueryRequest(
            request_type=C.REQUEST_TYPE_CREATE,
            auth_identity=bytes([i + 1]) * 32,
            auth_signature=b"\x01" * C.SIGNATURE_SIZE,
            record=RequestRecord(msg_id=C.ZERO_MSG_ID,
                                 recipient=bytes([i + 2]) * 32,
                                 payload=b"\x07" * C.PAYLOAD_SIZE)))
        assert resp.status_code == C.STATUS_CODE_SUCCESS
    yield srv, port
    srv.stop()


def test_trace_endpoint_serves_chrome_trace_json(tier):
    srv, port = tier
    status, body = _get(f"http://127.0.0.1:{port}/trace")
    assert status == 200
    trace = json.loads(body)  # valid JSON is the acceptance bar
    assert trace["otherData"]["rounds_recorded_total"] >= 2
    events = trace["traceEvents"]
    for e in events:
        assert {"name", "ph", "pid"} <= set(e)
    spans = [e for e in events if e.get("cat") == "round"]
    names = {e["name"] for e in spans}
    # every stable span present — durability is OFF in this tier, yet
    # journal/checkpoint/device appear (the stable-shape satellite)
    assert {f"grapevine/{s}" for s in STABLE_SPANS} <= names
    # scheduler-side spans paired into the same rounds
    assert "grapevine/assembly" in names and "grapevine/verify" in names


def test_trace_spans_carry_no_per_op_fields(tier):
    """Leak check: every span name is a phase, args carry only the
    round seq — nowhere for an op type, client id, or per-op timestamp
    to travel."""
    srv, port = tier
    _, body = _get(f"http://127.0.0.1:{port}/trace")
    for e in json.loads(body)["traceEvents"]:
        if e.get("cat") != "round":
            continue
        assert e["name"].removeprefix("grapevine/") in ALLOWED_SPAN_NAMES
        assert set(e.get("args", {})) <= {"seq"}
        assert isinstance(e["ts"], int) and isinstance(e["dur"], int)


def test_bubble_and_slo_series_on_metrics(tier):
    srv, port = tier
    status, text = _get(f"http://127.0.0.1:{port}/metrics")
    assert status == 200
    for series in ("grapevine_round_bubble_ratio",
                   "grapevine_trace_rounds_total",
                   "grapevine_trace_ring_rounds",
                   "grapevine_slo_commit_latency_seconds_bucket",
                   "grapevine_slo_rounds_total",
                   "grapevine_slo_burn_rate_fast",
                   "grapevine_slo_burn_rate_slow",
                   "grapevine_slo_alert", "grapevine_slo_target_ms"):
        assert series in text, series
    # the SLO actually measured the submitted rounds
    assert "grapevine_slo_rounds_total 0\n" not in text


def test_slo_burn_rate_flips_healthz(tier):
    """The acceptance flip, directed: a tracker whose windows are both
    burning turns /healthz 503 so the LB stops routing."""
    srv, port = tier
    status, body = _get(f"http://127.0.0.1:{port}/healthz")
    assert status == 200 and json.loads(body)["slo"]["ok"] is True

    t = [0.0]
    burned = _slo(lambda: t[0])
    for _ in range(10):
        t[0] += 0.1
        burned.observe(1.0)  # every round breaches
    real = srv.slo
    srv.slo = burned
    try:
        status, body = _get(f"http://127.0.0.1:{port}/healthz")
        detail = json.loads(body)
        assert status == 503 and detail["healthy"] is False
        assert detail["slo"]["ok"] is False
        assert detail["slo"]["fast_burn_rate"] > 2.0
    finally:
        srv.slo = real
    status, _ = _get(f"http://127.0.0.1:{port}/healthz")
    assert status == 200


@pytest.mark.slow  # ~67 s: the first capture pays jax.profiler's lazy
# init, and the test is wall-clock-flaky under concurrent load (socket
# timeout mid-init). Moved in the PR-9 tier-1 re-budget; the capture
# path stays covered here in slow and by tpu_capture's live_profile.
def test_profile_endpoint_gated_capture(tier):
    """/profile?ms=N runs a live jax.profiler capture (enabled in this
    fixture) and refuses a concurrent one with 409."""
    import os

    srv, port = tier
    # the first capture pays jax.profiler's lazy init (~10 s on this
    # sandbox); later captures are milliseconds
    status, body = _get(f"http://127.0.0.1:{port}/profile?ms=30",
                        timeout=90)
    assert status == 200
    result = json.loads(body)
    assert result["ms"] == 30 and os.path.isdir(result["trace_dir"])
    assert any(files for _, _, files in os.walk(result["trace_dir"]))
    # busy: a second capture while one holds the gate gets 409
    assert srv.profiler._lock.acquire(blocking=False)
    try:
        status, body = _get(f"http://127.0.0.1:{port}/profile?ms=10")
        assert status == 409
    finally:
        srv.profiler._lock.release()
    status, _ = _get(f"http://127.0.0.1:{port}/profile?ms=oops")
    assert status == 400


def test_profile_404_when_not_enabled():
    """Without --profile-enable the endpoint does not exist (the gate
    is absence, not a flag check at request time)."""
    from grapevine_tpu.obs.httpd import MetricsServer

    ms = MetricsServer(TelemetryRegistry(), port=0)
    port = ms.start()
    try:
        status, _ = _get(f"http://127.0.0.1:{port}/profile?ms=10")
        assert status == 404
        status, _ = _get(f"http://127.0.0.1:{port}/trace")
        assert status == 404
    finally:
        ms.stop()
