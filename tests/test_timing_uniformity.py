"""Timing-uniformity leak test (VERDICT r3 #6).

The reference's invariant covers timing, not just access patterns
(reference grapevine.proto:120-122). Transcript bit-equality cannot see
a timing channel, so this suite measures *round wall times* directly:
all-READ vs all-UPDATE vs all-DELETE rounds at one batch size must draw
from indistinguishable time distributions.

Design notes:
- one jit'd program serves every op mix (op semantics are masks, never
  control flow), so an honest engine's round time cannot depend on the
  mix; what this test guards against is a future change that introduces
  op-keyed branching (host dispatch or data-dependent ``lax.cond``);
- conditions are *interleaved* in measurement order (R,U,D,R,U,D,…) so
  host-load drift on a busy CI core hits every condition equally;
- DELETE rounds target absent ids (NOT_FOUND) so state is unchanged and
  every measured round sees the identical bus — the failing path must
  be as fast/slow as the succeeding one, which is itself part of the
  invariant (NOT_FOUND is deliberately indistinguishable from success
  work-wise, reference grapevine.proto:81-86);
- the canary proves the detector has teeth by injecting a 25% op-keyed
  slowdown at the dispatch layer and asserting the z-score explodes.
"""

from __future__ import annotations

import time

import numpy as np
import pytest

from grapevine_tpu.config import GrapevineConfig
from grapevine_tpu.engine.batcher import GrapevineEngine
from grapevine_tpu.testing.leakcheck import timing_twosample_z
from grapevine_tpu.wire import constants as C
from grapevine_tpu.wire.records import QueryRequest, RequestRecord

NOW = 1_700_000_000
N_ROUNDS = 30  # per condition
#: |z| threshold for honest rounds: Mann-Whitney z ~ N(0,1) under the
#: null; 4.5 is a ~7e-6 false-positive cut per comparison
HONEST_Z = 4.5


def _mk_engine(batch=8):
    cfg = GrapevineConfig(
        max_messages=256,
        max_recipients=32,
        mailbox_cap=8,
        batch_size=batch,
        bucket_cipher_rounds=8,
    )
    return GrapevineEngine(cfg, seed=3), cfg


def _populate(eng, cfg, n=16):
    """Create n records (spread over recipients under the 62/8-cap);
    returns (ids, recips, sender)."""
    ids = []
    recips = []
    sender = b"\x31" * 32
    bs = cfg.batch_size
    per_recip = max(1, cfg.mailbox_cap // 2)
    reqs = [
        QueryRequest(
            request_type=C.REQUEST_TYPE_CREATE,
            auth_identity=sender,
            record=RequestRecord(
                recipient=bytes([0x40 + i // per_recip]) * 32,
                payload=bytes([i]) * C.PAYLOAD_SIZE,
            ),
        )
        for i in range(n)
    ]
    for i in range(0, n, bs):
        for j, r in enumerate(eng.handle_queries(reqs[i : i + bs], NOW)):
            assert r.status_code == C.STATUS_CODE_SUCCESS, r.status_code
            ids.append(r.record.msg_id)
            recips.append(reqs[i + j].record.recipient)
    return ids, recips, sender


def _round_reqs(kind: str, ids, recips, sender, bs):
    if kind == "read":
        return [
            QueryRequest(
                request_type=C.REQUEST_TYPE_READ,
                auth_identity=sender,
                record=RequestRecord(msg_id=ids[j % len(ids)]),
            )
            for j in range(bs)
        ]
    if kind == "update":
        return [
            QueryRequest(
                request_type=C.REQUEST_TYPE_UPDATE,
                auth_identity=sender,
                record=RequestRecord(
                    msg_id=ids[j % len(ids)],
                    recipient=recips[j % len(ids)],
                    payload=bytes([j]) * C.PAYLOAD_SIZE,
                ),
            )
            for j in range(bs)
        ]
    # delete of ABSENT ids: NOT_FOUND, state unchanged, same touches
    absent = bytes([0xEE]) * 15 + b"\x01"
    return [
        QueryRequest(
            request_type=C.REQUEST_TYPE_DELETE,
            auth_identity=sender,
            record=RequestRecord(msg_id=absent, recipient=recips[0]),
        )
        for _ in range(bs)
    ]


def _measure(eng, cfg, ids, recips, sender, slow_delete_s: float = 0.0):
    """Interleaved R/U/D round times; returns {kind: np.ndarray}."""
    bs = cfg.batch_size
    kinds = ("read", "update", "delete")
    reqs = {k: _round_reqs(k, ids, recips, sender, bs) for k in kinds}
    # warmup: compile + settle every condition once
    for k in kinds:
        eng.handle_queries(reqs[k], NOW)
    times: dict[str, list[float]] = {k: [] for k in kinds}
    for _ in range(N_ROUNDS):
        for k in kinds:
            t0 = time.perf_counter()
            out = eng.handle_queries(reqs[k], NOW)
            if k == "delete" and slow_delete_s:
                time.sleep(slow_delete_s)  # canary: op-keyed slowdown
            times[k].append(time.perf_counter() - t0)
            assert len(out) == bs
    return {k: np.asarray(v) for k, v in times.items()}


def test_rud_round_times_indistinguishable():
    eng, cfg = _mk_engine()
    ids, recips, sender = _populate(eng, cfg)
    times = _measure(eng, cfg, ids, recips, sender)
    z_ru = timing_twosample_z(times["read"], times["update"])
    z_rd = timing_twosample_z(times["read"], times["delete"])
    z_ud = timing_twosample_z(times["update"], times["delete"])
    assert abs(z_ru) < HONEST_Z, f"read-vs-update timing z={z_ru:.2f}"
    assert abs(z_rd) < HONEST_Z, f"read-vs-delete timing z={z_rd:.2f}"
    assert abs(z_ud) < HONEST_Z, f"update-vs-delete timing z={z_ud:.2f}"


@pytest.mark.slow  # wall-clock-noise flaky inside a concurrent tier-1
# run on this 2-vCPU sandbox (observed z=3.09 < cut under load; passes
# solo) — itself a randomized timing campaign, so it rides -m slow. The
# honest-timing assertion (test_rud_round_times_indistinguishable)
# stays always-on. TRACKING: return to tier-1 when the suite moves off
# the shared-core sandbox or the canary gains a load-robust statistic.
def test_timing_canary_has_teeth():
    """A deliberate op-keyed slowdown (1× the round cost — e.g. a
    second ORAM pass only DELETE pays) must be flagged loudly, proving
    the detector catches an op-keyed cost difference.

    Note the rank statistic saturates: with N=30 per condition the
    maximum |z| at complete separation is sqrt(3·N²/(2N+1)) ≈ 6.65, so
    the canary cut sits between HONEST_Z and that ceiling."""
    eng, cfg = _mk_engine()
    ids, recips, sender = _populate(eng, cfg)
    # estimate the round cost to scale the injected delta
    t0 = time.perf_counter()
    eng.handle_queries(_round_reqs("read", ids, recips, sender, cfg.batch_size), NOW)
    per_round = time.perf_counter() - t0
    times = _measure(
        eng, cfg, ids, recips, sender, slow_delete_s=max(per_round, 5e-3)
    )
    z_rd = timing_twosample_z(times["read"], times["delete"])
    assert abs(z_rd) > HONEST_Z + 1, f"canary not detected: z={z_rd:.2f}"


def test_detector_statistics_sane():
    rng = np.random.default_rng(0)
    a = rng.normal(1.0, 0.1, 200)
    b = rng.normal(1.0, 0.1, 200)
    assert abs(timing_twosample_z(a, b)) < 4
    c = rng.normal(1.25, 0.1, 200)  # clearly shifted
    assert abs(timing_twosample_z(a, c)) > 10
    # ties + empty inputs do not crash
    assert timing_twosample_z(np.ones(50), np.ones(50)) == pytest.approx(0, abs=1e-9)
    assert timing_twosample_z(np.ones(0), np.ones(5)) == 0.0
