"""Unit tests for the oblivious vector primitives (batched under one jit)."""

import jax
import jax.numpy as jnp
import numpy as np

from grapevine_tpu.oblivious import primitives as P


def test_cmov_and_words_equal():
    out = np.asarray(
        jax.jit(
            lambda: jnp.stack(
                [
                    P.cmov(True, jnp.uint32(1), jnp.uint32(2)),
                    P.cmov(False, jnp.uint32(1), jnp.uint32(2)),
                ]
            )
        )()
    )
    assert out.tolist() == [1, 2]

    a = jnp.array([[1, 2], [3, 4], [0, 0]], jnp.uint32)
    b = jnp.array([[1, 2], [3, 5], [0, 0]], jnp.uint32)
    eq = np.asarray(jax.jit(P.words_equal)(a, b))
    assert eq.tolist() == [True, False, True]
    zero = np.asarray(jax.jit(P.is_zero_words)(a))
    assert zero.tolist() == [False, False, True]


def test_onehot_select_and_first_true():
    vals = jnp.arange(12, dtype=jnp.uint32).reshape(4, 3)
    mask = jnp.array([False, True, False, False])
    sel = np.asarray(jax.jit(P.onehot_select)(mask, vals))
    assert sel.tolist() == [3, 4, 5]

    none = jnp.zeros((4,), jnp.bool_)
    assert np.asarray(jax.jit(P.onehot_select)(none, vals)).tolist() == [0, 0, 0]

    oh = np.asarray(jax.jit(P.first_true_onehot)(jnp.array([False, True, True, False])))
    assert oh.tolist() == [False, True, False, False]
    oh = np.asarray(jax.jit(P.first_true_onehot)(none))
    assert oh.tolist() == [False] * 4


def test_argmin_u64_onehot_edges():
    f = jax.jit(P.argmin_u64_onehot)
    valid = jnp.array([True, True, True, False])
    hi = jnp.array([2, 1, 1, 0], jnp.uint32)
    lo = jnp.array([0, 5, 3, 0], jnp.uint32)
    oh, any_valid = f(valid, hi, lo)
    assert np.asarray(oh).tolist() == [False, False, True, False]  # (1,3) < (1,5) < (2,0)
    assert bool(any_valid)

    # all invalid → no selection
    oh, any_valid = f(jnp.zeros((4,), jnp.bool_), hi, lo)
    assert np.asarray(oh).tolist() == [False] * 4
    assert not bool(any_valid)

    # lanes whose payload equals the masking sentinel still win when valid
    valid = jnp.array([True, False, False, False])
    hi = jnp.full((4,), 0xFFFFFFFF, jnp.uint32)
    lo = jnp.full((4,), 0xFFFFFFFF, jnp.uint32)
    oh, any_valid = f(valid, hi, lo)
    assert np.asarray(oh).tolist() == [True, False, False, False]
    assert bool(any_valid)

    # tie on (hi, lo): first lane wins
    valid = jnp.array([True, True, True, True])
    hi = jnp.array([7, 7, 7, 7], jnp.uint32)
    lo = jnp.array([9, 3, 3, 9], jnp.uint32)
    oh, _ = f(valid, hi, lo)
    assert np.asarray(oh).tolist() == [False, True, False, False]


def test_rank_of():
    mask = jnp.array([True, False, True, True, False, True])
    r = np.asarray(jax.jit(P.rank_of)(mask))
    assert r.tolist() == [0, 1, 1, 2, 3, 3]
