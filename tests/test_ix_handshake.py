"""IX handshake: static-key authentication inside the handshake.

VERDICT r3 #4 / reference shape ``mc-attest-ake`` (grapevine.proto:17-36,
README.md:177-183): both sides' statics are authenticated by the DH mix
(ee ‖ es ‖ se) — an active MITM that substitutes either key derives
different channel keys, so the first frame fails AEAD; a pinned server
static is rejected before any frame flows.
"""

import pytest

from grapevine_tpu.session import channel
# whichever backend channel.py loaded (the wheel, or the stdlib port in
# wheel-less containers) — the handshake properties must hold on both
from grapevine_tpu.session.channel import X25519PrivateKey


def _full_handshake(client_static=None, attestation=None, pin=None,
                    identity=None):
    state, msg1 = channel.client_handshake(client_static)
    reply, server_chan = channel.server_handshake(
        msg1, attestation, identity=identity
    )
    client_chan = channel.client_finish(
        state, reply, attestation, expected_server_static=pin
    )
    return client_chan, server_chan


def test_ix_roundtrip_and_peer_statics():
    ident = channel.ServerIdentity.from_seed(b"\x05" * 32)
    cs = X25519PrivateKey.generate()
    state, msg1 = channel.client_handshake(cs)
    assert len(msg1) == 64
    reply, server_chan = channel.server_handshake(msg1, identity=ident)
    client_chan = channel.client_finish(
        state, reply, expected_server_static=ident.public
    )
    assert client_chan.peer_static == ident.public
    assert server_chan.peer_static == cs.public_key().public_bytes_raw()
    ct = client_chan.encrypt(b"ping")
    assert server_chan.decrypt(ct) == b"ping"
    assert client_chan.decrypt(server_chan.encrypt(b"pong")) == b"pong"


def test_anonymous_client_works_and_is_flagged():
    client_chan, server_chan = _full_handshake()
    assert server_chan.peer_static is None
    assert server_chan.decrypt(client_chan.encrypt(b"x")) == b"x"


def test_pinned_server_static_rejects_impostor():
    """Active MITM: the relay terminates the handshake with its OWN
    identity (it cannot forge the real one inside the AEAD). A client
    that pinned the real server static must refuse."""
    real = channel.ServerIdentity.from_seed(b"\x06" * 32)
    mitm = channel.ServerIdentity.generate()
    state, msg1 = channel.client_handshake()
    reply_from_mitm, _ = channel.server_handshake(msg1, identity=mitm)
    with pytest.raises(ValueError, match="pinned"):
        channel.client_finish(
            state, reply_from_mitm, expected_server_static=real.public
        )


def test_tampered_static_in_reply_fails_aead():
    """Flipping any byte of the encrypted (s_r ‖ evidence) blob — the
    attack surface for key substitution — fails the transcript-bound
    AEAD before any key is accepted."""
    state, msg1 = channel.client_handshake()
    reply, _ = channel.server_handshake(msg1)
    for pos in (32, 40, len(reply) - 1):  # inside e_r-adjacent ct
        bad = bytearray(reply)
        bad[pos] ^= 1
        with pytest.raises(ValueError, match="authentication"):
            channel.client_finish(state, bytes(bad))


def test_substituted_ephemeral_fails():
    """A MITM that swaps e_r (leaving the ciphertext) changes ee, so
    the handshake AEAD key is wrong — decryption fails."""
    state, msg1 = channel.client_handshake()
    reply, _ = channel.server_handshake(msg1)
    fake_e = X25519PrivateKey.generate().public_key().public_bytes_raw()
    with pytest.raises(ValueError, match="authentication"):
        channel.client_finish(state, fake_e + reply[32:])


def test_forged_client_static_cannot_talk():
    """A client claiming someone else's static without the private key
    completes the wire exchange but derives wrong keys (missing se):
    its first frame fails on the server — IX initiator authentication."""
    victim = X25519PrivateKey.generate()
    victim_pub = victim.public_key().public_bytes_raw()
    eph = X25519PrivateKey.generate()
    msg1 = eph.public_key().public_bytes_raw() + victim_pub  # forged claim
    reply, server_chan = channel.server_handshake(msg1)
    # forger CAN complete the wire exchange (that needs only ee) ...
    state = channel.ClientHandshake(eph, None, msg1)
    forged_chan = channel.client_finish(state, reply)
    # ... but cannot derive the channel keys: se is missing from its
    # mix, so the server rejects its very first frame
    with pytest.raises(Exception):
        server_chan.decrypt(forged_chan.encrypt(b"hello"))


def test_attestation_binding_receives_transcript():
    """Evidence is bound to the handshake transcript: the verify hook
    sees a stable binding that covers both messages + the static."""
    seen = {}

    class Recorder(channel.NullAttestation):
        def evidence(self, binding: bytes = b"") -> bytes:
            seen["evidence_binding"] = binding
            return b"EVIDENCE"

        def verify(self, evidence: bytes, binding: bytes = b"") -> bool:
            seen["verify_evidence"] = evidence
            seen["verify_binding"] = binding
            return True

    att = Recorder()
    client_chan, server_chan = _full_handshake(attestation=att)
    assert seen["verify_evidence"] == b"EVIDENCE"
    assert len(seen["verify_binding"]) == 32
    # a REAL provider signs the binding it is handed at evidence() time;
    # the verifier must therefore be handed the *identical* value
    assert seen["verify_binding"] == seen["evidence_binding"]
    assert server_chan.decrypt(client_chan.encrypt(b"ok")) == b"ok"


def test_rejecting_attestation_aborts():
    class Reject(channel.NullAttestation):
        def verify(self, evidence: bytes, binding: bytes = b"") -> bool:
            return False

    state, msg1 = channel.client_handshake()
    reply, _ = channel.server_handshake(msg1)
    with pytest.raises(ValueError, match="attestation"):
        channel.client_finish(state, reply, attestation=Reject())


def test_server_identity_from_seed_is_stable():
    a = channel.ServerIdentity.from_seed(b"\x09" * 32)
    b = channel.ServerIdentity.from_seed(b"\x09" * 32)
    c = channel.ServerIdentity.from_seed(b"\x0a" * 32)
    assert a.public == b.public != c.public
    with pytest.raises(ValueError):
        channel.ServerIdentity.from_seed(b"short")


def test_legacy_32_byte_msg1_rejected():
    with pytest.raises(ValueError, match="64|e_c"):
        channel.server_handshake(b"\x01" * 32)


def test_server_e2e_pinning(tmp_path):
    """Full gRPC stack: client pins server.identity.public; a client
    pinning a WRONG static refuses the session."""
    from grapevine_tpu.config import GrapevineConfig
    from grapevine_tpu.server.client import GrapevineClient
    from grapevine_tpu.server.service import GrapevineServer
    from grapevine_tpu.wire import constants as C

    ident = channel.ServerIdentity.from_seed(b"\x0c" * 32)
    cfg = GrapevineConfig(
        max_messages=64, max_recipients=8, mailbox_cap=4, batch_size=4,
        bucket_cipher_rounds=0,
    )
    server = GrapevineServer(config=cfg, identity=ident)
    port = server.start("insecure-grapevine://127.0.0.1:0")
    try:
        good = GrapevineClient(
            f"insecure-grapevine://127.0.0.1:{port}",
            identity_seed=b"\x21" * 32,
            server_static=ident.public,
        )
        good.auth()
        r = good.create(recipient=good.public_key,
                        payload=b"\x01" * C.PAYLOAD_SIZE)
        assert r.status_code == C.STATUS_CODE_SUCCESS

        wrong_pin = channel.ServerIdentity.generate().public
        bad = GrapevineClient(
            f"insecure-grapevine://127.0.0.1:{port}",
            identity_seed=b"\x22" * 32,
            server_static=wrong_pin,
        )
        with pytest.raises(ValueError, match="pinned"):
            bad.auth()
    finally:
        server.stop()
