"""Semantics-oracle tests: every documented CRUD behavior and status path.

Each case maps to a clause of the reference spec (grapevine.proto:57-122,
README.md:162-175); the device engine is later held equal to this model.
"""

import random

import pytest

from grapevine_tpu.config import GrapevineConfig
from grapevine_tpu.testing.reference import HardProtocolError, ReferenceEngine
from grapevine_tpu.wire import constants as C
from grapevine_tpu.wire.records import QueryRequest, RequestRecord

NOW = 1_700_000_000


def key(n: int) -> bytes:
    return bytes([n]) + b"\x00" * 31


def payload(n: int) -> bytes:
    return bytes([n]) * C.PAYLOAD_SIZE


def req(rt, auth, msg_id=C.ZERO_MSG_ID, recipient=C.ZERO_PUBKEY, pl=None):
    return QueryRequest(
        request_type=rt,
        auth_identity=auth,
        auth_signature=b"\x01" * C.SIGNATURE_SIZE,
        record=RequestRecord(
            msg_id=msg_id, recipient=recipient, payload=pl or payload(0)
        ),
    )


@pytest.fixture
def eng():
    return ReferenceEngine(
        config=GrapevineConfig(max_messages=64, max_recipients=8, mailbox_cap=4),
        rng=random.Random(42),
    )


def create(eng, sender, recipient, pl=None, now=NOW):
    return eng.handle_query(req(C.REQUEST_TYPE_CREATE, sender, recipient=recipient, pl=pl), now)


def test_create_assigns_random_nonzero_id_and_server_timestamp(eng):
    r = req(C.REQUEST_TYPE_CREATE, key(1), msg_id=b"\x07" * 16, recipient=key(2))
    resp = eng.handle_query(r, NOW)
    assert resp.status_code == C.STATUS_CODE_SUCCESS
    # client-supplied id ignored (grapevine.proto:66-68)
    assert resp.record.msg_id != b"\x07" * 16
    assert resp.record.msg_id != C.ZERO_MSG_ID
    assert resp.record.timestamp == NOW
    assert resp.record.sender == key(1)
    assert resp.record.recipient == key(2)


def test_create_zero_recipient_rejected(eng):
    resp = create(eng, key(1), C.ZERO_PUBKEY)
    assert resp.status_code == C.STATUS_CODE_INVALID_RECIPIENT
    assert resp.record.msg_id == C.ZERO_MSG_ID  # zeroed record on failure
    assert resp.record.timestamp != 0  # but real timestamp (constant size)


def test_read_by_id_as_sender_and_recipient(eng):
    mid = create(eng, key(1), key(2), pl=payload(9)).record.msg_id
    for auth in (key(1), key(2)):
        resp = eng.handle_query(req(C.REQUEST_TYPE_READ, auth, msg_id=mid), NOW)
        assert resp.status_code == C.STATUS_CODE_SUCCESS
        assert resp.record.payload == payload(9)
    # a third party gets NOT_FOUND, identical to absence (grapevine.proto:83-86)
    resp = eng.handle_query(req(C.REQUEST_TYPE_READ, key(3), msg_id=mid), NOW)
    assert resp.status_code == C.STATUS_CODE_NOT_FOUND
    resp = eng.handle_query(req(C.REQUEST_TYPE_READ, key(2), msg_id=b"\x55" * 16), NOW)
    assert resp.status_code == C.STATUS_CODE_NOT_FOUND


def test_read_zero_id_returns_oldest_message(eng):
    m1 = create(eng, key(1), key(2), pl=payload(1)).record.msg_id
    create(eng, key(3), key(2), pl=payload(2))
    resp = eng.handle_query(req(C.REQUEST_TYPE_READ, key(2)), NOW)
    assert resp.status_code == C.STATUS_CODE_SUCCESS
    assert resp.record.msg_id == m1  # oldest first
    # sender identity has no mailbox: NOT_FOUND
    resp = eng.handle_query(req(C.REQUEST_TYPE_READ, key(1)), NOW)
    assert resp.status_code == C.STATUS_CODE_NOT_FOUND


def test_update_semantics(eng):
    mid = create(eng, key(1), key(2), pl=payload(1)).record.msg_id
    # zero id is a hard protocol error (grapevine.proto:95)
    with pytest.raises(HardProtocolError):
        eng.handle_query(req(C.REQUEST_TYPE_UPDATE, key(1)), NOW)
    # wrong recipient -> INVALID_RECIPIENT (grapevine.proto:101-103)
    resp = eng.handle_query(
        req(C.REQUEST_TYPE_UPDATE, key(1), msg_id=mid, recipient=key(9)), NOW
    )
    assert resp.status_code == C.STATUS_CODE_INVALID_RECIPIENT
    # correct update refreshes payload + timestamp (grapevine.proto:92-94)
    resp = eng.handle_query(
        req(C.REQUEST_TYPE_UPDATE, key(2), msg_id=mid, recipient=key(2), pl=payload(7)),
        NOW + 5,
    )
    assert resp.status_code == C.STATUS_CODE_SUCCESS
    assert resp.record.payload == payload(7)
    assert resp.record.timestamp == NOW + 5
    # unauthorized/absent -> NOT_FOUND
    resp = eng.handle_query(
        req(C.REQUEST_TYPE_UPDATE, key(5), msg_id=mid, recipient=key(2)), NOW
    )
    assert resp.status_code == C.STATUS_CODE_NOT_FOUND


def test_delete_by_id_requires_recipient_match_and_pops_mailbox(eng):
    mid = create(eng, key(1), key(2)).record.msg_id
    resp = eng.handle_query(
        req(C.REQUEST_TYPE_DELETE, key(1), msg_id=mid, recipient=key(9)), NOW
    )
    assert resp.status_code == C.STATUS_CODE_INVALID_RECIPIENT
    resp = eng.handle_query(
        req(C.REQUEST_TYPE_DELETE, key(1), msg_id=mid, recipient=key(2)), NOW
    )
    assert resp.status_code == C.STATUS_CODE_SUCCESS
    assert eng.message_count() == 0
    # mailbox entry went with it (README.md:173-175)
    resp = eng.handle_query(req(C.REQUEST_TYPE_READ, key(2)), NOW)
    assert resp.status_code == C.STATUS_CODE_NOT_FOUND


def test_delete_zero_id_pops_in_order(eng):
    m1 = create(eng, key(1), key(2)).record.msg_id
    m2 = create(eng, key(1), key(2)).record.msg_id
    r1 = eng.handle_query(req(C.REQUEST_TYPE_DELETE, key(2)), NOW)
    r2 = eng.handle_query(req(C.REQUEST_TYPE_DELETE, key(2)), NOW)
    assert [r1.record.msg_id, r2.record.msg_id] == [m1, m2]
    r3 = eng.handle_query(req(C.REQUEST_TYPE_DELETE, key(2)), NOW)
    assert r3.status_code == C.STATUS_CODE_NOT_FOUND


def test_mailbox_cap(eng):
    for _ in range(4):  # cap configured to 4 in fixture
        assert create(eng, key(1), key(2)).status_code == C.STATUS_CODE_SUCCESS
    resp = create(eng, key(1), key(2))
    assert resp.status_code == C.STATUS_CODE_TOO_MANY_MESSAGES_FOR_RECIPIENT
    # deleting one frees a slot
    eng.handle_query(req(C.REQUEST_TYPE_DELETE, key(2)), NOW)
    assert create(eng, key(1), key(2)).status_code == C.STATUS_CODE_SUCCESS


def test_too_many_recipients():
    eng = ReferenceEngine(
        config=GrapevineConfig(max_messages=64, max_recipients=2, mailbox_cap=4),
        rng=random.Random(1),
    )
    assert create(eng, key(1), key(2)).status_code == C.STATUS_CODE_SUCCESS
    assert create(eng, key(1), key(3)).status_code == C.STATUS_CODE_SUCCESS
    assert create(eng, key(1), key(4)).status_code == C.STATUS_CODE_TOO_MANY_RECIPIENTS
    # existing recipient still fine
    assert create(eng, key(1), key(3)).status_code == C.STATUS_CODE_SUCCESS


def test_too_many_messages():
    eng = ReferenceEngine(
        config=GrapevineConfig(max_messages=4, max_recipients=8, mailbox_cap=62),
        rng=random.Random(1),
    )
    for i in range(4):
        assert create(eng, key(1), key(2 + i)).status_code == C.STATUS_CODE_SUCCESS
    assert create(eng, key(1), key(7)).status_code == C.STATUS_CODE_TOO_MANY_MESSAGES


def test_zero_auth_identity_is_hard_error(eng):
    with pytest.raises(HardProtocolError):
        eng.handle_query(req(C.REQUEST_TYPE_CREATE, C.ZERO_PUBKEY, recipient=key(2)), NOW)


def test_expiry_sweep(eng):
    create(eng, key(1), key(2), now=NOW)
    mid_live = create(eng, key(1), key(2), now=NOW + 100).record.msg_id
    assert eng.expire(NOW + 150, period=100) == 1
    assert eng.message_count() == 1
    resp = eng.handle_query(req(C.REQUEST_TYPE_READ, key(2)), NOW + 150)
    assert resp.record.msg_id == mid_live
    # update refreshes the expiry clock (grapevine.proto:93-94)
    eng.handle_query(
        req(C.REQUEST_TYPE_UPDATE, key(2), msg_id=mid_live, recipient=key(2)),
        NOW + 200,
    )
    assert eng.expire(NOW + 290, period=100) == 0
    assert eng.expire(NOW + 301, period=100) == 1
    assert eng.message_count() == 0
    assert eng.recipient_count() == 0


def test_collision_status_with_forced_id(eng):
    forced = b"\x11" * 16
    assert (
        eng.handle_query(
            req(C.REQUEST_TYPE_CREATE, key(1), recipient=key(2)), NOW, forced_msg_id=forced
        ).status_code
        == C.STATUS_CODE_SUCCESS
    )
    resp = eng.handle_query(
        req(C.REQUEST_TYPE_CREATE, key(1), recipient=key(3)), NOW, forced_msg_id=forced
    )
    assert resp.status_code == C.STATUS_CODE_MESSAGE_ID_ALREADY_IN_USE
