"""Tree-top cache equivalence + audit (ISSUE 8 tentpole).

The contract of ``GrapevineConfig.tree_top_cache_levels = k``
(oram/path_oram.py, ROADMAP item 1 — the measured path-HBM bottleneck):

1. responses AND final engine state bit-identical cached↔uncached↔oracle
   — "state" in the canonical logical form
   (testing/compare.py:assert_logical_state_equal): decrypted tree
   planes with the cache overlaid, stashes, maps, scalars. Raw
   ciphertext at cached levels legitimately diverges (the cached run
   never rewrites those HBM rows), which is exactly what the overlay
   normalizes;
2. stash occupancy and overflow identical cached↔uncached at EVERY
   round of a soak (a top-cache bug — wrong eviction eligibility, a
   dropped cache write — would first show up as silent stash drift),
   read through ``health()``'s ``stash_occupancy`` fold;
3. the cached round is index-blind and moves exactly B·(path_len−k)
   HBM bucket rows per plane (tools/check_tree_cache_oblivious.py,
   k=0 positive control);
4. a cached checkpoint can never silently restore into a
   differently-cached engine (geometry fingerprint covers k);
5. the leak monitor stays PASS on a live soak with caching enabled.

Always-on cost: ONE cached + ONE uncached engine compile (plaintext
BASE geometry, reused across every fast assertion) + small
directed-ORAM compiles + trace-only audits — the ≤2-engine-compile
budget (ROADMAP tier-1 note). Cipher pairs, recursive-posmap pairs,
regime breadth, and chaos ride ``-m slow``.
"""

from __future__ import annotations

import os
import random
import sys

import jax
import numpy as np
import pytest

from test_vphases_scan import (
    BASE,
    NOW,
    SAT_BUS,
    _assert_responses_bitequal,
    _campaign_plan,
    _gen_batch,
    key,
    req,
)

from grapevine_tpu.config import GrapevineConfig
from grapevine_tpu.engine.batcher import GrapevineEngine
from grapevine_tpu.testing.compare import assert_logical_state_equal
from grapevine_tpu.testing.reference import ReferenceEngine
from grapevine_tpu.wire import constants as C

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


def _mk_cache_pair(cfg_kwargs, seed, k=4):
    uncached = GrapevineEngine(
        GrapevineConfig(tree_top_cache_levels=0, **cfg_kwargs), seed=seed
    )
    cached = GrapevineEngine(
        GrapevineConfig(tree_top_cache_levels=k, **cfg_kwargs), seed=seed
    )
    return uncached, cached


def _run_tc_campaign(cfg_kwargs, seed, n_batches=3, batch_fill=None,
                     pair=None, sweep=False, k=4):
    """One campaign: uncached/cached pair + oracle over mixed batches,
    with per-round stash-occupancy equality (the drift canary) and
    final logical-state equality. ``pair`` reuses compiled engines."""
    rng = np.random.default_rng(seed)
    e0, ek = pair or _mk_cache_pair(
        cfg_kwargs, seed=int(rng.integers(1 << 30)), k=k
    )
    oracle = None
    if pair is None:
        oracle = ReferenceEngine(
            config=GrapevineConfig(**cfg_kwargs), rng=random.Random(seed)
        )
    idents = [key(i) for i in range(1, 1 + int(rng.integers(2, 6)))]
    live_ids: list[tuple[bytes, bytes]] = []
    bs = cfg_kwargs["batch_size"]
    for bi in range(n_batches):
        n = batch_fill or int(rng.integers(1, bs + 1))
        reqs = _gen_batch(rng, idents, live_ids, n)
        t = NOW + bi
        r0 = e0.handle_queries(reqs, t)
        rk = ek.handle_queries(reqs, t)
        _assert_responses_bitequal(r0, rk, f"tree_cache seed {seed} b {bi}")
        # per-round stash drift canary through the health() fold
        h0, hk = e0.health(), ek.health()
        assert h0["stash_occupancy"] == hk["stash_occupancy"], (
            f"tree_cache seed {seed} batch {bi}: stash occupancy drifts "
            f"cached vs uncached: {h0['stash_occupancy']} vs "
            f"{hk['stash_occupancy']}"
        )
        assert h0["stash_overflow"] == hk["stash_overflow"] == 0
        if oracle is not None:
            forced = [
                d.record.msg_id
                if r.request_type == C.REQUEST_TYPE_CREATE
                and d.status_code == C.STATUS_CODE_SUCCESS
                else None
                for r, d in zip(reqs, r0)
            ]
            ro = oracle.handle_batch(reqs, t, forced)
            for j, (d, o) in enumerate(zip(r0, ro)):
                assert d.status_code == o.status_code, (
                    f"tree_cache seed {seed} batch {bi} slot {j}: engine "
                    f"{d.status_code} != oracle {o.status_code}"
                )
                assert d.record.msg_id == o.record.msg_id
                assert d.record.payload == o.record.payload
            assert e0.message_count() == oracle.message_count()
            assert e0.recipient_count() == oracle.recipient_count()
        for r, d in zip(reqs, r0):
            if (r.request_type == C.REQUEST_TYPE_CREATE
                    and d.status_code == C.STATUS_CODE_SUCCESS):
                live_ids.append((d.record.msg_id, r.record.recipient))
            elif (r.request_type == C.REQUEST_TYPE_DELETE
                    and d.status_code == C.STATUS_CODE_SUCCESS):
                live_ids = [
                    (m, o_) for m, o_ in live_ids if m != d.record.msg_id
                ]
    if sweep:
        e0.expire(NOW + 10_000, 5_000)
        ek.expire(NOW + 10_000, 5_000)
    assert_logical_state_equal(
        e0.ecfg, e0.state, ek.ecfg, ek.state, f"tree_cache seed {seed}"
    )
    return e0, ek


# -- always-on: one compiled pair carries every fast assertion ----------


def test_tree_cache_campaign_with_sweep_soak_and_leakmon():
    """The budget-shaped always-on path: ONE uncached + ONE cached
    engine (plaintext BASE geometry) run a randomized oracle campaign
    with the per-round stash-drift canary, an expiry sweep, single-op
    batches, and a leakmon soak with caching enabled — zero additional
    compiles after the first round."""
    e0, ek = _run_tc_campaign(BASE, seed=5100, n_batches=4, sweep=True)
    assert ek.ecfg.rec.top_cache_levels == 4
    assert ek.ecfg.mb.top_cache_levels > 0  # clamped to the mb height

    # single-op batches on the same compiled pair (fill=1 → 7 dummies)
    _run_tc_campaign(BASE, seed=5101, n_batches=2, batch_fill=1,
                     pair=(e0, ek))

    # acceptance: leak monitor PASS on a live soak with caching enabled
    from grapevine_tpu.obs.leakmon import EngineLeakMonitor, LeakMonitorConfig

    mon = EngineLeakMonitor.for_engine(ek, LeakMonitorConfig(window_rounds=64))
    ek.attach_leakmon(mon)
    rng = np.random.default_rng(78)
    idents = [key(i) for i in range(1, 5)]
    live: list[tuple[bytes, bytes]] = []
    for bi in range(12):
        reqs = _gen_batch(rng, idents, live, 8)
        ek.handle_queries(reqs, NOW + 100 + bi)
    assert mon.flush(), "leak monitor did not drain"
    v = mon.verdict()
    assert v["verdict"] == "PASS", v
    mon.close()


@pytest.mark.slow  # ~35 s of oram-level cached/uncached equality
# breadth. Moved in the PR-9 tier-1 re-budget: the engine-level
# campaign above (sweep+soak+leakmon, logical-state equality) and the
# access-schedule CI audit keep the cache contract always-on.
def test_tree_cache_oram_level_directed():
    """Directed small-ORAM checks with NO engine compile: single
    ``oram_access`` CRUD against cached and uncached trees stays
    logically identical, the cache planes really hold the top levels,
    and k=0 state shapes are bit-for-bit the pre-PR-8 layout."""
    import jax.numpy as jnp

    from grapevine_tpu.oram.path_oram import (
        OramConfig,
        init_oram,
        oram_access,
        stash_occupancy,
    )
    from grapevine_tpu.testing.compare import logical_tree_planes

    kkey = jax.random.PRNGKey(5)
    cfgs = [
        OramConfig(height=4, value_words=4, n_blocks=16, cipher_rounds=8,
                   top_cache_levels=k)
        for k in (0, 2)
    ]
    states = [init_oram(c, kkey) for c in cfgs]
    assert states[0].cache_idx.size == 0
    assert states[1].cache_idx.size == 3 * 4  # (2^2−1) buckets × Z

    def wr(value, present, operand):
        return jnp.full((4,), operand, jnp.uint32), jnp.bool_(True), \
            jnp.bool_(True), present

    def rd(value, present, operand):
        return value, jnp.bool_(True), jnp.bool_(False), value

    # one small jit per (cfg, fn) — per-op eager dispatch of the whole
    # access program is ~10× slower on this sandbox
    import functools

    wrs = [
        jax.jit(functools.partial(oram_access, c, fn=wr)) for c in cfgs
    ]
    rds = [
        jax.jit(functools.partial(oram_access, c, fn=rd)) for c in cfgs
    ]

    rng = np.random.default_rng(3)
    for i in range(24):
        idx = np.uint32(rng.integers(0, 16))
        nl = np.uint32(rng.integers(0, 16))
        op = np.uint32(i + 1)
        outs = []
        for j in range(2):
            s, out, _leaf = wrs[j](states[j], idx, nl, op)
            states[j] = s
            outs.append(out)
        assert bool(outs[0]) == bool(outs[1]), f"access {i}: presence"
        assert int(stash_occupancy(states[0])) == int(
            stash_occupancy(states[1])
        ), f"access {i}: stash occupancy drifts"
    # reads see identical values through either path
    for idx in range(16):
        vals = []
        for j in range(2):
            s, out, _ = rds[j](
                states[j], np.uint32(idx), np.uint32(idx % 16), None
            )
            states[j] = s
            vals.append(np.asarray(out))
        assert np.array_equal(vals[0], vals[1]), f"read {idx}"
    p0 = logical_tree_planes(cfgs[0], states[0])
    p1 = logical_tree_planes(cfgs[1], states[1])
    assert np.array_equal(p0[0][:-1], p1[0][:-1])
    assert np.array_equal(p0[1][:-1], p1[1][:-1])
    # cached blocks live in the cache planes, not the HBM tree: the
    # cached state's top HBM rows must decrypt to NO live blocks — they
    # are stale by design (raw tree_idx is ciphertext under
    # cipher_rounds=8, so assert on the decrypted view, not raw bytes);
    # decode through the k=0 geometry (same tree shape, no overlay)
    from grapevine_tpu.oblivious.primitives import SENTINEL

    hbm_top = logical_tree_planes(cfgs[0], states[1])[0][
        : cfgs[1].cache_buckets
    ]
    assert np.all(hbm_top == int(SENTINEL)), (
        "cached top buckets' HBM rows must stay logically empty"
    )
    assert int(states[1].overflow) == 0
    # the cache really holds blocks (top levels fill under churn)
    assert np.any(np.asarray(states[1].cache_idx) != SENTINEL), (
        "24 accesses on a height-4 tree never evicted into the top "
        "2 levels — the cache is not being written"
    )


def test_tree_cache_access_schedule_audit():
    """CI gate (trace-only, flat map): index-blind census + the HBM
    row-count accounting with k=0 positive control — ISSUE-8's
    acceptance audit, wired into tier-1 next to the posmap/telemetry/
    seal gates."""
    from check_tree_cache_oblivious import check_tree_cache_schedule

    out = check_tree_cache_schedule(b=8, height=5, recursive=False)
    # per access: path_len − k bucket rows per HBM plane
    assert out["k0"]["tree_val"] == [8 * 6]
    assert out["k2"]["tree_val"] == [8 * 4]
    assert out["k2"]["cache_val"] == [8 * 2]


def test_tree_cache_checkpoint_fingerprint_rejects_cross_k(tmp_path):
    """A cached checkpoint must fail loudly against a differently-cached
    engine — the state shapes differ AND the fingerprint covers k. Pure
    serialization, no engine compile."""
    from grapevine_tpu.engine.checkpoint import (
        CheckpointError,
        bytes_to_state,
        engine_fingerprint,
        state_to_bytes,
    )
    from grapevine_tpu.engine.state import EngineConfig, init_engine

    kw = dict(BASE, max_messages=32, batch_size=4)
    ec0 = EngineConfig.from_config(
        GrapevineConfig(tree_top_cache_levels=0, **kw)
    )
    ec2 = EngineConfig.from_config(
        GrapevineConfig(tree_top_cache_levels=2, **kw)
    )
    assert engine_fingerprint(ec0) != engine_fingerprint(ec2)
    blob0 = state_to_bytes(ec0, init_engine(ec0, seed=1))
    blob2 = state_to_bytes(ec2, init_engine(ec2, seed=1))
    assert bytes_to_state(ec2, blob2) is not None  # control: self-loads
    with pytest.raises(CheckpointError, match="fingerprint"):
        bytes_to_state(ec2, blob0)
    with pytest.raises(CheckpointError, match="fingerprint"):
        bytes_to_state(ec0, blob2)


def test_tree_cache_config_validation():
    with pytest.raises(ValueError, match="tree_top_cache_levels"):
        GrapevineConfig(tree_top_cache_levels=-1)
    with pytest.raises(ValueError, match="tree_top_cache_levels"):
        GrapevineConfig(commit="op", tree_top_cache_levels=2)
    # per-tree clamp: k never exceeds a tree's height
    from grapevine_tpu.engine.state import EngineConfig

    ecfg = EngineConfig.from_config(
        GrapevineConfig(tree_top_cache_levels=30, **BASE)
    )
    assert ecfg.rec.top_cache_levels == ecfg.rec.height
    assert ecfg.mb.top_cache_levels == ecfg.mb.height
    # auto resolves per backend (4 under the phase engine everywhere —
    # the cache strictly removes HBM/cipher rows; PERF.md Round 10);
    # op-major (the differential oracle) stays cache-free
    auto = EngineConfig.from_config(GrapevineConfig(**BASE))
    assert auto.tree_top_cache_levels == 4
    op = EngineConfig.from_config(GrapevineConfig(commit="op", **BASE))
    assert op.tree_top_cache_levels == 0
    assert op.rec.top_cache_levels == 0
    # the OramConfig itself refuses k > height
    from grapevine_tpu.oram.path_oram import OramConfig

    with pytest.raises(ValueError, match="top_cache_levels"):
        OramConfig(height=3, value_words=4, top_cache_levels=4)
    # sizing helper: 2^k−1 bucket rows of idx+val words
    from grapevine_tpu.oram.path_oram import tree_cache_private_bytes

    c = OramConfig(height=5, value_words=8, top_cache_levels=3)
    assert tree_cache_private_bytes(c) == 7 * 4 * (4 + 4 * 8)


# -- slow: breadth, cipher, recursive posmap, geometry, chaos ----------


@pytest.mark.slow
def test_randomized_tree_cache_campaigns_full():
    """Regime breadth: steady-state, saturation fallback, single-op
    batches — fresh pairs + oracle per campaign, k varied."""
    n = int(os.environ.get("GRAPEVINE_TREE_CACHE_CAMPAIGNS", "12"))
    for i, (cfg, fill) in enumerate(_campaign_plan(n)):
        _run_tc_campaign(cfg, seed=5200 + i, batch_fill=fill,
                         k=(1, 2, 4)[i % 3])


@pytest.mark.slow
def test_tree_cache_campaign_cipher_on():
    """The at-rest cipher pair: cached levels skip cipher entirely while
    bottom levels re-key per round — the mixed regime must preserve the
    logical bit-identity end to end, sweep re-key included."""
    cfg = dict(BASE, bucket_cipher_rounds=8)
    _run_tc_campaign(cfg, seed=5300, n_batches=4, sweep=True)


@pytest.mark.slow
def test_tree_cache_campaign_recursive_posmap():
    """ROADMAP item 1 ∘ item 5: the cache applied to the payload trees
    AND the recursive posmap's internal tree (its top levels are touched
    every round too) stays bit-identical, leaf-metadata planes
    included."""
    cfg = dict(BASE, posmap_impl="recursive", bucket_cipher_rounds=8)
    _run_tc_campaign(cfg, seed=5400, n_batches=3, sweep=True, k=2)


@pytest.mark.slow
def test_tree_cache_campaign_scan_radix():
    """The cache split composes with the scan/radix round machinery
    (different gather layout, same logical content)."""
    cfg = dict(BASE, vphases_impl="scan", sort_impl="radix")
    _run_tc_campaign(cfg, seed=5500, n_batches=3)


@pytest.mark.slow
def test_tree_cache_single_op_batch_geometry():
    """batch_size=1 end to end: the B=1 cached round (degenerate owner
    map, single path) stays bit-identical and oracle-true."""
    cfg = dict(BASE, batch_size=1)
    for i in range(2):
        _run_tc_campaign(cfg, seed=5600 + i, n_batches=5, batch_fill=1,
                         k=3)


@pytest.mark.slow
def test_tree_cache_saturation_fallback_bitequal():
    """Bus saturation: rounds resolve through _admission_slow with the
    cache in the loop and must stay bit-identical, including
    TOO_MANY_MESSAGES admission order."""
    e0, ek = _mk_cache_pair(SAT_BUS, seed=9, k=3)
    a, x = key(1), key(2)
    rf = []
    for bi in range(3):
        reqs = [
            req(C.REQUEST_TYPE_CREATE, a, recipient=x, tag=bi * 8 + j)
            for j in range(8)
        ]
        rf = e0.handle_queries(reqs, NOW + bi)
        rk = ek.handle_queries(reqs, NOW + bi)
        _assert_responses_bitequal(rf, rk, f"sat batch {bi}")
    codes = {r.status_code for r in rf}
    assert C.STATUS_CODE_TOO_MANY_MESSAGES in codes
    assert_logical_state_equal(e0.ecfg, e0.state, ek.ecfg, ek.state, "sat")


@pytest.mark.slow
def test_tree_cache_recursive_audit():
    """The trace audit over a recursive-posmap geometry (inner tree's
    own cache planes included) — the heavier trace rides -m slow."""
    from check_tree_cache_oblivious import check_tree_cache_schedule

    check_tree_cache_schedule(b=8, height=5, recursive=True)


@pytest.mark.slow
def test_chaos_recovery_with_tree_cache():
    """SIGKILL trials with the tree-top cache on: sealed checkpoints
    cover the cache planes (they are ordinary state leaves), so
    recovered state and every response hash stay bit-identical to the
    uninterrupted oracle with leakmon PASS."""
    import chaos_run

    args = chaos_run.parse_args(
        ["--events", "14", "--tree-top-cache-levels", "2", "--seed", "43"]
    )
    failures = chaos_run.run_trials(3, args)
    assert not failures, "\n".join(failures)
