"""Block-index PRP (oblivious/prp.py): bijectivity + id-opacity.

The reference requires random-looking nonzero msg_ids so onlookers cannot
probe id structure (grapevine.proto:66-79); this engine meets it with a
keyed Feistel bijection over the block-index space.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from grapevine_tpu.oblivious.prp import (
    prp2_decrypt,
    prp2_encrypt,
    prp_decrypt,
    prp_encrypt,
)


@pytest.mark.parametrize("bits", [2, 3, 4, 7, 13, 16, 21])
def test_prp_bijection(bits):
    key = jax.random.bits(jax.random.PRNGKey(bits), (4,), jnp.uint32)
    n = min(1 << bits, 1 << 12)
    x = jnp.arange(n, dtype=jnp.uint32)
    y = prp_encrypt(key, x, bits)
    assert int(jnp.max(y)) < (1 << bits)
    # injective on the sample (and decrypt inverts)
    assert len(set(np.asarray(y).tolist())) == n
    np.testing.assert_array_equal(np.asarray(prp_decrypt(key, y, bits)), np.asarray(x))


def test_prp_full_domain_permutation():
    bits = 10
    key = jax.random.bits(jax.random.PRNGKey(7), (4,), jnp.uint32)
    x = jnp.arange(1 << bits, dtype=jnp.uint32)
    y = np.asarray(prp_encrypt(key, x, bits))
    assert sorted(y.tolist()) == list(range(1 << bits))


def test_prp_hides_sequential_structure():
    """Sequential plaintexts must not map to correlated ciphertexts: the
    top half of the index space should be hit ~half the time by the
    image of the bottom quarter (a raw or affine embedding would not)."""
    bits = 16
    key = jax.random.bits(jax.random.PRNGKey(3), (4,), jnp.uint32)
    x = jnp.arange(1 << 14, dtype=jnp.uint32)  # bottom quarter
    y = np.asarray(prp_encrypt(key, x, bits))
    frac_top = float((y >= (1 << 15)).mean())
    assert 0.4 < frac_top < 0.6
    # and keys matter
    key2 = jax.random.bits(jax.random.PRNGKey(4), (4,), jnp.uint32)
    y2 = np.asarray(prp_encrypt(key2, x, bits))
    assert (y != y2).mean() > 0.9


@pytest.mark.parametrize("bits", [2, 4, 13, 20, 31, 32])
def test_prp2_roundtrip_and_freshness(bits):
    key = jax.random.bits(jax.random.PRNGKey(bits), (4,), jnp.uint32)
    n = 1 << 10
    x = jnp.arange(n, dtype=jnp.uint32) % (1 << min(bits, 30))
    nonces = jax.random.bits(jax.random.PRNGKey(99), (n,), jnp.uint32)
    w0, w1 = prp2_encrypt(key, x, nonces, bits)
    assert int(jnp.max(w1)) < (1 << bits) or bits >= 32
    np.testing.assert_array_equal(
        np.asarray(prp2_decrypt(key, w0, w1, bits)), np.asarray(x)
    )
    # the same index under two nonces gives unrelated ciphertexts — the
    # LIFO-reuse probe from the round-3 review
    wa = prp2_encrypt(key, jnp.uint32(5), jnp.uint32(1), bits)
    wb = prp2_encrypt(key, jnp.uint32(5), jnp.uint32(2), bits)
    assert (int(wa[0]), int(wa[1])) != (int(wb[0]), int(wb[1]))


def test_engine_id_word0_fresh_across_block_reuse():
    """create → delete → create reuses the LIFO block; the id must still
    change in every word pair (no allocator-state probe)."""
    from grapevine_tpu.config import GrapevineConfig
    from grapevine_tpu.engine.batcher import GrapevineEngine
    from grapevine_tpu.wire import constants as C
    from grapevine_tpu.wire.records import QueryRequest, RequestRecord

    cfg = GrapevineConfig(bucket_cipher_rounds=0, 
        max_messages=64, max_recipients=8, mailbox_cap=4, batch_size=2
    )
    engine = GrapevineEngine(cfg, seed=2)
    me = b"\x05" * 32

    def create():
        r = engine.handle_queries(
            [
                QueryRequest(
                    request_type=C.REQUEST_TYPE_CREATE,
                    auth_identity=me,
                    auth_signature=b"\x01" * C.SIGNATURE_SIZE,
                    record=RequestRecord(
                        msg_id=C.ZERO_MSG_ID,
                        recipient=me,
                        payload=b"\x09" * C.PAYLOAD_SIZE,
                    ),
                )
            ],
            1_700_000_000,
        )[0]
        assert r.status_code == C.STATUS_CODE_SUCCESS
        return r.record.msg_id

    def delete(mid):
        r = engine.handle_queries(
            [
                QueryRequest(
                    request_type=C.REQUEST_TYPE_DELETE,
                    auth_identity=me,
                    auth_signature=b"\x01" * C.SIGNATURE_SIZE,
                    record=RequestRecord(
                        msg_id=mid, recipient=me, payload=b"\x00" * C.PAYLOAD_SIZE
                    ),
                )
            ],
            1_700_000_000,
        )[0]
        assert r.status_code == C.STATUS_CODE_SUCCESS

    seen = set()
    for _ in range(6):
        mid = create()
        assert mid[:8] not in seen, "id words 0-1 repeated across block reuse"
        seen.add(mid[:8])
        delete(mid)


def test_engine_ids_do_not_reveal_allocation_order():
    """End-to-end: consecutive creates' id word 0 must not be consecutive
    block indices (the round-2 verdict's allocator-state leak)."""
    from grapevine_tpu.config import GrapevineConfig
    from grapevine_tpu.engine.batcher import GrapevineEngine
    from grapevine_tpu.wire import constants as C
    from grapevine_tpu.wire.records import QueryRequest, RequestRecord

    cfg = GrapevineConfig(bucket_cipher_rounds=0, 
        max_messages=256, max_recipients=8, mailbox_cap=8, batch_size=4
    )
    engine = GrapevineEngine(cfg, seed=1)
    ident = b"\x01" * 32
    reqs = [
        QueryRequest(
            request_type=C.REQUEST_TYPE_CREATE,
            auth_identity=ident,
            auth_signature=b"\x01" * C.SIGNATURE_SIZE,
            record=RequestRecord(
                msg_id=C.ZERO_MSG_ID,
                recipient=ident,
                payload=bytes([i]) * C.PAYLOAD_SIZE,
            ),
        )
        for i in range(8)
    ]
    resps = engine.handle_queries(reqs, 1_700_000_000)
    words = [int.from_bytes(r.record.msg_id[:4], "little") for r in resps]
    assert all(r.status_code == C.STATUS_CODE_SUCCESS for r in resps)
    assert len(set(words)) == len(words)
    diffs = {b - a for a, b in zip(words, words[1:])}
    assert diffs != {1} and diffs != {-1}, "ids expose allocation order"
