"""ISSUE 14: Rangelint — geometry-scale overflow certification.

Five suites:

1. interval-domain directed units, one per primitive class the tentpole
   names (shift, mul, cast, scan-carry fixpoint, clamped gather) plus
   the transfer refinements the engine's idioms rely on (where-clamp
   predicate narrowing through pjit, select branch feasibility,
   scatter-min/add, exclusive-rank forms);
2. the seeded overflow-mutant teeth matrix under the PRODUCTION range
   allowlist (tools/check_ranges.py and the shared check_oblivious
   mutant control run the same set);
3. the tier-1 smoke gate: one toy-geometry engine trace certifies
   clean, zero compiles;
4. geometry certification: 2^36 (the ROADMAP item 4 design point) is
   REFUSED at construction by the certified-bound guard with a message
   this report can cite, while the max certified per-tree geometry
   traces clean (the full 2^30 matrix rides -m slow);
5. the allowlist contract: reachability accounting and family matching
   shared with oblint's AllowEntry.
"""

from __future__ import annotations

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from grapevine_tpu.analysis.allowlist import RANGE_ALLOWLIST
from grapevine_tpu.analysis.mutants import range_mutant_names, run_range_mutants
from grapevine_tpu.analysis.oblint import AllowEntry
from grapevine_tpu.analysis.rangelint import analyze_ranges, dtype_range

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

U32 = jnp.uint32


def _sds(*shape, dtype=np.uint32):
    return jax.ShapeDtypeStruct(shape, dtype)


def _kinds(rep):
    return {f.kind for f in rep.findings}


# ----------------------------------------------------------------------
# 1. interval-domain directed units
# ----------------------------------------------------------------------


def test_dtype_range():
    assert dtype_range(np.uint32) == (0, 2**32 - 1)
    assert dtype_range(np.int32) == (-(2**31), 2**31 - 1)
    assert dtype_range(np.bool_) == (0, 1)
    assert dtype_range(np.float32) is None


def test_add_within_bounds_is_clean_and_escape_flags():
    def fn(x):
        return x + U32(100)

    ok = analyze_ranges(fn, {"x": _sds(4)}, {"x": (0, 1000)})
    assert ok.ok, ok.summary()
    bad = analyze_ranges(fn, {"x": _sds(4)}, {"x": (0, 2**32 - 50)})
    assert _kinds(bad) == {"overflow"}


def test_shift_left_overflow_and_masked_recovery():
    def fn(x):
        return (x << U32(8)) & U32(0xFFFF)

    rep = analyze_ranges(fn, {"x": _sds(4)}, {"x": (0, 2**30)})
    # the shift escapes u32; the AND afterwards cannot unflag it
    assert _kinds(rep) == {"overflow"}
    ok = analyze_ranges(fn, {"x": _sds(4)}, {"x": (0, 2**20)})
    assert ok.ok


def test_mul_interval_products():
    def fn(rows):
        return rows * U32(4096)

    assert analyze_ranges(
        fn, {"rows": _sds(2)}, {"rows": (0, 2**19)}
    ).ok
    assert _kinds(analyze_ranges(
        fn, {"rows": _sds(2)}, {"rows": (0, 2**21)}
    )) == {"overflow"}


def test_sub_unsigned_underflow_flags():
    def fn(a, b):
        return a - b

    rep = analyze_ranges(
        fn, {"a": _sds(2), "b": _sds(2)}, {"a": (0, 10), "b": (0, 10)}
    )
    assert _kinds(rep) == {"overflow"}
    ok = analyze_ranges(
        fn, {"a": _sds(2), "b": _sds(2)}, {"a": (10, 20), "b": (0, 10)}
    )
    assert ok.ok


def test_narrowing_cast_flags_and_bounded_cast_clean():
    def fn(x):
        return x.astype(jnp.int32)

    assert _kinds(analyze_ranges(fn, {"x": _sds(4)})) == {"trunc-cast"}
    assert analyze_ranges(fn, {"x": _sds(4)}, {"x": (0, 2**31 - 1)}).ok


def test_gather_oob_flags_and_clamped_gather_clean():
    def raw(idx, table):
        return table[idx]

    def clamped(idx, table):
        return table[jnp.minimum(idx, U32(15))]

    # the unbounded index flags OOB (and its int32 conversion truncates)
    assert "oob-index" in _kinds(analyze_ranges(
        raw, {"idx": _sds(4), "table": _sds(16)}
    ))
    assert analyze_ranges(
        clamped, {"idx": _sds(4), "table": _sds(16)}
    ).ok


def test_where_clamp_idiom_narrows_through_pjit():
    """The codebase's `where(x < N, x, M)` clamp must bound the index
    even though jnp.where wraps its select_n in a pjit body."""
    def fn(idx, table):
        safe = jnp.where(idx < U32(16), idx, U32(16))
        return table[safe]

    assert analyze_ranges(fn, {"idx": _sds(4), "table": _sds(17)}).ok


def test_negative_index_normalization_branch_pruned():
    """jnp lowers x[i] (signed i) to select(i < 0, i + n, i); for i
    provably >= 0 the dead branch must not widen the interval."""
    def fn(idx, table):
        return table[idx.astype(jnp.int32)]

    assert analyze_ranges(
        fn, {"idx": _sds(4), "table": _sds(16)}, {"idx": (0, 15)}
    ).ok


def test_drop_mode_scatter_oob_is_the_masking_idiom():
    """OOB-drops-the-write is documented semantics — never flagged; the
    sentinel itself fitting the index lane is what gets certified."""
    def fn(idx, plane):
        tgt = jnp.where(idx < U32(8), idx, U32(8))  # 8 = drop sentinel
        return plane.at[tgt].set(U32(1), mode="drop")

    assert analyze_ranges(fn, {"idx": _sds(4), "plane": _sds(8)}).ok


def test_scan_carry_fixpoint_budgets_trip_count():
    """A counter gaining at most `inc` per step certifies at exactly
    length·inc — clean when the budget fits, flagged when it does not
    (the affine-widening half of the unbounded-scan-counter mutant)."""
    def fn(inc):
        def body(c, x):
            return c + inc[0], x

        return jax.lax.scan(body, U32(0), jnp.zeros((1024,), U32))

    assert analyze_ranges(fn, {"inc": _sds(1)}, {"inc": (0, 2**20)}).ok
    assert "overflow" in _kinds(analyze_ranges(
        fn, {"inc": _sds(1)}, {"inc": (0, 2**23)}
    ))


def test_scan_carry_derived_increment_not_certified_affine():
    """Soundness regression (review finding): an increment derived from
    the carry itself (c + (c >> 10): exponential growth that looks flat
    across two narrow passes) must NOT be certified by affine
    extrapolation — the inductiveness check widens it to the lane and
    the wrap flags inside the body."""
    def fn(xs):
        def body(c, x):
            return c + (c >> U32(10)), x

        return jax.lax.scan(body, U32(1024), xs)

    rep = analyze_ranges(fn, {"xs": _sds(1 << 16)})
    assert "overflow" in _kinds(rep), rep.summary()


def test_while_carry_widens_to_lane_and_flags_inside_body():
    def fn(s):
        def cond(c):
            return c[0] < s[0]

        def body(c):
            return (c[0] + U32(1), c[1] * U32(2))

        return jax.lax.while_loop(cond, body, (U32(0), U32(1)))

    rep = analyze_ranges(fn, {"s": _sds(1)})
    assert "overflow" in _kinds(rep)


def test_scatter_min_transfer_bounds_owner_map():
    """The owner-election idiom: full(B).at[hb].min(cols) stays in
    [0, B] — its consumer arithmetic must not widen to the lane."""
    def fn(hb, cols):
        bmap = jnp.full((64,), U32(8)).at[hb].min(cols)
        return bmap * U32(4)  # would flag if bmap were full-range

    assert analyze_ranges(
        fn, {"hb": _sds(16), "cols": _sds(16)},
        {"hb": (0, 63), "cols": (0, 7)},
    ).ok


def test_scatter_add_accumulation_budget():
    def fn(x, upd):
        return x.at[jnp.zeros((8,), jnp.int32)].add(upd)

    ok = analyze_ranges(
        fn, {"x": _sds(4), "upd": _sds(8)},
        {"x": (0, 100), "upd": (0, 10)},
    )
    assert ok.ok  # 100 + 8*10 fits easily
    bad = analyze_ranges(
        fn, {"x": _sds(4), "upd": _sds(8)},
        {"x": (0, 100), "upd": (0, 2**30)},
    )
    assert _kinds(bad) == {"overflow"}


def test_allowlist_admits_by_site_and_counts_hits():
    def fn(a, b):
        return a + b

    bare = analyze_ranges(fn, {"a": _sds(2), "b": _sds(2)})
    assert len(bare.findings) == 1
    site = bare.findings[0].site
    entry = AllowEntry("add", site, "test: wrap is intended here")
    allowed = analyze_ranges(
        fn, {"a": _sds(2), "b": _sds(2)}, allowlist=(entry,)
    )
    assert allowed.ok
    assert allowed.allowed == {f"add@{site}": 1}


def test_trace_abort_is_a_finding_not_a_crash():
    def fn(x):
        return x + np.uint32(2**31)  # fine

    # a builder that raises at trace time (e.g. a geometry guard)
    def boom(x):
        raise ValueError("refused: certified bound exceeded")

    rep = analyze_ranges(boom, {"x": _sds(2)})
    assert _kinds(rep) == {"trace-abort"}
    assert "refused" in rep.findings[0].message
    assert analyze_ranges(fn, {"x": _sds(2)}, {"x": (0, 100)}).ok


# ----------------------------------------------------------------------
# 2. overflow-mutant teeth matrix (under the PRODUCTION allowlist)
# ----------------------------------------------------------------------


def test_range_mutant_matrix_all_caught():
    assert len(range_mutant_names()) == 6
    results = run_range_mutants(RANGE_ALLOWLIST)
    missed = {
        name: (kind, [f.kind for f in rep.findings])
        for name, (rep, kind, hit) in results.items()
        if not hit
    }
    assert not missed, f"range mutants NOT caught: {missed}"


def test_range_mutants_caught_for_the_right_reason():
    for name, (rep, kind, hit) in run_range_mutants(RANGE_ALLOWLIST).items():
        kinds = [f.kind for f in rep.findings]
        assert kinds.count(kind) >= 1, (name, kind, kinds)


# ----------------------------------------------------------------------
# 3. the tier-1 smoke gate (traces only, zero engine compiles)
# ----------------------------------------------------------------------


def test_check_ranges_smoke_gate():
    """tools/check_ranges.py --smoke wired into tier-1 next to the
    telemetry/seal/oblint gates: one toy-geometry engine trace certifies
    interval-clean, the design point refuses, all overflow mutants
    caught. Budget: ~1 engine trace, 0 compiles."""
    import check_ranges as gate

    assert gate.main(["--smoke"]) == 0


def test_smoke_engine_audit_exercises_the_allowlist():
    import check_ranges as gate

    vp, srt, pmi, k, ee = gate.SMOKE_COMBO
    rep = gate.audit_engine_round(
        gate._engine(5, vp, srt, pmi, k, ee), RANGE_ALLOWLIST,
        "tier1_smoke",
    )
    assert rep.ok, rep.summary()
    # not vacuous: the ChaCha/mixer/carry sites really were walked
    assert sum(rep.allowed.values()) > 100
    assert rep.n_eqns > 1000


# ----------------------------------------------------------------------
# 4. geometry certification: the 2^36 design point
# ----------------------------------------------------------------------


def test_design_point_refused_with_citable_message():
    """2^36 records must REFUSE at engine construction, citing the
    certified bound — the directed guard ISSUE 14 installs so item 4
    starts from a certified substrate (never a silent wraparound)."""
    import check_ranges as gate

    problems, refusal = gate.certify_design_point(gate.DESIGN_POINT)
    assert not problems
    assert "certified bound" in refusal
    assert "OPERATIONS.md" in refusal


def test_certified_bound_guard_edges():
    """The guard's edges: the max certified geometry constructs; one
    height past it refuses; oversubscribed block spaces refuse."""
    from grapevine_tpu.oram.path_oram import (
        MAX_U32_BLOCKS, MAX_U32_HEIGHT, OramConfig,
    )

    OramConfig(height=MAX_U32_HEIGHT, value_words=1,
               n_blocks=MAX_U32_BLOCKS)  # constructs
    with pytest.raises(ValueError, match="certified"):
        OramConfig(height=MAX_U32_HEIGHT + 1, value_words=1)
    with pytest.raises(ValueError, match="certified"):
        OramConfig(height=MAX_U32_HEIGHT, value_words=1,
                   n_blocks=2 * MAX_U32_BLOCKS)


def test_journal_frame_length_guard():
    """The host prong: a batch geometry whose sealed journal frame
    cannot fit the u32 blob_len wire field refuses at construction."""
    from grapevine_tpu.config import GrapevineConfig
    from grapevine_tpu.engine.journal import BatchJournal
    from grapevine_tpu.engine.state import EngineConfig

    class _HugeBatch:
        """EngineConfig stand-in: only batch_size is consulted."""

        batch_size = 1 << 23  # ~8.6 GB frame: past the u32 blob_len

    with pytest.raises(ValueError, match="blob_len"):
        BatchJournal("/tmp/x", b"\x00" * 32, _HugeBatch())
    # a sane geometry constructs (no files touched before open)
    ecfg = EngineConfig.from_config(GrapevineConfig(
        max_messages=32, max_recipients=16, batch_size=4,
    ))
    BatchJournal("/tmp/x", b"\x00" * 32, ecfg)


@pytest.mark.slow
def test_full_certification_at_max_certified_geometry():
    """The acceptance sweep: every shipped knob combo at 2^30 AND the
    2^36 design point (refusal + shard certification), end to end."""
    import check_ranges as gate

    assert gate.main(["--geometry", "30"]) == 0
    assert gate.main(["--geometry", "36"]) == 0


@pytest.mark.slow
def test_full_knob_cross_product():
    import check_ranges as gate

    assert gate.main(["--full"]) == 0


# ----------------------------------------------------------------------
# 5. allowlist contract
# ----------------------------------------------------------------------


def test_range_allowlist_entries_have_arguments():
    for e in RANGE_ALLOWLIST:
        assert e.reason and len(e.reason) > 20, e.key


def test_range_allowlist_reachability_accounting():
    import check_ranges as gate

    problems, hits = gate.run_audit(
        (gate.SMOKE_COMBO,), 5, with_subrounds=False
    )
    assert not problems, problems
    # the smoke slice alone reaches the cipher/carry entries; full
    # reachability (every entry) is enforced by the default sweep
    assert any(k.startswith("add@oblivious/bucket_cipher.py")
               for k in hits)


if __name__ == "__main__":
    sys.exit(os.system(f"{sys.executable} -m pytest {__file__} -q"))
