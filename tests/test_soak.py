"""Gated randomized soak: engine ≡ oracle over many seeds and configs.

Skipped by default (CI runs the fixed-seed suites in test_round.py);
set GRAPEVINE_SOAK=N to run N seeded campaigns, each a full randomized
CRUD session (25 batches with same-key hazards) followed by a drain-to-
empty expiry check, cycling density × cipher × batch × cipher-impl.
Round-3 builder runs: 1,294 campaigns across six mixes — phase-major
(seeds 200-259, 300-599, 600-1099, 2000-2199, and 3000-3149 at 2
identities for extreme same-key contention) plus 80 op-major campaigns
(seeds 4000-4079 vs the per-op oracle); batch 4-32, density 1/2/4,
cipher on/off, jnp/pallas — zero divergence.
"""

import dataclasses
import os
import random

import pytest

from grapevine_tpu.engine.batcher import GrapevineEngine
from grapevine_tpu.testing.reference import ReferenceEngine
from grapevine_tpu.wire import constants as C

from test_round import SMALL, assert_responses_equal, key, req

N_SOAK = int(os.environ.get("GRAPEVINE_SOAK", "0"))

pytestmark = pytest.mark.skipif(
    N_SOAK <= 0, reason="set GRAPEVINE_SOAK=N to run N soak campaigns"
)

NOW = 1_700_000_000

VARIANTS = [
    (2, 8, 8, "jnp"),
    (4, 0, 16, "jnp"),
    (2, 0, 12, "pallas"),
    (4, 8, 6, "pallas"),
]


def _campaign(cfg, seed, n_steps=25):
    engine = GrapevineEngine(cfg, seed=seed)
    oracle = ReferenceEngine(config=cfg, rng=random.Random(seed + 1))
    rng = random.Random(seed + 2)
    idents = [key(i + 1) for i in range(6)]
    live = []
    t = NOW
    for step_no in range(n_steps):
        t += rng.randrange(3)
        reqs = []
        for _ in range(rng.randrange(1, cfg.batch_size + 1)):
            c = rng.random()
            if c < 0.35 or not live:
                reqs.append(req(C.REQUEST_TYPE_CREATE, rng.choice(idents),
                                recipient=rng.choice(idents), tag=rng.randrange(256)))
            elif c < 0.55:
                mid, snd, rcp = rng.choice(live)
                reqs.append(req(C.REQUEST_TYPE_READ,
                                rng.choice([snd, rcp, rng.choice(idents)]),
                                msg_id=mid))
            elif c < 0.7:
                reqs.append(req(C.REQUEST_TYPE_READ, rng.choice(idents)))
            elif c < 0.8:
                mid, snd, rcp = rng.choice(live)
                reqs.append(req(C.REQUEST_TYPE_UPDATE, rng.choice([snd, rcp]),
                                msg_id=mid, recipient=rcp, tag=rng.randrange(256)))
            elif c < 0.9:
                mid, snd, rcp = rng.choice(live)
                reqs.append(req(C.REQUEST_TYPE_DELETE,
                                rng.choice([snd, rcp, rng.choice(idents)]),
                                msg_id=mid, recipient=rcp))
            else:
                reqs.append(req(C.REQUEST_TYPE_DELETE, rng.choice(idents)))
        dev = engine.handle_queries(reqs, t)
        forced = [d.record.msg_id
                  if r.request_type == C.REQUEST_TYPE_CREATE
                  and d.status_code == C.STATUS_CODE_SUCCESS else None
                  for r, d in zip(reqs, dev)]
        ora = oracle.handle_batch(reqs, t, forced)
        for j, (r, d, o) in enumerate(zip(reqs, dev, ora)):
            assert_responses_equal(
                d, o, f"seed {seed} step {step_no} slot {j} rt {r.request_type}"
            )
            if o.status_code == C.STATUS_CODE_SUCCESS:
                if r.request_type == C.REQUEST_TYPE_CREATE:
                    live.append((o.record.msg_id, o.record.sender, o.record.recipient))
                elif r.request_type == C.REQUEST_TYPE_DELETE:
                    live = [e for e in live if e[0] != o.record.msg_id]
        assert engine.message_count() == oracle.message_count(), (seed, step_no)
        assert engine.recipient_count() == oracle.recipient_count(), (seed, step_no)
    assert engine.health()["stash_overflow"] == 0
    assert engine.expire(t + 10_000, period=5) == oracle.expire(t + 10_000, period=5)
    assert engine.message_count() == oracle.message_count() == 0


@pytest.mark.parametrize("i", range(max(N_SOAK, 0)))
def test_soak_campaign(i):
    seed = 1000 + i
    density, cipher, bs, impl = VARIANTS[i % len(VARIANTS)]
    cfg = dataclasses.replace(
        SMALL,
        tree_density=density,
        bucket_cipher_rounds=cipher,
        batch_size=bs,
        bucket_cipher_impl=impl,
    )
    _campaign(cfg, seed)
