"""Op-major device engine vs CPU oracle: result equality on random op sequences,
R/U/D transcript indistinguishability, expiry, and capacity reuse.

Test pyramid items (2), (4) from SURVEY.md §4.
"""

import random

import numpy as np

from grapevine_tpu.config import GrapevineConfig
from grapevine_tpu.engine.batcher import GrapevineEngine
from grapevine_tpu.testing.reference import ReferenceEngine
from grapevine_tpu.wire import constants as C
from grapevine_tpu.wire.records import QueryRequest, RequestRecord

NOW = 1_700_000_000

SMALL = GrapevineConfig(bucket_cipher_rounds=0, 
    max_messages=64,
    max_recipients=8,
    mailbox_cap=4,
    batch_size=8,
    stash_size=64,
    commit="op",
)


def key(n: int) -> bytes:
    return bytes([n, n ^ 0x5A]) + b"\x01" * 30


def req(rt, auth, msg_id=C.ZERO_MSG_ID, recipient=C.ZERO_PUBKEY, pl=None, tag=0):
    return QueryRequest(
        request_type=rt,
        auth_identity=auth,
        auth_signature=b"\x01" * C.SIGNATURE_SIZE,
        record=RequestRecord(
            msg_id=msg_id,
            recipient=recipient,
            payload=pl if pl is not None else bytes([tag & 0xFF]) * C.PAYLOAD_SIZE,
        ),
    )


def assert_responses_equal(dev, ora, ctx=""):
    assert dev.status_code == ora.status_code, f"{ctx}: status {dev.status_code} != {ora.status_code}"
    assert dev.record.msg_id == ora.record.msg_id, f"{ctx}: id"
    assert dev.record.sender == ora.record.sender, f"{ctx}: sender"
    assert dev.record.recipient == ora.record.recipient, f"{ctx}: recipient"
    assert dev.record.payload == ora.record.payload, f"{ctx}: payload"
    assert dev.record.timestamp == ora.record.timestamp, f"{ctx}: ts"


def test_engine_matches_oracle_random_ops():
    """~200 random CRUD ops, engine and oracle must agree on everything."""
    engine = GrapevineEngine(SMALL, seed=1)
    oracle = ReferenceEngine(config=SMALL, rng=random.Random(99))
    rng = random.Random(42)
    idents = [key(i + 1) for i in range(6)]
    live_ids: list[tuple[bytes, bytes, bytes]] = []  # (msg_id, sender, recipient)

    t = NOW
    for step_no in range(40):
        t += rng.randrange(3)
        n_ops = rng.randrange(1, SMALL.batch_size + 1)
        reqs = []
        for _ in range(n_ops):
            c = rng.random()
            if c < 0.4 or not live_ids:
                sender, recip = rng.choice(idents), rng.choice(idents)
                reqs.append(req(C.REQUEST_TYPE_CREATE, sender, recipient=recip, tag=rng.randrange(256)))
            elif c < 0.6:
                mid, snd, rcp = rng.choice(live_ids)
                auth = rng.choice([snd, rcp, rng.choice(idents)])
                mid_q = mid if rng.random() < 0.8 else rng.randbytes(16)
                reqs.append(req(C.REQUEST_TYPE_READ, auth, msg_id=mid_q))
            elif c < 0.7:
                auth = rng.choice(idents)
                reqs.append(req(C.REQUEST_TYPE_READ, auth))  # zero id: next message
            elif c < 0.8:
                mid, snd, rcp = rng.choice(live_ids)
                auth = rng.choice([snd, rcp])
                recip_q = rcp if rng.random() < 0.8 else rng.choice(idents)
                reqs.append(req(C.REQUEST_TYPE_UPDATE, auth, msg_id=mid, recipient=recip_q, tag=rng.randrange(256)))
            elif c < 0.9:
                mid, snd, rcp = rng.choice(live_ids)
                auth = rng.choice([snd, rcp, rng.choice(idents)])
                reqs.append(req(C.REQUEST_TYPE_DELETE, auth, msg_id=mid, recipient=rcp))
            else:
                auth = rng.choice(idents)
                reqs.append(req(C.REQUEST_TYPE_DELETE, auth))  # pop next

        dev_resps = engine.handle_queries(reqs, t)
        for r, dev in zip(reqs, dev_resps):
            forced = (
                dev.record.msg_id
                if r.request_type == C.REQUEST_TYPE_CREATE
                and dev.status_code == C.STATUS_CODE_SUCCESS
                else None
            )
            ora = oracle.handle_query(r, t, forced_msg_id=forced)
            assert_responses_equal(dev, ora, f"step {step_no} op {r.request_type}")
            # maintain the live-id pool from oracle state
            if ora.status_code == C.STATUS_CODE_SUCCESS:
                if r.request_type == C.REQUEST_TYPE_CREATE:
                    live_ids.append(
                        (ora.record.msg_id, ora.record.sender, ora.record.recipient)
                    )
                elif r.request_type == C.REQUEST_TYPE_DELETE:
                    live_ids = [e for e in live_ids if e[0] != ora.record.msg_id]

        assert engine.message_count() == oracle.message_count()
        assert engine.recipient_count() == oracle.recipient_count()
    assert engine.health()["stash_overflow"] == 0


def test_mailbox_cap_and_capacity_reuse():
    cfg = GrapevineConfig(bucket_cipher_rounds=0, 
        max_messages=8, max_recipients=4, mailbox_cap=3, batch_size=4, stash_size=64, commit="op"
    )
    engine = GrapevineEngine(cfg, seed=5)
    a, b = key(1), key(2)
    # fill b's mailbox to the cap
    for i in range(3):
        (r,) = engine.handle_queries([req(C.REQUEST_TYPE_CREATE, a, recipient=b)], NOW)
        assert r.status_code == C.STATUS_CODE_SUCCESS
    (r,) = engine.handle_queries([req(C.REQUEST_TYPE_CREATE, a, recipient=b)], NOW)
    assert r.status_code == C.STATUS_CODE_TOO_MANY_MESSAGES_FOR_RECIPIENT
    # pop one, slot frees up
    (r,) = engine.handle_queries([req(C.REQUEST_TYPE_DELETE, b)], NOW)
    assert r.status_code == C.STATUS_CODE_SUCCESS
    (r,) = engine.handle_queries([req(C.REQUEST_TYPE_CREATE, a, recipient=b)], NOW)
    assert r.status_code == C.STATUS_CODE_SUCCESS

    # fill the whole bus (8 messages): 3 live for b, then 3 to key(3) (its
    # cap), then the per-recipient cap kicks in
    fills = [
        engine.handle_queries([req(C.REQUEST_TYPE_CREATE, a, recipient=key(3))], NOW)[
            0
        ].status_code
        for _ in range(5)
    ]
    assert fills == [C.STATUS_CODE_SUCCESS] * 3 + [
        C.STATUS_CODE_TOO_MANY_MESSAGES_FOR_RECIPIENT
    ] * 2
    # 6 live; two more to fresh recipients fill the bus
    for peer in (key(4), key(5)):
        (r,) = engine.handle_queries([req(C.REQUEST_TYPE_CREATE, a, recipient=peer)], NOW)
        assert r.status_code == C.STATUS_CODE_SUCCESS
    # bus now full: 8 live messages
    (r,) = engine.handle_queries([req(C.REQUEST_TYPE_CREATE, a, recipient=key(6))], NOW)
    assert r.status_code == C.STATUS_CODE_TOO_MANY_MESSAGES
    # deleting one frees a block for reuse
    (r,) = engine.handle_queries([req(C.REQUEST_TYPE_DELETE, b)], NOW)
    assert r.status_code == C.STATUS_CODE_SUCCESS
    (r,) = engine.handle_queries([req(C.REQUEST_TYPE_CREATE, a, recipient=key(4))], NOW)
    assert r.status_code == C.STATUS_CODE_SUCCESS


def test_rud_transcripts_bit_identical():
    """READ, UPDATE, DELETE of the same message from identically-seeded
    engines produce bit-identical public transcripts — the reference's
    core obliviousness invariant (grapevine.proto:120-122), checked at
    its strongest: not just same distribution, the same bits."""
    a, b = key(7), key(8)

    def fresh():
        e = GrapevineEngine(SMALL, seed=11)
        (r,) = e.handle_queries([req(C.REQUEST_TYPE_CREATE, a, recipient=b)], NOW)
        assert r.status_code == C.STATUS_CODE_SUCCESS
        return e, r.record.msg_id

    transcripts = {}
    for rt in (C.REQUEST_TYPE_READ, C.REQUEST_TYPE_UPDATE, C.REQUEST_TYPE_DELETE):
        e, mid = fresh()
        _, tr = e.handle_queries_with_transcript(
            [req(rt, b, msg_id=mid, recipient=b)], NOW + 1
        )
        transcripts[rt] = tr
    assert np.array_equal(transcripts[C.REQUEST_TYPE_READ], transcripts[C.REQUEST_TYPE_UPDATE])
    assert np.array_equal(transcripts[C.REQUEST_TYPE_READ], transcripts[C.REQUEST_TYPE_DELETE])

    # failed ops are indistinguishable from successful ones too
    e, mid = fresh()
    _, tr_wrong_auth = e.handle_queries_with_transcript(
        [req(C.REQUEST_TYPE_DELETE, key(9), msg_id=mid, recipient=b)], NOW + 1
    )
    assert np.array_equal(transcripts[C.REQUEST_TYPE_DELETE], tr_wrong_auth)


def test_delete_with_half_guessed_id_mutates_nothing():
    """Regression: a DELETE whose msg_id matches on words 0-1 but not 2-3
    must not touch the mailbox (the oracle mutates nothing on mismatch)."""
    engine = GrapevineEngine(SMALL, seed=21)
    a, b = key(1), key(2)
    (r,) = engine.handle_queries([req(C.REQUEST_TYPE_CREATE, a, recipient=b)], NOW)
    assert r.status_code == C.STATUS_CODE_SUCCESS
    mid = r.record.msg_id
    half = mid[:8] + bytes(x ^ 0xFF for x in mid[8:])  # words 0-1 right, 2-3 wrong
    (d,) = engine.handle_queries(
        [req(C.REQUEST_TYPE_DELETE, b, msg_id=half, recipient=b)], NOW + 1
    )
    assert d.status_code == C.STATUS_CODE_NOT_FOUND
    # the message is still fully readable via the mailbox
    (rr,) = engine.handle_queries([req(C.REQUEST_TYPE_READ, b)], NOW + 2)
    assert rr.status_code == C.STATUS_CODE_SUCCESS
    assert rr.record.msg_id == mid
    assert engine.message_count() == 1


def test_expiry_sweep_engine_vs_oracle():
    cfg = GrapevineConfig(bucket_cipher_rounds=0, 
        max_messages=32, max_recipients=8, mailbox_cap=4, batch_size=4,
        stash_size=64, expiry_period=100, commit="op",
    )
    engine = GrapevineEngine(cfg, seed=6)
    oracle = ReferenceEngine(config=cfg, rng=random.Random(1))
    a, b, c = key(1), key(2), key(3)

    for auth, recip, t in [(a, b, NOW), (a, c, NOW + 60), (c, b, NOW + 120)]:
        (r,) = engine.handle_queries([req(C.REQUEST_TYPE_CREATE, auth, recipient=recip)], t)
        assert r.status_code == C.STATUS_CODE_SUCCESS
        oracle.handle_query(
            req(C.REQUEST_TYPE_CREATE, auth, recipient=recip), t,
            forced_msg_id=r.record.msg_id,
        )

    n_dev = engine.expire(NOW + 151)
    n_ora = oracle.expire(NOW + 151)
    assert n_dev == n_ora == 1  # only the NOW message is older than 100
    assert engine.message_count() == oracle.message_count() == 2
    assert engine.recipient_count() == oracle.recipient_count()

    # the expired message is gone from reads; survivors intact
    for auth in (b, c):
        dev = engine.handle_queries([req(C.REQUEST_TYPE_READ, auth)], NOW + 152)[0]
        ora = oracle.handle_query(req(C.REQUEST_TYPE_READ, auth), NOW + 152)
        assert_responses_equal(dev, ora, "post-expiry read")

    # freed capacity is reusable
    (r,) = engine.handle_queries([req(C.REQUEST_TYPE_CREATE, a, recipient=b)], NOW + 160)
    assert r.status_code == C.STATUS_CODE_SUCCESS


def test_expiry_clock_regression_keeps_future_records():
    """Regression: a sweep clock behind a record's timestamp must not
    mass-evict via u32 wraparound (oracle uses signed comparison)."""
    cfg = GrapevineConfig(bucket_cipher_rounds=0, 
        max_messages=16, max_recipients=4, mailbox_cap=4, batch_size=2,
        stash_size=64, expiry_period=100, commit="op",
    )
    engine = GrapevineEngine(cfg, seed=8)
    (r,) = engine.handle_queries([req(C.REQUEST_TYPE_CREATE, key(1), recipient=key(2))], NOW)
    assert r.status_code == C.STATUS_CODE_SUCCESS
    assert engine.expire(NOW - 10) == 0  # clock stepped back: keep everything
    assert engine.message_count() == 1
    (rr,) = engine.handle_queries([req(C.REQUEST_TYPE_READ, key(2))], NOW)
    assert rr.status_code == C.STATUS_CODE_SUCCESS


def test_default_mailbox_cap_62_enforced_and_drains():
    """The production default cap (62, the reference's compile-time
    constant, README.md:78-80) enforced at the exact boundary: 62
    creates to one recipient succeed, the 63rd fails, and the mailbox
    drains in creation order — against the oracle throughout."""
    import random as _random

    from grapevine_tpu.testing.reference import ReferenceEngine

    cfg = GrapevineConfig(
        bucket_cipher_rounds=0,
        max_messages=128,
        max_recipients=8,
        batch_size=16,
        stash_size=128,
    )
    assert cfg.mailbox_cap == 62
    engine = GrapevineEngine(cfg, seed=4)
    oracle = ReferenceEngine(config=cfg, rng=_random.Random(5))
    a, b = key(1), key(2)
    statuses = []
    t = NOW
    for start in range(0, 64, 16):
        reqs = [
            req(C.REQUEST_TYPE_CREATE, a, recipient=b, tag=start + j)
            for j in range(16)
        ]
        dev = engine.handle_queries(reqs, t)
        forced = [
            d.record.msg_id if d.status_code == C.STATUS_CODE_SUCCESS else None
            for d in dev
        ]
        ora = oracle.handle_batch(reqs, t, forced)
        for d, o in zip(dev, ora):
            assert d.status_code == o.status_code
            statuses.append(d.status_code)
    assert statuses.count(C.STATUS_CODE_SUCCESS) == 62
    assert statuses[:62] == [C.STATUS_CODE_SUCCESS] * 62
    assert set(statuses[62:]) == {C.STATUS_CODE_TOO_MANY_MESSAGES_FOR_RECIPIENT}
    assert engine.message_count() == oracle.message_count() == 62
    # drain in creation order (zero-id pop = oldest first)
    for start in range(0, 62, 16):
        n = min(16, 62 - start)
        reqs = [req(C.REQUEST_TYPE_DELETE, b) for _ in range(n)]
        dev = engine.handle_queries(reqs, t + 1)
        ora = oracle.handle_batch(reqs, t + 1)
        for j, (d, o) in enumerate(zip(dev, ora)):
            assert d.status_code == o.status_code == C.STATUS_CODE_SUCCESS
            assert d.record.payload == o.record.payload
            assert d.record.payload[0] == start + j  # oldest-first order
    assert engine.message_count() == oracle.message_count() == 0
