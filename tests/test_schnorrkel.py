"""sr25519 (schnorrkel) signature scheme tests.

Byte-compatibility target: reference clients sign challenges with
``sign_schnorrkel`` under context ``b"grapevine-challenge"`` (reference
README.md:193-199, types/src/lib.rs:13, Cargo.toml:62). The transcript
layer is vector-pinned in test_merlin.py; these tests pin the schnorrkel
construction on top (labels, marker bit, canonical-scalar rules) and the
scheme's integration into the verify/batch-verify seams.
"""

import os

import pytest

from grapevine_tpu.session import get_signature_scheme, ristretto, schnorrkel


def _mk(i: int):
    sk, pub = schnorrkel.keygen(bytes([i]) * 32)
    return sk, pub


def test_sign_verify_roundtrip():
    sk, pub = _mk(1)
    ctx, msg = b"grapevine-challenge", os.urandom(32)
    sig = schnorrkel.sign(sk, ctx, msg)
    assert len(sig) == 64
    assert schnorrkel.verify(pub, ctx, msg, sig)
    assert not schnorrkel.verify(pub, ctx, os.urandom(32), sig)
    assert not schnorrkel.verify(pub, b"other-context", msg, sig)
    other_pub = _mk(2)[1]
    assert not schnorrkel.verify(other_pub, ctx, msg, sig)


def test_signature_is_deterministic():
    sk, _ = _mk(3)
    msg = b"m" * 32
    assert schnorrkel.sign(sk, b"c", msg) == schnorrkel.sign(sk, b"c", msg)


def test_marker_bit_required_and_set():
    """schnorrkel Signature::{to,from}_bytes: bit 7 of byte 63 marks a
    schnorrkel signature; unmarked (ed25519-style) bytes are rejected."""
    sk, pub = _mk(4)
    msg = os.urandom(32)
    sig = schnorrkel.sign(sk, b"ctx", msg)
    assert sig[63] & 0x80
    unmarked = bytearray(sig)
    unmarked[63] &= 0x7F
    assert not schnorrkel.verify(pub, b"ctx", msg, bytes(unmarked))


def test_non_canonical_scalar_rejected():
    sk, pub = _mk(5)
    msg = os.urandom(32)
    sig = bytearray(schnorrkel.sign(sk, b"ctx", msg))
    # force s >= L while keeping the marker bit: set bits 252..254
    sig[63] |= 0x70
    assert not schnorrkel.verify(pub, b"ctx", msg, bytes(sig))


def test_malformed_inputs_never_raise():
    _, pub = _mk(6)
    for bad in (b"", b"x" * 63, b"x" * 64, b"x" * 65):
        assert schnorrkel.verify(pub, b"c", b"m", bad) is False
    sig = schnorrkel.sign(_mk(6)[0], b"c", b"m")
    assert schnorrkel.verify(b"short", b"c", b"m", sig) is False
    # non-canonical R encoding
    bad_r = bytearray(sig)
    bad_r[:32] = b"\xff" * 32
    assert schnorrkel.verify(pub, b"c", b"m", bytes(bad_r)) is False


def test_cross_scheme_rejection():
    """RFC-9496 signatures and sr25519 signatures must not cross-verify
    (different Fiat–Shamir derivations; rfc9496 sigs are unmarked)."""
    seed = bytes([7]) * 32
    sk_s, pub_s = schnorrkel.keygen(seed)
    sk_r, pub_r = ristretto.keygen(seed)
    assert pub_s == pub_r  # same key derivation, same group
    msg = os.urandom(32)
    assert not schnorrkel.verify(pub_s, b"c", msg, ristretto.sign(sk_r, b"c", msg))
    assert not ristretto.verify(pub_r, b"c", msg, schnorrkel.sign(sk_s, b"c", msg))


def test_batch_verify_all_valid_and_offender():
    ctx = b"grapevine-challenge"
    items = []
    for i in range(1, 33):
        sk, pub = _mk(i)
        msg = os.urandom(32)
        items.append((pub, ctx, msg, schnorrkel.sign(sk, ctx, msg)))
    assert schnorrkel.batch_verify(items)
    items[13] = (items[13][0], ctx, os.urandom(32), items[13][3])
    assert not schnorrkel.batch_verify(items)
    assert schnorrkel.batch_verify([])


def test_batch_matches_individual_under_pure_python():
    """Native and pure-Python paths agree (the native lib is the fast
    path; pure Python is the oracle)."""
    ctx = b"grapevine-challenge"
    items = []
    for i in range(40, 44):
        sk, pub = _mk(i)
        msg = os.urandom(32)
        items.append((pub, ctx, msg, schnorrkel.sign(sk, ctx, msg)))
    native = ristretto._native.lib
    try:
        assert schnorrkel.batch_verify(items)
        assert all(schnorrkel.verify(*it) for it in items)
        ristretto._native.lib = None
        assert schnorrkel.batch_verify(items)
        assert all(schnorrkel.verify(*it) for it in items)
    finally:
        ristretto._native.lib = native


def test_challenge_transcript_labels_golden():
    """Pin the exact challenge derivation as a golden value: any change
    to the transcript labels or framing (the compat surface vs
    schnorrkel sign.rs) shows up as a diff here."""
    k = schnorrkel._challenge_scalar(
        b"grapevine-challenge", b"\x01" * 32, b"\x02" * 32, b"\x03" * 32
    )
    assert k == 0xB4430E99729B59EBA580AB30C1D0968E4EF06EC3E803E837F1A4BDBEF47ECA


def test_scheme_registry():
    assert get_signature_scheme("schnorrkel") is schnorrkel
    assert get_signature_scheme("rfc9496") is ristretto
    with pytest.raises(ValueError):
        get_signature_scheme("ed25519")


# Substrate's well-known sr25519 dev keypairs (`subkey inspect //Alice`
# etc.) — externally published (seed, public) byte pairs this codebase
# did not generate. sp-core expands the mini secret with schnorrkel's
# ExpandMode::Ed25519, so reproducing public from seed transits SHA-512
# expansion, ed25519 clamping, divide-by-cofactor, ristretto255
# scalar*basepoint and compressed encoding against a foreign stack.
_SUBSTRATE_DEV_VECTORS = [
    (  # //Alice (SS58 5GrwvaEF5zXb26Fz9rcQpDWS57CtERHpNehXCPcNoHGKutQY)
        "e5be9a5092b81bca64be81d212e7f2f9eba183bb7a90954f7b76361f6edb5c0a",
        "d43593c715fdd31c61141abd04a99fd6822c8558854ccde39a5684e7a56da27d",
    ),
    (  # //Bob (SS58 5FHneW46xGXgs5mUiveU4sbTyGBzmstUspZC92UhjJM694ty)
        "398f0c28f98885e046333d4a41c19cee4c37368a9832c6502f6cfd182e2aef89",
        "8eaf04151687736326c9fea17e25fc5287613693c912909cb226aa4794f26a48",
    ),
]


@pytest.mark.parametrize("seed_hex,pub_hex", _SUBSTRATE_DEV_VECTORS)
def test_expand_mini_secret_substrate_vectors(seed_hex, pub_hex):
    sk, nonce = schnorrkel.expand_mini_secret(bytes.fromhex(seed_hex))
    assert len(nonce) == 32
    assert schnorrkel.public_key(sk).hex() == pub_hex


@pytest.mark.parametrize("seed_hex,pub_hex", _SUBSTRATE_DEV_VECTORS)
def test_expand_mini_secret_substrate_vectors_pure_python(seed_hex, pub_hex):
    """Same vectors with the native r255.c path disabled: pins the pure
    Python group arithmetic independently."""
    native = ristretto._native.lib
    ristretto.public_key.cache_clear()
    try:
        ristretto._native.lib = None
        sk, _ = schnorrkel.expand_mini_secret(bytes.fromhex(seed_hex))
        assert schnorrkel.public_key(sk).hex() == pub_hex
    finally:
        ristretto._native.lib = native
        ristretto.public_key.cache_clear()


def test_expanded_dev_key_signs_and_verifies():
    """The expanded //Alice secret is a working signing key here."""
    sk, _ = schnorrkel.expand_mini_secret(
        bytes.fromhex(_SUBSTRATE_DEV_VECTORS[0][0]))
    ctx, msg = b"grapevine-challenge", b"\x07" * 32
    sig = schnorrkel.sign(sk, ctx, msg)
    assert schnorrkel.verify(schnorrkel.public_key(sk), ctx, msg, sig)


def test_expand_mini_secret_rejects_bad_length():
    with pytest.raises(ValueError):
        schnorrkel.expand_mini_secret(b"\x00" * 31)
