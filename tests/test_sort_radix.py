"""xla-vs-radix sort engine equivalence: bit-identical rounds, no sort HLO.

The tentpole contract of the radix sort engine (oblivious/radix.py,
``GrapevineConfig.sort_impl="radix"``), mirroring PR 3's vphases
playbook (tests/test_vphases_scan.py):

1. responses AND final engine state bit-identical to the xla sorts —
   randomized oracle campaigns over same-key-chain-heavy mixes,
   saturation-fallback rounds, and single-op batches, reusing the
   vphases campaign harness with the sort knob as the only difference;
2. the radix ORAM round traces **zero** ``sort`` HLO ops (the xla impl
   as the positive control proving the counter sees them), and the
   radix engine round sheds every bounded-key sort — only the
   explicitly-gated wide-key sorts remain (the 256-bit recipient
   grouping and the u64 per-mailbox seq order);
3. the ``sort`` phase calibration registers under the telemetry
   registry without violating the leak policy.

The fast campaign set keeps tier-1 in budget; the full ≥200-campaign
sweep runs under ``-m slow`` (run at PR time — PERF.md Round 7). Set
$GRAPEVINE_SORT_CAMPAIGNS to override the fast count.
"""

import functools
import os

import jax
import jax.numpy as jnp
import pytest

from test_vphases_scan import (
    BASE,
    SAT_BUS,
    _campaign_plan,
    _run_campaign,
)

from grapevine_tpu.config import GrapevineConfig
from grapevine_tpu.engine.batcher import GrapevineEngine
from grapevine_tpu.engine.state import (
    EngineConfig,
    ID_WORDS,
    KEY_WORDS,
    PAYLOAD_WORDS,
    init_engine,
)
from grapevine_tpu.oram.path_oram import OramConfig, init_oram
from grapevine_tpu.oram.round import oram_round

U32 = jnp.uint32


def _mk_sort_pair(vphases):
    def mk_pair(cfg_kwargs, seed):
        kw = dict(cfg_kwargs, vphases_impl=vphases)
        xla = GrapevineEngine(
            GrapevineConfig(sort_impl="xla", **kw), seed=seed
        )
        radix = GrapevineEngine(
            GrapevineConfig(sort_impl="radix", **kw), seed=seed
        )
        return xla, radix

    return mk_pair


_FAST_N = int(os.environ.get("GRAPEVINE_SORT_CAMPAIGNS", "6"))


@pytest.mark.slow  # ~29 s of jit compiles — moved off tier-1 in the
# ISSUE-19 budget audit to offset the always-on replication tests. The
# dense-vphases campaign below and the zero-sort-HLO trace audits keep
# the sort knob covered every run; this set and the 220-campaign
# acceptance sweep both ride -m slow.
def test_randomized_sort_ab_campaigns():
    """Budget-shaped fast set under vphases "scan" (the impl whose
    group sorts the knob actually swaps): steady-state, bus-saturation
    (the _admission_slow fallback — identical under both sort impls),
    and single-op batches. Cost is ~all jit compiles, so the plan spans
    two geometries like the vphases fast set."""
    mk = _mk_sort_pair("scan")
    for i, (cfg, fill) in enumerate(_campaign_plan(_FAST_N)):
        if cfg is not BASE:
            cfg = SAT_BUS  # both saturation regimes share _admission_slow
        _run_campaign(cfg, seed=7000 + i, batch_fill=fill, mk_pair=mk)


def test_sort_ab_campaign_dense_vphases():
    """One dense-vphases campaign: dense has no group sorts, but the
    admission walk's slot grouping and the ORAM eviction/dedup sorts
    still follow the knob — the pair must stay bit-identical there too."""
    _run_campaign(BASE, seed=7900, mk_pair=_mk_sort_pair("dense"))


@pytest.mark.slow
def test_randomized_sort_ab_campaigns_full():
    """The full ≥200-campaign acceptance sweep (run at PR time; kept
    under -m slow so tier-1 stays within its budget)."""
    mk = _mk_sort_pair("scan")
    mkd = _mk_sort_pair("dense")
    for i, (cfg, fill) in enumerate(_campaign_plan(220)):
        m = mkd if i % 5 == 4 else mk  # dense pairs ride the sweep too
        _run_campaign(cfg, seed=9000 + i, batch_fill=fill, mk_pair=m)


# ----------------------------------------------------------------------
# jaxpr sort audit: the radix round traces ZERO sort HLO ops
# ----------------------------------------------------------------------


def _count_sorts(jaxpr):
    n, stack, seen = 0, [jaxpr], set()
    while stack:
        jx = stack.pop()
        if id(jx) in seen:
            continue
        seen.add(id(jx))
        for eqn in jx.eqns:
            if eqn.primitive.name == "sort":
                n += 1
            for v in eqn.params.values():
                vs = v if isinstance(v, (list, tuple)) else (v,)
                for x in vs:
                    inner = getattr(x, "jaxpr", None)
                    if inner is not None and hasattr(inner, "eqns"):
                        stack.append(inner)
                    elif hasattr(x, "eqns"):
                        stack.append(x)
    return n


def _trace_oram_round(sort_impl, b=64):
    """The batched ORAM round standalone (scan dedup + eviction under
    the knob), with a pass-through apply callback."""
    cfg = OramConfig(height=6, value_words=4, n_blocks=128)
    state = jax.eval_shape(lambda: init_oram(cfg, jax.random.PRNGKey(0)))
    u = lambda *s: jax.ShapeDtypeStruct(s, jnp.uint32)  # noqa: E731

    def run(state, idxs, nl, dl):
        return oram_round(
            cfg, state, idxs, nl, dl,
            lambda vals0, present0: ({}, vals0, present0),
            occ_impl="scan", sort_impl=sort_impl,
        )

    return jax.make_jaxpr(run)(state, u(b), u(b), u(b)).jaxpr


def test_radix_oram_round_traces_zero_sort_hlo():
    assert _count_sorts(_trace_oram_round("radix")) == 0


def test_xla_oram_round_audit_positive_control():
    """The xla round DOES trace sorts — proving the counter sees the
    ops the radix test asserts away."""
    assert _count_sorts(_trace_oram_round("xla")) > 0


def _trace_engine_jaxpr(sort_impl, b=32):
    from grapevine_tpu.engine.round_step import engine_round_step

    cfg = GrapevineConfig(
        max_messages=1 << 10,
        max_recipients=1 << 6,
        mailbox_cap=4,
        batch_size=b,
        bucket_cipher_rounds=0,
        stash_size=128,
        vphases_impl="scan",
        sort_impl=sort_impl,
    )
    ecfg = EngineConfig.from_config(cfg)
    state = jax.eval_shape(lambda: init_engine(ecfg, 0))
    u32 = jnp.uint32
    batch = {
        "req_type": jax.ShapeDtypeStruct((b,), u32),
        "auth": jax.ShapeDtypeStruct((b, KEY_WORDS), u32),
        "msg_id": jax.ShapeDtypeStruct((b, ID_WORDS), u32),
        "recipient": jax.ShapeDtypeStruct((b, KEY_WORDS), u32),
        "payload": jax.ShapeDtypeStruct((b, PAYLOAD_WORDS), u32),
        "now": jax.ShapeDtypeStruct((), u32),
        "now_hi": jax.ShapeDtypeStruct((), u32),
    }
    return jax.make_jaxpr(functools.partial(engine_round_step, ecfg))(
        state, batch
    ).jaxpr


def test_radix_engine_round_sheds_bounded_sorts():
    """Whole engine round: radix removes every bounded-key sort; the
    residue is exactly the explicitly-gated wide-key sites (256-bit
    recipient grouping, u64 seq entry ordering) — strictly fewer sorts
    than xla and a fixed small count, so a new unbounded sort sneaking
    into the round fails CI here."""
    n_xla = _count_sorts(_trace_engine_jaxpr("xla"))
    n_radix = _count_sorts(_trace_engine_jaxpr("radix"))
    assert n_radix < n_xla, (n_radix, n_xla)
    assert n_radix <= 5, (
        f"radix engine round traces {n_radix} sort ops — more than the "
        f"gated wide-key residue; a bounded-key sort escaped the knob"
    )


# ----------------------------------------------------------------------
# obs: the sort phase calibration registers cleanly
# ----------------------------------------------------------------------


def test_sort_phase_calibration_registers():
    eng = GrapevineEngine(
        GrapevineConfig(
            max_messages=64, max_recipients=8, mailbox_cap=4,
            batch_size=4, bucket_cipher_rounds=0, vphases_impl="scan",
            sort_impl="radix",
        )
    )
    dt = eng.calibrate_sort_phase(reps=2)
    assert dt > 0
    snap = eng.metrics.registry.snapshot()
    key = "grapevine_phase_seconds{phase=sort}_count"
    assert snap.get(key, 0) >= 1, sorted(
        k for k in snap if "phase" in k
    )[:10]
    eng.metrics.registry.audit()  # leak policy still holds
