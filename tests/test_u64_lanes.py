"""u64 timestamp/seq lanes (VERDICT r3 weak #7: 2106 rollover + 2^32
creates-per-lifetime were conscious-but-narrow u32 bounds; both are now
two u32 lanes end to end — device layouts, responses, expiry)."""

import numpy as np
import pytest

from grapevine_tpu.config import GrapevineConfig
from grapevine_tpu.engine.batcher import GrapevineEngine
from grapevine_tpu.oblivious.primitives import (
    lex_argsort,
    u64_add_u32,
    u64_sub,
)
from grapevine_tpu.wire import constants as C
from grapevine_tpu.wire.records import QueryRequest, RequestRecord

#: a post-2106 clock: 2**32 + a bit (u32 seconds would have wrapped)
FUTURE = (1 << 32) + 12_345


def _mk(commit="phase"):
    cfg = GrapevineConfig(
        bucket_cipher_rounds=0,
        max_messages=128,
        max_recipients=16,
        mailbox_cap=4,
        batch_size=4,
        commit=commit,
        mailbox_choices=1 if commit == "op" else None,
    )
    return GrapevineEngine(cfg, seed=4)


def req(rt, auth, msg_id=C.ZERO_MSG_ID, recipient=C.ZERO_PUBKEY, tag=0):
    return QueryRequest(
        request_type=rt,
        auth_identity=auth,
        record=RequestRecord(
            msg_id=msg_id,
            recipient=recipient,
            payload=bytes([tag]) * C.PAYLOAD_SIZE,
        ),
    )


def _post_2106_round_trip(commits):
    for commit in commits:
        e = _mk(commit)
        a, b = b"\x11" * 32, b"\x22" * 32
        r = e.handle_queries([req(1, a, recipient=b, tag=7)], FUTURE)[0]
        assert r.status_code == C.STATUS_CODE_SUCCESS
        assert r.record.timestamp == FUTURE, commit
        r2 = e.handle_queries([req(2, b)], FUTURE + 5)[0]
        assert r2.status_code == C.STATUS_CODE_SUCCESS
        assert r2.record.timestamp == FUTURE  # stored ts, not the clock
        # UPDATE refreshes to the new post-2106 clock
        r3 = e.handle_queries(
            [req(3, a, msg_id=r.record.msg_id, recipient=b, tag=8)],
            FUTURE + 9,
        )[0]
        assert r3.status_code == C.STATUS_CODE_SUCCESS
        r4 = e.handle_queries([req(2, b)], FUTURE + 10)[0]
        assert r4.record.timestamp == FUTURE + 9, commit


def test_post_2106_timestamps_round_trip():
    """CREATE at a post-2106 clock returns the full u64 timestamp, READ
    echoes it, and the wire codec carries it (timestamp is u64 on the
    wire, reference README.md:135). Always-on on the production phase
    engine; the op-major arm rides ``-m slow`` below (PR-10 tier-1
    re-budget: the op engine's compile was half of this test's ~25 s,
    and the u32-boundary semantics both engines share stay covered by
    the sibling always-on tests)."""
    _post_2106_round_trip(("phase",))


@pytest.mark.slow  # the op-major engine compile (~12 s) — breadth arm
def test_post_2106_timestamps_round_trip_op_commit():
    _post_2106_round_trip(("op",))


def test_expiry_across_the_u32_boundary():
    """Records stamped below 2^32 must expire under a sweep clock above
    it (the exact case a u32 clock breaks: now wraps to a tiny value and
    nothing ever ages)."""
    e = _mk()
    a, b = b"\x11" * 32, b"\x22" * 32
    t0 = (1 << 32) - 50  # pre-boundary stamp
    r = e.handle_queries([req(1, a, recipient=b)], t0)[0]
    assert r.status_code == C.STATUS_CODE_SUCCESS
    # 100 s later the clock has crossed 2^32; period 60 ⇒ expired
    evicted = e.expire(t0 + 100, period=60)
    assert evicted == 1
    r2 = e.handle_queries([req(2, b)], t0 + 101)[0]
    assert r2.status_code == C.STATUS_CODE_NOT_FOUND
    # and a fresh record at the post-boundary clock does NOT expire
    r3 = e.handle_queries([req(1, a, recipient=b)], t0 + 101)[0]
    assert r3.status_code == C.STATUS_CODE_SUCCESS
    assert e.expire(t0 + 102, period=60) == 0


def test_mailbox_order_across_wrapped_seq():
    """Pop-oldest ordering is by the full 64-bit seq: entries created
    after the low lane wraps (seq_hi=1, small seq_lo) must pop AFTER
    pre-wrap entries (seq_hi=0, huge seq_lo) — a 32-bit comparison would
    invert them."""
    e = _mk()
    # force the engine's seq counter near the u32 boundary
    st = e.state
    e.state = st._replace(seq=np.asarray([0xFFFFFFFE, 0], np.uint32))
    a, b = b"\x11" * 32, b"\x22" * 32
    r1 = e.handle_queries([req(1, a, recipient=b, tag=1)], 1000)[0]
    assert r1.status_code == C.STATUS_CODE_SUCCESS
    # seq has advanced past the wrap (hi lane = 1 now)
    assert int(np.asarray(e.state.seq)[1]) == 1
    r2 = e.handle_queries([req(1, a, recipient=b, tag=2)], 1001)[0]
    assert r2.status_code == C.STATUS_CODE_SUCCESS
    pop1 = e.handle_queries([req(4, b)], 1002)[0]  # zero-id delete = pop
    assert pop1.record.payload[0] == 1, "oldest (pre-wrap) must pop first"
    pop2 = e.handle_queries([req(4, b)], 1003)[0]
    assert pop2.record.payload[0] == 2


def test_u64_lane_helpers():
    import jax.numpy as jnp

    lo, hi = u64_add_u32(
        jnp.uint32(0xFFFFFFFF), jnp.uint32(7), jnp.uint32(1)
    )
    assert (int(lo), int(hi)) == (0, 8)
    d_lo, d_hi = u64_sub(
        jnp.uint32(2), jnp.uint32(5), jnp.uint32(0xFFFFFFFF), jnp.uint32(4)
    )
    assert (int(d_lo), int(d_hi)) == (3, 0)
    # lexicographic sort: (hi, lo) pairs
    lo_a = jnp.asarray([5, 1, 9], jnp.uint32)
    hi_a = jnp.asarray([0, 2, 0], jnp.uint32)
    order = [int(x) for x in lex_argsort(lo_a, hi_a)]
    assert order == [0, 2, 1]  # (0,5) < (0,9) < (2,1)
