"""Mailbox hash-table load analysis (the single-choice table's bargain).

The mailbox tier is a keyed single-choice hash table of K-mailbox
buckets (engine/state.py:mb_bucket_hash) run at low load instead of a
relocating cuckoo scheme (reference README.md:78-80 traces its 62-cap to
mc-oblivious-map's bucketed cuckoo). The bargain, quantified in
config.py: a recipient whose bucket is full gets TOO_MANY_RECIPIENTS
*early* (before max_recipients is reached) with probability governed by
the Poisson tail P(X ≥ K+1), λ = K · load · fill. These tests (a) force
that path deterministically-in-distribution with a load-1.0 config and
assert the engine stays consistent through it, and (b) measure the
early-failure rate at the default load and check it against the Poisson
bound the docs claim.
"""

import random

import numpy as np

from grapevine_tpu.config import GrapevineConfig
from grapevine_tpu.engine.batcher import GrapevineEngine
from grapevine_tpu.wire import constants as C
from grapevine_tpu.wire.records import QueryRequest, RequestRecord

NOW = 1_700_000_000


def key(n: int) -> bytes:
    return n.to_bytes(4, "little") + b"\x02" * 28


def req(rt, auth, msg_id=C.ZERO_MSG_ID, recipient=C.ZERO_PUBKEY, tag=0):
    return QueryRequest(
        request_type=rt,
        auth_identity=auth,
        auth_signature=b"\x01" * C.SIGNATURE_SIZE,
        record=RequestRecord(
            msg_id=msg_id,
            recipient=recipient,
            payload=bytes([tag & 0xFF]) * C.PAYLOAD_SIZE,
        ),
    )


def test_bucket_overflow_path_is_consistent():
    """At load 1.0 (table slots == max_recipients), filling the table
    with distinct recipients must hit the early-TOO_MANY_RECIPIENTS path
    with overwhelming probability (64 balls, 16 buckets, K=4), and the
    engine must stay consistent: every SUCCESS is drainable, every
    early failure left no trace, and total placements equal the live
    recipient count."""
    cfg = GrapevineConfig(bucket_cipher_rounds=0, 
        max_messages=256,
        max_recipients=64,
        mailbox_cap=4,
        batch_size=8,
        mailbox_load=1.0,
    )
    engine = GrapevineEngine(cfg, seed=13)
    sender = key(9999)
    statuses = {}
    for i in range(64):
        r = engine.handle_queries(
            [req(C.REQUEST_TYPE_CREATE, sender, recipient=key(i), tag=i)], NOW
        )[0]
        statuses[i] = r.status_code
    ok = [i for i, s in statuses.items() if s == C.STATUS_CODE_SUCCESS]
    early = [i for i, s in statuses.items() if s == C.STATUS_CODE_TOO_MANY_RECIPIENTS]
    assert set(statuses.values()) <= {
        C.STATUS_CODE_SUCCESS,
        C.STATUS_CODE_TOO_MANY_RECIPIENTS,
    }
    # P(no bucket overflows | 64 uniform balls, 16 buckets of 4) ≈ 0 —
    # a perfectly even spread is the only overflow-free outcome
    assert early, "expected at least one early bucket-overflow failure"
    assert engine.recipient_count() == len(ok)
    assert engine.message_count() == len(ok)
    # successes are drainable; early-failed recipients read NOT_FOUND
    for i in ok[:8]:
        r = engine.handle_queries([req(C.REQUEST_TYPE_READ, key(i))], NOW + 1)[0]
        assert r.status_code == C.STATUS_CODE_SUCCESS, f"recipient {i}"
        assert r.record.payload[0] == i
    for i in early[:4]:
        r = engine.handle_queries([req(C.REQUEST_TYPE_READ, key(i))], NOW + 1)[0]
        assert r.status_code == C.STATUS_CODE_NOT_FOUND


def test_default_load_early_failure_rate_within_poisson_bound():
    """At the default load (0.125) and HALF recipient fill, early
    failures must be at least as rare as the documented Poisson model
    says (λ = K·load·fill = 0.25 ⇒ P(X≥5) ≈ 6.6e-6 per bucket).
    Empirical check across seeds at small scale: zero early failures
    expected in ~10 fills of a 64-recipient table (expected count
    ≈ 10 · M · 6.6e-6 ≈ 0.008 at M=128)."""
    rng = random.Random(7)
    total_early = 0
    for seed in range(10):
        cfg = GrapevineConfig(bucket_cipher_rounds=0, 
            max_messages=256,
            max_recipients=64,
            mailbox_cap=4,
            batch_size=8,
        )
        engine = GrapevineEngine(cfg, seed=seed)
        sender = key(12345)
        for i in range(32):  # 50% fill
            r = engine.handle_queries(
                [req(C.REQUEST_TYPE_CREATE, sender, recipient=key(rng.randrange(1 << 20)))],
                NOW,
            )[0]
            if r.status_code == C.STATUS_CODE_TOO_MANY_RECIPIENTS:
                total_early += 1
    # Poisson expectation ~0.008; even 2 would mean the model is off by
    # orders of magnitude
    assert total_early <= 1, f"early failures at default load: {total_early}"


def test_memory_overhead_documented_ratio():
    """The documented cost of the single-choice table: mailbox-tier HBM
    per recipient = (1/load) × mailbox bytes. Assert the configured
    geometry actually matches the docs' 8× figure at the default load."""
    from grapevine_tpu.engine.state import EngineConfig

    cfg = GrapevineConfig(bucket_cipher_rounds=0, max_messages=1 << 12, max_recipients=1 << 8)
    ecfg = EngineConfig.from_config(cfg)
    slots = ecfg.mb_table_buckets * ecfg.mb_slots
    assert slots == cfg.max_recipients / cfg.mailbox_load  # 8× at 0.125
