"""Mailbox hash-table load analysis (two-choice table's bargain).

The mailbox tier is a keyed TWO-CHOICE hash table of K-mailbox buckets
(engine/state.py:mb_bucket_hash with per-choice salts; claims take the
emptier candidate at round start) approximating the reference's
relocating bucketed cuckoo (README.md:78-80) without eviction chains.
A recipient gets TOO_MANY_RECIPIENTS *early* (before max_recipients)
only when BOTH candidates are full — simulated ≈0 failures through 75%
fill at the default load 0.5 (config.py). These tests (a) force the
overflow path with a load-1.0 config and assert the engine stays
consistent through it, (b) measure the early-failure rate at default
load, and (c) keep the legacy single-choice path (mailbox_choices=1,
the op-major oracle engine's scheme) covered.
"""

import random


from grapevine_tpu.config import GrapevineConfig
from grapevine_tpu.engine.batcher import GrapevineEngine
from grapevine_tpu.wire import constants as C
from grapevine_tpu.wire.records import QueryRequest, RequestRecord

NOW = 1_700_000_000


def key(n: int) -> bytes:
    return n.to_bytes(4, "little") + b"\x02" * 28


def req(rt, auth, msg_id=C.ZERO_MSG_ID, recipient=C.ZERO_PUBKEY, tag=0):
    return QueryRequest(
        request_type=rt,
        auth_identity=auth,
        auth_signature=b"\x01" * C.SIGNATURE_SIZE,
        record=RequestRecord(
            msg_id=msg_id,
            recipient=recipient,
            payload=bytes([tag & 0xFF]) * C.PAYLOAD_SIZE,
        ),
    )


def test_bucket_overflow_path_is_consistent():
    """At load 1.0 (table slots == max_recipients), filling the table
    with distinct recipients must hit the early-TOO_MANY_RECIPIENTS path
    with overwhelming probability (64 balls, 16 buckets of 4 — even
    two-choice placement fails ~4.8 times on average; P(none) < 1/400
    by simulation), and the
    engine must stay consistent: every SUCCESS is drainable, every
    early failure left no trace, and total placements equal the live
    recipient count."""
    cfg = GrapevineConfig(bucket_cipher_rounds=0, 
        max_messages=256,
        max_recipients=64,
        mailbox_cap=4,
        batch_size=8,
        mailbox_load=1.0,
    )
    engine = GrapevineEngine(cfg, seed=13)
    sender = key(9999)
    statuses = {}
    for i in range(64):
        r = engine.handle_queries(
            [req(C.REQUEST_TYPE_CREATE, sender, recipient=key(i), tag=i)], NOW
        )[0]
        statuses[i] = r.status_code
    ok = [i for i, s in statuses.items() if s == C.STATUS_CODE_SUCCESS]
    early = [i for i, s in statuses.items() if s == C.STATUS_CODE_TOO_MANY_RECIPIENTS]
    assert set(statuses.values()) <= {
        C.STATUS_CODE_SUCCESS,
        C.STATUS_CODE_TOO_MANY_RECIPIENTS,
    }
    # P(no bucket overflows | 64 uniform balls, 16 buckets of 4) ≈ 0 —
    # a perfectly even spread is the only overflow-free outcome
    assert early, "expected at least one early bucket-overflow failure"
    assert engine.recipient_count() == len(ok)
    assert engine.message_count() == len(ok)
    # successes are drainable; early-failed recipients read NOT_FOUND
    for i in ok[:8]:
        r = engine.handle_queries([req(C.REQUEST_TYPE_READ, key(i))], NOW + 1)[0]
        assert r.status_code == C.STATUS_CODE_SUCCESS, f"recipient {i}"
        assert r.record.payload[0] == i
    for i in early[:4]:
        r = engine.handle_queries([req(C.REQUEST_TYPE_READ, key(i))], NOW + 1)[0]
        assert r.status_code == C.STATUS_CODE_NOT_FOUND


def test_default_load_early_failure_rate_within_documented_bound():
    """At the default two-choice load (0.5) and HALF recipient fill,
    early failures need BOTH candidates full — simulated ≈0 through
    75% fill (config.py). Empirical check across seeds at small scale:
    at most one early failure in 10 half-fills."""
    rng = random.Random(7)
    total_early = 0
    for seed in range(10):
        cfg = GrapevineConfig(bucket_cipher_rounds=0, 
            max_messages=256,
            max_recipients=64,
            mailbox_cap=4,
            batch_size=8,
        )
        engine = GrapevineEngine(cfg, seed=seed)
        sender = key(12345)
        for i in range(32):  # 50% fill
            r = engine.handle_queries(
                [req(C.REQUEST_TYPE_CREATE, sender, recipient=key(rng.randrange(1 << 20)))],
                NOW,
            )[0]
            if r.status_code == C.STATUS_CODE_TOO_MANY_RECIPIENTS:
                total_early += 1
    # Poisson expectation ~0.008; even 2 would mean the model is off by
    # orders of magnitude
    assert total_early <= 1, f"early failures at default load: {total_early}"


def test_memory_overhead_documented_ratio():
    """The documented cost: mailbox-tier slots per recipient = 1/load —
    2× at the two-choice default (0.5), 8× at the single-choice legacy
    load (0.125)."""
    from grapevine_tpu.engine.state import EngineConfig

    cfg = GrapevineConfig(bucket_cipher_rounds=0, max_messages=1 << 12, max_recipients=1 << 8)
    ecfg = EngineConfig.from_config(cfg)
    assert ecfg.mb_choices == 2
    slots = ecfg.mb_table_buckets * ecfg.mb_slots
    assert slots == cfg.max_recipients / cfg.resolved_mailbox_load  # 2×
    legacy = GrapevineConfig(
        bucket_cipher_rounds=0, max_messages=1 << 12,
        max_recipients=1 << 8, mailbox_choices=1,
    )
    ecfg1 = EngineConfig.from_config(legacy)
    assert ecfg1.mb_choices == 1
    slots1 = ecfg1.mb_table_buckets * ecfg1.mb_slots
    assert slots1 == legacy.max_recipients / legacy.resolved_mailbox_load  # 8×


def test_single_choice_legacy_path_still_serves():
    """mailbox_choices=1 (required by the op-major oracle engine) keeps
    full CRUD semantics."""
    cfg = GrapevineConfig(bucket_cipher_rounds=0, 
        max_messages=128,
        max_recipients=32,
        mailbox_cap=4,
        batch_size=4,
        mailbox_choices=1,
    )
    engine = GrapevineEngine(cfg, seed=5)
    sender = key(777)
    r = engine.handle_queries(
        [req(C.REQUEST_TYPE_CREATE, sender, recipient=key(1), tag=42)], NOW
    )[0]
    assert r.status_code == C.STATUS_CODE_SUCCESS
    r2 = engine.handle_queries([req(C.REQUEST_TYPE_READ, key(1))], NOW)[0]
    assert r2.status_code == C.STATUS_CODE_SUCCESS
    assert r2.record.payload[0] == 42
    r3 = engine.handle_queries([req(C.REQUEST_TYPE_DELETE, key(1))], NOW)[0]
    assert r3.status_code == C.STATUS_CODE_SUCCESS
    assert engine.message_count() == 0


def test_two_choice_spreads_hot_bucket():
    """Direct two-choice-vs-single-choice comparison at identical tight
    geometry (16 buckets of 4, filled to 75% of slots with uniform
    recipients): single-choice overflows ~4.9 buckets per fill in
    expectation while two-choice overflows ~0.3 — so across 3 seeded
    fills single-choice must see strictly more early failures (and at
    least a few), proving the emptier-candidate rule actually engages
    (a regression collapsing both hashes to one candidate fails this)."""
    def fill(choices: int, seed: int) -> int:
        cfg = GrapevineConfig(bucket_cipher_rounds=0, 
            max_messages=256,
            max_recipients=64,
            mailbox_cap=4,
            batch_size=8,
            mailbox_choices=choices,
            mailbox_load=1.0,  # 16 buckets x 4 slots for 64 recipients
        )
        engine = GrapevineEngine(cfg, seed=seed)
        rng = random.Random(100 + seed)
        sender = key(4242)
        early = 0
        for _ in range(48):  # 75% of table slots
            r = engine.handle_queries(
                [req(C.REQUEST_TYPE_CREATE, sender,
                     recipient=key(rng.randrange(1 << 20)))], NOW,
            )[0]
            early += r.status_code == C.STATUS_CODE_TOO_MANY_RECIPIENTS
        return early

    single = sum(fill(1, s) for s in (0, 1, 2))
    double = sum(fill(2, s) for s in (0, 1, 2))
    assert single >= 3, f"single-choice control unexpectedly clean ({single})"
    assert double < single, (
        f"two-choice ({double}) not better than single-choice ({single})"
    )
