"""Wire-discipline tests, mirroring the reference's conformance suite.

The reference proves (a) its two type stacks round-trip byte-for-byte and
(b) every random request/response encodes at the identical byte length —
the serialization-layer obliviousness property
(reference api/tests/grapevine_types.rs:13-55). Here the two stacks are the
fixed-layout channel codec (wire/records.py) and the protobuf-wire codec
(wire/protowire.py).
"""

import pytest

from grapevine_tpu.testing import fixtures as fx
from grapevine_tpu.wire import constants as C
from grapevine_tpu.wire import protowire as pw
from grapevine_tpu.wire.records import QueryRequest, QueryResponse, Record, RequestRecord


def test_query_request_round_trip_fixed():
    fx.run_with_several_seeds(
        lambda rng: _assert_rt_fixed(fx.random_query_request(rng))
    )


def _assert_rt_fixed(req: QueryRequest):
    assert QueryRequest.unpack(req.pack()) == req


def test_query_response_round_trip_fixed():
    def check(rng):
        resp = fx.random_query_response(rng)
        assert QueryResponse.unpack(resp.pack()) == resp

    fx.run_with_several_seeds(check)


def test_query_request_round_trip_protowire():
    """The two codec stacks agree on every random instance."""

    def check(rng):
        req = fx.random_query_request(rng)
        assert pw.decode_query_request(pw.encode_query_request(req)) == req
        # cross-stack: fixed-layout round trip composed with protowire round
        # trip yields the same object
        assert pw.decode_query_request(
            pw.encode_query_request(QueryRequest.unpack(req.pack()))
        ) == req

    fx.run_with_several_seeds(check)


def test_query_response_round_trip_protowire():
    def check(rng):
        resp = fx.random_query_response(rng)
        assert pw.decode_query_response(pw.encode_query_response(resp)) == resp

    fx.run_with_several_seeds(check)


def test_query_request_constant_size():
    """Every valid request is byte-identical in length on both codecs."""
    rng = fx.get_seeded_rng()
    expected_fixed = len(fx.random_query_request(rng).pack())
    rng = fx.get_seeded_rng()
    expected_proto = len(pw.encode_query_request(fx.random_query_request(rng)))

    def check(rng):
        req = fx.random_query_request(rng)
        assert len(req.pack()) == expected_fixed == C.QUERY_REQUEST_WIRE_SIZE
        assert len(pw.encode_query_request(req)) == expected_proto

    fx.run_with_several_seeds(check, n_seeds=16)


def test_query_response_constant_size():
    rng = fx.get_seeded_rng()
    expected_fixed = len(fx.random_query_response(rng).pack())
    rng = fx.get_seeded_rng()
    expected_proto = len(pw.encode_query_response(fx.random_query_response(rng)))

    def check(rng):
        resp = fx.random_query_response(rng)
        assert len(resp.pack()) == expected_fixed == C.QUERY_RESPONSE_WIRE_SIZE
        assert len(pw.encode_query_response(resp)) == expected_proto

    fx.run_with_several_seeds(check, n_seeds=16)


def test_zero_payload_still_constant_size():
    """All-zero (but full-length) byte fields must not shrink the encoding."""
    rng = fx.get_seeded_rng()
    req = fx.random_query_request(rng)
    req.record.payload = b"\x00" * C.PAYLOAD_SIZE
    req.record.msg_id = C.ZERO_MSG_ID
    assert len(pw.encode_query_request(req)) == len(
        pw.encode_query_request(fx.random_query_request(fx.get_seeded_rng(3)))
    )
    assert len(req.pack()) == C.QUERY_REQUEST_WIRE_SIZE


def test_request_type_enum_values():
    """Constants match the reference RequestType enum (grapevine.proto:44-55)."""
    assert C.REQUEST_TYPE_INVALID == 0
    assert C.REQUEST_TYPE_CREATE == 1
    assert C.REQUEST_TYPE_READ == 2
    assert C.REQUEST_TYPE_UPDATE == 3
    assert C.REQUEST_TYPE_DELETE == 4


def test_status_code_enum_values():
    """Constants match the reference StatusCode enum (grapevine.proto:178-197)."""
    assert C.STATUS_CODE_INVALID == 0
    assert C.STATUS_CODE_SUCCESS == 1
    assert C.STATUS_CODE_NOT_FOUND == 2
    assert C.STATUS_CODE_MESSAGE_ID_ALREADY_IN_USE == 3
    assert C.STATUS_CODE_INVALID_RECIPIENT == 4
    assert C.STATUS_CODE_TOO_MANY_MESSAGES_FOR_RECIPIENT == 5
    assert C.STATUS_CODE_TOO_MANY_RECIPIENTS == 6
    assert C.STATUS_CODE_TOO_MANY_MESSAGES == 7
    assert C.STATUS_CODE_INTERNAL_ERROR == 8


def test_record_geometry():
    """1024-byte record layout (reference README.md:132-136)."""
    assert C.RECORD_SIZE == 1024
    assert C.PAYLOAD_SIZE == 936
    assert C.MAILBOX_CAP == 62
    r = fx.random_record(fx.get_seeded_rng())
    packed = r.pack()
    assert len(packed) == 1024
    assert packed[:16] == r.msg_id
    assert packed[16:48] == r.sender
    assert packed[48:80] == r.recipient
    assert packed[88:] == r.payload


def test_validation_rejects_bad_lengths():
    with pytest.raises(ValueError):
        RequestRecord(msg_id=b"\x00" * 15).validate()
    with pytest.raises(ValueError):
        Record(payload=b"\x00" * 935).validate()
    with pytest.raises(ValueError):
        QueryRequest(auth_signature=b"\x00" * 63).validate()


def test_outer_envelope_round_trip():
    m = pw.EnvelopeMessage(aad=b"a", channel_id=b"chan", data=b"\x01" * 100)
    assert pw.decode_envelope(pw.encode_envelope(m)) == m
    a = pw.AuthMessageWithChallengeSeed(
        auth_message=pw.AuthMessage(data=b"handshake"),
        encrypted_challenge_seed=b"\x02" * 48,
    )
    assert pw.decode_auth_with_seed(pw.encode_auth_with_seed(a)) == a
