"""Batch-level metrics (engine/metrics.py): counters, occupancy, p99."""


from grapevine_tpu.config import GrapevineConfig
from grapevine_tpu.engine.batcher import GrapevineEngine
from grapevine_tpu.engine.metrics import EngineMetrics
from grapevine_tpu.wire import constants as C
from grapevine_tpu.wire.records import QueryRequest, RequestRecord

NOW = 1_700_000_000


def _req(rt, auth, recipient=C.ZERO_PUBKEY):
    return QueryRequest(
        request_type=rt,
        auth_identity=auth,
        auth_signature=b"\x01" * C.SIGNATURE_SIZE,
        record=RequestRecord(
            msg_id=C.ZERO_MSG_ID,
            recipient=recipient,
            payload=b"\x07" * C.PAYLOAD_SIZE,
        ),
    )


def test_metrics_ring_and_percentiles():
    m = EngineMetrics(ring_size=8)
    for i in range(20):  # wraps the ring
        m.record_round(n_real=3, batch_size=4, seconds=0.001 * (i + 1))
    m.record_sweep(5)
    m.record_auth(failures=2)
    m.observe_stash(17)
    m.observe_stash(9)  # high-water keeps the max
    s = m.snapshot()
    assert s["rounds"] == 20
    assert s["real_ops"] == 60
    assert s["batch_occupancy"] == 0.75
    assert s["sweeps"] == 1 and s["evicted"] == 5
    assert s["batch_verifies"] == 1 and s["auth_failures"] == 2
    assert s["stash_high_water"] == 17
    # ring holds the last 8 rounds (13..20 ms)
    assert 12.9 < s["round_ms_p50"] < 17.1
    assert s["round_ms_p99"] <= 20.1


def test_concurrent_recording_is_lossless():
    """Hammer every recording entry point from N threads: counter totals
    must be exact and the ring consistent — record_round runs outside
    the engine lock in production (PendingRound.resolve), so the
    internal locks are the only thing between us and lost samples."""
    import threading

    m = EngineMetrics(ring_size=64)
    n_threads, per = 8, 250
    barrier = threading.Barrier(n_threads)

    def hammer(tid):
        barrier.wait()  # maximize interleaving
        for i in range(per):
            m.record_round(n_real=1, batch_size=2, seconds=0.002)
            m.record_auth(failures=1)
            m.observe_stash(i % 50)
            m.observe_phase("verify", 0.0005)
            m.observe_queue_depth(i % 7)
            m.record_sweep(2)

    threads = [
        threading.Thread(target=hammer, args=(t,)) for t in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    total = n_threads * per
    s = m.snapshot()
    assert s["rounds"] == total
    assert s["real_ops"] == total
    assert s["batch_occupancy"] == 0.5
    assert s["batch_verifies"] == total and s["auth_failures"] == total
    assert s["sweeps"] == total and s["evicted"] == 2 * total
    assert s["stash_high_water"] == 49
    assert s["queue_depth_high_water"] == 6
    # ring integrity: every committed sample is a real write (all equal
    # here, so any interleaving must yield exactly 2ms at any quantile)
    assert s["round_ms_p50"] == 2.0 and s["round_ms_p99"] == 2.0
    # histogram totals are exact too
    assert s["grapevine_phase_seconds{phase=verify}_count"] == total
    assert s["grapevine_stash_occupancy_count"] == total
    # and the hammered registry still audits clean
    assert m.registry.audit()["ok"]


def test_small_sample_percentiles_do_not_underreport():
    """Satellite fix: linear interpolation under-reported p99 on a
    partially-filled ring (at 20 rounds it blended the 19th and 20th
    samples). method="higher" returns a real order statistic."""
    m = EngineMetrics(ring_size=1024)
    for i in range(20):
        m.record_round(n_real=1, batch_size=1, seconds=0.001 * (i + 1))
    s = m.snapshot()
    # p99 of 20 samples must be the largest sample, not an interpolation
    assert s["round_ms_p99"] == 20.0
    assert s["round_ms_p50"] == 11.0  # ceil order statistic, never below


def test_engine_health_includes_batch_metrics():
    cfg = GrapevineConfig(
        bucket_cipher_rounds=0,
        max_messages=64,
        max_recipients=16,
        mailbox_cap=4,
        batch_size=4,
        stash_size=96,
        expiry_period=10,
    )
    e = GrapevineEngine(cfg, seed=1)
    a, b = bytes([1]) * 32, bytes([2]) * 32
    resps = e.handle_queries(
        [_req(C.REQUEST_TYPE_CREATE, a, recipient=b)] * 2, NOW
    )
    assert all(r.status_code == C.STATUS_CODE_SUCCESS for r in resps)
    e.expire(NOW + 100)
    h = e.health()
    assert h["rounds"] == 1
    assert h["real_ops"] == 2
    assert h["batch_occupancy"] == 0.5  # 2 real ops in a 4-slot round
    assert h["sweeps"] == 1 and h["evicted"] == 2
    assert h["round_ms_p99"] > 0
    # two live records were inserted then expired
    assert h["messages"] == 0
    assert h["stash_high_water"] >= 0
    assert h["stash_overflow"] == 0
