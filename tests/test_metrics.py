"""Batch-level metrics (engine/metrics.py): counters, occupancy, p99."""

import numpy as np

from grapevine_tpu.config import GrapevineConfig
from grapevine_tpu.engine.batcher import GrapevineEngine
from grapevine_tpu.engine.metrics import EngineMetrics
from grapevine_tpu.wire import constants as C
from grapevine_tpu.wire.records import QueryRequest, RequestRecord

NOW = 1_700_000_000


def _req(rt, auth, recipient=C.ZERO_PUBKEY):
    return QueryRequest(
        request_type=rt,
        auth_identity=auth,
        auth_signature=b"\x01" * C.SIGNATURE_SIZE,
        record=RequestRecord(
            msg_id=C.ZERO_MSG_ID,
            recipient=recipient,
            payload=b"\x07" * C.PAYLOAD_SIZE,
        ),
    )


def test_metrics_ring_and_percentiles():
    m = EngineMetrics(ring_size=8)
    for i in range(20):  # wraps the ring
        m.record_round(n_real=3, batch_size=4, seconds=0.001 * (i + 1))
    m.record_sweep(5)
    m.record_auth(failures=2)
    m.observe_stash(17)
    m.observe_stash(9)  # high-water keeps the max
    s = m.snapshot()
    assert s["rounds"] == 20
    assert s["real_ops"] == 60
    assert s["batch_occupancy"] == 0.75
    assert s["sweeps"] == 1 and s["evicted"] == 5
    assert s["batch_verifies"] == 1 and s["auth_failures"] == 2
    assert s["stash_high_water"] == 17
    # ring holds the last 8 rounds (13..20 ms)
    assert 12.9 < s["round_ms_p50"] < 17.1
    assert s["round_ms_p99"] <= 20.1


def test_engine_health_includes_batch_metrics():
    cfg = GrapevineConfig(
        bucket_cipher_rounds=0,
        max_messages=64,
        max_recipients=16,
        mailbox_cap=4,
        batch_size=4,
        stash_size=96,
        expiry_period=10,
    )
    e = GrapevineEngine(cfg, seed=1)
    a, b = bytes([1]) * 32, bytes([2]) * 32
    resps = e.handle_queries(
        [_req(C.REQUEST_TYPE_CREATE, a, recipient=b)] * 2, NOW
    )
    assert all(r.status_code == C.STATUS_CODE_SUCCESS for r in resps)
    e.expire(NOW + 100)
    h = e.health()
    assert h["rounds"] == 1
    assert h["real_ops"] == 2
    assert h["batch_occupancy"] == 0.5  # 2 real ops in a 4-slot round
    assert h["sweeps"] == 1 and h["evicted"] == 2
    assert h["round_ms_p99"] > 0
    # two live records were inserted then expired
    assert h["messages"] == 0
    assert h["stash_high_water"] >= 0
    assert h["stash_overflow"] == 0
