"""ISSUE 12: the unified obliviousness analyzer + host lock lint.

Four suites:

1. taint propagation units — one tiny traced program per jax primitive
   class (elementwise, gather, scatter, dynamic-slice, select, sort,
   cond, while, scan carry, pjit nesting, callback), pinning both the
   flow (secret reaches the sink) and the non-flow (public indices stay
   clean);
2. the seeded-mutant teeth matrix: every leaky mutant FAILS under the
   production allowlist (tools/check_oblivious.py runs the same set);
3. allowlist round-trip at tier-1 scale: the smoke engine audit is
   violation-free, and the DEFAULT sweep reaches every allowlist entry
   (dead entries fail) — the full cross-product rides -m slow;
4. locklint directed tests against deliberately mis-locked fake
   batchers, plus the real repo passing.
"""

from __future__ import annotations

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from grapevine_tpu.analysis.allowlist import ENGINE_ALLOWLIST
from grapevine_tpu.analysis.locklint import lint_repo, lint_sources
from grapevine_tpu.analysis.mutants import mutant_names, run_mutants
from grapevine_tpu.analysis.oblint import AllowEntry, analyze

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

U32 = jnp.uint32


def _sds(*shape):
    return jax.ShapeDtypeStruct(shape, np.uint32)


def _kinds(rep):
    return {v.kind for v in rep.violations}


# ----------------------------------------------------------------------
# 1. taint propagation units, one per primitive class
# ----------------------------------------------------------------------


def test_elementwise_propagates_and_public_stays_clean():
    def fn(s, p):
        mixed = (s * 2 + p).astype(U32) ^ s
        return p[mixed % 4], p[p % 4]  # tainted gather + clean gather

    rep = analyze(fn, {"s": _sds(4), "p": _sds(4)}, secrets=("s",))
    assert len(rep.violations) == 1  # ONLY the secret-indexed gather
    v = rep.violations[0]
    assert v.kind == "gather-index" and "s" in v.labels


def test_gather_by_secret_flagged_with_label():
    def fn(s, table):
        return table[s % 8]

    rep = analyze(fn, {"s": _sds(4), "table": _sds(8)}, secrets=("s",))
    assert _kinds(rep) == {"gather-index"}
    assert rep.violations[0].labels == ("s",)


def test_scatter_family_by_secret_flagged():
    def fn(s, plane):
        a = plane.at[s % 8].set(U32(1))
        b = plane.at[s % 8].add(U32(1))  # scatter-add: same family
        return a, b

    rep = analyze(fn, {"s": _sds(4), "plane": _sds(8)}, secrets=("s",))
    assert _kinds(rep) == {"scatter-index"}
    fam = AllowEntry("scatter", rep.violations[0].site, "test")
    assert all(fam.matches(v) for v in rep.violations)


def test_dynamic_slice_start_by_secret_flagged():
    def fn(s, x):
        return jax.lax.dynamic_slice(x, (s[0].astype(jnp.int32),), (2,))

    rep = analyze(fn, {"s": _sds(2), "x": _sds(8)}, secrets=("s",))
    assert _kinds(rep) == {"dynamic-slice-start"}


def test_select_and_sort_transmit_taint_without_sinking():
    """where/sort on secrets is fine — until the result indexes memory."""
    def fn(s, p, table):
        picked = jnp.where(s > 0, s, p)  # tainted
        perm = jnp.argsort(picked)  # tainted, but sort is not a sink
        return table[perm]  # the gather IS

    rep = analyze(
        fn, {"s": _sds(4), "p": _sds(4), "table": _sds(4)}, secrets=("s",)
    )
    assert _kinds(rep) == {"gather-index"}
    assert "s" in rep.violations[0].labels


def test_cond_predicate_flagged_and_branches_walked():
    def fn(s, table):
        # the predicate leaks AND a branch hides a secret gather
        return jax.lax.cond(
            s[0] > 1,
            lambda: table[s % 4].sum(),
            lambda: jnp.zeros((), U32),
        )

    rep = analyze(fn, {"s": _sds(4), "table": _sds(4)}, secrets=("s",))
    assert {"cond-predicate", "gather-index"} <= _kinds(rep)


def test_while_predicate_flagged_via_carry_fixpoint():
    """The secret enters the predicate only through the carry after one
    body iteration — catches analyzers that skip the fixpoint."""
    def fn(s):
        def body(c):
            i, acc = c
            return i + U32(1), acc | s[0]  # taint enters carry here

        def cond(c):
            i, acc = c
            return (i < U32(3)) | (acc > U32(0))  # tainted via acc

        return jax.lax.while_loop(cond, body, (U32(0), U32(0)))

    rep = analyze(fn, {"s": _sds(2)}, secrets=("s",))
    assert "while-predicate" in _kinds(rep)


def test_scan_carry_fixpoint_and_clean_scan_passes():
    def leaky(s, table):
        def body(c, x):
            # the sink reads the CARRY, which is clean on the first
            # body pass and secret only after one iteration — a
            # single-pass analyzer misses it, the fixpoint must not
            y = table[c % 4]  # scalar index -> dynamic_slice sink
            return c + s[0], y

        return jax.lax.scan(body, U32(0), jnp.arange(3, dtype=U32))

    rep = analyze(
        leaky, {"s": _sds(2), "table": _sds(4)}, secrets=("s",)
    )
    assert "dynamic-slice-start" in _kinds(rep)
    assert "s" in rep.violations[0].labels

    def clean(s, table):
        def body(c, x):
            return c + x, table[x % 4] + s[0]  # public index, secret data

        return jax.lax.scan(body, U32(0), jnp.arange(3, dtype=U32))

    rep2 = analyze(
        clean, {"s": _sds(2), "table": _sds(4)}, secrets=("s",)
    )
    assert rep2.ok, rep2.summary()


def test_pjit_nesting_walked():
    @jax.jit
    def inner(s, table):
        return table[s % 4]

    def fn(s, table):
        return inner(s, table) + 1

    rep = analyze(fn, {"s": _sds(4), "table": _sds(4)}, secrets=("s",))
    assert _kinds(rep) == {"gather-index"}


def test_callback_sink_flagged():
    def fn(s, x):
        jax.debug.print("leaf {v}", v=s[0])
        return x

    rep = analyze(fn, {"s": _sds(2), "x": _sds(2)}, secrets=("s",))
    assert _kinds(rep) == {"callback"}


def test_secret_prefix_matches_pytree_paths():
    """Dotted prefixes select pytree leaves: state.stash is secret,
    state.nonces is not."""
    state = {"stash": _sds(4), "nonces": _sds(4)}

    def fn(state, table):
        return table[state["stash"] % 4], table[state["nonces"] % 4]

    rep = analyze(
        fn, {"state": state, "table": _sds(4)},
        secrets=("state.stash",),
    )
    assert len(rep.violations) == 1
    assert rep.violations[0].labels == ("state.stash",)


def test_allowlist_admits_and_counts_hits():
    def fn(s, table):
        return table[s % 4]

    bare = analyze(fn, {"s": _sds(4), "table": _sds(4)}, secrets=("s",))
    site = bare.violations[0].site
    allowed = analyze(
        fn, {"s": _sds(4), "table": _sds(4)}, secrets=("s",),
        allowlist=(AllowEntry("gather", site, "test entry"),),
    )
    assert allowed.ok
    assert allowed.allowed == {f"gather@{site}": 1}


# ----------------------------------------------------------------------
# 2. mutant teeth matrix (under the PRODUCTION allowlist)
# ----------------------------------------------------------------------


def test_mutant_matrix_all_caught():
    assert len(mutant_names()) >= 6
    results = run_mutants(ENGINE_ALLOWLIST)
    missed = {
        name: (kind, [v.kind for v in rep.violations])
        for name, (rep, kind, hit) in results.items()
        if not hit
    }
    assert not missed, f"mutants NOT caught (analyzer lost teeth): {missed}"


def test_mutants_caught_for_the_right_reason():
    """Each mutant's finding is its seeded class, not incidental noise."""
    for name, (rep, kind, hit) in run_mutants(ENGINE_ALLOWLIST).items():
        kinds = [v.kind for v in rep.violations]
        assert kinds.count(kind) >= 1, (name, kind, kinds)


# ----------------------------------------------------------------------
# 3. the engine audit (smoke always-on; sweep reachability; full = slow)
# ----------------------------------------------------------------------


def test_check_oblivious_smoke_gate():
    """tools/check_oblivious.py --smoke wired into tier-1 next to the
    telemetry/seal/perf gates: one engine trace, taint-clean, all
    mutants caught, locklint green. Budget: ~1 engine trace, 0 compiles."""
    import check_oblivious as gate

    assert gate.main(["--smoke"]) == 0


def test_engine_round_audit_is_violation_free_and_uses_allowlist():
    import check_oblivious as gate

    vp, srt, pmi, k, ee = gate.SMOKE_COMBO
    assert ee > 1  # ISSUE 15: smoke pins the delayed-eviction fetch round
    rep = gate.audit_engine_round(
        gate._small_engine(vp, srt, pmi, k, ee), ENGINE_ALLOWLIST,
        "tier1_smoke",
    )
    assert rep.ok, rep.summary()
    # the audit is not vacuous: dozens of reviewed sinks were exercised
    assert sum(rep.allowed.values()) > 20
    assert rep.n_eqns > 1000
    # the write half (the standalone flush program) audits clean too
    repf = gate.audit_engine_flush(
        gate._small_engine(vp, srt, pmi, k, ee), ENGINE_ALLOWLIST,
        "tier1_smoke",
    )
    assert repf.ok, repf.summary()


@pytest.mark.slow
def test_allowlist_round_trip_default_sweep():
    """Every reviewed allowlist entry is REACHED by the default sweep
    and no combo produces a violation — dead entries rot, so their
    presence alone fails this test."""
    import check_oblivious as gate

    problems, hits = gate.run_audit(gate.DEFAULT_COMBOS)
    assert not problems, problems
    dead = gate.check_allowlist_reachability(hits)
    assert not dead, dead


@pytest.mark.slow
def test_full_matrix_and_mutants_via_cli():
    """The whole gate end to end at the full 2x2x2x2 cross-product."""
    import check_oblivious as gate

    assert gate.main(["--full"]) == 0


# ----------------------------------------------------------------------
# 4. locklint directed tests
# ----------------------------------------------------------------------


_FAKE_OK = '''
import threading

def pack_batch(reqs): return reqs
def validate_request(r): pass

class BatchJournal:
    def append_round(self, b, n): pass

class GrapevineEngine:
    def __init__(self):
        self._lock = threading.Lock()
        self.state = 0
        self.durability = None

    def _assemble_round(self, reqs):
        for r in reqs: validate_request(r)
        return pack_batch(reqs)

    def _journal_round(self, batch):
        if self.durability: self.durability.append_round(batch, 1)

    def _dispatch_round(self, batch):
        self.state = self.state + 1
        return batch

    def handle_queries_async(self, reqs):
        batch = self._assemble_round(reqs)
        with self._lock:
            self._journal_round(batch)
            out = self._dispatch_round(batch)
        return out
'''


def _mutate(src: str, old: str, new: str) -> str:
    assert old in src
    return src.replace(old, new)


def test_locklint_fake_batcher_clean():
    assert lint_sources({"fake.py": _FAKE_OK}, allow=()) == []


def test_locklint_split_holds_flagged():
    bad = _mutate(
        _FAKE_OK,
        "        with self._lock:\n"
        "            self._journal_round(batch)\n"
        "            out = self._dispatch_round(batch)\n",
        "        with self._lock:\n"
        "            self._journal_round(batch)\n"
        "        with self._lock:\n"
        "            out = self._dispatch_round(batch)\n",
    )
    vs = lint_sources({"fake.py": bad}, allow=())
    assert any(v.kind == "same-hold" for v in vs), vs


def test_locklint_stage1_under_lock_flagged():
    bad = _mutate(
        _FAKE_OK,
        "        batch = self._assemble_round(reqs)\n        with self._lock:",
        "        with self._lock:\n            batch = self._assemble_round(reqs)\n"
        "        with self._lock:",
    )
    vs = lint_sources({"fake.py": bad}, allow=())
    assert any(v.kind == "stage1-under-lock" for v in vs), vs


def test_locklint_journal_growing_a_lock_flagged():
    bad = _mutate(
        _FAKE_OK,
        "class BatchJournal:\n    def append_round(self, b, n): pass",
        "class BatchJournal:\n"
        "    def __init__(self):\n"
        "        self._jlock = threading.Lock()\n"
        "    def append_round(self, b, n):\n"
        "        with self._jlock: pass",
    )
    vs = lint_sources({"fake.py": bad}, allow=())
    assert any(v.kind == "journal-lock" for v in vs), vs


def test_locklint_ordering_cycle_flagged():
    cyc = _FAKE_OK + '''
class BatchScheduler:
    def __init__(self, engine: GrapevineEngine):
        self.engine = engine
        self._cv = threading.Condition()

    def submit(self, req):
        with self._cv:
            self.engine.handle_queries_async([req])  # cv -> engine lock
'''
    # close the cycle: the engine, under its lock, calls back into a
    # scheduler method that takes the cv
    cyc = _mutate(
        cyc,
        "    def __init__(self):\n        self._lock = threading.Lock()",
        "    def __init__(self, sched: BatchScheduler):\n"
        "        self.sched = sched\n"
        "        self._lock = threading.Lock()",
    )
    cyc = _mutate(
        cyc,
        "            self._journal_round(batch)\n",
        "            self._journal_round(batch)\n"
        "            self.sched.submit(None)\n",
    )
    # give the binding a target class annotation order-independently:
    # BatchScheduler is annotated above; GrapevineEngine.sched binds it
    vs = lint_sources({"fake.py": cyc}, allow=())
    assert any(v.kind == "lock-cycle" for v in vs), vs


def test_locklint_unguarded_shared_attr_flagged():
    shared = _FAKE_OK + '''
import threading as _t

class BatchScheduler:
    def __init__(self, engine):
        self.engine = engine
        self._cv = threading.Condition()
        self._depth = 0
        self._worker = threading.Thread(target=self._run)

    def _run(self):
        self._depth = self._depth - 1  # worker write, no lock

    def submit(self, req):
        self._depth = self._depth + 1  # caller write, no lock
        return self._depth
'''
    vs = lint_sources({"fake.py": shared}, allow=())
    assert any(
        v.kind == "shared-attr" and "_depth" in v.where for v in vs
    ), vs


def test_locklint_covers_hostpipe_handoff():
    """The multiprocess host pipeline's main-side hand-off (ISSUE 20)
    is in coverage: a HostPipeline whose reader thread and submitters
    race on an unlocked attribute must be flagged like the scheduler's."""
    piped = _FAKE_OK + '''
class HostPipeline:
    def __init__(self):
        self._lock = threading.Lock()
        self._inflight = 0

    def _start(self):
        self._reader = threading.Thread(target=self._read_loop)

    def _read_loop(self):
        self._inflight = self._inflight - 1  # reader write, no lock

    def submit(self, task):
        self._inflight = self._inflight + 1  # caller write, no lock
        return self._inflight
'''
    vs = lint_sources({"fake.py": piped}, allow=())
    assert any(
        v.kind == "shared-attr" and "HostPipeline._inflight" in v.where
        for v in vs
    ), vs


def test_locklint_missing_code_is_loud():
    vs = lint_sources({"fake.py": "x = 1\n"}, allow=())
    assert any(v.kind == "missing-code" for v in vs)


def test_locklint_dead_allow_entry_flagged():
    """A LOCK_ALLOW entry documenting a race that no longer exists must
    fail the lint — the oblint dead-entry rule, host-side."""
    from grapevine_tpu.analysis.locklint import LockAllow

    vs = lint_sources(
        {"fake.py": _FAKE_OK},
        allow=(LockAllow("GrapevineEngine", "ghost",
                         "a race that was refactored away"),),
    )
    assert any(
        v.kind == "dead-allow" and "ghost" in v.where for v in vs
    ), vs


def test_locklint_reads_only_entry_still_fails_unlocked_write():
    from grapevine_tpu.analysis.locklint import LockAllow

    src = _FAKE_OK + '''
class Extra:
    pass
'''
    src = src.replace(
        "    def handle_queries_async(self, reqs):",
        "    def poke(self):\n"
        "        self.state = self.state + 1  # unlocked WRITE\n\n"
        "    def handle_queries_async(self, reqs):",
    )
    entry = LockAllow("GrapevineEngine", "state", "reads tolerated",
                      reads_only=True)
    vs = lint_sources({"fake.py": src}, allow=(entry,))
    assert any(
        v.kind == "shared-attr" and "state" in v.where for v in vs
    ), vs


def test_locklint_real_repo_passes():
    """The PR-10 invariant holds in the live tree — statically."""
    vs = lint_repo(os.path.join(REPO, "grapevine_tpu"))
    assert vs == [], [str(v) for v in vs]


# ----------------------------------------------------------------------
# legacy-checker convergence (satellite: identical verdicts via the core)
# ----------------------------------------------------------------------


def test_legacy_checkers_share_the_analyzer_core():
    import check_posmap_oblivious as posmap_gate
    import check_tree_cache_oblivious as cache_gate

    from grapevine_tpu.analysis import jaxpr_walk

    assert posmap_gate._census is jaxpr_walk.census
    assert cache_gate._census is jaxpr_walk.census
    assert cache_gate._shared_plane_rows is jaxpr_walk.plane_rows


def test_k0_recursive_census_cell():
    """Regression (ISSUE 12 satellite): the k=0 recursive cell the
    pre-unification wiring never ran always-on — the uncached recursive
    round must be index-blind and move full B*path_len rows per plane,
    tree_leaf included, with no cache planes declared. height=5 keeps
    the bucket-axis [n, Z] plane shapes disjoint from the inner posmap
    round's working buffers (the shape-keyed accounting's one
    constraint, see _tree_planes)."""
    import check_tree_cache_oblivious as cache_gate

    out = cache_gate.check_k0_recursive_census(b=4, height=5)
    assert out["tree_leaf"] == [4 * 6]  # B * (height+1)
    assert "cache_idx" not in out
