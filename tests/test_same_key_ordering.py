"""Directed same-key op-ordering tests for the phase-major engine.

Round-2 verdict reproduced a silent message loss: in one batch, a
zero-id op by recipient X *before* the first CREATE→X (with X's mailbox
block absent) made the create return SUCCESS and insert the record, but
never appended the mailbox entry — the claimed key slot was gathered
from the group's *first op* instead of its first-*create* op
(engine/vphases.py). The randomized suites rarely generate that
ordering, so this file enumerates same-key op-order permutations
directly, on absent and present mailboxes, and checks the engine against
the oracle plus a follow-up drain.

Reference semantics: zero-id ops (grapevine.proto:87-91,115-118);
within-batch slot order is this build's documented extension
(engine/round_step.py).
"""

import itertools
import random

import pytest

from grapevine_tpu.config import GrapevineConfig
from grapevine_tpu.engine.batcher import GrapevineEngine
from grapevine_tpu.testing.reference import ReferenceEngine
from grapevine_tpu.wire import constants as C
from grapevine_tpu.wire.records import QueryRequest, RequestRecord

NOW = 1_700_000_000

CFG = GrapevineConfig(bucket_cipher_rounds=0, 
    max_messages=64,
    max_recipients=8,
    mailbox_cap=4,
    batch_size=8,
    stash_size=96,
)


def key(n: int) -> bytes:
    return bytes([n, n ^ 0x5A]) + b"\x01" * 30


def req(rt, auth, msg_id=C.ZERO_MSG_ID, recipient=C.ZERO_PUBKEY, pl=None, tag=0):
    return QueryRequest(
        request_type=rt,
        auth_identity=auth,
        auth_signature=b"\x01" * C.SIGNATURE_SIZE,
        record=RequestRecord(
            msg_id=msg_id,
            recipient=recipient,
            payload=pl if pl is not None else bytes([tag & 0xFF]) * C.PAYLOAD_SIZE,
        ),
    )


def assert_responses_equal(dev, ora, ctx=""):
    assert dev.status_code == ora.status_code, (
        f"{ctx}: status {dev.status_code} != {ora.status_code}"
    )
    assert dev.record.msg_id == ora.record.msg_id, f"{ctx}: id"
    assert dev.record.sender == ora.record.sender, f"{ctx}: sender"
    assert dev.record.recipient == ora.record.recipient, f"{ctx}: recipient"
    assert dev.record.payload == ora.record.payload, f"{ctx}: payload"
    assert dev.record.timestamp == ora.record.timestamp, f"{ctx}: ts"


def run_pair(engine, oracle, reqs, t):
    """One batch through engine and oracle (forced ids), compare all."""
    dev = engine.handle_queries(reqs, t)
    forced = [
        d.record.msg_id
        if r.request_type == C.REQUEST_TYPE_CREATE
        and d.status_code == C.STATUS_CODE_SUCCESS
        else None
        for r, d in zip(reqs, dev)
    ]
    ora = oracle.handle_batch(reqs, t, forced)
    for j, (r, d, o) in enumerate(zip(reqs, dev, ora)):
        assert_responses_equal(d, o, f"slot {j} rt {r.request_type}")
    assert engine.message_count() == oracle.message_count()
    assert engine.recipient_count() == oracle.recipient_count()
    return dev, ora


def test_zero_read_before_create_on_absent_mailbox():
    """The round-2 verdict reproduction: batch [zero-id READ by X,
    CREATE→X] on a fresh engine, then a follow-up zero-id READ by X must
    return SUCCESS with the created record (not NOT_FOUND)."""
    engine = GrapevineEngine(CFG, seed=3)
    oracle = ReferenceEngine(config=CFG, rng=random.Random(99))
    x, s = key(1), key(2)

    batch = [
        req(C.REQUEST_TYPE_READ, x),  # zero-id: "next message for X"
        req(C.REQUEST_TYPE_CREATE, s, recipient=x, tag=7),
    ]
    dev, _ = run_pair(engine, oracle, batch, NOW)
    assert dev[1].status_code == C.STATUS_CODE_SUCCESS

    follow, _ = run_pair(engine, oracle, [req(C.REQUEST_TYPE_READ, x)], NOW + 1)
    assert follow[0].status_code == C.STATUS_CODE_SUCCESS
    assert follow[0].record.msg_id == dev[1].record.msg_id
    assert follow[0].record.payload == bytes([7]) * C.PAYLOAD_SIZE


def _ops_for(kind, x, s, tag):
    """An op on recipient-X's mailbox group, by kind tag."""
    if kind == "create":
        return req(C.REQUEST_TYPE_CREATE, s, recipient=x, tag=tag)
    if kind == "zread":
        return req(C.REQUEST_TYPE_READ, x)
    if kind == "zdel":
        return req(C.REQUEST_TYPE_DELETE, x)
    raise ValueError(kind)


@pytest.mark.parametrize("preexisting", [0, 1, 2])
@pytest.mark.parametrize(
    "perm",
    list(itertools.permutations(["zread", "create", "zdel"]))
    + [
        ("zread", "create"),
        ("zdel", "create"),
        ("zread", "zdel", "create", "create"),
        ("zdel", "zread", "create", "zread"),
        ("create", "zdel", "zread", "create"),
    ],
)
def test_same_key_order_permutations(perm, preexisting):
    """Every ordering of {zero-id read, zero-id delete, create} on one
    recipient within a batch must match the oracle, with the mailbox
    absent (preexisting=0) or present with 1-2 messages, and must leave
    a drainable state (follow-up zero-id reads agree too)."""
    engine = GrapevineEngine(CFG, seed=11)
    oracle = ReferenceEngine(config=CFG, rng=random.Random(42))
    x, s = key(1), key(2)

    t = NOW
    if preexisting:
        setup = [
            req(C.REQUEST_TYPE_CREATE, s, recipient=x, tag=100 + i)
            for i in range(preexisting)
        ]
        run_pair(engine, oracle, setup, t)
        t += 1

    batch = [_ops_for(kind, x, s, 10 + i) for i, kind in enumerate(perm)]
    run_pair(engine, oracle, batch, t)

    # drain: the mailbox contents after the hazard batch must agree
    for i in range(preexisting + len(perm) + 1):
        t += 1
        run_pair(engine, oracle, [req(C.REQUEST_TYPE_READ, x)], t)
        run_pair(engine, oracle, [req(C.REQUEST_TYPE_DELETE, x)], t)


def test_zero_ops_by_two_recipients_interleaved():
    """Two recipient groups sharing a batch, each with a zero-id op
    before its first create; neither group's claim may be lost."""
    engine = GrapevineEngine(CFG, seed=5)
    oracle = ReferenceEngine(config=CFG, rng=random.Random(7))
    x, y, s = key(1), key(3), key(2)
    batch = [
        req(C.REQUEST_TYPE_READ, x),
        req(C.REQUEST_TYPE_DELETE, y),
        req(C.REQUEST_TYPE_CREATE, s, recipient=y, tag=1),
        req(C.REQUEST_TYPE_CREATE, s, recipient=x, tag=2),
    ]
    run_pair(engine, oracle, batch, NOW)
    for ident, tag in ((x, 2), (y, 1)):
        resp, _ = run_pair(
            engine, oracle, [req(C.REQUEST_TYPE_READ, ident)], NOW + 1
        )
        assert resp[0].status_code == C.STATUS_CODE_SUCCESS
        assert resp[0].record.payload == bytes([tag]) * C.PAYLOAD_SIZE
