"""The perf-regression sentinel (tools/check_perf_regression.py): the
tier-1 gate next to check_telemetry_policy / check_checkpoint_seal,
plus directed units over the comparator.

No bench run happens here — smoke mode is file parsing + dict math, so
the gate costs milliseconds of the tier-1 budget.
"""

import importlib.util
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def sentinel():
    path = os.path.join(REPO, "tools", "check_perf_regression.py")
    spec = importlib.util.spec_from_file_location(
        "check_perf_regression", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _line(ops=100.0, p99=50.0, *, config="synth", batch=8, sizes="full",
          backend="cpu", tag="t"):
    return {
        "sizes": sizes, "backend": backend, "pr": tag,
        "configs": {config: {"ops_per_sec": ops, "p99_round_ms": p99,
                             "batch": batch, "capacity_log2": 10}},
    }


def test_smoke_gate_passes_on_banked_baseline(sentinel, capsys):
    """The acceptance criterion: --smoke runs in tier-1 and passes on
    the repo's banked BENCH_trajectory.jsonl."""
    assert sentinel.main(["--smoke"]) == 0
    out = capsys.readouterr().out
    assert "self-test ok" in out and "clean" in out


def test_throughput_regression_detected(sentinel):
    series = sentinel.extract_series([_line(100.0), _line(30.0)])
    regs, n = sentinel.compare_latest(series, factor=2.0)
    assert n == 2 and len(regs) == 1 and "ops_per_sec" in regs[0]


def test_latency_regression_detected(sentinel):
    series = sentinel.extract_series([_line(p99=50.0), _line(p99=200.0)])
    regs, _ = sentinel.compare_latest(series, factor=2.0)
    assert len(regs) == 1 and "p99_round_ms" in regs[0]


def test_within_factor_drift_passes(sentinel):
    series = sentinel.extract_series(
        [_line(100.0, 50.0), _line(80.0, 60.0)])
    regs, n = sentinel.compare_latest(series, factor=2.0)
    assert n == 2 and regs == []


def test_median_banked_value_is_the_baseline(sentinel):
    """Regression is judged against the MEDIAN of the banked history,
    not the best-ever value — one lucky-fast historical run must not
    ratchet the bar toward itself on noisy hardware."""
    # history [100 (lucky), 40, 42] → median 42; latest 35 is within
    # 2x of the median even though it is far outside best-ever/2
    series = sentinel.extract_series(
        [_line(100.0), _line(40.0), _line(42.0), _line(35.0)])
    regs, n = sentinel.compare_latest(series, factor=2.0)
    assert n == 2 and regs == []  # p99 series rides along unchanged
    # a genuine past-factor collapse against the same history DOES fire
    series = sentinel.extract_series(
        [_line(100.0), _line(40.0), _line(42.0), _line(15.0)])
    regs, _ = sentinel.compare_latest(series, factor=2.0)
    assert len(regs) == 1 and "ops_per_sec" in regs[0]


def test_geometry_and_sizes_partition_series(sentinel):
    """Toy smoke shapes never gate full-size runs and vice versa; a
    different batch size is a different series."""
    for variant in (
        _line(1.0, 5000.0, sizes="smoke"),
        _line(1.0, 5000.0, batch=2048),
        _line(1.0, 5000.0, backend="tpu"),
    ):
        series = sentinel.extract_series([_line(100.0, 50.0), variant])
        regs, n = sentinel.compare_latest(series, factor=2.0)
        assert n == 0 and regs == []


def test_skipped_error_and_nonnumeric_configs_ignored(sentinel):
    lines = [
        {"sizes": "full", "backend": "cpu", "configs": {
            "a": {"skipped": "no wheel"},
            "b": {"error": "boom"},
            "c": {"note": "text only", "leakaudit": "PASS"},
            "d": {"ops_per_sec": 0.0, "batch": 8},  # 0 = unmeasured
        }},
    ]
    assert sentinel.extract_series(lines) == {}


def test_fresh_line_compared_against_banked(sentinel):
    banked = [_line(100.0, 50.0, tag="PR5")]
    regs, n = sentinel.compare_fresh(_line(20.0, 500.0, tag="new"),
                                     banked, factor=2.0)
    assert n == 2 and len(regs) == 2
    regs, n = sentinel.compare_fresh(_line(95.0, 55.0, tag="new"),
                                     banked, factor=2.0)
    assert n == 2 and regs == []


def test_selftest_rejects_a_toothless_comparator(sentinel, monkeypatch):
    """If the comparator silently stops firing, the self-test fails the
    gate rather than letting a dead sentinel ride along green."""
    monkeypatch.setattr(sentinel, "compare_latest",
                        lambda series, factor: ([], 2))
    with pytest.raises(AssertionError, match="not flagged"):
        sentinel.selftest(2.0)


def test_corrupt_trajectory_fails_loudly(sentinel, tmp_path):
    bad = tmp_path / "traj.jsonl"
    bad.write_text('{"ok": 1}\n{not json\n')
    with pytest.raises(SystemExit, match="unparseable"):
        sentinel.load_trajectory(str(bad))
