"""Segmented saturating-scan primitives vs naive sequential models."""

import numpy as np
import jax.numpy as jnp

from grapevine_tpu.oblivious.segmented import (
    group_sort,
    sat_apply,
    sat_compose,
    sat_elem,
    sat_identity,
    segmented_counts_before,
    segmented_exclusive_sat_scan,
)


def naive_sat(x, steps):
    """Apply (add, lo, hi) steps sequentially to x."""
    for a, lo, hi in steps:
        x = min(max(x + a, lo), hi)
    return x


def test_sat_compose_matches_sequential():
    rng = np.random.default_rng(0)
    for _ in range(200):
        steps = [
            (int(rng.integers(-3, 4)), int(rng.integers(-5, 1)), int(rng.integers(1, 8)))
            for _ in range(rng.integers(1, 6))
        ]
        f = sat_identity()
        for s in steps:
            f = sat_compose(f, sat_elem(*s))
        for x0 in range(-4, 9):
            assert int(sat_apply(f, jnp.int32(x0))) == naive_sat(x0, steps), (
                steps,
                x0,
            )


def test_segmented_exclusive_scan_counts():
    """Mailbox-style walk: +1 clamped at cap, -1 clamped at 0, identity."""
    rng = np.random.default_rng(1)
    b, cap = 64, 3
    group = rng.integers(0, 6, b).astype(np.uint32)
    kind = rng.integers(0, 3, b)  # 0=create, 1=pop, 2=other
    c0 = {g: int(rng.integers(0, cap + 1)) for g in range(6)}

    # naive per-group walk
    want_before = np.zeros(b, np.int32)
    cnt = dict(c0)
    for j in range(b):
        g = int(group[j])
        want_before[j] = cnt[g]
        if kind[j] == 0:
            cnt[g] = min(cnt[g] + 1, cap)
        elif kind[j] == 1:
            cnt[g] = max(cnt[g] - 1, 0)

    add = np.where(kind == 0, 1, np.where(kind == 1, -1, 0)).astype(np.int32)
    lo = np.zeros(b, np.int32)
    hi = np.full(b, cap, np.int32)

    perm, inv, seg_start = group_sort(jnp.asarray(group))
    elems = (
        jnp.asarray(add)[perm],
        jnp.asarray(lo)[perm],
        jnp.asarray(hi)[perm],
    )
    pre = segmented_exclusive_sat_scan(elems, seg_start)
    c0_arr = jnp.asarray([c0[int(g)] for g in np.asarray(group[np.asarray(perm)])], np.int32)
    before_sorted = sat_apply(pre, c0_arr)
    got = np.asarray(before_sorted[inv])
    np.testing.assert_array_equal(got, want_before)


def test_segmented_counts_before():
    group = jnp.asarray([0, 1, 0, 2, 1, 0], jnp.uint32)
    flags = jnp.asarray([1, 0, 1, 1, 1, 0], bool)
    got = np.asarray(segmented_counts_before(group, flags))
    np.testing.assert_array_equal(got, [0, 0, 1, 0, 0, 2])
