"""Segmented saturating-scan primitives vs naive sequential models."""

import numpy as np
import jax.numpy as jnp

from grapevine_tpu.oblivious.segmented import (
    group_sort,
    multiword_group_sort,
    sat_apply,
    sat_compose,
    sat_elem,
    sat_identity,
    segment_bounds,
    segmented_counts_before,
    segmented_exclusive_sat_scan,
    segmented_scan,
    segmented_sum_before,
    segmented_sum_total,
)


def naive_sat(x, steps):
    """Apply (add, lo, hi) steps sequentially to x."""
    for a, lo, hi in steps:
        x = min(max(x + a, lo), hi)
    return x


def test_sat_compose_matches_sequential():
    rng = np.random.default_rng(0)
    for _ in range(200):
        steps = [
            (int(rng.integers(-3, 4)), int(rng.integers(-5, 1)), int(rng.integers(1, 8)))
            for _ in range(rng.integers(1, 6))
        ]
        f = sat_identity()
        for s in steps:
            f = sat_compose(f, sat_elem(*s))
        for x0 in range(-4, 9):
            assert int(sat_apply(f, jnp.int32(x0))) == naive_sat(x0, steps), (
                steps,
                x0,
            )


def test_segmented_exclusive_scan_counts():
    """Mailbox-style walk: +1 clamped at cap, -1 clamped at 0, identity."""
    rng = np.random.default_rng(1)
    b, cap = 64, 3
    group = rng.integers(0, 6, b).astype(np.uint32)
    kind = rng.integers(0, 3, b)  # 0=create, 1=pop, 2=other
    c0 = {g: int(rng.integers(0, cap + 1)) for g in range(6)}

    # naive per-group walk
    want_before = np.zeros(b, np.int32)
    cnt = dict(c0)
    for j in range(b):
        g = int(group[j])
        want_before[j] = cnt[g]
        if kind[j] == 0:
            cnt[g] = min(cnt[g] + 1, cap)
        elif kind[j] == 1:
            cnt[g] = max(cnt[g] - 1, 0)

    add = np.where(kind == 0, 1, np.where(kind == 1, -1, 0)).astype(np.int32)
    lo = np.zeros(b, np.int32)
    hi = np.full(b, cap, np.int32)

    perm, inv, seg_start = group_sort(jnp.asarray(group))
    elems = (
        jnp.asarray(add)[perm],
        jnp.asarray(lo)[perm],
        jnp.asarray(hi)[perm],
    )
    pre = segmented_exclusive_sat_scan(elems, seg_start)
    c0_arr = jnp.asarray([c0[int(g)] for g in np.asarray(group[np.asarray(perm)])], np.int32)
    before_sorted = sat_apply(pre, c0_arr)
    got = np.asarray(before_sorted[inv])
    np.testing.assert_array_equal(got, want_before)


def test_segmented_counts_before():
    group = jnp.asarray([0, 1, 0, 2, 1, 0], jnp.uint32)
    flags = jnp.asarray([1, 0, 1, 1, 1, 0], bool)
    got = np.asarray(segmented_counts_before(group, flags))
    np.testing.assert_array_equal(got, [0, 0, 1, 0, 0, 2])


def test_multiword_group_sort_and_bounds_vs_naive():
    """The scan-vphases sort machinery vs a naive Python model: the
    permutation orders ops by (multi-word key, slot), segment starts
    mark key boundaries, and segment_bounds finds each element's
    first/last segment index — including B=1 and all-equal keys."""
    rng = np.random.default_rng(3)
    sizes = [1, 2, 3, 7, 16, 33]  # fixed shapes: bounded compile count
    for trial in range(18):
        b = sizes[trial % len(sizes)]
        nw = int(rng.integers(1, 4))
        cols = [rng.integers(0, 3, b).astype(np.uint32) for _ in range(nw)]
        keys = list(zip(*[c.tolist() for c in cols]))
        perm, inv, seg = multiword_group_sort([jnp.asarray(c) for c in cols])
        perm, inv, seg = np.asarray(perm), np.asarray(inv), np.asarray(seg)
        want = sorted(range(b), key=lambda i: (keys[i], i))
        assert perm.tolist() == want, trial
        assert (np.arange(b)[perm][inv] == np.arange(b)).all()
        want_seg = [True] + [
            keys[perm[i]] != keys[perm[i - 1]] for i in range(1, b)
        ]
        assert seg.tolist() == want_seg
        start, end = map(np.asarray, segment_bounds(jnp.asarray(seg)))
        for j in range(b):
            s = j
            while not seg[s]:
                s -= 1
            e = j
            while e + 1 < b and not seg[e + 1]:
                e += 1
            assert start[j] == s and end[j] == e


def test_segmented_sums_and_scan_vs_naive():
    rng = np.random.default_rng(4)
    sizes = [1, 2, 5, 17, 40]
    for trial in range(15):
        b = sizes[trial % len(sizes)]
        seg = np.zeros(b, bool)
        seg[0] = True
        seg[1:] = rng.random(b - 1) < 0.3
        start, end = map(np.asarray, segment_bounds(jnp.asarray(seg)))
        x = rng.integers(0, 5, (b, 2)).astype(np.int32)
        bef = np.asarray(segmented_sum_before(jnp.asarray(x), jnp.asarray(seg)))
        tot = np.asarray(segmented_sum_total(jnp.asarray(x), jnp.asarray(seg)))
        v = rng.integers(-9, 9, b).astype(np.int32)
        mx = np.asarray(
            segmented_scan(jnp.asarray(v), jnp.asarray(seg), jnp.maximum)
        )
        for j in range(b):
            s, e = start[j], end[j]
            np.testing.assert_array_equal(bef[j], x[s:j].sum(axis=0))
            np.testing.assert_array_equal(tot[j], x[s : e + 1].sum(axis=0))
            assert mx[j] == v[s : j + 1].max()
