"""TPU Mosaic lowering contract for the Pallas kernels (CPU-hosted).

Interpret-mode tests prove kernel SEMANTICS but not the Mosaic tiling
contract — all three kernels passed interpret-mode CI for two rounds
while the first real TPU window rejected them at lowering (rank-1 block
of 86 rows: neither full-array nor 128-aligned; TPURUN_r5.jsonl).
``jax.export(platforms=("tpu",))`` runs the Pallas→Mosaic lowering
pipeline on a CPU-only host, so this gate catches the whole class
without hardware. Full geometry sweep: tools/mosaic_lowering_check.py.
"""

import functools

import jax
import jax.numpy as jnp
import pytest
from jax import export

from grapevine_tpu.oblivious.pallas_cipher import cipher_rows_pallas
from grapevine_tpu.oblivious.pallas_gather import (
    gather_decrypt_rows,
    gather_decrypt_rows_tiled,
    scatter_encrypt_rows,
    scatter_encrypt_rows_tiled,
)

U32 = jnp.uint32


def _lower_tpu(fn, *specs, **static):
    export.export(jax.jit(functools.partial(fn, **static)),
                  platforms=("tpu",))(*specs)


def _s(*shape):
    return jax.ShapeDtypeStruct(shape, U32)


@pytest.mark.parametrize("r,z,vw", [(172, 4, 380), (14, 4, 1016)])
def test_cipher_kernel_lowers_for_tpu(r, z, vw):
    _lower_tpu(cipher_rows_pallas, _s(8), _s(r), _s(r, 2), _s(r, z),
               _s(r, vw), rounds=8, interpret=False)


#: jaxlib 0.4.36 mis-canonicalizes a 0-d vector load compared against a
#: scalar inside the one-row gather kernel's Mosaic lowering
#: ('arith.cmpi' op requires all operands to have the same type — a
#: vector<i32> vs i32 operand pair from ``nonce_row_ref[0, 0, 0] != 0``,
#: pallas_gather.py:76). Fixed in later jaxlib; the tiled kernel pair
#: and the one-row scatter lower clean even here. TRACKING: remove this
#: gate when the container's jaxlib moves past 0.4.36 — the skip is
#: version-scoped so current jax keeps running the case.
_JAXLIB_MOSAIC_CMPI_BUG = tuple(
    int(x) for x in jax.lib.__version__.split(".")[:3]
) <= (0, 4, 36)


@pytest.mark.parametrize(
    "fn", [gather_decrypt_rows, gather_decrypt_rows_tiled]
)
def test_gather_kernel_lowers_for_tpu(fn):
    if fn is gather_decrypt_rows and _JAXLIB_MOSAIC_CMPI_BUG:
        pytest.skip(
            "jaxlib <= 0.4.36 Mosaic cmpi vector/scalar bug on the "
            "one-row gather kernel (see _JAXLIB_MOSAIC_CMPI_BUG)"
        )
    n, r, z, v = 65, 22, 4, 254
    _lower_tpu(fn, _s(8), _s(n * z), _s(n, z * v),
               _s(n, 2), _s(r), z=z, rounds=8, interpret=False)


@pytest.mark.parametrize(
    "fn", [scatter_encrypt_rows, scatter_encrypt_rows_tiled]
)
def test_scatter_kernel_lowers_for_tpu(fn):
    n, r, z, v = 65, 22, 4, 254
    specs = [_s(8), _s(n * z), _s(n, z * v), _s(n, 2), _s(r),
             jax.ShapeDtypeStruct((r,), jnp.bool_), _s(2), _s(r, z),
             _s(r, z * v)]
    _lower_tpu(fn, *specs, z=z, rounds=8, interpret=False)


# ----------------------------------------------------------------------
# the whole phase-major engine round, per vphases impl: the sort/scan
# path (variadic lax.sort, associative scans, cummax/cummin, scatter
# tables) must lower for TPU cross-platform just like the Pallas
# kernels — a scan geometry that only ever ran on CPU would repeat the
# window-1 lowering surprise at the first vphases_perf A/B.
# ----------------------------------------------------------------------


@pytest.mark.parametrize(
    "impl,sort,geom",
    [
        # (batch, max_messages, max_recipients, mailbox_cap, density);
        # scan gets both geometries (the new, never-TPU-compiled path),
        # dense one (it already compiled on the real chip in window 1).
        # Each vphases impl also lowers with sort_impl="radix" — the
        # counting-pass engine (scatter-bincount, [B,R] cumsum tables,
        # per-pass unique scatters) must pass the Mosaic pipeline
        # BEFORE the sort_perf capture stage meets a real chip, or that
        # window repeats the window-1 lowering surprise.
        ("scan", "xla", (8, 64, 8, 4, 2)),
        ("scan", "xla", (16, 1 << 10, 1 << 6, 62, 4)),  # production-shaped
        ("dense", "xla", (8, 64, 8, 4, 2)),
        ("scan", "radix", (8, 64, 8, 4, 2)),
        ("scan", "radix", (16, 1 << 10, 1 << 6, 62, 4)),
        ("dense", "radix", (8, 64, 8, 4, 2)),
    ],
)
def test_engine_round_lowers_for_tpu(impl, sort, geom):
    from grapevine_tpu.config import GrapevineConfig
    from grapevine_tpu.engine.round_step import engine_round_step
    from grapevine_tpu.engine.state import (
        EngineConfig,
        ID_WORDS,
        KEY_WORDS,
        PAYLOAD_WORDS,
        init_engine,
    )

    b, cap, recips, mcap, density = geom
    cfg = GrapevineConfig(
        max_messages=cap,
        max_recipients=recips,
        mailbox_cap=mcap,
        batch_size=b,
        tree_density=density,
        bucket_cipher_rounds=8,
        vphases_impl=impl,
        sort_impl=sort,
    )
    ecfg = EngineConfig.from_config(cfg)
    state = jax.eval_shape(lambda: init_engine(ecfg, 0))
    batch = {
        "req_type": _s(b),
        "auth": _s(b, KEY_WORDS),
        "msg_id": _s(b, ID_WORDS),
        "recipient": _s(b, KEY_WORDS),
        "payload": _s(b, PAYLOAD_WORDS),
        "now": _s(),
        "now_hi": _s(),
    }
    export.export(
        jax.jit(functools.partial(engine_round_step, ecfg)),
        platforms=("tpu",),
    )(state, batch)
