"""The load harness: open-loop replay, workload telemetry, capacity
knee, and the adversarial-vs-honest /leakaudit discrimination drill
(ISSUE 9 tentpole + satellite).

Fast always-on coverage (one tiny engine compile, shared module-wide):

- open-loop property, behaviorally: a replay against a scheduler whose
  completions are wedged still submits every op on schedule (arrival
  times independent of completion times), and never mutates the
  schedule (fingerprint-stable);
- workload telemetry lands: fill/depth histograms sampled at round
  cadence, arrival EWMA > 0, per-phase utilization from the span
  ledgers, flightrec rounds carrying the queue_depth field;
- honest traffic through the REAL engine: /leakaudit verdict PASS;
- the probe campaign + ProbeCampaignInjector: verdict flips SUSPECT
  within the soak (detection power under adversarial timing — an
  honest engine cannot be flipped by traffic shape, which is exactly
  what the honest-scenario FP gate pins);
- capacity knee math on synthetic steps (no engine).

Scenario breadth (every honest generator soaked, the no-false-positive
budget under bursty/diurnal/pop-heavy timing) rides ``-m slow``.
"""

import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

from grapevine_tpu.config import GrapevineConfig
from grapevine_tpu.engine.batcher import GrapevineEngine
from grapevine_tpu.load import (
    ProbeCampaignInjector,
    ScenarioRunner,
    adversarial_probe,
    analyze_ramp,
    bursty_onoff,
    diurnal_sinusoid,
    find_knee,
    pop_heavy_drain,
    ramp_to_saturation,
    steady_poisson,
)
from grapevine_tpu.load.capacity import step_stats
from grapevine_tpu.obs.leakmon import PASS, SUSPECT, EngineLeakMonitor
from grapevine_tpu.obs.workload import WorkloadTelemetry
from grapevine_tpu.server.scheduler import BatchScheduler

NOW = 1_700_000_000


# ---------------------------------------------------------------------
# open-loop behavior against a fake scheduler (no engine, no jax)
# ---------------------------------------------------------------------


class _WedgedFakeScheduler:
    """Accepts every op instantly, completes none until released —
    the worst-case server an open-loop harness must not wait for."""

    def __init__(self):
        self.submit_walls: list[float] = []
        self.futures: list[Future] = []
        self._lock = threading.Lock()

    def submit_nowait(self, req, auth=None) -> Future:
        fut: Future = Future()
        with self._lock:
            self.submit_walls.append(time.perf_counter())
            self.futures.append(fut)
        return fut

    def release_all(self):
        from grapevine_tpu.wire import constants as C
        from grapevine_tpu.wire.records import QueryResponse, Record

        zero = Record(msg_id=b"\x00" * 16, sender=b"\x00" * 32,
                      recipient=b"\x00" * 32, timestamp=0,
                      payload=b"\x00" * C.PAYLOAD_SIZE)
        for fut in self.futures:
            fut.set_result(
                QueryResponse(record=zero,
                              status_code=C.STATUS_CODE_SUCCESS))


def test_replay_is_open_loop_and_schedule_immutable():
    """Submissions track the schedule even when nothing ever completes
    (no self-throttling), and the schedule object is untouched."""
    sched = steady_poisson(150.0, 1.0, 21, n_idents=8)
    fp_before = sched.fingerprint()
    fake = _WedgedFakeScheduler()
    runner = ScenarioRunner(fake, n_idents=8, settle_timeout_s=0.2)

    release = threading.Timer(1.6, fake.release_all)
    release.start()
    t0 = time.perf_counter()
    res = runner.run(sched)
    release.cancel()
    fake.release_all()  # idempotent: settle anything left

    assert len(fake.submit_walls) == sched.n_ops, (
        "open-loop replay must submit EVERY op regardless of completions"
    )
    # submissions happened on schedule, not after completions: the last
    # op went in by ~duration, far before any completion existed
    assert fake.submit_walls[-1] - t0 < sched.duration_s + 0.5
    # loose always-on bound: on this sandbox's 2-vCPU host a GC pause
    # or scheduler preemption can stall one dispatch tick by ~0.5s
    # (observed p99 0.53s) without the dispatcher actually falling
    # behind the open-loop schedule; the tight realtime bound lives in
    # the -m slow variant below
    skew = res.skew_s[~np.isnan(res.skew_s)]
    assert np.percentile(skew, 99) < 1.5, "dispatcher fell behind"
    assert sched.fingerprint() == fp_before, "replay mutated the schedule"


@pytest.mark.slow
def test_replay_dispatch_skew_tight():
    """The realtime claim at full strength: p99 dispatch skew under
    250 ms against a wedged server. Meaningful on an unloaded host;
    under tier-1's parallel suite the shared 2 vCPUs make sub-second
    scheduler stalls routine, so this tight variant rides -m slow."""
    sched = steady_poisson(150.0, 1.0, 21, n_idents=8)
    fake = _WedgedFakeScheduler()
    runner = ScenarioRunner(fake, n_idents=8, settle_timeout_s=0.2)
    release = threading.Timer(1.6, fake.release_all)
    release.start()
    res = runner.run(sched)
    release.cancel()
    fake.release_all()
    skew = res.skew_s[~np.isnan(res.skew_s)]
    assert np.percentile(skew, 99) < 0.25, "dispatcher fell behind"


def test_replay_time_scale_compresses_wall_clock():
    sched = steady_poisson(50.0, 2.0, 22, n_idents=8)
    fake = _WedgedFakeScheduler()
    runner = ScenarioRunner(fake, n_idents=8, time_scale=0.25,
                            settle_timeout_s=0.1)
    t0 = time.perf_counter()
    fake_release = threading.Timer(0.9, fake.release_all)
    fake_release.start()
    runner.run(sched)
    fake_release.cancel()
    fake.release_all()
    assert time.perf_counter() - t0 < 2.0 * 0.25 + 1.0


# ---------------------------------------------------------------------
# capacity knee math (synthetic steps; no engine)
# ---------------------------------------------------------------------


def _step(rate, burn, fail_frac=0.0, n=64):
    return {
        "offered_rate": rate, "arrival_rate": rate, "n_ops": n,
        "achieved_ops_per_sec": rate,
        "breach_fraction": burn * 0.01, "burn_rate": burn,
        "failure_fraction": fail_frac,
        "p99_commit_ms": 10.0,
    }


def test_find_knee_last_holding_step_before_failure():
    steps = [_step(100, 0.0), _step(200, 0.5), _step(400, 40.0),
             _step(800, 99.0)]
    k = find_knee(steps)
    assert k["knee_ops_per_sec"] == 200 and k["saturated"]
    assert k["first_failing_rate"] == 400


def test_find_knee_unsaturated_ramp_is_a_lower_bound():
    k = find_knee([_step(100, 0.0), _step(200, 0.2)])
    assert k["knee_ops_per_sec"] == 200 and not k["saturated"]
    assert k["first_failing_rate"] is None


def test_find_knee_lucky_late_step_cannot_inflate():
    steps = [_step(100, 0.0), _step(200, 50.0), _step(400, 0.0)]
    k = find_knee(steps)
    assert k["knee_ops_per_sec"] == 100, (
        "a pass AFTER a measured failure must not raise the knee"
    )


def test_find_knee_failing_ops_do_not_hold():
    # latency fine but the server failed 40% of ops: not holding
    steps = [_step(100, 0.0), _step(200, 0.0, fail_frac=0.4)]
    k = find_knee(steps)
    assert k["knee_ops_per_sec"] == 100 and k["saturated"]


def test_find_knee_thin_steps_grade_nothing():
    k = find_knee([_step(100, 99.0, n=2)])
    assert k["knee_ops_per_sec"] == 0.0 and not k["saturated"]


def test_step_stats_unsettled_ops_breach():
    s = step_stats(100.0, 1.0, [0.001, np.nan, 0.5], [True, False, True],
                   target_ms=250.0, error_budget=0.01)
    # NaN (never settled) and 0.5s (past target) both breach
    assert s["breach_fraction"] == pytest.approx(2 / 3, abs=1e-3)
    assert s["burn_rate"] == pytest.approx(66.67, abs=0.1)


def test_analyze_ramp_on_synthetic_replay():
    sched = ramp_to_saturation(200.0, 2.0, 3, 1.0, 23)

    class _Res:
        time_scale = 1.0
        latency_s = np.where(sched.t_s < 2.0, 0.01, 1.0)
        ok = np.ones(sched.n_ops, bool)

    out = analyze_ramp(sched, _Res(), target_ms=250.0)
    assert out["saturated"]
    assert out["knee_ops_per_sec"] == pytest.approx(400.0)
    assert len(out["steps"]) == 3


# ---------------------------------------------------------------------
# the real engine: telemetry + discrimination (one shared tiny engine)
# ---------------------------------------------------------------------


@pytest.fixture(scope="module")
def loaded_engine():
    cfg = GrapevineConfig(
        max_messages=1 << 10, max_recipients=1 << 8, batch_size=4,
        bucket_cipher_rounds=0,
    )
    engine = GrapevineEngine(cfg)
    wl = WorkloadTelemetry(engine.metrics.registry, batch_size=4)
    engine.attach_workload(wl)
    # pay the jit compile outside every test's measurement window
    sched = BatchScheduler(engine, clock=lambda: NOW)
    try:
        ScenarioRunner(sched, n_idents=8).run(
            steady_poisson(40.0, 0.2, 1, n_idents=8))
    finally:
        sched.close()
    return engine, wl


def _fresh_monitor(engine):
    return EngineLeakMonitor(
        mb_leaves=engine.ecfg.mb.leaves, rec_leaves=engine.ecfg.rec.leaves,
        mb_choices=engine.ecfg.mb_choices,
    )


def _run_scenario(engine, schedule, sink):
    engine.attach_leakmon(sink)
    sched = BatchScheduler(engine, clock=lambda: NOW)
    try:
        runner = ScenarioRunner(sched, n_idents=16, settle_timeout_s=60.0)
        return runner.run(schedule)
    finally:
        sched.close()
        sink.flush(30)
        engine.attach_leakmon(None)


def test_workload_telemetry_lands_at_round_cadence(loaded_engine):
    engine, wl = loaded_engine
    mon = _fresh_monitor(engine)
    res = _run_scenario(
        engine, steady_poisson(120.0, 1.2, 31, n_idents=16), mon)
    s = res.summary()
    assert s["n_failed"] == 0 and s["n_ok"] == s["n_ops"]
    assert s["p99_commit_ms"] > 0

    reg = engine.metrics.registry
    fill = reg.get("grapevine_load_batch_fill").child()
    depth = reg.get("grapevine_load_queue_depth").child()
    assert fill.count > 0 and depth.count > 0, (
        "fill/depth histograms must sample at round cadence"
    )
    assert reg.get("grapevine_load_arrivals_total").get() >= s["n_ops"]
    # the EWMA gauge saw the ~100 ops/s stream (wide noise bounds)
    assert reg.get("grapevine_load_arrival_rate_ops_s").get() > 1.0
    util = wl.utilization()
    assert util["device"] > 0.0, "device-window utilization never derived"
    assert all(0.0 <= u <= 1.0 for u in util.values())
    # flightrec rounds carry the queue-depth summary field
    rounds = mon.recorder.dump()["rounds"]
    assert rounds and all("queue_depth" in r for r in rounds)
    v = mon.verdict()
    assert v["verdict"] == PASS and v["rounds_observed"] > 0
    mon.close()
    reg.audit()  # the new namespace stays batch-level under live load


def test_probe_campaign_flips_leakaudit_suspect(loaded_engine):
    """The discrimination drill's detection half: a leak signature
    riding probe-shaped traffic flips the monitor within the soak. The
    engine itself stays honest — the injector rewrites only the
    transcript COPY fed to the detectors (load/harness.py docstring)."""
    engine, _ = loaded_engine
    mon = _fresh_monitor(engine)
    inj = ProbeCampaignInjector(mon, engine.ecfg)
    _run_scenario(
        engine,
        adversarial_probe(0.03, 1.5, 32, n_probe_keys=4,
                          probes_per_pulse=2),
        inj,
    )
    v = mon.verdict()
    assert v["verdict"] == SUSPECT, v
    tripped = {d["name"] for d in v["detectors"] if d["verdict"] == SUSPECT}
    assert "cross_round_repeat" in tripped, tripped
    mon.close()


def test_probe_campaign_without_leak_stays_pass(loaded_engine):
    """The FP half, fast edition: the SAME adversarial timing against
    the honest engine (no injector) must NOT flip the audit — traffic
    shape alone cannot simulate a leak, which is the obliviousness
    claim the thresholds are sized against."""
    engine, _ = loaded_engine
    mon = _fresh_monitor(engine)
    _run_scenario(
        engine,
        adversarial_probe(0.03, 1.5, 33, n_probe_keys=4,
                          probes_per_pulse=2),
        mon,
    )
    v = mon.verdict()
    assert v["verdict"] == PASS, v
    # PASS by measurement, not by missing evidence: the probe shape
    # exists to maximize detector samples
    coll = next(d for d in v["detectors"]
                if d["name"] == "samekey_collision" and d["tree"] == "mb")
    assert coll["samples"] >= coll["min_samples"], coll
    mon.close()


# ---------------------------------------------------------------------
# scenario breadth: the full honest soak + an end-to-end knee (-m slow)
# ---------------------------------------------------------------------


HONEST_SOAKS = {
    "bursty": lambda: bursty_onoff(250.0, 0.3, 1.0, 4.0, 41, n_idents=16),
    "diurnal": lambda: diurnal_sinusoid(120.0, 0.8, 2.0, 4.0, 42,
                                        n_idents=16),
    "pop_heavy": lambda: pop_heavy_drain(120.0, 4.0, 43, n_idents=16,
                                         n_hot=4),
}


@pytest.mark.slow
@pytest.mark.parametrize("name", sorted(HONEST_SOAKS))
def test_honest_soak_stays_pass(loaded_engine, name):
    """ISSUE 9 satellite: the false-positive gate for the scale-aware
    thresholds under non-uniform TIMING — every honest shape soaked
    through the real engine, verdict PASS with measured evidence."""
    engine, _ = loaded_engine
    mon = _fresh_monitor(engine)
    res = _run_scenario(engine, HONEST_SOAKS[name](), mon)
    assert res.summary()["n_failed"] == 0
    v = mon.verdict()
    assert v["verdict"] == PASS, (name, v)
    assert v["rounds_observed"] >= 32
    mon.close()


@pytest.mark.slow
def test_ramp_finds_a_knee_end_to_end(loaded_engine):
    engine, _ = loaded_engine
    mon = _fresh_monitor(engine)
    # calibrate a plausible staircase around this host's capacity
    t0 = time.perf_counter()
    sched = BatchScheduler(engine, clock=lambda: NOW)
    try:
        ScenarioRunner(sched, n_idents=16).run(
            steady_poisson(40.0, 0.3, 44, n_idents=16))
    finally:
        sched.close()
    est = 4 / max(1e-3, (time.perf_counter() - t0) / 8)  # rough ops/s
    schedule = ramp_to_saturation(max(10.0, 0.25 * est), 2.0, 4, 1.0, 45,
                                  n_idents=16)
    res = _run_scenario(engine, schedule, mon)
    out = analyze_ramp(schedule, res, target_ms=250.0)
    assert out["knee_ops_per_sec"] > 0, out
    assert len(out["steps"]) == 4
    mon.close()
