"""/leakaudit + /flightrec over a live engine tier (ISSUE 2 tentpole).

Mirrors tests/test_obs_endpoint.py's approach: the engine tier imports
without the session layer's `cryptography` dependency, and its metrics
endpoint machinery is byte-identical to the monolithic server's. Covers
the serving surface of the continuous obliviousness audit:

- /leakaudit serves the machine-readable verdict (per-detector
  statistic, threshold, window, sample counts) with HTTP 200 on PASS;
- honest traffic through the real scheduler + engine stays PASS and
  /healthz carries the folded verdict;
- a SUSPECT verdict flips /leakaudit AND /healthz to 503, and the
  flight recorder auto-dumps to the configured path;
- /flightrec serves the ring dump; both endpoints 404 when the monitor
  is off;
- the --leakmon-* CLI flags build the right config and obey the role
  matrix (device-owning roles only).
"""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from grapevine_tpu.config import GrapevineConfig
from grapevine_tpu.obs.leakmon import LeakMonitorConfig
from grapevine_tpu.server import cli
from grapevine_tpu.server.tier import EngineServer
from grapevine_tpu.wire import constants as C
from grapevine_tpu.wire.records import QueryRequest, RequestRecord

NOW = 1_700_000_000


def _req(rt, auth, recipient=C.ZERO_PUBKEY, msg_id=C.ZERO_MSG_ID):
    return QueryRequest(
        request_type=rt,
        auth_identity=auth,
        auth_signature=b"\x01" * C.SIGNATURE_SIZE,
        record=RequestRecord(
            msg_id=msg_id,
            recipient=recipient,
            payload=b"\x07" * C.PAYLOAD_SIZE,
        ),
    )


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:  # 503 still carries a body
        return e.code, e.read().decode()


@pytest.fixture(scope="module")
def tier(tmp_path_factory):
    dump_path = str(tmp_path_factory.mktemp("leakmon") / "flight.json")
    cfg = GrapevineConfig(
        bucket_cipher_rounds=0,
        max_messages=64,
        max_recipients=16,
        mailbox_cap=4,
        batch_size=4,
        stash_size=96,
    )
    srv = EngineServer(
        cfg, seed=7, max_wait_ms=5.0, clock=lambda: NOW,
        leakmon=LeakMonitorConfig(
            window_rounds=64,
            min_pairs=4, min_opportunities=4, min_pooled_leaves=32,
            dump_path=dump_path,
        ),
    )
    port = srv.start_metrics(0, host="127.0.0.1")
    yield srv, port, dump_path
    srv.stop()


def test_leakaudit_serves_verdict_and_healthz_folds_it(tier):
    srv, port, _ = tier
    # honest traffic through the real scheduler + engine + monitor
    a, b = bytes([1]) * 32, bytes([2]) * 32
    for i in range(12):
        resp = srv.scheduler.submit(_req(C.REQUEST_TYPE_CREATE, a, recipient=b))
        assert resp.status_code in (
            C.STATUS_CODE_SUCCESS,
            C.STATUS_CODE_TOO_MANY_MESSAGES_FOR_RECIPIENT,
        )
        srv.scheduler.submit(_req(C.REQUEST_TYPE_READ, b))
    assert srv.leakmon.flush(30), "monitor queue did not drain"

    status, body = _get(f"http://127.0.0.1:{port}/leakaudit")
    assert status == 200, body
    v = json.loads(body)
    assert v["verdict"] == "PASS"
    assert v["rounds_observed"] >= 12
    assert v["window_rounds"] == 64
    names = {(d["name"], d["tree"]) for d in v["detectors"]}
    assert names == {
        (n, t)
        for n in ("samekey_collision", "cross_round_repeat", "uniformity")
        for t in ("rec", "mb")
    }
    for d in v["detectors"]:  # machine-readable: every field present
        for field in ("statistic", "threshold", "samples", "min_samples",
                      "verdict"):
            assert field in d

    status, body = _get(f"http://127.0.0.1:{port}/healthz")
    assert status == 200
    assert json.loads(body)["leakaudit"] == "PASS"

    # leakmon aggregates ride the merged /metrics view
    status, text = _get(f"http://127.0.0.1:{port}/metrics")
    assert status == 200
    assert 'grapevine_leakmon_rounds_total' in text
    assert 'grapevine_leakmon_uniformity_z{tree="rec"}' in text
    assert 'grapevine_leakmon_suspect 0' in text


def test_flightrec_serves_ring_dump(tier):
    srv, port, _ = tier
    status, body = _get(f"http://127.0.0.1:{port}/flightrec")
    assert status == 200
    dump = json.loads(body)
    assert dump["retained"] >= 1
    last = dump["rounds"][-1]
    assert {"seq", "fill", "phase_s", "stats", "verdict"} <= set(last)
    # the scheduler's hand-off threaded assembly timing into the summary
    assert "assembly" in last["phase_s"]
    assert "dispatch" in last["phase_s"]


def test_suspect_flips_endpoints_and_dumps_flight_recorder(tier):
    """Feed the monitor a no-remap-shaped synthetic stream (same key,
    same leaf, round after round) and watch the whole serving surface
    flip: /leakaudit 503, /healthz 503 with the folded verdict, the
    flight recorder dumped to the configured path. Then confirm the
    window drains back to PASS — the runbook's re-baseline."""
    srv, port, dump_path = tier
    mon = srv.leakmon.monitor
    for _ in range(16):
        mon.observe("rec", np.zeros(4, np.int64), np.full(4, 3))
    # the worker caches verdicts per engine round; the synthetic feed
    # bypasses it, so push one real round through to refresh the cache
    srv.scheduler.submit(_req(C.REQUEST_TYPE_READ, bytes([2]) * 32))
    assert srv.leakmon.flush(30)

    status, body = _get(f"http://127.0.0.1:{port}/leakaudit")
    assert status == 503
    v = json.loads(body)
    assert v["verdict"] == "SUSPECT"
    tripped = [d for d in v["detectors"] if d["verdict"] == "SUSPECT"]
    assert any(d["name"] == "cross_round_repeat" for d in tripped)

    status, body = _get(f"http://127.0.0.1:{port}/healthz")
    assert status == 503
    assert json.loads(body)["leakaudit"] == "SUSPECT"

    with open(dump_path, encoding="utf-8") as fh:
        dumped = json.load(fh)
    assert dumped["retained"] >= 1  # the PASS→SUSPECT transition dumped

    # drain: honest synthetic rounds age the leak out of the window
    rng = np.random.default_rng(3)
    for _ in range(80):
        mon.observe(
            "rec", np.arange(4, dtype=np.int64),
            rng.integers(0, srv.engine.ecfg.rec.leaves, size=4),
        )
    srv.scheduler.submit(_req(C.REQUEST_TYPE_READ, bytes([2]) * 32))
    assert srv.leakmon.flush(30)
    status, _ = _get(f"http://127.0.0.1:{port}/leakaudit")
    assert status == 200
    status, _ = _get(f"http://127.0.0.1:{port}/healthz")
    assert status == 200


def test_endpoints_404_without_monitor():
    cfg = GrapevineConfig(
        bucket_cipher_rounds=0, max_messages=64, max_recipients=16,
        mailbox_cap=4, batch_size=4, stash_size=96,
    )
    srv = EngineServer(cfg, seed=9, max_wait_ms=5.0, clock=lambda: NOW)
    port = srv.start_metrics(0, host="127.0.0.1")
    try:
        assert _get(f"http://127.0.0.1:{port}/leakaudit")[0] == 404
        assert _get(f"http://127.0.0.1:{port}/flightrec")[0] == 404
        assert _get(f"http://127.0.0.1:{port}/healthz")[0] == 200
    finally:
        srv.stop()


# ---------------------------------------------------------------------
# CLI flag plumbing
# ---------------------------------------------------------------------


def _parse(argv):
    parser = cli.build_parser()
    args = parser.parse_args(argv)
    cli._reject_misapplied_flags(parser, args, argv)
    return args


def test_cli_leakmon_config_built_from_flags():
    args = _parse([
        "--role", "engine", "--engine-listen", "127.0.0.1:0",
        "--leakmon", "--leakmon-window", "128",
        "--leakmon-uniformity-z", "6.5",
        "--leakmon-collision-threshold", "0.01",
        "--leakmon-repeat-threshold", "0.03",
        "--leakmon-dump-path", "/tmp/fr.json",
    ])
    lcfg = cli._leakmon_config(args)
    assert lcfg is not None
    assert lcfg.window_rounds == 128
    assert lcfg.uniformity_z_threshold == 6.5
    assert lcfg.collision_threshold == 0.01
    assert lcfg.repeat_threshold == 0.03
    assert lcfg.dump_path == "/tmp/fr.json"


def test_cli_leakmon_off_by_default():
    args = _parse(["--role", "engine", "--engine-listen", "127.0.0.1:0"])
    assert cli._leakmon_config(args) is None


@pytest.mark.parametrize("argv", [
    ["--role", "mono", "--leakmon"],
    ["--role", "engine", "--engine-listen", "127.0.0.1:0", "--leakmon",
     "--leakmon-window", "512"],
])
def test_cli_leakmon_allowed_on_device_roles(argv):
    _parse(argv)  # must not raise


@pytest.mark.parametrize("argv", [
    ["--role", "frontend", "--engine", "127.0.0.1:4000", "--leakmon"],
    ["--role", "frontend", "--engine", "127.0.0.1:4000",
     "--leakmon-window", "64"],
])
def test_cli_leakmon_rejected_on_frontend(argv):
    """A frontend has no transcript; expecting monitoring there is the
    misconfiguration the role matrix exists to catch."""
    with pytest.raises(SystemExit, match="does not take"):
        _parse(argv)
