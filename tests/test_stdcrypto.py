"""Stdlib channel-crypto backend (session/stdcrypto.py): RFC vectors and
bit-compatibility pins.

The wheel-less backend must produce the *same bytes* as the
``cryptography``-backed channel, or a stdlib client could not talk to a
wheel-backed server. Each primitive is pinned to its RFC test vector
(the same vectors the wheel's implementations are certified against —
two implementations that both match the RFC match each other), and when
the wheel happens to be present in the container, directly against the
wheel's output inside the same always-running tests (plain ``if``, not
a skip: the wheel-less container must exercise every line here)."""

import hashlib
import os

import pytest

from grapevine_tpu.session import chacha, channel, stdcrypto

try:
    import cryptography  # noqa: F401

    HAVE_WHEEL = True
except ModuleNotFoundError:
    HAVE_WHEEL = False


# -- ChaCha20 -----------------------------------------------------------


def test_chacha20_rfc8439_block_and_stream():
    """RFC 8439 §2.3.2 (block) / §2.4.2 (encryption) vectors, plus the
    numpy stream pinned to the pure-Python spec oracle."""
    key = bytes(range(32))
    nonce = bytes.fromhex("000000000000004a00000000")
    pt = (
        b"Ladies and Gentlemen of the class of '99: If I could offer you "
        b"only one tip for the future, sunscreen would be it."
    )
    ct = stdcrypto.chacha20_xor(key, nonce, pt, counter=1)
    assert ct[:16] == bytes.fromhex("6e2e359a2568f98041ba0728dd0d6981")
    assert ct[-14:] == bytes.fromhex("74a35be6b40b8eedf2785e42874d")
    assert stdcrypto.chacha20_xor(key, nonce, ct, counter=1) == pt
    # numpy stream == pure-Python block oracle, arbitrary counter
    pure = chacha.ChaCha20(key, nonce, counter=7)
    want = b"".join(pure._block(7 + i) for i in range(3))
    assert stdcrypto.chacha20_keystream(key, nonce, 192, counter=7) == want


def test_challenge_rng_uses_same_stream_regardless_of_backend():
    """ChallengeRng draws are the cross-implementation contract
    (README.md:189-196) — the keystream fallback must not change them."""
    seed = bytes(range(32))
    rng = chacha.ChallengeRng(seed)
    draws = [rng.next_challenge() for _ in range(4)]
    # spec oracle: block function at counters 0..1 (4 × 32 bytes)
    oracle = chacha.ChaCha20(seed)
    want = b"".join(oracle._block(i) for i in range(2))
    assert b"".join(draws) == want


# -- Poly1305 / AEAD ----------------------------------------------------


def test_poly1305_rfc8439_vector():
    key = bytes.fromhex(
        "85d6be7857556d337f4452fe42d506a8"
        "0103808afb0db2fd4abff6af4149f51b"
    )
    msg = b"Cryptographic Forum Research Group"
    assert stdcrypto.poly1305(key, msg) == bytes.fromhex(
        "a8061dc1305136c6c22b8baf0c0127a9"
    )


def test_chacha20poly1305_rfc8439_vector_and_wheel_compat():
    """RFC 8439 §2.8.2 AEAD vector; when the wheel is present, also pin
    byte-equality against its ChaCha20Poly1305."""
    key = bytes(range(0x80, 0xA0))
    nonce = bytes.fromhex("070000004041424344454647")
    aad = bytes.fromhex("50515253c0c1c2c3c4c5c6c7")
    pt = (
        b"Ladies and Gentlemen of the class of '99: If I could offer you "
        b"only one tip for the future, sunscreen would be it."
    )
    out = stdcrypto.ChaCha20Poly1305(key).encrypt(nonce, pt, aad)
    ct, tag = out[:-16], out[-16:]
    assert ct[:16] == bytes.fromhex("d31a8d34648e60db7b86afbc53ef7ec2")
    assert tag == bytes.fromhex("1ae10b594f09e26a7e902ecbd0600691")
    assert stdcrypto.ChaCha20Poly1305(key).decrypt(nonce, out, aad) == pt
    if HAVE_WHEEL:
        from cryptography.hazmat.primitives.ciphers.aead import (
            ChaCha20Poly1305 as WheelAEAD,
        )

        assert WheelAEAD(key).encrypt(nonce, pt, aad) == out
        assert WheelAEAD(key).decrypt(nonce, out, aad) == pt


def test_chacha20poly1305_rejects_tampering():
    key = os.urandom(32)
    nonce = os.urandom(12)
    aead = stdcrypto.ChaCha20Poly1305(key)
    out = aead.encrypt(nonce, b"payload", b"aad")
    for mutate in (
        lambda b: bytes([b[0] ^ 1]) + b[1:],          # ciphertext bit
        lambda b: b[:-1] + bytes([b[-1] ^ 1]),        # tag bit
        lambda b: b[:8],                              # truncation
    ):
        with pytest.raises(stdcrypto.InvalidTag):
            aead.decrypt(nonce, mutate(out), b"aad")
    with pytest.raises(stdcrypto.InvalidTag):
        aead.decrypt(nonce, out, b"other aad")


# -- X25519 -------------------------------------------------------------


def test_x25519_rfc7748_vectors():
    # RFC 7748 §5.2 vector 1
    k = bytes.fromhex(
        "a546e36bf0527c9d3b16154b82465edd62144c0ac1fc5a18506a2244ba449ac4"
    )
    u = bytes.fromhex(
        "e6db6867583030db3594c1a424b15f7c726624ec26b3353b10a903a6d0ab1c4c"
    )
    assert stdcrypto.x25519(k, u) == bytes.fromhex(
        "c3da55379de9c6908e94ea4df28d084f32eccf03491c71f754b4075577a28552"
    )
    # RFC 7748 §6.1 Diffie-Hellman vector
    a = bytes.fromhex(
        "77076d0a7318a57d3c16c17251b26645df4c2f87ebc0992ab177fba51db92c2a"
    )
    b = bytes.fromhex(
        "5dab087e624a8a4b79e17f8b83800ee66f3bb1292618b6fd1c2f8b27ff88e0eb"
    )
    a_pub = stdcrypto.X25519PrivateKey(a).public_key().public_bytes_raw()
    b_pub = stdcrypto.X25519PrivateKey(b).public_key().public_bytes_raw()
    assert a_pub == bytes.fromhex(
        "8520f0098930a754748b7ddcb43ef75a0dbf3a0d26381af4eba4a98eaa9b4e6a"
    )
    assert b_pub == bytes.fromhex(
        "de9edb7d7b7dc1b4d35b61c2ece435373f8343c85b78674dadfc7e146f882b4f"
    )
    shared = bytes.fromhex(
        "4a5d9d5ba4ce2de1728e3bf480350f25e07e21c947d19e3376f09b3c1e161742"
    )
    k_a = stdcrypto.X25519PrivateKey(a).exchange(
        stdcrypto.X25519PublicKey(b_pub)
    )
    k_b = stdcrypto.X25519PrivateKey(b).exchange(
        stdcrypto.X25519PublicKey(a_pub)
    )
    assert k_a == k_b == shared
    if HAVE_WHEEL:
        from cryptography.hazmat.primitives.asymmetric.x25519 import (
            X25519PrivateKey as WheelPriv,
            X25519PublicKey as WheelPub,
        )

        assert (
            WheelPriv.from_private_bytes(a)
            .exchange(WheelPub.from_public_bytes(b_pub))
            == shared
        )


def test_x25519_rejects_all_zero_secret():
    # the all-zero point is low-order: exchange must refuse it, like the
    # wheel's contributory-behavior check
    priv = stdcrypto.X25519PrivateKey.generate()
    with pytest.raises(ValueError):
        priv.exchange(stdcrypto.X25519PublicKey(b"\x00" * 32))


# -- HKDF ---------------------------------------------------------------


def test_hkdf_rfc5869_case1_and_wheel_compat():
    ikm = b"\x0b" * 22
    salt = bytes(range(13))
    info = bytes.fromhex("f0f1f2f3f4f5f6f7f8f9")
    okm = stdcrypto.hkdf_sha256(ikm, salt, info, 42)
    assert okm == bytes.fromhex(
        "3cb25f25faacd57a90434f64d0362f2a"
        "2d2d0a90cf1a5a4c5db02d56ecc4c5bf"
        "34007208d5b887185865"
    )
    if HAVE_WHEEL:
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.kdf.hkdf import HKDF

        assert (
            HKDF(algorithm=hashes.SHA256(), length=42, salt=salt, info=info)
            .derive(ikm)
            == okm
        )


# -- channel integration ------------------------------------------------


def test_channel_backend_is_declared_and_handshake_works():
    """Whichever backend loaded, a full IX handshake + framed traffic
    must work — this is the line `server_loopback` and the session test
    modules now rely on in wheel-less containers."""
    assert channel.CRYPTO_BACKEND in ("cryptography", "stdlib")
    if not HAVE_WHEEL:
        assert channel.CRYPTO_BACKEND == "stdlib"
    ident = channel.ServerIdentity.from_seed(b"\x07" * 32)
    state, msg1 = channel.client_handshake()
    reply, server_chan = channel.server_handshake(msg1, identity=ident)
    client_chan = channel.client_finish(
        state, reply, expected_server_static=ident.public
    )
    for i in range(3):
        msg = hashlib.sha256(bytes([i])).digest()
        assert server_chan.decrypt(client_chan.encrypt(msg, b"a"), b"a") == msg
        assert client_chan.decrypt(server_chan.encrypt(msg)) == msg
    # tamper → AEAD failure, whatever exception class the backend uses
    ct = bytearray(client_chan.encrypt(b"x"))
    ct[0] ^= 1
    with pytest.raises(Exception):
        server_chan.decrypt(bytes(ct))
