"""BucketCipher (oblivious/bucket_cipher.py): RFC vectors + properties."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from grapevine_tpu.oblivious.bucket_cipher import (
    chacha_blocks,
    epoch_next,
    row_keystream,
)
from grapevine_tpu.session.chacha import ChaCha20

U32 = jnp.uint32


def _host_block(key_words, counter, bucket, epoch_lo, epoch_hi=0):
    """RFC 7539 block via the host implementation: nonce = LE(bucket,
    epoch_lo, epoch_hi), counter = block index."""
    key = b"".join(int(w).to_bytes(4, "little") for w in key_words)
    nonce = (
        int(bucket).to_bytes(4, "little")
        + int(epoch_lo).to_bytes(4, "little")
        + int(epoch_hi).to_bytes(4, "little")
    )
    return ChaCha20(key, nonce=nonce, counter=counter)._block(counter)


def test_device_chacha20_matches_rfc_host_implementation():
    key = jnp.arange(1, 9, dtype=U32) * U32(0x9E3779B9)
    for bucket, elo, ehi, ctr in [
        (0, 1, 0, 0),
        (12345, 7, 0, 3),
        (0xFFFF, 0xABCD, 5, 63),
    ]:
        dev = chacha_blocks(
            key,
            jnp.full((1,), ctr, U32),
            jnp.full((1,), bucket, U32),
            jnp.full((1,), elo, U32),
            jnp.full((1,), ehi, U32),
            rounds=20,
        )[0]
        host = _host_block(np.asarray(key), ctr, bucket, elo, ehi)
        dev_bytes = b"".join(int(w).to_bytes(4, "little") for w in np.asarray(dev))
        assert dev_bytes == host


def test_row_keystream_roundtrip_and_epoch0_identity():
    key = jax.random.bits(jax.random.PRNGKey(0), (8,), U32)
    rows = jax.random.bits(jax.random.PRNGKey(1), (5, 100), U32)
    buckets = jnp.arange(5, dtype=U32)
    epochs = jnp.stack(
        [jnp.array([0, 1, 1, 2, 9], U32), jnp.zeros((5,), U32)], axis=1
    )
    ks = row_keystream(key, buckets, epochs, 100)
    ct = rows ^ ks
    # epoch 0 = identity (never-written bucket stays its own ciphertext)
    np.testing.assert_array_equal(np.asarray(ct[0]), np.asarray(rows[0]))
    assert (np.asarray(ct[1:]) != np.asarray(rows[1:])).mean() > 0.99
    # decrypt = same keystream
    np.testing.assert_array_equal(np.asarray(ct ^ ks), np.asarray(rows))
    # same bucket, different epoch ⇒ unrelated streams (snapshot diffing)
    ks2 = row_keystream(key, buckets, epochs.at[:, 0].add(U32(1)), 100)
    assert (np.asarray(ks[1]) != np.asarray(ks2[1])).mean() > 0.99
    # the high epoch word matters too (64-bit counter; wrap safety)
    ks3 = row_keystream(key, buckets, epochs.at[:, 1].add(U32(1)), 100)
    assert (np.asarray(ks[1]) != np.asarray(ks3[1])).mean() > 0.99


def test_epoch_next_carries():
    e = epoch_next(jnp.array([0xFFFFFFFF, 4], U32))
    np.testing.assert_array_equal(np.asarray(e), [0, 5])
    e2 = epoch_next(jnp.array([7, 0], U32))
    np.testing.assert_array_equal(np.asarray(e2), [8, 0])


def test_engine_trees_encrypted_at_rest():
    """After traffic, the HBM tree arrays must not contain the payload
    plaintext, and rewriting identical content must change ciphertext
    (fresh epoch per round). The oracle-equality suites prove semantics
    are unchanged; this proves the at-rest property itself."""
    from grapevine_tpu.config import GrapevineConfig
    from grapevine_tpu.engine.batcher import GrapevineEngine
    from grapevine_tpu.wire import constants as C
    from grapevine_tpu.wire.records import QueryRequest, RequestRecord

    cfg = GrapevineConfig(
        max_messages=64, max_recipients=8, mailbox_cap=4, batch_size=2,
        bucket_cipher_rounds=8,
    )
    engine = GrapevineEngine(cfg, seed=4)
    me = b"\x21" * 32
    marker = (b"\xDE\xAD\xBE\xEF" * 234)[: C.PAYLOAD_SIZE]

    def create():
        return engine.handle_queries(
            [
                QueryRequest(
                    request_type=C.REQUEST_TYPE_CREATE,
                    auth_identity=me,
                    auth_signature=b"\x01" * C.SIGNATURE_SIZE,
                    record=RequestRecord(
                        msg_id=C.ZERO_MSG_ID, recipient=me, payload=marker
                    ),
                )
            ],
            1_700_000_000,
        )[0]

    r = create()
    assert r.status_code == C.STATUS_CODE_SUCCESS
    tree_bytes = np.asarray(engine.state.rec.tree_val).tobytes()
    assert marker not in tree_bytes, "payload visible in HBM tree"
    word = int.from_bytes(b"\xDE\xAD\xBE\xEF", "little")
    frac = float((np.asarray(engine.state.rec.tree_val) == word).mean())
    assert frac < 1e-3, "payload words visible in HBM tree"

    # a read rewrites the same record content; the touched rows must not
    # repeat their previous ciphertext (epoch advances)
    snap1 = np.asarray(engine.state.rec.tree_val).copy()
    nz1 = snap1[snap1.any(axis=1)]
    rd = engine.handle_queries(
        [
            QueryRequest(
                request_type=C.REQUEST_TYPE_READ,
                auth_identity=me,
                auth_signature=b"\x01" * C.SIGNATURE_SIZE,
                record=RequestRecord(
                    msg_id=r.record.msg_id,
                    recipient=C.ZERO_PUBKEY,
                    payload=b"\x00" * C.PAYLOAD_SIZE,
                ),
            )
        ],
        1_700_000_001,
    )[0]
    assert rd.status_code == C.STATUS_CODE_SUCCESS
    assert rd.record.payload == marker  # semantics intact through cipher
    snap2 = np.asarray(engine.state.rec.tree_val)
    nz2 = snap2[snap2.any(axis=1)]
    assert nz1.shape[0] >= 1 and nz2.shape[0] >= 1
    row_sets_equal = {r.tobytes() for r in nz1} == {r.tobytes() for r in nz2}
    assert not row_sets_equal, "rewritten rows kept identical ciphertext"


@pytest.mark.slow  # ~75 s randomized cipher+sweep campaign (chunked
# ChaCha re-encryption of whole trees on a scalar backend); the
# always-on cipher coverage stays: trees-encrypted-at-rest, nonce
# rotation, keystream unit equality above. Tier-1 budget: ROADMAP.md
# tier-1 note (PR 5).
def test_expiry_sweep_with_cipher_evicts_and_reencrypts():
    from grapevine_tpu.config import GrapevineConfig
    from grapevine_tpu.engine.batcher import GrapevineEngine
    from grapevine_tpu.wire import constants as C
    from grapevine_tpu.wire.records import QueryRequest, RequestRecord

    cfg = GrapevineConfig(
        max_messages=64, max_recipients=8, mailbox_cap=4, batch_size=2,
        bucket_cipher_rounds=8, expiry_period=10,
    )
    engine = GrapevineEngine(cfg, seed=6)
    me = b"\x33" * 32
    r = engine.handle_queries(
        [
            QueryRequest(
                request_type=C.REQUEST_TYPE_CREATE,
                auth_identity=me,
                auth_signature=b"\x01" * C.SIGNATURE_SIZE,
                record=RequestRecord(
                    msg_id=C.ZERO_MSG_ID,
                    recipient=me,
                    payload=b"\x07" * C.PAYLOAD_SIZE,
                ),
            )
        ],
        1_700_000_000,
    )[0]
    assert r.status_code == C.STATUS_CODE_SUCCESS
    assert engine.message_count() == 1
    evicted = engine.expire(now=1_700_000_100)
    assert evicted == 1 and engine.message_count() == 0
    # the record is gone for clients
    rd = engine.handle_queries(
        [
            QueryRequest(
                request_type=C.REQUEST_TYPE_READ,
                auth_identity=me,
                auth_signature=b"\x01" * C.SIGNATURE_SIZE,
                record=RequestRecord(
                    msg_id=r.record.msg_id,
                    recipient=C.ZERO_PUBKEY,
                    payload=b"\x00" * C.PAYLOAD_SIZE,
                ),
            )
        ],
        1_700_000_101,
    )[0]
    assert rd.status_code == C.STATUS_CODE_NOT_FOUND
