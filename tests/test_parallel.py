"""Sharded engine ≡ single-chip engine, bit for bit.

Test pyramid item (5) from SURVEY.md §4: multi-chip = single-chip results
under sharding, on the virtual 8-device CPU mesh (the stand-in for real
hardware, the way the reference CI's SGX_MODE=SW simulator stands in for
SGX, reference .github/workflows/ci.yaml:15-16).

Since ISSUE 18 the delayed-eviction flush composes with the mesh
(parallel/mesh.py make_sharded_flush; OPERATIONS.md §22): fetch-only
rounds accumulate into the REPLICATED eviction buffer and the flush
owner-masks its scatters per chip. Always-on cost: one tiny 2-shard E=2
step/flush pair (trace + compile of the small geometry only); the
E∈{2,4} × shards∈{2,4} campaign breadth — saturation, recursive posmap,
tree-top cache, ReferenceEngine oracle — rides ``-m slow``.
"""

import random

import numpy as np
import jax
import pytest

from grapevine_tpu.config import GrapevineConfig
from grapevine_tpu.engine.batcher import GrapevineEngine, pack_batch
from grapevine_tpu.engine.round_step import engine_flush_step, engine_round_step
from grapevine_tpu.engine.state import EngineConfig, init_engine
from grapevine_tpu.parallel import (
    make_mesh,
    make_sharded_flush,
    make_sharded_step,
    shard_engine_state,
)
from grapevine_tpu.wire import constants as C
from grapevine_tpu.wire.records import QueryRequest, RequestRecord

NOW = 1_700_000_000

def make_cfg(cipher_rounds: int, cipher_impl: str = "jnp") -> GrapevineConfig:
    return GrapevineConfig(
        max_messages=64,
        max_recipients=8,
        mailbox_cap=4,
        batch_size=4,
        stash_size=64,
        bucket_cipher_rounds=cipher_rounds,
        bucket_cipher_impl=cipher_impl,
    )


def key(n: int) -> bytes:
    return bytes([n, n ^ 0x5A]) + b"\x01" * 30


def req(rt, auth, msg_id=C.ZERO_MSG_ID, recipient=C.ZERO_PUBKEY, tag=0):
    return QueryRequest(
        request_type=rt,
        auth_identity=auth,
        auth_signature=b"\x01" * C.SIGNATURE_SIZE,
        record=RequestRecord(
            msg_id=msg_id,
            recipient=recipient,
            payload=bytes([tag & 0xFF]) * C.PAYLOAD_SIZE,
        ),
    )


@pytest.mark.parametrize(
    "cipher_rounds,n_dev,impl",
    [
        # real equality cases, ~35 s each on the timesliced CPU mesh —
        # they ride -m slow to keep tier-1 inside its 870 s budget
        # (they only became runnable with the shard_map compat shim —
        # before that the whole set failed at import-time attribute;
        # run `pytest -m slow tests/test_parallel.py` for the sweep).
        pytest.param(0, 2, "jnp", marks=pytest.mark.slow),
        pytest.param(0, 8, "jnp", marks=pytest.mark.slow),
        pytest.param(8, 8, "jnp", marks=pytest.mark.slow),
        pytest.param(8, 4, "jnp", marks=pytest.mark.slow),
        pytest.param(8, 8, "pallas", marks=pytest.mark.slow),
    ],
)
def test_sharded_step_matches_single_chip(cipher_rounds, n_dev, impl):
    """Sharded ≡ single-chip at 2/4/8-way meshes, with the at-rest
    bucket cipher both off and on (the cipher's nonce arrays are sharded
    along the bucket axis like the trees), and the fused Pallas cipher
    kernel running inside shard_map (the pod + pallas combination)."""
    assert len(jax.devices()) >= 8, "conftest forces an 8-device CPU mesh"
    ecfg = EngineConfig.from_config(make_cfg(cipher_rounds, impl))

    state = init_engine(ecfg, seed=3)
    single = jax.jit(engine_round_step, static_argnums=(0,))

    mesh = make_mesh(jax.devices()[:n_dev])
    sstate = shard_engine_state(init_engine(ecfg, seed=3), mesh)
    sstep = make_sharded_step(ecfg, mesh)

    a, b, c = key(1), key(2), key(3)
    batches = [
        [req(C.REQUEST_TYPE_CREATE, a, recipient=b, tag=7),
         req(C.REQUEST_TYPE_CREATE, a, recipient=c, tag=8),
         req(C.REQUEST_TYPE_CREATE, c, recipient=b, tag=9)],
        [req(C.REQUEST_TYPE_READ, b),
         req(C.REQUEST_TYPE_DELETE, c),
         req(C.REQUEST_TYPE_READ, b, msg_id=b"\x99" * 16)],
        [req(C.REQUEST_TYPE_DELETE, b),
         req(C.REQUEST_TYPE_READ, b),
         req(C.REQUEST_TYPE_CREATE, b, recipient=a, tag=10)],
    ]

    for i, reqs in enumerate(batches):
        batch = pack_batch(reqs, ecfg.batch_size, NOW + i)
        state, resp1, tr1 = single(ecfg, state, batch)
        sstate, resp2, tr2 = sstep(sstate, batch)
        for k in resp1:
            assert np.array_equal(np.asarray(resp1[k]), np.asarray(resp2[k])), (
                f"batch {i}: response field {k} diverged"
            )
        assert np.array_equal(np.asarray(tr1), np.asarray(tr2)), (
            f"batch {i}: transcript diverged"
        )

    # full final state equality, including both bucket trees
    flat1, _ = jax.tree.flatten(state)
    flat2, _ = jax.tree.flatten(sstate)
    for x, y in zip(flat1, flat2):
        assert np.array_equal(np.asarray(x), np.asarray(y))


# -- ISSUE 18: the delayed-eviction flush composes with the mesh --------


def _evict_cfg(shards=1, e=2, **kw):
    base = dict(
        max_messages=64, max_recipients=8, mailbox_cap=4,
        batch_size=4, stash_size=64, bucket_cipher_rounds=8,
    )
    base.update(kw)
    return GrapevineConfig(shards=shards, evict_every=e, **base)


def test_sharded_flush_knob_refusals():
    """Satellite 1's directed-refusal direction: every genuinely
    uncovered knob combination fails with a precise error naming it —
    never a silent fallback, never an opaque shape error later."""
    with pytest.raises(ValueError, match="power-of-two"):
        GrapevineConfig(shards=3)
    with pytest.raises(ValueError, match="commit='op'"):
        GrapevineConfig(shards=2, commit="op")
    with pytest.raises(ValueError, match="JAX device"):
        GrapevineEngine(_evict_cfg(shards=64))
    ecfg = EngineConfig.from_config(_evict_cfg())
    with pytest.raises(ValueError, match="evict_every=1 has no flush"):
        make_sharded_flush(
            EngineConfig.from_config(_evict_cfg(e=1)),
            make_mesh(jax.devices()[:2]),
        )
    # a mesh that does not divide the padded bucket counts (6 devices
    # vs power-of-two trees) names the tree and the geometry
    with pytest.raises(ValueError, match="padded buckets"):
        make_sharded_step(ecfg, make_mesh(jax.devices()[:6]))
    with pytest.raises(ValueError, match="padded buckets"):
        make_sharded_flush(ecfg, make_mesh(jax.devices()[:6]))


def test_sharded_flush_matches_single_chip_fast():
    """The always-on ISSUE-18 identity pair (tier-1 budget: this one
    small 2-shard E=2 compile): fetch-only rounds accumulate into the
    replicated buffer, the owner-masked flush drains the window, and
    responses, transcripts, AND the full final state — trees, nonces,
    buffer planes, window counters — equal the single-chip engine bit
    for bit. Plaintext geometry keeps the four compiles inside the
    budget; breadth (shards×E×cipher×recursive×cache) rides -m slow."""
    assert len(jax.devices()) >= 2, "conftest forces an 8-device CPU mesh"
    ecfg = EngineConfig.from_config(_evict_cfg(bucket_cipher_rounds=0))

    state = init_engine(ecfg, seed=3)
    single = jax.jit(engine_round_step, static_argnums=(0,))
    sflush1 = jax.jit(engine_flush_step, static_argnums=(0,))

    mesh = make_mesh(jax.devices()[:2])
    sstate = shard_engine_state(init_engine(ecfg, seed=3), mesh)
    sstep = make_sharded_step(ecfg, mesh)
    sflush = make_sharded_flush(ecfg, mesh)

    a, b, c = key(1), key(2), key(3)
    batches = [
        [req(C.REQUEST_TYPE_CREATE, a, recipient=b, tag=7),
         req(C.REQUEST_TYPE_CREATE, a, recipient=c, tag=8)],
        [req(C.REQUEST_TYPE_READ, b),
         req(C.REQUEST_TYPE_CREATE, c, recipient=b, tag=9)],
        [req(C.REQUEST_TYPE_DELETE, c),
         req(C.REQUEST_TYPE_READ, b)],
        [req(C.REQUEST_TYPE_READ, b),
         req(C.REQUEST_TYPE_CREATE, b, recipient=a, tag=10)],
    ]
    for i, reqs in enumerate(batches):
        batch = pack_batch(reqs, ecfg.batch_size, NOW + i)
        state, resp1, tr1 = single(ecfg, state, batch)
        sstate, resp2, tr2 = sstep(sstate, batch)
        for k in resp1:
            assert np.array_equal(
                np.asarray(resp1[k]), np.asarray(resp2[k])
            ), f"batch {i}: response field {k} diverged"
        assert np.array_equal(np.asarray(tr1), np.asarray(tr2)), (
            f"batch {i}: transcript diverged"
        )
        if (i + 1) % ecfg.evict_every == 0:
            state = sflush1(ecfg, state)
            sstate = sflush(sstate)

    flat1, _ = jax.tree.flatten(state)
    flat2, _ = jax.tree.flatten(sstate)
    for x, y in zip(flat1, flat2):
        assert np.array_equal(np.asarray(x), np.asarray(y))


def _key32(n: int) -> bytes:
    return bytes([n & 0xFF, (n >> 8) & 0xFF, n ^ 0x5A]) + b"\x01" * 29


def _run_sharded_campaign(cfg_kwargs, seed, shards, e, n_batches=6,
                          oracle=False):
    """One randomized campaign: a sharded engine and a single-chip
    engine at the SAME evict_every consume identical mixed batches —
    responses bit-equal every round (mid-window included), full final
    state bit-equal, and optionally the ReferenceEngine oracle's
    responses + live counts every batch (the E=1↔E>1 logical-content
    leg is test_evict.py's; composing both gives sharded E>1 ↔
    oracle)."""
    from test_vphases_scan import _assert_responses_bitequal, _gen_batch

    from grapevine_tpu.testing.reference import ReferenceEngine

    e1 = GrapevineEngine(_evict_cfg(shards=1, e=e, **cfg_kwargs),
                         seed=seed)
    es = GrapevineEngine(_evict_cfg(shards=shards, e=e, **cfg_kwargs),
                         seed=seed)
    ref = (ReferenceEngine(config=_evict_cfg(e=e, **cfg_kwargs),
                           rng=random.Random(seed))
           if oracle else None)
    rng = np.random.default_rng(seed)
    idents = [_key32(i) for i in range(1, 5)]
    live: list[tuple[bytes, bytes]] = []
    bs = es.ecfg.batch_size
    for bi in range(n_batches):
        reqs = _gen_batch(rng, idents, live, int(rng.integers(1, bs + 1)))
        t = NOW + bi
        r1 = e1.handle_queries(reqs, t)
        rs = es.handle_queries(reqs, t)
        _assert_responses_bitequal(
            r1, rs, f"shards={shards} E={e} seed={seed} batch={bi}"
        )
        assert es.health()["stash_overflow"] == 0
        if ref is not None:
            forced = [
                d.record.msg_id
                if r.request_type == C.REQUEST_TYPE_CREATE
                and d.status_code == C.STATUS_CODE_SUCCESS
                else None
                for r, d in zip(reqs, r1)
            ]
            ro = ref.handle_batch(reqs, t, forced)
            _assert_responses_bitequal(
                r1, ro, f"oracle shards={shards} E={e} batch={bi}"
            )
            assert es.message_count() == ref.message_count()
            assert es.recipient_count() == ref.recipient_count()
        for q, d in zip(reqs, r1):
            if (q.request_type == C.REQUEST_TYPE_CREATE
                    and d.status_code == C.STATUS_CODE_SUCCESS):
                live.append((d.record.msg_id, q.record.recipient))
            elif (q.request_type == C.REQUEST_TYPE_DELETE
                    and d.status_code == C.STATUS_CODE_SUCCESS):
                live = [x for x in live if x[0] != d.record.msg_id]
    flat1, _ = jax.tree.flatten(e1.state)
    flat2, _ = jax.tree.flatten(es.state)
    for x, y in zip(flat1, flat2):
        assert np.array_equal(np.asarray(x), np.asarray(y)), (
            f"shards={shards} E={e}: final state diverged"
        )


@pytest.mark.slow
@pytest.mark.parametrize("shards,e", [(2, 2), (2, 4), (4, 2), (4, 4)])
def test_sharded_evict_campaign(shards, e):
    """The acceptance grid: randomized campaigns at E∈{2,4} ×
    shards∈{2,4}, ciphered, vs the single-chip engine AND the
    ReferenceEngine oracle (logical content)."""
    _run_sharded_campaign({}, seed=8100 + 10 * shards + e,
                          shards=shards, e=e, oracle=True)


@pytest.mark.slow
def test_sharded_evict_campaign_recursive_cache():
    """ROADMAP item 1 composition cell: recursive posmap (replicated
    inner trees flushing inside the same owner-masked pass) × tree-top
    cache (replicated planes peeled off the scatter) × the mesh."""
    _run_sharded_campaign(
        dict(posmap_impl="recursive", tree_top_cache_levels=2),
        seed=8200, shards=2, e=4,
    )


@pytest.mark.slow
def test_sharded_evict_campaign_saturated_window():
    """Saturation fallback on the mesh: a near-full tiny bus at E=4
    drives flush_target_slots to its n_buckets_padded clamp — the
    owner partition must hold when every chip's mask covers its whole
    local range."""
    from grapevine_tpu.oram.round import flush_target_slots

    kw = dict(max_messages=16, mailbox_cap=16, batch_size=8,
              stash_size=96)
    ecfg = EngineConfig.from_config(_evict_cfg(e=4, **kw))
    assert flush_target_slots(ecfg.rec) == ecfg.rec.n_buckets_padded
    _run_sharded_campaign(kw, seed=8300, shards=2, e=4, n_batches=9)
