"""Sharded engine ≡ single-chip engine, bit for bit.

Test pyramid item (5) from SURVEY.md §4: multi-chip = single-chip results
under sharding, on the virtual 8-device CPU mesh (the stand-in for real
hardware, the way the reference CI's SGX_MODE=SW simulator stands in for
SGX, reference .github/workflows/ci.yaml:15-16).
"""

import numpy as np
import jax
import pytest

from grapevine_tpu.config import GrapevineConfig
from grapevine_tpu.engine.batcher import pack_batch
from grapevine_tpu.engine.state import EngineConfig, init_engine
from grapevine_tpu.engine.round_step import engine_round_step
from grapevine_tpu.parallel import make_mesh, make_sharded_step, shard_engine_state
from grapevine_tpu.wire import constants as C
from grapevine_tpu.wire.records import QueryRequest, RequestRecord

NOW = 1_700_000_000

def make_cfg(cipher_rounds: int, cipher_impl: str = "jnp") -> GrapevineConfig:
    return GrapevineConfig(
        max_messages=64,
        max_recipients=8,
        mailbox_cap=4,
        batch_size=4,
        stash_size=64,
        bucket_cipher_rounds=cipher_rounds,
        bucket_cipher_impl=cipher_impl,
    )


def key(n: int) -> bytes:
    return bytes([n, n ^ 0x5A]) + b"\x01" * 30


def req(rt, auth, msg_id=C.ZERO_MSG_ID, recipient=C.ZERO_PUBKEY, tag=0):
    return QueryRequest(
        request_type=rt,
        auth_identity=auth,
        auth_signature=b"\x01" * C.SIGNATURE_SIZE,
        record=RequestRecord(
            msg_id=msg_id,
            recipient=recipient,
            payload=bytes([tag & 0xFF]) * C.PAYLOAD_SIZE,
        ),
    )


@pytest.mark.parametrize(
    "cipher_rounds,n_dev,impl",
    [
        # real equality cases, ~35 s each on the timesliced CPU mesh —
        # they ride -m slow to keep tier-1 inside its 870 s budget
        # (they only became runnable with the shard_map compat shim —
        # before that the whole set failed at import-time attribute;
        # run `pytest -m slow tests/test_parallel.py` for the sweep).
        pytest.param(0, 2, "jnp", marks=pytest.mark.slow),
        pytest.param(0, 8, "jnp", marks=pytest.mark.slow),
        pytest.param(8, 8, "jnp", marks=pytest.mark.slow),
        pytest.param(8, 4, "jnp", marks=pytest.mark.slow),
        pytest.param(8, 8, "pallas", marks=pytest.mark.slow),
    ],
)
def test_sharded_step_matches_single_chip(cipher_rounds, n_dev, impl):
    """Sharded ≡ single-chip at 2/4/8-way meshes, with the at-rest
    bucket cipher both off and on (the cipher's nonce arrays are sharded
    along the bucket axis like the trees), and the fused Pallas cipher
    kernel running inside shard_map (the pod + pallas combination)."""
    assert len(jax.devices()) >= 8, "conftest forces an 8-device CPU mesh"
    ecfg = EngineConfig.from_config(make_cfg(cipher_rounds, impl))

    state = init_engine(ecfg, seed=3)
    single = jax.jit(engine_round_step, static_argnums=(0,))

    mesh = make_mesh(jax.devices()[:n_dev])
    sstate = shard_engine_state(init_engine(ecfg, seed=3), mesh)
    sstep = make_sharded_step(ecfg, mesh)

    a, b, c = key(1), key(2), key(3)
    batches = [
        [req(C.REQUEST_TYPE_CREATE, a, recipient=b, tag=7),
         req(C.REQUEST_TYPE_CREATE, a, recipient=c, tag=8),
         req(C.REQUEST_TYPE_CREATE, c, recipient=b, tag=9)],
        [req(C.REQUEST_TYPE_READ, b),
         req(C.REQUEST_TYPE_DELETE, c),
         req(C.REQUEST_TYPE_READ, b, msg_id=b"\x99" * 16)],
        [req(C.REQUEST_TYPE_DELETE, b),
         req(C.REQUEST_TYPE_READ, b),
         req(C.REQUEST_TYPE_CREATE, b, recipient=a, tag=10)],
    ]

    for i, reqs in enumerate(batches):
        batch = pack_batch(reqs, ecfg.batch_size, NOW + i)
        state, resp1, tr1 = single(ecfg, state, batch)
        sstate, resp2, tr2 = sstep(sstate, batch)
        for k in resp1:
            assert np.array_equal(np.asarray(resp1[k]), np.asarray(resp2[k])), (
                f"batch {i}: response field {k} diverged"
            )
        assert np.array_equal(np.asarray(tr1), np.asarray(tr2)), (
            f"batch {i}: transcript diverged"
        )

    # full final state equality, including both bucket trees
    flat1, _ = jax.tree.flatten(state)
    flat2, _ = jax.tree.flatten(sstate)
    for x, y in zip(flat1, flat2):
        assert np.array_equal(np.asarray(x), np.asarray(y))
