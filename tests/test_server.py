"""End-to-end gRPC loopback: Auth handshake, challenge lockstep, signed
CRUD through the encrypted channel, cross-client batching."""

import threading

import grpc
import pytest

from grapevine_tpu.config import GrapevineConfig
from grapevine_tpu.server.client import GrapevineClient
from grapevine_tpu.server.service import GrapevineServer
from grapevine_tpu.server.uri import GrapevineUri
from grapevine_tpu.wire import constants as C

CFG = GrapevineConfig(bucket_cipher_rounds=0, 
    max_messages=64, max_recipients=8, mailbox_cap=8, batch_size=4, stash_size=64
)


@pytest.fixture(scope="module")
def server():
    srv = GrapevineServer(CFG, seed=2, max_wait_ms=5.0, clock=lambda: 1_700_000_000)
    port = srv.start("insecure-grapevine://127.0.0.1:0")
    yield srv, port
    srv.stop()


def make_client(port, seed_byte):
    c = GrapevineClient(
        f"insecure-grapevine://127.0.0.1:{port}", identity_seed=bytes([seed_byte]) * 32
    )
    c.auth()
    return c


def pl(text: bytes) -> bytes:
    return text.ljust(C.PAYLOAD_SIZE, b"\x00")


def test_uri_parsing():
    u = GrapevineUri.parse("grapevine://example.com")
    assert (u.host, u.port, u.use_tls) == ("example.com", 443, True)
    u = GrapevineUri.parse("insecure-grapevine://127.0.0.1:0")
    assert (u.host, u.port, u.use_tls) == ("127.0.0.1", 0, False)
    u = GrapevineUri.parse("insecure-grapevine://box")
    assert u.port == 3229
    with pytest.raises(ValueError):
        GrapevineUri.parse("http://example.com")


def test_end_to_end_messaging(server):
    _, port = server
    alice = make_client(port, 1)
    bob = make_client(port, 2)

    r = alice.create(bob.public_key, pl(b"hello bob"))
    assert r.status_code == C.STATUS_CODE_SUCCESS
    mid = r.record.msg_id
    assert mid != C.ZERO_MSG_ID

    r = bob.read()
    assert r.status_code == C.STATUS_CODE_SUCCESS
    assert r.record.payload.startswith(b"hello bob")
    assert r.record.sender == alice.public_key

    r = bob.update(mid, bob.public_key, pl(b"edited"))
    assert r.status_code == C.STATUS_CODE_SUCCESS

    r = alice.read(mid)
    assert r.record.payload.startswith(b"edited")

    r = bob.delete()  # pop next
    assert r.status_code == C.STATUS_CODE_SUCCESS
    assert bob.read().status_code == C.STATUS_CODE_NOT_FOUND

    # third client sees nothing
    eve = make_client(port, 3)
    assert eve.read(mid).status_code == C.STATUS_CODE_NOT_FOUND
    for c in (alice, bob, eve):
        c.close()


def test_challenge_lockstep_many_requests(server):
    """Dozens of requests on one session: RNGs must stay in sync."""
    _, port = server
    c = make_client(port, 4)
    me = c.public_key
    for i in range(8):  # mailbox cap in CFG
        assert c.create(me, pl(b"x%d" % i)).status_code == C.STATUS_CODE_SUCCESS
    assert (
        c.create(me, pl(b"over")).status_code
        == C.STATUS_CODE_TOO_MANY_MESSAGES_FOR_RECIPIENT
    )
    seen = set()
    for _ in range(8):
        r = c.delete()
        assert r.status_code == C.STATUS_CODE_SUCCESS
        seen.add(r.record.payload[:2])
    assert len(seen) == 8
    c.close()


def test_concurrent_clients_batched(server):
    """Multiple sessions firing in parallel land in shared engine rounds."""
    _, port = server
    clients = [make_client(port, 10 + i) for i in range(4)]
    target = clients[0].public_key
    errors = []

    def worker(c):
        try:
            for _ in range(2):  # 3 workers x 2 < mailbox cap 8
                assert c.create(target, pl(b"cc")).status_code == C.STATUS_CODE_SUCCESS
        except Exception as exc:  # pragma: no cover
            errors.append(exc)

    threads = [threading.Thread(target=worker, args=(c,)) for c in clients[1:]]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    # 6 messages queued for clients[0]
    n = 0
    while clients[0].delete().status_code == C.STATUS_CODE_SUCCESS:
        n += 1
    assert n == 6
    for c in clients:
        c.close()


def test_bad_signature_and_unknown_channel_rejected(server):
    _, port = server
    c = make_client(port, 30)
    # skipping a challenge draw desyncs the client: next request must fail
    c._challenge.next_challenge()
    with pytest.raises(grpc.RpcError) as err:
        c.create(c.public_key, pl(b"desync"))
    assert err.value.code() == grpc.StatusCode.UNAUTHENTICATED
    c.close()

    # unknown channel id
    c2 = GrapevineClient(f"insecure-grapevine://127.0.0.1:{port}", b"\x05" * 32)
    c2._channel_id = b"\x99" * 32
    from grapevine_tpu.wire import protowire as pw

    with pytest.raises(grpc.RpcError) as err:
        c2._query_rpc(
            pw.encode_envelope(
                pw.EnvelopeMessage(channel_id=c2._channel_id, data=b"\x00" * 64)
            )
        )
    assert err.value.code() == grpc.StatusCode.UNAUTHENTICATED
    c2.close()


def test_hard_errors_are_grpc_errors(server):
    _, port = server
    c = make_client(port, 31)
    with pytest.raises(grpc.RpcError) as err:
        c.update(C.ZERO_MSG_ID, c.public_key, pl(b"x"))  # zero-id update
    assert err.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    c.close()


def test_ipv6_address_brackets():
    u = GrapevineUri.parse("insecure-grapevine://[::1]:3229")
    assert u.address == "[::1]:3229"


def test_session_eviction_cap():
    srv = GrapevineServer(CFG, seed=9, max_sessions=3)
    port = srv.start("insecure-grapevine://127.0.0.1:0")
    try:
        clients = [make_client(port, 40 + i) for i in range(4)]
        # the first session was evicted when the 4th authenticated
        with pytest.raises(grpc.RpcError) as err:
            clients[0].read()
        assert err.value.code() == grpc.StatusCode.UNAUTHENTICATED
        # newest session still works
        assert clients[3].read().status_code == C.STATUS_CODE_NOT_FOUND
        for c in clients:
            c.close()
    finally:
        srv.stop()


def test_scheduler_bisection_rejects_only_bad_signatures():
    """A round mixing valid and garbage signatures must reject exactly
    the garbage (via batch bisection) and serve the rest."""
    import threading

    from grapevine_tpu.config import GrapevineConfig
    from grapevine_tpu.engine.batcher import GrapevineEngine
    from grapevine_tpu.server.scheduler import AuthFailure, BatchScheduler
    from grapevine_tpu.session import schnorrkel
    from grapevine_tpu.wire import constants as C
    from grapevine_tpu.wire.records import QueryRequest, RequestRecord

    cfg = GrapevineConfig(
        bucket_cipher_rounds=0,
        max_messages=64,
        max_recipients=8,
        mailbox_cap=4,
        batch_size=8,
    )
    engine = GrapevineEngine(cfg, seed=21)
    sched = BatchScheduler(engine, max_wait_ms=50.0)
    try:
        results: dict[int, object] = {}

        def submit(i, good):
            # sign with the scheduler's default scheme (sr25519)
            sk, pub = schnorrkel.keygen(bytes([i + 1]) * 32)
            msg = bytes([i]) * 32
            sig = (
                schnorrkel.sign(sk, C.GRAPEVINE_CHALLENGE_SIGNING_CONTEXT, msg)
                if good
                else b"\x42" * 64
            )
            req = QueryRequest(
                request_type=C.REQUEST_TYPE_CREATE,
                auth_identity=pub,
                auth_signature=sig,
                record=RequestRecord(
                    msg_id=C.ZERO_MSG_ID,
                    recipient=pub,
                    payload=bytes([i]) * C.PAYLOAD_SIZE,
                ),
            )
            auth = (pub, C.GRAPEVINE_CHALLENGE_SIGNING_CONTEXT, msg, sig)
            try:
                results[i] = sched.submit(req, auth=auth)
            except AuthFailure as e:
                results[i] = e

        goods = {0, 2, 3, 5}
        threads = [
            threading.Thread(target=submit, args=(i, i in goods))
            for i in range(6)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i in range(6):
            if i in goods:
                assert results[i].status_code == C.STATUS_CODE_SUCCESS, i
            else:
                assert isinstance(results[i], AuthFailure), i
    finally:
        sched.close()


def test_replayed_and_injected_envelopes_do_not_desync_session(server):
    """A captured Query envelope replayed verbatim, or garbage injected
    with a valid (cleartext) channel_id, must be rejected WITHOUT
    consuming a lockstep challenge or advancing cipher state — otherwise
    one injected request permanently desyncs the legitimate client
    (an injection-DoS; see service._query). The session keeps working."""
    from grapevine_tpu.wire import protowire as pw
    from grapevine_tpu.wire.records import QueryRequest, RequestRecord

    srv, port = server
    c = make_client(port, 41)
    peer = make_client(port, 42)

    # hand-rolled query (mirrors client._query) so we hold the raw bytes
    challenge = c._challenge.next_challenge()
    req = QueryRequest(
        request_type=C.REQUEST_TYPE_CREATE,
        auth_identity=c.public_key,
        auth_signature=c._scheme.sign(
            c.sk, C.GRAPEVINE_CHALLENGE_SIGNING_CONTEXT, challenge
        ),
        record=RequestRecord(
            recipient=peer.public_key, payload=pl(b"captured")
        ),
    )
    raw = pw.encode_envelope(
        pw.EnvelopeMessage(
            channel_id=c._channel_id, data=c._channel.encrypt(req.pack())
        )
    )
    reply = pw.decode_envelope(c._query_rpc(raw))
    from grapevine_tpu.wire.records import QueryResponse

    r = QueryResponse.unpack(c._channel.decrypt(reply.data))
    assert r.status_code == C.STATUS_CODE_SUCCESS

    # 1. replay the captured envelope verbatim
    with pytest.raises(grpc.RpcError) as exc:
        c._query_rpc(raw)
    assert exc.value.code() == grpc.StatusCode.UNAUTHENTICATED

    # 2. inject garbage under the same (cleartext) channel id
    forged = pw.encode_envelope(
        pw.EnvelopeMessage(channel_id=c._channel_id, data=b"\x13" * 256)
    )
    with pytest.raises(grpc.RpcError) as exc:
        c._query_rpc(forged)
    assert exc.value.code() == grpc.StatusCode.UNAUTHENTICATED

    # 3. the legitimate session is fully intact: lockstep + counters
    r = peer.read()
    assert r.status_code == C.STATUS_CODE_SUCCESS
    assert r.record.payload == pl(b"captured")
    for _ in range(3):
        assert c.read().status_code in (
            C.STATUS_CODE_SUCCESS,
            C.STATUS_CODE_NOT_FOUND,
        )
