"""The streaming leak monitor has the canaries' teeth (ISSUE 2).

tests/test_leak_canary.py proves the *pytest* detectors catch
deliberately-leaky round variants; these tests prove the *continuous*
monitor (obs/leakmon.py) catches the same leaks when fed round-by-round
like production — every leak built through the public ``oram_round``
parameters, so the monitor is auditing the real round code path:

- the no-remap canary (remap target = current leaf) flips the verdict
  to SUSPECT within 64 rounds at batch 256 (the ISSUE acceptance
  criterion), via the cross-round repeat detector;
- the no-dedup canary (dummy fetches reuse the real leaf) trips the
  same-key collision detector;
- the biased-dummy canary (constant leaf 0) trips the uniformity
  detector;
- 512 honest rounds at batch 256 report PASS on all three detectors
  (the false-positive side of the acceptance criterion);
- the streaming collision counter agrees with the quadratic pytest
  detector; the flight recorder enforces its batch-level schema so a
  dump can never carry logical keys, recipient ids, or per-op
  timestamps.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from grapevine_tpu.obs.flightrec import FlightRecorder
from grapevine_tpu.obs.leakmon import (
    PASS,
    SUSPECT,
    LeakMonitorConfig,
    TranscriptLeakMonitor,
)
from grapevine_tpu.obs.registry import TelemetryLeakError, TelemetryRegistry
from grapevine_tpu.oram.path_oram import OramConfig, init_oram
from grapevine_tpu.oram.round import oram_round
from grapevine_tpu.testing.leakcheck import (
    samekey_collision_counts,
    samekey_leaf_collisions,
    uniformity_z,
    uniformity_z_from_counts,
)

U32 = jnp.uint32

CFG = OramConfig(height=12, value_words=4, stash_size=512)
B = 256  # the acceptance criterion's batch size

#: acceptance-shaped monitor config: production thresholds, a window
#: that spans the whole honest soak
MCFG = LeakMonitorConfig(window_rounds=512)


def _passthrough(vals0, present0):
    return {}, vals0, present0


def _step(state, idxs, nl, dl):
    st, _, leaves = oram_round(CFG, state, idxs, nl, dl, _passthrough)
    return st, leaves


STEP = jax.jit(_step)


def _uniform(key, n=B):
    return jax.random.bits(key, (n,), U32) & U32(CFG.leaves - 1)


def _populated(seed=0):
    state = init_oram(CFG, jax.random.PRNGKey(seed))

    def ins(vals0, present0):
        return {}, jnp.ones_like(vals0), jnp.ones_like(present0)

    key = jax.random.PRNGKey(seed + 100)
    k1, k2 = jax.random.split(key)
    idxs = jnp.arange(B, dtype=U32)
    state, _, _ = oram_round(CFG, state, idxs, _uniform(k1), _uniform(k2), ins)
    return state


def _mon(cfg=MCFG, registry=None):
    return TranscriptLeakMonitor({"oram": CFG.leaves}, cfg, registry)


def _keys_np(idxs):
    """Monitor key ids from round indices: dummies have no key (-1)."""
    k = np.asarray(idxs).astype(np.int64)
    return np.where(k == CFG.dummy_index, -1, k)


def test_no_remap_leak_flips_suspect_within_64_rounds():
    """ISSUE acceptance: a no-remap leaky variant (remap target = the
    key's current leaf, so every re-access repeats its path) is SUSPECT
    within 64 rounds at batch 256."""
    mon = _mon()
    state = _populated()
    # a quarter of the batch re-reads tracked keys each round; the rest
    # is padding — a realistic partially-filled round
    idxs = jnp.where(
        jnp.arange(B) < B // 4, jnp.arange(B, dtype=U32),
        U32(CFG.dummy_index),
    )
    key = jax.random.PRNGKey(2)
    flipped_at = None
    for r in range(64):
        key, k2 = jax.random.split(key)
        nl = state.posmap[idxs]  # THE LEAK: remap to the current leaf
        state, leaves = STEP(state, idxs, nl, _uniform(k2))
        mon.observe("oram", _keys_np(idxs), np.asarray(leaves))
        if mon.verdict()["verdict"] == SUSPECT:
            flipped_at = r + 1
            break
    assert flipped_at is not None and flipped_at <= 64, (
        f"no-remap leak not flagged within 64 rounds (verdict "
        f"{mon.verdict()})"
    )
    tripped = [
        d["name"] for d in mon.verdict()["detectors"]
        if d["verdict"] == SUSPECT
    ]
    assert "cross_round_repeat" in tripped


def test_no_dedup_leak_trips_collision_detector():
    """Dummy fetches reusing the key's real leaf correlate same-key ops
    within a round — the collision detector's case."""
    mon = _mon()
    state = _populated()
    idxs = jnp.zeros((B,), U32)  # every op touches key 0
    key = jax.random.PRNGKey(3)
    for _ in range(4):
        key, k1 = jax.random.split(key)
        real_leaf = jnp.broadcast_to(state.posmap[0], (B,))
        state, leaves = STEP(state, idxs, _uniform(k1), real_leaf)
        mon.observe("oram", _keys_np(idxs), np.asarray(leaves))
    v = mon.verdict()
    coll = next(
        d for d in v["detectors"] if d["name"] == "samekey_collision"
    )
    assert v["verdict"] == SUSPECT and coll["verdict"] == SUSPECT, v
    assert coll["statistic"] > 0.9  # every same-key pair collides


def test_biased_dummy_leak_trips_uniformity_detector():
    """All-padding rounds fetching constant leaf 0 skew the pooled
    histogram — the uniformity detector's case."""
    mon = _mon()
    state = _populated()
    idxs = jnp.full((B,), U32(CFG.dummy_index))
    key = jax.random.PRNGKey(4)
    for _ in range(8):
        key, k1 = jax.random.split(key)
        state, leaves = STEP(
            state, idxs, _uniform(k1), jnp.zeros((B,), U32)
        )
        mon.observe("oram", _keys_np(idxs), np.asarray(leaves))
    v = mon.verdict()
    unif = next(d for d in v["detectors"] if d["name"] == "uniformity")
    assert unif["verdict"] == SUSPECT, v
    assert unif["statistic"] > 50  # orders of magnitude past threshold


def test_honest_soak_512_rounds_passes_all_detectors():
    """ISSUE acceptance: 512 honest rounds at batch 256 PASS on all
    three detectors — with every detector holding enough samples that
    PASS means 'measured honest', not 'insufficient evidence'."""
    reg = TelemetryRegistry()
    mon = _mon(registry=reg)
    state = _populated()
    # mixed traffic: re-read a rotating slice of keys (cross-round
    # repeats + same-key pairs), half the batch padding
    key = jax.random.PRNGKey(5)
    for r in range(512):
        key, k1, k2 = jax.random.split(key, 3)
        base = (r * 16) % B
        track = (jnp.arange(B, dtype=U32) + U32(base)) % U32(B)
        # duplicate keys within the round: slots 2i and 2i+1 share a key
        track = track // U32(2)
        idxs = jnp.where(
            jnp.arange(B) < B // 2, track, U32(CFG.dummy_index)
        )
        state, leaves = STEP(state, idxs, _uniform(k1), _uniform(k2))
        mon.observe("oram", _keys_np(idxs), np.asarray(leaves))
    v = mon.verdict()
    assert v["verdict"] == PASS, v
    for d in v["detectors"]:
        assert d["verdict"] == PASS, d
        assert d["samples"] >= d["min_samples"], (
            f"{d['name']}: PASS by insufficient evidence, not by "
            f"measurement ({d['samples']} < {d['min_samples']})"
        )
    # aggregate gauges exported, sane
    assert reg.get("grapevine_leakmon_uniformity_z") is not None
    z = reg.get("grapevine_leakmon_uniformity_z").get(tree="oram")
    assert abs(z) < 8


def test_streaming_collision_counts_match_quadratic_detector():
    """The O(B log B) windowed counter is the same statistic as the
    all-pairs pytest detector."""
    rng = np.random.default_rng(11)
    for _ in range(20):
        keys = rng.integers(0, 12, size=64)
        leaves = rng.integers(0, 16, size=64)
        coll, pairs = samekey_collision_counts(keys, leaves)
        assert coll == samekey_leaf_collisions(keys, leaves)
        same = keys[:, None] == keys[None, :]
        upper = np.triu(np.ones_like(same, dtype=bool), k=1)
        assert pairs == int(np.sum(same & upper))
    # the -1 no-key sentinel is excluded
    coll, pairs = samekey_collision_counts(
        np.array([-1, -1, 3, 3]), np.array([5, 5, 7, 7])
    )
    assert (coll, pairs) == (1, 1)


def test_uniformity_from_counts_matches_pooled_detector():
    rng = np.random.default_rng(13)
    leaves = rng.integers(0, 4096, size=8192)
    z_pooled = uniformity_z(leaves, 4096, bins=16)
    counts = np.bincount(leaves * 16 // 4096, minlength=16)
    assert uniformity_z_from_counts(counts) == pytest.approx(z_pooled)


def test_window_slides_and_verdict_recovers():
    """Old rounds age out: a burst of leaky rounds followed by honest
    traffic drains the window and the verdict returns to PASS — the
    re-baseline behavior the runbook describes."""
    cfg = LeakMonitorConfig(window_rounds=8, min_opportunities=4)
    mon = TranscriptLeakMonitor({"oram": 4096}, cfg)
    # leaky burst: one key repeating its leaf every round
    for _ in range(8):
        mon.observe("oram", np.zeros(4, np.int64), np.full(4, 9))
    assert mon.verdict()["verdict"] == SUSPECT
    rng = np.random.default_rng(7)
    for _ in range(16):
        mon.observe(
            "oram",
            np.arange(4, dtype=np.int64),
            rng.integers(0, 4096, size=4),
        )
    assert mon.verdict()["verdict"] == PASS


def test_undeclared_stream_raises():
    mon = _mon()
    with pytest.raises(KeyError):
        mon.observe("nope", None, np.zeros(4, np.int64))


# ---------------------------------------------------------------------
# flight recorder leak policy (ISSUE satellite: tier-1 proof the dump
# carries no logical keys, recipient ids, or per-op timestamps)
# ---------------------------------------------------------------------


def test_flight_recorder_dump_is_batch_level_only():
    """Schema enforcement: the ring rejects any field that could carry
    per-op or per-client data, so no dump ever can."""
    fr = FlightRecorder(capacity=4)
    ok = {
        "seq": 1, "t_mono_s": 12.5, "batch_size": 256, "n_real": 100,
        "fill": 0.39, "phase_s": {"dispatch": 0.001, "round": 0.004},
        "stats": {"rec": {"uniformity_z": 0.3, "pooled_leaves": 512}},
        "verdict": "PASS",
    }
    fr.record(ok)
    # a recursive posmap engine's rounds carry the internal-ORAM streams
    # too (leakmon *_pm, PR 7) — the schema must admit them or every
    # round with --posmap-impl recursive raises in the leakmon worker
    fr.record({**ok, "stats": {
        t: {"uniformity_z": 0.1, "pooled_leaves": 64}
        for t in ("rec", "mb", "rec_pm", "mb_pm")
    }})
    for bad in (
        {"recipient": "deadbeef"},            # identity field
        {"msg_id": 7},                        # message id field
        {"keys": [1, 2, 3]},                  # logical keys
        {"op_timestamps": [0.1, 0.2]},        # per-op timestamps
        {**ok, "seq": [1, 2]},                # array-valued scalar slot
        {**ok, "phase_s": {"op_0": 0.1}},     # per-op phase key
        {**ok, "stats": {"client": {}}},      # per-client stat tree
    ):
        with pytest.raises(TelemetryLeakError):
            fr.record(bad)
    # the dump round-trips as JSON and carries only schema'd fields
    dump = json.loads(fr.dump_json())
    assert dump["retained"] == 2  # the ok summary + the *_pm one
    from grapevine_tpu.obs.flightrec import ALLOWED_FIELDS

    for summary in dump["rounds"]:
        assert set(summary) <= ALLOWED_FIELDS
    text = fr.dump_json()
    for forbidden in ("recipient", "msg_id", "auth", "client", "op_"):
        assert forbidden not in text


def test_flush_phase_schema_has_teeth():
    """The delayed-eviction observability surface (ISSUE 15): ``flush``
    is a declared phase across all three vocabularies — the flight
    recorder's ``phase_s`` schema, the canonical PHASES tuple, and the
    phase histogram's declared values — while window-positioned
    variants (the shape a schedule channel would take) are rejected.
    The pop-heavy E=4 soak that exercises this surface end-to-end is
    tests/test_evict.py::test_evict_leakmon_pop_heavy_and_probe."""
    from grapevine_tpu.engine.metrics import EngineMetrics
    from grapevine_tpu.obs.phases import PHASES

    assert "flush" in PHASES
    fr = FlightRecorder(capacity=2)
    fr.record({"seq": 1, "verdict": "PASS",
               "phase_s": {"flush": 0.002, "round": 0.01}})
    with pytest.raises(TelemetryLeakError):
        fr.record({"seq": 2, "verdict": "PASS",
                   "phase_s": {"flush_w3": 0.002}})
    em = EngineMetrics()
    em.observe_phase("flush", 0.001)
    with pytest.raises(TelemetryLeakError):
        em.observe_phase("flush_w3", 0.001)
    # the buffer canaries are label-free scrape-cadence sums by policy
    for name in ("grapevine_evict_buffer_occupancy",
                 "grapevine_evict_buffer_high_water"):
        m = em.registry.get(name)
        assert m is not None and not m.label_keys


def test_flight_recorder_ring_wraps():
    fr = FlightRecorder(capacity=3)
    for i in range(7):
        fr.record({"seq": i, "verdict": "PASS"})
    d = fr.dump()
    assert d["recorded_total"] == 7 and d["retained"] == 3
    assert [r["seq"] for r in d["rounds"]] == [4, 5, 6]


def test_flight_recorder_dump_to_file(tmp_path):
    fr = FlightRecorder(capacity=2)
    fr.record({"seq": 0, "verdict": "SUSPECT"})
    path = str(tmp_path / "flight.json")
    fr.dump_to(path)
    with open(path, encoding="utf-8") as fh:
        payload = json.load(fh)
    assert payload["rounds"][0]["verdict"] == "SUSPECT"
