"""Delayed batched eviction — equivalence, cadence, canary, audit
(ISSUE 15 tentpole).

The contract of ``GrapevineConfig.evict_every = E`` (oram/round.py,
ROADMAP item 1 — the scatter+encrypt half of the round amortized 1/E):

1. responses bit-identical E=1 ↔ E>1 ↔ oracle at EVERY round, and the
   final LOGICAL state — live blocks, values, positions, freelist,
   scalars — bit-identical too (physical placement legitimately
   differs: E=1 evicts per round, E>1 evicts each window's
   deduplicated union of paths; testing/compare.py
   ``assert_logical_content_equal`` is the canonical form);
2. the fetch-only round is index-blind and performs ZERO HBM tree
   scatters; one flush scatters exactly ``flush_target_slots =
   min(E·F·path_len, n_buckets_padded)`` rows per plane
   (tools/check_tree_cache_oblivious.py:check_evict_round_accounting);
3. the buffer is bounded private state with the stash's standing:
   overflow rides the same sticky counter, ``health()`` exposes
   occupancy/capacity, and the ``grapevine_evict_buffer_*`` gauges
   track the near-overflow canary;
4. a buffer-bearing checkpoint can never silently restore into a
   differently-cadenced engine (fingerprint covers E via the per-tree
   window fields), and journal replay — KIND_FLUSH included —
   reproduces crashed runs bit-identically (chaos kill-at-flush);
5. the leak monitor stays PASS on a live E=4 soak (the flush cadence
   is not a timing channel), and the probe-campaign injector still
   flips SUSPECT (tests/test_load_harness.py breadth rides -m slow
   here).

Always-on cost: ONE E=1 + ONE E=4 engine compile (plaintext BASE
geometry, reused across the fast assertions incl. the leakmon soak) +
one tiny near-overflow engine + trace-only audits. Cipher/recursive/
scan-radix pairs, E breadth, chaos, and the scenario-runner soaks ride
``-m slow`` (the PR-5/9/10 tier-1 budget playbook).
"""

from __future__ import annotations

import os
import random
import sys

import numpy as np
import pytest

from test_vphases_scan import (
    BASE,
    NOW,
    _assert_responses_bitequal,
    _gen_batch,
    key,
)

from grapevine_tpu.config import GrapevineConfig
from grapevine_tpu.engine.batcher import GrapevineEngine
from grapevine_tpu.testing.compare import (
    assert_logical_content_equal,
    logical_block_map,
)
from grapevine_tpu.testing.reference import ReferenceEngine
from grapevine_tpu.wire import constants as C

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


def _mk_evict_pair(cfg_kwargs, seed, e=4):
    e1 = GrapevineEngine(
        GrapevineConfig(evict_every=1, **cfg_kwargs), seed=seed
    )
    ee = GrapevineEngine(
        GrapevineConfig(evict_every=e, **cfg_kwargs), seed=seed
    )
    return e1, ee


def _run_evict_campaign(cfg_kwargs, seed, n_batches=6, batch_fill=None,
                        pair=None, sweep=False, e=4):
    """One campaign: E=1/E pair + oracle over mixed batches, responses
    bit-equal per round, zero overflow, logical content equal at the
    end (typically MID-window for the E arm — the content contract
    must hold with live buffer state, not only at flush barriers)."""
    rng = np.random.default_rng(seed)
    e1, ee = pair or _mk_evict_pair(
        cfg_kwargs, seed=int(rng.integers(1 << 30)), e=e
    )
    oracle = None
    if pair is None:
        oracle = ReferenceEngine(
            config=GrapevineConfig(**cfg_kwargs), rng=random.Random(seed)
        )
    idents = [key(i) for i in range(1, 1 + int(rng.integers(2, 6)))]
    live_ids: list[tuple[bytes, bytes]] = []
    bs = cfg_kwargs["batch_size"]
    rounds0 = ee._rounds_since_flush  # reused pairs carry a live window
    for bi in range(n_batches):
        n = batch_fill or int(rng.integers(1, bs + 1))
        reqs = _gen_batch(rng, idents, live_ids, n)
        t = NOW + bi
        r1 = e1.handle_queries(reqs, t)
        re_ = ee.handle_queries(reqs, t)
        _assert_responses_bitequal(r1, re_, f"evict seed {seed} b {bi}")
        h1, he = e1.health(), ee.health()
        assert h1["stash_overflow"] == he["stash_overflow"] == 0
        # window invariant: the host cadence counter tracks the
        # state-side one (the recovery anchor)
        assert he["evict_rounds_since_flush"] == (rounds0 + bi + 1) % e
        occ = he["evict_buffer_occupancy"]
        caps = he["evict_buffer_slots"]
        assert set(occ) >= {"rec", "mb"}
        assert all(occ[k2] <= caps[k2] for k2 in ("rec", "mb"))
        if oracle is not None:
            forced = [
                d.record.msg_id
                if r.request_type == C.REQUEST_TYPE_CREATE
                and d.status_code == C.STATUS_CODE_SUCCESS
                else None
                for r, d in zip(reqs, r1)
            ]
            ro = oracle.handle_batch(reqs, t, forced)
            for j, (d, o) in enumerate(zip(r1, ro)):
                assert d.status_code == o.status_code, (
                    f"evict seed {seed} batch {bi} slot {j}: engine "
                    f"{d.status_code} != oracle {o.status_code}"
                )
                assert d.record.msg_id == o.record.msg_id
                assert d.record.payload == o.record.payload
            assert e1.message_count() == oracle.message_count()
            assert e1.recipient_count() == oracle.recipient_count()
        for r, d in zip(reqs, r1):
            if (r.request_type == C.REQUEST_TYPE_CREATE
                    and d.status_code == C.STATUS_CODE_SUCCESS):
                live_ids.append((d.record.msg_id, r.record.recipient))
            elif (r.request_type == C.REQUEST_TYPE_DELETE
                    and d.status_code == C.STATUS_CODE_SUCCESS):
                live_ids = [
                    (m, o_) for m, o_ in live_ids if m != d.record.msg_id
                ]
    if sweep:
        # mid-window sweep: stale-bucket masking + buffer sweep must
        # keep the two engines' logical content identical
        e1.expire(NOW + 10_000, 5_000)
        ee.expire(NOW + 10_000, 5_000)
    assert_logical_content_equal(
        e1.ecfg, e1.state, ee.ecfg, ee.state, f"evict seed {seed}"
    )
    return e1, ee


# -- always-on: one compiled pair carries every fast assertion ----------


def test_evict_campaign_with_sweep_and_leakmon():
    """The budget-shaped always-on path: ONE E=1 + ONE E=4 engine
    (plaintext BASE geometry) run a randomized oracle campaign crossing
    several flush boundaries, an expiry sweep mid-window, single-op
    batches, and a leakmon soak at E=4 — zero additional compiles
    after the first window."""
    e1, e4 = _run_evict_campaign(BASE, seed=7100, n_batches=9, sweep=True)
    assert e4.evict_every == 4
    assert e4.ecfg.rec.evict_window == 4
    assert e4.ecfg.mb.evict_window == 8  # two mailbox rounds per round

    # single-op batches on the same compiled pair
    _run_evict_campaign(BASE, seed=7101, n_batches=4, batch_fill=1,
                        pair=(e1, e4))

    # the flush really moves content back: after an exact window
    # boundary the buffer is empty and the tree holds the blocks
    # (pad with single READ rounds — an empty request list dispatches
    # no round, so it cannot advance the window)
    from test_vphases_scan import req

    while int(e4.state.rec.ebuf_rounds) % 4:
        e4.handle_queries([req(C.REQUEST_TYPE_READ, key(1))], NOW + 500)
    from grapevine_tpu.oram.path_oram import evict_buffer_occupancy

    assert int(evict_buffer_occupancy(e4.state.rec)) == 0
    assert int(e4.state.rec.ebuf_rounds) == 0

    # the near-overflow canary gauges exist and sampled something
    snap = e4.metrics.registry.snapshot()
    assert "grapevine_evict_buffer_occupancy" in snap
    assert snap["grapevine_evict_buffer_high_water"] > 0
    e4.metrics.registry.audit()  # the new gauges stay batch-level

    # acceptance: leak monitor PASS on a live soak at E=4 — the flush
    # cadence must not become a timing channel
    from grapevine_tpu.obs.leakmon import EngineLeakMonitor, LeakMonitorConfig

    mon = EngineLeakMonitor.for_engine(e4, LeakMonitorConfig(window_rounds=64))
    e4.attach_leakmon(mon)
    rng = np.random.default_rng(79)
    idents = [key(i) for i in range(1, 5)]
    live: list[tuple[bytes, bytes]] = []
    for bi in range(12):
        reqs = _gen_batch(rng, idents, live, 8)
        e4.handle_queries(reqs, NOW + 100 + bi)
    assert mon.flush(), "leak monitor did not drain"
    v = mon.verdict()
    assert v["verdict"] == "PASS", v
    mon.close()


def test_evict_config_validation():
    with pytest.raises(ValueError, match="evict_every"):
        GrapevineConfig(evict_every=0)
    with pytest.raises(ValueError, match="evict_every"):
        GrapevineConfig(commit="op", evict_every=2)
    with pytest.raises(ValueError, match="evict_buffer_slots"):
        GrapevineConfig(evict_buffer_slots=0)
    from grapevine_tpu.engine.state import EngineConfig

    # auto resolves to 1 (per-round eviction) on every backend until
    # tools/tpu_capture.py evict_perf prices the flush overlap on-chip
    auto = EngineConfig.from_config(GrapevineConfig(**BASE))
    assert auto.evict_every == 1
    assert auto.rec.evict_window == 1
    assert auto.rec.evict_buffer_slots == 0
    # E > 1: per-tree windows (rec E, mb 2E — rounds A and C), fetch
    # counts (B, B·D), and clamped auto buffer sizing
    e4 = EngineConfig.from_config(GrapevineConfig(evict_every=4, **BASE))
    assert (e4.rec.evict_window, e4.mb.evict_window) == (4, 8)
    b, d = e4.batch_size, e4.mb_choices
    assert e4.rec.evict_fetch_count == b
    assert e4.mb.evict_fetch_count == b * d
    from grapevine_tpu.oram.path_oram import derive_evict_buffer_slots

    # the clamp: a buffer that can hold every live block never overflows
    assert derive_evict_buffer_slots(64, 4, 8, 4) == 64
    assert e4.rec.evict_buffer_slots == min(
        e4.rec.blocks, 2 * 4 * 4 * b + 4 * b
    )
    # the OramConfig itself refuses inconsistent delayed geometry
    from grapevine_tpu.oram.path_oram import OramConfig

    with pytest.raises(ValueError, match="evict_window"):
        OramConfig(height=3, value_words=4, evict_window=0)
    with pytest.raises(ValueError, match="evict_window > 1"):
        OramConfig(height=3, value_words=4, evict_window=2)
    # flush target arithmetic: the dedup cap IS the amortization
    from grapevine_tpu.oram.round import flush_target_slots

    c = OramConfig(height=3, value_words=4, evict_window=8,
                   evict_fetch_count=16, evict_buffer_slots=64)
    assert flush_target_slots(c) == c.n_buckets_padded  # saturated
    c2 = OramConfig(height=9, value_words=4, evict_window=2,
                    evict_fetch_count=4, evict_buffer_slots=64)
    assert flush_target_slots(c2) == 2 * 4 * c2.path_len  # unsaturated


def test_evict_checkpoint_fingerprint_rejects_cross_e(tmp_path):
    """A buffer-bearing checkpoint must fail loudly against a
    differently-cadenced engine — the plane shapes differ AND the
    fingerprint covers the per-tree windows. Pure serialization."""
    from grapevine_tpu.engine.checkpoint import (
        CheckpointError,
        bytes_to_state,
        engine_fingerprint,
        state_to_bytes,
    )
    from grapevine_tpu.engine.state import EngineConfig, init_engine

    kw = dict(BASE, max_messages=32, batch_size=4)
    ec1 = EngineConfig.from_config(GrapevineConfig(evict_every=1, **kw))
    ec4 = EngineConfig.from_config(GrapevineConfig(evict_every=4, **kw))
    assert engine_fingerprint(ec1) != engine_fingerprint(ec4)
    blob4 = state_to_bytes(ec4, init_engine(ec4, seed=1))
    assert bytes_to_state(ec4, blob4) is not None  # control: self-loads
    with pytest.raises(CheckpointError, match="fingerprint"):
        bytes_to_state(ec1, blob4)


def test_evict_access_schedule_audit():
    """CI gate (trace-only, flat map): the fetch round is index-blind
    and HBM-read-only; one flush scatters exactly the deduplicated
    window — ISSUE-15's acceptance audit, wired into tier-1 next to
    the tree-cache/posmap/telemetry gates."""
    from check_tree_cache_oblivious import check_evict_round_accounting

    out = check_evict_round_accounting(b=8, height=7, k=2, window=2)
    assert out["fetch"]["tree_val"] == [8 * 6]  # B·(plen−k), gathers
    assert out["flush"]["tree_val"] == [2 * 8 * 8]  # t rows, scatters


def test_sharded_evict_access_schedule_audit():
    """ISSUE-18 trace gate (compile-free, always-on): per shard, the
    sharded fetch round is index-blind and HBM-read-only at the uniform
    B·(path_len−k) working-set shape, and the sharded flush's scatter
    ops carry all t rows on every chip (owner-masked lanes drop via
    out-of-range targets — the static shape never shrinks). The runtime
    owner-partition claim and its seeded mutant ride -m slow."""
    from check_tree_cache_oblivious import check_sharded_evict_accounting

    out = check_sharded_evict_accounting(runtime=False)
    assert out["shards"] == 2
    assert out["fetch"]["tree_val"] == [6 * 6]  # B·(plen−k) per shard
    assert out["flush"]["tree_val"] == [2 * 6 * 8]  # all t rows per shard


@pytest.mark.slow
def test_sharded_evict_owner_partition_and_mutant():
    """Runtime halves of the ISSUE-18 audit, both directions: (a) every
    bucket the single-chip flush writes is written by exactly its
    heap-range owner shard and per-shard counts sum to the single-chip
    count; (b) the seeded unmasked-scatter mutant (shard mask dropped,
    wrapped local targets) must FAIL the partition check."""
    from check_tree_cache_oblivious import check_sharded_evict_accounting

    out = check_sharded_evict_accounting()
    assert sum(out["per_shard_written"]) == out["oracle_written"]
    with pytest.raises(AssertionError, match="owner partition|diverges"):
        check_sharded_evict_accounting(_unmasked_scatter=True)


def test_evict_buffer_overflow_canary():
    """Directed near-overflow: an explicitly undersized buffer + stash
    must trip the shared sticky overflow counter and surface through
    health() — silent block loss is the one failure mode the canary
    exists to catch. (Responses after overflow are undefined; this
    test only asserts the alarm fires.)"""
    from test_vphases_scan import req

    cfg = GrapevineConfig(
        **dict(BASE, stash_size=8), evict_every=8, evict_buffer_slots=2,
    )
    eng = GrapevineEngine(cfg, seed=3)
    assert eng.ecfg.rec.evict_buffer_slots == 2
    idents = [key(i) for i in range(1, 6)]
    h = eng.health()
    for bi in range(6):  # pure creates: live blocks pile into a
        reqs = [         # 2-row buffer + 8-row stash, no flush due
            req(C.REQUEST_TYPE_CREATE, idents[j % 5],
                recipient=idents[(j + 1) % 5], tag=bi * 8 + j)
            for j in range(8)
        ]
        eng.handle_queries(reqs, NOW + bi)
        h = eng.health()
        if h["stash_overflow"] > 0:
            break
    assert h["stash_overflow"] > 0, (
        "2-slot buffer + 8-slot stash under create-heavy traffic never "
        "overflowed — the canary cannot fire"
    )
    occ = h["evict_buffer_occupancy"]
    assert occ["rec"] <= 2 and occ["mb"] <= 2
    # the gauge sums the trees (batch-level): capped by rec C + mb C
    assert 0 < eng.metrics.registry.snapshot()[
        "grapevine_evict_buffer_high_water"
    ] <= 4


def test_evict_recovery_mid_window(tmp_path):
    """Durability at E=4: close mid-window, reopen (journal replay
    re-executes rounds AND KIND_FLUSH records through the jitted
    programs), continue, and land bit-identical to an uninterrupted
    engine — buffer planes, window counter, and placement included."""
    import hashlib

    from grapevine_tpu.config import DurabilityConfig
    from grapevine_tpu.engine.checkpoint import state_to_bytes

    kw = dict(BASE, max_messages=32, batch_size=4)
    idents = [key(i) for i in range(1, 4)]

    def batches(n):
        r = np.random.default_rng(31)
        live: list = []
        return [_gen_batch(r, idents, live, 4) for _ in range(n)]

    evs = batches(6)  # 6 rounds at E=4: one flush + a 2-round tail
    d = str(tmp_path / "state")
    dc = DurabilityConfig(state_dir=d, checkpoint_every_rounds=3)
    eng = GrapevineEngine(
        GrapevineConfig(evict_every=4, **kw), seed=2, durability=dc
    )
    for i, reqs in enumerate(evs[:4]):
        eng.handle_queries(reqs, NOW + i)
    eng.close()  # dies mid-window (2 rounds buffered)

    eng2 = GrapevineEngine(
        GrapevineConfig(evict_every=4, **kw), seed=2,
        durability=DurabilityConfig(state_dir=d, checkpoint_every_rounds=3),
    )
    assert eng2._rounds_since_flush == int(eng2.state.rec.ebuf_rounds)
    for i, reqs in enumerate(evs[4:]):
        eng2.handle_queries(reqs, NOW + 4 + i)
    h_rec = hashlib.sha256(
        state_to_bytes(eng2.ecfg, eng2.state)
    ).hexdigest()
    eng2.close()

    ref = GrapevineEngine(GrapevineConfig(evict_every=4, **kw), seed=2)
    for i, reqs in enumerate(evs):
        ref.handle_queries(reqs, NOW + i)
    h_ref = hashlib.sha256(
        state_to_bytes(ref.ecfg, ref.state)
    ).hexdigest()
    assert h_rec == h_ref, (
        "recovered + continued state diverges from the uninterrupted "
        "run — journal replay did not reproduce the flush cadence"
    )


def test_evict_replay_refuses_cross_e_journal(tmp_path):
    """Journal-only recovery (no checkpoint) must refuse a journal
    written under a different cadence: a KIND_FLUSH frame replayed on
    an evict_every=1 engine raises JournalError instead of crashing
    (or silently corrupting the window ledger)."""
    from grapevine_tpu.config import DurabilityConfig
    from grapevine_tpu.engine.journal import JournalError

    kw = dict(BASE, max_messages=32, batch_size=4)
    d = str(tmp_path / "xe")
    eng = GrapevineEngine(
        GrapevineConfig(evict_every=2, **kw), seed=2,
        durability=DurabilityConfig(state_dir=d,
                                    checkpoint_every_rounds=1 << 20),
    )
    rng = np.random.default_rng(41)
    idents = [key(1), key(2)]
    for bi in range(2):  # 2 rounds at E=2 -> one flush frame journaled
        eng.handle_queries(_gen_batch(rng, idents, [], 4), NOW + bi)
    eng.close()
    with pytest.raises(JournalError, match="evict_every"):
        GrapevineEngine(
            GrapevineConfig(evict_every=1, **kw), seed=2,
            durability=DurabilityConfig(state_dir=d,
                                        checkpoint_every_rounds=1 << 20),
        )


# -- slow: breadth, cipher, recursive posmap, chaos, scenario soaks -----


@pytest.mark.slow
def test_evict_replay_refuses_missing_flush_frames(tmp_path):
    """The converse cadence guard: an evict_every=1 journal (no flush
    frames) replayed by an E>1 engine raises once more rounds than one
    window replay without a flush — instead of silently clamping the
    window ledger and overflowing the buffer."""
    from grapevine_tpu.config import DurabilityConfig
    from grapevine_tpu.engine.journal import JournalError

    kw = dict(BASE, max_messages=32, batch_size=4)
    d = str(tmp_path / "xe1")
    eng = GrapevineEngine(
        GrapevineConfig(evict_every=1, **kw), seed=2,
        durability=DurabilityConfig(state_dir=d,
                                    checkpoint_every_rounds=1 << 20),
    )
    rng = np.random.default_rng(43)
    idents = [key(1), key(2)]
    for bi in range(4):  # > one E=2 window of rounds, zero flush frames
        eng.handle_queries(_gen_batch(rng, idents, [], 4), NOW + bi)
    eng.close()
    with pytest.raises(JournalError, match="different evict_every"):
        GrapevineEngine(
            GrapevineConfig(evict_every=2, **kw), seed=2,
            durability=DurabilityConfig(state_dir=d,
                                        checkpoint_every_rounds=1 << 20),
        )


@pytest.mark.slow
def test_evict_campaign_cipher_on():
    """The at-rest cipher pair at E=2: fetch rounds decrypt-only, the
    flush re-keys the deduplicated window — logical content identity
    must hold end to end, sweep re-key included."""
    cfg = dict(BASE, bucket_cipher_rounds=8)
    _run_evict_campaign(cfg, seed=7300, n_batches=5, sweep=True, e=2)


@pytest.mark.slow
def test_evict_campaign_recursive_posmap():
    """ROADMAP item 1 ∘ item 5: delayed eviction applied to the payload
    trees AND the recursive posmap's internal trees (their buffers
    flush inside the same oram_flush pass) stays content-identical,
    leaf-metadata planes included."""
    cfg = dict(BASE, posmap_impl="recursive", bucket_cipher_rounds=8)
    _run_evict_campaign(cfg, seed=7400, n_batches=4, sweep=True, e=4)


@pytest.mark.slow
def test_evict_campaign_scan_radix_e8():
    """The delayed round composes with the scan/radix machinery, at the
    widest shipped window (E=8 — two full windows crossed)."""
    cfg = dict(BASE, vphases_impl="scan", sort_impl="radix")
    _run_evict_campaign(cfg, seed=7500, n_batches=17, e=8)


@pytest.mark.slow
def test_evict_campaign_with_tree_cache_interaction():
    """Tree-top cache × delayed eviction: cached top buckets go stale
    within a window (their rows migrate to the buffer) and get
    rewritten at flush via the heap-prefix peel — content identity
    and zero overflow across both knobs."""
    cfg = dict(BASE, tree_top_cache_levels=2, bucket_cipher_rounds=8)
    _run_evict_campaign(cfg, seed=7600, n_batches=6, sweep=True, e=4)


@pytest.mark.slow
def test_chaos_kill_at_flush():
    """SIGKILL trials aimed at the flush crash windows, at pipeline
    depth 2 (the ISSUE-15 acceptance): recovery replays journal order
    — KIND_FLUSH included — and every response hash + the final state
    stay bit-identical to the uninterrupted E=4 oracle, leakmon
    PASS."""
    import chaos_run

    args = chaos_run.parse_args(
        ["--events", "14", "--evict-every", "4", "--pipeline-depth", "2",
         "--seed", "47", "--checkpoint-every", "5"]
    )
    modes = ["flush.pre_dispatch", "flush.post_dispatch", "timer"]
    failures = chaos_run.run_trials(0, args, modes=modes)
    assert not failures, "\n".join(failures)


@pytest.mark.slow
def test_evict_leakmon_pop_heavy_and_probe():
    """The ISSUE-15 leakmon soak: the PR-9 pop-heavy mailbox-drain
    scenario runs PASS at E=4 (the op-independent flush cadence adds
    no timing channel even under drain-shaped traffic), and the
    probe-campaign injector still flips SUSPECT — detection power is
    not degraded by the extra flush dispatches."""
    from grapevine_tpu.load import (
        ProbeCampaignInjector,
        ScenarioRunner,
        adversarial_probe,
        pop_heavy_drain,
    )
    from grapevine_tpu.obs.leakmon import (
        PASS,
        SUSPECT,
        EngineLeakMonitor,
        LeakMonitorConfig,
    )
    from grapevine_tpu.server.scheduler import BatchScheduler

    engine = GrapevineEngine(
        GrapevineConfig(
            evict_every=4,
            **dict(BASE, max_messages=256, max_recipients=32,
                   batch_size=8, mailbox_cap=8),
        ),
        seed=9,
    )

    def soak(schedule, sink):
        engine.attach_leakmon(sink)
        sched = BatchScheduler(engine, clock=lambda: NOW)
        try:
            runner = ScenarioRunner(sched, n_idents=16,
                                    settle_timeout_s=60.0)
            return runner.run(schedule)
        finally:
            sched.close()
            sink.flush(30)
            engine.attach_leakmon(None)

    def fresh_monitor():
        # registry-free monitors: two soaks on one engine must not
        # double-register the leakmon gauges (the load-harness pattern)
        return EngineLeakMonitor(
            mb_leaves=engine.ecfg.mb.leaves,
            rec_leaves=engine.ecfg.rec.leaves,
            mb_choices=engine.ecfg.mb_choices,
            cfg=LeakMonitorConfig(window_rounds=64),
        )

    mon = fresh_monitor()
    soak(pop_heavy_drain(120.0, 1.5, 37, n_idents=16), mon)
    v = mon.verdict()
    assert v["verdict"] == PASS, v
    assert engine.health()["stash_overflow"] == 0
    mon.close()

    mon2 = fresh_monitor()
    inj = ProbeCampaignInjector(mon2, engine.ecfg)
    soak(
        adversarial_probe(0.03, 1.5, 38, n_probe_keys=4,
                          probes_per_pulse=2),
        inj,
    )
    v2 = mon2.verdict()
    assert v2["verdict"] == SUSPECT, v2
    mon2.close()


@pytest.mark.slow
def test_evict_recursive_schedule_audit():
    """The trace audit over a recursive-posmap delayed geometry (inner
    buffers + inner flush accounting included) — the heavier trace
    rides -m slow."""
    from check_tree_cache_oblivious import check_evict_round_accounting

    check_evict_round_accounting(recursive=True)


@pytest.mark.slow
def test_sharded_evict_recursive_schedule_audit():
    """The sharded trace+runtime audit over the recursive-posmap
    geometry: the replicated inner trees flush axis-free inside every
    chip's pass while the outer planes owner-partition."""
    from check_tree_cache_oblivious import check_sharded_evict_accounting

    check_sharded_evict_accounting(recursive=True)


@pytest.mark.slow
def test_evict_content_map_partition_invariant():
    """logical_block_map's partition assertion has teeth across many
    windows: no block is ever duplicated between tree, buffer, and
    stash at any round boundary of a long mixed campaign."""
    cfg = GrapevineConfig(evict_every=4, **BASE)
    eng = GrapevineEngine(cfg, seed=13)
    rng = np.random.default_rng(99)
    idents = [key(i) for i in range(1, 6)]
    live: list[tuple[bytes, bytes]] = []
    for bi in range(10):
        reqs = _gen_batch(rng, idents, live, 8)
        r = eng.handle_queries(reqs, NOW + bi)
        for q, d in zip(reqs, r):
            if (q.request_type == C.REQUEST_TYPE_CREATE
                    and d.status_code == C.STATUS_CODE_SUCCESS):
                live.append((d.record.msg_id, q.record.recipient))
            elif (q.request_type == C.REQUEST_TYPE_DELETE
                    and d.status_code == C.STATUS_CODE_SUCCESS):
                live = [x for x in live if x[0] != d.record.msg_id]
        # raises internally on any duplicate placement
        m = logical_block_map(eng.ecfg.rec, eng.state.rec)
        assert len(m) == eng.message_count()
