"""Journal-shipped hot standby (engine/replication.py, ISSUE 19).

Tier-1 half of the PR-19 acceptance: the follower read path's liveness
contract (torn tails poll, roll/prune races rescan, transient reads
retry), epoch fencing (O_EXCL single winner, stale-primary appends
refused), the replication fingerprint's normalization story, and the
full loopback ship → link-cut → fenced-promote → bit-identical-serve
cycle — plus the cross-knob rolling-upgrade drill and one chaos
--standby smoke trial. The kill-at-every-site sweep and the live CLI
flip drill live in tests/test_chaos_recovery.py (-m slow).
"""

import builtins
import dataclasses
import errno
import os
import sys
import time

import pytest

from grapevine_tpu.config import DurabilityConfig, GrapevineConfig
from grapevine_tpu.engine import journal as jr
from grapevine_tpu.engine.batcher import GrapevineEngine, pack_batch
from grapevine_tpu.engine.checkpoint import engine_fingerprint, state_to_bytes
from grapevine_tpu.engine.replication import (
    JournalShipper,
    ReplicationError,
    StandbyReplica,
    replication_fingerprint,
)
from grapevine_tpu.engine.state import EngineConfig
from grapevine_tpu.testing.compare import assert_logical_state_equal
from grapevine_tpu.wire import constants as C
from grapevine_tpu.wire.records import QueryRequest, RequestRecord

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ROOT = bytes(range(32))
NOW = 1_700_000_000


def _cfg(**kw):
    base = dict(
        max_messages=64, max_recipients=8, mailbox_cap=4,
        batch_size=4, stash_size=64, bucket_cipher_rounds=0,
        tree_top_cache_levels=0, pipeline_depth=1,
    )
    base.update(kw)
    return GrapevineConfig(**base)


SMALL = _cfg()
SMALL_E2 = _cfg(evict_every=2)


def _plant_key(d: str) -> None:
    """Both ends of a replication pair unseal under ONE root key — the
    production secret-mount story (OPERATIONS.md §23)."""
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, "root.key")
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o600)
    try:
        os.write(fd, ROOT)
    finally:
        os.close(fd)


def _dcfg(d: str, **kw) -> DurabilityConfig:
    kw.setdefault("checkpoint_every_rounds", 1 << 20)
    return DurabilityConfig(state_dir=d, **kw)


def _req(tag: int, rt=C.REQUEST_TYPE_CREATE):
    return QueryRequest(
        request_type=rt,
        auth_identity=bytes([tag & 0xFF]) * 32,
        auth_signature=b"\x01" * C.SIGNATURE_SIZE,
        record=RequestRecord(
            msg_id=C.ZERO_MSG_ID,
            recipient=bytes([(tag ^ 0x5A) & 0xFF]) * 32,
            payload=bytes([tag & 0xFF]) * C.PAYLOAD_SIZE,
        ),
    )


def _round_batch(ecfg, tag: int):
    return pack_batch([_req(tag)], ecfg.batch_size, NOW + tag), 1


def _fresh_journal(d, ecfg, **kw):
    j = jr.BatchJournal(str(d), ROOT, ecfg, **kw)
    list(j.replay(after_seq=0))
    j.open_for_append()
    return j


def _wait(pred, timeout=60.0, what=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    pytest.fail(f"timed out waiting for {what}")


@pytest.fixture(scope="module")
def ecfg():
    return EngineConfig.from_config(SMALL)


# -- follower liveness contract (journal.py follow/_follow_scan) --------


def test_follow_torn_final_frame_is_poll_again_not_error(tmp_path, ecfg):
    """A half-written FINAL frame means "not yet durable": the scan
    yields everything before it, stops silently, and a later call (the
    writer finished the append) picks the frame up."""
    j = _fresh_journal(tmp_path, ecfg)
    j.append_round(*_round_batch(ecfg, 1))
    j.append_round(*_round_batch(ecfg, 2))
    j.close()
    (_, path), = jr.BatchJournal(str(tmp_path), ROOT, ecfg)._segments()
    blob = open(path, "rb").read()
    frame_len = len(blob) // 2

    reader = jr.BatchJournal(str(tmp_path), ROOT, ecfg)
    for cut in (frame_len + 1, frame_len + jr._HEADER.size,
                len(blob) - 1):
        with open(path, "wb") as fh:
            fh.write(blob[:cut])
        assert [s for s, _ in reader.follow_frames(after_seq=0)] == [1]
    # the writer's append completes: the next poll yields the frame
    with open(path, "wb") as fh:
        fh.write(blob)
    assert [s for s, _ in reader.follow_frames(after_seq=1)] == [2]


def test_follow_rescans_when_roll_prune_races_the_reader(tmp_path, ecfg,
                                                         monkeypatch):
    """A segment vanishing between listdir and open (roll/prune racing
    the reader) triggers a directory rescan, not an error."""
    j = _fresh_journal(tmp_path, ecfg)
    j.append_round(*_round_batch(ecfg, 1))
    j.append_round(*_round_batch(ecfg, 2))
    j.close()

    real = jr.BatchJournal._read_segment
    calls = {"n": 0}

    def flaky(self, path):
        calls["n"] += 1
        if calls["n"] == 1:
            raise FileNotFoundError(path)
        return real(self, path)

    monkeypatch.setattr(jr.BatchJournal, "_read_segment", flaky)
    reader = jr.BatchJournal(str(tmp_path), ROOT, ecfg)
    assert [s for s, _ in reader.follow_frames(after_seq=0)] == [1, 2]
    assert calls["n"] == 2  # first open raced a roll; the rescan read


def test_follow_behind_prune_horizon_demands_rebootstrap(tmp_path, ecfg):
    """Segments covering consumed frames may vanish freely; a follower
    whose NEXT frame was pruned gets a hard error pointing at the
    checkpoint bootstrap path."""
    j = _fresh_journal(tmp_path, ecfg)
    j.append_round(*_round_batch(ecfg, 1))
    j.append_round(*_round_batch(ecfg, 2))
    j.roll()  # checkpoint covering seq 2 landed: frames 1-2 pruned
    j.append_round(*_round_batch(ecfg, 3))
    j.close()

    reader = jr.BatchJournal(str(tmp_path), ROOT, ecfg)
    # already past the pruned prefix: fine
    assert [s for s, _ in reader.follow_frames(after_seq=2)] == [3]
    # behind it: frames 1-2 are gone for good
    with pytest.raises(jr.JournalError, match="prune horizon"):
        list(reader.follow_frames(after_seq=0))


def test_follow_retries_transient_reads_with_bounded_backoff(tmp_path, ecfg,
                                                             monkeypatch):
    """EIO from a flaky mount retries (bounded, backed off) before
    raising; exhaustion is a JournalError, not a raw OSError."""
    j = _fresh_journal(tmp_path, ecfg)
    j.append_round(*_round_batch(ecfg, 1))
    j.append_round(*_round_batch(ecfg, 2))
    j.close()

    real_open = builtins.open
    fails = {"n": 2}

    def flaky(path, *a, **kw):
        if str(path).endswith(".wal") and fails["n"] > 0:
            fails["n"] -= 1
            raise OSError(errno.EIO, "flaky mount")
        return real_open(path, *a, **kw)

    monkeypatch.setattr(builtins, "open", flaky)
    monkeypatch.setattr(jr.time, "sleep", lambda s: None)
    reader = jr.BatchJournal(str(tmp_path), ROOT, ecfg)
    assert [s for s, _ in reader.follow_frames(after_seq=0)] == [1, 2]

    fails["n"] = 10_000  # never recovers: bounded retries then raise
    with pytest.raises(jr.JournalError, match="transient read errors"):
        list(reader.follow_frames(after_seq=0))


# -- epoch fencing (journal.py write_fence/_check_fence) ----------------


def test_fence_is_o_excl_exactly_one_winner(tmp_path):
    d = str(tmp_path)
    payload = jr.write_fence(d, epoch=3, fingerprint="fp-a")
    assert payload["epoch"] == 3
    assert jr.read_fence(d)["epoch"] == 3
    with pytest.raises(jr.JournalError, match="already fenced"):
        jr.write_fence(d, epoch=4, fingerprint="fp-b")
    # the loser's attempt did not clobber the winner's marker
    assert jr.read_fence(d)["fingerprint"] == "fp-a"


def test_epoch_file_roundtrip_and_default(tmp_path):
    d = str(tmp_path)
    assert jr.read_epoch(d) == 0
    jr.write_epoch(d, 7)
    assert jr.read_epoch(d) == 7
    jr.write_epoch(d, 8)  # re-promote into the same dir bumps again
    assert jr.read_epoch(d) == 8


def test_fenced_journal_refuses_stale_appends_and_reopen(tmp_path, ecfg):
    """The split-brain guard, both halves: a live stale primary's next
    append raises the moment a newer-epoch fence lands, and a REVIVED
    stale primary refuses in open_for_append — before it would truncate
    the tail the promoted replica already drained."""
    j = _fresh_journal(tmp_path, ecfg)
    j.append_round(*_round_batch(ecfg, 1))
    jr.write_fence(str(tmp_path), epoch=j.epoch + 1, fingerprint="fp")
    with pytest.raises(jr.JournalError, match="fenced"):
        j.append_round(*_round_batch(ecfg, 2))
    j.close()

    j2 = jr.BatchJournal(str(tmp_path), ROOT, ecfg)
    assert [r.seq for r in j2.replay(after_seq=0)] == [1]  # reads stay legal
    with pytest.raises(jr.JournalError, match="fenced"):
        j2.open_for_append()

    # the promoted owner itself (epoch == fence epoch) appends freely
    jr.write_epoch(str(tmp_path), jr.read_fence(str(tmp_path))["epoch"])
    j3 = _fresh_journal(tmp_path, ecfg)
    assert j3.append_round(*_round_batch(ecfg, 2)) == 2
    j3.close()


# -- replication fingerprint --------------------------------------------


def test_replication_fingerprint_normalizes_placement_knobs_only():
    """Frames replay across tree-top-cache depths and host-side round
    scheduling (the rolling-upgrade surface), but never across frame
    geometry or eviction cadence."""
    base = SMALL_E2
    # k is placement-only: normalized out
    assert replication_fingerprint(base) == replication_fingerprint(
        dataclasses.replace(base, tree_top_cache_levels=4))
    # pipeline depth is host-side scheduling: outside the frame format
    assert replication_fingerprint(base) == replication_fingerprint(
        dataclasses.replace(base, pipeline_depth=2))
    # eviction cadence changes the frame stream itself: fences
    assert replication_fingerprint(base) != replication_fingerprint(SMALL)
    # geometry changes the frame sizes: fences
    assert replication_fingerprint(base) != replication_fingerprint(
        dataclasses.replace(base, max_messages=128))
    # ...while the FULL fingerprint (checkpoint compatibility) still
    # distinguishes the k=4 placement the repl fingerprint normalizes
    assert engine_fingerprint(
        EngineConfig.from_config(base)
    ) != engine_fingerprint(
        EngineConfig.from_config(
            dataclasses.replace(base, tree_top_cache_levels=4))
    )


def test_shipper_requires_a_journal_to_tail():
    eng = GrapevineEngine(SMALL, seed=0)
    try:
        with pytest.raises(ReplicationError, match="state-dir"):
            JournalShipper(eng, "127.0.0.1:1")
    finally:
        eng.close()


# -- the loopback cycle: ship → cut → promote → fence → serve -----------


def test_ship_promote_fence_cycle_bit_identical(tmp_path):
    """One continuous drill over a real socket: live catch-up at round
    cadence (leakmon's ship_cadence book PASS), link cut, primary dies
    with a durable tail the standby never saw, fenced promote drains it
    off disk (RPO 0, bit-identical state), the promoted replica serves,
    and every split-brain door is shut: shipped frames refused, the
    revived stale primary refused, the second promoter refused."""
    from grapevine_tpu.obs.leakmon import EngineLeakMonitor, LeakMonitorConfig

    primary_dir = str(tmp_path / "primary")
    standby_dir = str(tmp_path / "standby")
    _plant_key(primary_dir)
    _plant_key(standby_dir)

    primary = GrapevineEngine(SMALL_E2, seed=0,
                              durability=_dcfg(primary_dir))
    monitor = EngineLeakMonitor.for_engine(
        primary, LeakMonitorConfig(window_rounds=64))
    primary.attach_leakmon(monitor)
    replica = StandbyReplica(SMALL_E2, seed=0,
                             durability=_dcfg(standby_dir))
    port = replica.listen()
    shipper = JournalShipper(primary, ("127.0.0.1", port))
    monitor.attach_shipper(shipper)
    shipper.start()
    primary_open = True
    try:
        for i in range(4):
            primary.handle_queries([_req(i + 1)], NOW + i)
        primary.expire(NOW + 10, period=3600)
        _wait(lambda: replica.dm.applied_seq == primary.durability.seq,
              what="live catch-up")
        assert replica.connected and not replica.promoted
        healthy, detail = replica.healthz()
        assert healthy and detail["role"] == "standby"

        # the cadence book: every on-wire frame was one of the
        # geometry's constant sizes — content-independent by size
        v = monitor.verdict()
        ship = [d for d in v["detectors"] if d["name"] == "ship_cadence"]
        assert ship and ship[0]["verdict"] == "PASS"
        assert v["replication"]["cadence_ok"]
        # 4 rounds + 2 flush frames (E=2) + 1 sweep
        assert v["replication"]["frames_shipped"] == 7

        # link cut; the primary's final rounds reach disk only
        shipper.close()
        for i in range(3):
            primary.handle_queries([_req(40 + i)], NOW + 20 + i)
        dead_seq = primary.durability.seq
        dead_bytes = state_to_bytes(primary.ecfg, primary.state)
        primary.close()
        primary_open = False

        res = replica.promote(primary_state_dir=primary_dir)
        assert res["epoch"] == 1
        assert res["rpo_durable_frames"] == 0
        assert res["applied_seq"] == dead_seq
        assert res["drained_frames"] == dead_seq - 7
        assert state_to_bytes(replica.engine.ecfg,
                              replica.engine.state) == dead_bytes
        healthy, detail = replica.healthz()
        assert healthy and detail["promoted"]
        assert jr.read_epoch(standby_dir) == 1

        # serves inside the same process: its own journal advances
        replica.engine.handle_queries([_req(99)], NOW + 40)
        assert replica.dm.seq > dead_seq

        # door 1: shipped frames bounce off a promoted replica
        with pytest.raises(ReplicationError, match="promoted"):
            replica.apply_frame(replica.dm.seq + 1, b"\x00" * 64)

        # door 2: the revived stale primary dies in open_for_append,
        # before truncating the tail the replica drained
        with pytest.raises(jr.JournalError, match="fenced"):
            GrapevineEngine(SMALL_E2, seed=0, durability=_dcfg(primary_dir))

        # door 3: a double-promote has exactly one winner
        loser_dir = str(tmp_path / "loser")
        _plant_key(loser_dir)
        loser = StandbyReplica(SMALL_E2, seed=0,
                               durability=_dcfg(loser_dir))
        try:
            with pytest.raises(jr.JournalError, match="already fenced"):
                loser.promote(primary_state_dir=primary_dir)
            assert not loser.promoted
        finally:
            loser.close()
    finally:
        shipper.close()
        if primary_open:
            primary.close()
        monitor.close()
        replica.close()


# -- rolling-upgrade drill: cross-knob legal, cross-geometry fenced -----


def test_cross_knob_standby_promotes_under_k4_depth2_primary(tmp_path):
    """The rolling-upgrade shape: a k=0/depth-1 standby follows a
    k=4/depth-2 primary from genesis (same frame fingerprint — k and
    pipeline depth are placement/scheduling, not frame format) and
    promotes to the logically identical store."""
    pcfg = _cfg(tree_top_cache_levels=4, pipeline_depth=2, evict_every=2)
    scfg = SMALL_E2
    assert replication_fingerprint(pcfg) == replication_fingerprint(scfg)

    primary_dir = str(tmp_path / "primary")
    standby_dir = str(tmp_path / "standby")
    _plant_key(primary_dir)
    _plant_key(standby_dir)
    primary = GrapevineEngine(pcfg, seed=0, durability=_dcfg(primary_dir))
    replica = StandbyReplica(scfg, seed=0, durability=_dcfg(standby_dir))
    port = replica.listen()
    shipper = JournalShipper(primary, ("127.0.0.1", port))
    shipper.start()
    primary_open = True
    try:
        for i in range(4):
            primary.handle_queries([_req(i + 1)], NOW + i)
        _wait(lambda: replica.dm.applied_seq == primary.durability.seq,
              what="cross-knob catch-up")
        shipper.close()
        primary.handle_queries([_req(9)], NOW + 9)
        dead_seq = primary.durability.seq
        dead_state = primary.state
        primary.close()
        primary_open = False

        res = replica.promote(primary_state_dir=primary_dir)
        assert res["applied_seq"] == dead_seq
        # different placement → different bits; logically equal store
        assert_logical_state_equal(primary.ecfg, dead_state,
                                   replica.engine.ecfg,
                                   replica.engine.state,
                                   ctx="cross-knob promote")
    finally:
        shipper.close()
        if primary_open:
            primary.close()
        replica.close()


def test_cross_geometry_ship_refused_with_fingerprint_error(tmp_path):
    """evict_every changes the frame stream itself: the handshake
    refuses, permanently (reconnects can never fix it)."""
    primary_dir = str(tmp_path / "primary")
    standby_dir = str(tmp_path / "standby")
    _plant_key(primary_dir)
    _plant_key(standby_dir)
    primary = GrapevineEngine(SMALL, seed=0, durability=_dcfg(primary_dir))
    replica = StandbyReplica(SMALL_E2, seed=0,
                             durability=_dcfg(standby_dir))
    port = replica.listen()
    shipper = JournalShipper(primary, ("127.0.0.1", port))
    shipper.start()
    try:
        _wait(lambda: shipper.fatal is not None,
              what="fingerprint refusal")
        assert "fingerprint" in shipper.fatal
        assert replica.dm.seq == 0 and not replica.promoted
    finally:
        shipper.close()
        primary.close()
        replica.close()


# -- chaos --standby smoke (full sweep is -m slow) ----------------------


def test_chaos_standby_smoke_flush_boundary_kill():
    """One --standby trial at the nastiest site (flush.pre_dispatch at
    E=2: flush frame durable, flush never dispatched): SIGKILL the
    primary, promote the parent's replica, finish the event schedule,
    and match the serial oracle bit-identically with leakmon PASS."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import chaos_run as chaos

    args = chaos.parse_args(
        ["--standby", "--events", "10", "--evict-every", "2",
         "--seed", "11"]
    )
    failures = chaos.run_trials(0, args, modes=["flush.pre_dispatch"])
    assert not failures, "\n".join(failures)
