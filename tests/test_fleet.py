"""Fleet observatory (obs/fleet.py + leakmon.FleetUniformityMonitor +
load sharding): merge/degrade semantics, the shard label policy, the
cross-shard discrimination drill, replication-lag gauges, and the live
2-member fleet boot (ISSUE 16).

The discrimination drill mirrors test_leakmon.py's shape: honest
uniformly-scheduled N-shard soaks must PASS under every arrival shape
(the false-positive gate — at fleet grain, client traffic shape is
allowed to be anything), while the seeded skewed-scheduler mutant (a
shard dispatches a round only when its own queue is hot) must flip the
fleet verdict to SUSPECT within a bounded number of ticks.
"""

from __future__ import annotations

import http.server
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from grapevine_tpu.config import DurabilityConfig, GrapevineConfig
from grapevine_tpu.engine.checkpoint import DurabilityManager
from grapevine_tpu.engine.state import EngineConfig, init_engine
from grapevine_tpu.load.capacity import analyze_ramp, fleet_capacity
from grapevine_tpu.load.generators import (
    CREATE,
    partition_schedule,
    ramp_to_saturation,
    steady_poisson,
)
from grapevine_tpu.load.harness import ShardedScenarioRunner, ShardRoundDriver
from grapevine_tpu.obs.exporter import render_prometheus
from grapevine_tpu.obs.fleet import (
    FleetAggregator,
    FleetConfig,
    parse_exposition,
)
from grapevine_tpu.obs.leakmon import FleetUniformityMonitor
from grapevine_tpu.obs.registry import TelemetryLeakError, TelemetryRegistry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- helpers ------------------------------------------------------------


def member_text(rounds, qdepth=0, flushes=0, durable=None, applied=None,
                fill_mean=0.5):
    """A minimal member /metrics body with the families the fleet
    consumes."""
    lines = [
        "# HELP grapevine_rounds_total oblivious rounds committed",
        "# TYPE grapevine_rounds_total counter",
        f"grapevine_rounds_total {rounds}",
        "# TYPE grapevine_queue_depth gauge",
        f"grapevine_queue_depth {qdepth}",
        "# TYPE grapevine_evict_flushes_total counter",
        f"grapevine_evict_flushes_total {flushes}",
        "# TYPE grapevine_load_batch_fill histogram",
        f'grapevine_load_batch_fill_bucket{{le="+Inf"}} {rounds}',
        f"grapevine_load_batch_fill_sum {rounds * fill_mean}",
        f"grapevine_load_batch_fill_count {rounds}",
    ]
    if durable is not None:
        lines += ["# TYPE grapevine_last_durable_seq gauge",
                  f"grapevine_last_durable_seq {durable}"]
    if applied is not None:
        lines += ["# TYPE grapevine_journal_applied_seq gauge",
                  f"grapevine_journal_applied_seq {applied}"]
    return "\n".join(lines) + "\n"


class FakeFleet:
    """Dict-driven fetch injection: members[addr][path] is a str/dict
    body or an Exception to raise."""

    def __init__(self, members: dict):
        self.members = members

    def __call__(self, url: str, timeout_s: float) -> bytes:
        addr, _, path = url.split("//")[1].partition("/")
        doc = self.members[addr].get("/" + path)
        if doc is None:
            return b""
        if isinstance(doc, Exception):
            raise doc
        if isinstance(doc, dict):
            return json.dumps(doc).encode()
        return doc.encode()


# -- exposition parser --------------------------------------------------


def test_parse_exposition_families_and_labels():
    fams = parse_exposition(
        "# HELP m one\n# TYPE m counter\n"
        'm{phase="a b",q="x\\"y"} 3\nm{phase="c"} 4.5\n'
        "# TYPE h histogram\n"
        'h_bucket{le="+Inf"} 2\nh_sum 1.5\nh_count 2\n'
    )
    assert fams["m"]["kind"] == "counter" and fams["m"]["help"] == "one"
    assert fams["m"]["samples"][0] == (
        "m", (("phase", "a b"), ("q", 'x"y')), 3.0)
    # histogram suffixes fold into one family
    assert {s[0] for s in fams["h"]["samples"]} == {
        "h_bucket", "h_sum", "h_count"}


@pytest.mark.parametrize("body", [
    "grapevine_rounds_total",                 # no value (cut mid-line)
    "grapevine_rounds_total 1.2e",            # torn float
    'm{phase="a} 1',                          # unterminated label string
    "m{phase=a} 1",                           # unquoted label value
    "not a metric line at all!",
])
def test_parse_exposition_rejects_malformed_whole(body):
    """Strictness is the degraded-view guard: any malformed line rejects
    the WHOLE scrape (last-good retained) — never a half-merged family."""
    with pytest.raises(ValueError):
        parse_exposition("# TYPE m counter\nm 1\n" + body)


# -- shard label policy (ISSUE 16 satellite 1) --------------------------


def test_shard_label_values_must_be_integer_indices():
    r = TelemetryRegistry()
    r.gauge("grapevine_fleet_ok", "x", labels={"shard": ("0", "1", "2")})
    for bad in ("engine-a.internal", "10.0.0.7:9464", "shard-0", "-1",
                "١"):  # non-ASCII digit must not sneak past isdigit()
        with pytest.raises(TelemetryLeakError):
            TelemetryRegistry().gauge(
                "grapevine_fleet_bad", "x", labels={"shard": (bad,)})


def test_member_label_key_rejected():
    with pytest.raises(TelemetryLeakError):
        TelemetryRegistry().gauge(
            "grapevine_fleet_bad", "x", labels={"member": ("0",)})


# -- merged views -------------------------------------------------------


def _fresh_agg(n=2, interval=1.0, members=None):
    fake = FakeFleet(members or {})
    t = [0.0]
    cfg = FleetConfig(
        members=tuple(f"m{i}:1" for i in range(n)),
        scrape_interval_s=interval,
    )
    agg = FleetAggregator(cfg, clock=lambda: t[0], fetch=fake)
    return agg, fake, t


def test_merged_metrics_inject_shard_label():
    agg, fake, t = _fresh_agg()
    fake.members["m0:1"] = {"/metrics": member_text(8, qdepth=3)}
    fake.members["m1:1"] = {"/metrics": member_text(5, qdepth=1)}
    agg.scrape_once()
    merged = agg.render_merged()
    assert 'grapevine_rounds_total{shard="0"} 8' in merged
    assert 'grapevine_rounds_total{shard="1"} 5' in merged
    # existing labels survive with shard appended
    assert 'grapevine_load_batch_fill_bucket{le="+Inf",shard="0"} 8' in merged
    # HELP/TYPE once per family, not per member
    assert merged.count("# TYPE grapevine_rounds_total counter") == 1
    # the fleet's own registry rides along
    assert 'grapevine_fleet_member_up{shard="0"} 1' in merged
    # a member's own stray shard label is dropped, never re-exported
    fake.members["m0:1"] = {
        "/metrics": '# TYPE x gauge\nx{shard="9"} 1\n'}
    agg.scrape_once()
    assert 'x{shard="0"} 1' in agg.render_merged()


def test_healthz_folds_members_burn_rates_and_uniformity():
    agg, fake, t = _fresh_agg()
    for i, addr in enumerate(("m0:1", "m1:1")):
        fake.members[addr] = {
            "/metrics": member_text(4),
            "/healthz": {"healthy": True, "role": "engine",
                         "slo": {"fast_burn_rate": 0.5 + i,
                                 "slow_burn_rate": 0.25}},
            "/leakaudit": {"verdict": "PASS"},
        }
    agg.scrape_once()
    healthy, detail = agg.healthz()
    assert healthy
    assert detail["role"] == "fleet" and detail["n_members"] == 2
    # merged burn rate = worst member (budgets do not average away)
    assert detail["slo_fast_burn_rate"] == 1.5
    assert [m["shard"] for m in detail["members"]] == [0, 1]
    # one member unhealthy -> fleet unhealthy
    fake.members["m1:1"]["/healthz"] = {"healthy": False, "role": "engine"}
    agg.scrape_once()
    healthy, _ = agg.healthz()
    assert not healthy


def test_standby_fold_counts_roles_and_sums_promotions():
    """_update_standbys (ISSUE 19): ``grapevine_fleet_standbys`` counts
    live un-promoted role=standby members by their /healthz tag (a fed
    standby exports no round counter, so nothing else in the merge
    distinguishes it from a dead shard), ``grapevine_fleet_promotions``
    sums the members' promotion counters, and the fleet /healthz entry
    carries the DR surface an operator pages on."""
    agg, fake, t = _fresh_agg()
    fake.members["m0:1"] = {
        "/metrics": member_text(4),
        "/healthz": {"healthy": True, "role": "engine"},
    }
    standby_metrics = (
        "# TYPE grapevine_replication_promotions_total counter\n"
        "grapevine_replication_promotions_total 0\n")
    fake.members["m1:1"] = {
        "/metrics": standby_metrics,
        "/healthz": {"healthy": True, "role": "standby",
                     "promoted": False, "replication_connected": True,
                     "journal_epoch": 0},
    }
    agg.scrape_once()

    def fleet_gauge(name):
        fams = parse_exposition(agg.render_merged())
        ((_, _, val),) = fams[name]["samples"]
        return val

    assert fleet_gauge("grapevine_fleet_standbys") == 1.0
    assert fleet_gauge("grapevine_fleet_promotions") == 0.0
    healthy, detail = agg.healthz()
    assert healthy and detail["n_standbys"] == 1
    (sb,) = [m for m in detail["members"] if m.get("role") == "standby"]
    assert sb["promoted"] is False
    assert sb["replication_connected"] is True
    assert sb["journal_epoch"] == 0
    # the DR keys are the standby's surface alone
    (eng,) = [m for m in detail["members"] if m.get("role") == "engine"]
    assert "promoted" not in eng and "replication_connected" not in eng

    # promotion flips the member out of the standby count and into the
    # promotions sum — the fleet sees the takeover, not a dead shard
    fake.members["m1:1"]["/metrics"] = standby_metrics.replace(
        "total 0", "total 1")
    fake.members["m1:1"]["/healthz"] = {
        "healthy": True, "role": "standby", "promoted": True,
        "replication_connected": False, "journal_epoch": 1}
    t[0] += 2.0
    agg.scrape_once()
    assert fleet_gauge("grapevine_fleet_standbys") == 0.0
    assert fleet_gauge("grapevine_fleet_promotions") == 1.0
    _, detail = agg.healthz()
    assert detail["n_standbys"] == 0
    (sb,) = [m for m in detail["members"] if m.get("role") == "standby"]
    assert sb["promoted"] is True and sb["journal_epoch"] == 1


def test_leakaudit_folds_member_verdicts():
    agg, fake, t = _fresh_agg()
    fake.members["m0:1"] = {"/metrics": member_text(4),
                            "/leakaudit": {"verdict": "PASS"}}
    fake.members["m1:1"] = {"/metrics": member_text(4),
                            "/leakaudit": {"verdict": "PASS"}}
    agg.scrape_once()
    assert agg.leakaudit()["verdict"] == "PASS"
    fake.members["m1:1"]["/leakaudit"] = {"verdict": "SUSPECT"}
    agg.scrape_once()
    v = agg.leakaudit()
    assert v["verdict"] == "SUSPECT"
    assert v["members"][1]["verdict"] == "SUSPECT"
    # fleet detectors ride the same body
    assert {d["name"] for d in v["fleet_detectors"]} == {
        "cadence_ratio", "fill_load_correlation", "flush_phase"}


def test_scrape_attempts_are_traffic_independent():
    """Every member is attempted every cycle in declared order — a down
    or 'boring' member is scraped exactly as often as a hot one (the
    cadence-leak argument, OPERATIONS.md §20)."""
    agg, fake, t = _fresh_agg()
    fake.members["m0:1"] = {"/metrics": member_text(1000, qdepth=99)}
    fake.members["m1:1"] = {"/metrics": ConnectionRefusedError("down")}
    for k in range(7):
        t[0] = float(k)
        agg.scrape_once()
    text = render_prometheus(agg.registry)
    assert 'grapevine_fleet_scrapes_total{shard="0"} 7' in text
    assert 'grapevine_fleet_scrapes_total{shard="1"} 7' in text
    assert 'grapevine_fleet_scrape_failures_total{shard="1"} 7' in text


# -- degraded-scrape edge (ISSUE 16 satellite 3) ------------------------


class _FakeMemberHTTP:
    """A real HTTP member whose behavior is switchable mid-test:
    'ok' serves a valid exposition, 'truncated' a torn body, 'sleep'
    times the client out."""

    def __init__(self):
        self.mode = "ok"
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if outer.mode == "sleep":
                    time.sleep(1.0)
                    return
                body = member_text(7, qdepth=2).encode()
                if outer.mode == "truncated":
                    # a torn write: headers promise more than arrives,
                    # and the last line is cut mid-sample
                    body = body[: len(body) - 12]
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.httpd = http.server.ThreadingHTTPServer(
            ("127.0.0.1", 0), Handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        threading.Thread(
            target=self.httpd.serve_forever, daemon=True).start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def test_flapping_member_degrades_without_tearing_merged_view():
    members = [_FakeMemberHTTP(), _FakeMemberHTTP(), _FakeMemberHTTP()]
    try:
        t = [100.0]
        cfg = FleetConfig(
            members=tuple(f"127.0.0.1:{m.port}" for m in members),
            scrape_interval_s=1.0, scrape_timeout_s=0.25,
        )
        agg = FleetAggregator(cfg, clock=lambda: t[0])
        agg.scrape_once()
        assert all(st.up for st in agg._members)
        # member 1 flaps to a truncated body, member 2 to a timeout
        members[1].mode = "truncated"
        members[2].mode = "sleep"
        t[0] = 103.0
        agg.scrape_once()
        ups = [st.up for st in agg._members]
        assert ups == [True, False, False]
        merged = agg.render_merged()
        # last-good families still serve for the down members...
        for shard in (0, 1, 2):
            assert f'grapevine_rounds_total{{shard="{shard}"}} 7' in merged
        # ...with up=0 and a truthful stale age, and healthz degrades
        assert 'grapevine_fleet_member_up{shard="1"} 0' in merged
        assert 'grapevine_fleet_member_up{shard="2"} 0' in merged
        assert 'grapevine_fleet_member_stale_age_seconds{shard="1"} 3' \
            in merged
        healthy, detail = agg.healthz()
        assert not healthy
        assert [m["up"] for m in detail["members"]] == [True, False, False]
        # recovery: the flapper comes back, the view heals
        members[1].mode = "ok"
        t[0] = 104.0
        agg.scrape_once()
        assert agg._members[1].up
    finally:
        for m in members:
            m.close()


# -- cross-shard uniformity drill (satellite 4, fast tier) --------------


def _bursty_arrivals(seed, n=3):
    """Shard 0 breathes hot/cold; the others trickle — the load shape
    most likely to fool a cadence detector."""
    rng = np.random.default_rng(seed)

    def f(k):
        out = []
        for i in range(n):
            out.append(12 if (k // 8) % 2 == 0 else 0) if i == 0 \
                else out.append(int(rng.poisson(2)))
        return out

    return f


def _steady_arrivals(seed, n=3):
    rng = np.random.default_rng(seed)
    return lambda k: [int(rng.poisson(3)) for _ in range(n)]


@pytest.mark.parametrize("shape", ["bursty", "steady"])
def test_honest_uniform_scheduler_passes(shape):
    mon = FleetUniformityMonitor(3)
    drv = ShardRoundDriver(3, mon, policy="uniform")
    arr = (_bursty_arrivals if shape == "bursty" else _steady_arrivals)(11)
    v = drv.run(arr, 200)
    assert v["verdict"] == "PASS", v
    for det in v["detectors"]:
        assert det["verdict"] == "PASS", det


@pytest.mark.parametrize("shape", ["bursty", "steady"])
def test_skewed_scheduler_mutant_suspects_within_64_ticks(shape):
    """The seeded mutant: a shard dispatches only when its own queue is
    hot — per-shard load reaches per-shard cadence, the exact leak the
    fleet detectors exist to flag. Bounded detection: <= 64 ticks."""
    mon = FleetUniformityMonitor(3)
    drv = ShardRoundDriver(3, mon, policy="skewed")
    arr = (_bursty_arrivals if shape == "bursty" else _steady_arrivals)(13)
    v = drv.run(arr, 64, stop_on="SUSPECT")
    assert v["verdict"] == "SUSPECT", v
    assert v["ticks"] <= 64
    tripped = [d for d in v["detectors"] if d["verdict"] == "SUSPECT"]
    assert tripped, v


def test_insufficient_evidence_is_pass():
    """min-samples stance (the PR-2 rule): a young window grades PASS,
    never SUSPECT-by-default."""
    mon = FleetUniformityMonitor(2)
    drv = ShardRoundDriver(2, mon, policy="skewed")
    v = drv.run(_steady_arrivals(7, n=2), 4)
    assert v["verdict"] == "PASS"
    assert all(d["samples"] < d["min_samples"] or d["verdict"] == "PASS"
               for d in v["detectors"])


def test_monitor_tolerates_missing_members_and_counter_resets():
    mon = FleetUniformityMonitor(2)
    base = lambda r: {"rounds_total": float(r), "flushes_total": 0.0,  # noqa: E731
                      "fill_sum": 0.0, "fill_count": 0.0,
                      "queue_depth": 0.0}
    mon.observe_tick([base(1), base(1)])
    mon.observe_tick([base(2), None])        # partial scrape: no evidence
    mon.observe_tick([base(3), base(0)])     # member 1 restarted (reset)
    mon.observe_tick([base(4), base(1)])
    assert mon.verdict()["verdict"] == "PASS"
    with pytest.raises(ValueError):
        mon.observe_tick([base(5)])          # wrong shard count
    with pytest.raises(ValueError):
        FleetUniformityMonitor(1)            # a fleet of one has no pairs


# -- per-shard scenario replay (load/) ----------------------------------


def test_partition_schedule_routes_and_preserves():
    sched = steady_poisson(rate=500.0, duration_s=1.0, seed=3)
    parts = partition_schedule(sched, 3)
    assert sum(p.n_ops for p in parts) == sched.n_ops
    for i, p in enumerate(parts):
        assert p.meta["shard"] == i and p.meta["n_shards"] == 3
        creates = p.kind == CREATE
        assert np.all(p.recipient[creates] % 3 == i)
        assert np.all(p.auth[~creates] % 3 == i)
        # still a valid sorted schedule
        assert np.all(np.diff(p.t_s) >= 0)
    # deterministic: same split twice
    again = partition_schedule(sched, 3)
    assert [p.fingerprint() for p in parts] == \
        [p.fingerprint() for p in again]
    with pytest.raises(ValueError):
        partition_schedule(sched, 0)


class _StubScheduler:
    """submit_nowait -> already-settled future (status SUCCESS)."""

    def __init__(self):
        from concurrent.futures import Future

        from grapevine_tpu.wire import constants as C

        self.n = 0
        self._mk = Future
        self._status = C.STATUS_CODE_SUCCESS

    def submit_nowait(self, req):
        import types

        self.n += 1
        fut = self._mk()
        fut.set_result(types.SimpleNamespace(status_code=self._status))
        return fut


def test_sharded_runner_replays_partition_and_folds_capacity():
    sched = ramp_to_saturation(rate0=400.0, factor=2.0, n_steps=3,
                               step_s=0.08, seed=5)
    stubs = [_StubScheduler(), _StubScheduler()]
    runner = ShardedScenarioRunner(stubs, time_scale=1.0,
                                   settle_timeout_s=5.0)
    results = runner.run(sched)
    assert len(results) == 2
    assert sum(s.n for s in stubs) == sched.n_ops
    analyses = [
        analyze_ramp(r.schedule, r, target_ms=250.0) for r in results
    ]
    fleet = fleet_capacity(analyses)
    assert fleet["shard_count"] == 2
    assert fleet["fleet_knee_ops_per_sec"] == pytest.approx(
        sum(a["knee_ops_per_sec"] for a in analyses))
    assert [s["shard"] for s in fleet["shards"]] == [0, 1]


# -- replication-lag gauges (ISSUE 16 third leg) ------------------------


def test_journal_lag_tracks_follower_through_checkpoint_cycle(tmp_path):
    """Primary journals + checkpoints; a follower recovers from shipped
    copies of the state dir; the fleet lag gauges must read the gap and
    its closure — the hot-standby RPO as a number (ROADMAP item 4)."""
    ecfg = EngineConfig.from_config(GrapevineConfig(
        max_messages=64, max_recipients=8, mailbox_cap=4,
        batch_size=4, stash_size=64, bucket_cipher_rounds=0,
    ))
    state = init_engine(ecfg, seed=5)
    pdir, fdir = str(tmp_path / "primary"), str(tmp_path / "follower")

    reg_p = TelemetryRegistry()
    mgr_p = DurabilityManager(
        DurabilityConfig(state_dir=pdir, checkpoint_every_rounds=4),
        ecfg, registry=reg_p)
    mgr_p.recover(state, lambda s, rec: s)
    for _ in range(3):
        mgr_p.append_sweep(now=1, now_hi=0, period=1)
    assert mgr_p.applied_seq == 3 and mgr_p.status()["applied_seq"] == 3

    def ship_and_recover():
        """Journal shipping, crudely: rsync the sealed state dir and
        replay it on the follower side."""
        if os.path.isdir(fdir):
            shutil.rmtree(fdir)
        shutil.copytree(pdir, fdir)
        reg_f = TelemetryRegistry()
        mgr_f = DurabilityManager(
            DurabilityConfig(state_dir=fdir, checkpoint_every_rounds=4),
            ecfg, registry=reg_f)
        mgr_f.recover(state, lambda s, rec: s)
        mgr_f.close()
        return reg_f

    reg_f = ship_and_recover()  # follower caught up at seq 3

    # primary advances THROUGH a checkpoint cycle: 3 more records trip
    # checkpoint_every_rounds=4, sealing at seq 6 and rolling the journal
    for _ in range(3):
        mgr_p.append_sweep(now=2, now_hi=0, period=1)
    assert mgr_p.should_checkpoint()
    mgr_p.checkpoint(state)
    assert mgr_p.ckpt_seq == 6 and mgr_p.applied_seq == 6

    t = [50.0]
    agg = FleetAggregator(
        FleetConfig(members=("p:1", "f:1")),
        clock=lambda: t[0],
        fetch=FakeFleet({
            "p:1": {"/metrics": render_prometheus(reg_p)},
            "f:1": {"/metrics": render_prometheus(reg_f)},
        }),
    )
    agg.scrape_once()
    own = render_prometheus(agg.registry)
    assert 'grapevine_fleet_journal_lag_seq{shard="0"} 0' in own
    assert 'grapevine_fleet_journal_lag_seq{shard="1"} 3' in own

    # the follower re-ships past the checkpoint: recovery loads the
    # sealed checkpoint (seq 6) and the lag closes
    reg_f2 = ship_and_recover()
    t[0] = 55.0
    agg._fetch = FakeFleet({
        "p:1": {"/metrics": render_prometheus(reg_p)},
        "f:1": {"/metrics": render_prometheus(reg_f2)},
    })
    agg.scrape_once()
    own = render_prometheus(agg.registry)
    assert 'grapevine_fleet_journal_lag_seq{shard="1"} 0' in own
    assert 'grapevine_fleet_journal_lag_seconds{shard="1"} 0' in own
    mgr_p.close()


def test_journal_follow_is_read_only(tmp_path):
    from grapevine_tpu.engine.journal import BatchJournal

    ecfg = EngineConfig.from_config(GrapevineConfig(
        max_messages=64, max_recipients=8, mailbox_cap=4,
        batch_size=4, stash_size=64, bucket_cipher_rounds=0,
    ))
    root = bytes(range(32))
    j = BatchJournal(str(tmp_path), root, ecfg)
    list(j.replay())
    j.open_for_append()
    j.append_sweep(1, 0, 1)
    j.append_sweep(2, 0, 1)
    with pytest.raises(RuntimeError, match="read-only"):
        list(j.follow())  # open for append: not a follower
    f = BatchJournal(str(tmp_path), root, ecfg)
    assert [r.seq for r in f.follow()] == [1, 2]
    j.append_sweep(3, 0, 1)
    # a later follow picks up newly shipped frames
    assert [r.seq for r in f.follow(after_seq=2)] == [3]
    j.close()


# -- live 2-member fleet (satellite 2 + acceptance) ---------------------


def _wait_port_line(proc, needle, timeout=120.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            if proc.poll() is not None:
                raise AssertionError(
                    f"process died rc={proc.returncode}: "
                    f"{proc.stderr.read()[-2000:]}")
            time.sleep(0.05)
            continue
        if needle in line:
            return line
    raise AssertionError(f"no {needle!r} line within {timeout}s")


def _get(url, timeout=5.0):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_live_two_member_fleet_boots_merges_and_drains():
    """Two engine-role processes + the fleet role, end to end: merged
    /metrics with shard-labeled families, merged /healthz, fleet
    /leakaudit, then SIGTERM-drain to exit 0 for all three."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    engine_argv = [
        sys.executable, "-m", "grapevine_tpu.server.cli",
        "--role", "engine", "--engine-listen", "127.0.0.1:0",
        "--msg-capacity", "64", "--recipient-capacity", "8",
        "--batch-size", "4", "--metrics-port", "0",
    ]
    procs = []
    try:
        for seed in ("0", "1"):
            procs.append(subprocess.Popen(
                engine_argv + ["--seed", seed], cwd=REPO, env=env,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
        mports = []
        for p in procs:
            _wait_port_line(p, "engine tier listening")
            line = _wait_port_line(p, "metrics endpoint on port")
            mports.append(int(line.rsplit(" ", 1)[1]))
        fport = _free_port()
        fleet = subprocess.Popen(
            [sys.executable, "-m", "grapevine_tpu.server.cli",
             "--role", "fleet",
             "--fleet-members",
             ",".join(f"127.0.0.1:{mp}" for mp in mports),
             "--fleet-scrape-interval", "0.2",
             "--fleet-port", str(fport)],
            cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        procs.append(fleet)
        _wait_port_line(fleet, "fleet aggregator on port", timeout=60)
        deadline = time.monotonic() + 30
        merged = ""
        while time.monotonic() < deadline:
            _, merged = _get(f"http://127.0.0.1:{fport}/metrics")
            if ('grapevine_rounds_total{shard="0"}' in merged
                    and 'grapevine_rounds_total{shard="1"}' in merged):
                break
            time.sleep(0.3)
        assert 'grapevine_rounds_total{shard="0"}' in merged, merged[:800]
        assert 'grapevine_rounds_total{shard="1"}' in merged
        assert 'grapevine_fleet_member_up{shard="0"} 1' in merged
        assert 'grapevine_fleet_member_up{shard="1"} 1' in merged
        code, body = _get(f"http://127.0.0.1:{fport}/healthz")
        hz = json.loads(body)
        assert code == 200 and hz["healthy"] and hz["role"] == "fleet"
        assert [m["up"] for m in hz["members"]] == [True, True]
        code, body = _get(f"http://127.0.0.1:{fport}/leakaudit")
        assert code == 200 and json.loads(body)["verdict"] == "PASS"
        # SIGTERM-drain: all three exit 0
        for p in reversed(procs):
            p.send_signal(signal.SIGTERM)
        for p in procs:
            assert p.wait(timeout=60) == 0, p.stderr.read()[-2000:]
        procs = []
    finally:
        for p in procs:
            p.kill()
            p.wait(timeout=10)
